"""Repo-root executor shim: same CLI as the reference's executor script.

Lets reference-style invocations (``python executor.py --relative_path ...``)
run against the TPU-native framework unmodified.
"""

import sys

from traceweaver_tpu.runtime.cli import main

if __name__ == "__main__":
    sys.exit(main())
