// Streaming Jaeger-JSON corpus loader: parses trace files (in parallel
// across a thread pool) into an interned, struct-of-arrays span corpus that
// the Python side turns into Span objects / device tensors without touching
// a Python JSON parser.
//
// This is the real implementation of the role sketched by the reference's
// C++ skeleton (reference: src/trace_reconstructor/ports/cpp/{span.h:12-34,
// trace.h:4-7, main.cpp:6-21} — all bodies `//!TODO` there). Field
// extraction mirrors the reference Python parser
// (reference: src/trace_reconstructor/ports/python/executor.py:342-488):
//   - span.kind from the tags array (verbatim value, last tag wins);
//   - operationName with Alibaba's requestType taking precedence;
//   - the full references list (parent edges);
//   - caller/callee (Alibaba converter fields) when present;
//   - the top-level processes table (pid -> serviceName).
// Dataset repair and Alibaba client/server rewrites stay in Python so that
// all RNG-dependent semantics live in one place.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "json.hpp"

namespace tw {

struct Corpus {
  // Interned strings; index 0 is always "" so 0 can double as "empty".
  std::vector<std::string> strings;
  std::unordered_map<std::string, int32_t> intern_map;

  // Span SoA (parallel arrays).
  std::vector<double> start_mus, duration_mus;
  std::vector<int32_t> trace_sidx, sid_sidx, op_sidx, process_sidx;
  std::vector<int32_t> kind_sidx;  // verbatim span.kind tag value, -1 absent
  std::vector<int32_t> caller_sidx, callee_sidx;  // -1 = absent
  // References, flattened (a span may carry several): refs of span i are
  // [ref_offsets[i], ref_offsets[i+1]) in ref_trace/ref_sid.
  std::vector<int64_t> ref_offsets{0};
  std::vector<int32_t> ref_trace_sidx, ref_sid_sidx;

  // Trace boundaries: spans of trace t are [offsets[t], offsets[t+1]).
  std::vector<int64_t> trace_offsets{0};
  std::vector<int32_t> trace_id_sidx;
  std::vector<int32_t> trace_file;  // input-path index

  // Flattened per-trace process tables (trace index, pid, service).
  std::vector<int32_t> proc_trace, proc_pid, proc_service;

  std::string error;

  int32_t intern(const std::string& s) {
    auto it = intern_map.find(s);
    if (it != intern_map.end()) return it->second;
    int32_t idx = static_cast<int32_t>(strings.size());
    strings.push_back(s);
    intern_map.emplace(strings.back(), idx);
    return idx;
  }
};

namespace {

thread_local std::string g_last_error;

bool read_file(const char* path, std::string* out) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (n < 0) {
    std::fclose(f);
    return false;
  }
  out->resize(static_cast<size_t>(n));
  size_t got = n ? std::fread(&(*out)[0], 1, static_cast<size_t>(n), f) : 0;
  std::fclose(f);
  return got == static_cast<size_t>(n);
}

// Verbatim span.kind tag value, last occurrence winning — matching the
// Python front-end's tag loop exactly. Returns nullptr when absent.
const std::string* span_kind_of(const Json& span) {
  const Json* tags = span.find("tags");
  if (!tags || !tags->is_arr()) return nullptr;
  const std::string* kind = nullptr;
  for (const Json& tag : tags->arr) {
    const std::string* key = tag.find_str("key");
    if (key && *key == "span.kind") {
      const std::string* value = tag.find_str("value");
      kind = value;  // may be nullptr for a non-string value, like Python's
                     // tag.get("value") -> None
    }
  }
  return kind;
}

// Extract one trace object ({traceID, spans, processes}) into the corpus.
bool extract_trace(const Json& trace, int file_idx, Corpus* c) {
  const std::string* trace_id = trace.find_str("traceID");
  const Json* spans = trace.find("spans");
  if (!trace_id || !spans || !spans->is_arr()) {
    c->error = "trace object missing traceID/spans";
    return false;
  }
  int32_t tidx = static_cast<int32_t>(c->trace_id_sidx.size());
  c->trace_id_sidx.push_back(c->intern(*trace_id));
  c->trace_file.push_back(file_idx);

  for (const Json& s : spans->arr) {
    const std::string* sid = s.find_str("spanID");
    const std::string* span_trace = s.find_str("traceID");
    bool ok_start = false, ok_dur = false;
    double start = s.find_num("startTime", &ok_start);
    double dur = s.find_num("duration", &ok_dur);
    if (!sid || !span_trace || !ok_start || !ok_dur) {
      c->error = "span missing spanID/traceID/startTime/duration";
      return false;
    }
    // Alibaba-converted files carry requestType; it wins over operationName
    // (reference executor.py:358-360 via the converter's field layout).
    const std::string* op = s.find_str("requestType");
    if (!op) op = s.find_str("operationName");

    const std::string* pid = s.find_str("processID");

    // Every reference, in order (Python keeps the full list; parity).
    const Json* refs = s.find("references");
    if (refs && refs->is_arr()) {
      for (const Json& ref : refs->arr) {
        const std::string* ref_trace = ref.find_str("traceID");
        const std::string* ref_sid = ref.find_str("spanID");
        if (ref_trace && ref_sid) {
          c->ref_trace_sidx.push_back(c->intern(*ref_trace));
          c->ref_sid_sidx.push_back(c->intern(*ref_sid));
        }
      }
    }
    c->ref_offsets.push_back(static_cast<int64_t>(c->ref_trace_sidx.size()));

    const std::string* caller = s.find_str("caller");
    const std::string* callee = s.find_str("callee");
    const std::string* kind = span_kind_of(s);

    c->start_mus.push_back(start);
    c->duration_mus.push_back(dur);
    c->trace_sidx.push_back(c->intern(*span_trace));
    c->sid_sidx.push_back(c->intern(*sid));
    c->op_sidx.push_back(op ? c->intern(*op) : -1);
    c->process_sidx.push_back(pid ? c->intern(*pid) : -1);
    c->kind_sidx.push_back(kind ? c->intern(*kind) : -1);
    c->caller_sidx.push_back(caller ? c->intern(*caller) : -1);
    c->callee_sidx.push_back(callee ? c->intern(*callee) : -1);
  }
  c->trace_offsets.push_back(static_cast<int64_t>(c->start_mus.size()));

  const Json* procs = trace.find("processes");
  if (procs && procs->is_obj()) {
    for (size_t i = 0; i < procs->keys.size(); ++i) {
      const std::string* svc = procs->vals[i].find_str("serviceName");
      if (!svc) continue;
      c->proc_trace.push_back(tidx);
      c->proc_pid.push_back(c->intern(procs->keys[i]));
      c->proc_service.push_back(c->intern(*svc));
    }
  }
  return true;
}

}  // namespace
}  // namespace tw

extern "C" {

const char* tw_last_error() { return tw::g_last_error.c_str(); }

// Parse `n` Jaeger-JSON files into one corpus. JSON decoding runs across a
// thread pool; extraction/interning is a serial second phase so string ids
// are globally consistent. Returns nullptr (see tw_last_error) on failure.
tw::Corpus* tw_parse_files(const char* const* paths, long n) {
  std::vector<tw::Json> docs(static_cast<size_t>(n));
  std::vector<std::string> errors(static_cast<size_t>(n));
  std::atomic<long> next{0};

  unsigned hw = std::thread::hardware_concurrency();
  unsigned n_threads = hw ? hw : 4;
  if (static_cast<long>(n_threads) > n) n_threads = static_cast<unsigned>(n);

  auto worker = [&]() {
    std::string buf;
    for (long i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      if (!tw::read_file(paths[i], &buf)) {
        errors[i] = std::string("cannot read ") + paths[i];
        continue;
      }
      tw::JsonParser parser(buf.data(), buf.size());
      if (!parser.parse(&docs[i]))
        errors[i] = std::string(paths[i]) + ": " + parser.error();
    }
  };
  std::vector<std::thread> pool;
  for (unsigned t = 1; t < n_threads; ++t) pool.emplace_back(worker);
  worker();
  for (auto& t : pool) t.join();

  for (long i = 0; i < n; ++i) {
    if (!errors[i].empty()) {
      tw::g_last_error = errors[i];
      return nullptr;
    }
  }

  auto* corpus = new tw::Corpus();
  corpus->intern("");
  for (long i = 0; i < n; ++i) {
    const tw::Json* data = docs[i].find("data");
    if (!data || !data->is_arr()) {
      tw::g_last_error = std::string(paths[i]) + ": no data[] array";
      delete corpus;
      return nullptr;
    }
    for (const tw::Json& trace : data->arr) {
      if (!tw::extract_trace(trace, static_cast<int>(i), corpus)) {
        tw::g_last_error = std::string(paths[i]) + ": " + corpus->error;
        delete corpus;
        return nullptr;
      }
    }
    docs[i] = tw::Json();  // free the DOM as we go
  }
  return corpus;
}

// Parse one Jaeger-JSON POST body (already in memory — the serve path's
// accepted wire bytes) into a corpus. Same extraction/interning semantics
// as tw_parse_files with a single "file" at index 0; fail-fast on any
// malformed trace/span — the Python caller falls back to its own wire
// parser, which owns skip-and-count dead-letter accounting. Returns
// nullptr (see tw_last_error) on failure.
tw::Corpus* tw_parse_payload(const char* data, long n) {
  tw::Json doc;
  tw::JsonParser parser(data, static_cast<size_t>(n));
  if (!parser.parse(&doc)) {
    tw::g_last_error = std::string("payload: ") + parser.error();
    return nullptr;
  }
  const tw::Json* entries = doc.find("data");
  if (!entries || !entries->is_arr()) {
    tw::g_last_error = "payload: no data[] array";
    return nullptr;
  }
  auto* corpus = new tw::Corpus();
  corpus->intern("");
  for (const tw::Json& trace : entries->arr) {
    if (!tw::extract_trace(trace, 0, corpus)) {
      tw::g_last_error = std::string("payload: ") + corpus->error;
      delete corpus;
      return nullptr;
    }
  }
  return corpus;
}

void tw_corpus_free(tw::Corpus* c) { delete c; }

long tw_num_spans(const tw::Corpus* c) {
  return static_cast<long>(c->start_mus.size());
}
long tw_num_traces(const tw::Corpus* c) {
  return static_cast<long>(c->trace_id_sidx.size());
}
long tw_num_strings(const tw::Corpus* c) {
  return static_cast<long>(c->strings.size());
}
const char* tw_string(const tw::Corpus* c, long i) {
  return c->strings[static_cast<size_t>(i)].c_str();
}

const double* tw_span_start(const tw::Corpus* c) { return c->start_mus.data(); }
const double* tw_span_duration(const tw::Corpus* c) {
  return c->duration_mus.data();
}
const int32_t* tw_span_trace(const tw::Corpus* c) {
  return c->trace_sidx.data();
}
const int32_t* tw_span_sid(const tw::Corpus* c) { return c->sid_sidx.data(); }
const int32_t* tw_span_op(const tw::Corpus* c) { return c->op_sidx.data(); }
const int32_t* tw_span_process(const tw::Corpus* c) {
  return c->process_sidx.data();
}
const int32_t* tw_span_kind(const tw::Corpus* c) {
  return c->kind_sidx.data();
}
long tw_num_refs(const tw::Corpus* c) {
  return static_cast<long>(c->ref_trace_sidx.size());
}
const int64_t* tw_span_ref_offsets(const tw::Corpus* c) {
  return c->ref_offsets.data();
}
const int32_t* tw_ref_trace(const tw::Corpus* c) {
  return c->ref_trace_sidx.data();
}
const int32_t* tw_ref_sid(const tw::Corpus* c) {
  return c->ref_sid_sidx.data();
}
const int32_t* tw_span_caller(const tw::Corpus* c) {
  return c->caller_sidx.data();
}
const int32_t* tw_span_callee(const tw::Corpus* c) {
  return c->callee_sidx.data();
}

const int64_t* tw_trace_span_offsets(const tw::Corpus* c) {
  return c->trace_offsets.data();
}
const int32_t* tw_trace_id(const tw::Corpus* c) {
  return c->trace_id_sidx.data();
}
const int32_t* tw_trace_file(const tw::Corpus* c) {
  return c->trace_file.data();
}

long tw_num_process_entries(const tw::Corpus* c) {
  return static_cast<long>(c->proc_trace.size());
}
const int32_t* tw_process_trace(const tw::Corpus* c) {
  return c->proc_trace.data();
}
const int32_t* tw_process_pid(const tw::Corpus* c) {
  return c->proc_pid.data();
}
const int32_t* tw_process_service(const tw::Corpus* c) {
  return c->proc_service.data();
}

// Root-span start time of the first trace in a file — the sort key for
// time-ordered directory listing (reference executor.py:287-318). Returns
// +inf when the file has no rooted span (matching the Python fallback).
double tw_root_start_time(const char* path) {
  std::string buf;
  if (!tw::read_file(path, &buf)) return HUGE_VAL;
  tw::Json doc;
  tw::JsonParser parser(buf.data(), buf.size());
  if (!parser.parse(&doc)) return HUGE_VAL;
  const tw::Json* data = doc.find("data");
  if (!data || !data->is_arr() || data->arr.empty()) return HUGE_VAL;
  const tw::Json* spans = data->arr[0].find("spans");
  if (!spans || !spans->is_arr()) return HUGE_VAL;
  for (const tw::Json& s : spans->arr) {
    const tw::Json* refs = s.find("references");
    if (!refs || !refs->is_arr() || refs->arr.empty()) {
      bool ok = false;
      double t = s.find_num("startTime", &ok);
      if (ok) return t;
    }
  }
  return HUGE_VAL;
}

}  // extern "C"
