// Native reconstruction schemes over packed span arrays.
//
// The reference ships a C++ plugin skeleton — an abstract
// `Scheme::FindAssignments()` and an empty `Fcfs` subclass
// (reference: src/trace_reconstructor/ports/cpp/scheme.h:4-11,
// fcfs.h:6-13, fcfs.cpp — all `//!TODO`). This file is the real thing:
// the same plugin shape, implemented over struct-of-arrays inputs so the
// Python layer can hand a whole service partition across the FFI in one
// call. Assignment semantics mirror the Python baselines exactly
// (reference: ports/python/algorithms/{fcfs.py:1-26, vpath.py:36-89,
// vpath_old.py:1-31}); equivalence is asserted in tests/test_native.py.

#include <algorithm>
#include <cstdint>
#include <unordered_map>
#include <vector>

namespace tw {

// One service's assignment problem: a single incoming partition plus all
// outgoing spans tagged with their endpoint index. Times are microseconds;
// trace ids are interned ints (any consistent numbering works).
struct ServiceProblem {
  const double* in_start;
  const double* in_end;
  const int32_t* in_trace;
  long n_in;
  const double* out_start;
  const double* out_end;
  const int32_t* out_ep;
  const int32_t* out_trace;
  long n_out;
  long n_eps;
};

// Plugin contract, native edition: fill assign[ep * n_in + i] with the
// outgoing-span index matched to incoming span i on endpoint ep, -1 = NA.
class Scheme {
 public:
  virtual ~Scheme() = default;
  virtual void FindAssignments(const ServiceProblem& p, int32_t* assign) = 0;
};

// First-come-first-served: the i-th incoming span takes the i-th outgoing
// span of every endpoint, both sides in start-time order.
class Fcfs : public Scheme {
 public:
  void FindAssignments(const ServiceProblem& p, int32_t* assign) override {
    std::fill(assign, assign + p.n_eps * p.n_in, -1);
    // Per-endpoint arrival order of outgoing spans.
    std::vector<long> count(static_cast<size_t>(p.n_eps), 0);
    std::vector<long> order(static_cast<size_t>(p.n_out));
    for (long j = 0; j < p.n_out; ++j) order[j] = j;
    std::stable_sort(order.begin(), order.end(), [&](long a, long b) {
      return p.out_start[a] < p.out_start[b];
    });
    for (long j : order) {
      long ep = p.out_ep[j];
      long i = count[ep]++;
      if (i < p.n_in) assign[ep * p.n_in + i] = static_cast<int32_t>(j);
    }
  }
};

// vPath single time-ordered event sweep: a server request makes its span
// the latest in-flight incoming span, a server response clears it, a client
// request attaches to it, and a client response restores the in-flight span
// to the incoming span of the same trace (thread-serialized processing).
class VPathSweep : public Scheme {
  struct Event {
    double t;
    int sort_key;   // 1 in-req, 2 out-req, 3 out-resp, 4 in-resp
    bool is_server;
    bool is_request;
    long idx;       // span index on its own side
  };

 public:
  void FindAssignments(const ServiceProblem& p, int32_t* assign) override {
    std::fill(assign, assign + p.n_eps * p.n_in, -1);
    std::vector<Event> events;
    events.reserve(static_cast<size_t>(2 * (p.n_in + p.n_out)));
    for (long i = 0; i < p.n_in; ++i) {
      events.push_back({p.in_start[i], 1, true, true, i});
      events.push_back({p.in_end[i], 4, true, false, i});
    }
    for (long j = 0; j < p.n_out; ++j) {
      events.push_back({p.out_start[j], 2, false, true, j});
      events.push_back({p.out_end[j], 3, false, false, j});
    }
    // Stable sort on (time, sort_key) keeps insertion order for full ties,
    // matching Python's list.sort over the same construction order.
    std::stable_sort(events.begin(), events.end(),
                     [](const Event& a, const Event& b) {
                       if (a.t != b.t) return a.t < b.t;
                       return a.sort_key < b.sort_key;
                     });

    // trace id -> first incoming span with that trace (partition order).
    std::unordered_map<int32_t, long> in_by_trace;
    for (long i = 0; i < p.n_in; ++i)
      in_by_trace.emplace(p.in_trace[i], i);

    long latest_incoming = -1;
    for (const Event& e : events) {
      if (e.is_server) {
        latest_incoming = e.is_request ? e.idx : -1;
      } else if (e.is_request) {
        if (latest_incoming >= 0) {
          long ep = p.out_ep[e.idx];
          assign[ep * p.n_in + latest_incoming] = static_cast<int32_t>(e.idx);
        }
      } else {
        auto it = in_by_trace.find(p.out_trace[e.idx]);
        if (it != in_by_trace.end()) latest_incoming = it->second;
      }
    }
  }
};

// vPathOld per-endpoint pointer sweep: each incoming span claims the next
// outgoing span starting after it but before the next incoming span starts.
class VPathOldSweep : public Scheme {
 public:
  void FindAssignments(const ServiceProblem& p, int32_t* assign) override {
    std::fill(assign, assign + p.n_eps * p.n_in, -1);
    // Per-endpoint outgoing spans in start order.
    std::vector<std::vector<long>> by_ep(static_cast<size_t>(p.n_eps));
    std::vector<long> order(static_cast<size_t>(p.n_out));
    for (long j = 0; j < p.n_out; ++j) order[j] = j;
    std::stable_sort(order.begin(), order.end(), [&](long a, long b) {
      return p.out_start[a] < p.out_start[b];
    });
    for (long j : order) by_ep[p.out_ep[j]].push_back(j);

    for (long ep = 0; ep < p.n_eps; ++ep) {
      const std::vector<long>& outs = by_ep[ep];
      size_t j = 0;
      for (long i = 0; i < p.n_in; ++i) {
        while (j < outs.size() && p.out_start[outs[j]] < p.in_start[i]) ++j;
        if (j >= outs.size()) break;
        bool is_last = i == p.n_in - 1;
        if (p.out_start[outs[j]] >= p.in_start[i] &&
            (is_last || p.out_start[outs[j]] < p.in_start[i + 1])) {
          assign[ep * p.n_in + i] = static_cast<int32_t>(outs[j]);
          ++j;
        }
      }
    }
  }
};

}  // namespace tw

extern "C" {

static void run_scheme(tw::Scheme&& scheme, const double* in_start,
                       const double* in_end, const int32_t* in_trace,
                       long n_in, const double* out_start,
                       const double* out_end, const int32_t* out_ep,
                       const int32_t* out_trace, long n_out, long n_eps,
                       int32_t* assign) {
  tw::ServiceProblem p{in_start, in_end, in_trace, n_in,
                       out_start, out_end, out_ep, out_trace, n_out, n_eps};
  scheme.FindAssignments(p, assign);
}

void tw_fcfs_assign(const double* in_start, const double* in_end,
                    const int32_t* in_trace, long n_in,
                    const double* out_start, const double* out_end,
                    const int32_t* out_ep, const int32_t* out_trace,
                    long n_out, long n_eps, int32_t* assign) {
  run_scheme(tw::Fcfs(), in_start, in_end, in_trace, n_in, out_start, out_end,
             out_ep, out_trace, n_out, n_eps, assign);
}

void tw_vpath_assign(const double* in_start, const double* in_end,
                     const int32_t* in_trace, long n_in,
                     const double* out_start, const double* out_end,
                     const int32_t* out_ep, const int32_t* out_trace,
                     long n_out, long n_eps, int32_t* assign) {
  run_scheme(tw::VPathSweep(), in_start, in_end, in_trace, n_in, out_start,
             out_end, out_ep, out_trace, n_out, n_eps, assign);
}

void tw_vpath_old_assign(const double* in_start, const double* in_end,
                         const int32_t* in_trace, long n_in,
                         const double* out_start, const double* out_end,
                         const int32_t* out_ep, const int32_t* out_trace,
                         long n_out, long n_eps, int32_t* assign) {
  run_scheme(tw::VPathOldSweep(), in_start, in_end, in_trace, n_in, out_start,
             out_end, out_ep, out_trace, n_out, n_eps, assign);
}

}  // extern "C"
