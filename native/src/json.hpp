// Minimal recursive-descent JSON parser (DOM), self-contained — the image
// ships no jsoncpp. Sufficient for Jaeger trace files: objects, arrays,
// strings (with escapes incl. \uXXXX surrogate pairs), numbers as double
// (microsecond epoch timestamps are < 2^53, so exact), true/false/null.
//
// Replaces the reference's jsoncpp-based loader stub
// (reference: src/trace_reconstructor/ports/cpp/main.cpp:6-21, Makefile:1-25)
// with a real implementation.
#pragma once

#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace tw {

struct Json {
  enum class Type { Null, Bool, Num, Str, Arr, Obj };
  Type type = Type::Null;
  bool boolean = false;
  double num = 0.0;
  std::string str;
  std::vector<Json> arr;          // Type::Arr elements
  std::vector<std::string> keys;  // Type::Obj keys, parallel to vals
  std::vector<Json> vals;         // Type::Obj values

  bool is_obj() const { return type == Type::Obj; }
  bool is_arr() const { return type == Type::Arr; }
  bool is_str() const { return type == Type::Str; }
  bool is_num() const { return type == Type::Num; }

  const Json* find(const char* key) const {
    if (type != Type::Obj) return nullptr;
    for (size_t i = 0; i < keys.size(); ++i)
      if (keys[i] == key) return &vals[i];
    return nullptr;
  }
  // Convenience: string field or fallback.
  const std::string* find_str(const char* key) const {
    const Json* v = find(key);
    return (v && v->is_str()) ? &v->str : nullptr;
  }
  // Convenience: numeric field; ok=false if absent / not a number.
  double find_num(const char* key, bool* ok) const {
    const Json* v = find(key);
    if (v && v->is_num()) {
      if (ok) *ok = true;
      return v->num;
    }
    if (ok) *ok = false;
    return 0.0;
  }
};

class JsonParser {
 public:
  JsonParser(const char* data, size_t len) : p_(data), end_(data + len) {}

  // Parses one JSON document. Returns false (with error()) on malformed
  // input; trailing whitespace is allowed, trailing garbage is not.
  bool parse(Json* out) {
    skip_ws();
    if (!parse_value(out)) return false;
    skip_ws();
    if (p_ != end_) return fail("trailing characters after document");
    return true;
  }

  const std::string& error() const { return error_; }

 private:
  const char* p_;
  const char* end_;
  std::string error_;

  bool fail(const char* msg) {
    if (error_.empty()) error_ = msg;
    return false;
  }

  void skip_ws() {
    while (p_ != end_ &&
           (*p_ == ' ' || *p_ == '\t' || *p_ == '\n' || *p_ == '\r'))
      ++p_;
  }

  bool parse_value(Json* out) {
    if (p_ == end_) return fail("unexpected end of input");
    switch (*p_) {
      case '{': return parse_object(out);
      case '[': return parse_array(out);
      case '"':
        out->type = Json::Type::Str;
        return parse_string(&out->str);
      case 't':
        if (end_ - p_ >= 4 && std::memcmp(p_, "true", 4) == 0) {
          p_ += 4;
          out->type = Json::Type::Bool;
          out->boolean = true;
          return true;
        }
        return fail("bad literal");
      case 'f':
        if (end_ - p_ >= 5 && std::memcmp(p_, "false", 5) == 0) {
          p_ += 5;
          out->type = Json::Type::Bool;
          out->boolean = false;
          return true;
        }
        return fail("bad literal");
      case 'n':
        if (end_ - p_ >= 4 && std::memcmp(p_, "null", 4) == 0) {
          p_ += 4;
          out->type = Json::Type::Null;
          return true;
        }
        return fail("bad literal");
      default: return parse_number(out);
    }
  }

  bool parse_object(Json* out) {
    out->type = Json::Type::Obj;
    ++p_;  // '{'
    skip_ws();
    if (p_ != end_ && *p_ == '}') {
      ++p_;
      return true;
    }
    while (true) {
      skip_ws();
      if (p_ == end_ || *p_ != '"') return fail("expected object key");
      std::string key;
      if (!parse_string(&key)) return false;
      skip_ws();
      if (p_ == end_ || *p_ != ':') return fail("expected ':'");
      ++p_;
      skip_ws();
      out->keys.push_back(std::move(key));
      out->vals.emplace_back();
      if (!parse_value(&out->vals.back())) return false;
      skip_ws();
      if (p_ == end_) return fail("unterminated object");
      if (*p_ == ',') {
        ++p_;
        continue;
      }
      if (*p_ == '}') {
        ++p_;
        return true;
      }
      return fail("expected ',' or '}'");
    }
  }

  bool parse_array(Json* out) {
    out->type = Json::Type::Arr;
    ++p_;  // '['
    skip_ws();
    if (p_ != end_ && *p_ == ']') {
      ++p_;
      return true;
    }
    while (true) {
      skip_ws();
      out->arr.emplace_back();
      if (!parse_value(&out->arr.back())) return false;
      skip_ws();
      if (p_ == end_) return fail("unterminated array");
      if (*p_ == ',') {
        ++p_;
        continue;
      }
      if (*p_ == ']') {
        ++p_;
        return true;
      }
      return fail("expected ',' or ']'");
    }
  }

  bool parse_number(Json* out) {
    char* num_end = nullptr;
    double v = std::strtod(p_, &num_end);
    if (num_end == p_) return fail("bad number");
    p_ = num_end;
    out->type = Json::Type::Num;
    out->num = v;
    return true;
  }

  static void append_utf8(std::string* s, unsigned cp) {
    if (cp < 0x80) {
      s->push_back(static_cast<char>(cp));
    } else if (cp < 0x800) {
      s->push_back(static_cast<char>(0xC0 | (cp >> 6)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      s->push_back(static_cast<char>(0xE0 | (cp >> 12)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else {
      s->push_back(static_cast<char>(0xF0 | (cp >> 18)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    }
  }

  bool parse_hex4(unsigned* out) {
    if (end_ - p_ < 4) return fail("bad \\u escape");
    unsigned v = 0;
    for (int i = 0; i < 4; ++i) {
      char c = p_[i];
      v <<= 4;
      if (c >= '0' && c <= '9') v |= static_cast<unsigned>(c - '0');
      else if (c >= 'a' && c <= 'f') v |= static_cast<unsigned>(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F') v |= static_cast<unsigned>(c - 'A' + 10);
      else return fail("bad \\u escape");
    }
    p_ += 4;
    *out = v;
    return true;
  }

  bool parse_string(std::string* out) {
    ++p_;  // opening quote
    out->clear();
    // Fast path: scan for a segment free of escapes.
    while (true) {
      const char* seg = p_;
      while (p_ != end_ && *p_ != '"' && *p_ != '\\') ++p_;
      out->append(seg, static_cast<size_t>(p_ - seg));
      if (p_ == end_) return fail("unterminated string");
      if (*p_ == '"') {
        ++p_;
        return true;
      }
      ++p_;  // backslash
      if (p_ == end_) return fail("unterminated escape");
      char c = *p_++;
      switch (c) {
        case '"': out->push_back('"'); break;
        case '\\': out->push_back('\\'); break;
        case '/': out->push_back('/'); break;
        case 'b': out->push_back('\b'); break;
        case 'f': out->push_back('\f'); break;
        case 'n': out->push_back('\n'); break;
        case 'r': out->push_back('\r'); break;
        case 't': out->push_back('\t'); break;
        case 'u': {
          unsigned cp;
          if (!parse_hex4(&cp)) return false;
          if (cp >= 0xD800 && cp <= 0xDBFF && end_ - p_ >= 6 &&
              p_[0] == '\\' && p_[1] == 'u') {
            p_ += 2;
            unsigned lo;
            if (!parse_hex4(&lo)) return false;
            if (lo >= 0xDC00 && lo <= 0xDFFF)
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            else
              return fail("bad surrogate pair");
          }
          append_utf8(out, cp);
          break;
        }
        default: return fail("bad escape character");
      }
    }
  }
};

}  // namespace tw
