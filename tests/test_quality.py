"""Reconstruction-quality observability tests (tier-1, CPU — ISSUE 10).

Contracts covered (docs/OBSERVABILITY.md "Quality telemetry"):

- per-span confidence records ride every fleet solve (base tier: plan
  support + OT-override from the EXISTING packed channels — default
  device programs untouched); quarantined windows score zero;
- the device tier (``TW_CONF_DEVICE=1``) adds quantized margin/entropy
  channels as ONE extra program variant: assignments identical to the
  base program, and a second enabled solve costs zero backend compiles;
- every trace emitted by the stream sink carries ``tw.confidence``;
  per-tenant ``tw_trace_confidence`` histograms + low-confidence
  counters land in the obs registry;
- the serve ring records carry per-trace confidence, the
  ``low_confidence`` query ranks ascending, and the delay-culprit
  bracket's ``min_confidence`` filter excludes (counted) low-trust
  reconstructions;
- the PSI drift watcher freezes a reference, tracks the rolling
  distribution, alerts ONCE per excursion into the event sink;
- calibration: accuracy bucketed by confidence decile via the scorecard
  harness — top decile >= bottom decile on the synthetic labeled
  corpus, monotone-ish check noise-aware (field unit-tested);
- the registry's label-cardinality guard collapses past-cap label sets
  into one counted ``overflow="1"`` series.
"""

import json

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")

import networkx as nx

from traceweaver_tpu.obs import events as obs_events
from traceweaver_tpu.obs import quality
from traceweaver_tpu.spans import Span
from traceweaver_tpu.metrics import get_ground_truth
from traceweaver_tpu.metrics.accuracy import (
    accuracy_by_confidence_decile,
    calibration_monotone,
    overlap_fraction,
    service_regime,
)

pytestmark = pytest.mark.quality


# ---------------------------------------------------------------------------
# helpers: a small solvable service problem
# ---------------------------------------------------------------------------

def _service_problem(n=20, burst=1, jitter=2.0, n_eps=2, seed=0):
    rng = np.random.default_rng(seed)
    in_spans, out_parts = [], {f"EP{e}": [] for e in range(n_eps)}
    t = 0.0
    for i in range(n):
        t += 40.0 if (burst > 1 and i % burst) else 5000.0
        tid = f"t{i:03d}"
        in_spans.append(Span(tid, "in", t, 900.0, "op", [], "svc", "server"))
        for e in range(n_eps):
            start = t + 30.0 + 90.0 * e + float(rng.normal(0, jitter))
            out_parts[f"EP{e}"].append(
                Span(tid, f"c{e}", max(start, t + 1.0), 40.0, f"call{e}",
                     [(tid, "in")], "svc", "client"))
    for ep in out_parts:
        out_parts[ep].sort(key=lambda s: (s.start_mus, s.sid))
    in_parts = {"IN": in_spans}
    truth = get_ground_truth(in_parts, out_parts)
    dag = nx.DiGraph()
    dag.add_nodes_from(out_parts)
    return in_parts, out_parts, truth, dag


def _solve(in_parts, out_parts, truth, dag, **fleet_kw):
    from traceweaver_tpu.algorithms.fleet import FleetItem, solve_fleet

    item = FleetItem("svc", in_parts, out_parts, truth, dag)
    confs = [None]
    outs = solve_fleet([item], confidences=confs, **fleet_kw)
    return outs[0], confs[0]


# ---------------------------------------------------------------------------
# knobs + score math
# ---------------------------------------------------------------------------

def test_quality_knobs_registered():
    from traceweaver_tpu.runtime import knobs

    for name in ("TW_CONFIDENCE", "TW_CONF_DEVICE", "TW_CONF_LOW",
                 "TW_CONF_DRIFT_PSI", "TW_CONF_DRIFT_WINDOW",
                 "TW_METRICS_MAX_SERIES"):
        assert name in knobs.REGISTRY
    assert knobs.get_bool("TW_CONFIDENCE") is True
    assert knobs.get_bool("TW_CONF_DEVICE") is False


def test_confidence_scores_monotone_in_inputs():
    """The score must fall with more credible alternatives and with an
    OT override — in both tiers (the calibration table leans on this)."""
    base = dict(not_best=np.array([False, False, True, False]),
                cands=np.array([1, 8, 8, 64]),
                support=np.array([1, 2, 2, 5]))
    conf = quality.confidence_scores(base)
    assert conf[0] == 1.0
    assert conf[1] < conf[0] and conf[3] < conf[1]   # support grows
    assert conf[2] == pytest.approx(conf[1] / 2)     # override halves
    dev = dict(base, margin=np.array([5.0, 1.0, 1.0, 0.0]),
               entropy=np.zeros(4))
    dconf = quality.confidence_scores(dev)
    assert dconf[0] > dconf[1] > dconf[3]            # margin thins
    assert dconf[2] == pytest.approx(dconf[1] / 2)
    assert dconf[3] == 0.0                           # dead tie: no trust


# ---------------------------------------------------------------------------
# fleet path: records, quarantine, device tier
# ---------------------------------------------------------------------------

def test_fleet_solve_fills_confidence_records():
    in_parts, out_parts, truth, dag = _service_problem(n=16)
    out, recs = _solve(in_parts, out_parts, truth, dag)
    in_ids = {s.GetId() for s in in_parts["IN"]}
    assert set(recs) == in_ids
    for rec in recs.values():
        assert 0.0 < rec["conf"] <= 1.0
        assert rec["support"] >= 1 and rec["cands"] >= 1
    # sequential geometry: the solver is certain and right
    assert out[3] == 16
    assert all(r["conf"] == 1.0 for r in recs.values())


def test_overlapping_geometry_lowers_confidence():
    seq = _solve(*_service_problem(n=24, burst=1))[1]
    hard = _solve(*_service_problem(n=24, burst=6, jitter=35.0))[1]
    mean = lambda rs: sum(r["conf"] for r in rs.values()) / len(rs)  # noqa: E731
    assert mean(hard) < mean(seq)
    assert any(r["support"] > 1 for r in hard.values())


def test_quarantined_item_scores_zero_confidence(monkeypatch):
    from traceweaver_tpu.runtime import faults

    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    in_parts, out_parts, truth, dag = _service_problem(n=8)
    with faults.override("dispatch:1.0,host:1.0", seed=0):
        out, recs = _solve(in_parts, out_parts, truth, dag)
    assert out[5] == 8  # all-NA quarantine result
    assert recs and all(r["conf"] == 0.0 for r in recs.values())


def test_conf_device_variant_identical_and_zero_recompiles(monkeypatch):
    """TW_CONF_DEVICE is a static program variant: assignments equal the
    base program's, margin/entropy ride the records, and the SECOND
    enabled solve costs zero backend compiles (the acceptance pin)."""
    from traceweaver_tpu.runtime.jax_cache import (
        compile_counters,
        counters_delta,
    )

    prob = _service_problem(n=24, burst=6, jitter=35.0)
    base_out, base_recs = _solve(*prob)
    monkeypatch.setenv("TW_CONF_DEVICE", "1")
    dev_out, dev_recs = _solve(*prob)
    assert dev_out[0] == base_out[0]  # same assignments per endpoint
    assert all("margin" in r and "entropy" in r for r in dev_recs.values())
    assert any(r["entropy"] > 0 for r in dev_recs.values())
    before = compile_counters()
    dev_out2, dev_recs2 = _solve(*prob)
    assert counters_delta(before)["backend_compiles"] == 0
    assert dev_recs2 == dev_recs
    # margins thin exactly where the base tier saw contested support
    contested = [sid for sid, r in base_recs.items() if r["support"] > 1]
    assert contested
    assert min(dev_recs[sid]["margin"] for sid in contested) < 4.0


def test_confidence_disabled_kills_the_path(monkeypatch):
    monkeypatch.setenv("TW_CONFIDENCE", "0")
    from traceweaver_tpu.algorithms.fleet import FleetItem, solve_fleet

    in_parts, out_parts, truth, dag = _service_problem(n=8)
    confs = [None]
    outs = solve_fleet([FleetItem("svc", in_parts, out_parts, truth, dag)],
                       confidences=confs)
    assert outs[0][3] == 8
    assert confs[0] is None  # no records computed


# ---------------------------------------------------------------------------
# stream emission surface
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def stream_corpus(tmp_path_factory):
    from traceweaver_tpu.alibaba.synthesize import synthesize_corpus
    from traceweaver_tpu.ingest import load_corpus

    root = tmp_path_factory.mktemp("quality_corpus")
    dirs = synthesize_corpus(str(root / "cg"), n_graphs=1,
                             traces_per_graph=40, seed=7)
    return load_corpus(dirs[0], fix=5, max_traces=40, cache=False)


def test_stream_sink_records_carry_tw_confidence(stream_corpus, tmp_path):
    from traceweaver_tpu.obs.registry import get_registry
    from traceweaver_tpu.stream import (
        ReplaySource,
        StreamConfig,
        StreamingReconstructor,
        TraceSink,
    )

    sink_path = str(tmp_path / "out.jsonl")
    cfg = StreamConfig(window_us=20e6, overlap_us=4e6, ooo_bound_us=1e6,
                       checkpoint_every=10_000, verbose=False)
    svc = StreamingReconstructor(
        ReplaySource(stream_corpus, ooo_us=0.0, seed=1), cfg,
        sink=TraceSink(sink_path))
    before = get_registry().snapshot()
    summary = svc.run()
    after = get_registry().snapshot()
    assert summary["confidence"]["enabled"]

    recs = [json.loads(line) for line in open(sink_path)]
    assert recs
    with_conf = [r for r in recs if "tw.confidence" in r]
    assert with_conf, "no emitted window carried tw.confidence"
    n_scored_traces = 0
    for rec in with_conf:
        win = rec["tw.confidence"]["window"]
        assert win["n"] > 0 and 0.0 <= win["min"] <= 1.0
        for tid, tconf in rec["tw.confidence"]["traces"].items():
            assert tid in rec["traces"]
            if tconf is not None:
                assert 0.0 <= tconf["conf"] <= 1.0
                n_scored_traces += 1
    assert n_scored_traces > 0
    # the per-tenant histogram saw every scored trace (tenant "default")
    key = 'tw_trace_confidence_count{tenant="default"}'
    assert after.get(key, 0) - before.get(key, 0) == n_scored_traces


# ---------------------------------------------------------------------------
# serve surface: ring confidence, low_confidence query, culprit filter
# ---------------------------------------------------------------------------

def _hotel_payload(n=24, prefix="q"):
    from tests.test_serve import hotel_payload

    return hotel_payload(n_traces=n, prefix=prefix)


def test_serve_ring_low_confidence_and_culprit_filter(tmp_path):
    from traceweaver_tpu.serve import ServeConfig, TenantService

    svc = TenantService(ServeConfig(
        fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
        verbose=False, pump_windows=10**9))
    svc.ingest("acme", _hotel_payload())
    svc.flush()

    recs = svc.tenants["acme"].ring.records()
    assert recs
    scored = [r for r in recs if "tw.confidence" in r]
    assert scored, "ring records carry no tw.confidence"
    for r in scored:
        assert 0.0 <= r["tw.confidence"]["conf"] <= 1.0

    low = svc.query_low_confidence("acme", limit=5, max_conf=1.0)
    assert low["n_scored"] == len(scored)
    confs = [t["confidence"] for t in low["traces"]]
    assert confs == sorted(confs)

    # an impossible bar excludes every scored record — counted, and the
    # unfiltered result is unchanged
    res_all = svc.query_delay_culprit("acme", percentile=0.5)
    res_f = svc.query_delay_culprit("acme", percentile=0.5,
                                    min_confidence=1.01)
    assert res_f["n_low_confidence_excluded"] == len(scored)
    assert res_f["n_traces"] == res_all["n_traces"] - len(scored)
    assert res_all["n_low_confidence_excluded"] == 0

    # /metrics exposition carries the per-tenant confidence histogram
    from traceweaver_tpu.obs.exposition import render_metrics

    text = render_metrics(extra=svc.metrics_families())
    assert any(line.startswith("tw_trace_confidence_bucket{")
               and 'tenant="acme"' in line
               for line in text.splitlines())


def test_serve_http_low_confidence_endpoint(tmp_path):
    import urllib.request

    from traceweaver_tpu.serve import ServeConfig, TenantService
    from traceweaver_tpu.serve.http import make_server

    svc = TenantService(ServeConfig(
        fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
        verbose=False, pump_windows=10**9))
    svc.ingest("acme", _hotel_payload(prefix="h"))
    svc.flush()
    server = make_server(svc)
    import threading

    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        url = (f"http://127.0.0.1:{server.port}/api/v1/tenants/acme/"
               "query/low_confidence?limit=3&max_conf=1.0")
        body = json.loads(urllib.request.urlopen(url).read())
        assert body["n_scored"] > 0
        assert len(body["traces"]) <= 3
        url2 = (f"http://127.0.0.1:{server.port}/api/v1/tenants/acme/"
                "query/delay_culprit?percentile=0.5&min_conf=1.01")
        body2 = json.loads(urllib.request.urlopen(url2).read())
        assert body2["n_low_confidence_excluded"] > 0
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# drift watcher
# ---------------------------------------------------------------------------

def test_drift_psi_reference_rolling_and_single_alert(tmp_path):
    log_path = str(tmp_path / "events.jsonl")
    prev = obs_events.install(obs_events.EventLog(log_path))
    try:
        d = quality.ConfidenceDrift(window=16, threshold=0.2)
        # freeze the reference on a high-confidence regime
        assert d.update("svc", [0.9] * 16) is None or True
        stat = d.update("svc", [0.9] * 16)
        assert stat is not None and stat < 0.05
        assert d.alerts == 0
        # regime shift: confidence collapses -> PSI crosses, ONE alert
        stat = d.update("svc", [0.2] * 16)
        assert stat > 0.2
        assert d.alerts == 1
        d.update("svc", [0.2] * 16)  # sustained shift: no alert flood
        assert d.alerts == 1
        # recovery re-arms: a second excursion alerts again
        d.update("svc", [0.9] * 16)
        d.update("svc", [0.2] * 16)
        assert d.alerts == 2
    finally:
        obs_events.install(prev)
    events = [json.loads(line) for line in open(log_path)]
    shifts = [e for e in events if e.get("kind") == "confidence_drift"]
    assert len(shifts) == 2
    assert shifts[0]["key"] == "svc" and shifts[0]["psi"] > 0.2


def test_drift_mature_gauge_and_immature_psi_not_actionable():
    """ISSUE 19 satellite (the CAMPAIGN_r18 psi=6.17 scrape): right
    after the reference freezes, the rolling window is thin and its PSI
    is sampling noise — mature() must be False there (the adapt ladder
    gates on it, stream/service.py passes psi=None to the controller),
    and the explicit tw_confidence_drift_mature gauge must export 0 so
    a scrape can tell a thin-window excursion from a real shift."""
    from traceweaver_tpu.obs.registry import get_registry

    d = quality.ConfidenceDrift(window=16, threshold=0.2)
    d.update("k", [0.9] * 16)            # freezes the reference
    # thin rolling window: PSI exports (operators can weigh it) but the
    # key is NOT mature — this is exactly the r18 excursion shape
    stat = d.update("k", [0.2] * 4)
    assert stat is not None
    assert d.mature("k") is False
    snap = get_registry().snapshot()
    assert snap.get('tw_confidence_drift_mature{key="k"}') == 0.0
    assert snap.get('tw_confidence_drift_psi{key="k"}') == stat
    # a full rolling window matures the key and flips the gauge
    d.update("k", [0.2] * 16)
    assert d.mature("k") is True
    snap = get_registry().snapshot()
    assert snap.get('tw_confidence_drift_mature{key="k"}') == 1.0


def test_drift_state_roundtrip():
    d = quality.ConfidenceDrift(window=8, threshold=0.3)
    d.update("a", [0.8] * 8)
    d.update("a", [0.7] * 4)
    d2 = quality.ConfidenceDrift.from_state(d.state())
    assert d2.last_psi("a") == d.last_psi("a")
    assert d2.window == 8 and d2.threshold == 0.3


# ---------------------------------------------------------------------------
# calibration + regimes + scorecard
# ---------------------------------------------------------------------------

def test_regime_classifier():
    seq = _service_problem(n=12, burst=1)
    asy = _service_problem(n=12, burst=6, jitter=35.0)
    fan = _service_problem(n=12, burst=6, jitter=35.0, n_eps=5)
    assert service_regime(seq[0], seq[1])["regime"] == "sequential"
    assert service_regime(asy[0], asy[1])["regime"] == "async"
    assert service_regime(fan[0], fan[1])["regime"] == "fanout"
    assert overlap_fraction(seq[0]["IN"]) == 0.0
    assert overlap_fraction(asy[0]["IN"]) > 0.5


def test_accuracy_by_confidence_decile_and_monotone_check():
    conf = {("t", str(i)): i / 100.0 for i in range(100)}
    # perfectly calibrated: correctness tracks confidence
    correct = {sid: c >= 0.5 for sid, c in conf.items()}
    table = accuracy_by_confidence_decile(conf, correct, nbins=10)
    assert [row["decile"] for row in table] == list(range(1, 11))
    assert sum(row["n"] for row in table) == 100
    assert table[0]["accuracy"] == 0.0 and table[-1]["accuracy"] == 1.0
    ok, violations = calibration_monotone(table)
    assert ok and not violations
    # a REAL inversion (confidently wrong at scale) fails despite the
    # noise-aware slack
    bad = [dict(decile=1, conf_lo=0.0, conf_hi=0.2, n=400, accuracy=0.9),
           dict(decile=2, conf_lo=0.8, conf_hi=1.0, n=400, accuracy=0.3)]
    ok, violations = calibration_monotone(bad)
    assert not ok and "decile 2" in violations[0]
    # small-bucket jitter at the same true accuracy passes
    noisy = [dict(decile=1, conf_lo=0.0, conf_hi=0.5, n=14, accuracy=0.29),
             dict(decile=2, conf_lo=0.5, conf_hi=1.0, n=14, accuracy=0.14)]
    assert calibration_monotone(noisy)[0]


def test_scorecard_harness_regimes_and_calibration():
    """The acceptance pin: all 5 baselines + the TPU solver over the
    labeled corpus, per-regime accuracy present, and the calibration
    table's top decile >= bottom decile (confidence predicts)."""
    from traceweaver_tpu.metrics.scorecard import (
        ALL_METHODS,
        format_scorecard,
        run_scorecard,
    )

    card = run_scorecard(n_traces=24, exact_traces=8, nbins=5)
    assert set(card["per_regime"]) == {"sequential", "async", "fanout"}
    for regime, accs in card["per_regime"].items():
        assert set(accs) == set(ALL_METHODS)
        for acc in accs.values():
            assert 0.0 <= acc <= 1.0
    assert card["per_regime"]["sequential"]["weaver_tpu"] == 1.0
    cal = card["calibration"]
    assert cal and sum(row["n"] for row in cal) == 3 * 24
    assert cal[-1]["accuracy"] >= cal[0]["accuracy"]
    assert card["weaver_exact_subset_spans"] == 8
    text = format_scorecard(card)
    assert "sequential" in text and "weaver_tpu" in text


# ---------------------------------------------------------------------------
# registry label-cardinality guard
# ---------------------------------------------------------------------------

def test_metrics_label_cardinality_guard(monkeypatch):
    from traceweaver_tpu.obs.registry import MetricsRegistry

    monkeypatch.setenv("TW_METRICS_MAX_SERIES", "3")
    reg = MetricsRegistry()
    c = reg.counter("tw_test_guard_total", "t", labels=("tenant",))
    for i in range(3):
        c.inc(1.0, tenant=f"t{i}")
    # past the cap: new label sets collapse into ONE counted overflow
    c.inc(2.0, tenant="t3")
    c.inc(3.0, tenant="t4")
    # existing series keep counting normally
    c.inc(1.0, tenant="t0")
    samples = {tuple(sorted(lab.items())): v for lab, v in c.samples()}
    assert samples[(("tenant", "t0"),)] == 2.0
    assert samples[(("overflow", "1"),)] == 5.0
    assert len(samples) == 4  # 3 real series + the overflow series
    # histograms guard too (the per-tenant confidence histogram is the
    # many-tenant risk this exists for)
    h = reg.histogram("tw_test_guard_seconds", "t", labels=("tenant",),
                      buckets=(1.0,))
    for i in range(5):
        h.observe(0.5, tenant=f"t{i}")
    hs = h.samples()
    overflow_counts = [v for lab, v in hs
                       if lab.get("overflow") == "1"
                       and lab.get("__name__", "").endswith("_count")]
    assert overflow_counts == [2.0]
    # unlabeled families are untouched by the cap
    u = reg.counter("tw_test_guard_unlabeled_total", "t")
    u.inc(5.0)
    assert u.samples() == [({}, 5.0)]
