"""twlint (traceweaver_tpu/analysis) tests.

Engine mechanics (suppressions, baseline, fingerprints), per-rule
fixture snippets (positive + suppressed + clean), the knob-registry
mirror pins, the TW002 regression tests (env changes take effect
without reimport — the two import-time freezes this PR removed), and
the tier-1 repo-wide zero-violation gate.

Everything here is synthetic/in-memory except the gate, which walks the
real repo with the real baseline — pure stdlib ``ast``, no JAX backend
work, so the whole file is tier-1 fast.
"""

from __future__ import annotations

import subprocess
import sys
import textwrap

import pytest

from traceweaver_tpu.analysis import engine
from traceweaver_tpu.analysis.engine import META_RULE

pytestmark = pytest.mark.lint


def lint(src, path="traceweaver_tpu/mod.py", extra=()):
    sources = [(path, textwrap.dedent(src))] + [
        (p, textwrap.dedent(s)) for p, s in extra]
    return engine.analyze_sources(sources)


def rules_of(findings):
    return [f.rule for f in findings]


# a minimal stand-in for runtime/knobs.py: the TW001 cross-module
# reconciliation parses _k(...) declarations out of whatever module sits
# at that path
KNOBS_FIXTURE = ("traceweaver_tpu/runtime/knobs.py", """
    def _k(name, type, default):
        return (name, type, default)

    REGISTRY = {k[0]: k for k in [
        _k("TW_ALPHA", "int", 1),
        _k("TW_ORPHAN", "int", 2),
    ]}
""")


# ---------------------------------------------------------------------------
# engine: suppressions, baseline, fingerprints
# ---------------------------------------------------------------------------

RAW_READ = """
    import os

    def f():
        return os.environ.get("TW_FOO", "1")
"""


def test_suppression_same_line():
    src = RAW_READ.replace(
        'os.environ.get("TW_FOO", "1")',
        'os.environ.get("TW_FOO", "1")  # twlint: disable=TW001 — test')
    findings, suppressed = lint(src)
    assert findings == [] and suppressed == 1


def test_suppression_on_preceding_comment_line():
    src = """
        import os

        def f():
            # twlint: disable=TW001 — justified here
            return os.environ.get("TW_FOO", "1")
    """
    findings, suppressed = lint(src)
    assert findings == [] and suppressed == 1


def test_suppression_file_wide():
    src = "# twlint: disable-file=TW001\n" + textwrap.dedent(RAW_READ)
    findings, suppressed = engine.analyze_sources(
        [("traceweaver_tpu/mod.py", src)])
    assert findings == [] and suppressed == 1


def test_suppression_with_unknown_rule_id_is_itself_a_finding():
    src = RAW_READ.replace(
        'os.environ.get("TW_FOO", "1")',
        'os.environ.get("TW_FOO", "1")  # twlint: disable=TW999')
    findings, _ = lint(src)
    assert META_RULE in rules_of(findings)      # the typo'd waiver
    assert "TW001" in rules_of(findings)        # ...did not waive


def test_unsuppressed_raw_read_is_flagged():
    findings, suppressed = lint(RAW_READ)
    assert rules_of(findings) == ["TW001"] and suppressed == 0
    assert "TW_FOO" in findings[0].message


def test_fingerprint_stable_across_line_drift():
    a, _ = lint(RAW_READ)
    b, _ = lint("\n\n\n" + textwrap.dedent(RAW_READ))
    assert a[0].line != b[0].line
    assert a[0].fingerprint() == b[0].fingerprint()


def test_baseline_roundtrip_and_staleness(tmp_path):
    root = tmp_path / "repo"
    (root / "traceweaver_tpu").mkdir(parents=True)
    mod = root / "traceweaver_tpu" / "mod.py"
    mod.write_text(textwrap.dedent(RAW_READ))
    report = engine.run(root=str(root), baseline_path=None)
    (f,) = report.findings
    bl = tmp_path / "baseline.txt"
    bl.write_text(f"{f.rule} {f.path} {f.fingerprint()}  # grandfathered\n")
    report = engine.run(root=str(root), baseline_path=str(bl))
    assert report.ok and report.baselined == 1
    # fix the violation -> the baseline entry goes stale -> TW000
    mod.write_text("def f():\n    return 1\n")
    report = engine.run(root=str(root), baseline_path=str(bl))
    assert [f.rule for f in report.findings] == [META_RULE]
    assert "stale" in report.findings[0].message


def test_baseline_entry_requires_justification(tmp_path):
    bl = tmp_path / "baseline.txt"
    bl.write_text("TW001 traceweaver_tpu/mod.py abcdef123456\n")
    with pytest.raises(engine.BaselineError):
        engine.load_baseline(str(bl))


# ---------------------------------------------------------------------------
# TW001 — knob discipline
# ---------------------------------------------------------------------------

def test_tw001_subscript_read_flagged_write_allowed():
    findings, _ = lint("""
        import os

        def f():
            os.environ["TW_FOO"] = "1"          # a write: launch config
            os.environ.setdefault("TW_BAR", "0")  # also a write
            return os.environ["TW_FOO"]          # the read is the hazard
    """)
    assert rules_of(findings) == ["TW001"]
    assert findings[0].line_text.strip().startswith("return")


def test_tw001_getenv_flagged_and_knobs_module_exempt():
    findings, _ = lint("""
        import os

        def f():
            return os.getenv("TW_FOO")
    """)
    assert rules_of(findings) == ["TW001"]
    findings, _ = lint(RAW_READ, path="traceweaver_tpu/runtime/knobs.py")
    assert findings == []
    findings, _ = lint(RAW_READ, path="traceweaver_tpu/runtime/faults.py")
    assert findings == []


def test_tw001_non_tw_env_reads_are_not_this_linters_business():
    findings, _ = lint("""
        import os

        def f():
            return os.environ.get("JAX_PLATFORMS", "cpu")
    """)
    assert findings == []


def test_tw001_registry_read_of_undeclared_knob():
    findings, _ = lint("""
        from traceweaver_tpu.runtime import knobs

        def f():
            # reading every declared knob keeps the fixture registry
            # clean, isolating the undeclared-read finding
            return (knobs.get_int("TW_ALPHA"), knobs.get_int("TW_ORPHAN"),
                    knobs.get_int("TW_GHOST"))
    """, extra=[KNOBS_FIXTURE])
    assert rules_of(findings) == ["TW001"]
    assert "never declared" in findings[0].message
    assert "TW_GHOST" in findings[0].message


def test_tw001_registered_but_never_read():
    findings, _ = lint("""
        from traceweaver_tpu.runtime import knobs as _knobs

        def f():
            return _knobs.get_int("TW_ALPHA")
    """, extra=[KNOBS_FIXTURE])
    (f,) = findings
    assert f.rule == "TW001" and "TW_ORPHAN" in f.message
    assert f.path == "traceweaver_tpu/runtime/knobs.py"


# ---------------------------------------------------------------------------
# TW002 — import-time freeze
# ---------------------------------------------------------------------------

def test_tw002_module_scope_reads_flagged_call_time_clean():
    findings, _ = lint("""
        import os
        from traceweaver_tpu.runtime import knobs

        FROZEN_RAW = os.environ.get("TW_FOO", "1")
        FROZEN_TYPED = knobs.get_int("TW_BAR")

        def f():
            return knobs.get_int("TW_BAR")
    """)
    tw002 = [f for f in findings if f.rule == "TW002"]
    assert len(tw002) == 2 and {f.line for f in tw002} == {5, 6}


def test_tw002_scripts_outside_the_library_are_exempt():
    findings, _ = lint("""
        from traceweaver_tpu.runtime import knobs

        DEADLINE = knobs.get_int("TW_BENCH_DEADLINE")
    """, path="bench.py")
    assert rules_of(findings) == []


def test_tw002_class_body_counts_as_import_time():
    findings, _ = lint("""
        from traceweaver_tpu.runtime import knobs

        class C:
            BUDGET = knobs.get_int("TW_FOO")
    """)
    assert "TW002" in rules_of(findings)


# ---------------------------------------------------------------------------
# TW003 — host-sync hazard
# ---------------------------------------------------------------------------

HOT = "traceweaver_tpu/algorithms/fleet.py"


def test_tw003_direct_conversion_of_dispatch_result():
    findings, _ = lint("""
        import numpy as np

        def f(x):
            out = solve_windows_fleet(x)
            return np.asarray(out)
    """, path=HOT)
    assert rules_of(findings) == ["TW003"]


def test_tw003_fetch_helper_is_the_allowed_site():
    findings, _ = lint("""
        import numpy as np

        def _fetch(handle):
            return np.asarray(handle)

        def f(x):
            out = solve_windows_fleet(x)
            return _fetch(out)
    """, path=HOT)
    assert findings == []


def test_tw003_taint_through_unpack_container_and_float():
    findings, _ = lint("""
        import numpy as np

        def f(xs):
            pending = []
            for x in xs:
                packed, out = solve_em_fleet(x)
                pending.append((packed, out))
            return [np.asarray(o) for _, o in pending]

        def g(x):
            out = refit_fleet_params(x)
            v = out[0]
            return float(v)

        def h(x):
            out = solve_windows(x)
            return out.sum().item()
    """, path=HOT)
    assert rules_of(findings) == ["TW003", "TW003", "TW003"]


def test_tw003_host_values_and_cold_modules_are_clean():
    src = """
        import numpy as np

        def f(spans):
            a = np.array([s.start for s in spans])
            return np.asarray(a), float(a[0])
    """
    findings, _ = lint(src, path=HOT)
    assert findings == []
    # device-looking code outside the hot modules: not this rule's scope
    findings, _ = lint("""
        import numpy as np

        def f(x):
            return np.asarray(solve_windows(x))
    """, path="traceweaver_tpu/parallel/mesh.py")
    assert findings == []


# ---------------------------------------------------------------------------
# TW004 — jit / recompile discipline
# ---------------------------------------------------------------------------

def test_tw004_sensitive_params_must_be_static():
    findings, _ = lint("""
        from functools import partial

        import jax

        @partial(jax.jit, static_argnames=("precision", "pallas"))
        def ok(x, precision, pallas):
            return x

        @partial(jax.jit, static_argnames=("n",))
        def bad(x, n, precision):
            return x

        @jax.jit
        def bad2(x, pallas):
            return x
    """)
    assert rules_of(findings) == ["TW004", "TW004"]
    assert "precision" in findings[0].message
    assert "pallas" in findings[1].message


def test_tw004_call_form_and_argnums_mapping():
    findings, _ = lint("""
        import jax

        def plain(x, precision):
            return x

        ok = jax.jit(plain, static_argnums=(1,))
        bad = jax.jit(plain)
    """)
    assert rules_of(findings) == ["TW004"]
    assert findings[0].line_text.strip().startswith("bad")


def test_tw004_inline_pow2_bucketing():
    src = """
        def pad(n):
            return 1 << (n - 1).bit_length()
    """
    findings, _ = lint(src, path="traceweaver_tpu/algorithms/timing.py")
    assert rules_of(findings) == ["TW004"]
    # the one place allowed to implement it
    findings, _ = lint(src, path="traceweaver_tpu/runtime/bucketing.py")
    assert findings == []


# ---------------------------------------------------------------------------
# TW005 — lock discipline
# ---------------------------------------------------------------------------

def test_tw005_guarded_attr_written_without_lock():
    findings, _ = lint("""
        import threading

        class Stats:
            def __init__(self):
                self._lock = threading.Lock()
                self.d = {}
                self.events = []

            def add(self, k):
                with self._lock:
                    self.d[k] = self.d.get(k, 0) + 1
                    self.events.append(k)

            def racy(self, k):
                self.d[k] = 0

            def racy_mutator(self, k):
                self.events.append(k)

            def fine(self):
                self.unguarded_elsewhere = 1
    """)
    assert rules_of(findings) == ["TW005", "TW005"]
    assert {f.line for f in findings} == {16, 19}


def test_tw005_closure_bodies_do_not_inherit_the_lock():
    findings, _ = lint("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.d = {}

            def locked(self, k):
                with self._lock:
                    self.d[k] = 1

                    def cb():
                        self.d[k] = 2  # runs later, outside the lock
                    return cb
    """)
    assert rules_of(findings) == ["TW005"]


def test_tw005_lockless_classes_are_out_of_scope():
    findings, _ = lint("""
        class Plain:
            def __init__(self):
                self.d = {}

            def set(self, k):
                self.d[k] = 1
    """)
    assert findings == []


# ---------------------------------------------------------------------------
# TW006 — precision discipline
# ---------------------------------------------------------------------------

OPS = "traceweaver_tpu/ops/mod.py"


def test_tw006_accumulating_over_bf16():
    findings, _ = lint("""
        import jax.numpy as jnp

        def f(x):
            s = x.astype(jnp.bfloat16)
            return jnp.sum(s)

        def g(x):
            return x.astype(jnp.bfloat16).sum()
    """, path=OPS)
    assert rules_of(findings) == ["TW006", "TW006"]


def test_tw006_f32_upcast_or_accumulator_is_the_contract():
    findings, _ = lint("""
        import jax
        import jax.numpy as jnp

        def f(x):
            s = x.astype(jnp.bfloat16)
            return jnp.sum(s.astype(jnp.float32))

        def g(a, b):
            logits = jax.lax.dot_general(
                a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return jnp.logsumexp(logits)
    """, path=OPS)
    assert findings == []


def test_tw006_outside_ops_is_out_of_scope():
    findings, _ = lint("""
        import jax.numpy as jnp

        def f(x):
            return jnp.sum(x.astype(jnp.bfloat16))
    """, path="traceweaver_tpu/stream/window.py")
    assert findings == []


# ---------------------------------------------------------------------------
# TW007 — metric discipline
# ---------------------------------------------------------------------------

FLEET = "traceweaver_tpu/algorithms/fleet.py"


def test_tw007_adhoc_counter_growth_flagged():
    findings, _ = lint("""
        _COUNTERS = {"hits": 0, "misses": 0}

        def f(stats, key):
            stats[key] += 1

        def g(d, k, v):
            d[k] = d.get(k, 0.0) + v
    """, path=FLEET)
    assert rules_of(findings) == ["TW007", "TW007", "TW007"]


def test_tw007_sanctioned_accumulators_and_non_counters_clean():
    findings, _ = lint("""
        STAGES = {"pack": "host", "decode": "host"}  # not a counter table

        class _Stats:
            def add(self, key, val=1.0):
                self.d[key] = self.d.get(key, 0.0) + val

            def bucket(self, key, subkey, val=1.0):
                d = self.d.setdefault(key, {})
                d[subkey] = d.get(subkey, 0.0) + val

        class Svc:
            def _bump(self, key, n=1):
                self.stats[key] = self.stats.get(key, 0) + n

            def offer(self):
                self.shed_spilled += 1  # attribute counter: out of scope
    """, path="traceweaver_tpu/stream/service.py")
    assert findings == []


def test_tw007_suppression_and_scope():
    findings, _ = lint("""
        def f(live, spec):
            # twlint: disable=TW007 — gate state, not telemetry
            live["elems"] += spec.cost
    """, path="traceweaver_tpu/serve/tenancy.py")
    assert findings == []
    # outside the watched modules the rule says nothing
    findings, _ = lint("""
        _COUNTERS = {"hits": 0}

        def f(stats):
            stats["x"] += 1
    """, path="traceweaver_tpu/runtime/jax_cache.py")
    assert findings == []


# ---------------------------------------------------------------------------
# TW008 — packed-block channel layout discipline
# ---------------------------------------------------------------------------

def test_tw008_raw_channel_index_flagged():
    findings, _ = lint("""
        def decode(o):
            assign = o[..., 0]
            not_best = o[..., 1].astype(bool)
            topk = o[..., 3:]
            tail = o[..., :5]
            return assign, not_best, topk, tail
    """, path=FLEET)
    assert rules_of(findings) == ["TW008"] * 4


def test_tw008_axis_insertion_and_explicit_dims_clean():
    findings, _ = lint("""
        def pack(assign, not_best, ranges):
            a = assign[..., None]           # axis insertion, not a channel
            b = not_best[..., None]
            r0 = ranges[:, :, 0]            # explicit dims: not packed-block
            s = assign[..., a:b]            # non-constant bounds
            return a, b, r0, s
    """, path="traceweaver_tpu/algorithms/weaver_tpu.py")
    assert findings == []


def test_tw008_layout_module_and_unwatched_files_exempt():
    src = """
        CH_ASSIGN = 0

        def split(block):
            return block[..., 0], block[..., 3:]
    """
    findings, _ = lint(src,
                       path="traceweaver_tpu/algorithms/packed_layout.py")
    assert findings == []
    findings, _ = lint(src, path="traceweaver_tpu/parallel/mesh.py")
    assert findings == []
    # suppression works like every rule
    findings, suppressed = lint("""
        def f(o):
            # twlint: disable=TW008 — test fixture
            return o[..., 2]
    """, path=FLEET)
    assert findings == [] and suppressed == 1


# ---------------------------------------------------------------------------
# TW009 — device-resident column discipline
# ---------------------------------------------------------------------------

def test_tw009_bare_asarray_over_assembled_tensors_flagged():
    findings, _ = lint("""
        def dispatch(ring, idx):
            outs = assemble_windows(ring.buf, ring.buf, idx, idx, idx, idx)
            host = np.asarray(outs[0])
            return host
    """, path=FLEET)
    assert "TW009" in rules_of(findings)


def test_tw009_ring_buffer_attribute_is_resident():
    findings, _ = lint("""
        def peek(ring):
            buf = ring.buf
            return np.asarray(buf)
    """, path="traceweaver_tpu/ops/devcols.py")
    assert "TW009" in rules_of(findings)


def test_tw009_ledgered_fetch_and_unwatched_files_clean():
    # fetch_resident is THE ledgered materialization: launders taint
    findings, _ = lint("""
        def grab(ring):
            return fetch_resident(ring.buf)
    """, path="traceweaver_tpu/ops/devcols.py")
    assert [f for f in findings if f.rule == "TW009"] == []
    # outside the hot modules the rule does not apply
    findings, _ = lint("""
        def peek(ring):
            return np.asarray(ring.buf)
    """, path="traceweaver_tpu/parallel/mesh.py")
    assert [f for f in findings if f.rule == "TW009"] == []


# ---------------------------------------------------------------------------
# registry mirrors + TW002 regressions (the two unfrozen knobs)
# ---------------------------------------------------------------------------

def test_vmem_registry_bounds_mirror_kernel_constants():
    from traceweaver_tpu.ops import pallas_sinkhorn as ps
    from traceweaver_tpu.runtime.knobs import REGISTRY

    k = REGISTRY["TW_PALLAS_VMEM_CAP"]
    assert k.default == ps._VMEM_CAP_DEFAULT_BYTES
    assert k.lo == ps._VMEM_FLOOR_BYTES
    assert k.hi == ps._VMEM_HW_BYTES_V5E


def test_score_gemm_env_takes_effect_without_reimport(monkeypatch):
    """The old import-time ``_USE_GEMM`` froze TW_SCORE_GEMM before a
    fixture could export it; the call-time registry read must route the
    very next (eager) evaluation."""
    import numpy as np

    import traceweaver_tpu.ops.scores as scores

    calls = []
    real = scores.mixture_logpdf_gemm

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(scores, "mixture_logpdf_gemm", spy)
    t_prev = np.array([0.0, 10.0], dtype=np.float32)
    out_start = np.array([5.0, 15.0, 25.0], dtype=np.float32)
    w = np.array([1.0], dtype=np.float32)
    mu = np.array([10.0], dtype=np.float32)
    sd = np.array([3.0], dtype=np.float32)

    monkeypatch.delenv("TW_SCORE_GEMM", raising=False)
    base = np.asarray(scores.pair_scores(t_prev, out_start, w, mu, sd))
    assert not calls
    monkeypatch.setenv("TW_SCORE_GEMM", "1")
    gemm = np.asarray(scores.pair_scores(t_prev, out_start, w, mu, sd))
    assert calls, "TW_SCORE_GEMM=1 set after import must reach pair_scores"
    np.testing.assert_allclose(gemm, base, rtol=1e-5, atol=1e-5)


def test_fleet_budget_env_takes_effect_between_two_solves(monkeypatch):
    """TW_FLEET_BUDGET exported between two solve_fleet calls (same
    process, no reimport) must flip the second solve onto the budget-
    fallback path — the old import-time FLEET_BUDGET_ELEMS constant
    could not see it."""
    import traceweaver_tpu.algorithms.fleet as fleet
    from traceweaver_tpu.algorithms.fleet import FleetItem, solve_fleet
    from test_columnar import _random_problem

    assert fleet.FLEET_BUDGET_ELEMS is None  # env-driven unless patched

    def items():
        in_spans, out_parts, _, ta, dag = _random_problem(
            seed=3, n_traces=24, eps=("A", "B"))
        return [FleetItem("svc", {"IN": in_spans}, out_parts, ta, dag)]

    monkeypatch.delenv("TW_FLEET_BUDGET", raising=False)
    stats_default = {}
    fused = solve_fleet(items(), stats=stats_default)
    assert stats_default.get("fleet_fallback_budget", 0) == 0

    monkeypatch.setenv("TW_FLEET_BUDGET", "1")
    stats_tiny = {}
    fell_back = solve_fleet(items(), stats=stats_tiny)
    assert stats_tiny.get("fleet_fallback_budget", 0) >= 1.0
    for a, b in zip(fused, fell_back):
        assert a[0] == b[0]  # budget path is result-equivalent

    # the test-override hook still wins over the env
    monkeypatch.setattr(fleet, "FLEET_BUDGET_ELEMS", 123)
    assert fleet._fleet_budget_bytes() == 123 * 4


# ---------------------------------------------------------------------------
# TW010 — adaptation actuation discipline
# ---------------------------------------------------------------------------

ADAPT = "traceweaver_tpu/adapt/refit.py"


def test_tw010_bare_actuation_in_adapt_flagged():
    findings, _ = lint("""
        def sneak_refit(svc, material):
            outs = solve_fleet([material])
            dists = refit_from_assignments({}, {}, None, outs[0][0], {})
            svc.carried.update("svc", dists)
    """, path=ADAPT)
    assert rules_of(findings).count("TW010") == 2  # both primitives


def test_tw010_ledgered_actuation_clean():
    findings, _ = lint("""
        def execute(svc, ctrl, key, material):
            outs = solve_fleet([material])
            dists = refit_from_assignments({}, {}, None, outs[0][0], {})
            ctrl.refit_done(key, ok=bool(dists))
            return dists

        def act_direct(self, key):
            solve_fleet([])
            self._act("refit", key)
    """, path=ADAPT)
    assert [f for f in findings if f.rule == "TW010"] == []


def test_tw010_private_controller_access_outside_adapt_flagged():
    findings, _ = lint("""
        def pump(self):
            self.adapt._keys.clear()
            svc.adapt._act("refit", "k")
    """, path="traceweaver_tpu/stream/service.py")
    # only the CALL is an actuation; the attribute read alone is not
    assert rules_of(findings).count("TW010") == 1


def test_tw010_public_api_and_unrelated_modules_clean():
    findings, _ = lint("""
        def pump(self):
            self.adapt.observe("k", psi=0.5, low_rate=0.0)
            for key in self.adapt.pending_refits():
                self.adapt.refit_done(key, ok=True)
            warm = self.adapt.warm_dists("k", None)
    """, path="traceweaver_tpu/stream/service.py")
    assert [f for f in findings if f.rule == "TW010"] == []
    # solve_fleet outside adapt/ is the ordinary hot path, not an
    # adaptation actuation
    findings, _ = lint("""
        def pump(self):
            return solve_fleet(self.items)
    """, path="traceweaver_tpu/serve/tenancy.py")
    assert [f for f in findings if f.rule == "TW010"] == []
    # suppression works like every rule
    findings, suppressed = lint("""
        def f(svc):
            # twlint: disable=TW010 — test fixture
            return solve_fleet([])
    """, path=ADAPT)
    assert findings == [] and suppressed == 1


# ---------------------------------------------------------------------------
# TW011 — AOT compile discipline
# ---------------------------------------------------------------------------

def test_tw011_chained_lower_compile_outside_aot_flagged():
    findings, _ = lint("""
        import jax

        def private_warmup(fn, spec):
            return fn.lower(spec, spec).compile()
    """, path="traceweaver_tpu/serve/tenancy.py")
    assert rules_of(findings).count("TW011") == 1


def test_tw011_two_statement_form_flagged():
    findings, _ = lint("""
        def warm(fn, spec):
            lowered = fn.lower(spec)
            exe = lowered.compile()
            return exe
    """, path="traceweaver_tpu/stream/service.py")
    assert rules_of(findings).count("TW011") == 1


def test_tw011_cache_config_write_outside_jax_cache_flagged():
    findings, _ = lint("""
        import jax

        def my_cache(path):
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    """, path="traceweaver_tpu/runtime/cli.py")
    assert rules_of(findings).count("TW011") == 2


def test_tw011_lattice_modules_and_lookalikes_clean():
    # the lattice enumerator and the cache module own the idiom
    for allowed in ("traceweaver_tpu/runtime/aot.py",
                    "traceweaver_tpu/runtime/jax_cache.py"):
        findings, _ = lint("""
            import jax

            def warm(fn, spec, path):
                jax.config.update("jax_compilation_cache_dir", path)
                return fn.lower(spec).compile()
        """, path=allowed)
        assert [f for f in findings if f.rule == "TW011"] == []
    # string .lower(), re.compile over lowered strings, and non-cache
    # config updates are not AOT compiles
    findings, _ = lint("""
        import re
        import jax

        def f(name, pattern):
            jax.config.update("jax_platforms", "cpu")
            key = (name or "").lower()
            rx = re.compile(pattern.lower())
            return key, rx
    """, path="traceweaver_tpu/stream/service.py")
    assert [f for f in findings if f.rule == "TW011"] == []
    # suppression works like every rule
    findings, suppressed = lint("""
        def warm(fn, spec):
            return fn.lower(spec).compile()  # twlint: disable=TW011 — why
    """, path="traceweaver_tpu/serve/http.py")
    assert findings == [] and suppressed == 1


# ---------------------------------------------------------------------------
# TW012 — serve ticket discipline
# ---------------------------------------------------------------------------

def test_tw012_inflight_mutation_outside_lifecycle_flagged():
    # every mutation shape: mutator call, clear, slice-assign, rebind,
    # augmented assign — all outside the lifecycle allowlist
    findings, _ = lint("""
        class TenantService:
            def prune(self, t, buf):
                t.in_flight.remove(buf)

            def reset(self, t):
                t.in_flight.clear()
                t.in_flight[:] = []
                t.in_flight = []
                t.in_flight += [1]
    """, path="traceweaver_tpu/serve/tenancy.py")
    assert rules_of(findings).count("TW012") == 5
    assert findings[0].line == 4  # the remove() site


def test_tw012_lifecycle_sites_and_reads_clean():
    # the real lifecycle: __init__ constructs, submit extends, the
    # retire helper slice-assigns; everything else only reads
    findings, _ = lint("""
        class Tenant:
            def __init__(self):
                self.in_flight = []

        class TenantService:
            def submit_admitted(self, plan):
                for t, bufs in plan:
                    t.in_flight.extend(bufs)

            def _ring_retire_locked(self, ticket):
                for t, bufs in ticket.taken:
                    drop = {id(b) for b in bufs}
                    t.in_flight[:] = [b for b in t.in_flight
                                      if id(b) not in drop]

            def checkpoint_all(self, t):
                if t.in_flight:
                    return len(t.in_flight)
                return 0
    """, path="traceweaver_tpu/serve/tenancy.py")
    assert [f for f in findings if f.rule == "TW012"] == []


def test_tw012_suppression():
    findings, suppressed = lint("""
        class TenantService:
            def emergency_reset(self, t):
                t.in_flight.clear()  # twlint: disable=TW012 — why
    """, path="traceweaver_tpu/serve/tenancy.py")
    assert [f for f in findings if f.rule == "TW012"] == []
    assert suppressed == 1


# ---------------------------------------------------------------------------
# TW013 — serve ack discipline
# ---------------------------------------------------------------------------

def test_tw013_unledgered_ingest_ack_flagged():
    # a 2xx whose payload comes from the bare in-memory ingest entry
    # points, with no TW_WAL guard anywhere above it — both ingest
    # shapes, plus a nested-expression payload
    findings, _ = lint("""
        class Handler:
            def do_POST(self):
                self._reply(200, self.service.ingest(tid, payload))
                self._reply(200, self.service.ingest_capture(tid, caps))
                self._reply(201, dict(self.service.ingest(tid, payload)))
    """, path="traceweaver_tpu/serve/http.py")
    assert rules_of(findings).count("TW013") == 3


def test_tw013_ledgered_and_guarded_acks_clean():
    # the real shape: the TW_WAL knob selects the ledgered form, and
    # the bare form lives on the guard's else branch (the explicit
    # no-durability opt-out); error replies and non-ingest payloads
    # are not ack surfaces
    findings, _ = lint("""
        class Handler:
            def do_POST(self):
                if _knobs.get_bool("TW_WAL"):
                    self._reply(200, self.service.wal_ingest(
                        tid, payload, raw=raw, client_seq=seq))
                else:
                    self._reply(200, self.service.ingest(tid, payload))
                self._reply(200, self.service.stats(tid))
                self._reply(400, {"error": self.service.ingest(tid, p)})
    """, path="traceweaver_tpu/serve/http.py")
    assert [f for f in findings if f.rule == "TW013"] == []
    # other modules' ingest-shaped calls are out of scope (the rule is
    # about the serve front door's ack, not every ingest() in the repo)
    findings, _ = lint("""
        class Handler:
            def do_POST(self):
                self._reply(200, self.service.ingest(tid, payload))
    """, path="traceweaver_tpu/fleet_serve/router.py")
    assert [f for f in findings if f.rule == "TW013"] == []


def test_tw013_suppression():
    findings, suppressed = lint("""
        class Handler:
            def do_POST(self):
                self._reply(200, self.service.ingest(tid, p))  # twlint: disable=TW013 — why
    """, path="traceweaver_tpu/serve/http.py")
    assert [f for f in findings if f.rule == "TW013"] == []
    assert suppressed == 1


# ---------------------------------------------------------------------------
# CLI plumbing + the tier-1 repo gate
# ---------------------------------------------------------------------------

def test_module_entry_point_and_cli_subcommand_list_rules(capsys):
    from traceweaver_tpu.analysis.__main__ import main as lint_main
    from traceweaver_tpu.runtime import cli

    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("TW001", "TW002", "TW003", "TW004", "TW005", "TW006",
                "TW012", "TW013"):
        assert rid in out
    assert cli.main(["lint", "--list-rules"]) == 0


def test_repo_is_lint_clean():
    """THE GATE: the full rule set over the whole repo, against the
    checked-in baseline (kept empty — violations get fixed, not
    grandfathered). A finding here blocks the merge; fix it, or if it
    truly cannot be fixed yet, baseline it WITH a justification."""
    report = engine.run()
    assert report.files > 100  # the walk really saw the repo
    assert report.ok, "\n" + report.render()


def test_repo_gate_via_subprocess_exit_code():
    """`python -m traceweaver_tpu.analysis` is what CI/operators run;
    pin the exit-code contract end to end."""
    proc = subprocess.run(
        [sys.executable, "-m", "traceweaver_tpu.analysis"],
        capture_output=True, text=True, cwd=engine.REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout
