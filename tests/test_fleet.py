"""Fleet (cross-service fused dispatch) vs per-service solve equivalence.

The fleet path pads every service's windows to one [B, E, W, M] shape
class and solves them in a single device program (fleet.py). Padding and
param-table indexing must be invisible: masked rows/columns/endpoints
cannot move any real assignment, so the fleet must reproduce the
per-service flagship exactly on recorded data — including the on-device
two-pass EM, whose per-service family refit must match the single-service
fused refit sample-for-sample.
"""

import numpy as np
import pytest

from traceweaver_tpu.algorithms.fleet import FleetItem, solve_fleet
from traceweaver_tpu.algorithms.weaver_tpu import WeaverTPU
from traceweaver_tpu.ingest import (
    build_service_problem,
    infer_invocation_dag,
    load_corpus,
)
from traceweaver_tpu.metrics import accuracy_for_service, get_ground_truth

HOTEL = "/root/reference/data/hotel_reservation/hotel_load25"


def _problems(path, fix, n_traces=300):
    store = load_corpus(path, fix=fix, max_traces=n_traces, cache=False)
    out = []
    for svc in store.out_spans_by_process:
        prob = build_service_problem(store, svc)
        if prob.skipped:
            continue
        ta = get_ground_truth(prob.in_span_partitions,
                              prob.out_span_partitions)
        dag = infer_invocation_dag(prob.in_span_partitions,
                                   prob.out_span_partitions, ta, store)
        out.append((store, svc, prob, ta, dag))
    return out


@pytest.fixture(scope="module")
def hotel_problems():
    return _problems(HOTEL, fix=2)


def test_fleet_single_dispatch_matches_per_service(hotel_problems):
    items, singles = [], []
    for store, svc, prob, ta, dag in hotel_problems:
        algo = WeaverTPU(store.all_spans, store.all_processes)
        singles.append(algo.FindAssignments(
            "MaxScoreBatchSubsetWithSkips", svc, prob.in_span_partitions,
            prob.out_span_partitions, False, [], ta, dag))
        items.append(FleetItem(svc, prob.in_span_partitions,
                               prob.out_span_partitions, ta, dag,
                               store=store))
    assert len(items) >= 2  # frontend + search, different endpoint counts

    stats = {}
    fleet = solve_fleet(items, stats=stats)

    assert stats.get("fleet_dispatches") == 1
    assert stats.get("fleet_services") == len(items)
    for (store, svc, prob, ta, dag), f, s in zip(hotel_problems, fleet,
                                                 singles):
        # identical hard assignments endpoint-for-endpoint
        assert f[0] == s[0], f"fleet assignments diverge on {svc}"
        # and identical bookkeeping: not_best count, per-span candidate
        # counts, and unassigned count (padded endpoints must contribute
        # nothing), plus the trivially-equal span count
        assert f[2] == s[2], f"not_best_count diverges on {svc}"
        assert f[3] == s[3]
        assert f[4] == s[4], f"per_span_candidates diverge on {svc}"
        assert f[5] == s[5], f"cnt_unassigned diverges on {svc}"
        acc_f = accuracy_for_service(f[0], ta, prob.in_span_partitions)
        acc_s = accuracy_for_service(s[0], ta, prob.in_span_partitions)
        assert acc_f == acc_s


def test_fleet_routes_ineligible_items_to_fallback(hotel_problems):
    store, svc, prob, ta, dag = hotel_problems[0]
    # no DAG -> bootstrap/1-iteration path -> fleet must fall back and
    # still return a FindAssignments-shaped result
    items = [FleetItem(svc, prob.in_span_partitions,
                       prob.out_span_partitions, ta, dag=None, store=store)]
    stats = {}
    out = solve_fleet(items, stats=stats)
    assert stats.get("fleet_dispatches") is None
    assert len(out) == 1 and len(out[0]) == 6
    acc = accuracy_for_service(out[0][0], ta, prob.in_span_partitions)
    assert acc > 0.9


def test_fleet_budget_fallback_is_equivalent(hotel_problems, monkeypatch):
    import traceweaver_tpu.algorithms.fleet as fleet_mod

    items = [FleetItem(svc, prob.in_span_partitions,
                       prob.out_span_partitions, ta, dag, store=store)
             for store, svc, prob, ta, dag in hotel_problems]
    fused = solve_fleet(items)
    monkeypatch.setattr(fleet_mod, "FLEET_BUDGET_ELEMS", 1)
    stats = {}
    fell_back = solve_fleet(items, stats=stats)
    # a COUNT of over-budget groups (>= 1), not a flag
    assert stats.get("fleet_fallback_budget", 0) >= 1.0
    for f, s in zip(fused, fell_back):
        assert f[0] == s[0]


def test_fleet_budget_bounds_refit_matrix_at_scale(hotel_problems,
                                                   monkeypatch):
    """exp5-scale fleets (P >= 15) must degrade gracefully: the budget
    check bounds the gathered [P*Ne, Bmax*W] refit matrix too, and when
    the combined block exceeds the budget every item still gets a correct
    per-service solve (with overlapped dispatches + merged stats)."""
    import traceweaver_tpu.algorithms.fleet as fleet_mod

    base = [FleetItem(svc, prob.in_span_partitions,
                      prob.out_span_partitions, ta, dag, store=store)
            for store, svc, prob, ta, dag in hotel_problems]
    # replicate to a 16-service fleet (distinct FleetItem objects)
    items = [FleetItem(it.svc, it.in_span_partitions,
                       it.out_span_partitions, it.true_assignments, it.dag,
                       store=it.store)
             for it in (base * ((15 // len(base)) + 1))][:16]
    singles = solve_fleet(base)

    # budget that the score block alone would pass but score+refit must
    # trip: P*Ne*Bmax*W dominates here because Ne grows as E^2
    stats = {}
    monkeypatch.setattr(fleet_mod, "FLEET_BUDGET_ELEMS", 1 << 18)
    out = solve_fleet(items, stats=stats)
    assert stats.get("fleet_fallback_budget", 0) >= 1.0
    assert stats.get("pack_s") is not None  # fallback stats merged
    by_svc = {it.svc: s for it, s in zip(base, singles)}
    for it, o in zip(items, out):
        assert o is not None and len(o) == 6
        assert o[0] == by_svc[it.svc][0]


def _cache_hit_copy(prob, ta, rate):
    """Deep-copied partitions with cache hits injected (skip budget > 0)."""
    import copy

    from traceweaver_tpu.synth import create_cache_hits

    inp = copy.deepcopy(prob.in_span_partitions)
    outp = copy.deepcopy(prob.out_span_partitions)
    ta2 = create_cache_hits(copy.deepcopy(ta), inp, outp, cache_rate=rate)
    return inp, outp, ta2


def test_fleet_carries_dynamism_single_pass(hotel_problems):
    """Cache-hit services (skip budget > 0 — the exp2 workload) must ride
    the fused dispatch as a single-pass group, NOT fall back per-service,
    and reproduce the per-service dynamism path exactly."""
    import copy

    items, singles = [], []
    n_dyn = 0
    for store, svc, prob, ta, dag in hotel_problems:
        if svc == "frontend":
            inp, outp, ta2 = _cache_hit_copy(prob, ta, 0.3)
            n_dyn += 1
        else:
            inp, outp, ta2 = (prob.in_span_partitions,
                              prob.out_span_partitions, ta)
        algo = WeaverTPU(store.all_spans, store.all_processes)
        singles.append(algo.FindAssignments(
            "MaxScoreBatchSubsetWithSkips", svc, copy.deepcopy(inp),
            copy.deepcopy(outp), False, [], copy.deepcopy(ta2), dag))
        items.append(FleetItem(svc, inp, outp, ta2, dag, store=store))
    assert n_dyn == 1

    stats = {}
    fleet = solve_fleet(items, stats=stats)
    # the cache-hit service formed a single-pass dynamism dispatch and
    # every service (incl. it) rode a fused program — zero fallbacks
    assert stats.get("fleet_dynamism_dispatches", 0) >= 1
    assert stats.get("fleet_services") == len(items)
    for (store, svc, *_), f, s in zip(hotel_problems, fleet, singles):
        assert f[0] == s[0], f"dynamism fleet diverges on {svc}"
        assert f[2] == s[2] and f[3] == s[3]
        assert f[4] == s[4] and f[5] == s[5]


def test_fleet_true_skips_oracle_rides_fleet(hotel_problems):
    """The true-skips oracle ships forced rows as per-window force-skip
    tensors inside the fused dispatch (weaver_tpu.py force_skip input) and
    matches the per-service oracle exactly."""
    import copy

    items, singles = [], []
    for store, svc, prob, ta, dag in hotel_problems:
        if svc == "frontend":
            inp, outp, ta2 = _cache_hit_copy(prob, ta, 0.3)
        else:
            inp, outp, ta2 = (prob.in_span_partitions,
                              prob.out_span_partitions, ta)
        algo = WeaverTPU(store.all_spans, store.all_processes)
        singles.append(algo.FindAssignments(
            "MaxScoreBatchSubsetWithTrueSkips", svc, copy.deepcopy(inp),
            copy.deepcopy(outp), False, [], copy.deepcopy(ta2), dag,
            true_skips=True))
        items.append(FleetItem(svc, inp, outp, ta2, dag,
                               method="MaxScoreBatchSubsetWithTrueSkips",
                               store=store))

    stats = {}
    fleet = solve_fleet(items, stats=stats)
    assert stats.get("fleet_services") == len(items)
    for (store, svc, *_), f, s in zip(hotel_problems, fleet, singles):
        assert f[0] == s[0], f"true-skips fleet diverges on {svc}"


def test_fleet_item_cells_attribution(hotel_problems):
    """solve_fleet reports per-item padded-cell costs (the wall-clock
    attribution model shared by the executor and the parity harness):
    every item gets a positive cost and bigger problems cost more."""
    items = [FleetItem(svc, prob.in_span_partitions,
                       prob.out_span_partitions, ta, dag, store=store)
             for store, svc, prob, ta, dag in hotel_problems]
    cells = [0.0] * len(items)
    solve_fleet(items, item_cells=cells)
    assert all(c > 0 for c in cells)
    # frontend (more endpoints, wider windows) must out-cost search
    by_svc = {it.svc: c for it, c in zip(items, cells)}
    if "frontend" in by_svc and "search" in by_svc:
        assert by_svc["frontend"] > by_svc["search"]


def test_fleet_services_stat_accumulates(hotel_problems):
    items = [FleetItem(svc, prob.in_span_partitions,
                       prob.out_span_partitions, ta, dag, store=store)
             for store, svc, prob, ta, dag in hotel_problems]
    stats = {}
    solve_fleet(items, stats=stats)
    solve_fleet(items, stats=stats)
    assert stats["fleet_services"] == 2.0 * len(items)
    assert stats["fleet_dispatches"] == 2.0
