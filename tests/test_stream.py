"""Streaming reconstruction service tests (tier-1, CPU).

Contracts covered (ISSUE 1):

- out-of-order delivery: watermark-bounded jitter routes every span to
  its owner window; nothing is lost or double-owned;
- late-span handling: spans behind a sealed owner reroute into a
  still-open window or land in the quantified ``late_dropped`` counter;
- backpressure: a throttled consumer sheds sealed windows to the spill
  queue (solved later — shed, not lost) and only drops with accounting
  once the spill bound is hit;
- checkpoint/kill/resume: interrupting mid-corpus and resuming from the
  last checkpoint reproduces the uninterrupted run's emitted trace set
  exactly (no loss, no double-emit);
- streamed-vs-batch accuracy parity on a small corpus.

Solve-carrying tests use a synthesized Alibaba-style corpus (the repo's
own generator) since the reference datasets may be absent.
"""

import json
import os
import subprocess
import sys

import pytest

from traceweaver_tpu.spans import Span
from traceweaver_tpu.stream.scheduler import MicroBatchScheduler
from traceweaver_tpu.stream.watermark import WatermarkTracker
from traceweaver_tpu.stream.window import WindowingEngine


# ---------------------------------------------------------------------------
# windowing + watermark units (no solver)
# ---------------------------------------------------------------------------

def _span(i, t, kind="server"):
    return Span(f"t{i}", f"s{i}", float(t), 10.0, None, [], "p", kind)


def test_out_of_order_routing_within_watermark_bound():
    """Jitter within the watermark bound never makes a span late: every
    span lands owned in exactly one window, and windows seal in order
    only once the watermark passes their end."""
    wm = WatermarkTracker(bound_us=100.0)
    eng = WindowingEngine(size_us=1000.0, overlap_us=200.0)
    # event times 0..1999, delivered with a deterministic +-<=100 shuffle
    events = [(i, float(t)) for i, t in enumerate(range(0, 2000, 50))]
    arrival = sorted(events, key=lambda e: e[1] + (97 * e[0] % 100) - 50)
    sealed = []
    for i, t in arrival:
        wm.observe(t)
        assert eng.add(_span(i, t), t) == "ok"
        sealed.extend(eng.poll(wm.value))
    sealed.extend(eng.flush())
    assert eng.late_rerouted == 0 and eng.late_dropped == 0
    ks = [b.k for b in sealed]
    assert ks == sorted(ks)
    owners = {}
    for b in sealed:
        for sid in b.owned_ids:
            assert sid not in owners, "double-owned span"
            owners[sid] = b.k
    assert len(owners) == len(events)  # nothing lost
    # overlap: boundary spans appear as context in the adjacent window
    ctx = sum(b.n_spans - b.n_owned for b in sealed)
    assert ctx > 0


def test_ownership_and_overlap_geometry():
    eng = WindowingEngine(size_us=1000.0, overlap_us=200.0)
    # stride 800: t=850 belongs to windows 0 ([0,1000)) and 1 ([800,1800))
    assert eng.covering(850.0) == [0, 1]
    assert eng.owner_of(850.0) == 1
    # t=100 is only in window 0
    assert eng.covering(100.0) == [0]
    assert eng.owner_of(100.0) == 0


def test_late_span_reroute_vs_drop_accounting():
    eng = WindowingEngine(size_us=1000.0, overlap_us=0.0)
    eng.add(_span(0, 100.0), 100.0)
    eng.add(_span(1, 1500.0), 1500.0)
    # watermark far past window 0: it seals
    sealed = eng.poll(1400.0)
    assert [b.k for b in sealed] == [0]
    # a span for sealed window 0 arrives now: window 1 is open -> reroute
    assert eng.add(_span(2, 50.0), 50.0) == "late_rerouted"
    assert eng.late_rerouted == 1
    buf1 = eng.open[1]
    assert ("t2", "s2") in buf1.owned_ids
    # seal everything; with nothing open a late span must drop, counted
    sealed = eng.poll(5000.0)
    assert [b.k for b in sealed] == [1]
    assert eng.add(_span(3, 60.0), 60.0) == "late_dropped"
    assert eng.late_dropped == 1
    # conservation: owned across sealed windows + dropped == offered
    owned = sum(b.n_owned for b in sealed) + 1  # window 0 sealed earlier
    assert owned + eng.late_dropped == 4


def test_grace_keeps_window_open_past_watermark():
    eng = WindowingEngine(size_us=1000.0, overlap_us=0.0, grace_us=500.0)
    eng.add(_span(0, 100.0), 100.0)
    assert eng.poll(1400.0) == []          # within grace: still open
    assert eng.add(_span(1, 200.0), 200.0) == "ok"  # allowed lateness
    sealed = eng.poll(1600.0)              # past end + grace: seals
    assert [b.k for b in sealed] == [0]
    assert sealed[0].n_owned == 2


def test_watermark_monotone_and_late_counting():
    wm = WatermarkTracker(bound_us=50.0)
    assert wm.value == float("-inf")
    wm.observe(1000.0)
    assert wm.value == 950.0
    assert wm.observe(960.0) is False      # within bound
    assert wm.value == 950.0               # monotone (max-driven)
    assert wm.observe(900.0) is True       # behind the watermark: late
    assert wm.n_late == 1
    assert wm.max_skew_us == 100.0


# ---------------------------------------------------------------------------
# backpressure (fake solver)
# ---------------------------------------------------------------------------

def test_backpressure_sheds_to_spill_then_drops_with_accounting():
    from traceweaver_tpu.stream.window import WindowBuffer

    solved = []

    def solve(batch):
        solved.extend(batch)
        return [b.k for b in batch]

    sched = MicroBatchScheduler(solve, max_pending=2, spill_max=2)

    def buf(k, n):
        b = WindowBuffer(k, 0.0, 1.0)
        for i in range(n):
            b.add(_span(1000 * k + i, float(i)), owned=True)
        return b

    # throttled consumer: no pump between offers
    assert sched.offer(buf(0, 3)) == "queued"
    assert sched.offer(buf(1, 3)) == "queued"
    assert sched.offer(buf(2, 3)) == "spilled"
    assert sched.offer(buf(3, 3)) == "spilled"
    assert sched.offer(buf(4, 3)) == "dropped"
    assert sched.shed_spilled == 2
    assert sched.shed_dropped_windows == 1
    assert sched.shed_dropped_spans == 3
    # a throttled pump solves one micro-batch, then the spill refills
    out = sched.pump(max_batches=1)
    assert out == [0, 1]
    assert sched.backlog == 2
    # full pump drains the spill: spilled windows were shed, NOT lost
    out = sched.pump()
    assert out == [2, 3]
    assert sched.backlog == 0
    assert sched.solved_windows == 4


# ---------------------------------------------------------------------------
# full service on a synthesized corpus (solver in the loop)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def synth_store(tmp_path_factory):
    from traceweaver_tpu.alibaba.synthesize import synthesize_corpus
    from traceweaver_tpu.ingest import load_corpus

    root = tmp_path_factory.mktemp("stream_corpus")
    dirs = synthesize_corpus(str(root / "cg"), n_graphs=1,
                             traces_per_graph=40, seed=7)
    store = load_corpus(dirs[0], fix=5, max_traces=40, cache=False)
    assert store.services()
    return dirs[0], store


def _stream_cfg(**kw):
    from traceweaver_tpu.stream import StreamConfig

    base = dict(window_us=20e6, overlap_us=4e6, ooo_bound_us=1e6,
                grace_us=0.0, checkpoint_every=10_000, verbose=False)
    base.update(kw)
    return StreamConfig(**base)


def _run_stream(store, sink_path=None, cfg=None, ooo_us=50_000.0):
    from traceweaver_tpu.stream import (
        ReplaySource,
        StreamingReconstructor,
        TraceSink,
    )

    source = ReplaySource(store, ooo_us=ooo_us, seed=1)
    sink = TraceSink(sink_path) if sink_path else None
    svc = StreamingReconstructor(source, cfg or _stream_cfg(), sink=sink)
    summary = svc.run()
    if sink:
        sink.close()
    return summary


def test_streamed_vs_batch_accuracy_parity(synth_store):
    """End-to-end: the streamed reconstruction must land within 2 pts of
    the batch executor on identical input (the ISSUE acceptance bar)."""
    from traceweaver_tpu.runtime.executor import ExecutorConfig, run_experiment

    _, store = synth_store
    summary = _run_stream(store)
    assert summary["final"]
    streamed = summary["accuracy"]["e2e"]
    assert summary["stats"].get("spans_emitted", 0) > 0

    cfg = ExecutorConfig(
        data_path="", results_directory="", fix=5, cache_rate=0.0,
        test_name="streamcmp", predictor_indices=[10])
    batch = run_experiment(cfg, store=store).accuracy_overall[
        "MaxScoreBatchSubsetWithSkips"]
    assert streamed >= batch - 2.0, (
        f"streamed {streamed:.2f}% vs batch {batch:.2f}%")


def test_stream_conserves_spans_and_reports_lateness(synth_store):
    """Heavy out-of-order arrival vs a tight watermark: every consumed
    span is either emitted (owned exactly once) or counted in
    late_dropped — conservation holds under lateness."""
    _, store = synth_store
    # overlap 0 (the owner window ends right at the bucket boundary) and
    # a watermark bound far below the arrival jitter: spans near a window
    # end with near-max jitter arrive after their owner sealed
    cfg = _stream_cfg(overlap_us=0.0, ooo_bound_us=1e4)
    summary = _run_stream(store, cfg=cfg, ooo_us=3e6)
    emitted = summary["stats"].get("spans_emitted", 0)
    assert emitted + summary["late_dropped"] == summary["consumed"]
    # the service must have seen and quantified late arrivals
    assert summary["late_rerouted"] + summary["late_dropped"] > 0


def test_checkpoint_kill_resume_no_loss_no_double_emit(synth_store, tmp_path):
    """Kill the stream mid-corpus (beyond the last checkpoint), resume
    from the checkpoint, and require the emitted trace set to equal the
    uninterrupted run's exactly — byte-for-byte, including windows that
    were emitted after the checkpoint and must be re-emitted once."""
    from traceweaver_tpu.stream import (
        ReplaySource,
        StreamingReconstructor,
        TraceSink,
    )

    _, store = synth_store
    golden_path = str(tmp_path / "golden.jsonl")
    _run_stream(store, sink_path=golden_path)
    with open(golden_path, "rb") as f:
        golden = f.read()
    assert golden.count(b"\n") >= 4  # several windows: a kill mid-way bites

    ckpt = str(tmp_path / "ckpt.pkl")
    out_path = str(tmp_path / "out.jsonl")
    cfg = _stream_cfg(checkpoint_path=ckpt, checkpoint_every=2)
    source = ReplaySource(store, ooo_us=50_000.0, seed=1)
    sink = TraceSink(out_path)
    svc = StreamingReconstructor(source, cfg, sink=sink)
    # kill after 3 emitted windows: the last checkpoint covers 2, window
    # 3's bytes are already in the sink and MUST NOT be double-emitted
    partial = svc.run(max_windows=3)
    assert not partial["final"]
    sink.close()
    assert os.path.exists(ckpt)
    with open(out_path, "rb") as f:
        assert 0 < len(f.read()) < len(golden)

    source2 = ReplaySource(store, ooo_us=50_000.0, seed=1)
    resumed = StreamingReconstructor.resume(ckpt, source2)
    summary = resumed.run()
    resumed.sink.close()
    assert summary["final"]
    with open(out_path, "rb") as f:
        replayed = f.read()
    assert replayed == golden
    # the resumed run's final accuracy matches too (grader state rode
    # the checkpoint; re-solved windows re-accumulated identically)
    uninterrupted = _run_stream(store)
    assert summary["accuracy"] == uninterrupted["accuracy"]


@pytest.mark.plan
def test_checkpoint_kill_resume_with_warm_plan_cache(tmp_path):
    """ISSUE 17 satellite: kill/resume byte-identity must extend to the
    checkpointed plan-cache state. On a high-volume corpus (windows
    above the TW_PLAN_MIN_SAMPLES admission bar, so the cache genuinely
    freezes window 0's plan and skips later refits) a run killed with a
    WARM cache and resumed from the checkpoint must re-emit exactly the
    uninterrupted run's bytes — the frozen plan rides state_dict, so
    the resumed windows solve with the SAME carried statistics the
    killed run would have used, not a re-fit that could drift them."""
    import bench
    from traceweaver_tpu.stream import StreamingReconstructor, TraceSink
    from traceweaver_tpu.stream.service import StreamConfig
    from traceweaver_tpu.stream.sources import IterableSource

    def events():
        return bench._adapt_burst_events(
            6, shift_at=10 ** 9, n_req=70, gap_us=120.0)[0]

    def cfg(**kw):
        return StreamConfig(window_us=1e6, overlap_us=0.0,
                            ooo_bound_us=1e3, verbose=False, **kw)

    golden_path = str(tmp_path / "golden.jsonl")
    sink = TraceSink(golden_path)
    svc = StreamingReconstructor(IterableSource(events()), cfg(
        checkpoint_every=10_000), sink=sink)
    svc.run()
    sink.close()
    c_gold = svc.plan_cache.counters()
    assert c_gold["admissions"] == 1 and c_gold["hits"] >= 4, c_gold
    with open(golden_path, "rb") as f:
        golden = f.read()

    ckpt = str(tmp_path / "ckpt.pkl")
    out_path = str(tmp_path / "out.jsonl")
    sink = TraceSink(out_path)
    svc = StreamingReconstructor(IterableSource(events()), cfg(
        checkpoint_path=ckpt, checkpoint_every=2), sink=sink)
    # kill after 3 windows: the cache is warm (window 0 admitted,
    # windows 1-2 hit) and the last checkpoint carries the frozen plan
    partial = svc.run(max_windows=3)
    assert not partial["final"]
    assert svc.plan_cache.counters()["entries"] == 1
    sink.close()

    resumed = StreamingReconstructor.resume(ckpt, IterableSource(events()))
    # the checkpointed cache came back warm — the resumed run must NOT
    # re-fit the frozen plan from scratch
    assert resumed.plan_cache.counters()["entries"] == 1
    summary = resumed.run()
    resumed.sink.close()
    assert summary["final"]
    c_res = resumed.plan_cache.counters()
    assert c_res["admissions"] == 1, c_res  # no re-fit after resume
    with open(out_path, "rb") as f:
        assert f.read() == golden
    # drift invalidation still bites on the resumed cache (the hook the
    # resume path re-attaches for the adapt controller)
    resumed._plan_invalidate("frontend")
    assert resumed.plan_cache.counters()["entries"] == 0


@pytest.mark.precision
def test_checkpoint_is_precision_portable(synth_store, tmp_path, monkeypatch):
    """A checkpoint written under one score precision must resume
    correctly under the other: every checkpointed value (carried
    EdgeDist statistics, window buffers, offsets) is host-side f32 and
    precision-independent — only device score blocks built AFTER the
    resume change. The resumed run must complete, keep span
    conservation, record its own precision in the summary, and land
    within the streamed-accuracy band of an uninterrupted f32 run."""
    from traceweaver_tpu.stream import (
        ReplaySource,
        StreamingReconstructor,
        TraceSink,
    )

    _, store = synth_store
    monkeypatch.delenv("TW_PRECISION", raising=False)
    golden = _run_stream(store)
    assert golden["precision"] == "f32"

    ckpt = str(tmp_path / "xprec.pkl")
    out_path = str(tmp_path / "xprec.jsonl")
    cfg = _stream_cfg(checkpoint_path=ckpt, checkpoint_every=2)
    source = ReplaySource(store, ooo_us=50_000.0, seed=1)
    svc = StreamingReconstructor(source, cfg, sink=TraceSink(out_path))
    assert svc.precision == "f32"
    partial = svc.run(max_windows=3)
    assert not partial["final"]
    svc.sink.close()

    # resume the f32 checkpoint under bf16
    monkeypatch.setenv("TW_PRECISION", "bf16")
    source2 = ReplaySource(store, ooo_us=50_000.0, seed=1)
    resumed = StreamingReconstructor.resume(ckpt, source2)
    assert resumed.precision == "bf16"
    summary = resumed.run()
    resumed.sink.close()
    assert summary["final"]
    assert summary["precision"] == "bf16"
    # span conservation survives the precision switch
    assert (summary["stats"].get("spans_emitted", 0)
            + summary["late_dropped"] == summary["consumed"])
    assert summary["consumed"] == golden["consumed"]
    assert summary["emitted_windows"] == golden["emitted_windows"]
    # accuracy parity across the switch (same bar as streamed-vs-batch)
    assert summary["accuracy"]["e2e"] >= golden["accuracy"]["e2e"] - 2.0

    # and the reverse direction: a bf16 checkpoint resumes under f32
    ckpt2 = str(tmp_path / "xprec2.pkl")
    cfg2 = _stream_cfg(checkpoint_path=ckpt2, checkpoint_every=2)
    svc2 = StreamingReconstructor(
        ReplaySource(store, ooo_us=50_000.0, seed=1), cfg2,
        sink=TraceSink(str(tmp_path / "xprec2.jsonl")))
    assert svc2.precision == "bf16"
    svc2.run(max_windows=3)
    svc2.sink.close()
    monkeypatch.delenv("TW_PRECISION", raising=False)
    back = StreamingReconstructor.resume(
        ckpt2, ReplaySource(store, ooo_us=50_000.0, seed=1))
    assert back.precision == "f32"
    summary2 = back.run()
    back.sink.close()
    assert summary2["final"] and summary2["precision"] == "f32"


def test_stream_emission_is_parseable_and_owned_once(synth_store, tmp_path):
    """Sink records: one JSON object per window; every emitted (service,
    endpoint) row references an owned incoming span at most once across
    the whole stream."""
    _, store = synth_store
    out = str(tmp_path / "emit.jsonl")
    _run_stream(store, sink_path=out)
    seen = set()
    n_windows = 0
    with open(out) as f:
        for line in f:
            rec = json.loads(line)
            n_windows += 1
            assert {"window", "services", "traces"} <= set(rec)
            for svc, eps in rec["services"].items():
                for ep, rows in eps.items():
                    for in_id, _out_id in rows:
                        key = (svc, ep, tuple(in_id))
                        assert key not in seen, "double-emitted assignment"
                        seen.add(key)
    assert n_windows >= 4
    assert seen


def test_warm_start_carries_state_between_windows(synth_store):
    """Warm-started streaming must produce single-pass fleet dispatches
    after the first window (carried dists) and stay within 2 pts of the
    cold two-pass-per-window configuration."""
    _, store = synth_store
    warm = _run_stream(store, cfg=_stream_cfg(warm_start=True))
    cold = _run_stream(store, cfg=_stream_cfg(warm_start=False))
    # warm runs route later windows through single-pass dynamism groups
    assert warm["fleet"].get("fleet_dynamism_dispatches", 0) > 0
    assert warm["accuracy"]["e2e"] >= cold["accuracy"]["e2e"] - 2.0


@pytest.mark.serve
def test_multi_tenant_checkpoint_kill_resume_no_leakage(tmp_path):
    """Two tenants at DIFFERENT watermarks through the serve layer's
    tenancy manager (same kill/resume machinery as the single-tenant
    test above, multiplexed): kill mid-stream after a drain checkpoint,
    resume, finish — each tenant's emitted bytes must equal its
    uninterrupted golden run exactly, with zero cross-tenant leakage
    (tenant A's sink never contains tenant B's traces, and vice versa).
    Open windows at the kill ride the checkpoints: zero lost windows."""
    from test_serve import hotel_trace

    from traceweaver_tpu.serve import ServeConfig, TenantService

    def _cfg(root):
        return ServeConfig(fix=2, window_us=20e6, overlap_us=4e6,
                           ooo_bound_us=1e6, verbose=False,
                           pump_windows=1, state_dir=str(root),
                           checkpoint_every=2)

    # tenant alpha consumes 2x beta's rate -> different watermarks at
    # every point, including the kill
    schedule = []
    ia = ib = 0
    while ia < 24 or ib < 12:
        for _ in range(2):
            if ia < 24:
                schedule.append(("alpha", ia)); ia += 1
        if ib < 12:
            schedule.append(("beta", ib)); ib += 1

    def one_trace_payload(tid, i):
        return {"data": [hotel_trace(i, tid[0], spacing_us=5e6)]}

    def feed(svc, steps):
        for tid, i in steps:
            svc.ingest(tid, one_trace_payload(tid, i))

    golden = TenantService(_cfg(tmp_path / "golden"))
    feed(golden, schedule)
    golden.flush()
    golden.drain()

    killed = TenantService(_cfg(tmp_path / "killed"))
    feed(killed, schedule[:20])
    a, b = killed.tenant("alpha").svc, killed.tenant("beta").svc
    assert a.watermark.value != b.watermark.value  # different frontiers
    assert a.emitted_windows > 0                   # kill bites mid-stream
    killed.drain()
    del killed

    resumed = TenantService.resume(_cfg(tmp_path / "killed"))
    assert sorted(resumed.tenants) == ["alpha", "beta"]
    feed(resumed, schedule[20:])
    resumed.flush()
    resumed.drain()

    for tid, other_prefix in (("alpha", b"b"), ("beta", b"a")):
        with open(tmp_path / "golden" / tid / "traces.jsonl", "rb") as f:
            want = f.read()
        with open(tmp_path / "killed" / tid / "traces.jsonl", "rb") as f:
            got = f.read()
        assert got == want, f"tenant {tid} resume not byte-identical"
        assert want.count(b"\n") >= 4  # several windows: the kill bit
        # zero cross-tenant leakage: no other-tenant trace ids anywhere
        for line in want.splitlines():
            rec = json.loads(line)
            for trace_id in rec["traces"]:
                assert not trace_id.startswith(
                    other_prefix.decode()), trace_id


def test_cli_stream_end_to_end(synth_store, tmp_path):
    """`python -m traceweaver_tpu.runtime.cli stream --source replay:...`
    runs end-to-end on CPU, emits incrementally, prints live window stats
    and the final streamed accuracy."""
    corpus_dir, _ = synth_store
    out = str(tmp_path / "cli.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu", TW_BACKEND="cpu")
    res = subprocess.run(
        [sys.executable, "-m", "traceweaver_tpu.runtime.cli", "stream",
         "--source", f"replay:{corpus_dir}?fix=5",
         "--window_s", "20", "--overlap_s", "4", "--watermark_s", "1",
         "--ooo_ms", "50", "--out", out],
        capture_output=True, text=True, timeout=600,
        cwd=os.path.join(os.path.dirname(__file__), ".."), env=env,
    )
    assert res.returncode == 0, res.stderr
    assert "[stream] win=" in res.stdout          # live per-window stats
    # per-window and summary lines are labeled with the score precision
    assert "prec=f32" in res.stdout
    assert "[stream] done [f32]:" in res.stdout
    assert "streamed end-to-end accuracy" in res.stdout
    with open(out) as f:
        lines = f.readlines()
    assert len(lines) >= 4
    json.loads(lines[0])
