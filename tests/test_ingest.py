"""Ingestion tests against the recorded reference datasets."""

import pytest

from traceweaver_tpu.ingest import build_service_problem, infer_invocation_dag
from traceweaver_tpu.metrics import get_ground_truth


def test_hotel_services(hotel_store):
    # hotel_reservation "HTTP GET /hotels" traces: frontend fans out; search
    # calls geo+rate; leaves have no outgoing spans.
    assert "frontend" in hotel_store.out_spans_by_process
    assert "search" in hotel_store.out_spans_by_process
    assert len(hotel_store.all_processes) >= 100


def test_hotel_partitions_single_incoming(hotel_store):
    for process in hotel_store.out_spans_by_process:
        prob = build_service_problem(hotel_store, process)
        if prob.skipped:
            continue
        assert len(prob.in_span_partitions) == 1
        n_in = len(next(iter(prob.in_span_partitions.values())))
        for ep, spans in prob.out_span_partitions.items():
            assert len(spans) == n_in  # no caching in the raw dataset
            # sorted by (start, end)
            keys = [(s.start_mus, s.start_mus + s.duration_mus) for s in spans]
            assert keys == sorted(keys)


def test_ground_truth_join(hotel_store):
    prob = build_service_problem(hotel_store, "search")
    ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
    _, in_spans = next(iter(prob.in_span_partitions.items()))
    for ep, mapping in ta.items():
        assert len(mapping) == len(in_spans)
        for (in_tid, _), (out_tid, _) in mapping.items():
            assert in_tid == out_tid  # trace-ID join


def test_containment(hotel_store):
    # every ground-truth outgoing span nests within its incoming span
    prob = build_service_problem(hotel_store, "search")
    ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
    _, in_spans = next(iter(prob.in_span_partitions.items()))
    by_id = {s.GetId(): s for part in prob.out_span_partitions.values() for s in part}
    violations = 0
    for in_span in in_spans:
        for ep in ta:
            out = by_id[ta[ep][in_span.GetId()]]
            if not (in_span.start_mus <= out.start_mus
                    and out.end_mus <= in_span.end_mus):
                violations += 1
    assert violations <= len(in_spans) * len(ta) * 0.05


def test_invocation_dag(hotel_store):
    prob = build_service_problem(hotel_store, "search")
    ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
    dag = infer_invocation_dag(prob.in_span_partitions, prob.out_span_partitions,
                               ta, hotel_store)
    import networkx as nx

    assert set(dag.nodes) == set(prob.out_span_partitions.keys())
    assert nx.is_directed_acyclic_graph(dag)


def test_nodejs_repair(nodejs_store):
    # FixSpans fabricates one client span per server span on the caller
    assert "init-service" in nodejs_store.out_spans_by_process
    n_in = sum(len(v) for v in nodejs_store.in_spans_by_process.values())
    n_out = sum(len(v) for v in nodejs_store.out_spans_by_process.values())
    n_traces = len(nodejs_store.all_processes)
    # every non-root call has both halves after repair; the root's caller is
    # the synthetic external client (no recorded client span)
    assert n_in == n_out + n_traces


def test_media_reroot(media_store):
    # every ingested trace is rooted at ComposeReview
    roots = [s for s in media_store.all_spans.values() if s.IsRoot()]
    assert roots
    assert all(s.op_name == "ComposeReview" for s in roots)


def test_fit_invocation_dag_recovers_chain():
    # mock evaluator: misfit = number of chain edges missing from the DAG —
    # the greedy search must add exactly the chain a->b->c and stop
    from traceweaver_tpu.ingest import fit_invocation_dag

    chain = [("a", "b"), ("b", "c")]
    parts = {"a": [], "b": [], "c": []}

    def evaluate(dag):
        return sum(1 for e in chain if not dag.has_edge(*e))

    dag, cost = fit_invocation_dag(parts, evaluate)
    assert cost == 0
    assert set(dag.edges()) == set(chain)
