"""Durable ingest WAL tests (tier-1, CPU).

Contracts covered (ISSUE 20, docs/ROBUSTNESS.md "Durability"):

- frame layer: CRC32-framed records round-trip through
  ``pack_frame``/``scan_frames``; a torn tail is truncated to the last
  CRC-valid frame boundary at open — exercised at EVERY byte cut point
  of the final frame, plus a mid-frame corruption flip;
- segment layer: rotation by size, checkpoint low-water truncation
  (whole segments only, the open tail never), transfer round-trip
  through ``read_all_bytes``/``install_bytes`` with a torn transfer
  tail;
- service layer: ack-after-ledger replay identity — a tenant killed
  hard after ack, before checkpoint, resumes from the WAL and emits
  byte-for-byte what an uncrashed run emits; a tenant killed before its
  FIRST checkpoint recovers purely from the WAL;
- idempotent re-ingest: per-tenant client ``seq`` echo, dedup on retry
  of a lost ack (original accounting returned, no re-append), the dedup
  window surviving crash + replay;
- ``TW_WAL=0`` inertness: no ``wal/`` directory, no WAL stats;
- the ``wal`` fault-injection site: a faulted append writes HALF a
  frame (a real torn append), the client gets no ack, and both the
  live rewind and the next open truncate it;
- X-TW-Seq over the wire: echo, dedup, and the 400 on a non-integer;
- the TW_WAL* / TW_FLEET_RESPAWN_MAX knobs: registered, typed, ranged.

The corpus is the handcrafted Jaeger JSON from test_serve.py — fully
deterministic, so byte-identity assertions are exact.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import pytest

import jax

import traceweaver_tpu.runtime  # noqa: F401  — breaks the serve import cycle
from traceweaver_tpu.serve import ServeConfig, TenantService
from traceweaver_tpu.stream import wal as walmod

jax.config.update("jax_platforms", "cpu")

pytestmark = pytest.mark.wal

from tests.test_serve import hotel_payload  # noqa: E402


def _cfg(**kw):
    base = dict(fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
                verbose=False, pump_windows=10**9)
    base.update(kw)
    return ServeConfig(**base)


def _raw(payload) -> bytes:
    return json.dumps(payload).encode("utf-8")


@pytest.fixture(autouse=True)
def _wal_on(monkeypatch):
    """These tests pin the knob explicitly — the suite must hold under
    any ambient TW_WAL/TW_WAL_SYNC setting."""
    monkeypatch.setenv("TW_WAL", "1")
    monkeypatch.setenv("TW_WAL_SYNC", "batch")
    monkeypatch.delenv("TW_FAULTS", raising=False)
    yield


# ---------------------------------------------------------------------------
# frame layer
# ---------------------------------------------------------------------------

def test_frame_roundtrip_and_scan():
    payloads = [b"alpha", b"", b"x" * 300]
    raw = b"".join(walmod.pack_frame(i + 1, p)
                   for i, p in enumerate(payloads))
    frames, valid_end = walmod.scan_frames(raw)
    assert valid_end == len(raw)
    assert [(seq, p) for _off, seq, p in frames] == [
        (1, b"alpha"), (2, b""), (3, b"x" * 300)]


def test_torn_tail_truncated_at_every_byte_boundary(tmp_path):
    """Cut a 3-frame log at EVERY byte offset inside the final frame:
    each cut must scan back to exactly 2 frames, and reopening the
    directory must truncate the file to the 2-frame boundary, count one
    torn tail, and append seq 3 cleanly on top."""
    payloads = [b"one", b"two", b"payload-three"]
    full = b"".join(walmod.pack_frame(i + 1, p)
                    for i, p in enumerate(payloads))
    keep = len(b"".join(walmod.pack_frame(i + 1, p)
                        for i, p in enumerate(payloads[:2])))
    for cut in range(keep + 1, len(full)):
        frames, valid_end = walmod.scan_frames(full[:cut])
        assert valid_end == keep, cut
        assert [s for _o, s, _p in frames] == [1, 2], cut

        d = tmp_path / f"cut{cut}"
        d.mkdir()
        seg = d / walmod.segment_name(1)
        seg.write_bytes(full[:cut])
        w = walmod.WriteAheadLog(str(d))
        assert w.torn_tails == 1 and w.torn_bytes == cut - keep, cut
        assert w.last_seq == 2, cut
        assert seg.stat().st_size == keep, cut
        assert w.append(payloads[2]) == 3
        w.close()
        assert seg.read_bytes() == full
    # a clean cut at the frame boundary is NOT torn
    d = tmp_path / "clean"
    d.mkdir()
    (d / walmod.segment_name(1)).write_bytes(full[:keep])
    w = walmod.WriteAheadLog(str(d))
    assert w.torn_tails == 0 and w.last_seq == 2
    w.close()


def test_mid_frame_corruption_ends_the_valid_prefix(tmp_path):
    """A flipped byte inside the LAST frame's payload fails its CRC and
    truncates it; earlier frames are untouched (append-only + whole-
    segment truncation mean only the tail can rot)."""
    full = b"".join(walmod.pack_frame(i + 1, b"p%d" % i) for i in range(3))
    keep = len(full) - len(walmod.pack_frame(3, b"p2"))
    rotten = bytearray(full)
    rotten[-1] ^= 0xFF
    frames, valid_end = walmod.scan_frames(bytes(rotten))
    assert valid_end == keep
    assert [s for _o, s, _p in frames] == [1, 2]


# ---------------------------------------------------------------------------
# segment layer
# ---------------------------------------------------------------------------

def test_segment_rotation_truncation_and_replay(tmp_path):
    d = str(tmp_path / "wal")
    w = walmod.WriteAheadLog(d, segment_bytes=64)  # ~2 frames per segment
    for i in range(10):
        assert w.append(b"payload-%02d" % i) == i + 1
    segs = walmod.list_segments(d)
    assert len(segs) >= 3
    # replay crosses segments in order, honoring the low-water mark
    assert [p for _s, p in w.replay(0)] == [b"payload-%02d" % i
                                            for i in range(10)]
    assert [s for s, _p in w.replay(7)] == [8, 9, 10]
    # checkpoint low-water truncation drops whole covered segments only;
    # the open tail always survives
    removed = w.truncate_below(w.last_seq)
    assert removed == len(segs) - 1
    assert walmod.list_segments(d) == [segs[-1]]
    assert [s for s, _p in w.replay(0)]  # tail records still replayable
    w.close()


def test_transfer_roundtrip_with_torn_tail(tmp_path):
    """The failover transfer halves: concatenated segment bytes from a
    crashed disk install as one fresh segment; a torn transfer tail is
    truncated on install, same contract as open."""
    src = str(tmp_path / "src")
    w = walmod.WriteAheadLog(src, segment_bytes=64)
    for i in range(6):
        w.append(b"rec-%d" % i)
    w.close()
    raw = walmod.read_all_bytes(src)
    dst = str(tmp_path / "dst")
    torn = walmod.pack_frame(7, b"torn-in-transfer")
    assert walmod.install_bytes(dst, raw + torn[: len(torn) // 2]) == 6
    r = walmod.WriteAheadLog(dst)
    assert [p for _s, p in r.replay(0)] == [b"rec-%d" % i for i in range(6)]
    r.close()
    assert walmod.install_bytes(str(tmp_path / "empty"), b"junk") == 0


# ---------------------------------------------------------------------------
# service layer: ack-after-ledger replay identity
# ---------------------------------------------------------------------------

def _wal_post(svc, tid, payload, seq):
    raw = _raw(payload)
    return svc.wal_ingest(tid, raw, raw=raw, client_seq=seq)


def _emitted(state_dir, tid):
    with open(os.path.join(state_dir, tid, "traces.jsonl"), "rb") as f:
        return f.read()


def test_replay_after_hard_death_emits_identical_bytes(tmp_path):
    """The tentpole contract: a tenant killed hard AFTER its acks but
    BEFORE the covering checkpoint resumes from the WAL tail and emits
    byte-for-byte what an uncrashed run emits. The first chunk is
    checkpointed (low-water covers it); the second exists only in the
    WAL at death."""
    chunk1 = hotel_payload(prefix="a")
    chunk2 = hotel_payload(prefix="b", base_us=200e6)

    clean_dir = str(tmp_path / "clean")
    svc = TenantService(_cfg(state_dir=clean_dir))
    assert _wal_post(svc, "ten", chunk1, 1)["ingested_traces"] == 24
    assert _wal_post(svc, "ten", chunk2, 2)["ingested_traces"] == 24
    svc.flush()
    svc.drain()
    want = _emitted(clean_dir, "ten")
    assert want

    crash_dir = str(tmp_path / "crash")
    svc = TenantService(_cfg(state_dir=crash_dir))
    _wal_post(svc, "ten", chunk1, 1)
    assert svc.tenant("ten").checkpoint() is True
    summary = _wal_post(svc, "ten", chunk2, 2)
    assert summary["ingested_traces"] == 24 and summary["seq"] == 2
    # kill -9: no drain, no close, no checkpoint — just abandon the
    # object; the batch policy already flushed every append to the OS
    del svc

    resumed = TenantService.resume(_cfg(state_dir=crash_dir))
    t = resumed.tenant("ten")
    assert t.counters.get("wal_replayed") == 1  # chunk2 only: low-water
    resumed.flush()
    resumed.drain()
    assert _emitted(crash_dir, "ten") == want


def test_recover_before_first_checkpoint_replays_everything(tmp_path):
    """A tenant that dies before its FIRST checkpoint exists only as a
    WAL directory — resume must still find it (no ckpt.pkl to scan for)
    and replay from seq 0."""
    state = str(tmp_path / "s")
    svc = TenantService(_cfg(state_dir=state))
    _wal_post(svc, "ten", hotel_payload(prefix="a"), 1)
    del svc  # kill -9 before any checkpoint

    resumed = TenantService.resume(_cfg(state_dir=state))
    assert "ten" in resumed.stats()["tenants"]
    t = resumed.tenant("ten")
    assert t.counters.get("wal_replayed") == 1
    resumed.flush()
    resumed.drain()
    assert _emitted(state, "ten")


def test_client_seq_dedup_on_retry_and_across_crash(tmp_path):
    """A client retry of a LOST ack (same X-TW-Seq) is answered with the
    original application's accounting — no re-append, no re-ingest —
    both live and after a crash+replay (the dedup window rides the WAL
    envelope and the checkpoint)."""
    state = str(tmp_path / "s")
    svc = TenantService(_cfg(state_dir=state))
    payload = hotel_payload(prefix="a")
    first = _wal_post(svc, "ten", payload, 41)
    assert first["ingested_traces"] == 24 and first["seq"] == 41
    retry = _wal_post(svc, "ten", payload, 41)
    assert retry["deduped"] is True and retry["seq"] == 41
    assert retry["ingested_traces"] == 24  # the ORIGINAL accounting
    t = svc.tenant("ten")
    assert t.wal.stats()["appended"] == 1  # the retry never hit the log
    assert t.counters["wal_deduped"] == 1
    del svc  # kill -9

    resumed = TenantService.resume(_cfg(state_dir=state))
    retry = _wal_post(resumed, "ten", payload, 41)
    assert retry["deduped"] is True and retry["ingested_traces"] == 24
    resumed.flush()
    resumed.drain()
    # exactly one emitted window despite 3 posts of the same seq
    assert _emitted(state, "ten").count(b"\n") == 1


def test_tw_wal_0_is_inert(tmp_path, monkeypatch):
    """The kill switch: with TW_WAL=0 the plain ingest path runs, no
    wal/ directory is ever created, and the stats surface reports no
    WAL."""
    monkeypatch.setenv("TW_WAL", "0")
    state = str(tmp_path / "s")
    svc = TenantService(_cfg(state_dir=state))
    assert svc.ingest("ten", _raw(hotel_payload()))["ingested_traces"] == 24
    svc.flush()
    assert not os.path.isdir(os.path.join(state, "ten", "wal"))
    assert svc.stats()["tenants"]["ten"]["wal"] is None
    svc.drain()


# ---------------------------------------------------------------------------
# the `wal` fault-injection site: torn appends on demand
# ---------------------------------------------------------------------------

def test_faulted_append_tears_the_frame_and_never_acks(tmp_path,
                                                       monkeypatch):
    from traceweaver_tpu.runtime import faults

    d = str(tmp_path / "wal")
    w = walmod.WriteAheadLog(d)
    w.append(b"good-1")
    monkeypatch.setenv("TW_FAULTS", "wal:1.0:max=1")
    with pytest.raises(Exception):
        w.append(b"never-acked")
    # half a frame is on disk past the valid boundary — exactly what a
    # death mid-write leaves; the live log rewinds it on the next append
    monkeypatch.delenv("TW_FAULTS")
    assert w.append(b"good-2") == 2  # seq 2: the torn record never counted
    w.close()
    assert [p for _s, p in walmod.WriteAheadLog(d).replay(0)] == [
        b"good-1", b"good-2"]
    assert faults.SITES.count("wal") == 1  # registered exactly once


def test_faulted_append_torn_on_disk_when_process_dies(tmp_path,
                                                       monkeypatch):
    """Same injection, but the process 'dies' holding the torn tail
    (no rewind): the next OPEN truncates and counts it."""
    d = str(tmp_path / "wal")
    w = walmod.WriteAheadLog(d)
    w.append(b"good-1")
    monkeypatch.setenv("TW_FAULTS", "wal:1.0:max=1")
    with pytest.raises(Exception):
        w.append(b"never-acked")
    del w  # kill -9 with the half frame on disk
    monkeypatch.delenv("TW_FAULTS")
    r = walmod.WriteAheadLog(d)
    assert r.torn_tails == 1 and r.last_seq == 1
    assert [p for _s, p in r.replay(0)] == [b"good-1"]
    r.close()


# ---------------------------------------------------------------------------
# over the wire: X-TW-Seq echo + dedup through serve/http.py
# ---------------------------------------------------------------------------

def _http(method, url, payload=None, headers=None, timeout=120):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_seq_echo_and_dedup(tmp_path):
    from traceweaver_tpu.serve import make_server

    svc = TenantService(_cfg(state_dir=str(tmp_path / "s")))
    server = make_server(svc, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        url = base + "/api/v1/tenants/ten/spans"
        code, out = _http("POST", url, hotel_payload(),
                          headers={"X-TW-Seq": "7"})
        assert code == 200 and out["seq"] == 7
        assert out["ingested_traces"] == 24
        code, out = _http("POST", url, hotel_payload(),
                          headers={"X-TW-Seq": "7"})
        assert code == 200 and out.get("deduped") is True
        assert out["ingested_traces"] == 24  # original accounting echoed
        # the ack really was ledgered before the 200 went out
        code, st = _http("GET", base + "/api/v1/stats")
        assert st["tenants"]["ten"]["wal"]["appended"] == 1
        assert st["tenants"]["ten"]["counters"]["wal_deduped"] == 1
        # a seq-less POST is plain (non-idempotent) ingest, still WAL'd
        code, out = _http("POST", url, hotel_payload(prefix="b",
                                                     base_us=200e6))
        assert code == 200 and "seq" not in out
        # a non-integer header is the client's bug: 400, nothing applied
        code, out = _http("POST", url, hotel_payload(),
                          headers={"X-TW-Seq": "not-a-number"})
        assert code == 400 and "X-TW-Seq" in out["error"]
    finally:
        server.shutdown()
        svc.drain()


# ---------------------------------------------------------------------------
# knobs: typed + ranged
# ---------------------------------------------------------------------------

def test_wal_knobs_registered_typed_and_ranged(monkeypatch):
    from traceweaver_tpu.runtime import knobs

    assert knobs.REGISTRY["TW_WAL"].type == "bool"
    assert knobs.REGISTRY["TW_WAL"].default is True
    assert knobs.REGISTRY["TW_WAL_SYNC"].type == "enum"
    assert knobs.get("TW_WAL_SYNC") == "batch"
    assert set(walmod.SYNC_POLICIES) == {"always", "batch", "off"}
    monkeypatch.setenv("TW_WAL_SYNC", "sometimes")
    with pytest.raises(knobs.KnobError):
        knobs.get("TW_WAL_SYNC")
    monkeypatch.setenv("TW_WAL_SEGMENT_MB", "0")
    assert knobs.get_int("TW_WAL_SEGMENT_MB") == 1  # clamped to lo
    monkeypatch.setenv("TW_WAL_SEGMENT_MB", "99999")
    assert knobs.get_int("TW_WAL_SEGMENT_MB") == 1024  # clamped to hi
    monkeypatch.setenv("TW_FLEET_RESPAWN_MAX", "-3")
    assert knobs.get_int("TW_FLEET_RESPAWN_MAX") == 0
    assert knobs.REGISTRY["TW_FLEET_RESPAWN_MAX"].hi == 64
    # every WAL knob is known at startup (no unknown-knob warning)
    for name in ("TW_WAL", "TW_WAL_SYNC", "TW_WAL_SEGMENT_MB",
                 "TW_FLEET_RESPAWN_MAX"):
        assert name not in knobs.unknown_knobs()
