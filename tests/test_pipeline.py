"""Pipelined fleet dispatch must be bit-identical to the serial flow.

The dispatcher overlaps pack/transfer/compute/decode across shape-class
groups (fleet._solve_groups_pipelined): a pack thread builds group N+1
while group N executes, each group's dispatch/compaction/decode flow runs
on a worker pool, and FLEET_BUDGET_ELEMS bounds the live in-flight
elements. The pipeline reorders WORK only — these tests pin down that the
6-tuple outputs are byte-for-byte the TW_PIPELINE=0 serial flow's, across
the compacted two-pass EM path, the single-pass dynamism path, and the
budget-drain path, and that the compaction flag fetch moves O(B) bytes
(its own [B] bool array) instead of the whole packed block. The mesh leg
checks that compaction now engages on sharded dispatches too, with the
redispatch bucketed per shard, identically on 1 vs 8 devices.

Everything here is synthetic (no dataset dependency) and interpret-safe
under JAX_PLATFORMS=cpu — tier-1.
"""

import numpy as np
import pytest

import jax

import traceweaver_tpu.algorithms.fleet as fleet_mod
from traceweaver_tpu.algorithms.fleet import FleetItem, solve_fleet
from traceweaver_tpu.algorithms.weaver_tpu import solve_windows_fleet
from traceweaver_tpu.spans import SKIP, Span

jax.config.update("jax_platforms", "cpu")

pytestmark = pytest.mark.pipeline


def _service_items(svc="svc", n_traces=48, burst=4, eps=("A", "B"),
                   gap=5000.0, seed=0, drop_every=0):
    """One FleetItem over a synthetic span stream: bursts of ``burst``
    overlapping requests then a gap (window boundary), a chain DAG over
    ``eps``. ``drop_every`` > 0 drops every k-th trace's outgoing spans
    (skip budget > 0 -> the single-pass dynamism group)."""
    import networkx as nx

    rng = np.random.default_rng(seed)
    in_spans = []
    out_spans = {ep: [] for ep in eps}
    ta = {ep: {} for ep in eps}
    t = 0.0
    for i in range(n_traces):
        t += 30.0 if i % burst else gap
        s_in = Span(f"{svc}-t{i}", "in", t, 400.0 + 40.0 * len(eps), "op",
                    [], svc, "server")
        in_spans.append(s_in)
        dropped = drop_every and (i % drop_every == 0)
        prev_end = t + 10.0
        for ep in eps:
            if dropped:
                ta[ep][s_in.GetId()] = SKIP
                continue
            start = prev_end + 15.0 + rng.normal(0, 2)
            s_out = Span(f"{svc}-t{i}", f"out-{ep}", start, 50.0,
                         f"op{ep}", [], svc, "client")
            out_spans[ep].append(s_out)
            ta[ep][s_in.GetId()] = s_out.GetId()
            prev_end = start + 50.0
    dag = nx.DiGraph()
    for a, b in zip(eps, eps[1:]):
        dag.add_edge(a, b)
    if len(eps) == 1:
        dag.add_node(eps[0])
    return FleetItem(svc, {"IN": in_spans}, out_spans, ta, dag)


def _mixed_items():
    """Three services in three distinct shape classes (different window
    widths / endpoint counts / pass counts), so the dispatcher builds
    several groups and the pipeline genuinely interleaves them."""
    return [
        _service_items("alpha", n_traces=48, burst=4, eps=("A", "B"),
                       seed=0),
        _service_items("beta", n_traces=60, burst=12, eps=("A", "B", "C"),
                       seed=1),
        _service_items("gamma", n_traces=40, burst=4, eps=("A", "B"),
                       seed=2, drop_every=5),
    ]


def _assert_identical(a, b):
    for x, y in zip(a, b):
        assert x[0] == y[0]   # assignments
        assert x[1] == y[1]   # top-k
        assert x[2:] == y[2:]  # not_best / n / candidates / unassigned


def test_pipelined_identical_to_serial(monkeypatch):
    monkeypatch.setenv("TW_FLEET_MERGE", "0")  # keep the classes separate
    items = _mixed_items()

    monkeypatch.setenv("TW_PIPELINE", "0")
    serial_stats = {}
    serial = solve_fleet(items, stats=serial_stats)
    assert serial_stats.get("pipeline_groups") is None  # kill switch works
    assert serial_stats.get("fleet_dispatches", 0) >= 3

    monkeypatch.setenv("TW_PIPELINE", "1")
    stats = {}
    piped = solve_fleet(items, stats=stats)
    # the pipeline path actually ran, over every group, and engaged the
    # compacted two-pass EM flow on the way (default TW_COMPACT=1)
    assert stats.get("pipeline_groups", 0) >= 3
    assert stats.get("pipeline_depth", 0) >= 1
    assert stats.get("compact_windows_total", 0) > 0
    _assert_identical(serial, piped)


def test_pipelined_budget_drain_identical(monkeypatch):
    """A live-element budget smaller than the workload total (but large
    enough that no group falls back per-service) forces the serial drain
    / pipeline admission gate; outputs must not change."""
    monkeypatch.setenv("TW_FLEET_MERGE", "0")
    items = _mixed_items()

    probe_stats = {}
    reference = solve_fleet(items, stats=probe_stats)
    cost_max = probe_stats["fleet_group_cost_max"]
    cost_total = probe_stats["fleet_group_cost_total"]
    assert cost_total > cost_max  # several groups: the budget can bind

    monkeypatch.setattr(fleet_mod, "FLEET_BUDGET_ELEMS", int(cost_max))
    for pipeline in ("0", "1"):
        monkeypatch.setenv("TW_PIPELINE", pipeline)
        stats = {}
        out = solve_fleet(items, stats=stats)
        # the budget bound admissions but never tripped the per-group
        # fallback (every group fits the budget alone)
        assert stats.get("fleet_fallback_budget") is None
        _assert_identical(reference, out)


def test_flag_only_fetch_matches_full_fetch_and_is_tiny():
    """The warm dispatch's convergence flags ride their own [B] bool
    array: fetching it ALONE must (a) yield the same convergence set as
    reading it after a full-tensor fetch and (b) move exactly B bytes,
    not the packed block (the d2h_bytes_flags ledger proves it)."""
    items = _mixed_items()[:1]
    stats = {}
    solve_fleet(items, stats=stats)
    total = stats.get("compact_windows_total", 0)
    assert total > 0  # compaction engaged
    # bool flags: exactly one byte per window per compacted warm pass
    assert stats["d2h_bytes_flags"] == total
    # and the flag traffic is a vanishing share of all D2H traffic
    assert stats["d2h_bytes_flags"] < 0.01 * stats["d2h_bytes_fetched"]

    # same convergence set whether the flags are fetched alone or after
    # the packed block has been pulled to the host (donation/aliasing of
    # the big block must not disturb the separate flag array)
    item = items[0]
    prep = fleet_mod._prepare(
        item, fleet_mod.WeaverTPU(None, None))
    from traceweaver_tpu.algorithms.weaver_tpu import (
        pack_problem, perfect_cut_windows)

    windows = perfect_cut_windows(prep["in_spans"], 1024)
    packed = pack_problem(prep["in_spans"], item.out_span_partitions,
                          prep["out_eps"], prep["dists"], prep["in_ep"],
                          item.dag, windows=windows)
    a = packed.arrays
    args = tuple(a[k] for k in fleet_mod._BATCH_KEYS) + (
        np.zeros(a["in_start"].shape[0], np.int32),)
    tables = tuple(a[k][None] for k in fleet_mod._TABLE_KEYS)
    out1, flags1 = solve_windows_fleet(*args, *tables, n_sweeps=2)
    flags_alone = np.asarray(flags1)              # flag-only fetch
    out2, flags2 = solve_windows_fleet(*args, *tables, n_sweeps=2)
    _full = np.asarray(out2)                      # full-tensor fetch first
    flags_after_full = np.asarray(flags2)
    assert flags_alone.dtype == np.bool_ and flags_alone.ndim == 1
    assert np.array_equal(flags_alone, flags_after_full)


def test_mesh_compaction_identical_on_1_vs_8_devices(monkeypatch):
    """Convergence compaction now covers sharded dispatches: the mesh
    path must redispatch only unconverged windows (per-shard-bucketed
    batch) and stay identical to the single-device fleet AND to the
    uncompacted mesh flow."""
    from traceweaver_tpu.parallel.mesh import bucket_rows_per_shard, make_mesh

    # the helper itself: per-shard power-of-two rows, divisible total
    assert bucket_rows_per_shard(5, 1) == 8
    assert bucket_rows_per_shard(5, 8) == 8
    assert bucket_rows_per_shard(9, 8) == 16
    assert bucket_rows_per_shard(17, 4) == 32

    monkeypatch.setenv("TW_FLEET_MERGE", "0")
    items = _mixed_items()
    mesh = make_mesh(8)

    single = solve_fleet(items)
    stats_m = {}
    sharded = solve_fleet(items, mesh=mesh, stats=stats_m)
    # compaction engaged on the sharded dispatches
    assert stats_m.get("compact_windows_total", 0) > 0
    assert stats_m["d2h_bytes_flags"] > 0
    _assert_identical(single, sharded)

    monkeypatch.setenv("TW_COMPACT", "0")
    stats_u = {}
    uncompacted = solve_fleet(items, mesh=mesh, stats=stats_u)
    assert stats_u.get("compact_windows_total") is None
    _assert_identical(sharded, uncompacted)


def test_stats_are_counts_not_flags(monkeypatch):
    """Budget fallbacks accumulate a COUNT (one per over-budget group),
    not an overwritten 1.0 flag — a mixed workload's ledger must say how
    many groups degraded."""
    monkeypatch.setenv("TW_FLEET_MERGE", "0")
    items = _mixed_items()
    monkeypatch.setattr(fleet_mod, "FLEET_BUDGET_ELEMS", 1)
    stats = {}
    out = solve_fleet(items, stats=stats)
    # every group fell back per-service, and the counter says so
    assert stats["fleet_fallback_budget"] >= 3.0
    assert all(o is not None and len(o) == 6 for o in out)
