"""Unit tests for the accuracy metrics on a synthetic micro-problem."""

from traceweaver_tpu.metrics import (
    accuracy_end_to_end,
    accuracy_for_service,
    bin_accuracy_by_response_times,
    get_ground_truth,
    get_out_eps_in_order,
)
from traceweaver_tpu.spans import Span


def _mk(tid, sid, start, dur, kind):
    return Span(tid, sid, start, dur, "op", [], "p1", kind)


def _problem():
    in_spans = [_mk(f"t{i}", "in", 100 * i, 90, "server") for i in range(4)]
    out_a = [_mk(f"t{i}", "a", 100 * i + 10, 20, "client") for i in range(4)]
    out_b = [_mk(f"t{i}", "b", 100 * i + 40, 20, "client") for i in range(4)]
    return {"up": in_spans}, {"A": out_a, "B": out_b}


def test_ground_truth():
    in_parts, out_parts = _problem()
    ta = get_ground_truth(in_parts, out_parts)
    assert ta["A"][("t2", "in")] == ("t2", "a")
    assert ta["B"][("t0", "in")] == ("t0", "b")


def test_accuracy_all_or_nothing_per_span():
    in_parts, out_parts = _problem()
    ta = get_ground_truth(in_parts, out_parts)
    pred = {ep: dict(m) for ep, m in ta.items()}
    # one wrong hop on t1 kills the whole span, not just one endpoint
    pred["B"][("t1", "in")] = ("t0", "b")
    assert accuracy_for_service(pred, ta, in_parts) == 0.75


def test_accuracy_list_unwrap():
    in_parts, out_parts = _problem()
    ta = get_ground_truth(in_parts, out_parts)
    pred = {ep: {k: [v] for k, v in m.items()} for ep, m in ta.items()}
    pred["A"][("t0", "in")] = [("t0", "a"), ("t1", "a")]  # ambiguous => wrong
    assert accuracy_for_service(pred, ta, in_parts) == 0.75


def test_end_to_end_requires_all_services():
    in_parts, out_parts = _problem()
    ta = get_ground_truth(in_parts, out_parts)
    pred = {ep: dict(m) for ep, m in ta.items()}
    pred["A"][("t3", "in")] = ("t2", "a")
    trace_acc, acc = accuracy_end_to_end({"svc": pred}, {"svc": ta},
                                         {"svc": in_parts["up"]})
    assert trace_acc[("t3")] is False and abs(acc - 0.75) < 1e-9


def test_out_eps_in_order():
    _, out_parts = _problem()
    assert get_out_eps_in_order(out_parts) == ["A", "B"]


def test_bin_accuracy():
    all_spans = {}
    trace_acc = {}
    for i in range(20):
        s = _mk(f"t{i}", "root", 0, 10 * (i + 1), "server")
        all_spans[s.GetId()] = s
        trace_acc[f"t{i}"] = i % 2 == 0
    bins = bin_accuracy_by_response_times(trace_acc, all_spans, nbins=10)
    assert len(bins) == 10
    assert all(0.0 <= acc <= 1.0 for _, acc, _ in bins)
