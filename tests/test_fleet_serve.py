"""Replica fleet tier tests (tier-1, CPU): router, migration, campaign.

Contracts covered (ISSUE 16):

- consistent hash ring: process-stable (two ring instances agree),
  complete preference orders, bounded remap when a replica joins;
- circuit breaker: consecutive-failure open, cooldown close, success
  reset;
- router retry-on-next-replica: a POST whose ring owner is dead lands
  on the next replica in preference order, gets pinned there, and the
  dead replica's breaker records the failure;
- LIVE tenant migration conservation: a tenant killed mid-stream on
  replica A (open windows, half its traces in flight) resumes on
  replica B and the final sink is byte-identical to the unmigrated
  single-replica run — zero lost, zero duplicated windows; the source
  answers 410 afterwards (and still does after a restart+resume);
- the checkpoint-transfer surface: CRC verification refuses torn bytes
  at both ends;
- every TW_FLEET_* knob is typed + ranged in the registry;
- the in-process wire campaign emits a ledger-compatible artifact that
  `campaign compare` passes against itself, with the zero-loss gate on
  every rung.

All tests here run the REAL wire path (ThreadingHTTPServer end to end)
with in-process replicas; the subprocess fleet smoke (2 replica
processes + router + migration + rolling restart) lives in
test_bench_smoke.py.
"""

import json
import socket
import time
import urllib.error
import urllib.request

import pytest

import jax

import traceweaver_tpu.runtime  # noqa: F401  — breaks the serve import cycle
from traceweaver_tpu.serve import ServeConfig, TenancyError, TenantService

jax.config.update("jax_platforms", "cpu")

pytestmark = pytest.mark.fleet

from tests.test_serve import _run_single_tenant, hotel_payload  # noqa: E402


def _cfg(**kw):
    base = dict(fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
                verbose=False, pump_windows=10**9)
    base.update(kw)
    return ServeConfig(**base)


def _http(method, url, payload=None, timeout=120, headers=None):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers or {}), json.loads(e.read())


# ---------------------------------------------------------------------------
# hash ring + breaker
# ---------------------------------------------------------------------------

def test_hash_ring_stable_complete_and_bounded_remap():
    from traceweaver_tpu.fleet_serve.router import HashRing

    names = ["r0", "r1", "r2"]
    a = HashRing(names, vnodes=64)
    b = HashRing(list(reversed(names)), vnodes=64)
    keys = [f"tenant-{i}" for i in range(300)]
    for k in keys:
        # process-stable and construction-order independent: two rings
        # over the same replica set agree on every preference order
        assert a.preference(k) == b.preference(k)
        assert sorted(a.preference(k)) == names  # complete failover order
        assert a.lookup(k) == a.preference(k)[0]
    # every replica owns a nontrivial share of the tenant space
    owners = {n: sum(1 for k in keys if a.lookup(k) == n) for n in names}
    assert all(v > len(keys) * 0.1 for v in owners.values()), owners
    # consistent hashing's point: a new replica remaps a bounded slice
    # of the tenant space, and every move lands ON the new replica
    grown = HashRing(names + ["r3"], vnodes=64)
    moved = [k for k in keys if grown.lookup(k) != a.lookup(k)]
    assert 0 < len(moved) < len(keys) * 0.5, f"{len(moved)} remapped"
    assert all(grown.lookup(k) == "r3" for k in moved)


def test_circuit_breaker_open_cooldown_reset():
    from traceweaver_tpu.fleet_serve.router import CircuitBreaker

    cb = CircuitBreaker(fail_max=3, cooldown_s=0.15)
    cb.record(False)
    cb.record(False)
    assert not cb.open  # under the threshold
    cb.record(False)
    assert cb.open and cb.opened == 1
    time.sleep(0.2)
    assert not cb.open  # cooldown elapsed: half-open, probes may flow
    cb.record(True)
    assert cb.fails == 0 and not cb.open  # success resets the streak
    cb.record(False)
    assert not cb.open  # one failure after reset is under the threshold


# ---------------------------------------------------------------------------
# router proxy: retry-on-next-replica, pins, health surface
# ---------------------------------------------------------------------------

def test_router_retries_dead_replica_and_pins_fallback(tmp_path):
    from traceweaver_tpu.fleet_serve.manager import InProcReplica
    from traceweaver_tpu.fleet_serve.router import FleetRouter, HashRing

    live = InProcReplica("live", _cfg(state_dir=str(tmp_path / "live")))
    # a replica that answers nothing: a bound-then-closed ephemeral port
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    router = FleetRouter(
        {"dead": f"http://127.0.0.1:{dead_port}",
         "live": live.base_url}, port=0).start()
    try:
        # pick a tenant the RING assigns to the dead replica, so the 200
        # can only come from a counted retry onto the next preference
        ring = HashRing(["dead", "live"])
        tenant = next(f"t{i}" for i in range(200)
                      if ring.lookup(f"t{i}") == "dead")
        code, _, out = _http(
            "POST", f"{router.base_url}/api/v1/tenants/{tenant}/spans",
            hotel_payload(n_traces=6, prefix="rt"))
        assert code == 200 and out["ingested_traces"] == 6, out
        assert router.counters["retried"] >= 1
        assert router.counters["rerouted"] >= 1
        # the failover is sticky: the tenant is pinned to the live
        # replica so its stream stays on ONE replica
        assert router.pins[tenant] == "live"
        assert router.replicas["dead"].breaker.fails >= 1
        # health/ready surface: the fleet is ready while >=1 routable
        code, _, out = _http("GET", router.base_url + "/readyz")
        assert code == 200 and out["ready"] is True
        code, _, out = _http("GET", router.base_url + "/healthz")
        assert code == 200
        assert {r["name"] for r in out["replicas"]} == {"dead", "live"}
    finally:
        router.stop()
        live.stop()


def test_router_classifies_reset_midbody_and_reroutes(tmp_path):
    """A replica that ACCEPTS the connection and then resets it (RST
    after the request starts flowing — a crashing process, not a dead
    port) is a distinct failure class: the router must count it as
    ``reset_midbody``, re-resolve the ring, and land the POST on a
    survivor — not surface the reset to the client."""
    import struct
    import threading

    from traceweaver_tpu.fleet_serve.manager import InProcReplica
    from traceweaver_tpu.fleet_serve.router import FleetRouter, HashRing

    live = InProcReplica("live", _cfg(state_dir=str(tmp_path / "live")))
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(5)
    rst_port = srv.getsockname()[1]

    def rst_loop():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            # SO_LINGER(1, 0): close() sends RST, the client sees
            # ConnectionResetError mid-request/response, not FIN
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
            try:
                conn.recv(64)
            except OSError:
                pass
            conn.close()

    threading.Thread(target=rst_loop, daemon=True).start()
    router = FleetRouter(
        {"rst": f"http://127.0.0.1:{rst_port}",
         "live": live.base_url}, port=0).start()
    try:
        ring = HashRing(["rst", "live"])
        tenant = next(f"t{i}" for i in range(200)
                      if ring.lookup(f"t{i}") == "rst")
        code, _, out = _http(
            "POST", f"{router.base_url}/api/v1/tenants/{tenant}/spans",
            hotel_payload(n_traces=6, prefix="rm"))
        assert code == 200 and out["ingested_traces"] == 6, out
        assert router.counters["reset_midbody"] >= 1
        assert router.counters["retried"] >= 1
        assert router.pins[tenant] == "live"
    finally:
        router.stop()
        live.stop()
        srv.close()


def test_router_forwards_client_seq_and_retry_dedups(tmp_path):
    """The lost-ack retry path end to end THROUGH the router: X-TW-Seq
    rides the proxy to the owning replica, the first POST is ledgered
    and acked, and a client retry of the same seq (its ack 'lost') is
    answered with the ORIGINAL accounting — no second WAL append, no
    double ingest."""
    from traceweaver_tpu.fleet_serve.manager import InProcReplica
    from traceweaver_tpu.fleet_serve.router import FleetRouter

    rep = InProcReplica("solo", _cfg(state_dir=str(tmp_path / "solo")))
    router = FleetRouter({"solo": rep.base_url}, port=0).start()
    try:
        url = f"{router.base_url}/api/v1/tenants/rt/spans"
        pay = hotel_payload(n_traces=6, prefix="sq")
        code, _, out = _http("POST", url, pay,
                             headers={"X-TW-Seq": "11"})
        assert code == 200 and out["seq"] == 11
        assert out["ingested_traces"] == 6
        code, _, out = _http("POST", url, pay,
                             headers={"X-TW-Seq": "11"})
        assert code == 200 and out.get("deduped") is True
        assert out["ingested_traces"] == 6  # original accounting echoed
        t = rep.service.tenant("rt")
        assert t.wal.stats()["appended"] == 1
        assert t.counters["wal_deduped"] == 1
    finally:
        router.stop()
        rep.stop()


# ---------------------------------------------------------------------------
# live migration: conservation, byte identity, tombstones
# ---------------------------------------------------------------------------

def test_live_migration_mid_stream_byte_identical(tmp_path):
    """The tentpole conservation proof: kill a tenant mid-stream on
    replica A (half its traces posted, window still OPEN), resume on
    replica B, post the second half there; B's final sink must be
    byte-identical to the unmigrated single-replica run — zero lost,
    zero duplicated windows."""
    from traceweaver_tpu.fleet_serve.manager import (
        FleetManager,
        InProcReplica,
    )

    # both halves land in the SAME event-time window: the open window
    # itself rides the migration checkpoint
    pay1 = hotel_payload(n_traces=12, prefix="m")
    pay2 = hotel_payload(n_traces=12, prefix="n", base_us=9_000_000.0)
    both = {"data": pay1["data"] + pay2["data"]}
    base_bytes, _ = _run_single_tenant(tmp_path, "mig", both)

    reps = [InProcReplica(f"r{i}", _cfg(state_dir=str(tmp_path / f"fr{i}")))
            for i in range(2)]
    fleet = FleetManager(reps, router_port=0)
    try:
        url = fleet.base_url
        code, _, out = _http("POST", url + "/api/v1/tenants/mig/spans",
                             pay1)
        assert code == 200 and out["ingested_traces"] == 12
        src = fleet.router.owner("mig")
        dst = "r1" if src == "r0" else "r0"
        res = fleet.migrate("mig", dst)
        assert res["src"] == src and res["dst"] == dst
        assert fleet.router.counters["migrations"] == 1
        # second half goes through the router to the NEW home (pin)
        code, _, out = _http("POST", url + "/api/v1/tenants/mig/spans",
                             pay2)
        assert code == 200 and out["ingested_traces"] == 12
        # the old home answers 410 (tombstone), never a forked twin
        old = fleet.router.replicas[src].base_url
        code, _, out = _http("POST", old + "/api/v1/tenants/mig/spans",
                             pay2)
        assert code == 410 and "migrated out" in out["error"]
        code, _, _ = _http("POST", url + "/api/v1/flush")
        assert code == 200
        dst_rep = next(r for r in reps if r.name == dst)
        dst_rep.service.flush()
        # per-tenant conservation on the destination
        st = dst_rep.service.stats("mig")
        assert st["counters"]["ingested_traces"] == 24
        assert st["traces_emitted"] == 24
        assert st["shed_dropped_windows"] == 0
        assert st["deadletter_windows"] == 0
    finally:
        fleet.stop()
    with open(tmp_path / f"fr{int(dst[1:])}" / "mig" / "traces.jsonl",
              "rb") as f:
        fleet_bytes = f.read()
    assert fleet_bytes == base_bytes


def test_migration_tombstone_survives_resume(tmp_path):
    """A migrated-out tenant must keep answering "migrated out" on the
    source even after the source restarts with --resume: the durable
    tombstone marker re-tombstones it instead of resurrecting a forked
    twin from leftover files."""
    cfg_a = _cfg(state_dir=str(tmp_path / "a"))
    cfg_b = _cfg(state_dir=str(tmp_path / "b"))
    a, b = TenantService(cfg_a), TenantService(cfg_b)
    a.ingest("ten", hotel_payload(n_traces=8, prefix="x"))
    transfer = a.migrate_out("ten")
    b.migrate_in("ten", transfer)
    with pytest.raises(TenancyError, match="migrated out"):
        a.tenant("ten")
    a.drain()
    # restart replica A from its state dir: the tombstone must survive
    a2 = TenantService.resume(cfg_a)
    assert "ten" in a2.migrated_out
    assert "ten" not in a2.tenants  # NOT resurrected
    with pytest.raises(TenancyError, match="migrated out"):
        a2.tenant("ten")
    b.flush()
    assert b.stats("ten")["traces_emitted"] == 8
    a2.drain()
    b.drain()


def test_checkpoint_transfer_surface_refuses_torn_bytes(tmp_path):
    from traceweaver_tpu.stream.checkpoint import (
        CheckpointCorrupt,
        read_checkpoint_bytes,
        save_checkpoint,
        verify_checkpoint_bytes,
        write_checkpoint_bytes,
    )

    path = str(tmp_path / "ckpt.pkl")
    save_checkpoint(path, {"hello": "world"})
    raw = read_checkpoint_bytes(path)
    # trailer strips cleanly on intact bytes
    assert verify_checkpoint_bytes(raw) + raw[-16:] == raw
    # torn transfer: flip a payload byte -> refused at the destination
    torn = bytes([raw[0] ^ 0xFF]) + raw[1:]
    with pytest.raises(CheckpointCorrupt, match="CRC"):
        write_checkpoint_bytes(str(tmp_path / "out.pkl"), torn)
    # truncated transfer: trailer length check names the failure
    with pytest.raises(CheckpointCorrupt, match="truncated"):
        verify_checkpoint_bytes(raw[:1] + raw[-16:])


# ---------------------------------------------------------------------------
# knobs + wire campaign artifact
# ---------------------------------------------------------------------------

def test_fleet_knobs_registered_typed_ranged():
    from traceweaver_tpu.runtime import knobs

    reg = dict(knobs.REGISTRY)
    expected = {
        "TW_FLEET_REPLICAS": "int",
        "TW_FLEET_ROUTER_PORT": "int",
        "TW_FLEET_MIGRATE_TIMEOUT_S": "float",
        "TW_FLEET_RETRY_MAX": "int",
        "TW_FLEET_VNODES": "int",
        "TW_FLEET_BREAKER_FAILS": "int",
        "TW_FLEET_BREAKER_COOLDOWN_S": "float",
        "TW_FLEET_HEALTH_S": "float",
        "TW_FLEET_PROXY_TIMEOUT_S": "float",
    }
    for name, typ in expected.items():
        assert name in reg, f"{name} missing from the knob registry"
        k = reg[name]
        assert k.type == typ, (name, k.type)
        assert k.help, f"{name} has no help text"
        assert k.lo is not None and k.hi is not None, name
    # defaults parse through the typed accessors
    assert knobs.get_int("TW_FLEET_REPLICAS") >= 1
    assert knobs.get_float("TW_FLEET_HEALTH_S") > 0


def test_inproc_wire_campaign_artifact_and_self_compare(tmp_path):
    """The wire campaign's artifact rides the PR-15 ledger machinery:
    ledger-valid shape, zero-loss gate on every rung, format_report
    renders it, and `campaign compare` is clean against itself."""
    from traceweaver_tpu.campaign.compare import (
        compare_artifacts,
        format_report,
    )
    from traceweaver_tpu.campaign.ledger import load_artifact
    from traceweaver_tpu.fleet_serve.campaign import run_fleet_campaign

    out = str(tmp_path / "CAMPAIGN_fleet_test.json")
    art = run_fleet_campaign(
        str(tmp_path / "state"), replica_counts=(1, 2), tenants=2,
        seconds=1.0, traces_per_post=4, base_period_s=0.1,
        mode="inproc", out=out)
    loaded = load_artifact(out)  # validates kind="campaign"
    assert loaded["backend"] == "wire"
    assert [r["rung"] for r in loaded["rungs"]] == ["fleet-1", "fleet-2"]
    for r in loaded["rungs"]:
        assert r["fleet"]["zero_loss"] is True
        assert r["accuracy"]["e2e_pct"] == 100.0
        assert r["steady"]["spans_per_s"] > 0
        assert r["manifest"]["spans"] == r["manifest"]["traces"] * 5
        # r18: the wire stage ledgers ride the fleet block (parse ran —
        # spans were ingested — so its sum must be positive)
        assert r["fleet"]["parse_s"] > 0.0
        assert r["fleet"]["stitch_s"] >= 0.0
        assert r["fleet"]["emit_s"] >= 0.0
    # the N=2 rung exercised at least the chaos-phase live migration
    # (plus any placement-rebalance moves the hash split required)
    assert loaded["rungs"][1]["fleet"]["migrations"] >= 1
    report = format_report(loaded)
    assert "fleet-1" in report and "fleet-2" in report
    res = compare_artifacts(art, loaded, tol_pct=10.0, tol_acc=1.0)
    assert res["ok"], res["regressions"]


# ---------------------------------------------------------------------------
# migration under the dispatch ring (ISSUE 19)
# ---------------------------------------------------------------------------

def test_migration_under_overlap_byte_identical(tmp_path):
    """Generalizes the handoff suite to the in-flight ring: migrate_out
    of a tenant whose windows are riding TWO different outstanding
    tickets must block until BOTH retire (the PR 16 wait-for-retire fix
    over a set of tickets, not one dispatch), and the migrated output
    stays byte-identical to the unmigrated single-service run."""
    import threading

    pays = [hotel_payload(n_traces=6, prefix=f"w{k}-",
                          base_us=10e6 + k * 61e6) for k in range(4)]
    both = {"data": [t for p in pays for t in p["data"]]}
    base_bytes, _ = _run_single_tenant(tmp_path, "mig", both)

    src = TenantService(_cfg(state_dir=str(tmp_path / "src")))
    dst = TenantService(_cfg(state_dir=str(tmp_path / "dst")))
    for p in pays:
        src.ingest("mig", p)
    with src._lock:
        t = src.tenants["mig"]
        ready = list(t.svc.scheduler.ready())
    assert len(ready) >= 2, f"need >=2 sealed windows, got {len(ready)}"
    tk1 = src.submit_admitted([(t, ready[:1])])
    tk2 = src.submit_admitted([(t, ready[1:])])
    assert tk1 is not None and tk2 is not None

    moved = []
    th = threading.Thread(
        target=lambda: moved.append(src.migrate_out("mig")), daemon=True)
    th.start()
    time.sleep(0.3)
    assert th.is_alive(), "migrate_out ran with tickets outstanding"
    src._ring_dispatch(tk1)
    src.complete_ticket(tk1)
    time.sleep(0.3)
    assert th.is_alive(), \
        "migrate_out proceeded with ticket 2 still outstanding"
    src._ring_dispatch(tk2)
    src.complete_ticket(tk2)
    th.join(timeout=30)
    assert not th.is_alive() and moved, "migration never unblocked"

    dst.migrate_in("mig", moved[0])
    with pytest.raises(TenancyError, match="migrated out"):
        src.tenant("mig")
    dst.flush()
    st = dst.stats("mig")
    assert st["traces_emitted"] == 24
    assert st["shed_dropped_windows"] == 0
    src.drain()
    dst.drain()
    with open(tmp_path / "dst" / "mig" / "traces.jsonl", "rb") as f:
        assert f.read() == base_bytes
