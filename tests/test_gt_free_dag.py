"""Ground-truth-free invocation-DAG discovery (VERDICT r4 #6).

The reference carries an unwired sketch of this capability
(``FindConstraintsUsingFit``, executor.py:152-212); here it is a
production path: ``discover_invocation_dag`` infers each service's
precedence DAG by EM over structure — solve unconstrained, prune edges
contradicted by the predicted assignments, re-solve — with ground truth
used for grading ONLY. Acceptance bar from the verdict: flagship
accuracy within 1 pt of the GT-DAG path on exp1 datasets.
"""

import pytest

from traceweaver_tpu.ingest import (
    build_service_problem,
    discover_invocation_dag,
    infer_dag_from_predictions,
    infer_invocation_dag,
    load_corpus,
)
from traceweaver_tpu.metrics import get_ground_truth

HOTEL = "/root/reference/data/hotel_reservation/hotel_load25"
MEDIA = "/root/reference/data/media_microservices/media_load25"


def test_prediction_pruning_equals_gt_pruning_on_truth():
    """Feeding the TRUE assignments through the prediction-driven variant
    must reproduce the ground-truth inference exactly (same core rule)."""
    store = load_corpus(HOTEL, fix=2, max_traces=200, cache=False)
    for svc in store.out_spans_by_process:
        prob = build_service_problem(store, svc)
        if prob.skipped:
            continue
        ta = get_ground_truth(prob.in_span_partitions,
                              prob.out_span_partitions)
        g_true = infer_invocation_dag(
            prob.in_span_partitions, prob.out_span_partitions, ta, store)
        # tol=0 restores strict any-contradiction pruning — with noiseless
        # truth the two variants must agree exactly (production uses a
        # small tolerance so one wrong prediction can't delete an edge)
        g_pred = infer_dag_from_predictions(
            prob.in_span_partitions, prob.out_span_partitions, ta, store,
            tol=0.0)
        assert set(g_true.edges()) == set(g_pred.edges()), svc


def test_prediction_pruning_never_returns_cycles():
    """Prediction rows can MISS endpoints (NA/SKIP): endpoint pairs that
    never co-occur must keep neither direction (a surviving 2-cycle
    would crash the solver's topological sort), and the result is always
    a DAG."""
    import networkx as nx

    store = load_corpus(HOTEL, fix=2, max_traces=120, cache=False)
    svc = "frontend"
    prob = build_service_problem(store, svc)
    ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
    out_eps = list(prob.out_span_partitions)
    assert len(out_eps) >= 2
    # degrade predictions: endpoint B is NA wherever A has a real
    # assignment, so (A, B) is never tested in any row
    a_ep, b_ep = out_eps[0], out_eps[1]
    degraded = {ep: dict(m) for ep, m in ta.items()}
    for in_id in list(degraded[a_ep]):
        degraded[b_ep].pop(in_id, None)
    g = infer_dag_from_predictions(
        prob.in_span_partitions, prob.out_span_partitions, degraded, store)
    assert nx.is_directed_acyclic_graph(g)
    assert not (g.has_edge(a_ep, b_ep) and g.has_edge(b_ep, a_ep))


@pytest.mark.parametrize("path,fix", [(HOTEL, 2), (MEDIA, 1)])
def test_flagship_accuracy_within_1pt_of_gt_dag_path(path, fix):
    """End-to-end: run_experiment with gt_free_dag=True must land within
    1 accuracy point of the GT-DAG run on exp1 datasets."""
    from traceweaver_tpu.runtime.executor import ExecutorConfig, run_experiment

    store = load_corpus(path, fix=fix, max_traces=300, cache=False)

    def run(gt_free):
        cfg = ExecutorConfig(
            data_path="", results_directory="", fix=fix, cache_rate=0.0,
            test_name="gtfree", predictor_indices=[10],
            gt_free_dag=gt_free,
        )
        return run_experiment(cfg, store=store)

    gt = run(False).accuracy_overall["MaxScoreBatchSubsetWithSkips"]
    free = run(True).accuracy_overall["MaxScoreBatchSubsetWithSkips"]
    assert free >= gt - 1.0, (
        f"GT-free DAG path {free:.2f}% vs GT-DAG {gt:.2f}% "
        f"(> 1 pt loss) on {path}")


def test_adaptive_tol_widens_on_bimodal_rates_only():
    """The prune tolerance must widen to the largest-gap midpoint on a
    clearly bimodal contradiction-rate spectrum (the measured hotel
    frontend load150x10 rates below: true edges 0.02/0.135/0.28 vs
    parallel pairs 0.782/0.88/0.988) and stand pat otherwise."""
    from traceweaver_tpu.ingest.order import _adaptive_tol

    measured = [0.020, 0.135, 0.280, 0.782, 0.880, 0.988]
    t = _adaptive_tol(measured, 0.05)
    assert abs(t - (0.280 + 0.782) / 2) < 1e-12
    # edge-free fan-out service: low cluster is parallelism (>= 0.35),
    # the floor stands and every pair still gets pruned
    assert _adaptive_tol([0.5, 0.9], 0.05) == 0.05
    # skewed-but-parallel pair (b tends to start after a: contra 0.40)
    # must NOT anchor a fake bimodal spectrum — ambiguous band, floor
    assert _adaptive_tol([0.02, 0.40, 0.95], 0.05) == 0.05
    # no wide gap: floor stands
    assert _adaptive_tol([0.2, 0.4, 0.45], 0.05) == 0.05
    # degenerate spectra: floor stands
    assert _adaptive_tol([0.3], 0.05) == 0.05
    assert _adaptive_tol([], 0.05) == 0.05
    # never returns below the floor
    assert _adaptive_tol([0.0, 0.9], 0.5) == 0.5


def test_adaptive_pruning_integration_on_synthetic_noisy_rows():
    """End-to-end through infer_dag_from_predictions: a true edge whose
    contradiction rate (0.2) sits far above the fixed 5% tolerance must
    survive when the spectrum is bimodal, while skewed/parallel pairs
    (0.7/1.0) are pruned; explicit tol=0.0 stays strict."""
    from traceweaver_tpu.spans import Span, TraceStore

    store = TraceStore()
    in_spans, assign = [], {"A": {}, "B": {}, "C": {}}
    parts = {"A": [], "B": [], "C": []}
    for i in range(100):
        t = float(i * 1000)
        s_in = Span(f"t{i}", "in", t, 500.0, None, [], "p", "server")
        in_spans.append(s_in)
        # A: [t+10, t+40]
        spans = {"A": Span(f"t{i}", "a", t + 10, 30.0, None, [], "p",
                           "client")}
        # B truly follows A, but 20% of rows carry noisy overlap
        b_start = t + 20 if i % 5 == 0 else t + 50
        spans["B"] = Span(f"t{i}", "b", b_start, 30.0, None, [], "p",
                          "client")
        # C: skewed-parallel — overlaps A and B in 70% of rows
        c_start = t + 15 if i % 10 < 7 else t + 200
        c_dur = 100.0 if i % 10 < 7 else 30.0
        spans["C"] = Span(f"t{i}", "c", c_start, c_dur, None, [], "p",
                          "client")
        for ep, sp in spans.items():
            store.all_spans[sp.GetId()] = sp
            parts[ep].append(sp)
            assign[ep][s_in.GetId()] = sp.GetId()
    in_parts = {"IN": in_spans}

    # D co-occurs in only 3 rows (NA elsewhere) with 1 contradiction vs A
    # (rate 1/3): statistically worthless — it must neither anchor the
    # bimodality spectrum nor ride the widened tolerance
    assign["D"] = {}
    parts["D"] = []
    for i in (0, 11, 22):
        t = float(i * 1000)
        d_start = t + 30 if i == 0 else t + 300  # i=0 overlaps A
        sp = Span(f"t{i}", "d", d_start, 20.0, None, [], "p", "client")
        store.all_spans[sp.GetId()] = sp
        parts["D"].append(sp)
        assign["D"][in_spans[i].GetId()] = sp.GetId()

    g = infer_dag_from_predictions(in_parts, parts, assign, store)
    assert set(g.edges()) == {("A", "B")}
    g_strict = infer_dag_from_predictions(in_parts, parts, assign, store,
                                          tol=0.0)
    assert set(g_strict.edges()) == set()


def test_directional_evidence_gates_widened_tolerance():
    """Per-pair directional evidence (ADVICE r5): a pair whose
    contradiction rate exceeds the fixed tolerance survives the widened
    bimodal-spectrum guard only with forward support well above an even
    split (>= 0.7) OR a near-totally-contradicted reverse direction
    (>= 0.98). Synthetic bimodal spectrum:

    - (A, B): true edge, 20% noisy overlap -> rate 0.20, support 0.80
      (kept via the support bar);
    - (A, E) and (B, E): noisy true edges at 0.34 whose reverse
      directions are contradicted in EVERY row (kept via the reverse
      bar);
    - (A, C) and (B, C): skewed-but-parallel at 0.34 — support 0.66 and
      a reverse direction C occasionally wins (reverse rate 0.90). Under
      the widened tolerance alone (midpoint 0.62 here) these became
      false precedence edges; the directional guard prunes them.
    """
    from traceweaver_tpu.spans import Span, TraceStore

    store = TraceStore()
    in_spans = []
    assign = {ep: {} for ep in ("A", "B", "C", "E")}
    parts = {ep: [] for ep in ("A", "B", "C", "E")}
    for i in range(100):
        t = float(i * 1000)
        s_in = Span(f"t{i}", "in", t, 500.0, None, [], "p", "server")
        in_spans.append(s_in)
        spans = {"A": Span(f"t{i}", "a", t + 10, 30.0, None, [], "p",
                           "client")}
        # B truly follows A; 20% of rows overlap (noise)
        b_start = t + 20 if i % 5 == 0 else t + 50
        spans["B"] = Span(f"t{i}", "b", b_start, 30.0, None, [], "p",
                          "client")
        # C: skewed-parallel. 24 rows long-overlap, 10 rows C completes
        # BEFORE A/B even start (the reverse direction is not near-1),
        # 66 rows strictly after -> (A,C)=(B,C)=0.34, (C,A)=(C,B)=0.90
        if i < 24:
            c_start, c_dur = t + 18, 100.0
        elif i < 34:
            c_start, c_dur = t + 1, 5.0
        else:
            c_start, c_dur = t + 130, 30.0
        spans["C"] = Span(f"t{i}", "c", c_start, c_dur, None, [], "p",
                          "client")
        # E: noisy true successor of A and B. 34 rows overlap, 66 rows
        # strictly after; E NEVER completes before A or B start, so the
        # reverse direction is contradicted in every row
        if i < 34:
            e_start, e_dur = t + 20, 100.0
        else:
            e_start, e_dur = t + 130, 30.0
        spans["E"] = Span(f"t{i}", "e", e_start, e_dur, None, [], "p",
                          "client")
        for ep, sp in spans.items():
            store.all_spans[sp.GetId()] = sp
            parts[ep].append(sp)
            assign[ep][s_in.GetId()] = sp.GetId()
    in_parts = {"IN": in_spans}

    g = infer_dag_from_predictions(in_parts, parts, assign, store)
    assert set(g.edges()) == {("A", "B"), ("A", "E"), ("B", "E")}
