"""Two-process corpus-level data parallelism (the DCN design's smoke test).

Launches two REAL OS processes (the reference's own process model,
exps/exp1/run_experiment.sh:74-79), each solving a disjoint shard of
service problems with the full flagship stack and contributing per-edge
delay statistics to a filesystem allreduce. Asserts:

- both shards solve and their merged accuracy matches a single-process
  run over the same problems;
- the allreduced corpus-wide edge statistics are identical on both
  processes and equal to the single-process statistics.
"""

import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from tests.conftest import REFERENCE_DATA

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")

from traceweaver_tpu.ingest import (
    build_service_problem, infer_invocation_dag, load_corpus)
from traceweaver_tpu.metrics import get_ground_truth, accuracy_for_service
from traceweaver_tpu.algorithms.weaver_tpu import WeaverTPU
from traceweaver_tpu.parallel.multislice import (
    allreduce_stats_files, edge_stats_from_samples, partition_problems)

pid = int(sys.argv[1])
n_proc = int(sys.argv[2])
rdv = sys.argv[3]
out_path = sys.argv[4]

store = load_corpus({data!r}, fix=2, max_traces=60, cache=False)
problems = []
for svc in sorted(store.out_spans_by_process):
    prob = build_service_problem(store, svc)
    if prob.skipped:
        continue
    problems.append((svc, prob))

mine = partition_problems(len(problems), n_proc, pid)
accs = {{}}
samples = {{}}
for i in mine:
    svc, prob = problems[i]
    ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
    dag = infer_invocation_dag(
        prob.in_span_partitions, prob.out_span_partitions, ta, store)
    algo = WeaverTPU(store.all_spans, store.all_processes)
    out = algo.FindAssignments(
        "MaxScoreBatchSubsetWithSkips", svc, prob.in_span_partitions,
        prob.out_span_partitions, False, [], ta, dag)
    accs[svc] = accuracy_for_service(out[0], ta, prob.in_span_partitions)
    # per-edge delay samples from this shard's ground truth stream
    in_ep = next(iter(prob.in_span_partitions))
    for ep, spans in prob.out_span_partitions.items():
        samples[(svc, ep)] = [float(s.start_mus) for s in spans[:50]]

stats = edge_stats_from_samples(samples)
merged = allreduce_stats_files(stats, rdv, pid, n_proc)
with open(out_path, "w") as f:
    json.dump({{
        "accs": accs,
        "merged": {{json.dumps(list(k)): v for k, v in merged.items()}},
    }}, f)
"""


@pytest.mark.slow
def test_two_process_corpus_parallelism():
    data = os.path.join(REFERENCE_DATA, "hotel_reservation/hotel_load25")
    if not os.path.isdir(data):
        pytest.skip("reference dataset not available")
    code = WORKER.format(repo=REPO, data=data)
    with tempfile.TemporaryDirectory() as td:
        rdv = os.path.join(td, "rdv")
        outs = [os.path.join(td, f"out_{p}.json") for p in range(2)]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", code, str(p), "2", rdv, outs[p]],
                env=env, cwd=REPO)
            for p in range(2)
        ]
        for p in procs:
            assert p.wait(timeout=420) == 0
        results = []
        for path in outs:
            with open(path) as f:
                results.append(json.load(f))

    # disjoint shards that together cover both solvable hotel services
    svcs0 = set(results[0]["accs"])
    svcs1 = set(results[1]["accs"])
    assert svcs0 and svcs1 and not (svcs0 & svcs1)
    all_accs = {**results[0]["accs"], **results[1]["accs"]}
    assert set(all_accs) == {"frontend", "search"}

    # single-process reference run over the same problems
    import jax

    jax.config.update("jax_platforms", "cpu")
    from traceweaver_tpu.algorithms.weaver_tpu import WeaverTPU
    from traceweaver_tpu.ingest import (
        build_service_problem, infer_invocation_dag, load_corpus)
    from traceweaver_tpu.metrics import accuracy_for_service, get_ground_truth

    store = load_corpus(data, fix=2, max_traces=60, cache=False)
    for svc, acc in all_accs.items():
        prob = build_service_problem(store, svc)
        ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
        dag = infer_invocation_dag(
            prob.in_span_partitions, prob.out_span_partitions, ta, store)
        algo = WeaverTPU(store.all_spans, store.all_processes)
        out = algo.FindAssignments(
            "MaxScoreBatchSubsetWithSkips", svc, prob.in_span_partitions,
            prob.out_span_partitions, False, [], ta, dag)
        ref = accuracy_for_service(out[0], ta, prob.in_span_partitions)
        assert abs(ref - acc) < 1e-9, svc

    # the allreduce produced identical corpus-wide statistics everywhere
    assert results[0]["merged"] == results[1]["merged"]


def test_partition_and_merge_units():
    from traceweaver_tpu.parallel.multislice import (
        merge_edge_stats, partition_problems)

    parts = [partition_problems(10, 3, p) for p in range(3)]
    assert sorted(i for part in parts for i in part) == list(range(10))
    assert all(len(p) in (3, 4) for p in parts)

    a = {("x", "y"): (2.0, 10.0, 60.0)}
    b = {("x", "y"): (1.0, 5.0, 25.0), ("p", "q"): (1.0, 1.0, 1.0)}
    m = merge_edge_stats(a, [b])
    assert m[("x", "y")] == (3.0, 15.0, 85.0)
    assert m[("p", "q")] == (1.0, 1.0, 1.0)
    n, s1, s2 = m[("x", "y")]
    assert abs(s1 / n - 5.0) < 1e-12  # corpus-wide mean recovered exactly


# plain argv plumbing (no str.format: the worker body is brace-heavy)
DIST_WORKER = r"""
import json, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
pid = int(sys.argv[1]); n = int(sys.argv[2]); port = sys.argv[3]
repo = sys.argv[4]
jax.distributed.initialize("127.0.0.1:" + port, num_processes=n,
                           process_id=pid)
sys.path.insert(0, repo)
from traceweaver_tpu.parallel.multislice import (
    allreduce_stats_jax, edge_stats_from_samples, stats_to_rows)

# deterministic per-process edge samples (disjoint edge sets overlap on
# one shared edge, the interesting reduction case)
# ms-scale microsecond delays: sum-of-squares ~3e9 exceeds f32's
# exactly-representable range, so only an f64 reduction reproduces the
# host merge EXACTLY (the test asserts bit-equality below)
samples = {("svc", "ep%d" % pid): [40000.0 + pid, 41000.0 + 2 * pid],
           ("svc", "shared"): [39500.0 + pid]}
stats = edge_stats_from_samples(samples)
edge_order = [("svc", "ep0"), ("svc", "ep1"), ("svc", "shared")]
rows = stats_to_rows(stats, edge_order)
merged = allreduce_stats_jax(rows)
print(json.dumps({"pid": pid, "merged": merged.tolist()}), flush=True)
"""


@pytest.mark.slow
def test_two_process_psum_transport_matches_filesystem():
    """The claimed JAX-distributed-runtime transport, actually exercised:
    two real processes form a jax.distributed CPU cluster (gloo
    collectives), allreduce their [Ne, 3] sufficient statistics with one
    XLA psum, and must produce the identical merged rows the filesystem
    transport / host merge produces."""
    import socket

    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", DIST_WORKER, str(p), "2", str(port),
             REPO],
            env=env, cwd=REPO, stdout=subprocess.PIPE, text=True)
        for p in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        assert p.returncode == 0
        outs.append(json.loads(out.strip().splitlines()[-1]))

    # both processes converged to the same reduction...
    assert outs[0]["merged"] == outs[1]["merged"]
    # ...equal to the host-side merge of the same per-process stats
    from traceweaver_tpu.parallel.multislice import (
        edge_stats_from_samples, merge_edge_stats, stats_to_rows)

    shards = []
    for pid in range(2):
        samples = {("svc", f"ep{pid}"): [40000.0 + pid, 41000.0 + 2 * pid],
                   ("svc", "shared"): [39500.0 + pid]}
        shards.append(edge_stats_from_samples(samples))
    want = stats_to_rows(
        merge_edge_stats(shards[0], shards[1:]),
        [("svc", "ep0"), ("svc", "ep1"), ("svc", "shared")])
    got = np.asarray(outs[0]["merged"])
    # exact: every input is integer-valued, so f64 sums are exact and any
    # f32 downcast in the transport shows up as a bit difference
    assert np.array_equal(got, want), (got, want)
