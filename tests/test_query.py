"""Query-engine tests over executor output and emitted-trace records."""

import json
import os
import pickle
import subprocess
import sys

import pytest

from traceweaver_tpu.query import (
    delay_culprit,
    extract_hop_latencies,
    filter_traces,
    live_delay_culprit,
    load_trace_records,
)
from traceweaver_tpu.spans import Span


def _span(tid, sid, start, dur):
    return Span(tid, sid, start, dur, "op", [], "p", "client")


def _e2e_pickle(path):
    true_traces, pred_traces = {}, {}
    for i in range(20):
        tid = f"t{i:02d}"
        dur = 100 + i * 50  # monotone latency; hop 1 dominates
        spans = [_span(tid, "a", 0, 10), _span(tid, "b", 20, dur)]
        true_traces[tid] = spans
        pred_traces[tid] = spans if i % 4 else [None, spans[1]]
    with open(path, "wb") as f:
        pickle.dump({"FCFS": [true_traces, pred_traces]}, f)


def test_filter_traces_percentile():
    traces = {
        f"t{i}": [_span(f"t{i}", "a", i * 10, 100 + i)] for i in range(10)
    }
    top = filter_traces(traces, percentile=0.8)
    assert len(top) == 2  # top 20%
    assert all(t[1][0].duration_mus >= 108 for t in top)


def test_extract_hops():
    traces = [("t", [_span("t", "a", 0, 5), _span("t", "b", 10, 7)])]
    hops = extract_hop_latencies(traces)
    assert hops[0][0][3] == 5 and hops[1][0][3] == 7


def test_delay_culprit_end_to_end(tmp_path):
    path = tmp_path / "e2e_test.pickle"
    _e2e_pickle(path)
    out = tmp_path / "query.pickle"
    results = delay_culprit(str(path), percentile=0.5, out_path=str(out))
    r = results["FCFS"]
    assert r["worst_true"][0] == 1  # hop 1 has the big duration
    assert r["worst_pred"][0] == 1
    assert r["n_pred"] <= r["n_true"]
    assert r["empty"] is False
    assert out.exists()
    with open(out, "rb") as f:
        ql = pickle.load(f)
    assert "FCFS" in ql and len(ql["FCFS"]) == 2


def test_delay_culprit_tolerates_empty_trace_sets(tmp_path):
    """Empty/incomplete trace sets return a COUNTED zero-result (the
    ISSUE's graceful-degradation requirement), never crash: empty dicts,
    methods whose every trace is incomplete, and an empty bracket."""
    path = tmp_path / "e2e_empty.pickle"
    with open(path, "wb") as f:
        pickle.dump({"Empty": [{}, {}],
                     "AllNone": [{"t": [None, None]}, {"t": [None]}]}, f)
    results = delay_culprit(str(path), percentile=0.95)
    for method in ("Empty", "AllNone"):
        r = results[method]
        assert r["empty"] is True
        assert r["n_true"] == 0 and r["n_pred"] == 0
        assert r["worst_true"] == (None, -1.0)
    # the CLI main prints the zero-result instead of crashing on None
    from traceweaver_tpu.query.delay_culprit import main

    assert main([str(path)]) == 0


def _record(tid, start, spans):
    """spans: [(service, kind, start, dur, self_us)]"""
    recs = [dict(sid=[tid, f"s{i}"], service=svc, kind=kind,
                 start_us=s, dur_us=d, self_us=self_us)
            for i, (svc, kind, s, d, self_us) in enumerate(spans)]
    end = max(r["start_us"] + r["dur_us"] for r in recs)
    return dict(trace_id=tid, window=0, root_start_us=start,
                e2e_us=end - start, n_spans=len(recs), complete=True,
                spans=recs)


def test_live_delay_culprit_attributes_self_time():
    """The live query charges latency to the service that SPENT it
    (self time), not the frontend that contained it, and filters by
    percentile + after_us like the reference query."""
    records = []
    for i in range(20):
        start = i * 1000.0
        slow = i >= 18  # the top-10% traces are slow in "db"
        db = 5000.0 if slow else 100.0
        records.append(_record(f"t{i}", start, [
            ("front", "server", start, db + 300.0, 200.0),
            ("front", "client", start + 50, db + 150.0, 150.0),
            ("db", "server", start + 100, db, db),
        ]))
    out = live_delay_culprit(records, percentile=0.9)
    assert not out["empty"]
    assert out["worst_service"] == "db"
    assert out["n_bracket"] == 2
    # after_us excludes the early slow trace
    out2 = live_delay_culprit(records, percentile=0.9, after_us=18_500.0)
    assert out2["n_bracket"] == 1
    # empty inputs: counted zero-result
    empty = live_delay_culprit([])
    assert empty["empty"] and empty["worst_service"] is None
    # incomplete records are excluded like the reference's None-hop rule
    partial = [dict(r, complete=False) for r in records]
    assert live_delay_culprit(partial)["empty"]


def test_query_cli_subcommand_offline_paths(tmp_path):
    """`python -m traceweaver_tpu.runtime.cli query <file>`: the offline
    path works on both an e2e pickle and an emitted-trace JSONL file,
    without a running server."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cwd = os.path.join(os.path.dirname(__file__), "..")

    pkl = tmp_path / "e2e_q.pickle"
    _e2e_pickle(pkl)
    res = subprocess.run(
        [sys.executable, "-m", "traceweaver_tpu.runtime.cli", "query",
         str(pkl), "--percentile", "0.5"],
        capture_output=True, text=True, timeout=300, cwd=cwd, env=env)
    assert res.returncode == 0, res.stderr
    assert "worst hop (true) #1" in res.stdout
    assert "AGREE" in res.stdout

    jsonl = tmp_path / "emitted.jsonl"
    with open(jsonl, "w") as f:
        for i in range(10):
            start = i * 1000.0
            dur = 4000.0 if i == 9 else 100.0
            f.write(json.dumps(_record(f"t{i}", start, [
                ("front", "server", start, dur + 100.0, 100.0),
                ("slowsvc", "server", start + 10, dur, dur),
            ])) + "\n")
    res = subprocess.run(
        [sys.executable, "-m", "traceweaver_tpu.runtime.cli", "query",
         str(jsonl), "--percentile", "0.9"],
        capture_output=True, text=True, timeout=300, cwd=cwd, env=env)
    assert res.returncode == 0, res.stderr
    assert "worst service: slowsvc" in res.stdout
    assert load_trace_records(str(jsonl))[0]["trace_id"] == "t0"

    # empty JSONL: the counted zero-result, exit 0
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    res = subprocess.run(
        [sys.executable, "-m", "traceweaver_tpu.runtime.cli", "query",
         str(empty)],
        capture_output=True, text=True, timeout=300, cwd=cwd, env=env)
    assert res.returncode == 0, res.stderr
    assert "empty bracket" in res.stdout and "no culprit" in res.stdout
