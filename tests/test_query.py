"""Query-engine tests over executor output."""

import pickle

import pytest

from traceweaver_tpu.query import delay_culprit, extract_hop_latencies, filter_traces
from traceweaver_tpu.spans import Span


def _span(tid, sid, start, dur):
    return Span(tid, sid, start, dur, "op", [], "p", "client")


def _e2e_pickle(path):
    true_traces, pred_traces = {}, {}
    for i in range(20):
        tid = f"t{i:02d}"
        dur = 100 + i * 50  # monotone latency; hop 1 dominates
        spans = [_span(tid, "a", 0, 10), _span(tid, "b", 20, dur)]
        true_traces[tid] = spans
        pred_traces[tid] = spans if i % 4 else [None, spans[1]]
    with open(path, "wb") as f:
        pickle.dump({"FCFS": [true_traces, pred_traces]}, f)


def test_filter_traces_percentile():
    traces = {
        f"t{i}": [_span(f"t{i}", "a", i * 10, 100 + i)] for i in range(10)
    }
    top = filter_traces(traces, percentile=0.8)
    assert len(top) == 2  # top 20%
    assert all(t[1][0].duration_mus >= 108 for t in top)


def test_extract_hops():
    traces = [("t", [_span("t", "a", 0, 5), _span("t", "b", 10, 7)])]
    hops = extract_hop_latencies(traces)
    assert hops[0][0][3] == 5 and hops[1][0][3] == 7


def test_delay_culprit_end_to_end(tmp_path):
    path = tmp_path / "e2e_test.pickle"
    _e2e_pickle(path)
    out = tmp_path / "query.pickle"
    results = delay_culprit(str(path), percentile=0.5, out_path=str(out))
    r = results["FCFS"]
    assert r["worst_true"][0] == 1  # hop 1 has the big duration
    assert r["worst_pred"][0] == 1
    assert r["n_pred"] <= r["n_true"]
    assert out.exists()
    with open(out, "rb") as f:
        ql = pickle.load(f)
    assert "FCFS" in ql and len(ql["FCFS"]) == 2
