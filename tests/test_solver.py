"""End-to-end solver tests: TPU Sinkhorn solver and CPU exact oracle."""

import random

import numpy as np

import pytest

from traceweaver_tpu.algorithms.weaver_exact import WeaverExact
from traceweaver_tpu.algorithms.weaver_tpu import WeaverTPU, perfect_cut_windows
from traceweaver_tpu.ingest import build_service_problem, infer_invocation_dag
from traceweaver_tpu.metrics import (
    accuracy_end_to_end,
    accuracy_for_service,
    get_ground_truth,
    topk_accuracy_for_service,
)
from traceweaver_tpu.spans import SKIP, Span
from traceweaver_tpu.synth import create_cache_hits


def _run(store, algo_factory, method, cache_rate=0.0, need_dag=True):
    random.seed(10)
    pred_by, true_by, extras = {}, {}, {}
    for svc in store.out_spans_by_process:
        prob = build_service_problem(store, svc)
        if prob.skipped:
            continue
        ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
        dag = infer_invocation_dag(
            prob.in_span_partitions, prob.out_span_partitions, ta, store
        ) if need_dag else None
        if svc == "frontend" and cache_rate > 0:
            ta = create_cache_hits(ta, prob.in_span_partitions,
                                   prob.out_span_partitions, cache_rate)
        algo = algo_factory()
        args = [method, svc, prob.in_span_partitions, prob.out_span_partitions,
                False, [], ta]
        if need_dag:
            args.append(dag)
        out = algo.FindAssignments(*args)
        pred = out[0] if isinstance(out, tuple) else out
        accuracy_for_service(pred, ta, prob.in_span_partitions)
        pred_by[svc], true_by[svc] = pred, ta
        extras[svc] = (out, prob, ta)
    _, e2e = accuracy_end_to_end(pred_by, true_by, store.in_spans_by_process)
    return e2e, extras


def test_weaver_tpu_hotel(hotel_store):
    e2e, _ = _run(
        hotel_store,
        lambda: WeaverTPU(hotel_store.all_spans, hotel_store.all_processes),
        "MaxScoreBatchSubsetWithSkips",
    )
    assert e2e >= 0.97, f"WeaverTPU e2e {e2e:.3f}"


def test_weaver_tpu_cache_hits(hotel_store):
    e2e, extras = _run(
        hotel_store,
        lambda: WeaverTPU(hotel_store.all_spans, hotel_store.all_processes),
        "MaxScoreBatchSubsetWithSkips",
        cache_rate=0.3,
    )
    assert e2e >= 0.90, f"WeaverTPU cached e2e {e2e:.3f}"
    # predicted Skips exist on the cached endpoint
    (out, prob, ta) = extras["frontend"]
    pred = out[0]
    n_skip_pred = sum(
        1 for ep in pred for v in pred[ep].values() if tuple(v) == SKIP
    )
    assert n_skip_pred > 0


def test_weaver_tpu_topk_contains_choice(hotel_store):
    _, extras = _run(
        hotel_store,
        lambda: WeaverTPU(hotel_store.all_spans, hotel_store.all_processes),
        "MaxScoreBatchSubsetWithSkips",
    )
    out, prob, ta = extras["search"]
    pred, topk = out[0], out[1]
    acc_topk = topk_accuracy_for_service(topk, ta, prob.in_span_partitions)
    acc = accuracy_for_service(pred, ta, prob.in_span_partitions)
    assert acc_topk >= acc  # top-K at least as good as top-1
    for ep in pred:
        for key, val in pred[ep].items():
            assert topk[ep][key][0] == val  # candidate 0 is the commitment


def test_weaver_exact_hotel(hotel_store):
    e2e, _ = _run(
        hotel_store,
        lambda: WeaverExact(hotel_store.all_spans, hotel_store.all_processes),
        "MaxScoreBatch",
        need_dag=False,
    )
    assert e2e >= 0.90, f"WeaverExact e2e {e2e:.3f}"


def test_tpu_matches_exact_on_unambiguous_data(hotel_store):
    """On low-load data both solvers should agree with ground truth (and
    hence each other) almost everywhere."""
    e2e_tpu, _ = _run(
        hotel_store,
        lambda: WeaverTPU(hotel_store.all_spans, hotel_store.all_processes),
        "MaxScoreBatchSubsetWithSkips",
    )
    e2e_exact, _ = _run(
        hotel_store,
        lambda: WeaverExact(hotel_store.all_spans, hotel_store.all_processes),
        "MaxScoreBatch",
        need_dag=False,
    )
    assert e2e_tpu >= e2e_exact - 0.02


def test_perfect_cut_windows_partition_and_disjoint():
    spans = []
    # 3 separated bursts of 4 overlapping spans each
    for burst in range(3):
        t0 = burst * 10_000
        for i in range(4):
            spans.append(Span(f"t{burst}_{i}", "in", t0 + i * 10, 500,
                              "op", [], "p", "server"))
    spans.sort(key=lambda s: s.start_mus)
    wins = perfect_cut_windows(spans, max_size=32)
    assert [w for w in wins] == [(0, 4), (4, 8), (8, 12)]
    # cap splitting
    wins = perfect_cut_windows(spans, max_size=2)
    assert all(hi - lo <= 2 for lo, hi in wins)
    assert wins[0][0] == 0 and wins[-1][1] == 12


def test_split_window_assignments_stay_one_to_one(hotel_store):
    """Forcing tiny capped sub-windows splits perfect-cut segments; the
    cross-window resolution pass must keep each outgoing span assigned to
    at most one incoming span and not tank accuracy."""
    e2e, extras = _run(
        hotel_store,
        lambda: WeaverTPU(hotel_store.all_spans, hotel_store.all_processes,
                          max_window=8),
        "MaxScoreBatchSubsetWithSkips",
    )
    for svc, (out, prob, ta) in extras.items():
        pred = out[0]
        for ep, amap in pred.items():
            real = [tuple(v) for v in amap.values()
                    if tuple(v) not in (("NA", "NA"), ("Skip", "Skip"))]
            assert len(real) == len(set(real)), f"{svc}/{ep} duplicates"
    assert e2e >= 0.90, f"split-window e2e {e2e:.3f}"


def test_cross_window_duplicate_resolution_semantics():
    """Time-order winner keeps a contested span; only losers reassign; a
    loser's fallback cannot displace another row's commitment; SKIP
    fallbacks respect the global |in|-|out| budget."""
    ep = "svc:op"
    o1, o2, o3 = ("t", "o1"), ("t", "o2"), ("t", "o3")
    A, B, C = ("t", "a"), ("t", "b"), ("t", "c")
    # decode order puts C first (smaller size class dispatched earlier) but
    # time order is A, B, C
    assignments = {ep: {C: o1, A: o1, B: o2}}
    topk = {ep: {C: [o1, o2, o3], A: [o1], B: [o2]}}
    WeaverTPU._resolve_cross_window_duplicates(
        assignments, topk, [A, B, C], {ep: 0})
    assert assignments[ep][A] == o1      # earliest in time keeps it
    assert assignments[ep][B] == o2      # untouched — never in conflict
    assert assignments[ep][C] == o3      # falls to first FREE candidate

    # skip budget: loser may take SKIP only while budget remains
    assignments = {ep: {A: o1, B: o1}}
    topk = {ep: {A: [o1], B: [o1, SKIP]}}
    WeaverTPU._resolve_cross_window_duplicates(
        assignments, topk, [A, B], {ep: 0})
    assert assignments[ep][B] == ("NA", "NA")  # budget 0: no skip
    assignments = {ep: {A: o1, B: o1}}
    topk = {ep: {A: [o1], B: [o1, SKIP]}}
    WeaverTPU._resolve_cross_window_duplicates(
        assignments, topk, [A, B], {ep: 1})
    assert assignments[ep][B] == SKIP          # budget 1: skip allowed


def test_kde_edgedist_exact_for_few_samples():
    """n <= K: the mixture IS the Gaussian KDE (component per sample)."""
    import scipy.stats

    from traceweaver_tpu.algorithms.timing import EdgeDist

    samples = [100.0, 220.0, 370.0, 540.0]
    d = EdgeDist.from_samples_kde(samples)
    kde = scipy.stats.gaussian_kde(samples)  # scott bandwidth, like ours
    xs = np.linspace(0.0, 700.0, 29)
    np.testing.assert_allclose(
        np.exp(d.logpdf(xs)), kde.evaluate(xs), rtol=1e-6, atol=1e-12)


def test_kde_edgedist_binned_approximates_scipy():
    import scipy.stats

    from traceweaver_tpu.algorithms.timing import EdgeDist

    rng = np.random.default_rng(3)
    samples = np.concatenate([rng.normal(1000, 40, 300),
                              rng.normal(4000, 120, 200)])
    d = EdgeDist.from_samples_kde(samples)
    kde = scipy.stats.gaussian_kde(samples)
    xs = np.linspace(500, 4500, 41)
    ours = np.exp(d.logpdf(xs))
    ref = kde.evaluate(xs)
    # binned to 5 components: coarse but must track the bimodal shape
    assert np.corrcoef(ours, ref)[0, 1] > 0.97


def test_weaver_tpu_kde_score_mode(hotel_store):
    from traceweaver_tpu.algorithms.weaver_tpu import WeaverTPU
    from traceweaver_tpu.ingest import build_service_problem, infer_invocation_dag
    from traceweaver_tpu.metrics import accuracy_for_service, get_ground_truth

    store = hotel_store
    svc = "frontend"
    prob = build_service_problem(store, svc)
    ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
    dag = infer_invocation_dag(prob.in_span_partitions,
                               prob.out_span_partitions, ta, store)
    algo = WeaverTPU(store.all_spans, store.all_processes, score_mode="kde")
    out = algo.FindAssignments(
        "MaxScoreBatchSubsetWithSkips", svc, prob.in_span_partitions,
        prob.out_span_partitions, False, [], ta, dag)
    acc = accuracy_for_service(out[0], ta, prob.in_span_partitions)
    assert acc > 0.9


def test_weaver_tpu_true_dist_ablation(hotel_store):
    """WithTrueDist oracle ablation (reference executor.py:976-987) — the
    GT-fed distributions path must run and score at least as well as the
    default path."""
    store = hotel_store
    svc = "frontend"
    prob = build_service_problem(store, svc)
    ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
    dag = infer_invocation_dag(prob.in_span_partitions,
                               prob.out_span_partitions, ta, store)
    algo = WeaverTPU(store.all_spans, store.all_processes)
    out = algo.FindAssignments(
        "MaxScoreBatchSubsetWithTrueDist", svc, prob.in_span_partitions,
        prob.out_span_partitions, False, [], ta, dag, true_dist=True)
    acc = accuracy_for_service(out[0], ta, prob.in_span_partitions)
    assert acc > 0.95


def test_fused_em_matches_host_refit(hotel_store):
    """The single-dispatch fused EM (on-device BIC-GMM refit between the
    two passes, solve_em_packed) must reproduce the two-dispatch path with
    the host refit (timing.refit_from_assignments) assignment-for-
    assignment."""
    from traceweaver_tpu.algorithms.weaver_tpu import WeaverTPU
    from traceweaver_tpu.ingest import (
        build_service_problem, infer_invocation_dag)
    from traceweaver_tpu.metrics import get_ground_truth

    store = hotel_store
    for svc in ("frontend", "search"):
        prob = build_service_problem(store, svc)
        if prob.skipped:
            continue
        ta = get_ground_truth(prob.in_span_partitions,
                              prob.out_span_partitions)
        dag = infer_invocation_dag(
            prob.in_span_partitions, prob.out_span_partitions, ta, store)
        args = ("MaxScoreBatchSubsetWithSkips", svc, prob.in_span_partitions,
                prob.out_span_partitions, False, [], ta, dag)

        fused = WeaverTPU(store.all_spans, store.all_processes)
        out_f = fused.FindAssignments(*args)
        assert fused.stats.get("fused_em_applied"), "fused path not taken"
        assert "refit_s" not in fused.stats  # the host refit never ran

        host = WeaverTPU(store.all_spans, store.all_processes)
        orig = host._solve_once
        host._solve_once = (
            lambda *a, **kw: orig(*a, **{**kw, "fused": False}))
        out_h = host.FindAssignments(*args)
        assert "refit_s" in host.stats

        assert out_f[0] == out_h[0], svc  # assignments identical


def test_sinkhorn_tol_default_matches_exact_potentials(hotel_store):
    """WeaverTPU defaults to sinkhorn_tol=1e-3 (early-exit on converged
    potentials). The tolerance must not flip any greedy-rounded
    assignment vs the exact tol=0.0 solve on recorded data (advisor
    round-3 finding: the default changed numerics for all callers but
    was only validated on one synthetic problem)."""
    e2e_tol, extras_tol = _run(
        hotel_store,
        lambda: WeaverTPU(hotel_store.all_spans, hotel_store.all_processes),
        "MaxScoreBatchSubsetWithSkips",
    )
    e2e_exact, extras_exact = _run(
        hotel_store,
        lambda: WeaverTPU(hotel_store.all_spans, hotel_store.all_processes,
                          sinkhorn_tol=0.0),
        "MaxScoreBatchSubsetWithSkips",
    )
    assert e2e_tol == e2e_exact
    for svc in extras_tol:
        assert extras_tol[svc][0][0] == extras_exact[svc][0][0], (
            f"tolerance flipped an assignment on {svc}")


def test_bounded_neighbour_score_build_identical_to_full():
    """The production score build gathers only real DAG neighbours
    (static max in/out degree); it must reproduce the unbounded
    all-endpoints sum exactly — gathered entries are the mask-true
    entries, padding contributes 0.0 (docs/ROOFLINE.md measured 1.70x
    from this; identity is the contract)."""
    import jax.numpy as jnp

    from traceweaver_tpu.algorithms.weaver_tpu import solve_windows

    rng = np.random.default_rng(0)
    B, E, W, M, K = 2, 4, 8, 8, 3
    in_start = jnp.asarray(
        np.sort(rng.uniform(0, 100, (B, W)), axis=1).astype(np.float32))
    in_end = in_start + 50
    out_start = jnp.asarray(
        np.sort(rng.uniform(0, 120, (B, E, M)), axis=2).astype(np.float32))
    pred_mask = np.zeros((E, E), bool)
    pred_mask[1, 0] = pred_mask[2, 1] = pred_mask[3, 1] = True  # branching
    root_mask = np.array([True, False, False, False])
    is_last = np.array([False, False, False, True])
    wt = np.zeros((E, E, K), np.float32); wt[..., 0] = 1
    mu = np.full((E, E, K), 10.0, np.float32)
    sd = np.full((E, E, K), 5.0, np.float32)
    iwt = np.zeros((E, K), np.float32); iwt[:, 0] = 1
    imu = np.full((E, K), 10.0, np.float32)
    isd = np.full((E, K), 5.0, np.float32)
    args = (in_start, in_end, jnp.ones((B, W), bool),
            out_start, out_start + 5, jnp.ones((B, E, M), bool),
            jnp.zeros((B, E), jnp.float32), jnp.zeros((B, E, W), bool),
            jnp.asarray(pred_mask), jnp.asarray(root_mask),
            jnp.asarray(is_last),
            jnp.asarray(wt), jnp.asarray(mu), jnp.asarray(sd),
            jnp.asarray(iwt), jnp.asarray(imu), jnp.asarray(isd),
            jnp.asarray(iwt), jnp.asarray(imu), jnp.asarray(isd))
    full = solve_windows(*args)  # max_preds/max_succs = 0 -> all E
    bounded = solve_windows(*args, max_preds=2, max_succs=2)
    for a, b in zip(full, bounded):
        assert np.array_equal(np.asarray(a), np.asarray(b))
