"""Golden parity: device-resident span columns vs the host packer.

The resident path (``TW_DEVCOLS=1``, ops/devcols.py) keeps span columns
in device ring buffers and assembles window tensors by on-device
gathers; ``TW_DEVCOLS=0`` restores the PR 7 host columnar packer
verbatim. The contract here:

- assembled window tensors BYTE-IDENTICAL to the host fill on
  integral-µs timestamps, across randomized geometries, forced skips,
  padded axes;
- end-to-end ``solve_fleet`` results identical under both switch
  positions, across compaction/pipeline on+off and both score
  precisions;
- the H2D byte ledger splits resident vs shipped honestly (ring appends
  + index arrays on the resident path, full window tensors on the host
  path) and a re-solve of resident spans ships ZERO new column bytes;
- ineligible inputs (non-integral timestamps, ring-overflow partitions)
  fall back to the host packer, counted, never approximated;
- a second identical resident solve costs zero backend compiles.

Everything synthetic, no datasets, JAX_PLATFORMS=cpu — tier-1.
"""

import numpy as np
import pytest

import jax

import traceweaver_tpu.algorithms.weaver_tpu as wt
from traceweaver_tpu.algorithms.fleet import FleetItem, solve_fleet
from traceweaver_tpu.ops import devcols
from traceweaver_tpu.runtime import knobs
from traceweaver_tpu.spans import SKIP, Span

jax.config.update("jax_platforms", "cpu")

pytestmark = pytest.mark.devcols


@pytest.fixture(autouse=True)
def _fresh_rings():
    """Every test starts from an empty device-column store (rings are
    process-global residency by design)."""
    devcols.get_store().clear()
    yield
    devcols.get_store().clear()


def _random_problem(seed=0, n_traces=50, eps=("A", "B"), burst=6,
                    drop_every=0, integral=True):
    """Randomized single-service partitions with INTEGRAL-µs timestamps
    (the Jaeger wire convention the resident path admits); integral=False
    mints fractional times to exercise the eligibility fallback."""
    import networkx as nx

    rng = np.random.default_rng(seed)
    in_spans = []
    out_spans = {ep: [] for ep in eps}
    ta = {ep: {} for ep in eps}
    t = 0.0
    frac = 0.0 if integral else 0.25
    for i in range(n_traces):
        t += float(rng.integers(20, 60)) if i % burst else 4000.0
        s_in = Span(f"t{i}", "in", t + frac, 350.0 + 30.0 * len(eps),
                    "op", [], "svc", "server")
        in_spans.append(s_in)
        dropped = drop_every and (i % drop_every == 0)
        prev = t + 8.0
        for ep in eps:
            if dropped:
                ta[ep][s_in.GetId()] = SKIP
                continue
            start = prev + 12.0 + float(rng.integers(0, 6))
            s_out = Span(f"t{i}", f"out-{ep}", start + frac, 40.0,
                         f"op{ep}", [], "svc", "client")
            out_spans[ep].append(s_out)
            ta[ep][s_in.GetId()] = s_out.GetId()
            prev = start + 40.0
    dag = nx.DiGraph()
    for a, b in zip(eps, eps[1:]):
        dag.add_edge(a, b)
    if len(eps) == 1:
        dag.add_node(eps[0])
    in_spans.sort(key=lambda s: (s.start_mus, s.end_mus))
    for part in out_spans.values():
        part.sort(key=lambda s: (s.start_mus, s.end_mus))
    return in_spans, out_spans, list(eps), ta, dag


def _items(n_services=2, method="MaxScoreBatchSubsetWithSkips",
           drop_every=0, integral=True, seed0=0):
    items = []
    for k in range(n_services):
        i, o, _eps, ta, dag = _random_problem(
            seed=seed0 + k, eps=("A", "B") if k % 2 == 0 else ("A",),
            drop_every=drop_every, integral=integral)
        items.append(FleetItem(f"svc{k}", {"IN": i}, o, ta, dag,
                               method=method))
    return items


def _solve(monkeypatch, devflag, items, **kw):
    monkeypatch.setenv("TW_DEVCOLS", devflag)
    devcols.get_store().clear()
    stats = {}
    res = solve_fleet(items, stats=stats, **kw)
    key = [(r[0], r[1], r[2], r[3], r[4], r[5]) for r in res]
    return key, stats


# ---------------------------------------------------------------------------
# assembled-tensor byte parity (the pack-level contract)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed,eps,drop", [
    (0, ("A", "B"), 0),
    (1, ("A", "B", "C"), 0),
    (2, ("A",), 0),
    (3, ("A", "B"), 5),     # skip budget > 0, forced-skip-capable
])
def test_assembled_tensors_byte_identical(monkeypatch, seed, eps, drop):
    in_spans, out_parts, out_eps, ta, dag = _random_problem(
        seed=seed, eps=eps, drop_every=drop)
    plan = wt.plan_find_assignments({"IN": in_spans}, out_parts, out_eps,
                                    dag, ta)
    monkeypatch.setenv("TW_COLUMNAR", "1")
    host = wt._pack_problem_columnar(
        in_spans, out_parts, out_eps, plan["dists"], "IN", dag,
        force_skip_ids=plan["force_skip_ids"])

    in_cols = wt.in_columns(in_spans)
    out_cols = wt.out_columns(out_parts, out_eps)
    store = devcols.get_store()
    ring_in = store.ring(None, "svc", "in")
    ring_out = store.ring(None, "svc", "out")
    in_slots = ring_in.resolve(in_cols)
    out_slots = {ep: ring_out.resolve(out_cols[ep], endpoint=ep)
                 for ep in out_eps}
    assert in_slots is not None and all(
        s is not None for s in out_slots.values())
    dc = wt._pack_problem_devcols(
        in_spans, out_parts, out_eps, plan["dists"], "IN", dag,
        in_slots, out_slots, ring_in, ring_out,
        force_skip_ids=plan["force_skip_ids"])

    assert dc.windows == host.windows
    assert dc.M == host.arrays["out_start"].shape[2]
    b = dc.devcols
    outs = devcols.assemble_windows(
        ring_in.buf, ring_out.buf, b["in_idx"], b["out_idx"],
        b["origin_in"], b["origin_out"])
    names = ("in_start", "in_end", "in_valid",
             "out_start", "out_end", "out_valid")
    for name, dev in zip(names, outs):
        got = devcols.fetch_resident(dev)
        want = host.arrays[name]
        assert got.dtype == want.dtype and got.shape == want.shape, name
        assert got.tobytes() == want.tobytes(), \
            f"{name} not byte-identical to the host fill"
    # the host-shipped small tensors and the decode id maps match too
    for name in ("skip_cap", "force_skip"):
        assert dc.arrays[name].tobytes() == host.arrays[name].tobytes()
    for e in range(len(out_eps)):
        a, c = host.out_id_array(e), dc.out_id_array(e)
        assert a.shape == c.shape and all(x == y for x, y in zip(a, c))


# ---------------------------------------------------------------------------
# end-to-end solve parity across flow variants
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("pipeline,compact", [
    ("0", "0"), ("0", "1"), ("1", "0"), ("1", "1")])
def test_solve_fleet_parity_flow_matrix(monkeypatch, pipeline, compact):
    monkeypatch.setenv("TW_PIPELINE", pipeline)
    monkeypatch.setenv("TW_COMPACT", compact)
    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    host, _ = _solve(monkeypatch, "0", _items(3))
    dev, st = _solve(monkeypatch, "1", _items(3))
    assert st.get("h2d_bytes_ring", 0) > 0, "resident path did not run"
    assert host == dev


@pytest.mark.parametrize("precision", ["f32", "bf16"])
def test_solve_fleet_parity_precisions(monkeypatch, precision):
    host, _ = _solve(monkeypatch, "0", _items(2), precision=precision)
    dev, st = _solve(monkeypatch, "1", _items(2), precision=precision)
    assert st.get("h2d_bytes_ring", 0) > 0
    assert host == dev


def test_solve_fleet_parity_forced_skips(monkeypatch):
    """The true-skips oracle's forced rows ride force_skip tensors —
    still host-shipped under devcols, identical results."""
    host, _ = _solve(monkeypatch, "0", _items(
        2, method="MaxScoreBatchSubsetWithTrueSkips", drop_every=4))
    dev, st = _solve(monkeypatch, "1", _items(
        2, method="MaxScoreBatchSubsetWithTrueSkips", drop_every=4))
    assert st.get("h2d_bytes_ring", 0) > 0
    assert host == dev


# ---------------------------------------------------------------------------
# ledger + residency economics
# ---------------------------------------------------------------------------

def test_h2d_ledger_splits_resident_vs_shipped(monkeypatch):
    host, s0 = _solve(monkeypatch, "0", _items(2))
    dev, s1 = _solve(monkeypatch, "1", _items(2))
    # host path: full window tensors shipped, no ring/index traffic
    assert s0.get("h2d_bytes_shipped", 0) > 0
    assert s0.get("h2d_bytes_ring", 0) == 0
    assert s0.get("h2d_bytes_index", 0) == 0
    # resident path: ring appends + index arrays, and the residual
    # shipped tensors (skip/force) are a fraction of the host path's
    assert s1.get("h2d_bytes_ring", 0) > 0
    assert s1.get("h2d_bytes_index", 0) > 0
    assert s1["h2d_bytes_shipped"] < s0["h2d_bytes_shipped"]


def test_second_solve_ships_zero_column_bytes(monkeypatch):
    """Residency is the point: re-solving spans already in the rings
    appends nothing — only index arrays ship."""
    monkeypatch.setenv("TW_DEVCOLS", "1")
    devcols.get_store().clear()
    items = _items(2)
    s1, s2 = {}, {}
    solve_fleet(_items(2), stats=s1)
    solve_fleet(items, stats=s2)
    assert s1.get("h2d_bytes_ring", 0) > 0
    assert s2.get("h2d_bytes_ring", 0) == 0, \
        "resident spans re-shipped on the second solve"
    assert s2.get("h2d_bytes_index", 0) > 0


def test_second_solve_zero_recompiles(monkeypatch):
    from traceweaver_tpu.runtime.jax_cache import (
        compile_counters,
        counters_delta,
    )

    monkeypatch.setenv("TW_DEVCOLS", "1")
    devcols.get_store().clear()
    solve_fleet(_items(2), stats={})
    before = compile_counters()
    solve_fleet(_items(2), stats={})
    delta = counters_delta(before)
    assert delta["backend_compiles"] == 0, \
        "identical resident solve recompiled"


# ---------------------------------------------------------------------------
# eligibility fallback
# ---------------------------------------------------------------------------

def test_fractional_timestamps_fall_back_counted(monkeypatch):
    """Non-integral µs cannot ride the int32 rings bit-exactly: the
    group falls back to the host packer, counted — and the results
    still match the TW_DEVCOLS=0 reference exactly."""
    host, _ = _solve(monkeypatch, "0", _items(2, integral=False))
    dev, st = _solve(monkeypatch, "1", _items(2, integral=False))
    assert st.get("devcols_fallbacks", 0) > 0
    assert st.get("h2d_bytes_ring", 0) == 0
    assert host == dev


def test_oversized_partition_falls_back(monkeypatch):
    """A partition larger than the ring capacity cannot be resident."""
    monkeypatch.setenv("TW_DEVCOLS_RING", "1024")
    host, _ = _solve(monkeypatch, "0", _items(1, seed0=7))
    monkeypatch.setenv("TW_DEVCOLS_RING", "1024")
    ring = devcols.ColumnRing("test", cap=16)
    in_spans, out_parts, out_eps, ta, dag = _random_problem(seed=9,
                                                            n_traces=40)
    cols = wt.in_columns(in_spans)
    assert ring.resolve(cols) is None  # > cap live spans


# ---------------------------------------------------------------------------
# ring mechanics
# ---------------------------------------------------------------------------

def _cols(times):
    from traceweaver_tpu.spans import SpanArray

    spans = [Span(f"r{i}", "s", float(t), 10.0, "op", [], "p", "server")
             for i, t in enumerate(times)]
    return SpanArray.from_spans(spans)


def test_ring_eviction_and_reappend():
    ring = devcols.ColumnRing("t", cap=8)
    a = _cols([100, 200, 300, 400])
    s1 = ring.resolve(a)
    assert s1 is not None and len(set(s1.tolist())) == 4
    # push enough NEW spans through to evict the first batch
    ring.resolve(_cols([500, 600, 700, 800]))
    ring.resolve(_cols([900, 1000, 1100, 1200]))
    # the original spans were evicted: resolving them re-appends (new
    # slots, correct values), never aliases stale slots
    before = ring.appended_rows
    s2 = ring.resolve(a)
    assert s2 is not None
    assert ring.appended_rows == before + 4
    got = devcols.fetch_resident(ring.buf)
    np.testing.assert_array_equal(got[s2, 0] + ring.epoch, a.start)


def test_ring_id_collision_reappends():
    """Same span ids with DIFFERENT times (another corpus reusing the
    id space) must re-append, not alias the stale values."""
    ring = devcols.ColumnRing("t", cap=64)
    ring.resolve(_cols([100, 200, 300]))
    b = _cols([1100, 1200, 1300])   # same ids r0..r2, shifted times
    slots = ring.resolve(b)
    got = devcols.fetch_resident(ring.buf)
    np.testing.assert_array_equal(got[slots, 0] + ring.epoch, b.start)


def test_resident_resolve_is_free():
    ring = devcols.ColumnRing("t", cap=64)
    a = _cols([100, 200, 300, 400, 500])
    ring.resolve(a)
    before_rows, before_bytes = ring.appended_rows, ring.appended_bytes
    s2 = ring.resolve(a)
    assert ring.appended_rows == before_rows
    assert ring.appended_bytes == before_bytes
    assert s2 is not None


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def test_knobs_registered():
    for name in ("TW_DEVCOLS", "TW_DEVCOLS_RING", "TW_SERVE_SLO_P99_MS",
                 "TW_SERVE_CONTINUOUS"):
        assert name in knobs.REGISTRY, name
    assert knobs.get_bool("TW_DEVCOLS") is True
    assert knobs.get_int("TW_DEVCOLS_RING") >= 1 << 10


def test_devcols_rides_only_the_columnar_path(monkeypatch):
    """TW_COLUMNAR=0 (object packer) implies the host path even with
    TW_DEVCOLS=1 — the rings are built FROM the SpanArray columns."""
    monkeypatch.setenv("TW_COLUMNAR", "0")
    dev, st = _solve(monkeypatch, "1", _items(2))
    assert st.get("h2d_bytes_ring", 0) == 0
    monkeypatch.setenv("TW_COLUMNAR", "1")
    host, _ = _solve(monkeypatch, "0", _items(2))
    assert dev == host


# ---------------------------------------------------------------------------
# ring-invalidate-and-rebuild rung (TW_FAULTS=devcols, ISSUE 12)
# ---------------------------------------------------------------------------

def test_ring_rebuild_preserves_live_slots_bit_identical():
    """rebuild() reconstructs the device buffer from the host mirror
    with slot assignments preserved — in-flight index arrays computed
    against the old map must gather identical columns afterwards."""
    ring = devcols.ColumnRing("t", cap=64)
    a = _cols([100, 200, 300, 400, 500])
    slots = ring.resolve(a, endpoint="EP0")
    before = devcols.fetch_resident(ring.buf)
    shipped = ring.rebuild()
    after = devcols.fetch_resident(ring.buf)
    assert shipped == after.nbytes and ring.rebuilds == 1
    np.testing.assert_array_equal(before[slots], after[slots])
    # the mapping survived: a re-resolve appends nothing
    rows_before = ring.appended_rows
    s2 = ring.resolve(a, endpoint="EP0")
    np.testing.assert_array_equal(s2, slots)
    assert ring.appended_rows == rows_before


def test_devcols_fault_at_resolve_rebuilds_and_solves_identical(
        monkeypatch):
    """A ring-append-site fault (TW_FAULTS=devcols) walks the
    ring-invalidate-and-rebuild rung: counted (devcols_ring_rebuilds),
    ledgered on the fault ladder, billed to h2d_bytes_ring, and the
    solve output is identical to the unfaulted run."""
    from traceweaver_tpu.runtime import faults

    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    faults.reset()
    clean, _ = _solve(monkeypatch, "1", _items(2))
    devcols.get_store().clear()
    monkeypatch.setenv("TW_DEVCOLS", "1")
    stats = {}
    with faults.override("devcols:1.0:max=1", seed=0):
        out = solve_fleet(_items(2), stats=stats)
    faults.reset()
    key = [(r[0], r[1], r[2], r[3], r[4], r[5]) for r in out]
    assert key == clean
    assert stats.get("devcols_ring_rebuilds", 0) >= 2  # both rings
    assert "ring-rebuild" in stats.get("fault_ladder", [])
    assert stats.get("faults_injected_devcols", 0) == 1
    assert stats.get("h2d_bytes_ring", 0) > 0


def test_devcols_fault_at_assembly_enters_ladder_with_rebuild(
        monkeypatch):
    """A resident-assembly-site fault surfaces from the dispatch
    attempt, enters the supervisor ladder, rebuilds the rings, and the
    retry recovers with identical output — a poisoned ring never
    reaches a later dispatch."""
    from traceweaver_tpu.runtime import faults

    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    # serial flow: the draw order (resolve sites first, then the
    # assembly site) is deterministic without pipeline interleaving
    monkeypatch.setenv("TW_PIPELINE", "0")
    faults.reset()
    clean, _ = _solve(monkeypatch, "1", _items(2))
    devcols.get_store().clear()
    stats = {}
    # draws: per-item resolve checks consume the first draws at p=0;
    # use max=N at p=1.0 so BOTH a resolve-site and an assembly-site
    # injection fire across the group's checks
    with faults.override("devcols:1.0:max=3", seed=0):
        out = solve_fleet(_items(2), stats=stats)
    faults.reset()
    key = [(r[0], r[1], r[2], r[3], r[4], r[5]) for r in out]
    assert key == clean
    assert stats.get("faults_injected_devcols", 0) == 3
    assert stats.get("fault_retries", 0) >= 1       # the ladder engaged
    ladder = stats.get("fault_ladder", [])
    assert ladder.count("ring-rebuild") >= 2
    assert all(r is not None for r in out)
