"""Water-filling skip allocation (reference TallySkipSpans/WaterFill,
traceweaver_v3.py:853-989)."""

import numpy as np

from traceweaver_tpu.algorithms.skips import water_fill, water_fill_skip_caps


def test_zero_budget_allocates_nothing():
    alloc = water_fill(np.array([5.0, 1.0]), np.array([10.0, 10.0]), 0)
    assert alloc.sum() == 0


def test_budget_spent_up_to_capacity():
    existing = np.array([8.0, 2.0, 5.0])
    expected = np.array([10.0, 10.0, 10.0])
    cap = np.maximum(expected - existing, 0)
    for budget in [1, 3, 7, 15, 100]:
        alloc = water_fill(existing, expected, budget)
        assert np.all(alloc >= 0)
        assert np.all(alloc <= cap + 1e-9)
        assert alloc.sum() == min(budget, cap.sum())


def test_fills_lowest_windows_first():
    # water level: the emptiest window gets skips before fuller ones
    existing = np.array([9.0, 1.0, 5.0])
    expected = np.array([10.0, 10.0, 10.0])
    alloc = water_fill(existing, expected, 4)
    assert alloc[1] == 4  # all budget goes to the emptiest window
    alloc = water_fill(existing, expected, 8)
    # level ~ (8 + 1 + 5) / 2 = 7: window1 -> 6, window2 -> 2
    assert alloc[1] > alloc[2] > 0
    assert alloc[0] == 0


def test_equalizes_water_level():
    existing = np.array([0.0, 0.0, 0.0, 0.0])
    expected = np.array([10.0, 10.0, 10.0, 10.0])
    alloc = water_fill(existing, expected, 20)
    assert alloc.sum() == 20
    assert np.ptp(alloc + existing) <= 1  # near-equal levels

def test_spill_into_capacity_when_level_capped():
    # window 1 hits its cap; leftover spills to others
    existing = np.array([0.0, 9.0])
    expected = np.array([2.0, 30.0])
    alloc = water_fill(existing, expected, 10)
    assert alloc[0] == 2.0        # capped at expected - existing
    assert alloc[1] == 8.0        # remainder spills here
    assert alloc.sum() == 10


def test_skip_caps_shape_and_budget_gate():
    windows = [(0, 4), (4, 8), (8, 10)]
    # E=2; ep0 has slack (budget 10-6=4), ep1 none (budget 10-12<0)
    ranges = np.zeros((3, 2, 2), dtype=np.int64)
    ranges[:, 0, 1] = [2, 2, 2]   # 2 candidates each window for ep0
    ranges[:, 1, 1] = [4, 4, 4]
    caps = water_fill_skip_caps(windows, ranges, 10, [6, 12])
    assert caps.shape == (3, 2)
    assert caps[:, 1].sum() == 0
    assert caps[:, 0].sum() == 4
