"""Golden parity: the columnar host pack path vs the object path.

The columnar span store (spans.SpanArray) and the vectorized pack path
(weaver_tpu._pack_problem_columnar) must be BIT-IDENTICAL to the
per-span object walk they replace (``TW_COLUMNAR=0``, kept verbatim as
the kill switch): same perfect-cut windows, byte-identical packed window
tensors across randomized geometries / forced skips / precomputed
ranges+skip_caps / padded axes, identical decode-time id resolution, and
identical end-to-end solve outputs under both switch positions and both
score precisions (the bf16 path stores 2-byte score blocks downstream of
the pack — the packed f32 tensors themselves must not depend on it).

Everything here is synthetic (no dataset dependency) and runs under
JAX_PLATFORMS=cpu — tier-1.
"""

import math

import numpy as np
import pytest

import jax

import traceweaver_tpu.algorithms.weaver_tpu as wt
from traceweaver_tpu.algorithms.fleet import FleetItem, solve_fleet
from traceweaver_tpu.runtime import knobs
from traceweaver_tpu.spans import (
    SKIP,
    Span,
    SpanArray,
    is_skip_span,
    make_skip_span,
    skip_span_wire,
)

jax.config.update("jax_platforms", "cpu")

pytestmark = pytest.mark.columnar


def _random_problem(seed=0, n_traces=60, eps=("A", "B"), burst=6,
                    drop_every=0, dup_times=False):
    """One service's partitions with randomized geometry: bursty
    arrivals (window structure), optional dropped outgoing spans (skip
    budget / forced-skip rows), optional duplicated timestamps (sort
    tie-stability)."""
    import networkx as nx

    rng = np.random.default_rng(seed)
    in_spans, out_spans, ta = [], {ep: [] for ep in eps}, {ep: {} for ep in eps}
    t = 0.0
    for i in range(n_traces):
        t += float(rng.integers(20, 60)) if i % burst else 4000.0
        if dup_times and i % 7 == 0:
            t = float(int(t))  # mint exact ties across traces
        s_in = Span(f"t{i}", "in", t, 350.0 + 30.0 * len(eps), "op", [],
                    "svc", "server")
        in_spans.append(s_in)
        dropped = drop_every and (i % drop_every == 0)
        prev = t + 8.0
        for ep in eps:
            if dropped:
                ta[ep][s_in.GetId()] = SKIP
                continue
            start = prev + 12.0 + float(rng.normal(0, 3))
            s_out = Span(f"t{i}", f"out-{ep}", start, 40.0, f"op{ep}", [],
                         "svc", "client")
            out_spans[ep].append(s_out)
            ta[ep][s_in.GetId()] = s_out.GetId()
            prev = start + 40.0
    dag = nx.DiGraph()
    for a, b in zip(eps, eps[1:]):
        dag.add_edge(a, b)
    if len(eps) == 1:
        dag.add_node(eps[0])
    in_spans = sorted(in_spans, key=lambda s: (s.start_mus, s.end_mus))
    for part in out_spans.values():
        part.sort(key=lambda s: (s.start_mus, s.end_mus))
    return in_spans, out_spans, list(eps), ta, dag


def _pack_both(monkeypatch, in_spans, out_parts, out_eps, dists, in_ep,
               dag, **kw):
    packs = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("TW_COLUMNAR", flag)
        packs[flag] = wt.pack_problem(in_spans, out_parts, out_eps, dists,
                                      in_ep, dag, **kw)
    return packs["0"], packs["1"]


def _assert_pack_identical(po, pc):
    assert po.windows == pc.windows
    assert set(po.arrays) == set(pc.arrays)
    for k in po.arrays:
        a, b = po.arrays[k], pc.arrays[k]
        assert a.dtype == b.dtype and a.shape == b.shape, k
        assert a.tobytes() == b.tobytes(), f"array {k!r} not byte-identical"
    assert list(po.in_ids) == list(pc.in_ids)
    assert po.n_in == pc.n_in
    for e in range(len(po.out_eps)):
        ao, ac = po.out_id_array(e), pc.out_id_array(e)
        assert ao.shape == ac.shape
        assert all(x == y for x, y in zip(ao, ac)), f"id map {e} differs"


@pytest.mark.parametrize("seed,eps,burst,drop,dup", [
    (0, ("A", "B"), 6, 0, False),
    (1, ("A", "B", "C", "D"), 12, 0, False),
    (2, ("A",), 3, 0, True),
    (3, ("A", "B", "C"), 9, 5, False),   # skip budget > 0
])
def test_pack_problem_byte_parity_randomized(monkeypatch, seed, eps, burst,
                                             drop, dup):
    in_spans, out_parts, out_eps, ta, dag = _random_problem(
        seed=seed, eps=eps, burst=burst, drop_every=drop, dup_times=dup)
    plan = wt.plan_find_assignments({"IN": in_spans}, out_parts, out_eps,
                                    dag, ta)
    po, pc = _pack_both(monkeypatch, in_spans, out_parts, out_eps,
                        plan["dists"], "IN", dag)
    _assert_pack_identical(po, pc)


def test_pack_parity_with_forced_skips_and_padding(monkeypatch):
    """The true-skips oracle's forced rows and the fleet packer's padded
    axes (pad_w/pad_m/pad_e + precomputed ranges/skip_caps) must pack
    identically on both paths."""
    from traceweaver_tpu.algorithms.skips import water_fill_skip_caps

    in_spans, out_parts, out_eps, ta, dag = _random_problem(
        seed=4, eps=("A", "B"), burst=8, drop_every=4)
    plan = wt.plan_find_assignments({"IN": in_spans}, out_parts, out_eps,
                                    dag, ta, true_skips=True)
    assert any(plan["force_skip_ids"][ep] for ep in out_eps)

    monkeypatch.setenv("TW_COLUMNAR", "0")
    windows = wt.perfect_cut_windows(in_spans, 16)  # force cap splits too
    out_starts = {
        ep: np.array(sorted(float(s.start_mus) for s in out_parts[ep]))
        for ep in out_eps
    }
    ranges = wt.candidate_ranges(in_spans, windows, out_eps, out_starts)
    caps = water_fill_skip_caps(windows, ranges, len(in_spans),
                                [len(out_parts[ep]) for ep in out_eps])
    po, pc = _pack_both(
        monkeypatch, in_spans, out_parts, out_eps, plan["dists"], "IN", dag,
        force_skip_ids=plan["force_skip_ids"], windows=windows,
        ranges=ranges, skip_caps=caps, pad_w=32, pad_m=64, pad_e=4)
    assert po.arrays["force_skip"].any()
    _assert_pack_identical(po, pc)


def test_perfect_cut_windows_parity_including_cap_splits(monkeypatch):
    for seed, cap in ((0, 4), (1, 7), (2, 1024), (5, 2)):
        in_spans, *_ = _random_problem(seed=seed, burst=11, dup_times=True)
        obj = wt.perfect_cut_windows(in_spans, cap)
        cols = wt.in_columns(in_spans)
        assert wt.perfect_cut_windows_cols(cols, cap) == obj


def test_candidate_ranges_parity(monkeypatch):
    in_spans, out_parts, out_eps, _, _ = _random_problem(seed=6, burst=9)
    out_starts = {
        ep: np.array(sorted(float(s.start_mus) for s in out_parts[ep]))
        for ep in out_eps
    }
    windows = wt.perfect_cut_windows(in_spans, 8)
    monkeypatch.setenv("TW_COLUMNAR", "0")
    obj = wt.candidate_ranges(in_spans, windows, out_eps, out_starts)
    monkeypatch.setenv("TW_COLUMNAR", "1")
    col = wt.candidate_ranges(in_spans, windows, out_eps, out_starts)
    col2 = wt.candidate_ranges(in_spans, windows, out_eps, out_starts,
                               in_cols=wt.in_columns(in_spans))
    assert np.array_equal(obj, col) and obj.dtype == col.dtype
    assert np.array_equal(obj, col2)


def test_endpoint_ids_rows_truncation_matches_object_slicing(monkeypatch):
    """The fleet packer drops pack_problem's power-of-two B padding;
    EndpointIds.rows must keep the id maps aligned exactly as the object
    path's flat-list slice did."""
    in_spans, out_parts, out_eps, ta, dag = _random_problem(seed=7)
    plan = wt.plan_find_assignments({"IN": in_spans}, out_parts, out_eps,
                                    dag, ta)
    po, pc = _pack_both(monkeypatch, in_spans, out_parts, out_eps,
                        plan["dists"], "IN", dag)
    n_w = len(po.windows)
    M = po.arrays["out_start"].shape[2]
    po.truncate_rows(n_w)
    pc.truncate_rows(n_w)
    for e in range(len(out_eps)):
        ao, ac = po.out_id_array(e), pc.out_id_array(e)
        assert len(ao) == len(ac) == n_w * M
        assert all(x == y for x, y in zip(ao, ac))


@pytest.mark.parametrize("precision", ["f32", "bf16"])
def test_fleet_solve_identical_under_both_switches(monkeypatch, precision):
    """End-to-end: solve_fleet outputs (assignments, top-k, counters)
    must be identical under TW_COLUMNAR=0 and =1, at both score-block
    itemsizes (f32 and bf16)."""
    def items():
        built = []
        for seed, eps, drop in ((0, ("A", "B"), 0), (1, ("A", "B", "C"), 5)):
            in_spans, out_parts, out_eps, ta, dag = _random_problem(
                seed=seed, n_traces=40, eps=eps, drop_every=drop)
            built.append(FleetItem(f"svc{seed}", {"IN": in_spans},
                                   out_parts, ta, dag))
        return built

    outs = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("TW_COLUMNAR", flag)
        outs[flag] = solve_fleet(items(), stats={}, precision=precision)
    for a, b in zip(outs["0"], outs["1"]):
        assert a[0] == b[0]   # assignments
        assert a[1] == b[1]   # top-k
        assert a[2:] == b[2:]  # counters


def test_skip_span_nan_sentinels_survive_the_round_trip():
    """make_skip_span now carries NaN floats in its float fields (no
    more the string "None" type-lied into start/duration): the columnar
    store represents it as NaN column entries, float arithmetic works
    (end_mus is NaN, not the string "NoneNone"), and the reference's
    all-"None" wire shape appears ONLY at emission via skip_span_wire."""
    sk = make_skip_span("s1")
    assert is_skip_span(sk)
    assert isinstance(sk.start_mus, float) and math.isnan(sk.start_mus)
    assert isinstance(sk.duration_mus, float) and math.isnan(sk.duration_mus)
    assert math.isnan(sk.end_mus)  # was "None" + "None" == "NoneNone"

    real = Span("t0", "r1", 100.0, 50.0, "op", [], "p", "client")
    arr = SpanArray.from_spans([real, sk])
    assert np.isnan(arr.start[1]) and np.isnan(arr.end[1])
    assert arr.start[0] == 100.0 and arr.end[0] == 150.0
    assert arr.ids[1] == ("None", "s1")

    wire = skip_span_wire(sk)
    assert wire["start_mus"] == "None" and wire["duration_mus"] == "None"
    assert wire["trace_id"] == "None" and wire["process_id"] == "None"
    # a real span's wire record keeps its numbers
    wire_real = skip_span_wire(real)
    assert wire_real["start_mus"] == 100.0
    assert wire_real["duration_mus"] == 50.0


def test_tw_columnar_knob_registered_and_kill_switch_semantics(monkeypatch):
    assert "TW_COLUMNAR" in knobs.REGISTRY
    monkeypatch.delenv("TW_COLUMNAR", raising=False)
    assert knobs.get_bool("TW_COLUMNAR") is True       # default: columnar
    assert wt.columnar_enabled() is True
    for off in ("0", "false", ""):
        monkeypatch.setenv("TW_COLUMNAR", off)
        assert wt.columnar_enabled() is False
    monkeypatch.setenv("TW_COLUMNAR", "1")
    assert wt.columnar_enabled() is True


def test_ingest_time_store_columns_match_span_lists(monkeypatch):
    """TraceStore.build_columns must mirror the in/out span lists
    exactly: same order, same ids, same f64 times, service id column
    attached."""
    from traceweaver_tpu.spans import TraceStore

    store = TraceStore()
    for i in range(5):
        sp = Span(f"t{i}", f"s{i}", 10.0 * i + 0.5, 3.0, "op", [], "p",
                  "server")
        store.in_spans_by_process.setdefault("svc", []).append(sp)
        cl = Span(f"t{i}", f"c{i}", 10.0 * i + 1.0, 1.0, "op", [], "p",
                  "client")
        store.out_spans_by_process.setdefault("svc", []).append(cl)
    cols = store.build_columns()
    assert set(cols) == {"svc"}
    for key, src in (("in", store.in_spans_by_process["svc"]),
                     ("out", store.out_spans_by_process["svc"])):
        arr = cols["svc"][key]
        assert len(arr) == len(src)
        assert list(arr.ids) == [s.GetId() for s in src]
        assert np.array_equal(arr.start,
                              [float(s.start_mus) for s in src])
        assert arr.service_table == ["svc"]
        assert np.all(arr.service == 0)
