"""Amortized plan cache tests (ISSUE 17, tier-1, CPU).

Contracts covered:

- :class:`~traceweaver_tpu.algorithms.plancache.PlanCache` semantics —
  hit/miss/admit/invalidate counting, the ``TW_PLAN_CACHE=0`` kill
  switch, the ``TW_PLAN_MIN_SAMPLES`` streaming admission bar, and the
  checkpoint ``state()``/``from_state()`` round trip;
- fleet integration — a warm cache collapses the two-pass EM to a
  single warm pass with BIT-IDENTICAL output, and the kill switch
  restores the uncached solve byte-for-byte;
- drift targeting — the adapt controller's actuations invalidate
  exactly the drifting service's entry, nothing else;
- stream integration — high-volume windows amortize the per-window
  refit (hits counted on ``/metrics`` and the stream ledger) while
  thin windows NEVER admit, keeping the warm-start feedback loop and
  the PR 12 PSI drift sensor running the pre-cache program (the
  chaos-adapt recovery story in tests/test_adapt.py depends on it);
- the satellite-2 precision pin — ``ops/gmm.fit_gmm_sharded``'s f32
  z-space EM against the host f64 ``from_samples_gmm`` fit at
  large-magnitude means (the bounded-deviation claim documented at
  ops/gmm.py:131-135, previously untested).
"""

import numpy as np
import pytest

import jax

from traceweaver_tpu.algorithms.plancache import PlanCache, admissible

jax.config.update("jax_platforms", "cpu")

pytestmark = pytest.mark.plan


# ---------------------------------------------------------------------------
# cache unit semantics
# ---------------------------------------------------------------------------

def test_cache_hit_miss_invalidate_counting():
    pc = PlanCache()
    assert pc.lookup("svc") is None
    plan = {("in", "a"): "dists-sentinel"}
    pc.admit("svc", plan)
    assert pc.lookup("svc") is plan
    assert len(pc) == 1
    pc.invalidate("svc")
    assert pc.lookup("svc") is None
    # empty plans are never admitted (a failed fit must not poison)
    pc.admit("svc", {})
    pc.admit("svc", None)
    assert len(pc) == 0
    assert pc.counters() == dict(hits=1, misses=2, admissions=1,
                                 invalidations=1, entries=0)
    # invalidate(None) clears everything
    pc.admit("a", plan)
    pc.admit("b", plan)
    pc.invalidate(None)
    assert len(pc) == 0 and pc.counters()["invalidations"] == 2


def test_kill_switch_makes_cache_inert(monkeypatch):
    monkeypatch.setenv("TW_PLAN_CACHE", "0")
    pc = PlanCache()
    pc.admit("svc", {("in", "a"): "x"})
    assert pc.lookup("svc") is None
    assert len(pc) == 0
    # disabled lookups/admits are not even counted: the disabled path
    # must be indistinguishable from a build without the cache
    assert pc.counters() == dict(hits=0, misses=0, admissions=0,
                                 invalidations=0, entries=0)


def test_state_roundtrip_preserves_entries_and_counters():
    pc = PlanCache()
    plan = {("in", "a"): "dists-sentinel"}
    pc.admit("svc", plan)
    pc.lookup("svc")
    pc.lookup("ghost")
    pc.invalidate("ghost")
    pc2 = PlanCache.from_state(pc.state())
    assert pc2.lookup("svc") == plan
    c, c2 = pc.counters(), pc2.counters()
    for k in ("misses", "admissions", "invalidations", "entries"):
        assert c2[k] == c[k], (k, c, c2)
    assert PlanCache.from_state(None).counters()["entries"] == 0


def test_admission_bar_tracks_knob(monkeypatch):
    assert admissible(64) and admissible(1000)
    assert not admissible(63) and not admissible(0)
    monkeypatch.setenv("TW_PLAN_MIN_SAMPLES", "8")
    assert admissible(8) and not admissible(7)


# ---------------------------------------------------------------------------
# fleet integration: warm pass equivalence + kill switch
# ---------------------------------------------------------------------------

def _identical(a, b):
    for x, y in zip(a, b):
        assert x[0] == y[0] and x[1] == y[1] and x[2:] == y[2:]


def test_fleet_warm_cache_single_pass_bit_identical():
    """The cached plan is the decoded on-device refit tables of the cold
    solve's two-pass EM; a warm solve packs them back and runs ONE pass
    whose output must be bit-identical to the cold solve's second pass
    (f32 -> f64 -> f32 round-trips exactly; unsampled edges keep the
    wide defaults the in-graph refit preserves)."""
    from test_pipeline import _mixed_items

    from traceweaver_tpu.algorithms.fleet import solve_fleet

    pc = PlanCache()
    cold_stats = {}
    cold = solve_fleet(_mixed_items(), stats=cold_stats, plan_cache=pc)
    c = pc.counters()
    assert c["admissions"] == 3 and c["hits"] == 0 and c["misses"] == 3
    assert cold_stats.get("plan_fit_s", 0) > 0

    warm = solve_fleet(_mixed_items(), stats={}, plan_cache=pc)
    assert pc.counters()["hits"] == 3
    _identical(cold, warm)

    # targeted invalidation refits ONLY the voided service
    pc.invalidate("beta")
    again = solve_fleet(_mixed_items(), stats={}, plan_cache=pc)
    c = pc.counters()
    assert c["misses"] == 4 and c["admissions"] == 4, c
    _identical(cold, again)


def test_fleet_kill_switch_restores_uncached_solve(monkeypatch):
    from test_pipeline import _mixed_items

    from traceweaver_tpu.algorithms.fleet import solve_fleet

    plain = solve_fleet(_mixed_items(), stats={})
    monkeypatch.setenv("TW_PLAN_CACHE", "0")
    pc = PlanCache()
    off = solve_fleet(_mixed_items(), stats={}, plan_cache=pc)
    _identical(plain, off)
    assert pc.counters() == dict(hits=0, misses=0, admissions=0,
                                 invalidations=0, entries=0)


# ---------------------------------------------------------------------------
# drift targeting: controller actuations void exactly one key
# ---------------------------------------------------------------------------

def test_controller_invalidates_only_the_drifting_service(monkeypatch):
    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    from traceweaver_tpu.adapt.controller import AdaptationController

    pc = PlanCache()
    plan = {("in", "a"): "x"}
    pc.admit("svcA", plan)
    pc.admit("svcB", plan)
    ctrl = AdaptationController()
    ctrl.invalidate_cb = pc.invalidate
    ctrl.observe("svcA", psi=9.9)           # excursion -> refit scheduled
    assert pc.lookup("svcA") is None         # voided
    assert pc.lookup("svcB") == plan         # untouched
    assert pc.counters()["invalidations"] == 1


# ---------------------------------------------------------------------------
# stream integration: volume-gated amortization + telemetry
# ---------------------------------------------------------------------------

def _burst_stream(n_bursts, n_req, gap_us, **cfg_kw):
    import bench
    from traceweaver_tpu.stream.service import (
        StreamConfig,
        StreamingReconstructor,
    )
    from traceweaver_tpu.stream.sources import IterableSource

    events, _ = bench._adapt_burst_events(
        n_bursts, shift_at=10 ** 9, n_req=n_req, gap_us=gap_us)
    cfg = StreamConfig(window_us=1e6, overlap_us=0.0, ooo_bound_us=1e3,
                       checkpoint_every=10_000, verbose=False, **cfg_kw)
    return StreamingReconstructor(IterableSource(events), cfg)


def test_stream_big_windows_amortize_refit_and_export_counters():
    from traceweaver_tpu.obs.registry import get_registry

    svc = _burst_stream(6, n_req=70, gap_us=120.0)  # 70 >= the bar
    svc.run()
    c = svc.plan_cache.counters()
    assert c["admissions"] == 1 and c["misses"] == 1, c
    assert c["hits"] >= 4, c
    # one refit ran (the cold window), then the plan froze
    assert svc.stats.get("plan_fit_s", 0) > 0
    snap = get_registry().snapshot()
    assert snap.get('tw_plan_cache_total{event="hit"}', 0) >= 4
    assert snap.get('tw_plan_cache_total{event="admit"}', 0) >= 1
    assert snap.get('tw_stream_ledger_total{key="plan_fit_s"}', 0) > 0


def test_stream_thin_windows_never_freeze(monkeypatch):
    """Below the admission bar every window refits (the pre-cache
    program): freezing a handful-of-samples fit starves the warm loop
    and turns the PSI sensor's confidence stream into atom noise — the
    chaos-adapt leg's recovery story depends on this gate."""
    svc = _burst_stream(6, n_req=8, gap_us=800.0)  # 8 < the bar
    svc.run()
    c = svc.plan_cache.counters()
    assert c["admissions"] == 0 and c["hits"] == 0, c
    assert c["misses"] >= 5, c


def test_stream_kill_switch_byte_identical(tmp_path, monkeypatch):
    """TW_PLAN_CACHE=0 on a HIGH-VOLUME stream (windows above the
    admission bar, where the cache genuinely skips refits) must emit
    byte-identical sink records to... itself — the cached run may
    differ from the uncached one only in HOW the carried statistics are
    refreshed, so the parity pin runs the same corpus twice with the
    switch flipped and asserts the uncached replay reproduces the
    pre-PR per-window refit program (admissions forced off, every
    window refit, plan_fit_s accumulating per window)."""
    import bench
    from traceweaver_tpu.stream.service import (
        StreamConfig,
        StreamingReconstructor,
        TraceSink,
    )
    from traceweaver_tpu.stream.sources import IterableSource

    def run(flag, name):
        monkeypatch.setenv("TW_PLAN_CACHE", flag)
        events, _ = bench._adapt_burst_events(
            5, shift_at=10 ** 9, n_req=70, gap_us=120.0)
        cfg = StreamConfig(window_us=1e6, overlap_us=0.0,
                           ooo_bound_us=1e3, checkpoint_every=10_000,
                           verbose=False)
        sink = TraceSink(str(tmp_path / name))
        svc = StreamingReconstructor(IterableSource(events), cfg,
                                     sink=sink)
        svc.run()
        sink.close()
        return (tmp_path / name).read_bytes(), svc

    bytes_off, svc_off = run("0", "off.jsonl")
    assert svc_off.plan_cache.counters()["admissions"] == 0
    n_windows = 5
    # pre-PR program: every window refit
    assert svc_off.stats.get("plan_fit_s", 0) > 0

    bytes_on, svc_on = run("1", "on.jsonl")
    assert svc_on.plan_cache.counters()["hits"] >= n_windows - 2

    # window 0's fit is shared; the cached run freezes it, and on this
    # stationary corpus the frozen plan solves every later window to
    # the same assignments — emitted bytes agree
    assert bytes_on == bytes_off


# ---------------------------------------------------------------------------
# satellite 2: sharded f32 EM vs host f64 fit (the ops/gmm.py claim)
# ---------------------------------------------------------------------------

def test_fit_gmm_sharded_matches_host_f64_fit():
    """ops/gmm.py:131-135 claims the sharded fit's f32 deviations stay
    bounded because standardization happens before any large-magnitude
    arithmetic. Pin it: at 1e6-magnitude means (where a naive f32
    raw-sample variance loses everything to cancellation — eps*mean^2
    exceeds the true variance) the psum'd z-space EM must agree with
    the host f64 sklearn BIC fit on component count, mixture moments,
    and average log-likelihood."""
    from functools import partial

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from traceweaver_tpu.algorithms.timing import EdgeDist
    from traceweaver_tpu.ops.gmm import fit_gmm_sharded
    from traceweaver_tpu.parallel.mesh import _CHECK_KW, make_mesh

    rng = np.random.default_rng(17)
    # edge 0: two components 5 ms apart riding a 1e6 µs offset;
    # edge 1: one wide component at 2e6 µs
    a = np.concatenate([1e6 + rng.normal(0, 30.0, 300),
                        1e6 + 5000 + rng.normal(0, 60.0, 212)])
    b = 2e6 + rng.normal(0, 300.0, 512)
    x = np.stack([a, b]).astype(np.float32)
    mask = np.ones_like(x, bool)

    mesh = make_mesh(4)
    axis = mesh.axis_names[0]

    @partial(shard_map, mesh=mesh,
             in_specs=(P(None, axis), P(None, axis)),
             out_specs=(P(), P(), P()),
             **{_CHECK_KW: False})
    def fit(s, m):
        return fit_gmm_sharded(s, m, axis, max_k=5)

    w, mu, sd = (np.asarray(o, np.float64) for o in jax.jit(fit)(x, mask))

    def moments(w_, mu_, sd_):
        mean = float((w_ * mu_).sum())
        var = float((w_ * (sd_ ** 2 + mu_ ** 2)).sum()) - mean ** 2
        return mean, float(np.sqrt(max(var, 0.0)))

    for e, samples in enumerate([a, b]):
        host = EdgeDist.from_samples_gmm(samples.tolist())
        # same BIC model order
        assert int((w[e] > 0.05).sum()) == int((host.weights > 0.05).sum())
        dm, ds = moments(w[e], mu[e], sd[e])
        hm, hs = moments(host.weights, host.means, host.stds)
        assert abs(dm - hm) / abs(hm) < 1e-6, (e, dm, hm)
        assert abs(ds - hs) / hs < 1e-3, (e, ds, hs)
        ll_dev = float(np.mean(EdgeDist(w[e], mu[e], sd[e])
                               .logpdf(samples)))
        ll_host = float(np.mean(host.logpdf(samples)))
        assert ll_dev > ll_host - 0.05, (e, ll_dev, ll_host)
