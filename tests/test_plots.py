"""Plot-script contract tests: synthetic result pickles -> PDF figures."""

import os
import pickle
import subprocess
import sys

import pytest

UTILS = os.path.join(os.path.dirname(__file__), "..", "utils")

ALL_METHODS = [
    "MaxScoreBatchSubsetWithSkipsTopK", "MaxScoreBatchSubsetWithSkips",
    "MaxScoreBatchParallel", "MaxScoreBatchParallelWithoutIterations",
    "MaxScore", "WAP5", "vPath", "FCFS",
]


def _accuracy_pickle(path):
    with open(path, "wb") as f:
        pickle.dump({m: 90.0 for m in ALL_METHODS}, f)


def _bins_pickle(path):
    bins = {m: [((b + 1) * 10, 0.9, 5.0) for b in range(10)]
            for m in ALL_METHODS}
    with open(path, "wb") as f:
        pickle.dump(bins, f)


def _run(script, results_dir, suffix, outfile):
    return subprocess.run(
        [sys.executable, os.path.join(UTILS, script),
         str(results_dir) + "/", suffix, str(outfile)],
        capture_output=True, text=True, cwd=UTILS, timeout=120,
    )


def test_fig4a_and_fig5(tmp_path):
    for app in ("hotel", "media", "node"):
        for load in (25, 50, 75, 100, 125, 150):
            _accuracy_pickle(tmp_path / f"accuracy_{app}_t_{load}_1_1_0.0.pickle")
            _bins_pickle(tmp_path / f"bin_acc_{app}_t_{load}_1_1_0.0.pickle")
    for script, fig in [
        ("plot_accuracy_vs_load_multiple_apps.py", "fig4a.pdf"),
        ("plot_accuracy_vs_response_times_multiple_apps.py", "fig4b.pdf"),
        ("plot_accuracy_vs_load_ablation_study.py", "fig5.pdf"),
    ]:
        out = _run(script, tmp_path, "t", tmp_path / fig)
        assert out.returncode == 0, out.stderr
        assert (tmp_path / fig).stat().st_size > 0


def test_fig4c(tmp_path):
    for rate in (0.0, 0.05, 0.1, 0.15, 0.2, 0.25, 0.3, 0.35,
                 0.4, 0.45, 0.5, 0.55, 0.6, 0.65, 0.7):
        _accuracy_pickle(tmp_path / f"accuracy_t_150_1_1_{rate}.pickle")
    out = _run("plot_accuracy_vs_cache_hit_rate.py", tmp_path, "t",
               tmp_path / "fig4c.pdf")
    assert out.returncode == 0, out.stderr


def test_fig4d(tmp_path):
    for rate in (0, 0.2, 0.4, 0.6, 0.8, 1):
        _accuracy_pickle(tmp_path / f"accuracy_node_{rate}_t_50_1_1_0.0.pickle")
    out = _run("plot_accuracy_vs_interleaving_intensity.py", tmp_path, "t",
               tmp_path / "fig4d.pdf")
    assert out.returncode == 0, out.stderr


def test_fig6(tmp_path):
    for cg in range(15):
        for compress in (1, 200, 1000, 4000, 10000, 15000):
            _accuracy_pickle(
                tmp_path / f"accuracy_alibaba_cg_{cg}_t_1_{compress}_1_0.0.pickle"
            )
        with open(tmp_path / f"confidence_scores_alibaba_cg_{cg}_t_1_15000_1_0.0.pickle", "wb") as f:
            pickle.dump({"svc": [0.9, 3, 100]}, f)
    out = _run("plot_accuracy_vs_load_multiple_cgs.py", tmp_path, "t",
               tmp_path / "fig6a.pdf")
    assert out.returncode == 0, out.stderr
    out = _run("plot_accuracy_vs_confidence_multiple_cgs.py", tmp_path, "t",
               tmp_path / "fig6b.pdf")
    assert out.returncode == 0, out.stderr
    assert "Pearson" in out.stdout
