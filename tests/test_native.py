"""Native (C++) layer: loader parity with the pure-Python parser, native
scheme equivalence with the Python baselines."""

import numpy as np
import pytest

from traceweaver_tpu import native
from traceweaver_tpu.ingest import build_service_problem, load_corpus
from traceweaver_tpu.ingest.jaeger import time_ordered_trace_files
from traceweaver_tpu.spans import NA

from tests.conftest import ref_data

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


def _store_fingerprint(store):
    spans = {
        sid: (s.trace_id, s.sid, int(s.start_mus), int(s.duration_mus),
              s.op_name, tuple(s.references), s.process_id, s.span_kind)
        for sid, s in store.all_spans.items()
    }
    parts = {
        svc: [s.GetId() for s in spans_list]
        for svc, spans_list in store.in_spans_by_process.items()
    }
    out_parts = {
        svc: [s.GetId() for s in spans_list]
        for svc, spans_list in store.out_spans_by_process.items()
    }
    return spans, parts, out_parts, store.all_processes


@pytest.mark.parametrize("relpath,fix", [
    ("hotel_reservation/hotel_load25", 2),
    ("media_microservices/media_load25", 1),
    ("nodejs_microservices/node_load25", 0),
])
def test_native_corpus_matches_python(relpath, fix):
    directory = ref_data(relpath)
    # Seed-sensitive steps (media repair ids) run identically in both paths
    # only if the RNG state matches at the start of each load.
    import random

    random.seed(10)
    nat = load_corpus(directory, fix=fix, max_traces=30, cache=False,
                      native="auto")
    random.seed(10)
    pure = load_corpus(directory, fix=fix, max_traces=30, cache=False,
                       native="never")
    assert _store_fingerprint(nat) == _store_fingerprint(pure)


def test_native_root_start_time_matches_python():
    import json
    import os

    directory = ref_data("hotel_reservation/hotel_load25")
    files = sorted(f for f in os.listdir(directory) if f.endswith("json"))[:5]
    for f in files:
        path = os.path.join(directory, f)
        native_t = native.root_start_time(path)
        with open(path) as fh:
            data = json.load(fh)["data"]
        root = next(s for s in data[0]["spans"] if not s.get("references"))
        assert native_t == float(root["startTime"])


def test_time_ordering_native_and_python_agree(monkeypatch):
    directory = ref_data("hotel_reservation/hotel_load25")
    files_native = time_ordered_trace_files(directory, cache=False)
    monkeypatch.setenv("TW_DISABLE_NATIVE", "1")
    files_python = time_ordered_trace_files(directory, cache=False)
    assert files_native == files_python


def _problem_arrays(prob):
    in_ep, in_spans = next(iter(prob.in_span_partitions.items()))
    eps = list(prob.out_span_partitions)
    trace_ids = {}

    def tid(trace):
        return trace_ids.setdefault(trace, len(trace_ids))

    in_start = [float(s.start_mus) for s in in_spans]
    in_end = [float(s.end_mus) for s in in_spans]
    in_trace = [tid(s.trace_id) for s in in_spans]
    out_start, out_end, out_ep_idx, out_trace, out_ids = [], [], [], [], []
    for e, ep in enumerate(eps):
        for s in prob.out_span_partitions[ep]:
            out_start.append(float(s.start_mus))
            out_end.append(float(s.end_mus))
            out_ep_idx.append(e)
            out_trace.append(tid(s.trace_id))
            out_ids.append(s.GetId())
    return (eps, in_spans, out_ids,
            (in_start, in_end, in_trace, out_start, out_end, out_ep_idx,
             out_trace))


@pytest.mark.parametrize("scheme,cls_name", [
    ("vpath", "VPath"),
    ("vpath_old", "VPathOld"),
    ("fcfs", "FCFS"),
])
def test_native_scheme_matches_python(hotel_store, scheme, cls_name):
    import traceweaver_tpu.algorithms as algos
    from traceweaver_tpu.metrics import get_ground_truth

    cls = getattr(algos, cls_name)
    for svc in ["frontend", "search"]:
        prob = build_service_problem(hotel_store, svc)
        if prob.skipped:
            continue
        ta = get_ground_truth(prob.in_span_partitions,
                              prob.out_span_partitions)
        py = cls(hotel_store.all_spans, hotel_store.all_processes)
        expected = py.FindAssignments(
            cls_name, svc,
            {k: list(v) for k, v in prob.in_span_partitions.items()},
            {k: list(v) for k, v in prob.out_span_partitions.items()},
            False, [], ta,
        )

        eps, in_spans, out_ids, arrays = _problem_arrays(prob)
        assign = native.run_scheme(scheme, *arrays[:3], *arrays[3:],
                                   n_eps=len(eps))
        assert assign is not None
        got = {
            ep: {
                in_spans[i].GetId():
                    (out_ids[assign[e, i]] if assign[e, i] >= 0 else NA)
                for i in range(len(in_spans))
            }
            for e, ep in enumerate(eps)
        }
        for ep in eps:
            exp_ep = {k: v for k, v in expected[ep].items()}
            assert got[ep] == exp_ep, f"{scheme} mismatch on {svc}/{ep}"


def test_parse_files_error_reporting(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert native.parse_files([str(bad)]) is None
    assert "bad.json" in native.last_error()
