"""Load-synthesis transform tests."""

import copy
import random

from traceweaver_tpu.metrics import get_ground_truth
from traceweaver_tpu.spans import SKIP, Span
from traceweaver_tpu.synth import compress_spans, create_cache_hits


def _mk(tid, sid, start, dur, kind):
    return Span(tid, sid, start, dur, "op", [], "p1", kind)


def _problem(n=50):
    in_spans = [_mk(f"t{i:03d}", "in", 1000 * i, 900, "server") for i in range(n)]
    out_a = [_mk(f"t{i:03d}", "a", 1000 * i + 100, 200, "client") for i in range(n)]
    out_b = [_mk(f"t{i:03d}", "b", 1000 * i + 400, 200, "client") for i in range(n)]
    return {"up": in_spans}, {"A": out_a, "B": out_b}


def test_compress_preserves_offsets():
    in_parts, out_parts = _problem()
    orig_offsets = [
        out_parts["A"][i].start_mus - in_parts["up"][i].start_mus
        for i in range(50)
    ]
    compress_spans(in_parts, out_parts, repeat_factor=1, compress_factor=10)
    by_tid_in = {s.trace_id: s for s in in_parts["up"]}
    by_tid_a = {s.trace_id: s for s in out_parts["A"]}
    for i in range(50):
        tid = f"t{i:03d}"
        assert by_tid_in[tid].start_mus == 1000 * i / 10
        assert by_tid_a[tid].start_mus - by_tid_in[tid].start_mus == orig_offsets[i]


def test_compress_noop_at_unity():
    in_parts, out_parts = _problem()
    snapshot = copy.deepcopy(in_parts)
    compress_spans(in_parts, out_parts, 1, 1)
    assert [s.start_mus for s in in_parts["up"]] == [s.start_mus for s in snapshot["up"]]


def test_cache_hits_mark_skips_and_delete_spans():
    random.seed(10)
    in_parts, out_parts = _problem()
    ta = get_ground_truth(in_parts, out_parts)
    n_before = len(out_parts["A"])
    ta = create_cache_hits(ta, in_parts, out_parts, cache_rate=0.2)
    skips = [k for k, v in ta["A"].items() if v == SKIP]
    assert len(skips) == 10  # int(0.2 * 50)
    assert len(out_parts["A"]) == n_before - 10
    # incoming spans of cached traces were shortened
    cached_tids = {k[0] for k in skips}
    for s in in_parts["up"]:
        if s.trace_id in cached_tids:
            assert s.duration_mus == 900 - 200
    # endpoint B untouched in count, but shifted earlier for cached traces
    assert len(out_parts["B"]) == n_before
    for s in out_parts["B"]:
        expected = 400 - 200 if s.trace_id in cached_tids else 400
        assert s.start_mus - 1000 * int(s.trace_id[1:]) == expected


def test_compress_spans_multi_call_traces():
    """Per-trace rigid rebase: traces where a service fires twice (or an
    endpoint is missing) compress without the reference's 1:1 alignment
    requirement, preserving intra-trace offsets exactly."""
    from traceweaver_tpu.spans import Span
    from traceweaver_tpu.synth.transforms import compress_spans

    def mk(tid, sid, start, dur, kind):
        return Span(tid, sid, start, dur, "op", [], "p", kind, {})

    in_parts = {"ep_in": [
        mk("t1", "a", 1_000_000, 500, "server"),
        mk("t1", "b", 1_000_800, 500, "server"),   # second call, same trace
        mk("t2", "c", 9_000_000, 500, "server"),
    ]}
    out_parts = {"ep_out": [
        mk("t1", "d", 1_000_100, 50, "client"),    # only one outgoing for t1
        mk("t2", "e", 9_000_200, 50, "client"),
    ]}
    compress_spans(in_parts, out_parts, 1, 100.0)

    by_sid = {s.sid: s for part in (*in_parts.values(), *out_parts.values())
              for s in part}
    # t1 anchored at 1_000_000 -> 10_000; offsets preserved
    assert by_sid["a"].start_mus == 10_000
    assert by_sid["b"].start_mus == 10_800
    assert by_sid["d"].start_mus == 10_100
    # t2 anchored independently
    assert by_sid["c"].start_mus == 90_000
    assert by_sid["e"].start_mus == 90_200
