"""Capture ingress: CollectorSource, skew estimation/correction,
partial-capture policies, churn re-keying, orphan bounds, the
``collector:`` source spec, serve capture ingestion, and the
capture_loss/clock_skew event-kind surface (docs/COLLECTOR.md)."""

import importlib
import json
import os
import sys

import pytest

import jax

jax.config.update("jax_platforms", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from traceweaver_tpu.collector.skew import SkewEstimator  # noqa: E402
from traceweaver_tpu.collector.source import (  # noqa: E402
    CaptureCounters,
    CaptureIngest,
    CollectorSource,
    iter_live,
)
from traceweaver_tpu.runtime import faults, knobs  # noqa: E402


@pytest.fixture()
def bench():
    sys.path.insert(0, REPO)
    import bench as bench_mod

    return importlib.reload(bench_mod)


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# skew estimation
# ---------------------------------------------------------------------------

def test_skew_estimator_recovers_chain_offsets():
    """A→B→C exchange chain: per-edge NTP estimates accumulate into
    absolute offsets anchored at the caller-only reference, robust (via
    the median) to one corrupt exchange."""
    est = SkewEstimator(min_pairs=3, max_us=10e6)
    # B's clock runs 100ms ahead of A; C's 40ms behind B
    for i in range(5):
        t0 = 1000.0 + i * 1e4
        est.observe_pair("a", "b", t0, t0 + 100_000 + 200, t0 + 100_000
                         + 1200, t0 + 1800)
        est.observe_pair("b", "c", t0, t0 - 40_000 + 150, t0 - 40_000
                         + 900, t0 + 1300)
    # one wildly corrupt pair must not drag the median
    est.observe_pair("a", "b", 0.0, 9e6, 9e6, 100.0)
    offs = est.fit()
    assert est.reference() == "a"
    assert abs(offs["a"]) == 0.0
    assert abs(offs["b"] - 100_000) < 1_000
    assert abs(offs["c"] - 60_000) < 2_000
    assert est.correct("b", 100_000.0) == pytest.approx(
        100_000.0 - offs["b"])


def test_skew_estimator_clamps_insane_offsets():
    est = SkewEstimator(min_pairs=1, max_us=1_000.0)
    est.observe_pair("a", "b", 0.0, 5_000_000.0, 5_000_100.0, 200.0)
    offs = est.fit()
    assert offs["b"] == 1_000.0
    assert est.clamped == 1


def test_skew_min_pairs_gate():
    est = SkewEstimator(min_pairs=4, max_us=10e6)
    for _ in range(3):
        est.observe_pair("a", "b", 0.0, 50_000.0, 51_000.0, 2_000.0)
    assert not est.ready()
    est.observe_pair("a", "b", 0.0, 50_000.0, 51_000.0, 2_000.0)
    assert est.ready()


# ---------------------------------------------------------------------------
# CollectorSource synthesis
# ---------------------------------------------------------------------------

def test_collector_source_synthesizes_linked_spans(bench):
    src = CollectorSource(bench._capture_workload(6, churn_at=3))
    events = list(src.events())
    assert len(events) == len(src) == 18  # 2 servers + 1 client per trace
    by_kind = {}
    spans = {}
    for ev in events:
        by_kind.setdefault(ev.span.span_kind, []).append(ev.span)
        spans[ev.span.sid] = ev.span
        # capture-derived spans carry the raw capture stamp
        assert ev.capture_us is not None
    assert len(by_kind["server"]) == 12 and len(by_kind["client"]) == 6
    # cross-source join: every search-side server span references the
    # frontend's client span (no stub was synthesized)
    search_servers = [s for s in by_kind["server"]
                      if s.process_id == "search"]
    assert len(search_servers) == 6
    for s in search_servers:
        assert len(s.references) == 1
        parent = spans[s.references[0][1]]
        assert parent.span_kind == "client"
        assert parent.process_id == "frontend"
        # containment: the client interval covers the server interval
        assert parent.start_mus <= s.start_mus
        assert s.end_mus <= parent.end_mus
    # arrival order is completion order and non-decreasing
    arrivals = [ev.arrival_us for ev in events]
    assert arrivals == sorted(arrivals)
    # clean capture: no loss, the mid-capture reconnect was re-keyed
    q = src.capture_quality()
    assert q["loss"] == {} and q["rekeyed_streams"] == 1


def test_uncaptured_callee_synthesizes_stub(bench):
    logs = bench._capture_workload(3, churn_at=99)
    del logs["search"]  # callee host not captured
    src = CollectorSource(logs)
    stubs = [ev.span for ev in src.events()
             if ev.span.process_id.startswith("ext:")]
    assert len(stubs) == 3
    for s in stubs:
        assert s.span_kind == "server" and len(s.references) == 1
    # the stub's process resolves to the authority-derived service
    ev = next(ev for ev in src.events()
              if ev.span.process_id.startswith("ext:"))
    assert ev.processes[ev.span.process_id] == "search"


def test_injected_skew_is_detected_and_corrected(bench, monkeypatch):
    """The 'skew' chaos site offsets one source's raw clock; the fit
    must detect it (gauge-visible offset ≈ injection) and correction
    must restore parent⊇child containment on solver event time."""
    monkeypatch.setenv("TW_SKEW_CHAOS_US", "300000")
    with faults.override("skew:1.0:max=1", seed=0):
        src = CollectorSource(bench._capture_workload(8, churn_at=99))
    offs = src.capture_quality()["skew_us"]
    assert max(abs(v) for v in offs.values()) == pytest.approx(
        300000, rel=0.05)
    spans = {ev.span.sid: ev.span for ev in src.events()}
    for s in spans.values():
        if s.span_kind == "server" and s.references:
            parent = spans[s.references[0][1]]
            assert parent.start_mus <= s.start_mus
            assert s.end_mus <= parent.end_mus
    # raw capture stamps keep the uncorrected clock: for the skewed
    # source they differ from event time by the fitted offset
    skewed = [ev for ev in src.events()
              if abs(offs.get(ev.span.process_id, 0.0)) > 1]
    assert skewed
    for ev in skewed:
        assert abs((ev.capture_us - ev.event_us)
                   - offs[ev.span.process_id]) < 1e-6


def test_capture_fault_site_drops_chunks_counted(bench):
    with faults.override("capture:1.0:max=2", seed=5):
        src = CollectorSource(bench._capture_workload(5, churn_at=99))
    q = src.capture_quality()
    assert q["loss"].get("dropped_chunk", 0) >= 2
    # the injector stayed a state perturbation: spans still flowed
    assert q["delivered_spans"] > 0


# ---------------------------------------------------------------------------
# partial capture + orphan bounds
# ---------------------------------------------------------------------------

def _truncated_logs(bench, n=4, drop_lines=1):
    logs = bench._capture_workload(n, churn_at=99)
    lines = logs["search"].splitlines()
    logs["search"] = "\n".join(lines[:-drop_lines])
    return logs


def test_partial_policy_synthetic_closes_out_half_open(bench,
                                                       monkeypatch):
    monkeypatch.setenv("TW_COLLECTOR_PARTIAL", "synthetic")
    src = CollectorSource(_truncated_logs(bench))
    q = src.capture_quality()
    assert q["loss"].get("half_open", 0) == 1
    assert "half_open_dropped" not in q["loss"]
    assert q["synthetic_spans"] == 1
    assert q["loss_rate"] > 0
    # the synthetic closeout still became a span event
    search_servers = [ev for ev in src.events()
                      if ev.span.process_id == "search"]
    assert len(search_servers) == 4


def test_partial_policy_deadletter_drops_half_open(bench, monkeypatch):
    monkeypatch.setenv("TW_COLLECTOR_PARTIAL", "deadletter")
    src = CollectorSource(_truncated_logs(bench))
    q = src.capture_quality()
    assert q["loss"].get("half_open", 0) == 1
    assert q["loss"].get("half_open_dropped", 0) == 1
    assert q["synthetic_spans"] == 0
    search_servers = [ev for ev in src.events()
                      if ev.span.process_id == "search"]
    assert len(search_servers) == 3


def test_orphan_buffer_bound_evicts_oldest(monkeypatch):
    """More open exchanges than TW_COLLECTOR_ORPHANS: the oldest is
    evicted and counted; the capture never grows unbounded state."""
    from traceweaver_tpu.collector.hpack import Encoder
    from traceweaver_tpu.collector.http2 import (
        FLAG_END_HEADERS,
        PREFACE,
        SETTINGS,
    )

    monkeypatch.setenv("TW_COLLECTOR_ORPHANS", "2")

    def frame(ftype, flags, stream_id, payload):
        return (len(payload).to_bytes(3, "big") + bytes([ftype, flags])
                + stream_id.to_bytes(4, "big") + payload)

    enc = Encoder()
    counters = CaptureCounters()
    ing = CaptureIngest("svc", counters)
    blob = PREFACE + frame(SETTINGS, 0, 0, b"")
    for sid in (1, 3, 5, 7):
        blob += frame(0x1, FLAG_END_HEADERS, sid, enc.encode([
            (":method", "GET"), (":path", "/x"), (":authority", "y")]))
    ing._on_payload((4, 0), "in", blob, 1000.0)
    assert counters.loss["svc"].get("orphan_evicted", 0) == 2
    ing.finish()
    # the surviving two closed out as half-open at end of capture
    assert counters.loss["svc"].get("half_open", 0) == 2


# ---------------------------------------------------------------------------
# source spec + live mode
# ---------------------------------------------------------------------------

def test_parse_source_spec_collector_file(bench, tmp_path):
    from traceweaver_tpu.stream.sources import parse_source_spec

    logs = bench._capture_workload(3, churn_at=99)
    path = tmp_path / "frontend.log"
    path.write_text(logs["frontend"])
    src = parse_source_spec(f"collector:{path}?service=frontend")
    assert isinstance(src, CollectorSource)
    assert len(src) > 0
    assert {ev.span.process_id for ev in src.events()} >= {"frontend"}

    # directory mode: every log file is one source (one clock each)
    d = tmp_path / "caps"
    d.mkdir()
    for name, text in logs.items():
        (d / f"{name}.log").write_text(text)
    multi = parse_source_spec(f"collector:{d}")
    assert sorted(multi._ingests) == ["frontend", "search"]

    # the error text surfaces the collector ingress
    with pytest.raises(ValueError, match="collector:"):
        parse_source_spec("bogus:/nowhere")
    with pytest.raises(ValueError, match="no such file"):
        parse_source_spec("collector:/nowhere/missing.log")


def test_iter_live_emits_incrementally(bench):
    """Live single-source mode: spans come out as exchanges complete,
    not at end-of-log."""
    logs = bench._capture_workload(4, churn_at=99)
    lines = logs["frontend"].splitlines()
    seen_at = []
    gen = iter_live(iter(lines), "frontend")
    count = 0
    for ev in gen:
        count += 1
        seen_at.append(ev.arrival_us)
    # 4 roots + 4 clients + 4 stub callees (single-source = stub mode)
    assert count == 12
    assert seen_at == sorted(seen_at)


def test_collector_knobs_registered_typed_and_ranged():
    for name in ("TW_COLLECTOR_PARTIAL", "TW_COLLECTOR_ORPHANS",
                 "TW_COLLECTOR_SERVICE", "TW_SKEW_MIN_PAIRS",
                 "TW_SKEW_MAX_US", "TW_SKEW_CHAOS_US"):
        assert name in knobs.REGISTRY, name
    assert knobs.REGISTRY["TW_COLLECTOR_PARTIAL"].choices == (
        "synthetic", "deadletter")
    assert knobs.REGISTRY["TW_COLLECTOR_ORPHANS"].lo == 1
    assert knobs.REGISTRY["TW_SKEW_MAX_US"].lo == 0.0
    # capture/skew are legal fault sites with per-seed determinism
    plan = faults.parse_faults("capture:0.5,skew:1.0:max=1", seed=2)
    assert plan.should_fail("skew") and not plan.should_fail("skew")


# ---------------------------------------------------------------------------
# events surface
# ---------------------------------------------------------------------------

def test_capture_events_tail_like_fault_ladder(bench, tmp_path, capsys):
    """capture_loss / clock_skew / capture_churn events land in the
    TW_EVENTS sink and `cli events` tails them (incl. --kind filter),
    exactly like fault-ladder and adapt events."""
    from traceweaver_tpu.obs import events as obs_events

    sink = tmp_path / "events.jsonl"
    prev = obs_events.install(obs_events.EventLog(str(sink)))
    try:
        # clean replay: churn (rekey) + skew-fit events
        CollectorSource(bench._capture_workload(4, churn_at=2))
        # faulted replay: chunk-loss events (drop a mid-capture chunk,
        # not the first preface — dead directions emit no churn)
        with faults.override("capture:0.3:max=2", seed=1):
            CollectorSource(bench._capture_workload(4, churn_at=99))
    finally:
        obs_events.install(prev)
    kinds = {json.loads(line)["kind"] for line in sink.read_text()
             .splitlines()}
    assert "capture_loss" in kinds
    assert "capture_churn" in kinds
    assert "clock_skew" in kinds
    for kind in ("capture_loss", "clock_skew"):
        assert kind in obs_events.KNOWN_KINDS
        rc = obs_events.tail_main([str(sink), "--kind", kind, "-n", "0"])
        assert rc == 0
        out = capsys.readouterr().out
        assert f"{kind}/" in out


# ---------------------------------------------------------------------------
# stream emission: loss-discounted confidence
# ---------------------------------------------------------------------------

def test_confidence_discounted_by_observed_loss(bench, tmp_path):
    from traceweaver_tpu.stream.service import (
        StreamConfig,
        StreamingReconstructor,
        TraceSink,
    )

    logs = _truncated_logs(bench, n=6, drop_lines=3)
    src = CollectorSource(logs)
    rate = src.capture_quality()["loss_rate"]
    assert rate > 0
    cfg = StreamConfig(window_us=0.2e6, overlap_us=0.05e6,
                       ooo_bound_us=0.02e6, verbose=False,
                       checkpoint_every=10_000)
    sink = TraceSink(str(tmp_path / "out.jsonl"))
    svc = StreamingReconstructor(src, cfg, sink=sink)
    summary = svc.run()
    # the summary carries the capture ledger
    assert summary["capture"]["loss_rate"] == rate
    saw_capture = False
    for raw in (tmp_path / "out.jsonl").read_text().splitlines():
        rec = json.loads(raw)
        tw = rec.get("tw.confidence")
        if not tw:
            continue
        assert tw["capture"]["discount"] == pytest.approx(1.0 - rate)
        saw_capture = True
        for tconf in tw["traces"].values():
            if tconf is not None:
                assert tconf["conf"] <= 1.0 - rate + 1e-9
    assert saw_capture, "no emitted record carried the capture block"


# ---------------------------------------------------------------------------
# serve ingestion mode
# ---------------------------------------------------------------------------

@pytest.mark.serve
def test_serve_capture_endpoint_roundtrip(bench, tmp_path):
    import threading
    import urllib.request

    from traceweaver_tpu.serve import ServeConfig, TenantService, make_server

    service = TenantService(ServeConfig(
        window_us=0.2e6, overlap_us=0.05e6, ooo_bound_us=0.02e6,
        verbose=False, pump_windows=10 ** 9,
        state_dir=str(tmp_path / "serve_state")))
    server = make_server(service, port=0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.port}"

    def call(method, path, data=None, ctype="application/json"):
        req = urllib.request.Request(base + path, data=data, method=method)
        if data:
            req.add_header("Content-Type", ctype)
        with urllib.request.urlopen(req, timeout=120) as resp:
            return json.loads(resp.read())

    logs = bench._capture_workload(6, churn_at=3)
    try:
        # the multi-source bundle form: one post carries every host's
        # capture so cross-source exchanges join (no duplicate roots)
        out = call("POST", "/api/v1/tenants/cap/capture",
                   json.dumps({"sources": logs}).encode())
        assert out["ingested_spans"] == 18
        assert out["rekeyed_streams"] == 1
        flushed = call("POST", "/api/v1/tenants/cap/flush")
        assert flushed["solved_windows"] >= 1
        traces = call("GET", "/api/v1/tenants/cap/traces")
        assert traces["n_traces"] == 6
        rec = call("GET",
                   f"/api/v1/tenants/cap/traces/{traces['trace_ids'][0]}")
        assert rec["n_spans"] == 3
        # single-source text form: stub-mode ingestion on a second tenant
        out2 = call("POST", "/api/v1/tenants/cap2/capture?source=frontend",
                    logs["frontend"].encode(), ctype="text/plain")
        assert out2["ingested_spans"] == 18  # roots + clients + stubs
    finally:
        server.shutdown()
        server.server_close()
    service.drain()
