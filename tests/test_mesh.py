"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax


@pytest.fixture(scope="module")
def mesh8():
    from traceweaver_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


def _example(B, **kw):
    import __graft_entry__ as ge

    return ge._example_arrays(B=B, **kw)


def test_shard_solve_matches_single_device(mesh8):
    from traceweaver_tpu.algorithms.weaver_tpu import solve_windows
    from traceweaver_tpu.parallel.mesh import shard_solve_windows
    import __graft_entry__ as ge

    arrays = _example(B=16, W=8, E=2, M=8)
    sharded = shard_solve_windows(arrays, mesh8, n_sinkhorn=20)
    single = solve_windows(
        *(arrays[k] for k in ge.ARG_ORDER), n_sinkhorn=20
    )
    np.testing.assert_array_equal(sharded[0], np.asarray(single[0]))


def test_shard_solve_pads_ragged_batch(mesh8):
    from traceweaver_tpu.parallel.mesh import shard_solve_windows

    arrays = _example(B=13, W=8, E=2, M=8)  # not a multiple of 8
    out = shard_solve_windows(arrays, mesh8, n_sinkhorn=20)
    assert out[0].shape[0] == 13


def test_em_step_sharded_recovers_means(mesh8):
    from traceweaver_tpu.parallel.mesh import em_step_sharded

    arrays = _example(B=16, W=8, E=2, M=8)
    assign, dists = em_step_sharded(arrays, mesh8, n_sinkhorn=20)
    assert assign.shape == (16, 2, 8)

    def mix_mean(w, mu):
        return float((w * mu).sum() / max(w.sum(), 1e-9))

    # all three production edge families come back as finite mixtures
    for fam in ("in", "edge", "ret"):
        for a in dists[fam]:
            assert np.isfinite(a).all(), fam
    # (in -> e0) synthetic delay is 300 ± 30 (e0 is the only root)
    in_w, in_mu, in_sd = dists["in"]
    assert abs(mix_mean(in_w[0], in_mu[0]) - 300.0) < 50.0
    # DAG edge (e0 -> e1): consecutive-call gap is 100 ± 50
    ed_w, ed_mu, _ = dists["edge"]
    assert abs(mix_mean(ed_w[1, 0], ed_mu[1, 0]) - 100.0) < 80.0
    assert (in_sd[0] > 0).all()


def test_flagship_identical_on_1_vs_8_devices(mesh8, hotel_store):
    """WeaverTPU with the mesh wired in must reproduce the single-device
    assignments exactly (windows are independent subproblems; sharding
    only changes placement)."""
    from traceweaver_tpu.algorithms.weaver_tpu import WeaverTPU
    from traceweaver_tpu.ingest import build_service_problem, infer_invocation_dag
    from traceweaver_tpu.metrics import get_ground_truth

    store = hotel_store
    for svc in ("frontend", "search"):
        prob = build_service_problem(store, svc)
        if prob.skipped:
            continue
        ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
        dag = infer_invocation_dag(
            prob.in_span_partitions, prob.out_span_partitions, ta, store)
        args = ("MaxScoreBatchSubsetWithSkips", svc, prob.in_span_partitions,
                prob.out_span_partitions, False, [], ta, dag)
        sharded = WeaverTPU(store.all_spans, store.all_processes,
                            mesh=mesh8).FindAssignments(*args)
        single = WeaverTPU(store.all_spans,
                           store.all_processes).FindAssignments(*args)
        assert sharded[0] == single[0], svc  # assignments
        assert sharded[2] == single[2], svc  # not_best_count


def test_fleet_identical_on_1_vs_8_devices(mesh8, hotel_store):
    """The PRODUCTION fleet path under a mesh: every dispatch group's
    window-batch axis sharded over 8 devices must reproduce the
    single-device fleet assignments service-for-service (padded rows are
    invalid everywhere; the refit's cross-shard window gather lowers to
    collectives under XLA SPMD)."""
    from traceweaver_tpu.algorithms.fleet import FleetItem, solve_fleet
    from traceweaver_tpu.ingest import (
        build_service_problem, infer_invocation_dag,
    )
    from traceweaver_tpu.metrics import get_ground_truth

    items = []
    for svc in hotel_store.out_spans_by_process:
        prob = build_service_problem(hotel_store, svc)
        if prob.skipped:
            continue
        ta = get_ground_truth(prob.in_span_partitions,
                              prob.out_span_partitions)
        dag = infer_invocation_dag(prob.in_span_partitions,
                                   prob.out_span_partitions, ta,
                                   hotel_store)
        items.append(FleetItem(svc, prob.in_span_partitions,
                               prob.out_span_partitions, ta, dag,
                               store=hotel_store))
    assert len(items) >= 2
    single = solve_fleet(items)
    stats = {}
    sharded = solve_fleet(items, mesh=mesh8, stats=stats)
    assert stats.get("fleet_dispatches", 0) >= 1
    # convergence compaction covers the sharded path too: the flag-only
    # fetch (O(B) bytes) and the per-shard-bucketed redispatch must have
    # engaged on this recorded workload
    assert stats.get("compact_windows_total", 0) > 0
    assert stats.get("d2h_bytes_flags", 0) > 0
    for it, s, m in zip(items, single, sharded):
        assert m[0] == s[0], f"mesh fleet diverged on {it.svc}"
        assert m[2] == s[2] and m[4] == s[4] and m[5] == s[5]


def test_mesh_flag_fetch_coalesced_and_batch_pow2_bucketed(mesh8):
    """ISSUE 15 satellites on a SYNTHETIC workload (no datasets): the
    mesh path's compaction flag fetch is ONE ledgered transfer per
    dispatch group (device-side shard gather, ``coalesce_to_device0``)
    billed under d2h_bytes_flags like the single-device path, the mesh
    batch axis pads to bucket_rows_per_shard (pow2 rows per shard — the
    bound that puts the sharded family inside the AOT lattice), and the
    sharded solve stays output-identical to single-device."""
    import sys

    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from test_pipeline import _mixed_items

    from traceweaver_tpu.algorithms.fleet import solve_fleet

    single = solve_fleet(_mixed_items(), stats={})
    stats = {}
    sharded = solve_fleet(_mixed_items(), mesh=mesh8, stats=stats)
    assert stats.get("compact_windows_total", 0) > 0
    # one coalesced fetch per compacted pass; each fetch is the padded
    # [B] bool flag vector, so the byte ledger equals the window count
    assert stats.get("d2h_flag_fetches", 0) > 0
    assert stats["d2h_bytes_flags"] == stats["compact_windows_total"]
    # every mesh dispatch's padded batch is pow2 rows per shard
    assert stats["compact_windows_total"] % 8 == 0
    for s, m in zip(single, sharded):
        assert m[0] == s[0] and m[1] == s[1] and m[2:] == s[2:]
