"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax


@pytest.fixture(scope="module")
def mesh8():
    from traceweaver_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


def _example(B, **kw):
    import __graft_entry__ as ge

    return ge._example_arrays(B=B, **kw)


def test_shard_solve_matches_single_device(mesh8):
    from traceweaver_tpu.algorithms.weaver_tpu import solve_windows
    from traceweaver_tpu.parallel.mesh import shard_solve_windows
    import __graft_entry__ as ge

    arrays = _example(B=16, W=8, E=2, M=8)
    sharded = shard_solve_windows(arrays, mesh8, n_sinkhorn=20)
    single = solve_windows(
        *(arrays[k] for k in ge.ARG_ORDER), n_sinkhorn=20
    )
    np.testing.assert_array_equal(sharded[0], np.asarray(single[0]))


def test_shard_solve_pads_ragged_batch(mesh8):
    from traceweaver_tpu.parallel.mesh import shard_solve_windows

    arrays = _example(B=13, W=8, E=2, M=8)  # not a multiple of 8
    out = shard_solve_windows(arrays, mesh8, n_sinkhorn=20)
    assert out[0].shape[0] == 13


def test_em_step_sharded_recovers_means(mesh8):
    from traceweaver_tpu.parallel.mesh import em_step_sharded

    arrays = _example(B=16, W=8, E=2, M=8)
    assign, new_mu, new_sd = em_step_sharded(arrays, mesh8, n_sinkhorn=20)
    assert assign.shape == (16, 2, 8)
    # synthetic delays are 300(e+1) ± 30; psum'd refit must land nearby
    assert abs(new_mu[0, 0] - 300.0) < 50.0
    assert abs(new_mu[1, 0] - 600.0) < 50.0
    assert (new_sd[:, 0] > 0).all()
