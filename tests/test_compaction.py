"""Convergence compaction must be output-identical to the uncompacted path.

The fleet dispatcher runs each solve pass as a warm dispatch (capped at
TW_SWEEP_WARM sweeps) plus a full-sweep redispatch of only the windows
whose Gauss-Seidel assignments were not yet a fixed point
(fleet._compacted_pass). Converged windows keep their warm output — a
reproducing sweep is a fixed point, so extra sweep budget cannot change
it — and stragglers rerun from sweep 0; both halves are therefore
bit-identical to one full-budget dispatch, and the two-pass EM flow's
refit (its own dispatch, weaver_tpu.refit_fleet_params) must match the
refit solve_em_fleet fuses in-graph. These tests pin all of that down on
synthetic fleet tensors (no dataset dependency) and at the solve_fleet
level on synthetic span problems.
"""

import numpy as np
import pytest

import jax

import traceweaver_tpu.algorithms.fleet as fleet_mod
from traceweaver_tpu.algorithms.weaver_tpu import (
    solve_em_fleet,
    solve_windows_fleet,
)

jax.config.update("jax_platforms", "cpu")


def _fleet_tensors(B=6, E=3, W=8, M=8, P=1, K=3, seed=0, n_easy=3):
    """Synthetic [B, E, W, M] fleet batch: the first ``n_easy`` windows
    hold well-separated spans (forced assignments — the sweep loop hits
    its fixed point within two sweeps), the rest heavily-overlapping
    noisy spans (stragglers that need the full sweep budget)."""
    rng = np.random.default_rng(seed)
    in_start = np.zeros((B, W), np.float32)
    in_end = np.zeros((B, W), np.float32)
    out_start = np.zeros((B, E, M), np.float32)
    for b in range(B):
        if b < n_easy:
            # sequential, disjoint in-spans; one obvious candidate each
            starts = np.arange(W, dtype=np.float32) * 1000.0
            in_start[b] = starts
            in_end[b] = starts + 800.0
            for e in range(E):
                out_start[b, e] = starts + 10.0 * (e + 1) + rng.normal(
                    0, 0.5, W)
        else:
            starts = np.sort(rng.uniform(0, 200, W)).astype(np.float32)
            in_start[b] = starts
            in_end[b] = starts + 400.0
            for e in range(E):
                out_start[b, e] = np.sort(
                    starts + 10.0 * (e + 1) + rng.normal(0, 30, W))
    out_end = out_start + 8.0
    batch = dict(
        in_start=in_start, in_end=in_end, in_valid=np.ones((B, W), bool),
        out_start=out_start, out_end=out_end,
        out_valid=np.ones((B, E, M), bool),
        skip_cap=np.zeros((B, E), np.float32),
        force_skip=np.zeros((B, E, W), bool),
    )
    pidx = np.zeros((B,), np.int32)
    pred = np.zeros((P, E, E), bool)
    for e in range(1, E):
        pred[:, e, e - 1] = True
    root = np.zeros((P, E), bool); root[:, 0] = True
    last = np.zeros((P, E), bool); last[:, E - 1] = True
    ew = np.zeros((P, E, E, K), np.float32); ew[..., 0] = 1
    emu = np.full((P, E, E, K), 10.0, np.float32)
    esd = np.full((P, E, E, K), 5.0, np.float32)
    iw = np.zeros((P, E, K), np.float32); iw[..., 0] = 1
    imu = np.full((P, E, K), 10.0, np.float32)
    isd = np.full((P, E, K), 5.0, np.float32)
    params = dict(pred_mask=pred, root_mask=root, is_last=last,
                  edge_wt=ew, edge_mu=emu, edge_sd=esd,
                  in_wt=iw, in_mu=imu, in_sd=isd,
                  ret_wt=iw.copy(), ret_mu=imu.copy(), ret_sd=isd.copy())
    tables = tuple(params[k] for k in (
        "pred_mask", "root_mask", "is_last",
        "edge_wt", "edge_mu", "edge_sd",
        "in_wt", "in_mu", "in_sd", "ret_wt", "ret_mu", "ret_sd"))
    window_rows = np.arange(B, dtype=np.int32)[None, :]
    window_valid = np.ones((1, B), bool)
    return batch, params, tables, pidx, window_rows, window_valid


HYPERS = dict(epsilon=1.0, n_sinkhorn=20, sinkhorn_tol=1e-3,
              max_preds=1, max_succs=1)


@pytest.mark.parametrize("warm", [1, 2, 3])
def test_compacted_pass_bit_identical(warm):
    batch, _, tables, pidx, _, _ = _fleet_tensors()
    args = tuple(batch[k] for k in fleet_mod._BATCH_KEYS) + (pidx,)
    full, _flags = solve_windows_fleet(*args, *tables, n_sweeps=5, **HYPERS)
    full = np.asarray(full)
    stats = {}
    compacted = fleet_mod._compacted_pass(
        batch, pidx, tables, 5, warm, HYPERS, stats)
    assert np.array_equal(full, compacted)
    assert stats["compact_windows_total"] == batch["in_start"].shape[0]
    # warm=1 can never certify (sweep 0 always reports changed), so the
    # counter must show a full redispatch there
    if warm == 1:
        assert (stats["compact_windows_redispatched"]
                == stats["compact_windows_total"])


def test_compaction_actually_compacts_easy_windows():
    """The easy windows' assignments are a fixed point within the warm
    budget, so the redispatch batch must be a strict subset — otherwise
    compaction never saves the VPU cycles it exists to save (a vacuous
    bit-identity test would hide that regression)."""
    batch, _, tables, pidx, _, _ = _fleet_tensors()
    stats = {}
    fleet_mod._compacted_pass(batch, pidx, tables, 5, 3, HYPERS, stats)
    assert stats["compact_windows_redispatched"] < stats[
        "compact_windows_total"]


def test_compacted_two_pass_em_bit_identical():
    """warm/full pass0 -> standalone refit dispatch -> warm/full pass1
    must reproduce the single fused solve_em_fleet program bitwise."""
    batch, params, tables, pidx, wr, wv = _fleet_tensors()
    args = tuple(batch[k] for k in fleet_mod._BATCH_KEYS) + (pidx,)
    fused, _flags = solve_em_fleet(*args, wr, wv, *tables, n_sweeps=5,
                                   **HYPERS)
    fused = np.asarray(fused)
    stats = {}
    compacted = fleet_mod._solve_group_compacted(
        batch, pidx, params, tables, wr, wv, n_passes=2, n_sweeps=5,
        warm=2, hypers=HYPERS, stats=stats)
    assert np.array_equal(fused, compacted)


def _synthetic_items(n_traces=60, seed=0):
    """FleetItems over synthetic span streams: one service, a 2-endpoint
    chain DAG, bursts of overlapping requests so perfect cuts yield
    several multi-span windows."""
    import networkx as nx

    from traceweaver_tpu.algorithms.fleet import FleetItem
    from traceweaver_tpu.spans import Span

    rng = np.random.default_rng(seed)
    in_spans, a_spans, b_spans = [], [], []
    ta = {"A": {}, "B": {}}
    t = 0.0
    for i in range(n_traces):
        # bursts of 4: overlapping arrivals, then a gap (window boundary)
        t += 30.0 if i % 4 else 5000.0
        start = t
        dur = 400.0
        s_in = Span(f"t{i}", "in", start, dur, "op", [], "svc", "server")
        a_start = start + 10 + rng.normal(0, 2)
        s_a = Span(f"t{i}", "a", a_start, 50.0, "opA", [], "svc", "client")
        b_start = a_start + 50 + 15 + rng.normal(0, 2)
        s_b = Span(f"t{i}", "b", b_start, 50.0, "opB", [], "svc", "client")
        in_spans.append(s_in)
        a_spans.append(s_a)
        b_spans.append(s_b)
        ta["A"][s_in.GetId()] = s_a.GetId()
        ta["B"][s_in.GetId()] = s_b.GetId()
    dag = nx.DiGraph()
    dag.add_edge("A", "B")
    return [FleetItem("svc", {"IN": in_spans}, {"A": a_spans, "B": b_spans},
                      ta, dag)]


def test_solve_fleet_compaction_toggle_identical(monkeypatch):
    items = _synthetic_items()

    monkeypatch.setenv("TW_COMPACT", "0")
    base = fleet_mod.solve_fleet(items, stats={})

    monkeypatch.setenv("TW_COMPACT", "1")
    monkeypatch.setenv("TW_SWEEP_WARM", "2")
    stats = {}
    compacted = fleet_mod.solve_fleet(items, stats=stats)

    # compaction must actually have run on this workload
    assert stats.get("compact_windows_total", 0) > 0
    for b, c in zip(base, compacted):
        assert b[0] == c[0]   # assignments
        assert b[1] == c[1]   # top-k
        assert b[2:] == c[2:]  # counters
