"""Fault-injection / solve-supervisor tests (tier-1, CPU).

Contracts covered (ISSUE 5):

- deterministic, seeded injection at every registered site; typo'd
  specs raise (the ops/precision.py rule);
- with ``TW_FAULTS`` unset the solve runs the HEAD program bit-identically
  and the supervisor ledger stays empty;
- transient dispatch faults recover through the degradation ladder with
  OUTPUT-IDENTICAL results (every rung except quarantine is an exact
  re-computation path);
- the ladder walks in order: retry -> bisect -> XLA -> host fallback ->
  quarantine, each step ledgered;
- checkpoint integrity: CRC trailer, v1 back-compat, corrupt/truncated
  primary falls back to the rotated last-good generation (counted, not
  fatal), kill/resume through a truncated checkpoint still reproduces
  the uninterrupted run byte-for-byte;
- dead-letter conservation: every sealed-and-solved window is either
  emitted or dead-lettered — never silently lost — and a kill/resume
  under injected faults (p=0.2, the acceptance bar) loses zero windows;
- the micro-batch watchdog times out, retries, and poisons with
  accounting;
- malformed ingest records dead-letter instead of raising (strict mode
  restores the raise);
- the TW_* knob registry raises on typos and warns on unknown names.
"""

import json
import os
import pickle
import time

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")

from traceweaver_tpu.runtime import faults, knobs  # noqa: E402

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_fault_state(monkeypatch):
    """Every test starts with no plan and a fresh RNG; the env knobs the
    tests set are scoped to the test."""
    monkeypatch.delenv("TW_FAULTS", raising=False)
    monkeypatch.delenv("TW_FAULTS_SEED", raising=False)
    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# spec parsing + injector units
# ---------------------------------------------------------------------------

def test_fault_spec_parsing_and_typos():
    assert faults.parse_faults("") is None
    assert faults.parse_faults("  ") is None
    plan = faults.parse_faults("dispatch:0.25,fetch:1.0:max=3", seed=5)
    assert plan.sites["dispatch"].p == 0.25
    assert plan.sites["fetch"].max == 3
    assert plan.seed == 5
    with pytest.raises(ValueError, match="unknown site"):
        faults.parse_faults("dispathc:0.5")  # typo'd site must raise
    with pytest.raises(ValueError, match="not a number"):
        faults.parse_faults("dispatch:lots")
    with pytest.raises(ValueError, match="not in"):
        faults.parse_faults("dispatch:1.5")
    with pytest.raises(ValueError, match="unknown option"):
        faults.parse_faults("dispatch:0.5:after=3")
    with pytest.raises(ValueError, match="duplicate"):
        faults.parse_faults("dispatch:0.5,dispatch:0.2")


def test_injection_is_deterministic_per_seed_at_every_site():
    for site in faults.SITES:
        a = faults.parse_faults(f"{site}:0.5", seed=11)
        b = faults.parse_faults(f"{site}:0.5", seed=11)
        seq_a = [a.should_fail(site) for _ in range(64)]
        seq_b = [b.should_fail(site) for _ in range(64)]
        assert seq_a == seq_b, f"site {site}: seeded draws not reproducible"
        assert any(seq_a) and not all(seq_a)
        # other sites never draw
        assert not a.should_fail("dispatch" if site != "dispatch"
                                 else "fetch")


def test_max_caps_injections_per_site():
    plan = faults.parse_faults("dispatch:1.0:max=2", seed=0)
    fails = [plan.should_fail("dispatch") for _ in range(5)]
    assert fails == [True, True, False, False, False]
    assert plan.injected["dispatch"] == 2


def test_maybe_fail_env_plan_and_override(monkeypatch):
    faults.maybe_fail("dispatch")  # unset: no-op
    monkeypatch.setenv("TW_FAULTS", "dispatch:1.0")
    with pytest.raises(faults.FaultError):
        faults.maybe_fail("dispatch")
    faults.maybe_fail("fetch")  # other sites still clean
    with faults.override("fetch:1.0") as plan:
        with pytest.raises(faults.FaultError):
            faults.maybe_fail("fetch")
        faults.maybe_fail("dispatch")  # override REPLACES the env plan
        assert plan.injected["fetch"] == 1
    with pytest.raises(faults.FaultError):
        faults.maybe_fail("dispatch")  # env plan back in force


def test_transient_classification():
    assert faults.is_transient_fault(faults.FaultError("x"))
    assert faults.is_transient_fault(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert not faults.is_transient_fault(ValueError("bad shape"))
    assert not faults.is_transient_fault(RuntimeError("plain bug"))

    class XlaRuntimeError(RuntimeError):
        pass

    assert faults.is_transient_fault(XlaRuntimeError("anything"))


# ---------------------------------------------------------------------------
# solve supervisor: ladder + bit-identity (fleet path)
# ---------------------------------------------------------------------------

def _clean_solve():
    from test_pipeline import _mixed_items

    from traceweaver_tpu.algorithms.fleet import solve_fleet

    stats = {}
    out = solve_fleet(_mixed_items(), stats=stats)
    return out, stats


def _assert_same_results(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x[0] == y[0] and x[1] == y[1] and x[2:] == y[2:]


def test_no_fault_ledger_is_empty_and_guard_is_inert(monkeypatch):
    """TW_FAULTS unset: no fault_* key may appear in the stats (the
    supervisor's happy path is the HEAD dispatch flow), and an ACTIVE
    plan that never fires (p=0) must not perturb the output either —
    the injection guard is observation-free."""
    out_clean, stats_clean = _clean_solve()
    assert not any(k.startswith("fault") for k in stats_clean), stats_clean

    monkeypatch.setenv("TW_FAULTS", "dispatch:0.0,fetch:0.0")
    out_guarded, stats_guarded = _clean_solve()
    _assert_same_results(out_clean, out_guarded)
    assert stats_guarded.get("fault_retries", 0) == 0
    assert stats_guarded.get("faults_injected", 0) == 0


def test_transient_dispatch_faults_recover_output_identical(monkeypatch):
    """Injected dispatch+fetch faults at meaningful rates: the solve
    completes through the ladder and the results are IDENTICAL to the
    unfaulted run (retry/bisect/XLA/host are all exact paths; no spec
    entry for 'host' means quarantine is unreachable)."""
    out_clean, _ = _clean_solve()
    monkeypatch.setenv("TW_FAULTS", "dispatch:0.5,fetch:0.2")
    monkeypatch.setenv("TW_FAULTS_SEED", "7")
    out_faulted, stats = _clean_solve()
    _assert_same_results(out_clean, out_faulted)
    assert stats.get("faults_injected", 0) > 0
    assert stats.get("fault_retries", 0) > 0
    assert stats.get("fault_quarantined", 0) == 0


def _check_ladder_order(ladder):
    """Each escalation event must be preceded by the rung below it."""
    order = {"retry": 0, "bisect": 1, "xla": 2, "host": 3, "quarantine": 4}
    assert ladder, "empty ladder"
    seen_rungs = set()
    for ev in ladder:
        assert ev in order, ev
        seen_rungs.add(ev)
    # escalations only happen after the cheaper rung was attempted
    for hi, lo in (("bisect", "retry"), ("xla", "retry"),
                   ("host", "xla"), ("quarantine", "host")):
        if hi in seen_rungs:
            assert ladder.index(lo) < ladder.index(hi), (
                f"{hi} before first {lo}: {ladder}")


def test_ladder_order_retry_bisect_xla_host_quarantine(monkeypatch):
    """Permanent dispatch+host failure: every item must walk retry ->
    (bisect) -> xla -> host -> quarantine, in order, and every item's
    slot must still hold a structurally valid all-NA result."""
    from test_pipeline import _mixed_items

    from traceweaver_tpu.algorithms.fleet import solve_fleet
    from traceweaver_tpu.spans import NA

    monkeypatch.setenv("TW_FAULTS", "dispatch:1.0,host:1.0")
    monkeypatch.setenv("TW_RETRY_MAX", "1")
    # serial dispatcher: the ladder event order is single-threaded
    monkeypatch.setenv("TW_PIPELINE", "0")
    items = _mixed_items()
    stats, q = {}, []
    out = solve_fleet(items, stats=stats, quarantined=q)
    assert sorted(q) == list(range(len(items)))
    assert stats["fault_quarantined"] == len(items)
    assert stats["fault_bisections"] >= 1
    assert stats["fault_xla_fallbacks"] == len(items)
    assert stats["fault_host_fallbacks"] == len(items)
    _check_ladder_order(stats["fault_ladder"])
    for res in out:
        assert res is not None and len(res) == 6
        amaps, _, _, n_in, cands, unassigned = res
        assert unassigned == n_in  # all-NA: the poison marker
        for ep_map in amaps.values():
            assert all(v == NA for v in ep_map.values())


def test_xla_rung_recovers_when_kernel_path_is_the_problem(monkeypatch):
    """A fault budget that dies through all retries but is exhausted by
    the time the XLA rung dispatches: the supervisor must recover on the
    Pallas-free program with output identical to the clean run and never
    reach the host rung."""
    from test_pipeline import _mixed_items

    from traceweaver_tpu.algorithms.fleet import solve_fleet

    out_clean, _ = _clean_solve()
    items = _mixed_items()
    # singleton group per item is not guaranteed; run items one at a time
    # so each ladder is: attempt + TW_RETRY_MAX retries (= 2 draws) then
    # the XLA rung draws past max -> succeeds
    monkeypatch.setenv("TW_RETRY_MAX", "1")
    monkeypatch.setenv("TW_PIPELINE", "0")
    for i, item in enumerate(items):
        faults.reset()
        monkeypatch.setenv("TW_FAULTS", "dispatch:1.0:max=2")
        stats, q = {}, []
        out = solve_fleet([item], stats=stats, quarantined=q)
        assert q == []
        assert stats["fault_xla_fallbacks"] == 1
        assert stats.get("fault_host_fallbacks", 0) == 0
        _assert_same_results([out_clean[i]], out)


def test_serial_and_pipelined_supervisors_agree(monkeypatch):
    """The ladder exists on both dispatch flows: identical fault spec +
    seed under TW_PIPELINE=0 and =1 both complete with clean-identical
    output (thread interleaving may shift which draws hit, but every
    non-quarantine recovery is exact)."""
    out_clean, _ = _clean_solve()
    for pipeline in ("0", "1"):
        faults.reset()
        monkeypatch.setenv("TW_PIPELINE", pipeline)
        monkeypatch.setenv("TW_FAULTS", "dispatch:0.6")
        monkeypatch.setenv("TW_FAULTS_SEED", "13")
        out, stats = _clean_solve()
        _assert_same_results(out_clean, out)
        assert stats.get("fault_retries", 0) > 0, f"pipeline={pipeline}"


# ---------------------------------------------------------------------------
# checkpoint integrity
# ---------------------------------------------------------------------------

def test_checkpoint_crc_roundtrip_and_v1_compat(tmp_path):
    from traceweaver_tpu.stream import checkpoint as cp

    path = str(tmp_path / "ck.pkl")
    cp.save_checkpoint(path, {"value": 42})
    state = cp.load_checkpoint(path)
    assert state["value"] == 42
    assert state["version"] == cp.CHECKPOINT_VERSION == 2

    # a version-1 checkpoint (bare pickle, no trailer) still reads
    v1 = str(tmp_path / "v1.pkl")
    with open(v1, "wb") as f:
        pickle.dump({"version": 1, "value": "old"}, f)
    assert cp.load_checkpoint(v1)["value"] == "old"


def test_corrupt_checkpoint_falls_back_to_last_good(tmp_path, capsys):
    from traceweaver_tpu.stream import checkpoint as cp

    path = str(tmp_path / "ck.pkl")
    cp.save_checkpoint(path, {"gen": 1})
    cp.save_checkpoint(path, {"gen": 2})  # rotates gen1 -> .prev
    assert os.path.exists(path + ".prev")

    # truncation: the trailer length check must catch it
    with open(path, "rb") as f:
        raw = f.read()
    with open(path, "wb") as f:
        f.write(raw[: len(raw) // 2])
    state = cp.load_checkpoint(path)
    assert state["gen"] == 1
    assert state["_recovered_from_prev"] is True

    # bit rot: same length, flipped byte -> CRC catches it
    cp.save_checkpoint(path, {"gen": 3})  # now .prev = the truncated gen?
    cp.save_checkpoint(path, {"gen": 4})  # .prev = gen 3 (good)
    with open(path, "rb") as f:
        raw = bytearray(f.read())
    raw[10] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(raw))
    state = cp.load_checkpoint(path)
    assert state["gen"] == 3

    # both generations corrupt -> fatal, with both causes named
    with open(path + ".prev", "wb") as f:
        f.write(b"garbage")
    with pytest.raises(cp.CheckpointCorrupt):
        cp.load_checkpoint(path)


# ---------------------------------------------------------------------------
# streaming: dead-letter conservation, kill/resume under faults
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def synth_store(tmp_path_factory):
    from traceweaver_tpu.alibaba.synthesize import synthesize_corpus
    from traceweaver_tpu.ingest import load_corpus

    root = tmp_path_factory.mktemp("faults_corpus")
    dirs = synthesize_corpus(str(root / "cg"), n_graphs=1,
                             traces_per_graph=40, seed=7)
    store = load_corpus(dirs[0], fix=5, max_traces=40, cache=False)
    assert store.services()
    return dirs[0], store


def _stream_cfg(**kw):
    from traceweaver_tpu.stream import StreamConfig

    base = dict(window_us=20e6, overlap_us=4e6, ooo_bound_us=1e6,
                grace_us=0.0, checkpoint_every=10_000, verbose=False)
    base.update(kw)
    return StreamConfig(**base)


def _run_stream(store, sink_path=None, cfg=None, max_windows=None):
    from traceweaver_tpu.stream import (
        ReplaySource,
        StreamingReconstructor,
        TraceSink,
    )

    source = ReplaySource(store, ooo_us=50_000.0, seed=1)
    sink = TraceSink(sink_path) if sink_path else None
    svc = StreamingReconstructor(source, cfg or _stream_cfg(), sink=sink)
    summary = svc.run(max_windows=max_windows)
    if sink:
        sink.close()
    return svc, summary


def _assert_window_and_span_conservation(svc, summary):
    """Every solved window was emitted or dead-lettered; every consumed
    span was emitted (owned once), dead-lettered, or counted late."""
    assert (summary["emitted_windows"]
            + summary["deadletter_windows"]
            == svc.scheduler.solved_windows)
    assert (summary["stats"].get("spans_emitted", 0)
            + summary["deadletter_spans"]
            + summary["late_dropped"]
            == summary["consumed"])


def test_dead_letter_conservation_under_full_quarantine(
        synth_store, tmp_path, monkeypatch):
    """Permanent device+host failure: EVERY window becomes a poison
    window, lands in the dead-letter queue (counted AND persisted), and
    span/window conservation holds exactly — emitted + dead-lettered ==
    sealed-and-solved, with nothing silently dropped."""
    _, store = synth_store
    monkeypatch.setenv("TW_FAULTS", "dispatch:1.0,host:1.0")
    monkeypatch.setenv("TW_RETRY_MAX", "0")
    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    out = str(tmp_path / "dlq_run.jsonl")
    svc, summary = _run_stream(store, sink_path=out)
    assert summary["final"]
    assert summary["deadletter_windows"] > 0
    assert summary["emitted_windows"] == 0
    assert summary["faults"]["quarantined"] > 0
    _assert_window_and_span_conservation(svc, summary)
    # the sidecar holds one parseable record per dead-lettered window
    dlq = out + ".deadletter.jsonl"
    assert os.path.exists(dlq)
    with open(dlq) as f:
        recs = [json.loads(line) for line in f]
    assert len(recs) == summary["deadletter_windows"]
    assert all("quarantined" in r["reason"] for r in recs)
    assert summary["deadletter_bytes"] == os.path.getsize(dlq)


def test_kill_resume_under_faults_zero_lost_windows(
        synth_store, tmp_path, monkeypatch):
    """The acceptance bar: dispatch faults at p=0.2, kill after 3
    windows, resume from the checkpoint — the stream completes, every
    sealed window is either emitted or dead-lettered (zero lost), and
    the emitted bytes equal the unfaulted golden run's exactly (every
    recovery rung is output-exact and no 'host' faults are injected, so
    nothing quarantines)."""
    from traceweaver_tpu.stream import (
        ReplaySource,
        StreamingReconstructor,
        TraceSink,
    )

    _, store = synth_store
    golden_path = str(tmp_path / "golden.jsonl")
    _run_stream(store, sink_path=golden_path)
    with open(golden_path, "rb") as f:
        golden = f.read()
    assert golden.count(b"\n") >= 4

    monkeypatch.setenv("TW_FAULTS", "dispatch:0.2,fetch:0.1")
    monkeypatch.setenv("TW_FAULTS_SEED", "3")
    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    ckpt = str(tmp_path / "ck.pkl")
    out_path = str(tmp_path / "chaos.jsonl")
    cfg = _stream_cfg(checkpoint_path=ckpt, checkpoint_every=2)
    svc = StreamingReconstructor(
        ReplaySource(store, ooo_us=50_000.0, seed=1), cfg,
        sink=TraceSink(out_path))
    partial = svc.run(max_windows=3)
    assert not partial["final"]
    svc.sink.close()

    resumed = StreamingReconstructor.resume(
        ckpt, ReplaySource(store, ooo_us=50_000.0, seed=1))
    summary = resumed.run()
    resumed.sink.close()
    assert summary["final"]
    assert summary["faults"]["injected"] > 0, "chaos never engaged"
    assert summary["deadletter_windows"] == 0  # no host faults: no poison
    _assert_window_and_span_conservation(resumed, summary)
    with open(out_path, "rb") as f:
        assert f.read() == golden


def test_truncated_checkpoint_resume_falls_back_and_completes(
        synth_store, tmp_path):
    """Kill, then TRUNCATE the latest checkpoint: resume must fall back
    to the rotated last-good generation (counted in the summary), and
    the final sink bytes must still equal the uninterrupted run's."""
    from traceweaver_tpu.stream import (
        ReplaySource,
        StreamingReconstructor,
        TraceSink,
    )

    _, store = synth_store
    golden_path = str(tmp_path / "golden.jsonl")
    _run_stream(store, sink_path=golden_path)
    with open(golden_path, "rb") as f:
        golden = f.read()

    ckpt = str(tmp_path / "ck.pkl")
    out_path = str(tmp_path / "trunc.jsonl")
    cfg = _stream_cfg(checkpoint_path=ckpt, checkpoint_every=1)
    svc = StreamingReconstructor(
        ReplaySource(store, ooo_us=50_000.0, seed=1), cfg,
        sink=TraceSink(out_path))
    partial = svc.run(max_windows=4)
    assert not partial["final"]
    svc.sink.close()
    assert os.path.exists(ckpt + ".prev")  # >= 2 checkpoints: rotation ran

    with open(ckpt, "rb") as f:
        raw = f.read()
    with open(ckpt, "wb") as f:
        f.write(raw[: len(raw) - 37])  # ate the trailer + tail

    resumed = StreamingReconstructor.resume(
        ckpt, ReplaySource(store, ooo_us=50_000.0, seed=1))
    summary = resumed.run()
    resumed.sink.close()
    assert summary["final"]
    assert summary["faults"]["checkpoint_recovered"] == 1
    with open(out_path, "rb") as f:
        assert f.read() == golden


def test_checkpoint_write_faults_do_not_kill_the_stream(
        synth_store, tmp_path, monkeypatch):
    """Injected checkpoint-I/O failure on every save: the stream runs to
    completion on the last good generation, counting every failure."""
    _, store = synth_store
    monkeypatch.setenv("TW_FAULTS", "checkpoint:1.0")
    cfg = _stream_cfg(checkpoint_path=str(tmp_path / "ck.pkl"),
                      checkpoint_every=1)
    svc, summary = _run_stream(store, cfg=cfg)
    assert summary["final"]
    assert summary["faults"]["checkpoint_failures"] > 0
    assert summary["emitted_windows"] > 0


def test_source_read_faults_retry_without_losing_events(
        synth_store, monkeypatch):
    """Source-read faults retry the same position: nothing is consumed
    by a failed read, so the event count (and everything downstream)
    matches the clean run."""
    _, store = synth_store
    _, clean = _run_stream(store)
    monkeypatch.setenv("TW_FAULTS", "source:0.3")
    monkeypatch.setenv("TW_FAULTS_SEED", "2")
    svc, summary = _run_stream(store)
    assert summary["final"]
    assert summary["faults"]["source_read_retries"] > 0
    assert summary["consumed"] == clean["consumed"]
    assert summary["emitted_windows"] == clean["emitted_windows"]


# ---------------------------------------------------------------------------
# micro-batch watchdog
# ---------------------------------------------------------------------------

def test_scheduler_watchdog_times_out_retries_then_succeeds():
    from traceweaver_tpu.stream.scheduler import MicroBatchScheduler
    from traceweaver_tpu.stream.window import WindowBuffer

    calls = {"n": 0}

    def solve(batch):
        calls["n"] += 1
        if calls["n"] == 1:
            time.sleep(2.0)  # first attempt hangs past the watchdog
        return [b.k for b in batch]

    sched = MicroBatchScheduler(solve, max_pending=4, watchdog_s=0.25,
                                solve_retries=1)
    sched.offer(WindowBuffer(0, 0.0, 1.0))
    out = sched.pump()
    assert out == [0]
    assert sched.solve_timeouts == 1
    assert sched.solve_retried == 1
    assert sched.poisoned_windows == 0
    sched.close()


def test_scheduler_poisons_batch_after_budget_with_accounting():
    from traceweaver_tpu.stream.scheduler import MicroBatchScheduler
    from traceweaver_tpu.stream.window import WindowBuffer

    def solve(batch):
        raise faults.FaultError("injected dispatch death")

    poisoned = []

    def poison(batch, err):
        poisoned.append((len(batch), str(err)))
        return [("poison", b.k) for b in batch]

    sched = MicroBatchScheduler(solve, max_pending=4, solve_retries=2,
                                poison_fn=poison)
    sched.offer(WindowBuffer(0, 0.0, 1.0))
    sched.offer(WindowBuffer(1, 1.0, 2.0))
    out = sched.pump()
    assert out == [("poison", 0), ("poison", 1)]
    assert sched.solve_retried == 2
    assert sched.poisoned_windows == 2
    assert poisoned and poisoned[0][0] == 2


def test_scheduler_propagates_non_transient_errors():
    from traceweaver_tpu.stream.scheduler import MicroBatchScheduler
    from traceweaver_tpu.stream.window import WindowBuffer

    def solve(batch):
        raise ValueError("a bug, not a fault")

    sched = MicroBatchScheduler(solve, poison_fn=lambda b, e: [])
    sched.offer(WindowBuffer(0, 0.0, 1.0))
    with pytest.raises(ValueError, match="a bug"):
        sched.pump()


# ---------------------------------------------------------------------------
# ingest dead-letter + knob registry
# ---------------------------------------------------------------------------

def test_malformed_ingest_records_dead_letter_not_raise(synth_store,
                                                        tmp_path):
    import shutil

    from traceweaver_tpu.ingest import MalformedSpan, load_corpus

    corpus_dir, _ = synth_store
    broken = tmp_path / "broken_corpus"
    shutil.copytree(corpus_dir, broken)
    # append malformed EXTRA records to one trace file: one span missing
    # its spanID, one with a non-numeric duration (trace structure stays
    # intact, so the rest of the file still ingests)
    victim = sorted(p for p in os.listdir(broken) if p.endswith("json"))[0]
    victim = str(broken / victim)
    with open(victim) as f:
        payload = json.load(f)
    spans = payload["data"][0]["spans"]
    no_sid = dict(spans[0])
    no_sid.pop("spanID")
    bad_dur = dict(spans[0], spanID="bad-duration-span",
                   duration="fourteen")
    spans.extend([no_sid, bad_dur])
    with open(victim, "w") as f:
        json.dump(payload, f)
    cache = broken / "time_order_filenames.pickle"
    if cache.exists():
        cache.unlink()

    store = load_corpus(str(broken), fix=5, max_traces=40, cache=False,
                        native="never")
    assert store.ingest_malformed_spans == 2
    assert store.services()  # the good records still loaded

    with pytest.raises(MalformedSpan):
        load_corpus(str(broken), fix=5, max_traces=40, cache=False,
                    native="never", strict=True)


def test_knob_registry_raises_on_typos_and_warns_on_unknown(monkeypatch):
    monkeypatch.setenv("TW_SWEEP_WARM", "abc")
    with pytest.raises(knobs.KnobError):
        knobs.get_int("TW_SWEEP_WARM")
    monkeypatch.setenv("TW_SWEEP_WARM", "0")
    assert knobs.get_int("TW_SWEEP_WARM") == 1  # clamped to declared lo
    monkeypatch.delenv("TW_SWEEP_WARM")
    assert knobs.get_int("TW_SWEEP_WARM") == 2  # declared default

    monkeypatch.setenv("TW_PIPLINE", "0")  # the classic silent typo
    warned = []
    names = knobs.warn_unknown(printer=warned.append)
    assert names == ["TW_PIPLINE"]
    assert warned and "TW_PIPLINE" in warned[0]

    # every knob this repo reads is declared (registry completeness is
    # what makes the unknown-name warning trustworthy)
    for name in ("TW_PIPELINE", "TW_COMPACT", "TW_SWEEP_WARM",
                 "TW_DECODE_WORKERS", "TW_PALLAS_VMEM_CAP", "TW_PRECISION",
                 "TW_FAULTS", "TW_FAULTS_SEED", "TW_RETRY_MAX",
                 "TW_RETRY_BACKOFF_S", "TW_FLEET_BUDGET", "TW_BACKEND"):
        assert name in knobs.REGISTRY, name


def test_fleet_knob_readers_ride_the_registry(monkeypatch):
    import traceweaver_tpu.algorithms.fleet as fleet_mod

    monkeypatch.setenv("TW_SWEEP_WARM", "oops")
    with pytest.raises(knobs.KnobError):
        fleet_mod._compaction_warm()
    monkeypatch.setenv("TW_SWEEP_WARM", "3")
    assert fleet_mod._compaction_warm() == 3
    monkeypatch.setenv("TW_RETRY_MAX", "5")
    assert fleet_mod._retry_max() == 5
