"""Campaign harness tests (ISSUE 15, docs/CAMPAIGN.md).

Corpus-ladder determinism + manifest invariants, plan-spec validation,
the regression-gate compare semantics, the events + tw_campaign_*
observability mirror, and the multislice/mesh integration seams. The
full end-to-end mini campaign (mesh-sharded run -> artifact ->
self-compare -> doctored-regression detection) is the tier-1 smoke in
tests/test_bench_smoke.py.
"""

import copy
import json
import os

import pytest

import jax

jax.config.update("jax_platforms", "cpu")

pytestmark = pytest.mark.campaign


# ---------------------------------------------------------------------------
# corpus ladder: synthesizer determinism + manifest invariants
# ---------------------------------------------------------------------------

def _tree_bytes(root):
    """{relative path: bytes} over a corpus tree (order-independent)."""
    out = {}
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            with open(path, "rb") as f:
                out[rel] = f.read()
    return out


def test_synthesize_same_seed_is_byte_identical(tmp_path):
    """Same seed => byte-identical corpus across runs: the campaign
    cache key is the spec fingerprint, which is only sound if the
    synthesizer is a pure function of it — every Jaeger file, every
    call-graph grouping, and the replica-table pickle must match."""
    from traceweaver_tpu.alibaba.synthesize import synthesize_corpus

    kw = dict(n_graphs=2, traces_per_graph=20, seed=33, base_gap_ms=500,
              n_services=10)
    dirs_a = synthesize_corpus(str(tmp_path / "a"), **kw)
    dirs_b = synthesize_corpus(str(tmp_path / "b"), **kw)
    assert len(dirs_a) == len(dirs_b) > 0
    a, b = _tree_bytes(tmp_path / "a"), _tree_bytes(tmp_path / "b")
    assert sorted(a) == sorted(b)
    diff = [rel for rel in a if a[rel] != b[rel]]
    assert diff == [], f"same-seed corpus diverged on {diff[:5]}"

    # a different seed must actually change the corpus (the ladder's
    # rungs are distinct workloads, not copies)
    synthesize_corpus(str(tmp_path / "c"), **dict(kw, seed=34))
    c = _tree_bytes(tmp_path / "c")
    assert sorted(a) != sorted(c) or any(
        a[rel] != c.get(rel) for rel in a)


def test_build_rung_manifest_matches_recomputed_regimes(tmp_path):
    """Manifest invariants: the regime-mix fields must equal
    service_regime recomputed from the loaded spans, and the span/
    service counts must equal what the stores actually hold."""
    from traceweaver_tpu.campaign.corpus import build_rung
    from traceweaver_tpu.campaign.plan import RungSpec
    from traceweaver_tpu.metrics.accuracy import service_regime

    spec = RungSpec("inv", n_graphs=2, traces_per_graph=25, gap_ms=300,
                    seed=5, n_services=10, source="synthetic")
    corpus = build_rung(spec, str(tmp_path))
    man = corpus.manifest
    assert man["spans"] == sum(len(s.all_spans) for s in corpus.stores)
    assert man["services_solvable"] == len(corpus.problems) > 0
    assert man["call_graphs"] == len(corpus.stores) == 2

    recomputed = {}
    for meta in corpus.problems:
        reg = service_regime(meta["prob"].in_span_partitions,
                             meta["prob"].out_span_partitions)
        recomputed[reg["regime"]] = recomputed.get(reg["regime"], 0) + 1
        # the per-problem regime the runner grades with matches too
        assert meta["regime"]["regime"] == reg["regime"]
        assert meta["regime"]["fan_out"] == reg["fan_out"]
    assert man["regime_mix"] == dict(sorted(recomputed.items()))
    per_service_mix = {}
    for row in man["per_service"]:
        per_service_mix[row["regime"]] = \
            per_service_mix.get(row["regime"], 0) + 1
    assert per_service_mix == man["regime_mix"]


def test_build_rung_reuses_cached_corpus(tmp_path):
    """Second build of the same spec must NOT re-synthesize: the
    manifest fingerprint keys the cache (a 1M-span rung is minutes of
    synthesis)."""
    from traceweaver_tpu.campaign.corpus import build_rung
    from traceweaver_tpu.campaign.plan import RungSpec

    spec = RungSpec("cache", n_graphs=2, traces_per_graph=15, seed=3,
                    n_services=8, source="synthetic")
    first = build_rung(spec, str(tmp_path))
    assert first.cached is False
    trace_file = next(
        os.path.join(dp, f) for dp, _, fs in os.walk(first.root)
        for f in fs if f.endswith(".json") and f != "manifest.json")
    mtime = os.path.getmtime(trace_file)
    second = build_rung(spec, str(tmp_path))
    assert second.cached is True
    assert os.path.getmtime(trace_file) == mtime
    assert second.manifest["spans"] == first.manifest["spans"]

    # a changed spec (different seed) must invalidate, not reuse
    third = build_rung(RungSpec("cache", n_graphs=2, traces_per_graph=15,
                                seed=4, n_services=8, source="synthetic"),
                       str(tmp_path))
    assert third.cached is False


# ---------------------------------------------------------------------------
# plan spec
# ---------------------------------------------------------------------------

def test_plan_validation_raises_on_bad_specs():
    from traceweaver_tpu.campaign.plan import (
        CampaignPlan,
        PlanError,
        RungSpec,
        from_dict,
    )

    with pytest.raises(PlanError):
        CampaignPlan(rungs=[]).validate()  # no rungs
    with pytest.raises(PlanError):
        CampaignPlan(rungs=[RungSpec("a"), RungSpec("a")]).validate()
    with pytest.raises(PlanError):
        CampaignPlan(rungs=[RungSpec("a")], devices=3).validate()
    with pytest.raises(PlanError):
        CampaignPlan(rungs=[RungSpec("a")],
                     knobs={"TW_TYPO": "1"}).validate()
    with pytest.raises(PlanError):
        from_dict({"rungs": [{"name": "a"}], "surprise": 1})
    with pytest.raises(PlanError):
        from_dict({"rungs": [{"name": "a", "surprise": 1}]})
    # round trip: to_dict -> from_dict is the identity on valid plans
    plan = CampaignPlan(rungs=[RungSpec("a"), RungSpec("b", seed=2)],
                        devices=2, slices=2, knobs={"TW_COMPACT": "1"})
    assert from_dict(plan.to_dict()).to_dict() == plan.to_dict()


def test_campaign_knobs_registered_typed_ranged():
    from traceweaver_tpu.runtime import knobs

    for name, typ in [("TW_CAMPAIGN_ROUNDS", "int"),
                      ("TW_CAMPAIGN_WARMUP_MAX", "int"),
                      ("TW_CAMPAIGN_CACHE", "str"),
                      ("TW_CAMPAIGN_TOL_PCT", "float"),
                      ("TW_CAMPAIGN_TOL_ACC", "float")]:
        assert name in knobs.REGISTRY, name
        assert knobs.REGISTRY[name].type == typ, name
    assert knobs.REGISTRY["TW_CAMPAIGN_ROUNDS"].lo == 1
    assert knobs.REGISTRY["TW_CAMPAIGN_TOL_ACC"].lo == 0.0


# ---------------------------------------------------------------------------
# regression gate
# ---------------------------------------------------------------------------

def _fake_artifact():
    def rung(name, tp, acc, misses=(), compiles=0):
        return dict(
            rung=name,
            manifest=dict(spans=1000, regime_mix={"sequential": 3}),
            steady=dict(spans_per_s=tp, backend_compiles=compiles,
                        aot_misses=list(misses), quarantined=0),
            accuracy=dict(e2e_pct=acc, per_regime={}),
        )

    return dict(schema=1, kind="campaign", name="t", created_unix=0.0,
                backend="cpu", devices_visible=2,
                plan=dict(devices=2, slices=2),
                rungs=[rung("r1", 1000.0, 99.0), rung("r2", 5000.0, 97.0)],
                metrics_scrape=None, wall_s=1.0)


def test_compare_flags_each_regression_class():
    from traceweaver_tpu.campaign.compare import compare_artifacts

    base = _fake_artifact()
    assert compare_artifacts(base, base)["ok"]

    # throughput drop past tolerance, named with the right rung+field
    cand = copy.deepcopy(base)
    cand["rungs"][1]["steady"]["spans_per_s"] = 4000.0
    res = compare_artifacts(base, cand, tol_pct=10.0, tol_acc=1.0)
    assert not res["ok"]
    assert [(r["rung"], r["field"]) for r in res["regressions"]] == \
        [("r2", "spans_per_s")]
    # inside tolerance -> clean
    assert compare_artifacts(base, cand, tol_pct=25.0, tol_acc=1.0)["ok"]

    # accuracy drop past the points bar
    cand = copy.deepcopy(base)
    cand["rungs"][0]["accuracy"]["e2e_pct"] = 97.5
    res = compare_artifacts(base, cand, tol_pct=10.0, tol_acc=1.0)
    assert [(r["rung"], r["field"]) for r in res["regressions"]] == \
        [("r1", "accuracy_e2e_pct")]

    # new AOT escapes + steady compiles are cold-start regressions
    cand = copy.deepcopy(base)
    cand["rungs"][0]["steady"]["aot_misses"] = ["solve_windows_fleet[B=64]"]
    cand["rungs"][0]["steady"]["backend_compiles"] = 3
    res = compare_artifacts(base, cand)
    fields = {r["field"] for r in res["regressions"]}
    assert fields == {"aot_misses", "steady_backend_compiles"}

    # a silently dropped rung must not pass
    cand = copy.deepcopy(base)
    cand["rungs"] = cand["rungs"][:1]
    res = compare_artifacts(base, cand)
    assert [r["field"] for r in res["regressions"]] == ["missing_rung"]

    # improvements are never flagged
    cand = copy.deepcopy(base)
    cand["rungs"][1]["steady"]["spans_per_s"] = 9000.0
    cand["rungs"][1]["accuracy"]["e2e_pct"] = 99.5
    assert compare_artifacts(base, cand)["ok"]


def test_compare_tolerances_come_from_registry_knobs(monkeypatch):
    from traceweaver_tpu.campaign.compare import compare_artifacts

    base = _fake_artifact()
    cand = copy.deepcopy(base)
    cand["rungs"][0]["steady"]["spans_per_s"] = 900.0  # -10%
    monkeypatch.setenv("TW_CAMPAIGN_TOL_PCT", "5")
    assert not compare_artifacts(base, cand)["ok"]
    monkeypatch.setenv("TW_CAMPAIGN_TOL_PCT", "15")
    assert compare_artifacts(base, cand)["ok"]


# ---------------------------------------------------------------------------
# events + /metrics mirror (TW007 discipline: scrape == ledger)
# ---------------------------------------------------------------------------

def test_campaign_run_emits_events_and_metrics(tmp_path, monkeypatch):
    """A (single-device, single-slice, tiny) campaign run must emit
    kind="campaign" start/rung/finish events to the TW_EVENTS sink and
    mirror the rung ledger onto tw_campaign_* families — values equal
    to the artifact's own numbers, by construction."""
    from traceweaver_tpu.campaign import ledger, mini_plan, run_campaign
    from traceweaver_tpu.campaign.plan import CampaignPlan, RungSpec
    from traceweaver_tpu.obs import events as obs_events
    from traceweaver_tpu.obs.registry import get_registry

    ledger.reset_for_tests()
    sink_path = tmp_path / "events.jsonl"
    prev = obs_events.install(obs_events.EventLog(str(sink_path)))
    try:
        plan = CampaignPlan(
            name="evt",
            rungs=[RungSpec("only", n_graphs=2, traces_per_graph=12,
                            seed=9, n_services=8, source="synthetic")],
            devices=0, slices=1, timed_rounds=1, warmup_max=2)
        art = run_campaign(plan, out_path=str(tmp_path / "evt.json"),
                           cache_root=str(tmp_path / "cache"))
    finally:
        obs_events.install(prev)

    records = [json.loads(line)
               for line in sink_path.read_text().splitlines()]
    campaign_events = [r for r in records if r.get("kind") == "campaign"]
    assert [r["event"] for r in campaign_events] == \
        ["start", "rung", "finish"]
    rung_evt = campaign_events[1]
    assert rung_evt["rung"] == "only"
    assert rung_evt["spans_per_s"] == pytest.approx(
        art["rungs"][0]["steady"]["spans_per_s"], rel=0.01)
    # "campaign" is a documented tailing kind (cli events --kind)
    assert "campaign" in obs_events.KNOWN_KINDS

    snap = get_registry().snapshot(include_collectors=True)
    assert snap['tw_campaign_spans_per_s{rung="only"}'] == \
        art["rungs"][0]["steady"]["spans_per_s"]
    assert snap['tw_campaign_accuracy_e2e{rung="only"}'] == \
        art["rungs"][0]["accuracy"]["e2e_pct"]
    assert snap["tw_campaign_runs_total"] == 1.0
    assert snap["tw_campaign_rungs_total"] == 1.0
    assert snap["tw_campaign_steady_compiles_total"] == \
        art["rungs"][0]["steady"]["backend_compiles"]
    # the artifact carries the mid-run /metrics scrape
    assert art["metrics_scrape"]["total_samples"] > 0
    assert any(s.startswith("tw_")
               for s in art["metrics_scrape"]["samples"])


# ---------------------------------------------------------------------------
# cli surface (no-backend paths)
# ---------------------------------------------------------------------------

def test_campaign_cli_compare_and_report_roundtrip(tmp_path, capsys):
    from traceweaver_tpu.campaign import main as campaign_main
    from traceweaver_tpu.campaign.ledger import write_artifact

    base = _fake_artifact()
    doctored = copy.deepcopy(base)
    doctored["rungs"][0]["steady"]["spans_per_s"] = 10.0
    p_base = str(tmp_path / "base.json")
    p_bad = str(tmp_path / "bad.json")
    write_artifact(p_base, base)
    write_artifact(p_bad, doctored)

    assert campaign_main(["compare", p_base, p_base]) == 0
    assert campaign_main(["compare", p_base, p_bad]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION r1/spans_per_s" in out
    assert campaign_main(["report", p_base]) == 0
    assert "r2" in capsys.readouterr().out
    assert campaign_main([]) == 2
    assert campaign_main(["frobnicate"]) == 2
