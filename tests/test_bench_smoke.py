"""Tier-1 bench smoke: repeated solves must not recompile.

The dispatch planner buckets every shape to a power of two precisely so
repeated solves reuse one compiled program per shape class; a regression
that lets shapes leak through unbucketed multiplies compiled variants,
silently turning every bench/stream dispatch into a fresh XLA compile
(the round-3 bench died of exactly this class of slowdown). This smoke
test runs the packed solve twice on a tiny problem and asserts the
second call costs ZERO backend compiles — measured by the process-wide
compile counters in runtime/jax_cache, the same counters the bench
report and the stream CLI now surface — and stays under a generous
wall-clock bound.
"""

import time

import numpy as np
import pytest

import jax

from traceweaver_tpu.runtime.jax_cache import compile_counters, counters_delta

jax.config.update("jax_platforms", "cpu")

# generous: the tiny warm solve takes milliseconds; this only exists to
# catch a catastrophic regression (e.g. retracing per call) without being
# flaky on a loaded CI host
WARM_SOLVE_BOUND_S = 60.0


def _tiny_args(seed=0):
    rng = np.random.default_rng(seed)
    B, E, W, M, K = 2, 2, 8, 8, 3
    in_start = np.sort(rng.uniform(0, 500, (B, W)), axis=1).astype(np.float32)
    out_start = np.zeros((B, E, M), np.float32)
    for b in range(B):
        for e in range(E):
            out_start[b, e] = np.sort(
                in_start[b] + 10 * (e + 1) + rng.normal(0, 2, W))
    pred = np.zeros((E, E), bool); pred[1, 0] = True
    root = np.array([True, False]); last = np.array([False, True])
    wt = np.zeros((E, E, K), np.float32); wt[..., 0] = 1
    mu = np.full((E, E, K), 10.0, np.float32)
    sd = np.full((E, E, K), 5.0, np.float32)
    iwt = np.zeros((E, K), np.float32); iwt[:, 0] = 1
    imu = np.full((E, K), 10.0, np.float32)
    isd = np.full((E, K), 5.0, np.float32)
    # numpy inputs on purpose: the packed entry point donates its window
    # tensors, so reusing device arrays across calls would be an error —
    # numpy rows are copied to fresh device buffers per call, exactly how
    # the pack/dispatch pipeline feeds the solver
    return (in_start, in_start + 300, np.ones((B, W), bool),
            out_start, out_start + 5, np.ones((B, E, M), bool),
            np.zeros((B, E), np.float32), np.zeros((B, E, W), bool),
            pred, root, last, wt, mu, sd, iwt, imu, isd,
            iwt.copy(), imu.copy(), isd.copy())


def test_second_solve_is_compile_free_and_fast():
    from traceweaver_tpu.algorithms.weaver_tpu import solve_windows_packed

    args = _tiny_args()
    kwargs = dict(n_sinkhorn=10, n_sweeps=3, sinkhorn_tol=1e-3)

    # first call: may compile (counters just have to be installed before
    # it so the second call's delta is trustworthy)
    compile_counters()
    out1 = np.asarray(solve_windows_packed(*args, **kwargs))

    before = compile_counters()
    t0 = time.perf_counter()
    out2 = np.asarray(solve_windows_packed(*args, **kwargs))
    warm_s = time.perf_counter() - t0
    delta = counters_delta(before)

    assert delta["backend_compiles"] == 0, (
        "identical second solve recompiled — a shape-class or static-arg "
        f"leak is multiplying program variants: {delta}")
    assert warm_s < WARM_SOLVE_BOUND_S
    assert np.array_equal(out1, out2)


def test_compaction_redispatch_shapes_stay_bucketed():
    """The compaction redispatch solves a gathered sub-batch; its batch
    size must be power-of-two bucketed so straggler counts (which vary
    run to run) cannot mint unbounded compiled variants. Two compacted
    runs with different straggler counts may compile at most the
    bucketed shapes once; an immediate repeat must be compile-free."""
    import traceweaver_tpu.algorithms.fleet as fleet_mod

    (in_start, in_end, in_valid, out_start, out_end, out_valid,
     skip_cap, force_skip, *tables) = _tiny_args(seed=1)
    batch = dict(in_start=in_start, in_end=in_end, in_valid=in_valid,
                 out_start=out_start, out_end=out_end, out_valid=out_valid,
                 skip_cap=skip_cap, force_skip=force_skip)
    pidx = np.zeros((in_start.shape[0],), np.int32)
    tables = tuple(t[None] for t in tables)  # [P=1, ...] fleet tables
    hypers = dict(epsilon=1.0, n_sinkhorn=10, sinkhorn_tol=1e-3,
                  max_preds=1, max_succs=1)
    fleet_mod._compacted_pass(batch, pidx, tables, 4, 2, hypers, {})
    before = compile_counters()
    out_a = fleet_mod._compacted_pass(batch, pidx, tables, 4, 2, hypers, {})
    delta = counters_delta(before)
    assert delta["backend_compiles"] == 0, delta
    out_b = fleet_mod._compacted_pass(batch, pidx, tables, 4, 2, hypers, {})
    assert np.array_equal(out_a, out_b)


@pytest.mark.precision
def test_bf16_solve_streams_bf16_blocks_and_recompiles_zero(monkeypatch):
    """Tier-1 mixed-precision smoke: a TW_PRECISION=bf16 solve under
    JAX_PLATFORMS=cpu must actually hand the Sinkhorn/rounding stage
    bfloat16 score blocks (no silent f32 fallback anywhere between the
    score build and the OT solve), and a second identical bf16 solve
    must cost zero backend compiles — the precision static argument may
    add exactly one compiled variant, never retrace per call."""
    import jax.numpy as jnp

    import traceweaver_tpu.algorithms.weaver_tpu as wt

    seen = []
    real_assign_topk = wt.assign_topk

    def spy(S_ot, *a, **k):
        # runs at trace time: S_ot is the assembled OT block the sweep
        # streams — its dtype IS the end-to-end score-path precision
        seen.append(jnp.dtype(S_ot.dtype).name)
        return real_assign_topk(S_ot, *a, **k)

    monkeypatch.setattr(wt, "assign_topk", spy)

    args = _tiny_args(seed=4)
    # unique hyper combo so this test owns its trace (the spy only
    # observes at trace time; a jit cache hit would record nothing)
    kwargs = dict(n_sinkhorn=11, n_sweeps=3, sinkhorn_tol=1e-3,
                  precision="bf16")
    compile_counters()
    out1 = np.asarray(wt.solve_windows_packed(*_tiny_args(seed=4), **kwargs))
    assert seen and set(seen) == {"bfloat16"}, (
        f"bf16 solve leaked non-bf16 score blocks into the sweep: {seen}")

    before = compile_counters()
    out2 = np.asarray(wt.solve_windows_packed(*args, **kwargs))
    delta = counters_delta(before)
    assert delta["backend_compiles"] == 0, (
        f"identical second bf16 solve recompiled: {delta}")
    assert np.array_equal(out1, out2)

    # the default remains f32 end-to-end (same observation point)
    seen.clear()
    np.asarray(wt.solve_windows_packed(
        *_tiny_args(seed=4), n_sinkhorn=11, n_sweeps=3, sinkhorn_tol=1e-3))
    assert seen and set(seen) == {"float32"}, seen


@pytest.mark.precision
def test_env_bf16_rides_the_whole_fleet_path(monkeypatch):
    """TW_PRECISION=bf16 (the env knob, no explicit argument) must reach
    every fused fleet dispatch — warm pass, compaction redispatch, and
    the pipeline — with bf16 blocks, and the byte-denominated ledger
    must account score traffic at 2 B/elem."""
    from test_pipeline import _mixed_items

    import jax.numpy as jnp

    import traceweaver_tpu.algorithms.weaver_tpu as wt
    from traceweaver_tpu.algorithms.fleet import solve_fleet

    seen = set()
    real_assign_topk = wt.assign_topk

    def spy(S_ot, *a, **k):
        seen.add(jnp.dtype(S_ot.dtype).name)
        return real_assign_topk(S_ot, *a, **k)

    monkeypatch.setattr(wt, "assign_topk", spy)
    monkeypatch.setenv("TW_PRECISION", "bf16")

    stats = {}
    # unique hyper combo so this solve owns its traces (the spy observes
    # at trace time only; a jit cache hit would record nothing)
    out = solve_fleet(_mixed_items(), stats=stats, n_sinkhorn=13)
    assert len(out) == 3
    assert seen == {"bfloat16"}, (
        f"env-selected bf16 leaked f32 score blocks: {seen or '(no trace)'}")
    assert stats.get("bytes_est_xla", 0) > 0


@pytest.mark.faults
def test_chaos_smoke_fault_injected_solve_completes_with_ledger(monkeypatch):
    """Tier-1 chaos smoke: a TW_FAULTS-injected fleet solve under
    JAX_PLATFORMS=cpu must COMPLETE through the supervisor's degradation
    ladder with a nonzero retry ledger and zero lost windows — every
    item's slot holds a result identical to the unfaulted run (no 'host'
    faults are injected, so every recovery rung is output-exact and
    quarantine is unreachable)."""
    from test_pipeline import _mixed_items

    from traceweaver_tpu.algorithms.fleet import solve_fleet
    from traceweaver_tpu.runtime import faults

    faults.reset()
    out_clean = solve_fleet(_mixed_items(), stats={})
    monkeypatch.setenv("TW_FAULTS", "dispatch:0.5,fetch:0.2")
    monkeypatch.setenv("TW_FAULTS_SEED", "1")
    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    try:
        stats, q = {}, []
        out = solve_fleet(_mixed_items(), stats=stats, quarantined=q)
    finally:
        faults.reset()
    assert stats.get("faults_injected", 0) > 0, "chaos never engaged"
    assert stats.get("fault_retries", 0) > 0, "retry ledger empty"
    assert q == [] and all(r is not None for r in out)  # zero lost windows
    for a, b in zip(out_clean, out):
        assert a[0] == b[0] and a[1] == b[1] and a[2:] == b[2:]


@pytest.mark.serve
def test_serve_smoke_two_tenants_http_roundtrip(tmp_path):
    """Tier-1 serve smoke: boot the multi-tenant HTTP service on an
    ephemeral port under JAX_PLATFORMS=cpu, POST Jaeger-JSON spans for
    TWO tenants, and assert that (a) each tenant round-trips a
    reconstructed trace through the trace-fetch API and (b) a live
    delay-culprit query returns the planted culprit service — the whole
    serving path (ingest -> windows -> shared fleet solve -> ring ->
    query) in one pass."""
    import json
    import threading
    import urllib.request

    from test_serve import hotel_payload

    from traceweaver_tpu.serve import ServeConfig, TenantService, make_server

    service = TenantService(ServeConfig(
        fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
        verbose=False, pump_windows=10**9,
        state_dir=str(tmp_path / "serve_state")))
    server = make_server(service, port=0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.port}"

    def call(method, path, payload=None):
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(base + path, data=data, method=method)
        if data:
            req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=120) as resp:
            return json.loads(resp.read())

    try:
        a = call("POST", "/api/v1/tenants/smoke-a/spans",
                 hotel_payload(prefix="a"))
        assert a["ingested_spans"] == 120 and a["malformed_spans"] == 0
        b = call("POST", "/api/v1/tenants/smoke-b/spans",
                 hotel_payload(prefix="b", base_us=9e6))
        assert b["ingested_traces"] == 24
        flushed = call("POST", "/api/v1/flush")
        assert flushed["solved_windows"] == 2

        for tid in ("smoke-a", "smoke-b"):
            traces = call("GET", f"/api/v1/tenants/{tid}/traces")
            assert traces["n_traces"] == 24
            rec = call("GET", f"/api/v1/tenants/{tid}/traces/"
                              f"{traces['trace_ids'][0]}")
            assert rec["complete"] and rec["n_spans"] == 5
            q = call("GET", f"/api/v1/tenants/{tid}/query/delay_culprit"
                            "?percentile=0.8")
            assert not q["empty"]
            assert q["worst_service"] == "search", q

        # both tenants' windows rode SHARED dispatches
        st = call("GET", "/api/v1/stats")
        assert st["dispatch"]["shared_solves"] == 1
        assert st["dispatch"]["tenant_batches"] == 2
    finally:
        server.shutdown()
        server.server_close()
    service.drain()


@pytest.mark.columnar
def test_fleet_solve_exercises_the_columnar_pack_path(monkeypatch):
    """Tier-1 columnar smoke: under JAX_PLATFORMS=cpu a default fleet
    solve must pack through the COLUMNAR path (no silent fallback to the
    object walk — the kill switch is TW_COLUMNAR=0, nothing else), and
    the object packer must not run at all."""
    from test_pipeline import _mixed_items

    import traceweaver_tpu.algorithms.weaver_tpu as wt
    from traceweaver_tpu.algorithms.fleet import solve_fleet

    monkeypatch.delenv("TW_COLUMNAR", raising=False)
    col_calls, obj_calls = [], []
    real_col = wt._pack_problem_columnar
    real_obj = wt._pack_problem_objects

    def col_spy(*a, **k):
        col_calls.append(1)
        return real_col(*a, **k)

    def obj_spy(*a, **k):
        obj_calls.append(1)
        return real_obj(*a, **k)

    monkeypatch.setattr(wt, "_pack_problem_columnar", col_spy)
    monkeypatch.setattr(wt, "_pack_problem_objects", obj_spy)
    out = solve_fleet(_mixed_items(), stats={})
    assert len(out) == 3 and all(r is not None for r in out)
    assert col_calls, (
        "fleet solve silently fell back to the object pack path")
    assert not obj_calls, (
        "object packer ran under the default TW_COLUMNAR=1")

    # the kill switch restores the object path — and only it
    monkeypatch.setenv("TW_COLUMNAR", "0")
    col_calls.clear()
    out_obj = solve_fleet(_mixed_items(), stats={})
    assert obj_calls and not col_calls
    for a, b in zip(out, out_obj):
        assert a[0] == b[0] and a[1] == b[1] and a[2:] == b[2:]


@pytest.mark.pipeline
def test_pipelined_fleet_runs_and_second_solve_is_compile_free():
    """Tier-1 pipeline smoke: under JAX_PLATFORMS=cpu the fleet solve
    must take the PIPELINED dispatch path (no silent fallback to the
    serial flow — the kill switch is TW_PIPELINE=0, nothing else), and a
    second identical pipelined solve must cost zero backend compiles
    (the pipeline cannot be allowed to multiply program variants)."""
    from test_pipeline import _mixed_items

    from traceweaver_tpu.algorithms.fleet import solve_fleet

    items = _mixed_items()
    stats = {}
    out1 = solve_fleet(items, stats=stats)
    assert stats.get("pipeline_groups", 0) > 0, (
        "fleet solve silently fell back to the serial dispatcher: "
        f"{stats}")
    assert stats.get("pipeline_depth", 0) >= 1
    assert stats.get("d2h_bytes_fetched", 0) > 0

    before = compile_counters()
    out2 = solve_fleet(items, stats={})
    delta = counters_delta(before)
    assert delta["backend_compiles"] == 0, (
        "identical second pipelined solve recompiled — a shape-class or "
        f"static-arg leak is multiplying program variants: {delta}")
    for a, b in zip(out1, out2):
        assert a[0] == b[0] and a[1] == b[1] and a[2:] == b[2:]


@pytest.mark.plan
def test_warm_plan_cache_solve_is_compile_free():
    """Tier-1 plan-cache smoke (ISSUE 17 acceptance pin): with a warm
    plan cache the fleet solve skips the host fit AND the first EM pass
    (single warm-pass dispatch), and a second warm solve costs zero
    backend compiles — the cached plan must ride the same pow2-bucketed
    AOT shape classes as the cold path, not mint new program variants.
    Output stays bit-identical to the cold two-pass solve (the cached
    plan IS the decoded on-device refit table that pass already used)."""
    from test_pipeline import _mixed_items

    from traceweaver_tpu.algorithms.fleet import solve_fleet
    from traceweaver_tpu.algorithms.plancache import PlanCache

    pc = PlanCache()
    cold = solve_fleet(_mixed_items(), stats={}, plan_cache=pc)
    assert pc.counters()["admissions"] == 3

    # first warm solve may compile the single-pass variant once; the
    # measured second warm solve must dispatch entirely from cache
    warm1 = solve_fleet(_mixed_items(), stats={}, plan_cache=pc)
    before = compile_counters()
    warm2 = solve_fleet(_mixed_items(), stats={}, plan_cache=pc)
    delta = counters_delta(before)
    assert delta["backend_compiles"] == 0, (
        "warm plan-cache solve recompiled — the cached plan is escaping "
        f"the AOT shape lattice: {delta}")
    assert pc.counters()["hits"] == 6
    for a, b, c in zip(cold, warm1, warm2):
        assert a[0] == b[0] == c[0] and a[1] == b[1] == c[1]
        assert a[2:] == b[2:] == c[2:]


@pytest.mark.collector
def test_capture_smoke_strace_to_traces_roundtrip(tmp_path):
    """Tier-1 capture smoke (ISSUE 13 acceptance pin): a recorded
    strace fixture flows source -> skew correction -> windowed solve ->
    emitted traces end to end under JAX_PLATFORMS=cpu — every trace
    stitched (root + call + callee), grading exact on the clean
    capture, zero capture loss, and the mid-capture reconnect re-keyed
    rather than corrupting the byte streams."""
    import json

    import bench
    from traceweaver_tpu.collector.source import CollectorSource
    from traceweaver_tpu.stream.service import (
        StreamConfig,
        StreamingReconstructor,
        TraceSink,
    )

    src = CollectorSource(bench._capture_workload(12))
    sink_path = tmp_path / "captured.jsonl"
    cfg = StreamConfig(window_us=0.2e6, overlap_us=0.05e6,
                       ooo_bound_us=0.02e6, verbose=False,
                       checkpoint_every=10_000)
    svc = StreamingReconstructor(src, cfg, sink=TraceSink(str(sink_path)))
    summary = svc.run()
    assert summary["accuracy"]["e2e"] == 100.0
    cap = summary["capture"]
    assert cap["loss"] == {} and cap["loss_rate"] == 0.0
    assert cap["rekeyed_streams"] == 1  # the workload's fd reuse
    traces = {}
    for raw in sink_path.read_text().splitlines():
        rec = json.loads(raw)
        traces.update(rec["traces"])
    assert len(traces) == 12
    assert all(len(ids) == 3 for ids in traces.values())


@pytest.mark.collector
def test_capture_chaos_smoke_loss_counted_confidence_discounted(
        tmp_path, monkeypatch):
    """Tier-1 capture-chaos smoke: injected skew + chunk loss through
    the full capture path must complete with NO crash, counted
    capture_loss, a fitted skew offset on the ledger, and emitted
    traces whose confidence is discounted by the observed loss rate —
    degradation is graceful and visible, never silent."""
    import json

    import bench
    from traceweaver_tpu.collector.source import CollectorSource
    from traceweaver_tpu.runtime import faults
    from traceweaver_tpu.stream.service import (
        StreamConfig,
        StreamingReconstructor,
        TraceSink,
    )

    monkeypatch.setenv("TW_SKEW_CHAOS_US", "200000")
    faults.reset()
    try:
        # loss capped at 4 chunks: unbounded chunk carnage can kill the
        # cross-source exchanges the skew fit needs (the bench leg
        # separates the two stimuli; this smoke wants both on one run)
        with faults.override("skew:1.0:max=1,capture:0.05:max=4", seed=3):
            src = CollectorSource(bench._capture_workload(12))
    finally:
        faults.reset()
    quality = src.capture_quality()
    assert sum(quality["loss"].values()) > 0, "chaos never engaged"
    assert quality["loss_rate"] > 0
    assert max(abs(v) for v in quality["skew_us"].values()) == \
        pytest.approx(200000, rel=0.05)

    sink_path = tmp_path / "captured.jsonl"
    cfg = StreamConfig(window_us=0.2e6, overlap_us=0.05e6,
                       ooo_bound_us=0.02e6, verbose=False,
                       checkpoint_every=10_000)
    svc = StreamingReconstructor(src, cfg, sink=TraceSink(str(sink_path)))
    summary = svc.run()  # the no-crash gate
    assert summary["capture"]["loss_rate"] == quality["loss_rate"]
    discount = 1.0 - quality["loss_rate"]
    saw = 0
    for raw in sink_path.read_text().splitlines():
        rec = json.loads(raw)
        tw = rec.get("tw.confidence")
        if not tw:
            continue
        assert tw["capture"]["discount"] == pytest.approx(discount)
        for tconf in tw["traces"].values():
            if tconf is not None:
                assert tconf["conf"] <= discount + 1e-9
                saw += 1
    assert saw, "no emitted trace carried discounted confidence"


@pytest.mark.aot
def test_aot_eager_warmup_makes_fleet_solve_compile_free(monkeypatch):
    """Tier-1 cold-start smoke (ISSUE 14 acceptance pin): after a
    TW_AOT=eager shape-lattice warmup under JAX_PLATFORMS=cpu, a
    representative fleet solve — compaction + pipeline on, the default
    serving configuration — performs ZERO backend compiles and the
    per-solve ``aot_misses`` ledger stays empty: every dispatched
    program (warm pass, compacted redispatch, standalone refit, devcols
    assembly, ring fills) was enumerated, compiled, and seeded by the
    lattice, so a warm rolling restart never stalls a solve on a cold
    jit."""
    from test_pipeline import _service_items

    from traceweaver_tpu.algorithms.fleet import solve_fleet
    from traceweaver_tpu.runtime import aot

    # a workload whose pow2 geometry sits inside a deliberately tiny
    # horizon, so the eager warmup stays test-sized: one service, two
    # windows of 8 (B=2, W=8, M=8), a 2-endpoint chain (E=2, mp=ms=1)
    monkeypatch.setenv("TW_AOT", "eager")
    monkeypatch.setenv("TW_AOT_HORIZON", "2:2:8:8")
    monkeypatch.setenv("TW_AOT_TIER", "serve")
    aot.reset_for_tests()
    try:
        status = aot.startup_warmup(context="test")
        assert status["phase"] == "ready", status["errors"]
        assert status["planned"] == status["compiled"] > 0
        ready, detail = aot.readiness()
        assert ready and detail["ready"]

        items = [_service_items("uni", n_traces=16, burst=8,
                                eps=("A", "B"), seed=0)]
        before = compile_counters()
        stats = {}
        out = solve_fleet(items, stats=stats)
        delta = counters_delta(before)

        assert len(out) == 1 and out[0] is not None
        assert stats.get("pipeline_groups", 0) > 0, (
            f"not the pipelined serving path: {stats}")
        assert stats.get("compact_windows_total", 0) > 0, (
            f"compaction never engaged: {stats}")
        assert delta["backend_compiles"] == 0, (
            "a dispatched program escaped the AOT lattice and compiled "
            f"during the solve: {delta}, misses={stats.get('aot_misses')}")
        assert stats.get("aot_misses", []) == [], (
            "the lattice enumerator and the dispatch planner disagree "
            f"on shapes: {stats['aot_misses']}")
    finally:
        aot.reset_for_tests()


@pytest.mark.aot
def test_aot_readyz_gates_503_while_warming_then_200(monkeypatch):
    """Tier-1 /readyz smoke (ISSUE 14 acceptance pin): the serve
    server's readiness endpoint returns 503 while the configured AOT
    lattice tier is still compiling and flips to 200 once it completes
    — the contract a rolling-restart orchestrator holds traffic on.
    The warmup is a real background ``startup_warmup`` whose variants
    are stubbed to block on an event, so the gate's transition is
    observed end to end without burning compile time; TW_AOT=off keeps
    /readyz at 200 (nothing gated, the default deployment)."""
    import json
    import threading
    import urllib.error
    import urllib.request

    from traceweaver_tpu.runtime import aot
    from traceweaver_tpu.serve import ServeConfig, TenantService, make_server

    service = TenantService(ServeConfig(
        fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
        verbose=False))
    server = make_server(service, port=0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.port}"

    def readyz():
        try:
            with urllib.request.urlopen(base + "/readyz",
                                        timeout=30) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    release = threading.Event()

    def fake_plan(tier, horizon, prelower=True):
        def run():
            release.wait(timeout=60)
            return 0.0
        return [aot._Variant(("fake", i), run) for i in range(2)]

    aot.reset_for_tests()
    try:
        # TW_AOT=off (the default): nothing gated, ready immediately
        code, body = readyz()
        assert code == 200 and body["ready"] and body["aot"] == "off"

        monkeypatch.setenv("TW_AOT", "background")
        monkeypatch.setattr(aot, "_plan", fake_plan)
        aot.startup_warmup(context="test")
        code, body = readyz()
        assert code == 503, body
        assert body["ready"] is False and body["phase"] == "warming"
        assert body["compiled"] < body["planned"] == 2

        release.set()
        assert aot.wait_ready(timeout_s=60)
        code, body = readyz()
        assert code == 200, body
        assert body["ready"] and body["phase"] == "ready"
        assert body["compiled"] == body["planned"] == 2
    finally:
        release.set()
        server.shutdown()
        server.server_close()
        aot.reset_for_tests()
    service.drain()


@pytest.mark.campaign
def test_campaign_smoke_mini_ladder_end_to_end(tmp_path):
    """Tier-1 campaign smoke (ISSUE 15 acceptance pin): the 2-rung
    synthetic mini campaign runs END TO END under JAX_PLATFORMS=cpu —
    corpus ladder synthesized + manifested, fleet driven data-parallel
    across a 2-device mesh through the compaction-capable mesh path,
    warmup until a round compiles nothing, timed steady-state rounds
    with zero compiles, the multislice allreduce tier agreeing — and
    writes a valid artifact that (a) self-compares clean through
    `campaign compare` and (b) FAILS the compare with the right field
    named when the throughput or accuracy is doctored."""
    import copy
    import json

    from traceweaver_tpu.campaign import (
        compare_artifacts,
        load_artifact,
        mini_plan,
        run_campaign,
        write_artifact,
    )

    plan = mini_plan(devices=2, slices=2, traces_per_graph=25)
    out = str(tmp_path / "CAMPAIGN_smoke.json")
    art = run_campaign(plan, out_path=out,
                       cache_root=str(tmp_path / "corpus"))

    # artifact round-trips from disk and carries the whole ledger
    loaded = load_artifact(out)
    assert loaded == json.loads(json.dumps(art))  # json-clean
    assert [r["rung"] for r in art["rungs"]] == ["mini-a", "mini-b"]
    for r in art["rungs"]:
        assert r["manifest"]["spans"] > 0
        assert r["steady"]["spans_per_s"] > 0
        assert r["steady"]["rounds"] == 2
        # the steady state is the zero-compile contract the warmup buys
        assert r["warmup"]["backend_compiles"][-1] == 0
        assert r["steady"]["backend_compiles"] == 0, r["steady"]
        assert r["steady"]["aot_misses"] == []
        assert r["steady"]["quarantined"] == 0
        # the mesh path actually ran: sharded dispatches fetched flags
        # through the coalesced single-transfer fan-in
        assert r["steady"]["bytes"]["d2h_flag_fetches"] > 0
        assert r["steady"]["bytes"]["d2h_bytes_flags"] > 0
        assert r["steady"]["fleet"]["compact_windows_total"] > 0
        assert r["accuracy"]["e2e_pct"] > 90.0
        assert r["multislice"]["agree"] and r["multislice"]["slices"] == 2
    assert art["plan"]["devices"] == 2

    # the regression gate: self-compare passes...
    assert compare_artifacts(art, art)["ok"]
    # ...a doctored throughput regression fails naming rung+field...
    slow = copy.deepcopy(art)
    slow["rungs"][1]["steady"]["spans_per_s"] *= 0.5
    res = compare_artifacts(art, slow)
    assert not res["ok"]
    assert [(r["rung"], r["field"]) for r in res["regressions"]] == \
        [("mini-b", "spans_per_s")]
    # ...and so does a doctored accuracy drop, through the CLI surface
    bad_acc = copy.deepcopy(art)
    bad_acc["rungs"][0]["accuracy"]["e2e_pct"] -= 5.0
    p_bad = str(tmp_path / "doctored.json")
    write_artifact(p_bad, bad_acc)
    from traceweaver_tpu.campaign import main as campaign_main

    assert campaign_main(["compare", out, p_bad]) == 1
    res2 = compare_artifacts(art, bad_acc)
    assert {r["field"] for r in res2["regressions"]} == \
        {"accuracy_e2e_pct"}


@pytest.mark.fleet
def test_fleet_smoke_migrate_and_rolling_restart_zero_loss(tmp_path):
    """Tier-1 fleet smoke: TWO real serve subprocesses behind the
    consistent-hash router — POST windows for three tenants through the
    router, LIVE-MIGRATE one tenant to the other replica, roll-restart
    the whole fleet one replica at a time, keep posting, and assert the
    conservation ledger balances: every ingested trace emitted exactly
    once, zero drops, across migration and both restarts."""
    from traceweaver_tpu.fleet_serve.campaign import (
        _aggregate,
        _flush_fleet,
        _settle,
        fleet_payload,
    )
    from traceweaver_tpu.fleet_serve.manager import (
        FleetManager,
        ReplicaProcess,
    )
    from traceweaver_tpu.fleet_serve.router import http_json

    tenants = ["smoke-x", "smoke-y", "smoke-z"]
    replicas = [ReplicaProcess(
        name, str(tmp_path / "fleet" / name), serve_args=["--fix", "2"])
        for name in ("r0", "r1")]
    for rep in replicas:
        rep.start()
    fleet = FleetManager(replicas, router_port=0)
    try:
        def post(tenant, seq):
            status, out = http_json(
                "POST",
                f"{fleet.base_url}/api/v1/tenants/{tenant}/spans",
                fleet_payload(tenant, seq, n_traces=4), timeout=120)
            assert status == 200, (status, out)

        for seq in range(2):
            for tid in tenants:
                post(tid, seq)

        # live migration: move one tenant onto the OTHER replica while
        # its first windows are still in flight
        mover = tenants[0]
        src = fleet.router.owner(mover)
        dst = next(n for n in sorted(fleet.router.replicas) if n != src)
        fleet.migrate(mover, dst)
        assert mover in fleet.replica_tenants(dst)
        assert mover not in fleet.replica_tenants(src)
        post(mover, 2)  # router must follow the pin to the new owner

        # rolling restart: each replica drains its tenants to the
        # survivor, restarts with --resume, and rejoins on /readyz 200
        report = fleet.rolling_restart()
        assert set(report) == {"r0", "r1"}
        for rep in replicas:
            assert rep.alive and rep.restarts == 1

        # the fleet must still be INGESTING after the rotation
        for tid in tenants:
            post(tid, 3)

        _flush_fleet(fleet, n=2)
        agg = _settle(fleet)
        assert agg["ingested_traces"] == len(tenants) * 3 * 4 + 4
        assert agg["traces_emitted"] == agg["ingested_traces"], agg
        assert agg["shed_dropped_windows"] == 0
        assert agg["deadletter_windows"] == 0
        assert agg["late_dropped"] == 0 and agg["backlog"] == 0
        counters = agg["router"]["counters"]
        assert counters["restarts"] == 2
        assert _aggregate(fleet)["router"]["counters"] is not None
    finally:
        fleet.stop()


@pytest.mark.adapt
def test_adapt_smoke_inert_off_and_compile_free_steady_state(
        monkeypatch, tmp_path):
    """Tier-1 chaos-adapt smoke (ISSUE 12 acceptance pins): a stable
    stream under TW_ADAPT=1 must (a) actuate NOTHING (steady state — no
    refits, no fallbacks), (b) emit BYTE-IDENTICAL sink records to the
    TW_ADAPT=0 run of the same corpus (the controller only observes),
    and (c) cost zero backend compiles beyond the TW_ADAPT=0 run's own
    programs — adaptation arms no new program variants. The full
    drift→refit→recovery chaos story runs in tests/test_adapt.py."""
    import bench
    from traceweaver_tpu.stream.service import (
        StreamConfig,
        StreamingReconstructor,
        TraceSink,
    )
    from traceweaver_tpu.stream.sources import IterableSource

    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")

    def run(flag, name):
        monkeypatch.setenv("TW_ADAPT", flag)
        events, _ = bench._adapt_burst_events(8, shift_at=99)
        sink = TraceSink(str(tmp_path / name))
        cfg = StreamConfig(window_us=1e6, overlap_us=0.0,
                           ooo_bound_us=1e3, checkpoint_every=10_000,
                           verbose=False)
        svc = StreamingReconstructor(IterableSource(events), cfg,
                                     sink=sink)
        summary = svc.run()
        sink.close()
        return (tmp_path / name).read_bytes(), summary

    bytes_off, sum_off = run("0", "off.jsonl")
    assert sum_off["adapt"] == dict(enabled=False)

    before = compile_counters()
    bytes_on, sum_on = run("1", "on.jsonl")
    delta = counters_delta(before)
    assert bytes_on == bytes_off, (
        "TW_ADAPT=1 steady state changed emitted records")
    assert delta["backend_compiles"] == 0, (
        f"enabled adaptation steady state minted new programs: {delta}")
    adapt = sum_on["adapt"]
    assert adapt["enabled"] and adapt["refits_scheduled"] == 0
    assert adapt["fallbacks"] == 0 and adapt["active_fallbacks"] == []


def test_serve_overlap_smoke_ring_overlaps_and_stays_compile_free(tmp_path):
    """Tier-1 overlapped-drain smoke (ISSUE 19): two real tickets
    dispatched CONCURRENTLY (barrier-released threads through the real
    ``_ring_dispatch``), completed in FIFO order — the ring ledger must
    measure solve-interval overlap (``overlap_pct`` > 0: the --serve-
    overlap leg's engagement gate) and a warm ticket pair must cost
    ZERO backend compiles (tickets ride the same admission lattice;
    depth changes concurrency, never shapes)."""
    import threading

    from test_continuous import _cfg, _ready_halves, _trace

    from traceweaver_tpu.serve import TenantService

    svc = TenantService(_cfg(state_dir=str(tmp_path / "overlap"),
                             pump_windows=10**9))

    def feed_round(r):
        # fresh trace ids + advancing event time per round, so every
        # round seals new windows of the SAME shape class
        for chunk in range(3):
            svc.ingest("t00", {"data": [
                _trace(k, f"r{r}c{chunk}",
                       base_us=(r * 3 + chunk + 1) * 200e6)
                for k in range(3)]})

    def run_pair(r):
        feed_round(r)
        t, plans = _ready_halves(svc)
        tk1 = svc.submit_admitted([(t, plans[0])])
        tk2 = svc.submit_admitted([(t, plans[1])])
        assert tk1 is not None and tk2 is not None
        barrier = threading.Barrier(2)

        def dispatch(tk):
            barrier.wait(timeout=60)
            svc._ring_dispatch(tk)

        threads = [threading.Thread(target=dispatch, args=(tk,),
                                    daemon=True) for tk in (tk1, tk2)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=300)
            assert not th.is_alive(), "concurrent dispatch wedged"
        assert svc.complete_ticket(tk1) >= 1
        assert svc.complete_ticket(tk2) >= 1

    try:
        run_pair(0)  # cold start: first-contact EM + solve compiles
        run_pair(1)  # geometry settles: round 0's unsealed tail window
        #              joins this pair, minting the steady batch bucket
        before = compile_counters()
        pairs = 3
        run_pair(2)
        delta = counters_delta(before)
        assert delta["backend_compiles"] == 0, (
            f"warm ticket pair minted new programs: {delta}")
        # barrier-released dispatches make interval overlap all but
        # certain; tolerate one pathological scheduling stall before
        # calling engagement broken (each extra round is warm: the
        # zero-compile pin above already passed)
        while svc.overlap_pct() <= 0.0 and pairs < 5:
            run_pair(pairs)
            pairs += 1
        st = svc.stats()["ring"]
        assert svc.overlap_pct() > 0.0, (
            f"no measured solve-interval overlap after {pairs} "
            f"barrier-synchronized ticket pairs: {st}")
        assert st["submitted"] == st["completed"] == pairs * 2
        assert st["aborted"] == 0 and st["outstanding"] == 0
    finally:
        svc.drain()


@pytest.mark.wal
def test_fleet_smoke_kill9_recovers_without_acked_loss(tmp_path):
    """Tier-1 durability smoke: a REAL replica process is SIGKILLed
    after acking a window, the crash supervisor brings the tenant back
    (respawn-with-resume), and the acked window is still there — the
    WAL replayed it. A retry of an already-acked client seq dedups
    instead of double-ingesting (docs/ROBUSTNESS.md "Durability")."""
    import json
    import time
    import urllib.error
    import urllib.request

    from test_serve import hotel_payload

    from traceweaver_tpu.fleet_serve.manager import (
        FleetManager,
        ReplicaProcess,
    )

    rep = ReplicaProcess(
        "r0", str(tmp_path / "r0"), serve_args=["--fix", "2"]).start()
    fleet = FleetManager([rep], router_port=0, supervise=True)

    def post(payload, seq, deadline_s=120.0):
        """POST through the router, riding out 503+Retry-After while
        the supervisor recovers the crashed replica."""
        data = json.dumps(payload).encode()
        deadline = time.time() + deadline_s
        while True:
            req = urllib.request.Request(
                fleet.base_url + "/api/v1/tenants/kt/spans",
                data=data, method="POST")
            req.add_header("Content-Type", "application/json")
            req.add_header("X-TW-Seq", str(seq))
            try:
                with urllib.request.urlopen(req, timeout=120) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as e:
                retry_in = float(e.headers.get("Retry-After", 0.3) or 0.3)
                e.read()
                if e.code not in (429, 503) or time.time() > deadline:
                    raise
            except (ConnectionError, OSError):
                retry_in = 0.3
                if time.time() > deadline:
                    raise
            time.sleep(retry_in)

    def get(path):
        with urllib.request.urlopen(fleet.base_url + path,
                                    timeout=120) as resp:
            return json.loads(resp.read())

    try:
        acked = post(hotel_payload(prefix="a"), seq=1)
        assert acked["ingested_traces"] == 24 and acked["seq"] == 1

        rep.proc.kill()  # SIGKILL: no atexit, no flush, no checkpoint
        deadline = time.time() + 120
        while (fleet.router.counters["respawns"]
               + fleet.router.counters["failovers"]) < 1:
            assert time.time() < deadline, "supervisor never recovered"
            time.sleep(0.2)

        # a retry of the acked-then-crashed seq dedups with the ORIGINAL
        # accounting — the dedup window rode the WAL through the crash
        retry = post(hotel_payload(prefix="a"), seq=1)
        assert retry.get("deduped") is True
        assert retry["ingested_traces"] == 24
        # fresh work lands normally on the respawned replica
        assert post(hotel_payload(prefix="b", base_us=200e6),
                    seq=2)["ingested_traces"] == 24

        req = urllib.request.Request(fleet.base_url + "/api/v1/flush",
                                     method="POST")
        with urllib.request.urlopen(req, timeout=300) as resp:
            resp.read()
        # both acked windows emitted: the pre-kill ack survived SIGKILL
        traces = get("/api/v1/tenants/kt/traces")
        assert traces["n_traces"] == 48, traces
    finally:
        fleet.stop()
