"""Telemetry subsystem tests (ISSUE 9, tier-1, CPU).

Contracts covered:

- typed registry semantics: counter monotonicity, label-schema
  conflicts raise, gauge set-max, histogram buckets, thread safety of
  concurrent increments, snapshot key format;
- Prometheus text exposition (families, label escaping, histogram
  sample expansion) and the stdlib sidecar exporter end-to-end;
- the fleet ledger mirror: a real solve's registry counter deltas
  equal its legacy stats dict field-for-field (the bench
  ``telemetry_snapshot`` agreement, proven live here);
- structured event sink: fault-ladder rungs land as JSONL records next
  to the in-dict ordered list, `cli events` tails both formats;
- SELF-TRACE ROUND TRIP (the acceptance path): a solve's own emitted
  Jaeger-JSON pipeline spans parse through ingest/jaeger.py, satisfy
  parent⊇child containment, and a fix=6 serve tenant reconstructs the
  pipeline's trace WITH THE SOLVER ITSELF — every journey span
  recovered, delay-culprit query answerable over the pipeline's own
  telemetry;
- TW_PROFILE hooks are inert by default and harmless on CPU.
"""

import json
import threading
import time
import urllib.request

import pytest

import jax

# break the ingest<->runtime import cycle regardless of collection order
# (the serve import below otherwise depends on an earlier test module
# having initialized traceweaver_tpu.runtime first)
import traceweaver_tpu.runtime  # noqa: F401  — must precede serve

from traceweaver_tpu.obs import events as obs_events
from traceweaver_tpu.obs import selftrace
from traceweaver_tpu.obs.exposition import render_metrics, start_metrics_server
from traceweaver_tpu.obs.registry import (
    MetricError,
    MetricsRegistry,
    get_registry,
)
from traceweaver_tpu.serve import ServeConfig, TenantService

jax.config.update("jax_platforms", "cpu")

pytestmark = pytest.mark.obs


# ---------------------------------------------------------------------------
# corpus helpers (the serve test fixture's shape: frontend -> search)
# ---------------------------------------------------------------------------

def hotel_trace(i, prefix="t", base_us=1_000_000.0, spacing_us=10_000.0):
    T = base_us + i * spacing_us
    tid = f"{prefix}{i:03d}"

    def span(sid, start, dur, op, refs, pid, kind):
        return dict(traceID=tid, spanID=sid, startTime=start, duration=dur,
                    operationName=op,
                    references=[{"traceID": tid, "spanID": r} for r in refs],
                    processID=pid,
                    tags=[{"key": "span.kind", "value": kind}])

    spans = [
        span("root", T, 1500.0, "HTTP GET /hotels", [], "p1", "server"),
        span("c1", T + 200, 1100.0, "call-search", ["root"], "p1", "client"),
        span("s1", T + 300, 600.0, "search", ["c1"], "p2", "server"),
    ]
    return dict(traceID=tid, spans=spans,
                processes=dict(p1={"serviceName": "frontend"},
                               p2={"serviceName": "search"}))


def hotel_payload(n_traces=24, **kw):
    return {"data": [hotel_trace(i, **kw) for i in range(n_traces)]}


def _cfg(**kw):
    base = dict(fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
                verbose=False, pump_windows=10**9)
    base.update(kw)
    return ServeConfig(**base)


@pytest.fixture()
def tracer():
    tr = selftrace.PipelineTracer()
    prev = selftrace.install(tr)
    yield tr
    selftrace.install(prev)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_semantics():
    reg = MetricsRegistry()
    c = reg.counter("tw_test_total", "t", labels=("key",))
    c.inc(key="a")
    c.inc(2.5, key="a")
    c.inc(key="b")
    snap = reg.snapshot()
    assert snap['tw_test_total{key="a"}'] == 3.5
    assert snap['tw_test_total{key="b"}'] == 1.0
    with pytest.raises(MetricError):
        c.inc(-1.0, key="a")  # counters are monotonic
    with pytest.raises(MetricError):
        c.inc(1.0, wrong="a")  # label schema enforced

    g = reg.gauge("tw_test_gauge", labels=("key",))
    g.set(5.0, key="depth")
    g.set_max(3.0, key="depth")  # set-if-greater: no-op
    g.set_max(9.0, key="depth")
    assert reg.snapshot()['tw_test_gauge{key="depth"}'] == 9.0

    h = reg.histogram("tw_test_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    snap = reg.snapshot()
    assert snap['tw_test_seconds_bucket{le="0.1"}'] == 1.0
    assert snap['tw_test_seconds_bucket{le="1"}'] == 2.0
    assert snap['tw_test_seconds_bucket{le="+Inf"}'] == 3.0
    assert snap["tw_test_seconds_count"] == 3.0
    assert snap["tw_test_seconds_sum"] == pytest.approx(5.55)


def test_redeclaration_same_schema_ok_conflict_raises():
    reg = MetricsRegistry()
    a = reg.counter("tw_x_total", labels=("k",))
    assert reg.counter("tw_x_total", labels=("k",)) is a  # idempotent
    with pytest.raises(MetricError):
        reg.counter("tw_x_total", labels=("other",))  # label fork
    with pytest.raises(MetricError):
        reg.gauge("tw_x_total", labels=("k",))  # kind fork
    with pytest.raises(MetricError):
        reg.counter("bad name")


def test_concurrent_increments_never_drop():
    reg = MetricsRegistry()
    c = reg.counter("tw_race_total", labels=("key",))

    def spin():
        for _ in range(2000):
            c.inc(key="x")

    threads = [threading.Thread(target=spin) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.snapshot()['tw_race_total{key="x"}'] == 16000.0


# ---------------------------------------------------------------------------
# exposition
# ---------------------------------------------------------------------------

def test_render_metrics_format_and_escaping():
    reg = MetricsRegistry()
    c = reg.counter("tw_fmt_total", "help text", labels=("svc",))
    c.inc(2, svc='we"ird\nname')
    reg.register_collector("extra", lambda: [
        ("tw_collected", "gauge", "from a collector",
         [({"kind": "x"}, 1.5)])])
    text = render_metrics(reg)
    assert "# HELP tw_fmt_total help text" in text
    assert "# TYPE tw_fmt_total counter" in text
    assert 'tw_fmt_total{svc="we\\"ird\\nname"} 2' in text
    assert "# TYPE tw_collected gauge" in text
    assert 'tw_collected{kind="x"} 1.5' in text


def test_sidecar_exporter_scrapes_over_http():
    reg = MetricsRegistry()
    reg.counter("tw_sidecar_total").inc(3)
    exporter = start_metrics_server(0, registry=reg)
    try:
        url = f"http://127.0.0.1:{exporter.port}/metrics"
        with urllib.request.urlopen(url, timeout=30) as resp:
            assert resp.status == 200
            assert "version=0.0.4" in resp.headers["Content-Type"]
            body = resp.read().decode()
        assert "tw_sidecar_total 3" in body
    finally:
        exporter.shutdown()
        exporter.server_close()


# ---------------------------------------------------------------------------
# the fleet ledger mirror: live agreement with the legacy stats dict
# ---------------------------------------------------------------------------

def test_registry_deltas_match_fleet_stats_dict_on_a_real_solve():
    """The mirror is real: a solve's tw_fleet_ledger_total deltas equal
    its _Stats dict for every scalar counter key (gauge-mirrored
    high-water marks excluded) — the live form of the bench
    telemetry_snapshot agreement field."""
    reg = get_registry()
    before = reg.snapshot()
    svc = TenantService(_cfg())
    svc.ingest("agree", hotel_payload())
    svc.flush()
    after = reg.snapshot()

    gauge_keys = {k.split('"')[1] for k in after
                  if k.startswith("tw_fleet_gauge{")}
    deltas = {}
    for name, val in after.items():
        if name.startswith("tw_fleet_ledger_total{"):
            d = val - before.get(name, 0.0)
            if d:
                deltas[name.split('"')[1]] = d
    legacy = {k: float(v) for k, v in svc.fleet_stats.items()
              if isinstance(v, (int, float)) and k not in gauge_keys}
    assert legacy, "solve produced no scalar ledger"
    for k, v in legacy.items():
        assert deltas.get(k, 0.0) == pytest.approx(v, rel=1e-6), k
    # nothing moved in the registry that the dict does not explain
    assert set(deltas) == {k for k, v in legacy.items() if v != 0}


def test_fault_ladder_counter_and_event_sink(tmp_path, monkeypatch):
    """A dispatch fault storm: ladder rungs land in the labelled
    registry counter AND as structured JSONL records (the dict's
    ordered fault_ladder list is unchanged); `cli events` tails them."""
    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    log = obs_events.EventLog(str(tmp_path / "events.jsonl"))
    prev = obs_events.install(log)
    reg = get_registry()
    before = reg.snapshot()
    try:
        svc = TenantService(_cfg())
        svc.tenant("storm").fault_spec = "dispatch:1.0,host:1.0"
        svc.ingest("storm", hotel_payload())
        svc.flush()
    finally:
        obs_events.install(prev)
    st = svc.stats("storm")
    assert st["faults"]["quarantined"] > 0
    after = reg.snapshot()
    key = 'tw_fault_ladder_events_total{key="fault_ladder",rung="quarantine"}'
    assert after.get(key, 0.0) > before.get(key, 0.0)

    recs = [json.loads(line) for line in
            open(log.path, encoding="utf-8")]
    kinds = {r["kind"] for r in recs}
    assert "fault_ladder" in kinds
    assert "fault_injected" in kinds  # runtime/faults.py emits too
    rungs = [r["event"] for r in recs if r["kind"] == "fault_ladder"]
    assert "quarantine" in rungs
    assert all("ts" in r for r in recs)

    # the tail subcommand reads the sink (and dead-letter format alike)
    import contextlib
    import io

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = obs_events.tail_main([log.path, "-n", "0"])
    assert rc == 0
    text = out.getvalue()
    assert "fault_ladder/quarantine" in text
    assert "fault_injected/dispatch" in text


def test_events_truncate_splices_like_the_deadletter_sink(tmp_path):
    log = obs_events.EventLog(str(tmp_path / "ev.jsonl"))
    log.emit("k", "one")
    offset = log.offset
    log.emit("k", "two")
    log.truncate(offset)
    log.emit("k", "three")
    log.close()
    events = [json.loads(line)["event"]
              for line in open(log.path, encoding="utf-8")]
    assert events == ["one", "three"]


def test_events_tail_kind_filter(tmp_path):
    """--kind shows only matching records; dead-letter-shaped records
    (no kind field) are filtered out rather than crashing the filter."""
    import contextlib
    import io

    log = obs_events.EventLog(str(tmp_path / "kinds.jsonl"))
    log.emit("fault_ladder", "retry")
    log.emit("confidence_drift", "shift", key="svc", psi=0.41)
    log.emit("fault_ladder", "bisect")
    with open(log.path, "a", encoding="utf-8") as f:  # dead-letter shape
        f.write(json.dumps({"window": 3, "reason": "quarantined"}) + "\n")
    log.close()

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = obs_events.tail_main([log.path, "-n", "0",
                                   "--kind", "fault_ladder"])
    assert rc == 0
    lines = [ln for ln in out.getvalue().splitlines() if ln.strip()]
    assert len(lines) == 2
    assert "fault_ladder/retry" in lines[0]
    assert "fault_ladder/bisect" in lines[1]
    assert all("confidence_drift" not in ln and "deadletter" not in ln
               for ln in lines)
    # -n bounds the non-follow read too
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        assert obs_events.tail_main([log.path, "-n", "1"]) == 0
    lines = [ln for ln in out.getvalue().splitlines() if ln.strip()]
    assert len(lines) == 1 and "deadletter" in lines[0]


class _TailProc:
    """A `cli events --follow` subprocess with line-buffered capture
    (the events path imports no JAX, so startup is fast)."""

    def __init__(self, path, *extra):
        import subprocess
        import sys

        self.proc = subprocess.Popen(
            [sys.executable, "-u", "-m", "traceweaver_tpu.runtime.cli",
             "events", path, "-n", "0", "--follow", *extra],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        self.lines = []
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _pump(self):
        for line in self.proc.stdout:
            self.lines.append(line.rstrip("\n"))

    def wait_for(self, needle, timeout=10.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if any(needle in ln for ln in self.lines):
                return True
            time.sleep(0.05)
        return False

    def stop(self):
        import signal as _signal

        self.proc.send_signal(_signal.SIGINT)
        try:
            rc = self.proc.wait(timeout=10)
        except Exception:
            self.proc.kill()
            rc = self.proc.wait()
        self._thread.join(timeout=5)
        return rc


def test_events_tail_follow_sees_new_records(tmp_path):
    log = obs_events.EventLog(str(tmp_path / "follow.jsonl"))
    log.emit("k", "pre-existing")
    tail = _TailProc(log.path)
    try:
        assert tail.wait_for("k/pre-existing")
        log.emit("k", "arrived-live")
        assert tail.wait_for("k/arrived-live")
        # --kind filtering applies live too
        log.emit("other", "filtered")
        log.emit("k", "kept")
        assert tail.wait_for("k/kept")
        assert not any("other/filtered" in ln for ln in tail.lines) \
            or True  # no --kind on this proc: both pass; filter below
    finally:
        rc = tail.stop()
    log.close()
    assert rc == 0  # SIGINT exits the follow loop cleanly


def test_events_tail_follow_survives_truncate_splice(tmp_path):
    """The checkpoint/resume splice mid-follow: the sink truncates back
    to a recorded offset and re-appends. The follower must pick up the
    re-emitted records from the splice point instead of blocking forever
    at its stale (now past-EOF) offset."""
    log = obs_events.EventLog(str(tmp_path / "splice.jsonl"))
    log.emit("k", "one")
    offset = log.offset
    log.emit("k", "two")
    tail = _TailProc(log.path, "--kind", "k")
    try:
        assert tail.wait_for("k/two")
        log.truncate(offset)          # rewind past the follower's offset
        log.emit("k", "respliced")    # the re-emitted record
        assert tail.wait_for("k/respliced"), (
            "follower stuck at a stale offset after truncate")
        # the live --kind filter held throughout
        log.emit("noise", "skipme")
        log.emit("k", "after")
        assert tail.wait_for("k/after")
        assert not any("noise/skipme" in ln for ln in tail.lines)
    finally:
        rc = tail.stop()
    log.close()
    assert rc == 0


# ---------------------------------------------------------------------------
# self-trace round trip (the acceptance path)
# ---------------------------------------------------------------------------

def _containment_ok(trace_json):
    spans = {s["spanID"]: s for s in trace_json["spans"]}
    for s in trace_json["spans"]:
        for ref in s["references"]:
            p = spans[ref["spanID"]]
            if not (p["startTime"] <= s["startTime"]
                    and p["startTime"] + p["duration"]
                    >= s["startTime"] + s["duration"]):
                return False
    return True


def test_selftrace_roundtrip_solver_reconstructs_own_pipeline(tracer):
    """THE acceptance round trip: a solve's own emitted Jaeger-JSON
    pipeline spans (window journey: ingest → seal → pack → dispatch →
    ... → emit, trace context carried through the pack thread and
    decode workers) are ingested through ingest/jaeger.py (fix=6) and
    reconstructed BY THE SOLVER — every journey span recovered into one
    well-formed trace, and the delay-culprit query answers over the
    pipeline's own telemetry."""
    svc = TenantService(_cfg())
    svc.ingest("alpha", hotel_payload())
    svc.flush()
    assert len(tracer) == 1  # one window journeyed

    payload = tracer.payload()
    assert len(payload["data"]) == 1
    trace_json = payload["data"][0]
    stages = {s["operationName"] for s in trace_json["spans"]
              if s["processID"] != "p-window"}
    # the full journey, in spans: stream phases + fleet phases
    for stage in ("ingest", "seal", "pack", "dispatch", "decode", "emit"):
        assert stage in stages, stages
    # parent ⊇ child containment holds on the raw payload
    assert _containment_ok(trace_json)

    # parse through the batch ingest layer (fix mode 6 = self-trace)
    from traceweaver_tpu.ingest.jaeger import parse_trace_payload

    parsed = parse_trace_payload(payload, selftrace.SELFTRACE_FIX, {}, {})
    assert len(parsed) == 1 and parsed[0] is not None

    # ... and reconstruct it with the solver itself: a fix=6 tenant
    # ingests the pipeline's own spans and solves them like any other
    # uninstrumented application
    meta = TenantService(_cfg(fix=6))
    out = meta.ingest("self", payload)
    assert out["ingested_traces"] == 1
    assert out["malformed_spans"] == 0
    meta.flush()
    recs = meta.tenant("self").ring.records()
    assert len(recs) == 1
    rec = recs[0]
    # EVERY span of the journey is in the reconstructed trace
    assert rec["n_spans"] == len(trace_json["spans"])
    assert rec["complete"] is True
    services = {s["service"] for s in rec["spans"]}
    assert selftrace.ROOT_SERVICE in services
    assert {"tw-pack", "tw-dispatch", "tw-decode"} <= services
    # the pipeline can answer "where did my window's time go" about
    # ITSELF — the paper's marquee query over the pipeline's own trace
    q = meta.query_delay_culprit("self", percentile=0.0)
    assert q["empty"] is False
    assert q["worst_service"].startswith("tw-")


def test_selftrace_multi_window_multi_tenant_journeys(tracer):
    """Several windows across tenants: every journey becomes its own
    well-formed trace (keys held apart by the tenant prefix), repeated
    stages merge to one span per stage, and the whole payload parses."""
    svc = TenantService(_cfg(window_us=20e6, overlap_us=4e6,
                             pump_windows=1))
    svc.ingest("a", hotel_payload(prefix="a", spacing_us=5e6))
    svc.ingest("b", hotel_payload(n_traces=12, prefix="b", spacing_us=5e6))
    svc.flush()
    payload = tracer.payload()
    assert len(payload["data"]) >= 4  # multiple windows per tenant
    ids = [t["traceID"] for t in payload["data"]]
    assert any("-a-" in i or i.endswith("a:0") or "a-" in i for i in ids)
    for trace_json in payload["data"]:
        assert _containment_ok(trace_json)
        ops = [s["operationName"] for s in trace_json["spans"]
               if s["processID"] != "p-window"]
        assert len(ops) == len(set(ops))  # stages merged, not repeated
        root = next(s for s in trace_json["spans"]
                    if s["spanID"] == "root")
        assert root["operationName"] == selftrace.ROOT_OP

    from traceweaver_tpu.ingest.jaeger import parse_trace_payload

    parsed = parse_trace_payload(payload, selftrace.SELFTRACE_FIX, {}, {})
    assert all(p is not None for p in parsed)


def test_selftrace_off_by_default_and_fleet_unaffected():
    """No tracer installed (the production default): solves run with
    zero journeys collected and no trace keys leak into results."""
    assert selftrace.active() is None
    svc = TenantService(_cfg())
    svc.ingest("quiet", hotel_payload())
    svc.flush()
    assert svc.stats("quiet")["emitted_windows"] == 1


# ---------------------------------------------------------------------------
# TW_PROFILE hooks + knob registration
# ---------------------------------------------------------------------------

def test_profile_knobs_registered_and_annotate_inert(monkeypatch):
    from traceweaver_tpu.obs import profile as obs_profile
    from traceweaver_tpu.runtime import knobs

    for name in ("TW_PROFILE", "TW_METRICS_PORT", "TW_SELFTRACE",
                 "TW_EVENTS"):
        assert name in knobs.REGISTRY, name
    monkeypatch.delenv("TW_PROFILE", raising=False)
    assert obs_profile.enabled() is False
    with obs_profile.annotate("tw:test"):  # null context, no jax import
        pass
    assert obs_profile.device_memory_families() == []
    monkeypatch.setenv("TW_PROFILE", "1")
    assert obs_profile.enabled() is True
    with obs_profile.annotate("tw:test"):  # real TraceAnnotation on CPU
        pass
    # CPU devices may or may not expose memory_stats; either way this
    # must not raise and must return collector-shaped families
    fams = obs_profile.device_memory_families()
    for name, kind, _help, samples in fams:
        assert name == "tw_device_memory_bytes" and kind == "gauge"
        assert all(isinstance(v, float) for _, v in samples)
    monkeypatch.setenv("TW_PROFILE", "nonsense-is-truthy")
    assert obs_profile.enabled() is True


def test_profile_data_feature_check_matches_import():
    from traceweaver_tpu.obs.profile import profile_data_available

    try:
        from jax.profiler import ProfileData  # noqa: F401
        expected = True
    except ImportError:
        expected = False
    assert profile_data_available() is expected
