"""Unit tests for the JAX numeric kernels."""

import numpy as np
import pytest
import scipy.stats

import jax.numpy as jnp

from traceweaver_tpu.ops import greedy_round, mixture_logpdf, sinkhorn_log


def test_mixture_logpdf_matches_scipy():
    w = jnp.array([0.3, 0.7, 0.0])
    mu = jnp.array([0.0, 5.0, 0.0])
    sd = jnp.array([1.0, 2.0, 1.0])
    x = jnp.array([-1.0, 0.0, 2.5, 7.0])
    got = np.asarray(mixture_logpdf(x, w, mu, sd))
    want = np.log(
        0.3 * scipy.stats.norm.pdf(np.asarray(x), 0.0, 1.0)
        + 0.7 * scipy.stats.norm.pdf(np.asarray(x), 5.0, 2.0)
    )
    np.testing.assert_allclose(got, want, rtol=1e-4)  # float32 on device


def test_sinkhorn_marginals():
    rng = np.random.default_rng(0)
    S = jnp.asarray(rng.normal(size=(6, 8)))
    r = jnp.array([1.0] * 5 + [3.0])  # last row absorbs surplus
    c = jnp.ones(8)
    P = sinkhorn_log(S, r, c, epsilon=0.5, n_iters=200)
    np.testing.assert_allclose(np.asarray(P.sum(1)), np.asarray(r), rtol=1e-3)
    np.testing.assert_allclose(np.asarray(P.sum(0)), np.asarray(c), rtol=1e-3)


def test_sinkhorn_disabled_rows_get_no_mass():
    S = jnp.zeros((3, 3))
    r = jnp.array([1.0, 0.0, 1.0])
    c = jnp.array([1.0, 1.0, 0.0])
    P = np.asarray(sinkhorn_log(S, r, c, epsilon=0.5, n_iters=100))
    assert P[1].sum() < 1e-6
    assert P[:, 2].sum() < 1e-6


def test_sinkhorn_sharp_scores_recover_permutation():
    # a strongly diagonal score matrix should transport on the diagonal
    S = jnp.asarray(np.where(np.eye(5), 0.0, -50.0))
    P = np.asarray(sinkhorn_log(S, jnp.ones(5), jnp.ones(5), epsilon=1.0, n_iters=50))
    assert (P.argmax(1) == np.arange(5)).all()


def test_greedy_round_one_to_one():
    # two rows prefer the same column; peel must give it to the stronger row
    plan = jnp.asarray(np.array([
        [0.9, 0.1, 0.0],   # cols: 2 real + skip
        [0.8, 0.7, 0.0],
    ]))
    assign = np.asarray(greedy_round(
        plan, jnp.array([True, True]), jnp.array([True, True, True]),
        jnp.asarray(1), n_steps=2))
    assert assign[0] == 0 and assign[1] == 1


def test_greedy_round_skip_capacity():
    # three rows want skip (col 2), capacity 2: one row must take a real col
    plan = jnp.asarray(np.array([
        [0.1, 0.0, 0.5],
        [0.2, 0.0, 0.6],
        [0.3, 0.0, 0.7],
    ]))
    assign = np.asarray(greedy_round(
        plan, jnp.array([True] * 3), jnp.array([True, True, True]),
        jnp.asarray(2), n_steps=3))
    assert (assign == 2).sum() == 2
    assert sorted(assign.tolist())[0] == 0  # someone took the real column
