"""Unit tests for the JAX numeric kernels."""

import numpy as np
import jax
import pytest
import scipy.stats

import jax.numpy as jnp

from traceweaver_tpu.ops import greedy_round, mixture_logpdf, sinkhorn_log


def test_mixture_logpdf_matches_scipy():
    w = jnp.array([0.3, 0.7, 0.0])
    mu = jnp.array([0.0, 5.0, 0.0])
    sd = jnp.array([1.0, 2.0, 1.0])
    x = jnp.array([-1.0, 0.0, 2.5, 7.0])
    got = np.asarray(mixture_logpdf(x, w, mu, sd))
    want = np.log(
        0.3 * scipy.stats.norm.pdf(np.asarray(x), 0.0, 1.0)
        + 0.7 * scipy.stats.norm.pdf(np.asarray(x), 5.0, 2.0)
    )
    np.testing.assert_allclose(got, want, rtol=1e-4)  # float32 on device


def test_mixture_logpdf_gemm_matches_elementwise_at_delay_scale():
    """The GEMM (quadratic-feature matmul) formulation must agree with
    the elementwise form at the solver's real magnitudes — µs-scale
    delays against tens-of-µs sds, where the UNcentered expansion loses
    every mantissa bit (x=5e5, sd=50: x^2 ~ 2.5e11, f32 ulp ~ 1.6e4)."""
    from traceweaver_tpu.ops.scores import mixture_logpdf_gemm

    cases = [
        # (x values, weights, means, stds) — matched-candidate regimes
        (jnp.array([5.0e5, 5.001e5, 4.999e5]),
         jnp.array([1.0, 0.0, 0.0]),
         jnp.array([5.001e5, 0.0, 0.0]),
         jnp.array([50.0, 1.0, 1.0])),
        (jnp.array([1.0e6, 1.0001e6]),
         jnp.array([0.4, 0.6, 0.0]),
         jnp.array([1.0001e6, 1.00005e6, 0.0]),
         jnp.array([20.0, 80.0, 1.0])),
    ]
    for x, w, mu, sd in cases:
        ref = np.asarray(mixture_logpdf(x, w, mu, sd))
        got = np.asarray(mixture_logpdf_gemm(x, w, mu, sd))
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-2)


def test_sinkhorn_marginals():
    rng = np.random.default_rng(0)
    S = jnp.asarray(rng.normal(size=(6, 8)))
    r = jnp.array([1.0] * 5 + [3.0])  # last row absorbs surplus
    c = jnp.ones(8)
    P = sinkhorn_log(S, r, c, epsilon=0.5, n_iters=200)
    np.testing.assert_allclose(np.asarray(P.sum(1)), np.asarray(r), rtol=1e-3)
    np.testing.assert_allclose(np.asarray(P.sum(0)), np.asarray(c), rtol=1e-3)


def test_sinkhorn_disabled_rows_get_no_mass():
    S = jnp.zeros((3, 3))
    r = jnp.array([1.0, 0.0, 1.0])
    c = jnp.array([1.0, 1.0, 0.0])
    P = np.asarray(sinkhorn_log(S, r, c, epsilon=0.5, n_iters=100))
    assert P[1].sum() < 1e-6
    assert P[:, 2].sum() < 1e-6


def test_sinkhorn_sharp_scores_recover_permutation():
    # a strongly diagonal score matrix should transport on the diagonal
    S = jnp.asarray(np.where(np.eye(5), 0.0, -50.0))
    P = np.asarray(sinkhorn_log(S, jnp.ones(5), jnp.ones(5), epsilon=1.0, n_iters=50))
    assert (P.argmax(1) == np.arange(5)).all()


def test_greedy_round_one_to_one():
    # two rows prefer the same column; peel must give it to the stronger row
    plan = jnp.asarray(np.array([
        [0.9, 0.1, 0.0],   # cols: 2 real + skip
        [0.8, 0.7, 0.0],
    ]))
    assign = np.asarray(greedy_round(
        plan, jnp.array([True, True]), jnp.array([True, True, True]),
        jnp.asarray(1), n_steps=2))
    assert assign[0] == 0 and assign[1] == 1


def test_greedy_round_skip_capacity():
    # three rows want skip (col 2), capacity 2: one row must take a real col
    plan = jnp.asarray(np.array([
        [0.1, 0.0, 0.5],
        [0.2, 0.0, 0.6],
        [0.3, 0.0, 0.7],
    ]))
    assign = np.asarray(greedy_round(
        plan, jnp.array([True] * 3), jnp.array([True, True, True]),
        jnp.asarray(2), n_steps=3))
    assert (assign == 2).sum() == 2
    assert sorted(assign.tolist())[0] == 0  # someone took the real column


def test_greedy_round_matches_serial_peel_under_skip_contention():
    # Row 1 wins the real column (0.95); row 0 then falls back to skip and
    # must take the single skip slot ahead of lower-mass row 2, exactly as
    # the serial highest-cell-first peel would order it.
    plan = jnp.asarray(np.array([
        [0.9, 0.8],    # loses col 0 to row 1, deserves the skip slot
        [0.95, 0.5],
        [0.0, 0.3],    # wants skip immediately but must NOT get it
    ]))
    assign = np.asarray(greedy_round(
        plan, jnp.array([True] * 3), jnp.array([True, True]),
        jnp.asarray(1), n_steps=3))
    assert assign[1] == 0
    assert assign[0] == 1   # skip column
    assert assign[2] == -1  # capacity exhausted, no real candidate


def test_greedy_round_matches_serial_peel_randomized():
    # brute-force serial peel oracle on random plans (incl. skip capacity)
    rng = np.random.default_rng(7)
    for trial in range(20):
        n, m1 = rng.integers(2, 9), rng.integers(2, 7)
        plan = rng.random((n, m1)).round(3)  # coarse grid avoids ties
        plan += np.arange(n)[:, None] * 1e-6  # deterministic tie-break
        cap = int(rng.integers(0, 3))
        col_valid = np.ones(m1, dtype=bool)
        col_valid[-1] = cap > 0

        # serial oracle
        mass = np.where(col_valid[None, :], plan, -1e9).copy()
        want = np.full(n, -1, dtype=np.int32)
        used = 0
        for _ in range(n):
            i, j = np.unravel_index(np.argmax(mass), mass.shape)
            if mass[i, j] <= -1e8:
                break
            want[i] = j
            mass[i, :] = -1e9
            if j == m1 - 1:
                used += 1
                if used >= cap:
                    mass[:, j] = -1e9
            else:
                mass[:, j] = -1e9

        got = np.asarray(greedy_round(
            jnp.asarray(plan), jnp.ones(n, bool), jnp.asarray(col_valid),
            jnp.asarray(cap), n_steps=n))
        np.testing.assert_array_equal(got, want, err_msg=f"trial {trial}")


def test_pallas_sinkhorn_matches_jnp_path():
    from traceweaver_tpu.ops.pallas_sinkhorn import sinkhorn_log_pallas

    rng = np.random.default_rng(3)
    for n, m in [(6, 9), (17, 33), (64, 128)]:
        S = rng.normal(size=(n, m)).astype(np.float32)
        S[rng.random((n, m)) < 0.2] = -1e9  # feasibility mask
        r = np.ones(n, np.float32)
        r[-1] = 3.0
        c = np.full(m, (n + 2) / m, np.float32)
        want = np.asarray(sinkhorn_log(
            jnp.asarray(S), jnp.asarray(r), jnp.asarray(c),
            epsilon=0.7, n_iters=60))
        got = np.asarray(sinkhorn_log_pallas(
            jnp.asarray(S), jnp.asarray(r), jnp.asarray(c),
            epsilon=0.7, n_iters=60, interpret=True))
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)


def test_pallas_sinkhorn_disabled_rows_and_vmap():
    from traceweaver_tpu.ops.pallas_sinkhorn import sinkhorn_log_pallas

    rng = np.random.default_rng(5)
    S = rng.normal(size=(4, 10, 12)).astype(np.float32)
    r = np.ones((4, 10), np.float32)
    r[:, 3] = 0.0  # disabled row
    c = np.full((4, 12), 9.0 / 12.0, np.float32)
    got = np.asarray(jax.vmap(
        lambda s, rr, cc: sinkhorn_log_pallas(
            s, rr, cc, epsilon=1.0, n_iters=80, interpret=True)
    )(jnp.asarray(S), jnp.asarray(r), jnp.asarray(c)))
    assert got[:, 3, :].sum() < 1e-6
    np.testing.assert_allclose(got.sum(2), r, rtol=1e-3, atol=1e-3)


def test_batched_gmm_recovers_mixture():
    from traceweaver_tpu.ops.gmm import fit_gmm_batched

    rng = np.random.default_rng(11)
    # edge 0: well-separated 2-component mixture; edge 1: single gaussian
    a = np.concatenate([rng.normal(1000.0, 50.0, 400),
                        rng.normal(9000.0, 100.0, 200)])
    b = rng.normal(5000.0, 300.0, 512)
    x = np.zeros((2, 1024), np.float32)
    mask = np.zeros((2, 1024), bool)
    x[0, :len(a)] = a; mask[0, :len(a)] = True
    x[1, :len(b)] = b; mask[1, :len(b)] = True

    w, mu, sd = (np.asarray(o) for o in fit_gmm_batched(x, mask, max_k=5))

    # edge 0: two dominant components near 1000 and 9000 with ~2:1 weights
    live = w[0] > 0.05
    assert live.sum() == 2, (w[0], mu[0])
    got = sorted(zip(mu[0][live], w[0][live]))
    assert abs(got[0][0] - 1000) < 100 and abs(got[1][0] - 9000) < 200
    assert abs(got[0][1] - 2 / 3) < 0.1

    # edge 1: single component near (5000, 300)
    live = w[1] > 0.05
    assert live.sum() == 1
    assert abs(mu[1][live][0] - 5000) < 100
    assert abs(sd[1][live][0] - 300) < 80


def test_fit_edge_gmms_matches_sklearn_loglik():
    from traceweaver_tpu.algorithms.timing import EdgeDist, fit_edge_gmms

    rng = np.random.default_rng(13)
    samples = np.concatenate([rng.normal(200.0, 20.0, 300),
                              rng.normal(800.0, 40.0, 300)])
    dev = fit_edge_gmms({("a", "b"): samples.tolist()})[("a", "b")]
    skl = EdgeDist.from_samples_gmm(samples.tolist())
    # average log-likelihood of the data under both fits should agree
    ll_dev = float(np.mean(dev.logpdf(samples)))
    ll_skl = float(np.mean(skl.logpdf(samples)))
    assert ll_dev > ll_skl - 0.15, (ll_dev, ll_skl)


def test_fit_edge_gmms_degenerate_rows():
    from traceweaver_tpu.algorithms.timing import fit_edge_gmms

    out = fit_edge_gmms({
        ("a", "b"): [5.0, 5.0, 5.0, 5.0, 5.0],   # constant -> host path
        ("a", "c"): [1.0, 2.0],                   # too few -> host path
        ("a", "d"): [],                           # empty -> host path
    })
    assert set(out) == {("a", "b"), ("a", "c"), ("a", "d")}
    assert abs(out[("a", "b")].means[0] - 5.0) < 1e-6


def test_sinkhorn_dispatch_cpu_lowering_with_pallas_forced(monkeypatch):
    """TW_PALLAS=1 with a CPU lowering target must not compile a
    non-interpret Pallas kernel for CPU: platform selection happens at
    lowering time (jax.lax.platform_dependent), so the CPU branch takes the
    jnp path and matches it exactly (regression for the default-backend
    vs mesh-devices dispatch mismatch)."""
    from traceweaver_tpu.ops.pallas_sinkhorn import sinkhorn
    from traceweaver_tpu.ops.sinkhorn import sinkhorn_log

    monkeypatch.setenv("TW_PALLAS", "1")
    monkeypatch.delenv("TW_PALLAS_INTERPRET", raising=False)
    rng = np.random.default_rng(7)
    n, m = 64, 128  # at/above the pallas size threshold
    S = rng.normal(size=(n, m)).astype(np.float32)
    r = np.ones(n, np.float32)
    c = np.full(m, n / m, np.float32)
    got = np.asarray(sinkhorn(jnp.asarray(S), jnp.asarray(r), jnp.asarray(c),
                              epsilon=0.9, n_iters=40))
    want = np.asarray(sinkhorn_log(jnp.asarray(S), jnp.asarray(r),
                                   jnp.asarray(c), epsilon=0.9, n_iters=40))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_fit_gmm_in_graph_families():
    """In-graph refit: >=4-sample rows get an EM fit close to the data,
    1-3-sample rows take the closed-form Gaussian, empty rows keep the
    prior params untouched."""
    import numpy as np

    from traceweaver_tpu.ops.gmm import fit_gmm_in_graph

    rng = np.random.default_rng(0)
    n = 64
    samples = np.zeros((3, n), np.float32)
    mask = np.zeros((3, n), bool)
    # row 0: rich bimodal data
    samples[0] = np.concatenate([
        rng.normal(100.0, 5.0, n // 2), rng.normal(500.0, 10.0, n // 2)
    ]).astype(np.float32)
    mask[0] = True
    # row 1: two samples -> closed-form single gaussian
    samples[1, :2] = [40.0, 60.0]
    mask[1, :2] = True
    # row 2: empty -> prior kept
    K = 5
    prior_w = np.zeros((3, K), np.float32)
    prior_w[:, 0] = 1.0
    prior_mu = np.full((3, K), 777.0, np.float32)
    prior_sd = np.full((3, K), 3.0, np.float32)

    w, mu, sd = (np.asarray(a) for a in fit_gmm_in_graph(
        samples, mask, prior_w, prior_mu, prior_sd, max_k=K))

    mix_mean = (w[0] * mu[0]).sum() / w[0].sum()
    assert abs(mix_mean - samples[0].mean()) < 10.0
    assert w[0].sum() > 0.99
    # row 1 closed form: mean 50, std 10
    assert abs(mu[1, 0] - 50.0) < 1e-3 and abs(sd[1, 0] - 10.0) < 1e-3
    assert w[1, 0] == 1.0
    # row 2 untouched prior
    np.testing.assert_allclose(mu[2], prior_mu[2])
    np.testing.assert_allclose(sd[2], prior_sd[2])
    np.testing.assert_allclose(w[2], prior_w[2])


def test_sinkhorn_tol_early_exit_matches_full_run():
    # tol > 0 must stop only after the potentials stop moving, so the plan
    # is indistinguishable from the full fixed-count run at rounding
    # granularity; tol=0 must be bitwise-identical to the pre-tolerance
    # fixed-count behaviour (exact-convergence exit is a no-op fixed point)
    rng = np.random.default_rng(11)
    S = jnp.asarray(rng.normal(size=(24, 30)).astype(np.float32) * 3.0)
    r = jnp.ones(24)
    c = jnp.full(30, 26.0 / 30.0)
    full = np.asarray(sinkhorn_log(S, r, c, epsilon=1.0, n_iters=200))
    fast = np.asarray(sinkhorn_log(S, r, c, epsilon=1.0, n_iters=200,
                                   tol=1e-3))
    np.testing.assert_allclose(fast, full, rtol=5e-3, atol=5e-4)
    assert (np.argmax(fast, axis=1) == np.argmax(full, axis=1)).all()


def test_pallas_sinkhorn_tol_matches_jnp_tol():
    from traceweaver_tpu.ops.pallas_sinkhorn import sinkhorn_log_pallas

    rng = np.random.default_rng(12)
    S = rng.normal(size=(16, 20)).astype(np.float32)
    r = np.ones(16, np.float32)
    c = np.full(20, 16.0 / 20.0, np.float32)
    want = np.asarray(sinkhorn_log(
        jnp.asarray(S), jnp.asarray(r), jnp.asarray(c),
        epsilon=0.7, n_iters=120))
    got = np.asarray(sinkhorn_log_pallas(
        jnp.asarray(S), jnp.asarray(r), jnp.asarray(c),
        epsilon=0.7, n_iters=120, interpret=True, tol=1e-3))
    np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-4)


def test_solver_early_exit_assignments_identical():
    # End-to-end: the sweep-stability exit is exact and the Sinkhorn
    # tolerance is tight enough that hard assignments cannot move on a
    # well-posed synthetic problem
    import __graft_entry__ as g
    from traceweaver_tpu.algorithms.weaver_tpu import solve_windows

    _, args = g.entry()
    base = solve_windows(*args, n_sinkhorn=40, n_sweeps=5, sinkhorn_tol=0.0)
    fast = solve_windows(*args, n_sinkhorn=40, n_sweeps=5, sinkhorn_tol=1e-3)
    np.testing.assert_array_equal(np.asarray(base[0]), np.asarray(fast[0]))


def test_pallas_vmem_gate(monkeypatch):
    """Blocks whose padded pipeline footprint (~6x the [N, M] f32 block,
    double-buffered in+out across grid steps) cannot fit the scoped-VMEM
    cap must take the XLA path — on chip the fleet-batched bench block
    tripped Mosaic's 16 MB default before the kernel sized its own
    budget (commit 795d50f)."""
    from traceweaver_tpu.ops import pallas_sinkhorn as ps

    # pin the default cap (TW_PALLAS_VMEM_CAP is read at CALL time)
    monkeypatch.delenv("TW_PALLAS_VMEM_CAP", raising=False)
    # the bench fleet shape that OOM'd on chip now fits the raised cap
    assert ps.fits_pallas_vmem(1032, 1152)
    # a block over the cap must be gated out (cap 96 MB -> 16 MB block)
    assert not ps.fits_pallas_vmem(4096, 4096)
    # gate respects lane/sublane padding: 1 x 1 pads to 8 x 128
    assert ps._padded_block_bytes(1, 1) == 8 * 128 * 4
    assert ps.fits_pallas_vmem(1, 1)
    # the env override takes effect per call (not frozen at import):
    # a ~55 MB-footprint block fits the 96 MB default but not a 32 MB cap
    assert ps.fits_pallas_vmem(1500, 1500)
    monkeypatch.setenv("TW_PALLAS_VMEM_CAP", str(32 * 1024 * 1024))
    assert not ps.fits_pallas_vmem(1500, 1500)
    # ... and is clamped to the v5e's physical per-core VMEM, so an
    # oversized override cannot push Mosaic past the hardware and fail
    # at compile time on chip
    monkeypatch.setenv("TW_PALLAS_VMEM_CAP", str(1 << 40))
    assert ps._vmem_cap_bytes() == ps._VMEM_HW_BYTES_V5E
    # a sub-floor override clamps up to the floor the kernel budgets
    monkeypatch.setenv("TW_PALLAS_VMEM_CAP", "1024")
    assert ps._vmem_cap_bytes() == ps._VMEM_FLOOR_BYTES
    # unparsable values now RAISE (the registry's raise-on-typo rule,
    # PR 8 — previously a silent fall-back to the default)
    from traceweaver_tpu.runtime.knobs import KnobError

    monkeypatch.setenv("TW_PALLAS_VMEM_CAP", "lots")
    with pytest.raises(KnobError):
        ps._vmem_cap_bytes()


def test_sinkhorn_dispatch_oversized_block_takes_jnp_path(monkeypatch):
    """With TW_PALLAS=1, an oversized block still routes to sinkhorn_log
    (no pallas lowering attempted) and produces the jnp answer."""
    from traceweaver_tpu.ops import pallas_sinkhorn as ps
    from traceweaver_tpu.ops.sinkhorn import sinkhorn_log

    monkeypatch.setenv("TW_PALLAS", "1")
    monkeypatch.delenv("TW_PALLAS_INTERPRET", raising=False)
    monkeypatch.setattr(ps, "fits_pallas_vmem",
                        lambda n, m, itemsize=4: False)
    called = {"pallas": False}

    def boom(*a, **k):
        called["pallas"] = True
        raise AssertionError("pallas path must not be taken")

    monkeypatch.setattr(ps, "sinkhorn_log_pallas", boom)
    rng = np.random.default_rng(3)
    n, m = 64, 128
    S = rng.normal(size=(n, m)).astype(np.float32)
    r = np.ones(n, np.float32)
    c = np.full(m, n / m, np.float32)
    got = np.asarray(ps.sinkhorn(jnp.asarray(S), jnp.asarray(r),
                                 jnp.asarray(c), epsilon=0.9, n_iters=25))
    want = np.asarray(sinkhorn_log(jnp.asarray(S), jnp.asarray(r),
                                   jnp.asarray(c), epsilon=0.9, n_iters=25))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
    assert not called["pallas"]


def test_topk_peel_matches_lax_top_k():
    """topk_peel must be bit-identical to lax.top_k (values AND indices,
    incl. tie order: equal values -> lower index first) — it replaces it
    in the solver purely to avoid the TPU lane-sort lowering."""
    from traceweaver_tpu.ops.rounding import topk_peel

    rng = np.random.default_rng(11)
    # random, with duplicates and NEG-masked cells like a real plan block
    x = rng.normal(size=(7, 33)).astype(np.float32)
    x[x < -0.5] = -1.0e9
    x[2] = -1.0e9                      # fully masked row
    x[3, :5] = x[3, 10:15] = 0.25      # exact ties across positions
    for k in (1, 3, 5):
        pv, pi = topk_peel(jnp.asarray(x), k)
        lv, li = jax.lax.top_k(jnp.asarray(x), k)
        np.testing.assert_array_equal(np.asarray(pv), np.asarray(lv))
        np.testing.assert_array_equal(np.asarray(pi), np.asarray(li))
    # batched (the solver calls it on [W, M+1] inside vmapped windows)
    xb = rng.normal(size=(4, 9, 130)).astype(np.float32)
    pv, pi = topk_peel(jnp.asarray(xb), 5)
    lv, li = jax.lax.top_k(jnp.asarray(xb), 5)
    np.testing.assert_array_equal(np.asarray(pv), np.asarray(lv))
    np.testing.assert_array_equal(np.asarray(pi), np.asarray(li))


def test_topk_peel_neg_inf_and_k_guard():
    """-inf inputs (the common JAX mask idiom) must still match
    lax.top_k exactly; k beyond the lane size raises at trace time as
    top_k does."""
    from traceweaver_tpu.ops.rounding import topk_peel

    x = jnp.asarray(np.array(
        [[5.0, -np.inf, -np.inf],
         [-np.inf, -np.inf, -np.inf],
         [2.0, 7.0, -np.inf]], np.float32))
    for k in (1, 2, 3):
        pv, pi = topk_peel(x, k)
        lv, li = jax.lax.top_k(x, k)
        np.testing.assert_array_equal(np.asarray(pv), np.asarray(lv))
        np.testing.assert_array_equal(np.asarray(pi), np.asarray(li))
    with pytest.raises(ValueError):
        topk_peel(x, 4)
    # the documented small-k bound: above MAX_PEEL_K the O(k*M) peel
    # loses to the sort and callers must use lax.top_k
    from traceweaver_tpu.ops.rounding import MAX_PEEL_K

    with pytest.raises(ValueError, match="MAX_PEEL_K"):
        topk_peel(jnp.zeros((2, 64), jnp.float32), MAX_PEEL_K + 1)
    # k=0 parity: empty arrays like lax.top_k, not a stack error
    pv, pi = topk_peel(x, 0)
    assert pv.shape == (3, 0) and pi.shape == (3, 0)
    # int dtypes are rejected (the -inf mask would promote to f32 where
    # ints >= 2^24 collide and tie order diverges from top_k)
    with pytest.raises(TypeError):
        topk_peel(jnp.asarray(np.array([[1, 2, 3]], np.int32)), 2)


def test_sinkhorn_tol_vmap_batch_independence():
    """Under vmap the tol early-exit's while_loop runs until the SLOWEST
    problem converges; each problem's per-window live mask must freeze
    its potentials the iteration after its own delta clears tol, so a
    problem's plan is bitwise identical whether it was solved alone or
    batched with an arbitrarily slow neighbour (the documented batch
    semantics in sinkhorn_log's docstring)."""
    from traceweaver_tpu.ops.sinkhorn import sinkhorn_log

    rng = np.random.default_rng(0)
    n, m = 8, 8
    # sharp scores: converges in a handful of iterations
    easy = (np.eye(n, m) * 50.0 + rng.normal(0, 0.1, (n, m))).astype(
        np.float32)
    # near-flat scores at high entropy: grinds toward the iteration cap
    hard = rng.normal(0, 1e-3, (n, m)).astype(np.float32)
    r = np.ones(n, np.float32)
    c = np.ones(m, np.float32)
    kw = dict(epsilon=1.0, n_iters=200, tol=1e-6)

    solo = np.asarray(sinkhorn_log(jnp.asarray(easy), jnp.asarray(r),
                                   jnp.asarray(c), **kw))
    from functools import partial

    batched = jax.vmap(partial(sinkhorn_log, **kw))
    both = np.asarray(batched(
        jnp.asarray(np.stack([easy, hard])),
        jnp.asarray(np.stack([r, r])), jnp.asarray(np.stack([c, c]))))
    np.testing.assert_array_equal(solo, both[0])
