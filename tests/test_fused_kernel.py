"""Fused persistent-sweep kernel vs the pure-jnp reference path.

The Pallas kernel (ops/pallas_sinkhorn.fused_assign_pallas) runs the
Sinkhorn solve, greedy rounding, and the small-k peel in ONE kernel with
the plan VMEM-resident; off-TPU the solver composes the same stages as
separate jitted programs (assign_topk_jnp). The contract is exact
agreement of the integer outputs — hard assignments and the
mass-filtered top-k ranking — across randomized window/endpoint
geometries, including padded (invalid) rows and endpoints with no valid
candidate columns. Runs in interpret mode on CPU (the kernel's
rounding/peel bodies are the SAME functions the jnp path jits, so this
pins the kernel plumbing and the Sinkhorn-loop equivalence).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from traceweaver_tpu.ops.pallas_sinkhorn import (
    NEG,
    assign_topk_jnp,
    fused_assign_pallas,
)

jax.config.update("jax_platforms", "cpu")


def _random_block(rng, W, M, all_masked_cols=False, some_invalid_rows=True):
    """One OT block in the solver's layout: [W+1, M+1] scores (dummy
    surplus row, skip column), marginals, validity masks, skip cap."""
    S = rng.normal(scale=5.0, size=(W + 1, M + 1)).astype(np.float32)
    in_v = (rng.random(W) > 0.25) if some_invalid_rows else np.ones(W, bool)
    if not in_v.any():
        in_v[0] = True
    o_v = np.zeros(M, bool) if all_masked_cols else rng.random(M) > 0.25
    cap = float(rng.integers(0, 4))
    n_rows = float(in_v.sum())
    n_cols = float(o_v.sum())
    cap_e = max(cap, max(n_rows - n_cols, 0.0))
    row_marg = np.concatenate(
        [in_v.astype(np.float32),
         [max(n_cols + cap_e - n_rows, 0.0)]]).astype(np.float32)
    col_marg = np.concatenate(
        [o_v.astype(np.float32), [cap_e]]).astype(np.float32)
    col_valid = np.concatenate([o_v, [cap_e > 0]])
    S = np.where(np.concatenate([in_v, [True]])[:, None]
                 & col_valid[None, :], S, NEG).astype(np.float32)
    return S, row_marg, col_marg, in_v, col_valid, np.float32(cap_e)


@pytest.mark.parametrize("tol", [0.0, 1e-3])
def test_fused_kernel_matches_jnp_randomized(tol):
    rng = np.random.default_rng(7)
    for trial in range(12):
        W = int(rng.integers(3, 24))
        M = int(rng.integers(6, 48))
        S, rm, cm, in_v, cv, cap = _random_block(rng, W, M)
        kw = dict(epsilon=1.0, n_iters=40, tol=tol, topk=5,
                  min_topk_mass=1e-3)
        a_ref, tk_ref = assign_topk_jnp(
            jnp.asarray(S), jnp.asarray(rm), jnp.asarray(cm),
            jnp.asarray(in_v), jnp.asarray(cv), jnp.asarray(cap), W, **kw)
        a_k, tk_k = fused_assign_pallas(
            jnp.asarray(S), jnp.asarray(rm), jnp.asarray(cm),
            jnp.asarray(cap), W, interpret=True, **kw)
        assert np.array_equal(np.asarray(a_ref), np.asarray(a_k)), (
            f"trial {trial} (W={W}, M={M}): assignments diverge")
        assert np.array_equal(np.asarray(tk_ref), np.asarray(tk_k)), (
            f"trial {trial} (W={W}, M={M}): top-k diverges")


def test_fused_kernel_all_masked_endpoint():
    """An endpoint with NO valid candidate columns (every column padded)
    must send every valid row to the skip column or nowhere — exactly
    what the jnp path does — not crash or fabricate columns."""
    rng = np.random.default_rng(3)
    for cap_zero in (True, False):
        W, M = 9, 12
        S, rm, cm, in_v, cv, cap = _random_block(
            rng, W, M, all_masked_cols=True)
        if cap_zero:
            # no skip capacity either: the whole block is infeasible
            cm[-1] = 0.0
            cv[-1] = False
            cap = np.float32(0.0)
        kw = dict(epsilon=1.0, n_iters=30, tol=0.0, topk=4,
                  min_topk_mass=1e-3)
        a_ref, tk_ref = assign_topk_jnp(
            jnp.asarray(S), jnp.asarray(rm), jnp.asarray(cm),
            jnp.asarray(in_v), jnp.asarray(cv), jnp.asarray(cap), W, **kw)
        a_k, tk_k = fused_assign_pallas(
            jnp.asarray(S), jnp.asarray(rm), jnp.asarray(cm),
            jnp.asarray(cap), W, interpret=True, **kw)
        assert np.array_equal(np.asarray(a_ref), np.asarray(a_k))
        assert np.array_equal(np.asarray(tk_ref), np.asarray(tk_k))
        if cap_zero:
            assert (np.asarray(a_k) == -1).all()
            assert (np.asarray(tk_k) == -1).all()


def test_fused_kernel_under_vmap_matches_per_window():
    """The solver calls the kernel under vmap (one grid program per
    window); each window's result must equal its solo solve."""
    rng = np.random.default_rng(11)
    B, W, M = 5, 8, 10
    blocks = [_random_block(rng, W, M) for _ in range(B)]
    S = jnp.asarray(np.stack([b[0] for b in blocks]))
    rm = jnp.asarray(np.stack([b[1] for b in blocks]))
    cm = jnp.asarray(np.stack([b[2] for b in blocks]))
    cap = jnp.asarray(np.stack([b[5] for b in blocks]))
    from functools import partial

    run = jax.vmap(partial(fused_assign_pallas, n_rows=W, epsilon=1.0,
                           n_iters=30, tol=1e-3, topk=3, interpret=True))
    a, tk = run(S, rm, cm, cap)
    for b, (Sb, rmb, cmb, in_v, cv, capb) in enumerate(blocks):
        a1, tk1 = fused_assign_pallas(
            jnp.asarray(Sb), jnp.asarray(rmb), jnp.asarray(cmb),
            jnp.asarray(capb), W, epsilon=1.0, n_iters=30, tol=1e-3,
            topk=3, interpret=True)
        assert np.array_equal(np.asarray(a[b]), np.asarray(a1)), b
        assert np.array_equal(np.asarray(tk[b]), np.asarray(tk1)), b


def test_solver_end_to_end_with_fused_interpret_kernel(monkeypatch):
    """Full solve_windows on synthetic tensors with the fused kernel
    forced (interpret mode) must reproduce the default XLA path's
    outputs. The block is sized past the small-block gate so the kernel
    actually engages."""
    from traceweaver_tpu.algorithms.weaver_tpu import solve_windows

    rng = np.random.default_rng(0)
    B, E, W, M, K = 2, 2, 96, 96, 3
    in_start = jnp.asarray(
        np.sort(rng.uniform(0, 3000, (B, W)), axis=1).astype(np.float32))
    in_end = in_start + 200
    out_start = jnp.asarray(np.sort(
        rng.uniform(0, 3100, (B, E, M)), axis=2).astype(np.float32))
    pred_mask = np.zeros((E, E), bool)
    pred_mask[1, 0] = True
    root_mask = np.array([True, False])
    is_last = np.array([False, True])
    wt = np.zeros((E, E, K), np.float32); wt[..., 0] = 1
    mu = np.full((E, E, K), 10.0, np.float32)
    sd = np.full((E, E, K), 5.0, np.float32)
    iwt = np.zeros((E, K), np.float32); iwt[:, 0] = 1
    imu = np.full((E, K), 10.0, np.float32)
    isd = np.full((E, K), 5.0, np.float32)
    args = (in_start, in_end, jnp.ones((B, W), bool),
            out_start, out_start + 5, jnp.ones((B, E, M), bool),
            jnp.zeros((B, E), jnp.float32), jnp.zeros((B, E, W), bool),
            jnp.asarray(pred_mask), jnp.asarray(root_mask),
            jnp.asarray(is_last),
            jnp.asarray(wt), jnp.asarray(mu), jnp.asarray(sd),
            jnp.asarray(iwt), jnp.asarray(imu), jnp.asarray(isd),
            jnp.asarray(iwt), jnp.asarray(imu), jnp.asarray(isd))
    kw = dict(n_sinkhorn=10, n_sweeps=2, sinkhorn_tol=1e-3)

    monkeypatch.delenv("TW_PALLAS", raising=False)
    monkeypatch.delenv("TW_PALLAS_INTERPRET", raising=False)
    base = solve_windows(*args, **kw)

    monkeypatch.setenv("TW_PALLAS", "1")
    monkeypatch.setenv("TW_PALLAS_INTERPRET", "1")
    fused = solve_windows(*args, **kw)

    for name, a, b in zip(("assign", "topk", "not_best", "feas"),
                          base, fused):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name
