"""Subset-accuracy regression gate: flagship TPU solver vs exact path.

Round 4's bench showed hotel/frontend TPU 0.80 vs exact 1.00 on an n=25
same-input subset — noise or regression? This gate makes the comparison
deterministic (VERDICT r4 #3): n=100 incoming spans per service,
hotel+media at load25 with the bench's compress x10 — NOT load150,
because there the exact DFS+MWIS side cannot finish hotel/frontend
n=100 inside a 20-minute alarm on this host (measured DNF; see
record_exact_gate.py's docstring), which would starve the gate. TPU
side is solved fresh here; exact side comes from the committed
recording ``tests/data/exact_gate_recorded.json`` (regenerate:
``python exps/parity/record_exact_gate.py`` — exact solves cost minutes
per service, far over unit-test budget).

Gate: per service, TPU accuracy >= exact accuracy - EPS; and the mean
delta over services >= 0 (the bench's ``accuracy_delta_same_inputs``
acceptance). Reference accuracy definitions: helpers/utils.py:62-79.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RECORD = os.path.join(REPO, "tests", "data", "exact_gate_recorded.json")
EPS = 0.02


def _load_recorder_module():
    path = os.path.join(REPO, "exps", "parity", "record_exact_gate.py")
    spec = importlib.util.spec_from_file_location("record_exact_gate", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def gate_sides():
    if not os.path.exists(RECORD):
        pytest.skip("exact_gate_recorded.json not generated yet")
    with open(RECORD) as f:
        recorded = json.load(f)

    rec_mod = _load_recorder_module()
    assert recorded["gate_spans"] == rec_mod.GATE_SPANS
    assert recorded["compress"] == rec_mod.COMPRESS

    from traceweaver_tpu.algorithms.fleet import FleetItem, solve_fleet
    from traceweaver_tpu.metrics import accuracy_for_service

    import copy

    problems = rec_mod.build_gate_problems()
    items = [
        FleetItem(svc, copy.deepcopy(sub_in), out_parts,
                  copy.deepcopy(sub_ta), dag, store=store)
        for label, svc, sub_in, out_parts, sub_ta, dag, store in problems
    ]
    outs = solve_fleet(items)
    tpu = {}
    for (label, svc, sub_in, out_parts, sub_ta, dag, store), out in zip(
            problems, outs):
        tpu[label] = accuracy_for_service(
            out[0], copy.deepcopy(sub_ta), sub_in)
    return tpu, recorded["services"]


def test_tpu_within_eps_of_exact_per_service(gate_sides):
    tpu, exact = gate_sides
    finished = {k: v for k, v in exact.items() if v.get("finished")}
    assert len(finished) >= 4, "gate needs a meaningful service set"
    for label, rec in finished.items():
        assert label in tpu, f"gate problem set lost {label}"
        assert tpu[label] >= rec["accuracy"] - EPS, (
            f"{label}: TPU {tpu[label]:.4f} < exact {rec['accuracy']:.4f}"
            f" - {EPS} — the r04 subset-accuracy signal is a regression,"
            " not noise")


def test_mean_delta_nonnegative(gate_sides):
    tpu, exact = gate_sides
    deltas = [tpu[k] - v["accuracy"] for k, v in exact.items()
              if v.get("finished") and k in tpu]
    assert deltas
    mean = sum(deltas) / len(deltas)
    assert mean >= 0.0, (
        f"mean same-input accuracy delta {mean:.4f} < 0 over {len(deltas)}"
        " services")
