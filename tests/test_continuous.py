"""Continuous-batching dispatch scheduler (serve/continuous.py).

Contracts pinned here:

- admission mechanics (unit level, deterministic): SLO-at-risk windows
  jump the queue, dispatches are class-coherent (one pow2 size class per
  dispatch — the zero-recompile lattice), round-robin fill across
  tenants (a hot tenant cannot monopolize admission), scheduler
  ready()/take() at-most-once semantics;
- end-to-end: a continuous service emits exactly what the fixed pump
  emits for the same feed; a lone sealed window below the fill target
  still dispatches (SLO urgency — no starvation by batch-fill greed);
- the fairness regression: one tenant at 100× the rate of the rest must
  not push the slow tenants' seal→emit p99 past the SLO;
- steady state: re-feeding identical shape classes through the
  continuous loop costs ZERO backend compiles (the admission lattice is
  bounded).

Synthetic feeds, JAX_PLATFORMS=cpu — tier-1.
"""

import os
import threading
import time

import pytest

import jax

# import-order bootstrap: initializing the runtime package first avoids
# the ingest<->runtime circular-import trap a bare serve-first import
# trips (the ingest package is mid-initialization when runtime.executor
# re-imports it)
import traceweaver_tpu.runtime.knobs  # noqa: F401  (import order)

from traceweaver_tpu.serve import ServeConfig, TenantService
from traceweaver_tpu.serve.continuous import ContinuousDispatcher
from traceweaver_tpu.stream.scheduler import MicroBatchScheduler
from traceweaver_tpu.stream.window import WindowBuffer

jax.config.update("jax_platforms", "cpu")

pytestmark = pytest.mark.devcols


def _trace(i, prefix, base_us, n_spans=5):
    """One synthetic frontend->search->geo Jaeger trace (fix=2)."""
    T = base_us + i * 10_000.0
    tid = f"{prefix}{i:04d}"

    def span(sid, start, dur, op, refs, pid, kind):
        return dict(traceID=tid, spanID=sid, startTime=start, duration=dur,
                    operationName=op,
                    references=[{"traceID": tid, "spanID": r}
                                for r in refs],
                    processID=pid,
                    tags=[{"key": "span.kind", "value": kind}])

    return dict(traceID=tid, spans=[
        span("root", T, 1500.0, "HTTP GET /hotels", [], "p1", "server"),
        span("c1", T + 200, 1100.0, "call-search", ["root"], "p1",
             "client"),
        span("s1", T + 300, 600.0, "search", ["c1"], "p2", "server"),
        span("c2", T + 400, 300.0, "call-geo", ["s1"], "p2", "client"),
        span("s2", T + 450, 200.0, "geo", ["c2"], "p3", "server"),
    ], processes=dict(p1={"serviceName": "frontend"},
                      p2={"serviceName": "search"},
                      p3={"serviceName": "geo"}))


def _cfg(**kw):
    base = dict(fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
                verbose=False)
    base.update(kw)
    return ServeConfig(**base)


def _feed(svc, n_tenants=3, chunks=3, traces=3, hot=None):
    """Chunked feed: chunk k+1's event times advance the watermark past
    chunk k, so earlier windows SEAL during ingest (the admission
    loop's food). ``hot`` = (tenant index, multiplier)."""
    for chunk in range(chunks):
        for i in range(n_tenants):
            n = traces * (hot[1] if hot and i == hot[0] else 1)
            svc.ingest(f"t{i:02d}", {"data": [
                _trace(k, f"u{i}c{chunk}", base_us=(chunk + 1) * 200e6)
                for k in range(n)]})


@pytest.fixture(scope="module")
def warm_programs():
    """Compile the feed's solve shapes once per module so SLO-bounded
    assertions below measure scheduling, not first-compile walls."""
    svc = TenantService(_cfg(pump_windows=10**9))
    _feed(svc, n_tenants=3, chunks=3, traces=3)
    svc.flush()
    svc.drain()


# ---------------------------------------------------------------------------
# scheduler admission primitives
# ---------------------------------------------------------------------------

def _buf(k, n_spans, sealed_ago_s=0.0):
    buf = WindowBuffer(k, float(k), float(k) + 1.0)
    buf.spans = [None] * n_spans
    buf.sealed_wall = time.monotonic() - sealed_ago_s
    return buf


def test_scheduler_ready_and_take():
    sched = MicroBatchScheduler(lambda b: [None] * len(b), max_pending=2,
                                spill_max=8)
    bufs = [_buf(k, 4) for k in range(5)]
    for b in bufs:
        sched.offer(b)  # 2 pending, 3 spill
    assert sched.ready() == bufs
    taken = sched.take([bufs[3], bufs[1]])
    assert taken == [bufs[3], bufs[1]]
    assert sched.ready() == [bufs[0], bufs[2], bufs[4]]
    # at-most-once: re-taking already-taken buffers is a no-op
    assert sched.take([bufs[1]]) == []


def _admission_service(n_tenants=3, **cfg_kw):
    """A pump-mode service (no dispatcher thread) whose tenants we seed
    with synthetic sealed windows, for deterministic _admit tests."""
    svc = TenantService(_cfg(pump_windows=10**9, **cfg_kw))
    for i in range(n_tenants):
        svc.tenant(f"t{i:02d}")
    return svc


def test_admission_urgent_jumps_queue():
    svc = _admission_service()
    disp = ContinuousDispatcher(svc, slo_ms=10_000.0, fill_target=4)
    # plenty of fresh windows on t00, one SLO-at-risk window on t02
    for k in range(6):
        svc.tenant("t00").svc.scheduler.offer(_buf(k, 8))
    svc.tenant("t02").svc.scheduler.offer(_buf(99, 8, sealed_ago_s=60.0))
    with svc._lock:
        plan, wait = disp._admit()
    assert plan is not None and wait == 0.0
    assert plan[0][0].id == "t02", "SLO-at-risk window did not jump"
    assert disp.urgent_dispatches == 1


def test_admission_is_class_coherent_and_defers_outliers():
    svc = _admission_service()
    disp = ContinuousDispatcher(svc, slo_ms=60_000.0, fill_target=8)
    for k in range(8):
        svc.tenant("t00").svc.scheduler.offer(_buf(k, 7))       # class 8
    svc.tenant("t01").svc.scheduler.offer(_buf(50, 1000))       # class 1024
    with svc._lock:
        plan, _ = disp._admit()
    assert plan is not None
    sizes = {disp._size_class(b) for _, bufs in plan for b in bufs}
    assert sizes == {8}, f"dispatch mixed size classes: {sizes}"


def test_admission_fill_round_robins_tenants():
    svc = _admission_service(n_tenants=4)
    disp = ContinuousDispatcher(svc, slo_ms=60_000.0, fill_target=4)
    for k in range(16):
        svc.tenant("t00").svc.scheduler.offer(_buf(k, 8))       # hot
    for i in (1, 2, 3):
        svc.tenant(f"t{i:02d}").svc.scheduler.offer(_buf(100 + i, 8))
    # force a fill dispatch (enough ready windows; the deep backlog
    # grows the fill limit adaptively — pow2, capped at 4x the target)
    with svc._lock:
        plan, _ = disp._admit()
    assert plan is not None
    tenants = [t.id for t, _ in plan]
    # every slow tenant got a slot before the hot tenant filled the
    # batch — round-robin, not greed
    assert set(tenants) == {"t00", "t01", "t02", "t03"}, tenants
    per = {t.id: len(b) for t, b in plan}
    assert per["t01"] == per["t02"] == per["t03"] == 1
    n = sum(per.values())
    assert 4 <= n <= 16 and (n & (n - 1)) == 0, n  # pow2-quantized


def test_admission_waits_when_below_fill_and_no_urgency():
    svc = _admission_service()
    disp = ContinuousDispatcher(svc, slo_ms=60_000.0, fill_target=8)
    svc.tenant("t00").svc.scheduler.offer(_buf(0, 8))
    with svc._lock:
        plan, wait = disp._admit()
    assert plan is None and 0.0 < wait <= 0.25


# ---------------------------------------------------------------------------
# end-to-end
# ---------------------------------------------------------------------------

def _totals(svc):
    st = svc.stats()
    return {tid: (t["emitted_windows"], t["spans_emitted"],
                  t["traces_emitted"])
            for tid, t in st["tenants"].items()}


@pytest.mark.slow
def test_continuous_emits_exactly_what_the_pump_emits(warm_programs):
    fixed = TenantService(_cfg(pump_windows=4))
    _feed(fixed)
    fixed.flush()
    want = _totals(fixed)
    fixed.drain()

    cont = TenantService(_cfg(continuous=True, slo_p99_ms=30_000.0,
                              pump_windows=4))
    _feed(cont)
    cont.flush()
    deadline = time.time() + 30
    while (cont.total_backlog() or cont.in_flight_windows()) \
            and time.time() < deadline:
        time.sleep(0.02)
    got = _totals(cont)
    cont.drain()
    assert got == want


def test_lone_window_dispatches_without_flush(warm_programs):
    """Batch-fill greed must not starve a lone sealed window: the SLO
    deadline admits it even though the fill target is far away."""
    svc = TenantService(_cfg(continuous=True, slo_p99_ms=500.0,
                             pump_windows=64))
    # chunk 2's ingest advances the watermark past chunk 1 -> one
    # sealed window for t00, far below the fill target
    svc.ingest("t00", {"data": [_trace(k, "a", base_us=200e6)
                                for k in range(3)]})
    svc.ingest("t00", {"data": [_trace(k, "b", base_us=400e6)
                                for k in range(3)]})
    deadline = time.time() + 20
    while time.time() < deadline:
        if svc.stats()["tenants"]["t00"]["emitted_windows"] >= 1:
            break
        time.sleep(0.05)
    st = svc.stats()
    svc.drain()
    assert st["tenants"]["t00"]["emitted_windows"] >= 1, \
        "lone sealed window never dispatched (fill-greed starvation)"
    assert st["continuous"]["dispatches"] >= 1


@pytest.mark.slow
def test_hot_tenant_cannot_starve_slow_tenants(warm_programs):
    """The fairness regression (ISSUE 11): one tenant at 100× the rate
    of the rest; the slow tenants' seal→emit p99 must stay within the
    SLO — round-robin fill + SLO queue-jumping bound their wait no
    matter how deep the hot tenant's backlog runs."""
    slo_ms = 20_000.0
    svc = TenantService(_cfg(continuous=True, slo_p99_ms=slo_ms,
                             pump_windows=4))
    _feed(svc, n_tenants=4, chunks=3, traces=1, hot=(0, 100))
    svc.flush()
    deadline = time.time() + 60
    while (svc.total_backlog() or svc.in_flight_windows()) \
            and time.time() < deadline:
        time.sleep(0.05)
    st = svc.stats()
    svc.drain()
    for tid in ("t01", "t02", "t03"):
        t = st["tenants"][tid]
        assert t["emitted_windows"] >= 3, f"{tid} starved: {t}"
        assert 0 < t["seal_emit_p99_ms"] <= slo_ms, \
            f"{tid} p99 {t['seal_emit_p99_ms']}ms blew the {slo_ms}ms SLO"
    # the hot tenant's windows all landed too (just not preferentially)
    assert st["tenants"]["t00"]["emitted_windows"] >= 3


def test_steady_state_costs_zero_backend_compiles():
    """The bounded-lattice pin: after one continuous round has compiled
    its shape classes, further rounds of the SAME classes — fresh trace
    ids, different tenant mixes, trace counts varying within a pow2
    class — must not compile anything. pow2 padding of the batch-row /
    service / refit-row-map axes plus class-coherent admission is what
    makes admission composition shape-invisible. Driven synchronously
    through the dispatcher's own admission chunking
    (``drain_backlog``) so the pin is deterministic — the threaded loop
    runs the same code paths."""
    from traceweaver_tpu.runtime.jax_cache import (
        compile_counters,
        counters_delta,
    )

    svc = TenantService(_cfg(pump_windows=10**9))  # no auto-pump
    disp = ContinuousDispatcher(svc, slo_ms=30_000.0, fill_target=4)

    def round_(prefix, tenants, counts):
        # identical event-time geometry every round (same chunk bases,
        # trace counts within one pow2 class); only ids/tenants differ
        for chunk in range(3):
            for i, tid in enumerate(tenants):
                svc.ingest(tid, {"data": [
                    _trace(k, f"{prefix}{i}c{chunk}",
                           base_us=(chunk + 1) * 200e6)
                    for k in range(counts[(chunk + i) % len(counts)])]})
        for t in svc.tenants.values():
            t.flush()
        return disp.drain_backlog()

    assert round_("w", ("t00", "t01"), (2, 3)) > 0
    before = compile_counters()
    solved = round_("x", ("t02", "t03"), (3, 2))
    delta = counters_delta(before)
    svc.drain()
    assert solved > 0
    assert delta["backend_compiles"] == 0, \
        f"steady continuous loop compiled {delta['backend_compiles']} " \
        "programs — the admission shape lattice leaked"


# ---------------------------------------------------------------------------
# stream-side SLO admission
# ---------------------------------------------------------------------------

def test_stream_slo_pressure_unit():
    from traceweaver_tpu.stream.service import (
        StreamConfig,
        StreamingReconstructor,
    )

    cfg = StreamConfig(slo_p99_ms=1000.0, verbose=False)
    svc = StreamingReconstructor(None, cfg)
    assert svc._slo_pressure() is False        # nothing sealed
    svc.scheduler.offer(_buf(0, 4, sealed_ago_s=0.0))
    assert svc._slo_pressure() is False        # fresh window: wait
    svc.scheduler.offer(_buf(1, 4, sealed_ago_s=5.0))
    assert svc._slo_pressure() is True         # past half the budget
    cfg_off = StreamConfig(verbose=False)
    svc2 = StreamingReconstructor(None, cfg_off)
    svc2.scheduler.offer(_buf(2, 4, sealed_ago_s=500.0))
    assert svc2._slo_pressure() is False       # knob unset: inert


# ---------------------------------------------------------------------------
# crash containment: the dispatcher thread must degrade, never wedge
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("inflight", [1, 2])
def test_dispatcher_crash_degrades_to_fixed_pump(tmp_path, warm_programs,
                                                 inflight):
    """An uncaught exception on the ContinuousDispatcher thread used to
    die silently with serve still accepting spans (every tenant's
    seal→emit path wedged). Now: the crash is counted + evented, the
    degraded gauge flips, the service falls back to the FIXED pump, and
    tenants keep emitting. Parametrized over the dispatch ring: with
    TW_SERVE_INFLIGHT>1 the poison fires on submit_admitted (the ring
    path the dispatcher actually calls) and containment must still land
    on the dispatcher thread via ring_raise_pending."""
    import json as _json

    from traceweaver_tpu.obs import events as obs_events
    from traceweaver_tpu.obs.registry import get_registry

    log = obs_events.EventLog(str(tmp_path / "events.jsonl"))
    prev_log = obs_events.install(log)
    svc = TenantService(_cfg(continuous=True, slo_p99_ms=50.0,
                             pump_windows=1, inflight=inflight))
    real_solve = svc.solve_admitted
    real_submit = svc.submit_admitted
    boom = lambda plan: (_ for _ in ()).throw(  # noqa: E731
        RuntimeError("boom: deliberate dispatcher crash"))
    svc.solve_admitted = boom
    svc.submit_admitted = boom
    try:
        _feed(svc, n_tenants=2, chunks=2, traces=2)
        deadline = time.time() + 30
        while svc.dispatcher is not None and time.time() < deadline:
            svc.dispatcher.kick()
            time.sleep(0.02)
        assert svc.dispatcher is None, "dispatcher crash not contained"
        st = svc.stats()
        assert st["dispatcher_degraded"] is True
        assert st["dispatch"]["dispatcher_crashes"] == 1
        snap = get_registry().snapshot()
        assert snap.get("tw_serve_dispatcher_degraded") == 1.0
        # the solve path heals once the poison is gone: ingest now pumps
        # inline (fixed-pump mode) and the stranded windows emit
        svc.solve_admitted = real_solve
        svc.submit_admitted = real_submit
        _feed(svc, n_tenants=2, chunks=2, traces=2)
        svc.flush()
        emitted = sum(t["emitted_windows"]
                      for t in svc.stats()["tenants"].values())
        assert emitted > 0, "seal→emit path stayed wedged after degrade"
    finally:
        obs_events.install(prev_log)
        svc.drain()
    recs = [_json.loads(line) for line in open(log.path) if line.strip()]
    degraded = [r for r in recs
                if r["kind"] == "serve"
                and r["event"] == "dispatcher_degraded"]
    assert len(degraded) == 1
    assert "boom" in degraded[0]["error"]


# ---------------------------------------------------------------------------
# the in-flight dispatch ring (ISSUE 19): overlap, FIFO consume, barriers
# ---------------------------------------------------------------------------

def _sink_bytes(state_dir):
    out = {}
    for ten in sorted(os.listdir(state_dir)):
        p = os.path.join(state_dir, ten, "traces.jsonl")
        if os.path.isfile(p):
            with open(p, "rb") as f:
                out[ten] = f.read()
    return out


def _quiesce(svc, timeout_s=30.0):
    deadline = time.time() + timeout_s
    while (svc.total_backlog() or svc.in_flight_windows()) \
            and time.time() < deadline:
        time.sleep(0.02)


def _manual_service(tmp_path, tag, n_tenants=1):
    """A pump-less, dispatcher-less service with naturally sealed
    windows — the fixture for driving the ticket lifecycle by hand
    (submit/_ring_dispatch/complete in controlled orders)."""
    svc = TenantService(_cfg(state_dir=str(tmp_path / tag),
                             pump_windows=10**9))
    _feed(svc, n_tenants=n_tenants, chunks=3, traces=3)
    return svc


def _ready_halves(svc, tid="t00"):
    with svc._lock:
        t = svc.tenants[tid]
        ready = list(t.svc.scheduler.ready())
    assert len(ready) >= 2, f"need >=2 sealed windows, got {len(ready)}"
    half = len(ready) // 2
    return t, [ready[:half], ready[half:]]


def test_serve_inflight_knob_registered_and_resolved():
    from traceweaver_tpu.runtime import knobs

    k = dict(knobs.REGISTRY)["TW_SERVE_INFLIGHT"]
    assert k.type == "int" and k.lo == 1 and k.hi == 8 and k.help
    assert knobs.get_int("TW_SERVE_INFLIGHT") == 2  # overlap is the default
    assert _cfg().inflight == 2          # ServeConfig resolves the knob
    assert _cfg(inflight=1).inflight == 1  # explicit kill switch wins


def test_inflight_one_kill_switch_byte_identical(tmp_path, warm_programs):
    """The kill switch (ISSUE 19 acceptance): TW_SERVE_INFLIGHT=1 runs
    the serial admit→solve→consume dispatcher and its emitted sinks are
    byte-identical to the fixed pump (the pre-ring reference the serial
    dispatcher was already pinned against); the default ring (inflight=2)
    must ALSO emit identical bytes — FIFO consume keeps per-tenant
    emission order, so overlap moves wall time, never content."""
    def run(tag, **kw):
        d = str(tmp_path / tag)
        svc = TenantService(_cfg(state_dir=d, pump_windows=4, **kw))
        _feed(svc, n_tenants=2, chunks=3, traces=3)
        svc.flush()
        _quiesce(svc)
        st = svc.stats()
        svc.drain()
        return _sink_bytes(d), st

    pump_bytes, _ = run("pump")
    ser_bytes, ser_st = run("serial", continuous=True,
                            slo_p99_ms=30_000.0, inflight=1)
    ring_bytes, ring_st = run("ring", continuous=True,
                              slo_p99_ms=30_000.0, inflight=2)
    assert ser_bytes == pump_bytes
    assert ring_bytes == pump_bytes
    # structural: inflight=1 never runs the worker pool, the ring does
    assert ser_st["ring"]["enabled"] is False
    assert ser_st["ring"]["inflight_limit"] == 1
    assert ring_st["ring"]["enabled"] is True
    assert ring_st["ring"]["submitted"] == ring_st["ring"]["completed"]
    assert ring_st["ring"]["outstanding"] == 0
    assert ring_st["ring"]["aborted"] == 0


def test_ticket_fifo_consume_and_out_of_order_dispatch(tmp_path,
                                                       warm_programs):
    """Two tickets submitted back-to-back, dispatched OUT of order:
    ticket 2's complete must block until ticket 1 retires (FIFO consume
    is what keeps per-tenant emission order serial), per-tenant
    in_flight retires per ticket (identity removal, not clear), and the
    final bytes equal the serial composition's."""
    serial = _manual_service(tmp_path, "serial")
    t, plans = _ready_halves(serial)
    for p in plans:
        assert serial.solve_admitted([(t, p)]) >= 1
    serial.drain()

    over = _manual_service(tmp_path, "overlap")
    t, plans = _ready_halves(over)
    tk1 = over.submit_admitted([(t, plans[0])])
    tk2 = over.submit_admitted([(t, plans[1])])
    assert tk1 is not None and tk2 is not None
    assert len(t.in_flight) == len(plans[0]) + len(plans[1])
    assert over.stats()["ring"]["outstanding"] == 2
    over._ring_dispatch(tk2)            # out of order on purpose
    over._ring_dispatch(tk1)
    done = []
    th = threading.Thread(
        target=lambda: done.append(over.complete_ticket(tk2)), daemon=True)
    th.start()
    time.sleep(0.25)
    assert th.is_alive(), "ticket 2 consumed before ticket 1 (FIFO broken)"
    n1 = over.complete_ticket(tk1)
    th.join(timeout=30)
    assert not th.is_alive() and n1 >= 1 and done and done[0] >= 1
    assert not t.in_flight                # both tickets fully retired
    st = over.stats()["ring"]
    assert st["outstanding"] == 0
    assert st["submitted"] == 2 and st["completed"] == 2
    over.drain()
    assert _sink_bytes(str(tmp_path / "overlap")) == \
        _sink_bytes(str(tmp_path / "serial"))


def test_checkpoint_skips_tenant_with_outstanding_ticket(tmp_path,
                                                         warm_programs):
    """state_dict captures the scheduler queues, not windows a ticket
    took off them: checkpoint_all must SKIP a tenant whose windows are
    riding an outstanding ticket (its last good checkpoint stays
    current) and land the checkpoint once the ticket retires."""
    svc = _manual_service(tmp_path, "ckpt")
    t, plans = _ready_halves(svc)
    tk = svc.submit_admitted([(t, plans[0] + plans[1])])
    assert tk is not None
    out = svc.checkpoint_all(timeout_s=0.3)   # bounded barrier times out
    assert out["skipped"] >= 1 and out["checkpointed"] == 0, out
    svc._ring_dispatch(tk)
    assert svc.complete_ticket(tk) >= 1
    out = svc.checkpoint_all(timeout_s=10.0)
    assert out["checkpointed"] == 1 and out["skipped"] == 0, out
    svc.drain()


def test_drain_barriers_on_outstanding_ticket_resume_byte_identical(
        tmp_path, warm_programs):
    """ISSUE 19 satellite: a drain cut while a ticket is in flight must
    barrier on the ticket (retired before state_dict, never lost), and
    the kill/resume output must stay byte-identical to an uninterrupted
    run."""
    ref = _manual_service(tmp_path, "ref")
    ref.flush()
    ref.drain()

    svc = _manual_service(tmp_path, "cut")
    t, plans = _ready_halves(svc)
    tk = svc.submit_admitted([(t, plans[0] + plans[1])])
    assert tk is not None

    def finish():
        time.sleep(0.3)
        svc._ring_dispatch(tk)
        svc.complete_ticket(tk)

    th = threading.Thread(target=finish, daemon=True)
    th.start()
    t0 = time.monotonic()
    out = svc.drain()                      # must block on the barrier
    th.join(timeout=30)
    assert time.monotonic() - t0 >= 0.25, \
        "drain returned before the outstanding ticket retired"
    assert out["checkpointed"] == 1 and out["skipped"] == 0, out
    # "kill": resume from the drained state dir, solve the remainder
    resumed = TenantService.resume(_cfg(state_dir=str(tmp_path / "cut"),
                                        pump_windows=10**9))
    resumed.flush()
    resumed.drain()
    assert _sink_bytes(str(tmp_path / "cut")) == \
        _sink_bytes(str(tmp_path / "ref"))
