"""Baseline algorithms on a real hotel_reservation slice.

Thresholds are a few points below observed values so regressions are caught
without flaking on dataset-slice choice.
"""

import pytest

from traceweaver_tpu.algorithms import FCFS, WAP5, ArrivalOrder, VPath, VPathOld
from traceweaver_tpu.ingest import build_service_problem
from traceweaver_tpu.metrics import (
    accuracy_end_to_end,
    accuracy_for_service,
    get_ground_truth,
)


def run_algo(store, algo_cls):
    pred_by, true_by = {}, {}
    for process in store.out_spans_by_process:
        prob = build_service_problem(store, process)
        if prob.skipped:
            continue
        ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
        algo = algo_cls(store.all_spans, store.all_processes)
        pred = algo.FindAssignments(
            algo_cls.__name__, process, prob.in_span_partitions,
            prob.out_span_partitions, False, [], ta,
        )
        accuracy_for_service(pred, ta, prob.in_span_partitions)  # unwraps lists
        pred_by[process], true_by[process] = pred, ta
    _, e2e = accuracy_end_to_end(pred_by, true_by, store.in_spans_by_process)
    return e2e


@pytest.mark.parametrize("algo_cls,floor", [
    (FCFS, 0.80),
    (ArrivalOrder, 0.90),
    (VPathOld, 0.65),
    (VPath, 0.75),
    (WAP5, 0.60),
])
def test_baseline_accuracy_floor(hotel_store, algo_cls, floor):
    e2e = run_algo(hotel_store, algo_cls)
    assert e2e >= floor, f"{algo_cls.__name__} e2e accuracy {e2e:.3f} < {floor}"
