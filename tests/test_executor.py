"""Executor + CLI integration tests."""

import os
import pickle
import subprocess
import sys

import pytest

from tests.conftest import ref_data


def test_run_experiment_writes_pickles(tmp_path, hotel_store):
    from traceweaver_tpu.runtime.executor import ExecutorConfig, run_experiment

    cfg = ExecutorConfig(
        data_path="",  # store provided directly
        results_directory=str(tmp_path) + "/",
        fix=2,
        cache_rate=0.0,
        test_name="hotel",
        load_level=25,
        predictor_indices=[4, 7],  # FCFS, vPath
        execute_parallel=False,
    )
    res = run_experiment(cfg, store=hotel_store)
    assert set(res.accuracy_overall) == {"FCFS", "vPath"}
    assert all(0 <= v <= 100 for v in res.accuracy_overall.values())
    suffix = "_hotel_25_1_1_0.0.pickle"
    for kind in ("bin_acc", "accuracy", "e2e", "confidence_scores",
                 "process_acc"):
        path = tmp_path / (kind + suffix)
        assert path.exists(), f"missing {path}"
    with open(tmp_path / ("accuracy" + suffix), "rb") as f:
        accuracy = pickle.load(f)
    assert accuracy == res.accuracy_overall


def test_run_experiment_flagship_topk(tmp_path, hotel_store):
    from traceweaver_tpu.runtime.executor import ExecutorConfig, run_experiment

    cfg = ExecutorConfig(
        data_path="",
        results_directory=str(tmp_path) + "/",
        fix=2,
        cache_rate=0.0,
        test_name="hotel",
        predictor_indices=[10],
        execute_parallel=True,
    )
    res = run_experiment(cfg, store=hotel_store)
    assert "MaxScoreBatchSubsetWithSkips" in res.accuracy_overall
    assert "MaxScoreBatchSubsetWithSkipsTopK" in res.accuracy_overall
    assert res.accuracy_overall["MaxScoreBatchSubsetWithSkips"] >= 95.0
    assert res.confidence_scores  # populated for the flagship method


def test_cli_end_to_end(tmp_path):
    data = ref_data("hotel_reservation/hotel_load25")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "executor.py"),
         "--absolute_path", data, "--fix", "2", "--cache_rate", "0.0",
         "--results_directory", str(tmp_path) + "/",
         "--predictor_indices", "4", "--max_traces", "20",
         "--test_name", "clitest"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert out.returncode == 0, out.stderr
    assert "End-to-end accuracy for method FCFS" in out.stdout
    assert (tmp_path / "accuracy_clitest_0_1_1_0.0.pickle").exists()


def test_cli_requires_path(tmp_path):
    out = subprocess.run(
        [sys.executable, "executor.py", "--fix", "2", "--cache_rate", "0.0",
         "--results_directory", str(tmp_path)],
        capture_output=True, text=True,
        cwd=os.path.join(os.path.dirname(__file__), ".."), timeout=60,
    )
    assert out.returncode != 0
    assert "relative_path" in out.stderr or "absolute_path" in out.stderr


def test_compile_cache_namespaced_per_host(tmp_path, monkeypatch):
    """A cache dir populated on another machine must never be read here:
    entries land under a backend+host-fingerprint subdir (round-3 driver
    logs showed cpu_aot_loader feature-mismatch errors from foreign
    entries at the cache root)."""
    from traceweaver_tpu.runtime.jax_cache import (
        enable_persistent_compilation_cache,
        host_cache_key,
    )

    monkeypatch.setenv("TW_JAX_CACHE_DIR", str(tmp_path))
    # a foreign machine's entry at the root (where rounds 1-3 wrote)
    (tmp_path / "jit_foo-deadbeef-cache").write_bytes(b"not for this host")
    used = enable_persistent_compilation_cache()
    assert os.path.dirname(used) == str(tmp_path)
    assert os.path.basename(used) == host_cache_key()
    assert os.path.isdir(used)
    # key is stable within a host and carries the platform selection
    assert host_cache_key() == host_cache_key()
    assert host_cache_key().startswith("cpu-")  # conftest pins JAX_PLATFORMS


def test_run_experiment_fleet_identical_to_per_service(hotel_store):
    """The production executor's fleet path (one fused dispatch for all
    services) must be output-identical to the per-service dispatch path
    on recorded data — same per-process accuracies, same e2e accuracy,
    same confidence inputs."""
    from traceweaver_tpu.runtime.executor import ExecutorConfig, run_experiment

    def run(fleet):
        cfg = ExecutorConfig(
            data_path="", results_directory="", fix=2, cache_rate=0.0,
            test_name="hotel", predictor_indices=[10], fleet=fleet,
        )
        return run_experiment(cfg, store=hotel_store)

    a, b = run(True), run(False)
    assert a.accuracy_per_process == b.accuracy_per_process
    assert a.accuracy_overall == b.accuracy_overall
    assert a.confidence_scores == b.confidence_scores
    assert a.candidates_per_process == b.candidates_per_process


def test_run_experiment_fleet_identical_with_cache_rate(hotel_store):
    """The exp2 workload (cache_rate > 0 -> frontend skip budget > 0) must
    run THROUGH the fleet path — single-pass dynamism dispatch groups, no
    per-service fallback — and stay output-identical to the per-service
    route (VERDICT r4 #4)."""
    from traceweaver_tpu.runtime.executor import ExecutorConfig, run_experiment

    def run(fleet):
        cfg = ExecutorConfig(
            data_path="", results_directory="", fix=2, cache_rate=0.3,
            test_name="hotel", predictor_indices=[10], fleet=fleet,
        )
        return run_experiment(cfg, store=hotel_store)

    a, b = run(True), run(False)
    assert a.accuracy_per_process == b.accuracy_per_process
    assert a.accuracy_overall == b.accuracy_overall
    assert a.confidence_scores == b.confidence_scores


def test_run_experiment_mesh_devices_identical(hotel_store):
    """TW_MESH_DEVICES / ExecutorConfig.mesh_devices: the executor's
    flagship results over an 8-device mesh must be identical to the
    single-device run (the whole multi-chip path — fleet dispatch groups
    sharded under XLA SPMD — behind the reference-compatible surface)."""
    from traceweaver_tpu.runtime.executor import ExecutorConfig, run_experiment

    def run(mesh_devices):
        cfg = ExecutorConfig(
            data_path="", results_directory="", fix=2, cache_rate=0.0,
            test_name="hotel", predictor_indices=[10],
            mesh_devices=mesh_devices,
        )
        return run_experiment(cfg, store=hotel_store)

    a, b = run(0), run(8)
    assert a.accuracy_per_process == b.accuracy_per_process
    assert a.accuracy_overall == b.accuracy_overall
