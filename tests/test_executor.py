"""Executor + CLI integration tests."""

import os
import pickle
import subprocess
import sys

import pytest

from tests.conftest import ref_data


def test_run_experiment_writes_pickles(tmp_path, hotel_store):
    from traceweaver_tpu.runtime.executor import ExecutorConfig, run_experiment

    cfg = ExecutorConfig(
        data_path="",  # store provided directly
        results_directory=str(tmp_path) + "/",
        fix=2,
        cache_rate=0.0,
        test_name="hotel",
        load_level=25,
        predictor_indices=[4, 7],  # FCFS, vPath
        execute_parallel=False,
    )
    res = run_experiment(cfg, store=hotel_store)
    assert set(res.accuracy_overall) == {"FCFS", "vPath"}
    assert all(0 <= v <= 100 for v in res.accuracy_overall.values())
    suffix = "_hotel_25_1_1_0.0.pickle"
    for kind in ("bin_acc", "accuracy", "e2e", "confidence_scores",
                 "process_acc"):
        path = tmp_path / (kind + suffix)
        assert path.exists(), f"missing {path}"
    with open(tmp_path / ("accuracy" + suffix), "rb") as f:
        accuracy = pickle.load(f)
    assert accuracy == res.accuracy_overall


def test_run_experiment_flagship_topk(tmp_path, hotel_store):
    from traceweaver_tpu.runtime.executor import ExecutorConfig, run_experiment

    cfg = ExecutorConfig(
        data_path="",
        results_directory=str(tmp_path) + "/",
        fix=2,
        cache_rate=0.0,
        test_name="hotel",
        predictor_indices=[10],
        execute_parallel=True,
    )
    res = run_experiment(cfg, store=hotel_store)
    assert "MaxScoreBatchSubsetWithSkips" in res.accuracy_overall
    assert "MaxScoreBatchSubsetWithSkipsTopK" in res.accuracy_overall
    assert res.accuracy_overall["MaxScoreBatchSubsetWithSkips"] >= 95.0
    assert res.confidence_scores  # populated for the flagship method


def test_cli_end_to_end(tmp_path):
    data = ref_data("hotel_reservation/hotel_load25")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "executor.py"),
         "--absolute_path", data, "--fix", "2", "--cache_rate", "0.0",
         "--results_directory", str(tmp_path) + "/",
         "--predictor_indices", "4", "--max_traces", "20",
         "--test_name", "clitest"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert out.returncode == 0, out.stderr
    assert "End-to-end accuracy for method FCFS" in out.stdout
    assert (tmp_path / "accuracy_clitest_0_1_1_0.0.pickle").exists()


def test_cli_requires_path(tmp_path):
    out = subprocess.run(
        [sys.executable, "executor.py", "--fix", "2", "--cache_rate", "0.0",
         "--results_directory", str(tmp_path)],
        capture_output=True, text=True,
        cwd=os.path.join(os.path.dirname(__file__), ".."), timeout=60,
    )
    assert out.returncode != 0
    assert "relative_path" in out.stderr or "absolute_path" in out.stderr
