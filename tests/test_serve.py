"""Multi-tenant reconstruction service tests (tier-1, CPU).

Contracts covered (ISSUE 6):

- end-to-end multi-tenant path: >=2 tenants POST Jaeger-JSON over HTTP
  into one running service, their windows solve in SHARED fleet
  dispatches (dispatch ledger: fewer dispatch groups than tenant-serial),
  each tenant's emitted traces match its single-tenant solve
  byte-for-byte, and a live delay_culprit query returns the planted
  culprit service;
- isolation under a fault storm: tenant 0 under ``TW_FAULTS``-style
  dispatch faults solves in isolated dispatches; other tenants' windows
  all emit, per-tenant conservation (emitted + dead-lettered == solved)
  holds, and only tenant 0 accrues quarantine/shed counts;
- per-tenant backpressure: pending bound -> spill -> counted shed, one
  tenant's burst never touching a neighbor's counters;
- the tenant id column through fleet pack/decode (per-tenant window
  buckets conserve: packed == decoded);
- tenancy guardrails (tenant cap, id validation, malformed payloads,
  strict mode) and the TW_SERVE_* knob registry.

The corpus is handcrafted Jaeger JSON (fix=2: root op "HTTP GET
/hotels", no Alibaba remapping — fully deterministic, no RNG) with a
planted culprit: every ``slow_every``-th trace spends its latency in the
``search`` service's self time.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import pytest

import jax

from traceweaver_tpu.serve import ServeConfig, TenancyError, TenantService

jax.config.update("jax_platforms", "cpu")

pytestmark = pytest.mark.serve


# ---------------------------------------------------------------------------
# handcrafted Jaeger-JSON corpus (shared with the tier-1 smoke in
# test_bench_smoke.py): frontend -> search -> geo, culprit = search
# ---------------------------------------------------------------------------

def hotel_trace(i, prefix, base_us=1_000_000.0, spacing_us=10_000.0,
                slow_every=6):
    T = base_us + i * spacing_us
    slow = (i % slow_every) == slow_every - 1
    s1_dur = 5000.0 if slow else 600.0
    c1_dur = s1_dur + 500.0
    root_dur = c1_dur + 400.0
    tid = f"{prefix}{i:03d}"

    def span(sid, start, dur, op, refs, pid, kind):
        return dict(traceID=tid, spanID=sid, startTime=start, duration=dur,
                    operationName=op,
                    references=[{"traceID": tid, "spanID": r} for r in refs],
                    processID=pid,
                    tags=[{"key": "span.kind", "value": kind}])

    spans = [
        span("root", T, root_dur, "HTTP GET /hotels", [], "p1", "server"),
        span("c1", T + 200, c1_dur, "call-search", ["root"], "p1", "client"),
        span("s1", T + 300, s1_dur, "search", ["c1"], "p2", "server"),
        span("c2", T + 400, 300.0, "call-geo", ["s1"], "p2", "client"),
        span("s2", T + 450, 200.0, "geo", ["c2"], "p3", "server"),
    ]
    return dict(traceID=tid, spans=spans,
                processes=dict(p1={"serviceName": "frontend"},
                               p2={"serviceName": "search"},
                               p3={"serviceName": "geo"}))


def hotel_payload(n_traces=24, prefix="t", base_us=1_000_000.0,
                  spacing_us=10_000.0, slow_every=6):
    return {"data": [hotel_trace(i, prefix, base_us, spacing_us, slow_every)
                     for i in range(n_traces)]}


def _cfg(**kw):
    base = dict(fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
                verbose=False, pump_windows=10**9)
    base.update(kw)
    return ServeConfig(**base)


def _run_single_tenant(tmp_path, name, payload):
    """One tenant alone through its own service (the tenant-serial
    baseline the shared-dispatch ledger is compared against)."""
    svc = TenantService(_cfg(state_dir=str(tmp_path / name)))
    svc.ingest(name, payload)
    svc.flush()
    dispatches = int(svc.fleet_stats.get("fleet_dispatches", 0))
    svc.drain()
    with open(tmp_path / name / name / "traces.jsonl", "rb") as f:
        return f.read(), dispatches


# ---------------------------------------------------------------------------
# tentpole: shared dispatches, parity, live query — over HTTP
# ---------------------------------------------------------------------------

def _http(method, url, payload=None, timeout=120):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_multi_tenant_http_end_to_end(tmp_path):
    """The acceptance path: two tenants POST Jaeger JSON over HTTP into
    one running service; one SHARED fleet dispatch solves both (ledger:
    fewer dispatch groups than the tenant-serial sum); each tenant's
    emitted traces equal its single-tenant solve byte-for-byte; the live
    delay-culprit query returns the planted culprit service."""
    from traceweaver_tpu.serve import make_server

    pay_a = hotel_payload(prefix="a")
    pay_b = hotel_payload(prefix="b", base_us=9_000_000.0)

    service = TenantService(_cfg(state_dir=str(tmp_path / "mt")))
    server = make_server(service, port=0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        code, out = _http("POST", base + "/api/v1/tenants/alpha/spans",
                          pay_a)
        assert code == 200 and out["ingested_traces"] == 24, out
        assert out["malformed_spans"] == 0
        code, out = _http("POST", base + "/api/v1/tenants/beta/spans",
                          pay_b)
        assert code == 200 and out["ingested_spans"] == 120

        code, out = _http("POST", base + "/api/v1/flush")
        assert code == 200 and out["solved_windows"] == 2, out

        code, st = _http("GET", base + "/api/v1/stats")
        assert code == 200
        # the flush just emitted, so the seal→emit window has samples;
        # the campaign's warmup boundary resets it over the wire
        assert st["tenants"]["alpha"]["seal_emit_p99_ms"] > 0.0
        code, out = _http("POST", base + "/api/v1/reset_latency_window")
        assert code == 200 and out["ok"] is True
        code, st2 = _http("GET", base + "/api/v1/stats")
        assert st2["tenants"]["alpha"]["seal_emit_p99_ms"] == 0.0
        shared_dispatches = st["dispatch"]["fleet_dispatches"]
        assert st["dispatch"]["shared_solves"] == 1
        assert st["dispatch"]["tenant_batches"] == 2

        # the live query returns the planted culprit for BOTH tenants
        for tid in ("alpha", "beta"):
            code, q = _http(
                "GET", base + f"/api/v1/tenants/{tid}/query/delay_culprit"
                "?percentile=0.8")
            assert code == 200 and not q["empty"]
            assert q["worst_service"] == "search", q
            assert q["n_bracket"] > 0

        # trace fetch/list round-trips a reconstructed trace
        code, tr = _http("GET", base + "/api/v1/tenants/alpha/traces")
        assert code == 200 and tr["n_traces"] == 24
        code, rec = _http(
            "GET", base + f"/api/v1/tenants/alpha/traces/{tr['trace_ids'][0]}")
        assert code == 200 and rec["complete"] and rec["n_spans"] == 5
        assert {s["service"] for s in rec["spans"]} \
            == {"frontend", "search", "geo"}
    finally:
        server.shutdown()
        server.server_close()
    service.drain()

    # per-tenant parity: the shared-dispatch traces equal each tenant's
    # single-tenant solve byte-for-byte, with zero cross-tenant leakage
    with open(tmp_path / "mt" / "alpha" / "traces.jsonl", "rb") as f:
        got_a = f.read()
    with open(tmp_path / "mt" / "beta" / "traces.jsonl", "rb") as f:
        got_b = f.read()
    solo_a, disp_a = _run_single_tenant(tmp_path, "alpha", pay_a)
    solo_b, disp_b = _run_single_tenant(tmp_path, "beta", pay_b)
    assert got_a == solo_a and got_b == solo_b
    assert b'"b' not in got_a and b'"a0' not in got_b  # no leakage
    # the dispatch ledger's headline claim: shared < tenant-serial
    assert shared_dispatches < disp_a + disp_b, (
        f"shared {shared_dispatches} vs serial {disp_a}+{disp_b}")


def test_tenant_id_column_conserves_through_pack_and_decode():
    """The fleet's tenancy id column: per-tenant packed window counts
    equal per-tenant decoded window counts (nothing attributed to the
    wrong tenant, nothing lost between pack and decode)."""
    svc = TenantService(_cfg())
    svc.ingest("t-a", hotel_payload(prefix="a"))
    svc.ingest("t-b", hotel_payload(prefix="b", base_us=9e6))
    svc.flush()
    packed = svc.fleet_stats.get("tenant_windows_packed", {})
    decoded = svc.fleet_stats.get("tenant_windows_decoded", {})
    assert set(packed) == {"t-a", "t-b"}
    assert packed == decoded
    assert all(v > 0 for v in packed.values())


# ---------------------------------------------------------------------------
# isolation: fault storm, backpressure, conservation
# ---------------------------------------------------------------------------

def _assert_conservation(t):
    assert t["emitted_windows"] + t["deadletter_windows"] \
        == t["solved_windows"], t


def test_isolation_under_dispatch_fault_storm(monkeypatch):
    """Tenant 0 under a ``dispatch:0.5`` storm (the acceptance spec):
    its windows solve in ISOLATED dispatches under its own fault plan;
    every other tenant's windows all emit, per-tenant conservation holds,
    and only tenant 0 accrues fault-ladder/quarantine counts."""
    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    svc = TenantService(_cfg(window_us=20e6, overlap_us=4e6,
                             pump_windows=1))
    svc.tenant("t0").fault_spec = "dispatch:0.5"
    # multi-window feed (traces 5 s apart, 20 s windows): several pumps,
    # several isolated dispatches for t0 — enough seeded draws to fire
    for i, tid in enumerate(("t0", "t1", "t2")):
        svc.ingest(tid, hotel_payload(
            prefix=tid[-1], base_us=(i + 1) * 1e6, spacing_us=5e6))
    svc.flush()
    st = svc.stats()
    assert st["dispatch"]["isolated_solves"] > 0

    t0 = st["tenants"]["t0"]
    _assert_conservation(t0)
    assert t0["faults"]["injected"] > 0, (
        "the storm never fired — not an isolation test")
    for tid in ("t1", "t2"):
        t = st["tenants"][tid]
        _assert_conservation(t)
        assert t["emitted_windows"] > 0
        assert t["deadletter_windows"] == 0
        assert t["quarantined_windows"] == 0
        assert t["shed_dropped_windows"] == 0
        assert all(v == 0 for v in t["faults"].values()), t["faults"]


def test_quarantine_storm_deadletters_only_the_faulty_tenant(monkeypatch):
    """A storm that exhausts the whole supervisor ladder
    (``dispatch:1.0,host:1.0``): tenant 0's windows quarantine and
    dead-letter — counted, conserved, never silently dropped — while the
    healthy neighbor emits everything."""
    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    svc = TenantService(_cfg())
    svc.tenant("t0").fault_spec = "dispatch:1.0,host:1.0"
    svc.ingest("t0", hotel_payload(prefix="a"))
    svc.ingest("t1", hotel_payload(prefix="b", base_us=9e6))
    svc.flush()
    st = svc.stats()
    t0, t1 = st["tenants"]["t0"], st["tenants"]["t1"]
    assert t0["deadletter_windows"] > 0
    assert t0["quarantined_windows"] > 0
    assert t0["faults"]["quarantined"] > 0
    _assert_conservation(t0)
    assert t0["emitted_windows"] == 0
    assert t1["emitted_windows"] == 1 and t1["deadletter_windows"] == 0
    assert t1["quarantined_windows"] == 0
    # the poison windows landed in t0's OWN dead-letter sidecar counters,
    # and t0's ring holds no phantom traces from the poisoned windows
    assert len(svc.tenant("t0").ring) == 0
    assert len(svc.tenant("t1").ring) == 24


def test_per_tenant_backpressure_sheds_only_the_bursting_tenant():
    """Per-tenant pending -> spill -> shed: a bursting tenant fills ITS
    queues and takes ITS losses; the quiet neighbor's counters stay
    zero and its windows all solve."""
    svc = TenantService(_cfg(window_us=2e6, overlap_us=0.0,
                             ooo_bound_us=1e5,
                             max_pending=1, spill_max=1))
    # ~60 windows' worth of spans for the burster, no pump in between
    svc.ingest("burst", hotel_payload(n_traces=40, prefix="x",
                                      spacing_us=3e6))
    svc.ingest("quiet", hotel_payload(n_traces=4, prefix="q",
                                      base_us=2e6, spacing_us=1e5))
    b = svc.tenant("burst").svc.scheduler
    assert b.shed_spilled > 0
    assert b.shed_dropped_windows > 0
    q = svc.tenant("quiet").svc.scheduler
    assert q.shed_spilled == 0 and q.shed_dropped_windows == 0
    svc.flush()
    st = svc.stats()
    assert st["tenants"]["quiet"]["emitted_windows"] > 0
    assert st["tenants"]["quiet"]["shed_dropped_windows"] == 0
    # shed is quantified loss: solved + dropped covers everything sealed
    burst = st["tenants"]["burst"]
    assert burst["shed_dropped_windows"] > 0
    assert burst["emitted_windows"] > 0  # shed != starved


# ---------------------------------------------------------------------------
# guardrails: tenancy caps, ids, malformed payloads, knobs
# ---------------------------------------------------------------------------

def test_tenant_cap_and_id_validation():
    svc = TenantService(_cfg(max_tenants=2))
    svc.tenant("a")
    svc.tenant("b")
    with pytest.raises(TenancyError, match="cap"):
        svc.tenant("c")
    with pytest.raises(TenancyError, match="invalid tenant id"):
        TenantService(_cfg()).tenant("no/slashes")
    with pytest.raises(TenancyError, match="invalid tenant id"):
        TenantService(_cfg()).tenant("")


def test_malformed_spans_deadletter_and_strict_mode():
    """The ingest dead-letter path over HTTP-shaped payloads: malformed
    span records skip-and-count (the jaeger.py rule), strict raises."""
    from traceweaver_tpu.ingest.jaeger import MalformedSpan

    payload = hotel_payload(n_traces=4, prefix="m")
    payload["data"][0]["spans"][1] = {"spanID": "broken"}  # no ids/times
    svc = TenantService(_cfg())
    out = svc.ingest("m", payload)
    assert out["malformed_spans"] == 1
    assert out["ingested_traces"] == 4  # the trace survives minus the span

    strict = TenantService(_cfg(strict=True))
    with pytest.raises(MalformedSpan):
        strict.ingest("m", payload)


def test_rejected_root_op_is_counted_not_ingested():
    payload = hotel_payload(n_traces=3, prefix="r")
    for rec in payload["data"]:
        rec["spans"][0]["operationName"] = "HTTP GET /other"
    svc = TenantService(_cfg())  # fix=2 requires "HTTP GET /hotels"
    out = svc.ingest("r", payload)
    assert out["ingested_traces"] == 0
    assert out["rejected_traces"] == 3


def test_serve_knobs_registered_and_typos_raise(monkeypatch):
    from traceweaver_tpu.runtime import knobs

    for name in ("TW_SERVE_PORT", "TW_SERVE_MAX_TENANTS",
                 "TW_SERVE_PENDING", "TW_SERVE_SPILL", "TW_SERVE_RING",
                 "TW_SERVE_DRAIN_S", "TW_SERVE_PUMP_WINDOWS"):
        assert name in knobs.REGISTRY, name
    monkeypatch.setenv("TW_SERVE_PENDING", "nope")
    with pytest.raises(knobs.KnobError):
        knobs.get_int("TW_SERVE_PENDING")
    # registered knobs are not "unknown" at startup; a typo'd one is
    monkeypatch.delenv("TW_SERVE_PENDING")
    monkeypatch.setenv("TW_SERVE_RING", "16")
    monkeypatch.setenv("TW_SERVE_RNIG", "16")
    unknown = knobs.unknown_knobs()
    assert "TW_SERVE_RNIG" in unknown
    assert "TW_SERVE_RING" not in unknown
    # knob defaults actually govern ServeConfig
    assert ServeConfig().ring_size == 16


def test_ring_bound_evicts_oldest_and_query_stays_live():
    svc = TenantService(_cfg(ring_size=8))
    svc.ingest("r", hotel_payload(n_traces=24, prefix="r"))
    svc.flush()
    t = svc.tenant("r")
    assert len(t.ring) == 8
    assert t.ring.evicted == 16
    ids = t.ring.ids()
    assert ids == [f"r{i:03d}" for i in range(16, 24)]  # newest 8 kept
    q = svc.query_delay_culprit("r", percentile=0.5)
    assert not q["empty"] and q["n_traces"] == 8


def test_query_before_first_window_returns_counted_zero_result():
    svc = TenantService(_cfg())
    svc.tenant("empty")
    q = svc.query_delay_culprit("empty")
    assert q["empty"] is True
    assert q["n_traces"] == 0 and q["n_bracket"] == 0
    assert q["worst_service"] is None


def test_serve_cli_subprocess_sigterm_drains(tmp_path):
    """`python -m traceweaver_tpu.runtime.cli serve` end-to-end: boots
    on an ephemeral port, ingests over HTTP, and a SIGTERM gracefully
    drains — every tenant checkpointed (resumable) before exit."""
    import re
    import signal
    import subprocess
    import sys
    import time

    state = tmp_path / "state"
    env = dict(os.environ, JAX_PLATFORMS="cpu", TW_BACKEND="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "traceweaver_tpu.runtime.cli", "serve",
         "--port", "0", "--fix", "2", "--state-dir", str(state)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=os.path.join(os.path.dirname(__file__), ".."), env=env)
    try:
        line = ""
        deadline = time.time() + 120
        while time.time() < deadline:
            line = proc.stdout.readline()
            if "listening on" in line:
                break
            assert proc.poll() is None, "serve CLI died during startup"
        m = re.search(r"http://127\.0\.0\.1:(\d+)", line)
        assert m, f"no listen line: {line!r}"
        base = f"http://127.0.0.1:{m.group(1)}"

        code, out = _http("POST", base + "/api/v1/tenants/cli-a/spans",
                          hotel_payload(prefix="a"))
        assert code == 200 and out["ingested_traces"] == 24
        code, out = _http("POST", base + "/api/v1/flush")
        assert code == 200 and out["solved_windows"] == 1
        code, q = _http("GET", base + "/api/v1/tenants/cli-a/query/"
                               "delay_culprit?percentile=0.8")
        assert code == 200 and q["worst_service"] == "search"

        proc.send_signal(signal.SIGTERM)
        rest = proc.stdout.read()
        assert proc.wait(timeout=120) == 0, rest
        assert "drained: 1 tenants checkpointed" in rest, rest
    finally:
        if proc.poll() is None:
            proc.kill()
    # the drain checkpoint is resumable
    resumed = TenantService.resume(_cfg(state_dir=str(state)))
    assert sorted(resumed.tenants) == ["cli-a"]
    assert resumed.tenant("cli-a").svc.emitted_windows == 1
    assert len(resumed.tenant("cli-a").ring) == 24


def test_serve_cli_resume_roundtrip(tmp_path):
    """`cli serve --resume` path machinery: drain writes per-tenant
    checkpoints, TenantService.resume restores every tenant (windows
    still open at drain included — zero lost windows)."""
    cfg = _cfg(state_dir=str(tmp_path / "st"), window_us=20e6,
               overlap_us=4e6, pump_windows=1)
    svc = TenantService(cfg)
    svc.ingest("a", hotel_payload(prefix="a", spacing_us=5e6))
    svc.ingest("b", hotel_payload(n_traces=12, prefix="b", spacing_us=5e6))
    pre = {tid: svc.tenant(tid).svc.consumed for tid in ("a", "b")}
    open_windows = {tid: len(svc.tenant(tid).svc.windower.open)
                    for tid in ("a", "b")}
    assert any(v > 0 for v in open_windows.values())
    drained = svc.drain()
    assert drained["checkpointed"] == 2 and drained["timed_out"] == 0

    resumed = TenantService.resume(cfg)
    assert sorted(resumed.tenants) == ["a", "b"]
    for tid in ("a", "b"):
        t = resumed.tenant(tid)
        assert t.svc.consumed == pre[tid]
        assert len(t.svc.windower.open) == open_windows[tid]
    out = resumed.flush()  # the checkpointed open windows still solve
    assert out["solved_windows"] > 0
    resumed.drain()


def test_metrics_scrape_under_load_matches_stats_ledger(tmp_path):
    """GET /metrics (Prometheus text) under concurrent ingest load:
    scrapes stay parseable while POSTs land, and the final scrape's
    per-tenant window/dispatch/ladder counters equal the /api/v1/stats
    JSON ledger EXACTLY (the exposition derives from the same stats()
    call, so disagreement is impossible by construction — this pins it
    against refactors that would fork the two surfaces)."""
    from traceweaver_tpu.serve import make_server

    service = TenantService(_cfg(state_dir=str(tmp_path / "m")))
    server = make_server(service, port=0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.port}"

    def _scrape():
        req = urllib.request.Request(base + "/metrics")
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.status == 200
            assert "version=0.0.4" in resp.headers["Content-Type"]
            return resp.read().decode()

    def _parse(text):
        out = {}
        for line in text.splitlines():
            if line.startswith("#") or not line.strip():
                continue
            name, _, val = line.rpartition(" ")
            out[name] = float(val)
        return out

    scrape_errors = []

    def scrape_loop():
        try:
            for _ in range(10):
                _parse(_scrape())
        except Exception as e:  # noqa: BLE001 — surfaced below
            scrape_errors.append(e)

    try:
        scraper = threading.Thread(target=scrape_loop)
        scraper.start()
        # concurrent load: several tenants POSTing while scrapes run
        posters = []
        for tid in ("alpha", "beta", "gamma"):
            def post(tid=tid):
                code, out = _http(
                    "POST", base + f"/api/v1/tenants/{tid}/spans",
                    hotel_payload(prefix=tid[0]))
                assert code == 200, out
            t = threading.Thread(target=post)
            t.start()
            posters.append(t)
        for t in posters:
            t.join()
        scraper.join()
        assert not scrape_errors, scrape_errors
        code, _ = _http("POST", base + "/api/v1/flush")
        assert code == 200

        metrics = _parse(_scrape())
        code, st = _http("GET", base + "/api/v1/stats")
        assert code == 200

        # dispatch ledger: every kind, exactly
        for kind, v in st["dispatch"].items():
            assert metrics[f'tw_serve_dispatch_total{{kind="{kind}"}}'] \
                == float(v), kind
        # per-tenant window counters: every exposed field, exactly
        for tid, t in st["tenants"].items():
            for key in ("consumed", "emitted_windows", "spans_emitted",
                        "traces_emitted", "solved_windows",
                        "deadletter_windows", "quarantined_windows",
                        "ring_traces"):
                name = (f'tw_serve_tenant_total{{key="{key}",'
                        f'tenant="{tid}"}}')
                assert metrics[name] == float(t[key]), name
            # ladder counters per tenant, exactly
            for rung, v in t["faults"].items():
                name = (f'tw_serve_tenant_faults_total{{rung="{rung}",'
                        f'tenant="{tid}"}}')
                assert metrics[name] == float(v), name
        # the process registry rides the same scrape: the fleet ledger
        # mirror saw this solve's dispatches
        assert metrics.get(
            'tw_fleet_ledger_total{key="fleet_dispatches"}', 0) > 0
        assert 'tw_xla_compile_events_total{kind="backend_compiles"}' \
            in metrics
    finally:
        server.shutdown()
        server.server_close()
    service.drain()


# ---------------------------------------------------------------------------
# fleet-tier serve surface: drain readiness + explicit backpressure
# ---------------------------------------------------------------------------

def _http_raw(method, url, payload=None, timeout=30):
    """Like _http but keeps the response headers (Retry-After)."""
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers or {}), json.loads(e.read())


def test_readyz_flips_503_when_drain_begins(tmp_path):
    """The rolling-restart contract (docs/SERVING.md "Replica fleet"):
    /readyz answers 200 on a live replica and 503 the instant
    begin_drain() marks it draining — while /healthz (liveness) stays
    200, because a draining replica is alive and still finishing
    in-flight work. The fleet router's health loop keys off exactly
    this split."""
    from traceweaver_tpu.serve import make_server

    svc = TenantService(_cfg(state_dir=str(tmp_path / "drain")))
    server = make_server(svc)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        svc.ingest("ten", hotel_payload(n_traces=6))
        code, _, _ = _http_raw("GET", base + "/readyz")
        assert code == 200
        svc.begin_drain()
        code, _, body = _http_raw("GET", base + "/readyz")
        assert code == 503
        assert body["draining"] is True
        code, _, _ = _http_raw("GET", base + "/healthz")
        assert code == 200
        # idempotent: a second begin_drain leaves the same answer
        svc.begin_drain()
        code, _, _ = _http_raw("GET", base + "/readyz")
        assert code == 503
    finally:
        server.shutdown()
        server.server_close()
    svc.drain()


def test_sigterm_handler_drains_before_listener_close(tmp_path,
                                                      monkeypatch):
    """The run_server signal path: the registered SIGTERM handler flips
    draining FIRST (so any probe still landing sees 503), then shuts
    the listener down and the drain checkpoints every tenant."""
    import signal as _signal
    import time as _time

    import traceweaver_tpu.serve.http as serve_http

    handlers = {}
    monkeypatch.setattr(serve_http.signal, "signal",
                        lambda sig, h: handlers.setdefault(sig, h))
    svc = TenantService(_cfg(state_dir=str(tmp_path / "sig")))
    svc.ingest("ten", hotel_payload(n_traces=6))
    done = {}

    def _run():
        done["summary"] = serve_http.run_server(
            svc, "127.0.0.1", 0, verbose=False)

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    deadline = _time.monotonic() + 30
    while _signal.SIGTERM not in handlers:
        assert _time.monotonic() < deadline, "SIGTERM handler never set"
        _time.sleep(0.01)
    handlers[_signal.SIGTERM](_signal.SIGTERM, None)
    assert svc.draining, "handler must flip draining synchronously"
    t.join(timeout=60)
    assert not t.is_alive()
    assert done["summary"]["checkpointed"] == 1
    assert os.path.isfile(tmp_path / "sig" / "ten" / "ckpt.pkl")


def test_backpressure_429_sets_retry_after_header(tmp_path):
    """Saturated per-tenant queues refuse the POST — 429 with a
    Retry-After header derived from backlog x drain pace — instead of
    dropping sealed windows. The admission check keeps headroom below
    the hard pending+spill bound, so the bursty seal that follows an
    accepted POST (watermark advance can seal several windows at once)
    never overflows into shed_dropped_windows. After a flush drains
    the backlog, the refused window POSTs clean — nothing was lost."""
    from traceweaver_tpu.serve import make_server

    svc = TenantService(_cfg(state_dir=str(tmp_path / "bp"),
                             max_pending=1, spill_max=2))
    server = make_server(svc)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{server.port}/api/v1/tenants/bp/spans"
    try:
        refused = None
        for seq in range(12):
            payload = hotel_payload(n_traces=2, prefix=f"s{seq}-",
                                    base_us=seq * 60e6 + 10e6)
            code, headers, body = _http_raw("POST", url, payload)
            if code == 429:
                refused = (payload, headers, body)
                break
            assert code == 200, body
        assert refused is not None, "backpressure never fired"
        payload, headers, body = refused
        # drain-rate-derived waits are fractional since the in-flight
        # ring (r19): sub-second values are the point — no 1s floor
        assert float(headers["Retry-After"]) >= 0.05
        assert "backpressured" in body["error"]
        # the headroom contract: refusal came BEFORE any window dropped
        st = svc.stats("bp")
        assert st["shed_dropped_windows"] == 0
        assert svc.stats()["dispatch"]["backpressure_429s"] >= 1
        # drain, then the refused window retries through unchanged
        svc.flush()
        code, _, _ = _http_raw("POST", url, payload)
        assert code == 200
    finally:
        server.shutdown()
        server.server_close()
    svc.flush()
    st = svc.stats("bp")
    assert st["shed_dropped_windows"] == 0
    assert st["traces_emitted"] == st["counters"]["ingested_traces"]
    svc.drain()
