"""Alibaba pipeline tests: schema, repair, convert, group, synthesize."""

import csv
import os

import pytest

from traceweaver_tpu.alibaba import (
    CallRecord,
    call_graph_signature,
    convert_trace_to_jaeger,
    repair_trace,
)
from traceweaver_tpu.alibaba.preprocess import split_all
from traceweaver_tpu.alibaba.synthesize import synthesize_corpus


def _rec(tid, rpc_id, caller, callee, ts=1000, rt=10):
    return CallRecord(tid, ts, rpc_id, caller, "rpc", callee, "if", rt)


def test_repair_sorts_and_validates():
    recs = [_rec("t", "0.1", "A", "B"), _rec("t", "0", "USER", "A"),
            _rec("t", "0.1.1", "B", "C")]
    fixed = repair_trace(recs)
    assert [r.rpc_id for r in fixed] == ["0", "0.1", "0.1.1"]


def test_repair_rejects_orphans_and_multiroots():
    assert repair_trace([_rec("t", "0", "U", "A"),
                         _rec("t", "0.2.1", "B", "C")]) is None
    assert repair_trace([_rec("t", "0", "U", "A"),
                         _rec("t", "1", "U", "B")]) is None


def test_repair_dedupes_mirrored_rows():
    good = _rec("t", "0.1", "A", "B", rt=10)
    mirror = _rec("t", "0.1", "A", "B", rt=-10)
    fixed = repair_trace([_rec("t", "0", "U", "A"), good, mirror])
    assert len(fixed) == 2
    assert fixed[1].rt_ms == 10


def test_repair_fills_missing_caller_from_parent():
    recs = [_rec("t", "0", "USER", "A"), _rec("t", "0.1", "(?)", "B")]
    fixed = repair_trace(recs)
    assert fixed[1].caller == "A"


def test_convert_emits_server_client_pairs():
    recs = repair_trace([_rec("t1", "0", "USER", "A"),
                         _rec("t1", "0.1", "A", "B")])
    doc = convert_trace_to_jaeger(recs)
    spans = doc["data"][0]["spans"]
    assert len(spans) == 3  # root server + child server/client pair
    kinds = [(s["spanID"], s["tags"][0]["value"]) for s in spans]
    assert ("0", "server") in kinds
    assert ("0.1", "server") in kinds and ("0.1", "client") in kinds
    client = next(s for s in spans if s["tags"][0]["value"] == "client")
    assert client["processID"] == "A"  # lives on the caller
    assert client["startTime"] == 1000 * 1000  # ms -> µs


def test_signature_groups_same_topology():
    a = [_rec("x", "0", "U", "A"), _rec("x", "0.1", "A", "B")]
    b = [_rec("y", "0", "U", "A", ts=9999), _rec("y", "0.1", "A", "B", ts=9999)]
    c = [_rec("z", "0", "U", "A"), _rec("z", "0.1", "A", "C")]
    assert call_graph_signature(a) == call_graph_signature(b)
    assert call_graph_signature(a) != call_graph_signature(c)


def test_split_all(tmp_path):
    rows = [["0", "t1", "100", "0", "U", "rpc", "A", "if", "5"],
            ["1", "t2", "200", "0", "U", "rpc", "B", "if", "5"]]
    csv_path = tmp_path / "MSCallGraph_0.csv"
    with open(csv_path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["", "traceid", "timestamp", "rpcid", "um", "rpctype",
                    "dm", "interface", "rt"])
        w.writerows(rows)
    n = split_all([str(csv_path)], str(tmp_path / "out"))
    assert n == 2
    assert (tmp_path / "out" / "shard0" / "t1.csv").exists()


def test_synthesize_and_reconstruct(tmp_path):
    from traceweaver_tpu.algorithms.weaver_tpu import WeaverTPU
    from traceweaver_tpu.ingest import (
        build_service_problem,
        infer_invocation_dag,
        load_corpus,
    )
    from traceweaver_tpu.metrics import accuracy_for_service, get_ground_truth

    dirs = synthesize_corpus(str(tmp_path), n_graphs=2, traces_per_graph=40,
                             seed=7)
    assert len(dirs) == 2
    store = load_corpus(dirs[0], fix=5, max_traces=40, cache=False)
    assert store.services()
    solved = 0
    for svc in store.out_spans_by_process:
        prob = build_service_problem(store, svc)
        if prob.skipped:
            continue
        ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
        dag = infer_invocation_dag(prob.in_span_partitions,
                                   prob.out_span_partitions, ta, store)
        algo = WeaverTPU(store.all_spans, store.all_processes)
        out = algo.FindAssignments(
            "MaxScoreBatchSubsetWithSkips", svc, prob.in_span_partitions,
            prob.out_span_partitions, False, [], ta, dag)
        assert accuracy_for_service(out[0], ta, prob.in_span_partitions) > 0.8
        solved += 1
    assert solved >= 1


def test_synthesize_writes_replica_table(tmp_path):
    """The generator must regenerate the reference's missing
    ``data/misc/service_to_replica_new.pickle`` artifact (loaded
    unconditionally at reference executor.py:912 and used to divide the
    compress factor per service, :922-929) next to the corpus, with
    Alibaba-like replica counts."""
    import pickle

    out = tmp_path / "alibaba_microservices" / "call_graph_data"
    synthesize_corpus(str(out), n_graphs=1, traces_per_graph=10, seed=7)
    table_path = tmp_path / "misc" / "service_to_replica_new.pickle"
    assert table_path.exists()
    with open(table_path, "rb") as f:
        table = pickle.load(f)
    assert len(table) == 60  # every MS_* service has an entry
    assert all(16 <= len(replicas) <= 128 for replicas in table.values())
    # deterministic: same seed regenerates the identical table
    synthesize_corpus(str(out), n_graphs=1, traces_per_graph=10, seed=7)
    with open(table_path, "rb") as f:
        assert pickle.load(f) == table


def test_repair_fills_missing_callee_from_child():
    recs = [_rec("t", "0", "USER", "A"), _rec("t", "0.1", "A", "(?)"),
            _rec("t", "0.1.1", "B", "C")]
    fixed = repair_trace(recs)
    assert fixed[1].callee == "B"


def test_repair_rejects_unrepairable_missing_leaf():
    # a leaf's '(?)' callee has no child row to fill from -> whole trace
    # rejected (reference real-parser.py:179-187 returns unfixable)
    assert repair_trace([_rec("t", "0", "U", "A"),
                         _rec("t", "0.1", "A", "(?)")]) is None


def test_synthesize_messy_corpus_repairs_and_rejects(tmp_path):
    """The hard corpus (VERDICT r4 #5): defects are injected BEFORE
    repair, repairable classes survive, structural corruption is
    rejected, and grouped datasets still come out the other end."""
    from traceweaver_tpu.alibaba.synthesize import MESSY_DEFAULT

    stats = {}
    dirs = synthesize_corpus(str(tmp_path / "cg"), n_graphs=2,
                             traces_per_graph=60, seed=7,
                             messy=MESSY_DEFAULT, stats=stats)
    assert stats["defect_injected"] > 0
    assert stats["dropped"] > 0, "structural corruption must be rejected"
    assert stats["kept"] > stats["dropped"], \
        "repairable defects must survive repair"
    assert stats["kept"] + stats["dropped"] == stats["emitted"]
    assert dirs, "grouped call-graph datasets must still be produced"


def test_synthesize_messy_multi_invocation_callees(tmp_path):
    """multi_invoke emits services that are callees of several calls in
    one trace (violating the clean-corpus invariant the way real
    MSCallGraph data does); the ingest pipeline must carry them without
    crashing — multi-upstream services end up skipped by the partitioner
    exactly as in the reference (executor.py:949-950)."""
    from traceweaver_tpu.ingest import build_service_problem, load_corpus

    dirs = synthesize_corpus(
        str(tmp_path / "cg"), n_graphs=3, traces_per_graph=40, seed=11,
        messy={"multi_invoke": 0.5})
    multi = 0
    for d in dirs:
        store = load_corpus(d, fix=5, max_traces=40, cache=False)
        for svc, spans in store.in_spans_by_process.items():
            by_trace = {}
            for s in spans:
                by_trace[s.trace_id] = by_trace.get(s.trace_id, 0) + 1
            if any(v > 1 for v in by_trace.values()):
                multi += 1
        for svc in store.out_spans_by_process:
            build_service_problem(store, svc)  # must not raise
    assert multi > 0, "expected at least one multi-invocation callee"


def test_replica_dist_knob():
    from traceweaver_tpu.alibaba.synthesize import replica_counts

    svcs = [f"MS_{i:05d}" for i in range(10)]
    fixed = replica_counts(svcs, seed=7, dist="fixed-64")
    assert set(fixed.values()) == {64}
    lo = replica_counts(svcs, seed=7, dist="loguniform-4-32")
    assert all(4 <= v <= 32 for v in lo.values())
    # deterministic per seed
    assert lo == replica_counts(svcs, seed=7, dist="loguniform-4-32")


def test_executor_replica_scaling_divides_compress(tmp_path):
    """ExecutorConfig.replica_count feeds ceil(compress/replicas)
    (reference executor.py:922-929): a 15000x corpus factor over ~100
    replicas must land the per-service load factor in the identifiable
    100-1000x regime, not at the raw floor."""
    import math
    import pickle

    from traceweaver_tpu.ingest import load_corpus
    from traceweaver_tpu.runtime.executor import ExecutorConfig

    out = tmp_path / "alibaba_microservices" / "call_graph_data"
    dirs = synthesize_corpus(str(out), n_graphs=1, traces_per_graph=10,
                             seed=7)
    with open(tmp_path / "misc" / "service_to_replica_new.pickle",
              "rb") as f:
        table = pickle.load(f)
    store = load_corpus(dirs[0], fix=5, max_traces=10, cache=False)
    cfg = ExecutorConfig(data_path="", results_directory="", fix=5,
                         cache_rate=0.0, compress_factor=15000,
                         service_to_replica=table)
    factors = [
        math.ceil(15000 / cfg.replica_count(svc, store))
        for svc in store.out_spans_by_process
    ]
    assert factors and all(100 <= f <= 1000 for f in factors), factors
