"""Zero-object wire-ingest tests (tier-1, CPU) — ISSUE 18.

Contracts covered:

- the DEFAULT POST path is columnar: an eligible Jaeger-JSON body over
  real HTTP never touches the object parser (a spy on
  ``parse_trace_payload`` must not fire), and the tenant's ledger
  counts the post under ``tw_wire_ingest_total{path="columnar"}``;
- ``TW_WIRE_COLUMNAR=0`` byte parity: the same posted bytes produce a
  byte-identical ``traces.jsonl`` under both knob settings (the knob
  moves time, never output);
- front-end parity: the pure-Python wire front end (``TW_DISABLE_NATIVE
  =1``) and the native loader agree with the object parser on
  randomized adversarial payloads — accepted spans, dead-letter
  counters, AND raised exceptions;
- malformed dead-letter accounting is preserved on the columnar path
  (skip-and-count non-strict, ``MalformedSpan`` under strict — strict
  falls back to the object parser by design);
- stitch equivalence: the batched array BFS (``_stitch_arrays``) equals
  the per-root object DFS (``_stitch_objects``) on randomized DAGs with
  phantom out-ids, NA/SKIP assignments, and shared subgraphs;
- the native-loads-or-fallback contract: every wire parse increments
  ``tw_wire_parse_total{engine=native|python}``, so a build where the
  native loader failed to load is visible on /metrics, never silent;
- ``TraceSink.write_lines`` is byte-identical to the equivalent
  ``write_line`` sequence (the batched emitter's storage contract);
- kill/resume byte identity holds with the batched emitter: a drain
  (checkpoint) mid-stream followed by a resume emits the same bytes as
  the uninterrupted run.

Corpus: the handcrafted fix=2 hotel traces shared with test_serve.py
(fully deterministic; the randomized trials use seeded ``random``).
"""

import json
import random
import threading
import urllib.error
import urllib.request
from types import SimpleNamespace

import pytest

import jax

from traceweaver_tpu.serve import ServeConfig, TenantService

jax.config.update("jax_platforms", "cpu")

pytestmark = pytest.mark.wire


# ---------------------------------------------------------------------------
# corpus (the test_serve.py hotel skeleton: frontend -> search -> geo)
# ---------------------------------------------------------------------------

def hotel_trace(i, prefix, base_us=1_000_000.0, spacing_us=10_000.0):
    T = base_us + i * spacing_us
    slow = (i % 6) == 5
    s1_dur = 5000.0 if slow else 600.0
    c1_dur = s1_dur + 500.0
    tid = f"{prefix}{i:03d}"

    def span(sid, start, dur, op, refs, pid, kind):
        return dict(traceID=tid, spanID=sid, startTime=start, duration=dur,
                    operationName=op,
                    references=[{"traceID": tid, "spanID": r} for r in refs],
                    processID=pid,
                    tags=[{"key": "span.kind", "value": kind}])

    spans = [
        span("root", T, c1_dur + 400.0, "HTTP GET /hotels", [], "p1",
             "server"),
        span("c1", T + 200, c1_dur, "call-search", ["root"], "p1", "client"),
        span("s1", T + 300, s1_dur, "search", ["c1"], "p2", "server"),
        span("c2", T + 400, 300.0, "call-geo", ["s1"], "p2", "client"),
        span("s2", T + 450, 200.0, "geo", ["c2"], "p3", "server"),
    ]
    return dict(traceID=tid, spans=spans,
                processes=dict(p1={"serviceName": "frontend"},
                               p2={"serviceName": "search"},
                               p3={"serviceName": "geo"}))


def hotel_payload(n_traces=24, prefix="t", base_us=1_000_000.0):
    return {"data": [hotel_trace(i, prefix, base_us)
                     for i in range(n_traces)]}


def _cfg(**kw):
    base = dict(fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
                verbose=False, pump_windows=10**9)
    base.update(kw)
    return ServeConfig(**base)


def _http(method, url, payload=None, timeout=120):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ---------------------------------------------------------------------------
# the default POST path is columnar — the object parser never fires
# ---------------------------------------------------------------------------

def test_default_post_is_columnar_object_parser_never_fires(
        tmp_path, monkeypatch):
    from traceweaver_tpu.serve import make_server
    import traceweaver_tpu.serve.tenancy as tenancy

    calls = []

    def spy(*a, **k):
        calls.append(a)
        raise AssertionError("object parser fired on the default wire path")

    monkeypatch.setattr(tenancy, "parse_trace_payload", spy)
    service = TenantService(_cfg(state_dir=str(tmp_path / "wp")))
    server = make_server(service, port=0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        code, out = _http("POST", base + "/api/v1/tenants/acme/spans",
                          hotel_payload(12))
        assert code == 200 and out["ingested_traces"] == 12, out
        assert out["ingested_spans"] == 60
        code, out = _http("POST", base + "/api/v1/flush")
        assert code == 200 and out["solved_windows"] == 1, out
    finally:
        server.shutdown()
        server.server_close()
    assert not calls
    ten = service.tenants["acme"]
    assert ten.counters.get("wire_columnar_posts") == 1
    assert not ten.counters.get("wire_object_posts")
    st = ten.stats()
    assert st["parse_s"] > 0.0
    assert st["stitch_s"] > 0.0 and st["emit_s"] > 0.0
    service.drain()


# ---------------------------------------------------------------------------
# TW_WIRE_COLUMNAR=0 parity: identical emitted bytes either way
# ---------------------------------------------------------------------------

def _emit_bytes(tmp_path, name, raw_payload):
    svc = TenantService(_cfg(state_dir=str(tmp_path / name)))
    summary = svc.ingest("t0", raw_payload)
    svc.flush()
    svc.drain()
    with open(tmp_path / name / "t0" / "traces.jsonl", "rb") as f:
        return f.read(), summary


def test_knob_off_emits_identical_bytes(tmp_path, monkeypatch):
    raw = json.dumps(hotel_payload(24)).encode()
    monkeypatch.setenv("TW_WIRE_COLUMNAR", "1")
    on_bytes, on_sum = _emit_bytes(tmp_path, "on", raw)
    monkeypatch.setenv("TW_WIRE_COLUMNAR", "0")
    off_bytes, off_sum = _emit_bytes(tmp_path, "off", raw)
    assert on_bytes and on_bytes == off_bytes
    assert on_sum == off_sum


# ---------------------------------------------------------------------------
# randomized front-end parity: native / pure-Python wire vs the object
# parser — accepted spans, counters, and exceptions must all agree
# ---------------------------------------------------------------------------

def _rand_payload(rng):
    data = []
    for t in range(rng.randint(0, 4)):
        tid = f"T{t}"
        spans, sids = [], []
        for i in range(rng.randint(0, 6)):
            sid = (f"s{i}" if rng.random() > 0.1 or not sids
                   else rng.choice(sids))  # duplicate sids sometimes
            sids.append(sid)
            rec = {
                "traceID": tid if rng.random() > 0.05 else f"X{t}",
                "spanID": sid,
                "startTime": rng.choice(
                    [1000 + i, float(1000 + i), str(1000 + i), 1000.5]),
                "duration": rng.choice([50, 50.0, "50"]),
                "operationName": rng.choice(
                    ["opA", "HTTP GET /hotels", "init-span"]),
                "processID": rng.choice(["p1", "p2", None]),
                "references": [],
                "tags": [{"key": "span.kind",
                          "value": rng.choice(["server", "client"])}],
            }
            if rec["processID"] is None:
                del rec["processID"]
            if i > 0 and rng.random() > 0.3:
                rec["references"] = [
                    {"traceID": tid, "spanID": rng.choice(sids[:-1] or [sid])}]
            if rng.random() < 0.05:
                del rec["startTime"]  # malformed span
            if rng.random() < 0.03:
                rec["requestType"] = "rt-op"
            spans.append(rec)
        entry = {"traceID": tid, "spans": spans,
                 "processes": {"p1": {"serviceName": "svcA"},
                               "p2": {"serviceName": "svcB"}}}
        if rng.random() < 0.05:
            del entry["spans"]  # malformed trace
        data.append(entry)
    return {"data": data}


def _canon_spans(spans):
    def num(v):
        try:
            return repr(float(v))
        except (TypeError, ValueError):
            return repr(v)
    return tuple(sorted(
        (s.sid, s.trace_id, num(s.start_mus), num(s.duration_mus),
         repr(s.op_name), repr(s.references), repr(s.process_id),
         repr(s.span_kind)) for s in spans.values()))


def _canon(entries, wire):
    out = []
    for e in entries:
        if e is None:
            out.append(None)
            continue
        tid, spans, procs = e.materialize() if wire else e
        out.append((tid, _canon_spans(spans),
                    tuple(sorted((str(k), repr(v))
                                 for k, v in (procs or {}).items()))))
    return out


def test_wire_frontend_parity_randomized(monkeypatch):
    from traceweaver_tpu.ingest import wire as wire_mod
    from traceweaver_tpu.ingest.jaeger import parse_trace_payload

    rng = random.Random(20180)
    ineligible = 0
    for trial in range(120):
        fix = rng.choice([2, 3, 4, 6])
        payload = _rand_payload(rng)
        raw = json.dumps(payload).encode()
        o_cnt = {}
        try:
            o_res = _canon(parse_trace_payload(
                json.loads(raw), fix, {}, {}, strict=False,
                counters=o_cnt), wire=False)
            o_exc = None
        except Exception as e:  # noqa: BLE001 — parity on the message
            o_res, o_exc = None, f"{type(e).__name__}: {e}"
        for disable in ("0", "1"):
            monkeypatch.setenv("TW_DISABLE_NATIVE", disable)
            w_cnt = {}
            try:
                entries = wire_mod.parse_payload_wire(
                    raw, fix, {}, strict=False, counters=w_cnt)
                if entries is None:
                    ineligible += 1
                    continue
                w_res, w_exc = _canon(entries, wire=True), None
            except Exception as e:  # noqa: BLE001
                w_res, w_exc = None, f"{type(e).__name__}: {e}"
            tag = f"trial {trial} fix={fix} native={disable == '0'}"
            assert o_exc == w_exc, f"{tag}: {o_exc!r} vs {w_exc!r}"
            assert o_cnt == w_cnt, f"{tag}: counters {o_cnt} vs {w_cnt}"
            assert o_res == w_res, f"{tag}: accepted spans diverge"
    assert ineligible == 0  # non-strict, fix in FIX_ROOT_OPS: all eligible


# ---------------------------------------------------------------------------
# malformed dead-letter accounting survives the columnar path
# ---------------------------------------------------------------------------

def test_malformed_deadletter_counters_pinned_on_columnar(monkeypatch):
    from traceweaver_tpu.ingest.jaeger import MalformedSpan

    payload = hotel_payload(n_traces=4, prefix="m")
    payload["data"][0]["spans"][1] = {"spanID": "broken"}  # no ids/times
    raw = json.dumps(payload).encode()

    monkeypatch.setenv("TW_WIRE_COLUMNAR", "1")
    svc = TenantService(_cfg())
    out = svc.ingest("m", raw)
    assert out["malformed_spans"] == 1
    assert out["ingested_traces"] == 4  # the trace survives minus the span
    assert svc.tenants["m"].counters.get("wire_columnar_posts") == 1

    monkeypatch.setenv("TW_WIRE_COLUMNAR", "0")
    ref = TenantService(_cfg())
    assert ref.ingest("m", raw) == out

    # strict mode is wire-ineligible by design: the object parser owns
    # the raise, and the columnar knob must not change the exception
    monkeypatch.setenv("TW_WIRE_COLUMNAR", "1")
    strict = TenantService(_cfg(strict=True))
    with pytest.raises(MalformedSpan):
        strict.ingest("m", raw)
    assert strict.tenants["m"].counters.get("wire_columnar_posts") is None


def test_invalid_json_post_is_malformed_not_500(tmp_path):
    from traceweaver_tpu.serve import make_server

    service = TenantService(_cfg())
    server = make_server(service, port=0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        req = urllib.request.Request(
            base + "/api/v1/tenants/j/spans", data=b"{not json",
            method="POST", headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400
    finally:
        server.shutdown()
        server.server_close()


# ---------------------------------------------------------------------------
# stitch property test: array BFS == object DFS on randomized DAGs
# ---------------------------------------------------------------------------

def _rand_stitch_case(rng):
    from traceweaver_tpu.spans import NA, SKIP, Span

    n = rng.randint(1, 28)
    services = ["A", "B", "C", None]
    spans = {}
    for i in range(n):
        tid = f"T{rng.randint(0, 3)}"
        kind = rng.choice(["server", "client"])
        s = Span.fast(tid, f"s{i}", float(i), 1.0, "op", [],
                      f"p{rng.randint(0, 2)}", kind)
        spans[s.GetId()] = s
    ids = list(spans)
    phantoms = [(f"T{rng.randint(0, 3)}", f"ghost{k}") for k in range(4)]
    for s in spans.values():
        for _ in range(rng.randint(0, 3)):
            s.children_spans.append(rng.choice(ids + phantoms))
    svc_of = {sid: rng.choice(services) for sid in ids}
    assignments = {}
    for svc in ("A", "B", "C"):
        eps = {}
        for ep in range(rng.randint(0, 3)):
            amap = {}
            for sid in rng.sample(ids, rng.randint(0, len(ids))):
                amap[sid] = rng.choice(
                    [rng.choice(ids), rng.choice(phantoms), NA, SKIP,
                     "not-a-tuple"])
            eps[f"ep{ep}"] = amap
        if eps:
            assignments[svc] = eps
    servers = [s for s in spans.values() if s.span_kind == "server"]
    roots = rng.sample(servers, min(len(servers), rng.randint(0, 5)))
    live = SimpleNamespace(
        all_spans=spans,
        service_of=lambda span: svc_of.get(span.GetId()))
    return SimpleNamespace(live=live, _stitch_roots=lambda buf: roots), \
        assignments


def test_stitch_arrays_equals_object_dfs_on_random_dags():
    from traceweaver_tpu.stream.service import StreamingReconstructor

    rng = random.Random(777)
    for trial in range(120):
        stub, assignments = _rand_stitch_case(rng)
        obj = StreamingReconstructor._stitch_objects(stub, None, assignments)
        arr = StreamingReconstructor._stitch_arrays(stub, None, assignments)
        assert obj == arr, f"trial {trial}: stitch paths diverge"


# ---------------------------------------------------------------------------
# native-loads-or-fallback: the parse engine is counted, never silent
# ---------------------------------------------------------------------------

def test_wire_parse_engine_counted_and_on_metrics(monkeypatch):
    from traceweaver_tpu.ingest import wire as wire_mod
    from traceweaver_tpu.native import get_lib
    from traceweaver_tpu.obs.exposition import render_metrics
    from traceweaver_tpu.obs.registry import get_registry

    raw = json.dumps(hotel_payload(2)).encode()

    def engine_counts():
        snap = get_registry().snapshot()
        return {eng: snap.get('tw_wire_parse_total{engine="%s"}' % eng, 0.0)
                for eng in ("native", "python")}

    monkeypatch.delenv("TW_DISABLE_NATIVE", raising=False)
    before = engine_counts()
    assert wire_mod.parse_payload_wire(raw, 2, {}, strict=False,
                                       counters={}) is not None
    after = engine_counts()
    expected = "native" if get_lib() is not None else "python"
    assert after[expected] == before[expected] + 1.0
    other = "python" if expected == "native" else "native"
    assert after[other] == before[other]

    # forcing the native loader off must fall back — and be counted
    monkeypatch.setenv("TW_DISABLE_NATIVE", "1")
    assert wire_mod.parse_payload_wire(raw, 2, {}, strict=False,
                                       counters={}) is not None
    assert engine_counts()["python"] == after["python"] + 1.0

    text = render_metrics()
    assert 'tw_wire_parse_total{engine="python"}' in text


# ---------------------------------------------------------------------------
# batched emission: storage layer and resume contract
# ---------------------------------------------------------------------------

def test_tracesink_write_lines_matches_sequential(tmp_path):
    from traceweaver_tpu.stream.service import TraceSink

    lines = ['{"a": %d}' % i for i in range(7)] + ["", "trailing"]
    seq = TraceSink(str(tmp_path / "seq.jsonl"))
    for line in lines:
        seq.write_line(line)
    bat = TraceSink(str(tmp_path / "bat.jsonl"))
    bat.write_lines(lines)
    bat.write_lines([])  # no-op, no bytes, no offset move
    assert seq.offset == bat.offset
    with open(seq.path, "rb") as f:
        seq_bytes = f.read()
    with open(bat.path, "rb") as f:
        bat_bytes = f.read()
    assert seq_bytes == bat_bytes
    assert seq_bytes.endswith(b"trailing\n")


def test_kill_resume_byte_identity_with_batched_emitter(tmp_path):
    pay_a = hotel_payload(12, prefix="a")
    pay_b = hotel_payload(12, prefix="b", base_us=70_000_000.0)

    # uninterrupted reference run
    ref = TenantService(_cfg(state_dir=str(tmp_path / "ref")))
    ref.ingest("t0", pay_a)
    ref.ingest("t0", pay_b)
    ref.flush()
    ref.drain()
    with open(tmp_path / "ref" / "t0" / "traces.jsonl", "rb") as f:
        want = f.read()
    assert want

    # killed mid-stream (graceful drain = checkpoint), then resumed
    svc = TenantService(_cfg(state_dir=str(tmp_path / "kr")))
    svc.ingest("t0", pay_a)
    svc.drain()  # checkpoint with the first window still open
    svc2 = TenantService.resume(_cfg(state_dir=str(tmp_path / "kr")))
    assert "t0" in svc2.tenants
    svc2.ingest("t0", pay_b)
    svc2.flush()
    svc2.drain()
    with open(tmp_path / "kr" / "t0" / "traces.jsonl", "rb") as f:
        got = f.read()
    assert got == want
