"""Drift→adapt online-adaptation tests (tier-1, CPU — ISSUE 12).

Contracts covered (docs/ROBUSTNESS.md "The adaptation ladder"):

- controller ladder walk: excursion → refit scheduled (once — cooldown
  hysteresis) → probation → recovery, or probation expiry → wide-prior
  fallback → cooldown-spaced retry / restore; every actuation lands in
  the metrics registry AND the TW_EVENTS sink (no silent transitions);
- ``TW_ADAPT=0`` (default) is fully inert: no controller on the stream
  service, summaries say so, and nothing actuates;
- the out-of-band refit executes against retained window material,
  installs fresh carried statistics, and is at-most-once per schedule
  within a process;
- the chaos-adapt recovery story end to end on the bench corpus: the
  injected latency swap degrades the control replay permanently, the
  adapted replay recovers to within 1 point of its pre-shift accuracy,
  and the drift gauge re-arms;
- checkpoint round-trip of drift-watcher + controller state UNDER THE
  FAULT INJECTOR: kill mid-probation at ``TW_FAULTS=checkpoint:0.2``,
  resume, no duplicate refit, no lost fallback;
- SLO-breach telemetry: one counted + evented excursion when the
  seal→emit p99 crosses the budget.
"""

import json
import os

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")

from traceweaver_tpu.adapt import AdaptationController, adapt_enabled
from traceweaver_tpu.obs import events as obs_events

pytestmark = pytest.mark.adapt


def _ctrl(**kw):
    base = dict(psi_threshold=0.25, low_rate=0.5, probation=2,
                cooldown_s=1000.0)
    base.update(kw)
    return AdaptationController(**base)


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# knobs + inertness
# ---------------------------------------------------------------------------

def test_adapt_knobs_registered_and_default_off():
    from traceweaver_tpu.runtime import knobs

    for name in ("TW_ADAPT", "TW_ADAPT_COOLDOWN_S", "TW_ADAPT_PROBATION",
                 "TW_ADAPT_LOW_RATE"):
        assert name in knobs.REGISTRY
    assert knobs.get_bool("TW_ADAPT") is False
    assert adapt_enabled() is False


def test_stream_service_inert_without_tw_adapt(monkeypatch):
    monkeypatch.delenv("TW_ADAPT", raising=False)
    from traceweaver_tpu.stream.service import (
        StreamConfig,
        StreamingReconstructor,
    )

    svc = StreamingReconstructor(None, StreamConfig(verbose=False))
    assert svc.adapt is None
    assert svc.maybe_adapt() == 0
    assert svc._summary(final=False)["adapt"] == dict(enabled=False)


# ---------------------------------------------------------------------------
# controller ladder (unit, injected clock)
# ---------------------------------------------------------------------------

def test_ladder_refit_probation_recovery_and_cooldown():
    clock = _Clock()
    c = _ctrl(clock=clock)
    # excursion by PSI schedules a refit, once
    assert c.observe("k", psi=0.6, low_rate=0.0) == "refit_pending"
    assert c.observe("k", psi=0.6, low_rate=0.0) == "refit_pending"
    assert c.pending_refits() == ["k"]
    assert c.begin_refit("k") and not c.begin_refit("k")  # at-most-once
    c.refit_done("k", ok=True)
    # still in excursion through probation window 1 of 2
    assert c.observe("k", psi=0.6) == "probation"
    # recovery inside probation re-arms with a cooldown
    assert c.observe("k", psi=0.05) == "healthy"
    assert c.recoveries == 1 and c.refits_done == 1
    # a fresh excursion inside the cooldown is held (hysteresis)
    assert c.observe("k", psi=0.9) == "healthy"
    assert c.pending_refits() == []
    # ... and fires again once the cooldown elapses
    clock.t += 2000.0
    assert c.observe("k", psi=0.9) == "refit_pending"


def test_ladder_probation_expiry_falls_back_and_restores():
    clock = _Clock()
    c = _ctrl(clock=clock)
    c.observe("k", psi=0.6)
    c.begin_refit("k")
    c.refit_done("k", ok=True)
    assert not c.fallback_active("k")
    # excursion persists through the whole probation window: fallback
    assert c.observe("k", low_rate=0.9) == "probation"
    assert c.observe("k", low_rate=0.9) == "fallback"
    assert c.fallback_active("k") and c.fallbacks == 1
    # wide-prior override while fallen back; reversible on recovery
    assert c.warm_dists("k", {"edge": 1}) == {}
    assert c.observe("k", psi=0.05, low_rate=0.0) == "healthy"
    assert not c.fallback_active("k") and c.restores == 1
    assert c.warm_dists("k", {"edge": 1}) == {"edge": 1}


def test_fallback_retry_is_cooldown_spaced_and_sticky():
    clock = _Clock()
    c = _ctrl(clock=clock, cooldown_s=100.0)
    c.observe("k", psi=0.6)
    c.begin_refit("k")
    c.refit_done("k", ok=False)   # refit died: straight to fallback
    assert c.fallback_active("k") and c.refits_failed == 1
    # still in excursion before the retry period: no new refit
    assert c.observe("k", psi=0.6) == "fallback"
    assert c.pending_refits() == []
    clock.t += 101.0
    assert c.observe("k", psi=0.6) == "refit_pending"
    # wide priors STAY in force through the retry refit
    assert c.fallback_active("k")
    assert c.warm_dists("k", {"edge": 1}) == {}
    c.begin_refit("k")
    c.refit_done("k", ok=True)    # landing lifts the fallback
    assert not c.fallback_active("k")


def test_every_actuation_is_evented_and_counted(tmp_path):
    from traceweaver_tpu.obs.registry import get_registry

    log = obs_events.EventLog(str(tmp_path / "events.jsonl"))
    prev = obs_events.install(log)
    try:
        c = _ctrl(probation=1)
        c.observe("svcA", psi=0.9)
        c.begin_refit("svcA")
        c.refit_done("svcA", ok=True)
        c.observe("svcA", low_rate=1.0)     # probation expiry → fallback
        c.observe("svcA", psi=0.0, low_rate=0.0)  # restore
    finally:
        obs_events.install(prev)
    recs = [json.loads(line)
            for line in open(log.path) if line.strip()]
    adapt_events = [r["event"] for r in recs if r["kind"] == "adapt"]
    assert adapt_events == ["refit", "refit_done", "fallback", "restore"]
    assert all(r["key"] == "svcA" for r in recs if r["kind"] == "adapt")
    # the metrics registry saw the same actuations, labelled per rung
    snap = get_registry().snapshot()
    series = [k for k in snap
              if k.startswith("tw_adapt_actions_total{")
              and 'service="svcA"' in k]
    assert series
    for rung in ("refit", "refit_done", "fallback", "restore"):
        assert any('rung="%s"' % rung in k for k in series), (rung, series)


def test_controller_state_roundtrip_restamps_clocks():
    clock = _Clock()
    c = _ctrl(clock=clock, cooldown_s=50.0)
    c.observe("a", psi=0.9)              # refit_pending
    c.begin_refit("a")                   # refitting: saves as pending
    c.observe("b", psi=0.9)
    c.begin_refit("b")
    c.refit_done("b", ok=True)           # probation
    c.observe("f", psi=0.9)
    c.begin_refit("f")
    c.refit_done("f", ok=False)          # fallback, retry in 50 s
    clock.t += 20.0
    clock2 = _Clock()
    c2 = AdaptationController.from_state(c.state(), clock=clock2)
    rungs = c2.summary()["rungs"]
    assert rungs == {"a": "refit_pending", "b": "probation",
                     "f": "fallback"}
    assert c2.fallback_active("f") and not c2.fallback_active("b")
    # remaining retry duration survived the re-stamp: 30 s left
    assert c2.observe("f", psi=0.9) == "fallback"
    clock2.t += 31.0
    assert c2.observe("f", psi=0.9) == "refit_pending"
    assert c2.summary()["generations"] == {"b": 1}


# ---------------------------------------------------------------------------
# stream integration: the chaos-adapt recovery story
# ---------------------------------------------------------------------------

def _run_leg(monkeypatch, n_bursts=44):
    import bench

    monkeypatch.setenv("TW_CONF_DRIFT_WINDOW", "64")
    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    monkeypatch.setenv("TW_BACKEND", "cpu")
    return bench.run_adapt_leg(n_bursts)


def test_chaos_adapt_recovery_story(monkeypatch):
    """The acceptance pin (small corpus; the artifact runs N=60): the
    PSI alert fires, a refit lands, the adapted tail returns to within
    1 pt of the pre-shift ledger, the gauge re-arms — and the control
    replay of the IDENTICAL corpus stays degraded, so the controller
    (not noise) recovered it."""
    report = _run_leg(monkeypatch, n_bursts=60)
    assert report["adapt_drift_alerts"] >= 1
    assert report["adapt_refits"] >= 1
    assert report["adapt_refits_control"] == 0
    assert report["adapt_recovered_within_1pt"], report
    assert report["adapt_control_stays_degraded"], report
    assert report["adapt_gauge_rearmed"], report


def test_refit_installs_fresh_statistics_and_is_out_of_band(monkeypatch):
    """Unit form of the refit rung: schedule a refit on a healthy
    stream via a forced excursion and assert the executor re-fits the
    retained window (carried statistics replaced, evented) without a
    pump in sight — and that an already-begun refit cannot run twice."""
    monkeypatch.setenv("TW_ADAPT", "1")
    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    import bench
    from traceweaver_tpu.stream.service import (
        StreamConfig,
        StreamingReconstructor,
    )
    from traceweaver_tpu.stream.sources import IterableSource

    events, _ = bench._adapt_burst_events(8, shift_at=99)
    cfg = StreamConfig(window_us=1e6, overlap_us=0.0, ooo_bound_us=1e3,
                       checkpoint_every=10_000, verbose=False)
    svc = StreamingReconstructor(IterableSource(events), cfg)
    svc.run()
    assert svc.adapt is not None
    assert "frontend" in svc.adapt_material
    before = svc.carried.get("frontend")
    assert before is not None
    svc.adapt.observe("frontend", psi=9.9, low_rate=1.0)
    assert svc.maybe_adapt() == 1
    assert svc.stats.get("adapt_refits") == 1
    after = svc.carried.get("frontend")
    assert after is not None and after is not before
    assert svc.adapt.summary()["rungs"]["frontend"] == "probation"
    # the schedule was consumed: nothing pending, nothing re-runs
    assert svc.maybe_adapt() == 0


# ---------------------------------------------------------------------------
# checkpoint round-trip under the fault injector
# ---------------------------------------------------------------------------

def test_kill_mid_probation_resume_no_duplicate_refit_no_lost_fallback(
        monkeypatch, tmp_path):
    """The ISSUE's checkpoint contract: kill mid-probation under
    ``TW_FAULTS=checkpoint:0.2`` (some checkpoint writes fail, counted,
    last good generation survives), resume, and assert the resumed
    controller (a) does NOT re-run the completed refit and (b) still
    holds an active fallback taken before the kill."""
    monkeypatch.setenv("TW_ADAPT", "1")
    monkeypatch.setenv("TW_RETRY_BACKOFF_S", "0")
    import bench
    from traceweaver_tpu.runtime import faults
    from traceweaver_tpu.stream.service import (
        StreamConfig,
        StreamingReconstructor,
    )
    from traceweaver_tpu.stream.sources import IterableSource

    ckpt = str(tmp_path / "ckpt.pkl")
    events, _ = bench._adapt_burst_events(8, shift_at=99)
    cfg = StreamConfig(window_us=1e6, overlap_us=0.0, ooo_bound_us=1e3,
                       checkpoint_path=ckpt, checkpoint_every=10_000,
                       verbose=False)
    svc = StreamingReconstructor(IterableSource(events), cfg)
    svc.run()
    # walk svcA (= frontend) to MID-PROBATION and a second key into
    # FALLBACK, then checkpoint under injected checkpoint faults
    svc.adapt.observe("frontend", psi=9.9)
    assert svc.maybe_adapt() == 1                       # refit lands
    assert svc.adapt.summary()["rungs"]["frontend"] == "probation"
    svc.adapt.observe("ghost", psi=9.9)
    svc.adapt.begin_refit("ghost")
    svc.adapt.refit_done("ghost", ok=False)             # fallback
    refits_before = svc.adapt.refits_done
    monkeypatch.setenv("TW_FAULTS", "checkpoint:0.2")
    monkeypatch.setenv("TW_FAULTS_SEED", "3")
    faults.reset()
    try:
        for _ in range(6):   # p=0.2: failures counted, a write lands
            svc._checkpoint()
        assert os.path.exists(ckpt)
    finally:
        # KILL under faults; the restarted process has a fresh env
        monkeypatch.delenv("TW_FAULTS", raising=False)
        faults.reset()
    resumed = StreamingReconstructor.resume(ckpt, IterableSource(events))
    rungs = resumed.adapt.summary()["rungs"]
    assert rungs["frontend"] == "probation"     # refit NOT re-pending
    assert rungs["ghost"] == "fallback"         # fallback NOT lost
    assert resumed.adapt.fallback_active("ghost")
    assert resumed.adapt.warm_dists("ghost", {"e": 1}) == {}
    assert resumed.adapt.refits_done == refits_before
    # no duplicate refit: nothing pending, the executor is a no-op
    assert resumed.adapt.pending_refits() == []
    assert resumed.maybe_adapt() == 0
    # the drift watcher rode the same checkpoint
    assert resumed.drift is not None
    assert resumed.drift.state()["ref"].keys() \
        == svc.drift.state()["ref"].keys()


# ---------------------------------------------------------------------------
# SLO-breach telemetry
# ---------------------------------------------------------------------------

def test_slo_breach_counted_and_evented_once_per_excursion(
        monkeypatch, tmp_path):
    import bench
    from traceweaver_tpu.stream.service import (
        StreamConfig,
        StreamingReconstructor,
    )
    from traceweaver_tpu.stream.sources import IterableSource

    log = obs_events.EventLog(str(tmp_path / "events.jsonl"))
    prev = obs_events.install(log)
    try:
        events, _ = bench._adapt_burst_events(6, shift_at=99)
        # an SLO budget no real solve can meet: every window breaches,
        # but the excursion is armed ONCE until the p99 recovers
        cfg = StreamConfig(window_us=1e6, overlap_us=0.0,
                           ooo_bound_us=1e3, checkpoint_every=10_000,
                           verbose=False, slo_p99_ms=1e-3)
        svc = StreamingReconstructor(IterableSource(events), cfg)
        summary = svc.run()
    finally:
        obs_events.install(prev)
    assert summary["slo_breaches"] == 1
    recs = [json.loads(line) for line in open(log.path) if line.strip()]
    breaches = [r for r in recs if r["kind"] == "slo_breach"]
    assert len(breaches) == 1
    assert breaches[0]["event"] == "excursion"
    assert breaches[0]["p99_ms"] > breaches[0]["slo_ms"]
    # the per-tenant counter landed in the registry
    from traceweaver_tpu.obs.registry import get_registry

    snap = get_registry().snapshot()
    assert any(k.startswith("tw_slo_breach_total") for k in snap)


def test_adapt_fields_ledger():
    """adapt_fields verdicts, unit-tested like chaos_fields."""
    import bench

    ctrl = dict(pre=1.0, tail=0.0, drift_alerts=2, refits=0, fallbacks=0)
    adapted = dict(windows=60, pre=1.0, tail=0.995, drift_alerts=2,
                   refits=1, fallbacks=0, final_psi=0.13,
                   steady_compiles=0, actions={"refits_done": 1})
    f = bench.adapt_fields(30, dict(psi_threshold=0.25), ctrl, adapted)
    assert f["adapt_recovery_gap_pts"] == 0.5
    assert f["adapt_recovered_within_1pt"] is True
    assert f["adapt_control_degradation_pts"] == 100.0
    assert f["adapt_control_stays_degraded"] is True
    assert f["adapt_gauge_rearmed"] is True
    # a failed recovery reads as failed
    bad = dict(adapted, tail=0.5, final_psi=0.9)
    f2 = bench.adapt_fields(30, dict(psi_threshold=0.25), ctrl, bad)
    assert f2["adapt_recovered_within_1pt"] is False
    assert f2["adapt_gauge_rearmed"] is False
