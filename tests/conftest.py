"""Test configuration.

Tests run JAX on a virtual 8-device CPU mesh standing in for a TPU slice
(the driver separately dry-runs the multi-chip path via __graft_entry__).
The sandbox's sitecustomize pins JAX_PLATFORMS=axon (the real chip), so we
must override both the env var and the jax config before anything imports
jax.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import pytest

REFERENCE_DATA = "/root/reference/data"


def ref_data(relpath: str) -> str:
    path = os.path.join(REFERENCE_DATA, relpath)
    if not os.path.isdir(path):
        pytest.skip(f"reference dataset not available: {path}")
    return path


@pytest.fixture(scope="session")
def hotel_store():
    from traceweaver_tpu.ingest import load_corpus

    return load_corpus(ref_data("hotel_reservation/hotel_load25"),
                       fix=2, max_traces=100, cache=False)


@pytest.fixture(scope="session")
def media_store():
    from traceweaver_tpu.ingest import load_corpus

    return load_corpus(ref_data("media_microservices/media_load25"),
                       fix=1, max_traces=50, cache=False)


@pytest.fixture(scope="session")
def nodejs_store():
    from traceweaver_tpu.ingest import load_corpus

    return load_corpus(ref_data("nodejs_microservices/node_load25"),
                       fix=0, max_traces=50, cache=False)
