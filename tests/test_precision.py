"""Mixed-precision score path (TW_PRECISION) property tests.

The contract (ops/precision.py): the [N, M] score BLOCKS — the arrays
the Sinkhorn sweep streams twice per iteration, the solve's dominant
HBM traffic — may be stored bfloat16, while everything that accumulates
or compares stays f32 (potentials, marginals, convergence test, the
transport plan, rounding's tie-break margins, the GMM EM fit). Two
properties are pinned here:

1. the default ``f32`` path is BIT-identical to the pre-PR program —
   no cast is inserted anywhere (checked against an inline verbatim
   copy of the pre-PR Sinkhorn, and default-vs-explicit equality of the
   packed solver output);
2. the ``bf16`` path agrees with f32 within tolerance across randomized
   geometries, padded/all-masked endpoints, vmap, the fused Pallas
   kernel in interpret mode, and end-to-end fleet accuracy — with the
   integer outputs of masked/degenerate rows agreeing EXACTLY (masking
   is not subject to rounding).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from traceweaver_tpu.ops.precision import (
    precision_from_env,
    score_dtype,
    score_itemsize,
    validate_precision,
)
from traceweaver_tpu.ops.sinkhorn import NEG, sinkhorn_log

jax.config.update("jax_platforms", "cpu")


# ---------------------------------------------------------------------------
# precision spec plumbing
# ---------------------------------------------------------------------------

def test_precision_spec_normalization_and_errors():
    assert validate_precision("f32") == "f32"
    assert validate_precision("FP32") == "f32"
    assert validate_precision(" float32 ") == "f32"
    assert validate_precision("bf16") == "bf16"
    assert validate_precision("BFLOAT16") == "bf16"
    # a typo'd knob must fail loudly, never silently run f32
    for bad in ("bf61", "fp16", "f64", "half", "1"):
        with pytest.raises(ValueError):
            validate_precision(bad)


def test_score_dtype_and_itemsize():
    assert score_dtype("f32") == jnp.float32
    assert score_dtype("bf16") == jnp.bfloat16
    assert score_itemsize("f32") == 4
    assert score_itemsize("bf16") == 2


def test_env_precision_routing(monkeypatch):
    from traceweaver_tpu.algorithms.weaver_tpu import WeaverTPU

    monkeypatch.delenv("TW_PRECISION", raising=False)
    assert precision_from_env() == "f32"
    monkeypatch.setenv("TW_PRECISION", "bf16")
    assert precision_from_env() == "bf16"
    assert WeaverTPU([], []).precision == "bf16"
    # explicit argument wins over the env
    assert WeaverTPU([], [], precision="f32").precision == "f32"
    monkeypatch.setenv("TW_PRECISION", "bf61")
    with pytest.raises(ValueError):
        precision_from_env()


# ---------------------------------------------------------------------------
# f32 default: bit-identical to the pre-PR program
# ---------------------------------------------------------------------------

def _sinkhorn_log_pre_pr(scores, row_marginals, col_marginals,
                         epsilon=1.0, n_iters=50, tol=0.0):
    """Verbatim copy of the pre-PR (commit 85174d0) sinkhorn_log body.

    The mixed-precision change must leave the f32 program untouched:
    for f32 scores the new code is op-for-op this function, so the
    jitted outputs must be byte-equal — any drift means a cast or an
    order change leaked into the default path."""
    log_r = jnp.where(row_marginals > 0,
                      jnp.log(jnp.maximum(row_marginals, 1e-30)), NEG)
    log_c = jnp.where(col_marginals > 0,
                      jnp.log(jnp.maximum(col_marginals, 1e-30)), NEG)
    logK = scores / epsilon

    def update(f, g):
        f = epsilon * (log_r - jax.nn.logsumexp(
            logK + g[None, :] / epsilon, axis=1))
        f = jnp.where(row_marginals > 0, f, NEG)
        g = epsilon * (log_c - jax.nn.logsumexp(
            logK + f[:, None] / epsilon, axis=0))
        g = jnp.where(col_marginals > 0, g, NEG)
        return f, g

    f0 = jnp.zeros_like(row_marginals, dtype=scores.dtype)
    g0 = jnp.zeros_like(col_marginals, dtype=scores.dtype)
    if tol == 0.0:
        f, g = jax.lax.fori_loop(
            0, n_iters, lambda _, fg: update(*fg), (f0, g0))
    else:
        def body(state):
            f, g, it, done = state
            f_new, g_new = update(f, g)
            live = row_marginals > 0
            delta = jnp.max(jnp.where(live, jnp.abs(f_new - f), 0.0))
            f = jnp.where(done, f, f_new)
            g = jnp.where(done, g, g_new)
            return f, g, it + 1, done | (delta <= tol)

        def cond(state):
            _, _, it, done = state
            return (it < n_iters) & ~done

        init = (f0, g0, jnp.asarray(0, jnp.int32), jnp.asarray(False))
        f, g, _, _ = jax.lax.while_loop(cond, body, init)

    log_plan = logK + (f[:, None] + g[None, :]) / epsilon
    return jnp.exp(jnp.clip(log_plan, -80.0, 80.0))


def _random_marg_block(rng, n, m):
    S = rng.normal(scale=5.0, size=(n, m)).astype(np.float32)
    in_v = rng.random(n) > 0.2
    if not in_v.any():
        in_v[0] = True
    o_v = rng.random(m) > 0.2
    if not o_v.any():
        o_v[0] = True
    S = np.where(in_v[:, None] & o_v[None, :], S, NEG).astype(np.float32)
    # balanced marginals (surplus absorbed uniformly on the lighter side)
    nr, nc = float(in_v.sum()), float(o_v.sum())
    rm = in_v.astype(np.float32) * (max(nr, nc) / nr)
    cm = o_v.astype(np.float32) * (max(nr, nc) / nc)
    return S, rm, cm


@pytest.mark.parametrize("tol", [0.0, 1e-3])
def test_f32_sinkhorn_bit_identical_to_pre_pr(tol):
    ref = jax.jit(_sinkhorn_log_pre_pr,
                  static_argnames=("epsilon", "n_iters", "tol"))
    rng = np.random.default_rng(0)
    for _ in range(6):
        n, m = int(rng.integers(3, 40)), int(rng.integers(3, 40))
        S, rm, cm = _random_marg_block(rng, n, m)
        a = sinkhorn_log(jnp.asarray(S), jnp.asarray(rm), jnp.asarray(cm),
                         epsilon=1.0, n_iters=30, tol=tol)
        b = ref(jnp.asarray(S), jnp.asarray(rm), jnp.asarray(cm),
                epsilon=1.0, n_iters=30, tol=tol)
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            "f32 Sinkhorn drifted from the pre-PR program")


def test_f32_default_solve_equals_explicit_f32():
    """The packed solver's default precision IS f32 — default and
    explicit produce byte-equal packed outputs."""
    from test_bench_smoke import _tiny_args

    from traceweaver_tpu.algorithms.weaver_tpu import solve_windows_packed

    kw = dict(n_sinkhorn=8, n_sweeps=2, sinkhorn_tol=1e-3)
    default = np.asarray(solve_windows_packed(*_tiny_args(seed=3), **kw))
    explicit = np.asarray(
        solve_windows_packed(*_tiny_args(seed=3), precision="f32", **kw))
    assert np.array_equal(default, explicit)


# ---------------------------------------------------------------------------
# score build: bf16 block emission
# ---------------------------------------------------------------------------

def test_gemm_score_build_bf16_out_dtype():
    """mixture_logpdf_gemm(out_dtype=bf16) emits a bf16 block via the
    bf16-operand / f32-accumulator contraction; values track the f32
    elementwise form to bf16 resolution, and out_dtype=None keeps the
    historical f32 output untouched."""
    from traceweaver_tpu.ops.scores import (
        mixture_logpdf,
        mixture_logpdf_gemm,
        pair_scores,
    )

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(10.0, 20.0, (13, 17)).astype(np.float32))
    w = jnp.asarray([0.5, 0.3, 0.2], jnp.float32)
    mu = jnp.asarray([8.0, 15.0, 30.0], jnp.float32)
    sd = jnp.asarray([2.0, 5.0, 9.0], jnp.float32)

    ref = np.asarray(mixture_logpdf(x, w, mu, sd))
    out_f32 = mixture_logpdf_gemm(x, w, mu, sd)
    assert out_f32.dtype == jnp.float32
    assert np.allclose(np.asarray(out_f32), ref, atol=1e-3)

    out_bf = mixture_logpdf_gemm(x, w, mu, sd, out_dtype=jnp.bfloat16)
    assert out_bf.dtype == jnp.bfloat16
    # bf16 relative resolution ~2^-8; these log-densities are O(10)
    assert np.max(np.abs(np.asarray(out_bf, np.float32) - ref)) < 0.5

    # pair_scores honors out_dtype on the non-GEMM path too
    ps = pair_scores(x[:, 0], x[0, :], w, mu, sd, out_dtype=jnp.bfloat16)
    assert ps.dtype == jnp.bfloat16


# ---------------------------------------------------------------------------
# bf16 scores through the Sinkhorn paths
# ---------------------------------------------------------------------------

def test_bf16_sinkhorn_plan_is_f32_and_close():
    rng = np.random.default_rng(1)
    for tol in (0.0, 1e-3):
        for _ in range(4):
            n, m = int(rng.integers(3, 40)), int(rng.integers(3, 40))
            S, rm, cm = _random_marg_block(rng, n, m)
            p32 = sinkhorn_log(jnp.asarray(S), jnp.asarray(rm),
                               jnp.asarray(cm), epsilon=1.0, n_iters=30,
                               tol=tol)
            pbf = sinkhorn_log(jnp.asarray(S, jnp.bfloat16),
                               jnp.asarray(rm), jnp.asarray(cm),
                               epsilon=1.0, n_iters=30, tol=tol)
            # potentials/plan stay f32 — only the score block is reduced
            assert pbf.dtype == jnp.float32
            assert float(jnp.max(jnp.abs(p32 - pbf))) < 0.05
            # the marginal residual is a property of the iteration/tol
            # budget, not the score precision: bf16 row sums track f32's
            live_rows = rm > 0
            rs32 = np.asarray(jnp.sum(p32, axis=1))[live_rows]
            rsbf = np.asarray(jnp.sum(pbf, axis=1))[live_rows]
            assert np.allclose(rsbf, rs32, atol=0.02)


def test_bf16_fused_kernel_matches_jnp_randomized():
    """The fused Pallas kernel and the jnp reference must agree EXACTLY
    on identical bf16 score blocks (same contract as f32: the kernel is
    plumbing, not an approximation — both paths read the same reduced
    block and compute f32 potentials/plan from it)."""
    from test_fused_kernel import _random_block

    from traceweaver_tpu.ops.pallas_sinkhorn import (
        assign_topk_jnp,
        fused_assign_pallas,
    )

    rng = np.random.default_rng(5)
    for trial in range(8):
        W = int(rng.integers(3, 24))
        M = int(rng.integers(6, 48))
        S, rm, cm, in_v, cv, cap = _random_block(rng, W, M)
        Sb = jnp.asarray(S, jnp.bfloat16)
        kw = dict(epsilon=1.0, n_iters=40, tol=1e-3, topk=5,
                  min_topk_mass=1e-3)
        a_ref, tk_ref = assign_topk_jnp(
            Sb, jnp.asarray(rm), jnp.asarray(cm),
            jnp.asarray(in_v), jnp.asarray(cv), jnp.asarray(cap), W, **kw)
        a_k, tk_k = fused_assign_pallas(
            Sb, jnp.asarray(rm), jnp.asarray(cm),
            jnp.asarray(cap), W, interpret=True, **kw)
        assert np.array_equal(np.asarray(a_ref), np.asarray(a_k)), (
            f"trial {trial} (W={W}, M={M}): bf16 assignments diverge")
        assert np.array_equal(np.asarray(tk_ref), np.asarray(tk_k)), (
            f"trial {trial} (W={W}, M={M}): bf16 top-k diverges")


def test_bf16_fused_kernel_all_masked_endpoint():
    from test_fused_kernel import _random_block

    from traceweaver_tpu.ops.pallas_sinkhorn import (
        assign_topk_jnp,
        fused_assign_pallas,
    )

    rng = np.random.default_rng(9)
    W, M = 9, 12
    S, rm, cm, in_v, cv, cap = _random_block(rng, W, M,
                                             all_masked_cols=True)
    Sb = jnp.asarray(S, jnp.bfloat16)
    kw = dict(epsilon=1.0, n_iters=20, tol=0.0, topk=3, min_topk_mass=1e-3)
    a_ref, tk_ref = assign_topk_jnp(
        Sb, jnp.asarray(rm), jnp.asarray(cm), jnp.asarray(in_v),
        jnp.asarray(cv), jnp.asarray(cap), W, **kw)
    a_k, tk_k = fused_assign_pallas(
        Sb, jnp.asarray(rm), jnp.asarray(cm), jnp.asarray(cap), W,
        interpret=True, **kw)
    assert np.array_equal(np.asarray(a_ref), np.asarray(a_k))
    assert np.array_equal(np.asarray(tk_ref), np.asarray(tk_k))
    # no fabricated columns: every row is skip/none, exactly like f32
    a32, _ = assign_topk_jnp(
        jnp.asarray(S), jnp.asarray(rm), jnp.asarray(cm),
        jnp.asarray(in_v), jnp.asarray(cv), jnp.asarray(cap), W, **kw)
    assert np.array_equal(np.asarray(a_ref), np.asarray(a32))


# ---------------------------------------------------------------------------
# end-to-end: solve_windows / fleet under bf16
# ---------------------------------------------------------------------------

def _consistent_problem(rng, B=2, E=2, W=24, M=24):
    """Windows whose out-span delays are actually DRAWN from the edge
    mixtures the solver scores with (the toy fixtures elsewhere use
    inconsistent mus, which makes the optimum itself scrambled and
    useless for cross-precision comparison). Ground truth is the
    identity matching after the per-endpoint time sort."""
    K = 3
    # guaranteed inter-arrival gap >> delay sd so the per-endpoint sort
    # order equals the arrival order (identity ground truth below)
    in_start = np.cumsum(rng.uniform(50.0, 250.0, (B, W)),
                         axis=1).astype(np.float32)
    out_start = np.zeros((B, E, M), np.float32)
    out_end = np.zeros((B, E, M), np.float32)
    prev_end = in_start.copy()
    for e in range(E):
        start = prev_end + np.maximum(
            rng.normal(10.0, 1.0, (B, W)), 0.5).astype(np.float32)
        out_start[:, e] = start
        out_end[:, e] = start + 5.0
        prev_end = out_end[:, e]
    in_end = (prev_end + np.maximum(
        rng.normal(10.0, 1.0, (B, W)), 0.5)).astype(np.float32)
    # spacing >> sd keeps the per-endpoint sort order = arrival order,
    # so ground truth is the identity and both precisions can hit it
    assert all(np.all(np.diff(out_start[b, e]) > 0)
               for b in range(B) for e in range(E))
    pred = np.zeros((E, E), bool)
    for e in range(1, E):
        pred[e, e - 1] = True
    root = np.zeros(E, bool); root[0] = True
    last = np.zeros(E, bool); last[E - 1] = True
    wt = np.zeros((E, E, K), np.float32); wt[..., 0] = 1
    # edge delay: succ_start - pred_end ~ N(10, 1); root in->out ditto
    mu = np.full((E, E, K), 10.0, np.float32)
    sd = np.full((E, E, K), 1.0, np.float32)
    iwt = np.zeros((E, K), np.float32); iwt[:, 0] = 1
    imu = np.full((E, K), 10.0, np.float32)
    isd = np.full((E, K), 1.0, np.float32)
    return (in_start, in_end, np.ones((B, W), bool),
            out_start, out_end, np.ones((B, E, M), bool),
            np.zeros((B, E), np.float32), np.zeros((B, E, W), bool),
            pred, root, last, wt, mu, sd, iwt, imu, isd,
            iwt.copy(), imu.copy(), isd.copy())


def test_bf16_solver_accuracy_parity_randomized_geometries():
    """On consistent geometry (delays drawn from the scored mixtures),
    bf16 must recover the same matching as f32 to within a small
    disagreement budget, and disagreements must be confined to rows the
    f32 solve itself ranks as near-ties. Covers vmap (B > 1) and
    several random geometries."""
    from traceweaver_tpu.algorithms.weaver_tpu import solve_windows

    rng = np.random.default_rng(2)
    kw = dict(n_sinkhorn=20, n_sweeps=3, sinkhorn_tol=1e-3)
    total = agree = gt32 = gtbf = 0
    for trial in range(3):
        W = int(rng.integers(12, 28))
        args = _consistent_problem(rng, B=2, E=2, W=W, M=W)
        a32 = np.asarray(solve_windows(*args, **kw)[0])
        abf = np.asarray(solve_windows(*args, precision="bf16", **kw)[0])
        ident = np.arange(W)[None, None, :]
        total += a32.size
        agree += int((a32 == abf).sum())
        gt32 += int((a32 == ident).sum())
        gtbf += int((abf == ident).sum())
    assert gt32 / total > 0.9, "f32 baseline failed its own geometry"
    # ground-truth accuracy parity: the acceptance bar is 1 pt on the
    # bench corpora; give the tiny synthetic 2 pts of slack
    assert abs(gt32 - gtbf) / total <= 0.02, (gt32, gtbf, total)
    assert agree / total > 0.95, f"bf16 agreement {agree}/{total}"


def test_bf16_masked_rows_and_forced_skips_match_f32_exactly():
    """Masking is not subject to rounding: invalid rows, forced skips,
    and all-masked endpoints must produce EXACTLY the f32 integer
    outputs under bf16."""
    from traceweaver_tpu.algorithms.weaver_tpu import solve_windows

    rng = np.random.default_rng(4)
    args = list(_consistent_problem(rng, B=2, E=2, W=16, M=16))
    in_valid = args[2].copy()
    in_valid[:, -4:] = False           # padded window rows
    args[2] = in_valid
    out_valid = args[5].copy()
    out_valid[:, 1, :] = False         # endpoint 1: no candidates at all
    args[5] = out_valid
    fskip = args[7].copy()
    fskip[:, 0, :3] = True             # forced skips on endpoint 0
    args[7] = fskip
    kw = dict(n_sinkhorn=20, n_sweeps=3, sinkhorn_tol=1e-3)
    a32, tk32, nb32, _ = solve_windows(*args, **kw)
    abf, tkbf, nbbf, _ = solve_windows(*args, precision="bf16", **kw)
    a32, abf = np.asarray(a32), np.asarray(abf)
    W = 16
    # invalid rows: identical (assign stays at its masked value)
    assert np.array_equal(a32[:, :, -4:], abf[:, :, -4:])
    # all-masked endpoint: every valid row lands on skip/none, same as f32
    assert np.array_equal(a32[:, 1, :], abf[:, 1, :])
    # forced-skip rows: identical
    assert np.array_equal(a32[:, 0, :3], abf[:, 0, :3])


def test_bf16_end_to_end_with_fused_interpret_kernel(monkeypatch):
    """Full bf16 solve with the fused kernel forced (interpret mode)
    must reproduce the bf16 XLA path exactly — the kernel sees the same
    reduced block and must make the same integer decisions."""
    from traceweaver_tpu.algorithms.weaver_tpu import solve_windows

    rng = np.random.default_rng(6)
    args = _consistent_problem(rng, B=1, E=2, W=96, M=96)
    kw = dict(n_sinkhorn=10, n_sweeps=2, sinkhorn_tol=1e-3,
              precision="bf16")

    monkeypatch.delenv("TW_PALLAS", raising=False)
    monkeypatch.delenv("TW_PALLAS_INTERPRET", raising=False)
    base = solve_windows(*args, **kw)

    monkeypatch.setenv("TW_PALLAS", "1")
    monkeypatch.setenv("TW_PALLAS_INTERPRET", "1")
    fused = solve_windows(*args, **kw)

    for name, a, b in zip(("assign", "topk", "not_best", "feas"),
                          base, fused):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name


def test_bf16_fleet_accuracy_parity_and_byte_halved_budget():
    """Whole-fleet integration: the pipelined dispatch under
    precision="bf16" stays within 2 pts of f32 recorded-truth accuracy
    on every service, and the byte-denominated group costs (the
    pipeline depth currency) come out at half the f32 cost for the
    score-block share."""
    from test_pipeline import _mixed_items

    from traceweaver_tpu.algorithms.fleet import solve_fleet
    from traceweaver_tpu.metrics import accuracy_for_service

    items = _mixed_items()
    st32, stbf = {}, {}
    out32 = solve_fleet(items, stats=st32, precision="f32")
    outbf = solve_fleet(_mixed_items(), stats=stbf, precision="bf16")
    for item, o32, obf in zip(items, out32, outbf):
        acc32 = accuracy_for_service(o32[0], item.true_assignments,
                                     item.in_span_partitions)
        accbf = accuracy_for_service(obf[0], item.true_assignments,
                                     item.in_span_partitions)
        assert accbf >= acc32 - 0.02, (
            f"{item.service}: bf16 {accbf:.3f} vs f32 {acc32:.3f}")
    # dtype-aware budget: bf16 group costs halve the score-block share
    # (refit samples stay f32, so the ratio sits in (0.5, 1.0))
    c32 = st32.get("fleet_group_cost_total", 0.0)
    cbf = stbf.get("fleet_group_cost_total", 0.0)
    assert c32 > 0 and cbf > 0
    assert 0.49 * c32 <= cbf <= 0.95 * c32, (c32, cbf)


# ---------------------------------------------------------------------------
# dtype-aware VMEM / budget accounting
# ---------------------------------------------------------------------------

def test_vmem_admission_is_dtype_aware(monkeypatch):
    from traceweaver_tpu.ops import pallas_sinkhorn as ps

    monkeypatch.delenv("TW_PALLAS_VMEM_CAP", raising=False)
    # bf16 halves the padded block bytes (module the sublane repack:
    # 16-row tiles instead of 8)
    assert ps._padded_block_bytes(128, 256, 4) == 128 * 256 * 4
    assert ps._padded_block_bytes(128, 256, 2) == 128 * 256 * 2
    # a block too big for the cap in f32 fits in bf16
    cap = ps._vmem_cap_bytes()
    n = 128
    m_f32_limit = (cap // (6 * n * 4)) // 128 * 128
    big_m = m_f32_limit + 256
    assert not ps.fits_pallas_vmem(n, big_m, 4)
    assert ps.fits_pallas_vmem(n, big_m, 2)
    # the v5e hardware clamp is itemsize-independent and unchanged
    monkeypatch.setenv("TW_PALLAS_VMEM_CAP", str(1 << 40))
    assert ps._vmem_cap_bytes() == ps._VMEM_HW_BYTES_V5E


def test_bf16_sublane_tiling():
    from traceweaver_tpu.ops import pallas_sinkhorn as ps

    assert ps._sublane(4) == 8
    assert ps._sublane(2) == 16
    # padding rounds rows up to the packed-dtype sublane count
    assert ps._padded_block_bytes(9, 100, 2) == 16 * 128 * 2
    assert ps._padded_block_bytes(9, 100, 4) == 16 * 128 * 4
