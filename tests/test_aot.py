"""AOT shape-lattice precompile unit tests (runtime/aot.py, ISSUE 14).

Lattice enumeration semantics (horizon grammar, tier composition, the
dispatch-rule gates: no B=1 warm-sweep variants, refit only under
compaction with B>=2), the miss-ledger hooks' key agreement with the
enumerator, readiness-state transitions, and the persistent-cache
failure hardening + compile-time histogram satellites in
runtime/jax_cache.py. Everything in-memory/tmp-path — the only real
compiles live in tests/test_bench_smoke.py's eager-warmup smoke.
"""

import os

import numpy as np
import pytest

import jax

from traceweaver_tpu.runtime import aot, knobs

jax.config.update("jax_platforms", "cpu")

pytestmark = pytest.mark.aot


@pytest.fixture(autouse=True)
def _clean_aot():
    aot.reset_for_tests()
    yield
    aot.reset_for_tests()


# ---------------------------------------------------------------------------
# horizon / knobs
# ---------------------------------------------------------------------------

def test_parse_horizon_rounds_to_pow2_grid():
    h = aot.parse_horizon("100:3:50:50")
    assert h == {"B": 128, "E": 4, "W": 64, "M": 64, "D": 1}
    # W/M honor the 8-minimum sublane tile; optional D axis
    assert aot.parse_horizon("1:1:1:1:3") == {
        "B": 1, "E": 1, "W": 8, "M": 8, "D": 4}


@pytest.mark.parametrize("bad", ["8:2:8", "a:2:8:16", "0:2:8:16", "1:2"])
def test_parse_horizon_raises_on_malformed_spec(bad):
    with pytest.raises(aot.AotError):
        aot.parse_horizon(bad)


def test_aot_knobs_are_registered_and_validated(monkeypatch):
    assert knobs.REGISTRY["TW_AOT"].choices == ("off", "background", "eager")
    assert knobs.REGISTRY["TW_AOT_TIER"].choices == ("core", "serve", "full")
    monkeypatch.setenv("TW_AOT", "sometimes")
    with pytest.raises(knobs.KnobError):
        knobs.get("TW_AOT")


# ---------------------------------------------------------------------------
# lattice enumeration
# ---------------------------------------------------------------------------

def _entries(keys):
    return {k[1] for k in keys if k[0] == "fleet"} | {
        k[0] for k in keys if k[0] != "fleet"}


def test_lattice_tiers_compose(monkeypatch):
    monkeypatch.setenv("TW_AOT_HORIZON", "2:1:8:8")
    core = aot.plan_lattice(tier="core")
    serve = aot.plan_lattice(tier="serve")
    full = aot.plan_lattice(tier="full")
    assert set(core) < set(serve) < set(full)
    assert _entries(core) == {"solve_windows_fleet", "assemble", "ring",
                              "gmm"}
    assert _entries(serve) == _entries(core) | {
        "solve_em_fleet", "refit_fleet_params"}
    assert _entries(full) == _entries(serve) | {
        "solve_windows_packed", "solve_em_packed"}


def test_lattice_respects_dispatch_rules(monkeypatch):
    monkeypatch.setenv("TW_AOT_HORIZON", "4:2:8:8")
    keys = aot.plan_lattice(tier="serve")
    fleet = [k for k in keys if k[0] == "fleet"]
    warm = knobs.get_int("TW_SWEEP_WARM")
    # no warm-sweep variant at B=1 (compaction needs n_rows > 1) and no
    # B=1 refit (singleton groups refit in-graph); solve_em_fleet only
    # at B=1 under compaction
    for k in fleet:
        entry, B, n_sweeps = k[1], k[2], k[10]
        if entry == "solve_windows_fleet" and n_sweeps == warm:
            assert B >= 2, k
        if entry == "refit_fleet_params":
            assert B >= 2, k
        if entry == "solve_em_fleet":
            assert B == 1, k
    # every geometry axis stays inside the horizon's pow2 grid
    for k in fleet:
        _, _, B, E, W, M = k[:6]
        assert B in (1, 2, 4) and E in (1, 2) and W == 8 and M == 8


def test_lattice_shrinks_without_compaction(monkeypatch):
    monkeypatch.setenv("TW_AOT_HORIZON", "4:1:8:8")
    keys_on = aot.plan_lattice(tier="serve")
    monkeypatch.setenv("TW_COMPACT", "0")
    keys_off = aot.plan_lattice(tier="serve")
    # no compaction: no warm-sweep or standalone-refit variants, but
    # solve_em_fleet now spans the whole B range (uncompacted two-pass
    # groups dispatch it directly)
    assert not any(k[1] == "refit_fleet_params" for k in keys_off
                   if k[0] == "fleet")
    em_bs = {k[2] for k in keys_off if k[0] == "fleet"
             and k[1] == "solve_em_fleet"}
    assert em_bs == {1, 2, 4}
    assert {k[2] for k in keys_on if k[0] == "fleet"
            and k[1] == "solve_em_fleet"} == {1}


# ---------------------------------------------------------------------------
# miss ledger — hook keys must agree with the enumerator
# ---------------------------------------------------------------------------

def _arm(monkeypatch, horizon="2:2:8:8", tier="serve"):
    """Arm the lattice WITHOUT compiling: plan, then install the key
    set directly (the smoke test covers the real warmup)."""
    monkeypatch.setenv("TW_AOT", "eager")
    monkeypatch.setenv("TW_AOT_HORIZON", horizon)
    monkeypatch.setenv("TW_AOT_TIER", tier)
    keys = aot.plan_lattice()
    with aot._LOCK:
        aot._LATTICE = frozenset(keys)
        aot._STATE.update(mode="eager", tier=tier, phase="ready",
                          planned=len(keys), compiled=len(keys),
                          seeded=len(keys))
    aot.__dict__["_ARMED"] = True
    return keys


def _common(B, E, W, M):
    return (np.zeros((B, W), np.float32), np.zeros((B, W), np.float32),
            np.zeros((B, W), bool), np.zeros((B, E, M), np.float32),
            np.zeros((B, E, M), np.float32), np.zeros((B, E, M), bool),
            np.zeros((B, E), np.float32), np.zeros((B, E, W), bool),
            np.zeros((B,), np.int32))


_HYPERS = dict(epsilon=1.0, n_sinkhorn=40, sinkhorn_tol=1e-3,
               precision="f32", pallas=True, confidence=False,
               max_preds=1, max_succs=1)


def test_note_fleet_hits_lattice_and_counts_escapes(monkeypatch):
    _arm(monkeypatch)
    tables = (np.zeros((1, 2, 2), bool),)  # only [0].shape[0] is read
    # an enumerated shape: full-sweep B=2/E=2/W=8/M=8/P=1 -> hit
    assert aot.note_fleet("solve_windows_fleet", _common(2, 2, 8, 8),
                          tables, 5, _HYPERS) is None
    # B=4 escapes the B<=2 horizon -> named miss, counted
    shape = aot.note_fleet("solve_windows_fleet", _common(4, 2, 8, 8),
                           tables, 5, _HYPERS)
    assert shape == ("solve_windows_fleet"
                     "[B=4,E=2,W=8,M=8,P=1,mp=1,ms=1,sweeps=5]")
    assert aot.status()["misses"] == {shape: 1.0}
    # non-default hypers select different programs -> miss even in-geometry
    assert aot.note_fleet("solve_windows_fleet", _common(2, 2, 8, 8),
                          tables, 5, dict(_HYPERS, n_sinkhorn=13))
    assert aot.status()["misses"][shape] == 1.0


def test_note_refit_and_assemble_agree_with_enumerator(monkeypatch):
    _arm(monkeypatch)
    from traceweaver_tpu.ops.devcols import ring_capacity

    cap = ring_capacity()
    assert aot.note_refit(np.zeros((2, 2, 8), np.int32),
                          np.zeros((1, 2), np.int32),
                          np.zeros((2, 2, 8), np.float32)) is None
    assert aot.note_assemble(cap, np.zeros((2, 8), np.int32),
                             np.zeros((2, 2, 8), np.int32)) is None
    # a foreign ring capacity is not enumerated
    assert aot.note_assemble(64, np.zeros((2, 8), np.int32),
                             np.zeros((2, 2, 8), np.int32))


def test_note_hooks_are_inert_until_armed():
    assert aot.note_fleet("solve_windows_fleet", _common(2, 2, 8, 8),
                          (np.zeros((1, 2, 2), bool),), 5, _HYPERS) is None
    assert aot.note_refit(np.zeros((2, 2, 8), np.int32),
                          np.zeros((1, 2), np.int32),
                          np.zeros((2, 2, 8), np.float32)) is None
    assert aot.status()["misses"] == {}


def test_mesh_family_enumerated_when_mesh_configured(monkeypatch):
    """ISSUE 15 satellite: with TW_MESH_DEVICES configured the lattice
    grows the sharded program family — per-shard pow2 row counts times
    the mesh size (the bucket_rows_per_shard padding fleet applies),
    keyed by shard count so host-fed variants can never masquerade as
    sharded ones. Without a mesh the family is absent."""
    monkeypatch.setenv("TW_AOT_HORIZON", "2:1:8:8")
    plain = aot.plan_lattice(tier="serve")
    assert all(k[-1] == 1 for k in plain if k[0] == "fleet")

    monkeypatch.setenv("TW_MESH_DEVICES", "2")
    keys = aot.plan_lattice(tier="serve")
    mesh_keys = [k for k in keys if k[0] == "fleet" and k[-1] == 2]
    assert mesh_keys, "no sharded variants planned"
    # B axis = per-shard pow2 x mesh size, inside the horizon
    assert {k[2] for k in mesh_keys} == {2, 4}
    assert {k[1] for k in mesh_keys} == {"solve_windows_fleet",
                                         "solve_em_fleet"}
    # mesh-origin standalone refits stay shards=1 (host-array programs)
    # but appear at the padded mesh row counts with the widened bmax
    refits = [k for k in keys if k[0] == "fleet"
              and k[1] == "refit_fleet_params"]
    assert any(k[2] == 4 and k[7] == 1 for k in refits), (
        "mesh-origin refit (B=4, bmax=1) not planned")
    # single-device family unchanged, keys dedupe cleanly
    assert set(plain) < set(keys)
    assert len(keys) == len(set(keys))
    # shard count renders in the operator-facing shape string
    assert any("x2dev" in aot._key_str(k) for k in mesh_keys)


def test_note_fleet_mesh_keys_agree_with_enumerator(monkeypatch):
    """A mesh dispatch's miss hook must hit the enumerated sharded key
    (and only it): same geometry without the mesh marker is a DIFFERENT
    program and must not be confused for it."""
    from traceweaver_tpu.parallel.mesh import make_mesh

    monkeypatch.setenv("TW_MESH_DEVICES", "2")
    _arm(monkeypatch, horizon="2:2:8:8")
    mesh = make_mesh(2)
    tables = (np.zeros((1, 2, 2), bool),)
    # the sharded full-sweep dispatch at B = 1 row/shard x 2 devices
    assert aot.note_fleet("solve_windows_fleet", _common(2, 2, 8, 8),
                          tables, 5, _HYPERS, mesh=mesh) is None
    # an 8-device dispatch under a 2-device lattice is an escape, named
    # with the shard marker
    shape = aot.note_fleet("solve_windows_fleet", _common(8, 2, 8, 8),
                           tables, 5, _HYPERS, mesh=make_mesh(8))
    assert shape == ("solve_windows_fleet"
                     "[B=8,E=2,W=8,M=8,P=1,mp=1,ms=1,sweeps=5,x8dev]")


def test_miss_ledger_is_bounded(monkeypatch):
    _arm(monkeypatch, horizon="1:1:8:8", tier="core")
    tables = (np.zeros((1, 1, 1), bool),)
    for b in range(2, 2 + aot.MISS_KEY_CAP + 50):
        aot.note_fleet("solve_windows_fleet", _common(b, 1, 8, 8),
                       tables, 5, _HYPERS)
    assert len(aot.status()["misses"]) == aot.MISS_KEY_CAP


# ---------------------------------------------------------------------------
# readiness / status
# ---------------------------------------------------------------------------

def test_readiness_off_mode_is_always_ready(monkeypatch):
    monkeypatch.setenv("TW_AOT", "off")
    assert aot.startup_warmup()["phase"] == "idle"
    ready, detail = aot.readiness()
    assert ready and detail == {"aot": "off", "phase": "off", "planned": 0,
                                "compiled": 0, "ready": True}


def test_warmup_errors_surface_in_readiness(monkeypatch):
    monkeypatch.setenv("TW_AOT", "eager")

    def broken_plan(tier, horizon, prelower=True):
        def boom():
            raise RuntimeError("variant exploded")
        return [aot._Variant(("fake", 0), boom)]

    monkeypatch.setattr(aot, "_plan", broken_plan)
    status = aot.startup_warmup()
    assert status["phase"] == "error"
    assert "variant exploded" in status["errors"][0]
    ready, detail = aot.readiness()
    # a wedged warmup must alert the rollout, not silently pass
    assert not ready and detail["errors"]


def test_startup_warmup_is_idempotent(monkeypatch):
    monkeypatch.setenv("TW_AOT", "eager")
    monkeypatch.setattr(
        aot, "_plan",
        lambda tier, horizon, prelower=True: [
            aot._Variant(("fake", 0), lambda: 0.01)])
    first = aot.startup_warmup()
    assert first["phase"] == "ready" and first["planned"] == 1
    # second call returns the standing state, does not re-plan
    monkeypatch.setattr(aot, "_plan", lambda *a, **k: pytest.fail(
        "re-armed an armed warmup"))
    assert aot.startup_warmup()["planned"] == 1


def test_metrics_collector_exposes_lattice_and_misses(monkeypatch):
    from traceweaver_tpu.obs.registry import get_registry

    monkeypatch.setenv("TW_AOT", "eager")
    monkeypatch.setattr(
        aot, "_plan",
        lambda tier, horizon, prelower=True: [
            aot._Variant(("fake", 0), lambda: 0.01)])
    aot.startup_warmup()
    with aot._LOCK:
        aot._MISSES["solve_windows_fleet[B=64,...]"] = 3.0
    snap = get_registry().snapshot(include_collectors=True)
    assert snap["tw_aot_lattice_size"] == 1.0
    assert snap["tw_aot_precompiled_total"] == 1.0
    assert snap["tw_aot_ready"] == 1.0
    assert snap['tw_aot_miss_total{entry="solve_windows_fleet"}'] == 3.0


# ---------------------------------------------------------------------------
# jax_cache satellites: compile-seconds histogram + cache-dir hardening
# ---------------------------------------------------------------------------

@pytest.mark.obs
def test_xla_compile_seconds_histogram_observes_compiles():
    from traceweaver_tpu.obs.registry import get_registry
    from traceweaver_tpu.runtime.jax_cache import install_compile_counters

    install_compile_counters()
    snap0 = get_registry().snapshot(include_collectors=True)
    before = snap0.get("tw_xla_compile_seconds_count", 0.0)

    @jax.jit
    def f(x):
        return x * 3.0 + 1.0

    np.asarray(f(np.arange(7.0, dtype=np.float32)))
    snap = get_registry().snapshot(include_collectors=True)
    assert snap["tw_xla_compile_seconds_count"] >= before + 1
    assert snap["tw_xla_compile_seconds_sum"] >= snap0.get(
        "tw_xla_compile_seconds_sum", 0.0)


def test_uncreatable_cache_dir_warns_counts_and_serves(
        tmp_path, monkeypatch, capsys):
    import traceweaver_tpu.runtime.jax_cache as jc

    blocker = tmp_path / "not_a_dir"
    blocker.write_text("a file where the cache dir should go")
    monkeypatch.setenv("TW_JAX_CACHE_DIR", str(blocker))
    monkeypatch.setattr(jc, "_CACHE_WARNED", False)
    errors_before = jc._CACHE_ERRORS
    # no raise: serving continues with the cache disabled
    assert jc.enable_persistent_compilation_cache() == ""
    assert jc._CACHE_ERRORS == errors_before + 1
    assert "WARNING" in capsys.readouterr().err
    # warned ONCE: a second enable counts but stays quiet
    assert jc.enable_persistent_compilation_cache() == ""
    assert jc._CACHE_ERRORS == errors_before + 2
    assert "WARNING" not in capsys.readouterr().err
    # the counter reaches /metrics through the jax_cache collector
    from traceweaver_tpu.obs.registry import get_registry

    snap = get_registry().snapshot(include_collectors=True)
    assert snap["tw_xla_cache_errors_total"] >= 2


def test_readonly_cache_dir_still_enables_reads(tmp_path, monkeypatch,
                                                capsys):
    import traceweaver_tpu.runtime.jax_cache as jc

    monkeypatch.setenv("TW_JAX_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(jc, "_CACHE_WARNED", False)
    # root ignores permission bits, so simulate the read-only mount at
    # the probe seam (the probe itself is a real write+unlink)
    monkeypatch.setattr(jc, "_probe_writable", lambda d: False)
    errors_before = jc._CACHE_ERRORS
    cache_dir = jc.enable_persistent_compilation_cache()
    # existing entries still deserialize — the cache stays ENABLED
    assert cache_dir.startswith(str(tmp_path))
    assert jc._CACHE_ERRORS == errors_before + 1
    assert "not writable" in capsys.readouterr().err


def test_writable_cache_dir_probe_is_clean(tmp_path, monkeypatch):
    import traceweaver_tpu.runtime.jax_cache as jc

    monkeypatch.setenv("TW_JAX_CACHE_DIR", str(tmp_path))
    errors_before = jc._CACHE_ERRORS
    cache_dir = jc.enable_persistent_compilation_cache()
    assert cache_dir and os.path.isdir(cache_dir)
    assert jc._CACHE_ERRORS == errors_before
    assert not os.path.exists(os.path.join(cache_dir, ".tw_write_probe"))
