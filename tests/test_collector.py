"""Span-collector pipeline: HPACK codec, HTTP/2 replay, strace reassembly,
thread attribution — end-to-end on a synthetic capture."""

import pytest

from traceweaver_tpu.collector import (
    CollectorReport,
    Decoder,
    Encoder,
    collect_from_strace_log,
    looks_like_http2,
    parse_strace_log,
    replay_connection,
    unescape_strace,
)
from traceweaver_tpu.collector.hpack import (
    HpackError,
    decode_integer,
    encode_integer,
    huffman_decode,
    huffman_encode,
)
from traceweaver_tpu.collector.http2 import (
    FLAG_END_HEADERS,
    FLAG_END_STREAM,
    HEADERS,
    PREFACE,
    SETTINGS,
)
from traceweaver_tpu.collector.ebpf import (
    BPF_PROGRAM,
    DataEvent,
    looks_like_http,
    parse_event,
)


# ---------------------------------------------------------------------------
# HPACK
# ---------------------------------------------------------------------------

def test_integer_coding_rfc_examples():
    # RFC 7541 C.1: 10 in 5-bit prefix; 1337 in 5-bit prefix; 42 in 8-bit
    assert encode_integer(10, 5) == bytes([0x0A])
    assert encode_integer(1337, 5) == bytes([0x1F, 0x9A, 0x0A])
    assert encode_integer(42, 8) == bytes([0x2A])
    for value, prefix in [(0, 1), (10, 5), (1337, 5), (2 ** 30, 7)]:
        data = encode_integer(value, prefix)
        got, pos = decode_integer(data, 0, prefix)
        assert (got, pos) == (value, len(data))


def test_rfc7541_c31_and_c41_request_vectors():
    expected = [(":method", "GET"), (":scheme", "http"), (":path", "/"),
                (":authority", "www.example.com")]
    raw = bytes.fromhex("828684410f7777772e6578616d706c652e636f6d")
    assert Decoder().decode(raw) == expected
    huffman = bytes.fromhex("828684418cf1e3c2e5f23a6ba0ab90f4ff")
    assert Decoder().decode(huffman) == expected


def test_huffman_roundtrip_and_padding():
    for payload in [b"", b"a", b"www.example.com", bytes(range(256))]:
        assert huffman_decode(huffman_encode(payload)) == payload
    # 'a' = 00011 (5 bits); trailing 000 padding is not an EOS prefix
    with pytest.raises(HpackError):
        huffman_decode(b"\x18")


def test_hpack_roundtrip_with_dynamic_table():
    headers = [
        (":method", "POST"),
        (":path", "/rate.Rate/GetRates"),
        ("uber-trace-id", "abc123:def:0:1"),
        ("x-custom", "hello world"),
        (":method", "POST"),           # now indexable
        ("x-custom", "hello world"),   # dynamic-table hit
    ]
    for huffman in (False, True):
        enc = Encoder(huffman=huffman)
        blob = enc.encode(headers)
        assert Decoder().decode(blob) == headers
        if not huffman:
            # repeated fields must compress to 1-byte indexed forms
            assert len(enc.encode(headers)) < len(blob)


def test_hpack_dynamic_table_eviction_under_resize():
    """RFC 7541 §4.2/§6.3: a mid-block table-size update evicts from the
    oldest end; entries evicted by the resize are no longer addressable
    while surviving ones keep decoding — the live replay hits this when
    a captured peer shrinks its table mid-connection."""
    from traceweaver_tpu.collector.hpack import (
        _STATIC,
        Decoder,
        encode_integer,
        encode_string,
    )

    def literal_indexed(name: bytes, value: bytes) -> bytes:
        return (encode_integer(0, 6, flags=0x40) + encode_string(name)
                + encode_string(value))

    dec = Decoder()
    # two dynamic entries: "aaaa" (older) then "bbbb" (newer)
    dec.decode(literal_indexed(b"x-aaaa", b"A" * 10)
               + literal_indexed(b"x-bbbb", b"B" * 10))
    assert len(dec.table.entries) == 2
    base = len(_STATIC)
    # newest first: index base+1 = x-bbbb, base+2 = x-aaaa
    assert dec.decode(encode_integer(base + 2, 7, flags=0x80)) == [
        ("x-aaaa", "A" * 10)]
    # resize to hold exactly ONE entry (entry size = 6+10+32 = 48):
    # the OLDER entry (x-aaaa) must evict, the newer one survives
    resize = encode_integer(48, 5, flags=0x20)
    assert dec.decode(resize + encode_integer(base + 1, 7, flags=0x80)) \
        == [("x-bbbb", "B" * 10)]
    assert [n for n, _ in dec.table.entries] == [b"x-bbbb"]
    # the evicted index is now out of bounds — a hard HpackError, which
    # the replay layer tolerates as a counted decode_error
    with pytest.raises(HpackError, match="out of table bounds"):
        dec.decode(encode_integer(base + 2, 7, flags=0x80))
    # resize above the protocol max is a protocol error
    with pytest.raises(HpackError, match="protocol max"):
        Decoder(max_table_size=4096).decode(
            encode_integer(65536, 5, flags=0x20))


# ---------------------------------------------------------------------------
# HTTP/2 framing helpers
# ---------------------------------------------------------------------------

def _frame(ftype: int, flags: int, stream_id: int, payload: bytes) -> bytes:
    return (len(payload).to_bytes(3, "big") + bytes([ftype, flags])
            + stream_id.to_bytes(4, "big") + payload)


def _client_request_bytes(encoder: Encoder, stream_id: int, path: str,
                          trace_id: str) -> bytes:
    block = encoder.encode([
        (":method", "POST"), (":scheme", "http"), (":path", path),
        (":authority", "svc"), ("uber-trace-id", f"{trace_id}:1:0:1"),
        ("content-type", "application/grpc"),
    ])
    return (_frame(HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM, stream_id,
                   block))


def _server_response_bytes(encoder: Encoder, stream_id: int) -> bytes:
    block = encoder.encode([(":status", "200"),
                            ("content-type", "application/grpc")])
    return _frame(HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM, stream_id,
                  block)


def test_replay_connection_recovers_requests_and_responses():
    enc_c = Encoder()
    enc_s = Encoder()
    inbound = (PREFACE + _frame(SETTINGS, 0, 0, b"")
               + _client_request_bytes(enc_c, 1, "/a", "t1")
               + _client_request_bytes(enc_c, 3, "/b", "t2"))
    outbound = (_frame(SETTINGS, 0, 0, b"")
                + _server_response_bytes(enc_s, 1)
                + _server_response_bytes(enc_s, 3))
    assert looks_like_http2(inbound, outbound)
    in_events, out_events = replay_connection(inbound, outbound)
    reqs = [e for e in in_events if e.kind == "request"]
    resps = [e for e in out_events if e.kind == "response"]
    assert [e.stream_id for e in reqs] == [1, 3]
    assert [e.stream_id for e in resps] == [1, 3]
    assert dict(reqs[0].headers)[":path"] == "/a"
    assert dict(reqs[1].headers)["uber-trace-id"].startswith("t2:")


def test_replay_tolerates_truncated_tail():
    enc = Encoder()
    stream = PREFACE + _client_request_bytes(enc, 1, "/a", "t1")
    truncated = stream + b"\x00\x00\xff\x01\x04"  # partial frame header+
    in_events, _ = replay_connection(truncated, b"")
    assert [e.kind for e in in_events if e.kind == "request"] == ["request"]


def test_interleaved_continuation_drops_pending_counted():
    """RFC 7540 §6.10: CONTINUATION must be contiguous with its HEADERS.
    A capture interleaving another frame (or another stream's
    CONTINUATION) drops the pending block — counted, and the replayer
    keeps decoding subsequent well-formed blocks."""
    from traceweaver_tpu.collector.http2 import (
        CONTINUATION,
        DirectionReplayer,
    )

    enc = Encoder()
    block = enc.encode([(":method", "POST"), (":path", "/a"),
                        (":authority", "svc")])
    # HEADERS without END_HEADERS (expects CONTINUATION)...
    headers_open = _frame(HEADERS, 0, 1, block[:4])
    # ...but a DATA frame for another stream interleaves
    interleaved = _frame(0x0, 0, 3, b"zz")
    # a later complete request must still decode (fresh encoder state —
    # the dropped block never reached the decoder's dynamic table)
    enc2 = Encoder()
    ok_request = _client_request_bytes(enc2, 5, "/b", "t2")
    rep = DirectionReplayer()
    events = rep.feed(PREFACE + headers_open + interleaved + ok_request)
    assert rep.dropped_header_blocks == 1
    reqs = [e for e in events if e.kind == "request"]
    assert [e.stream_id for e in reqs] == [5]

    # CONTINUATION for a DIFFERENT stream also drops the pending block
    rep2 = DirectionReplayer()
    wrong_stream = _frame(CONTINUATION, 0x4, 9, b"")
    events2 = rep2.feed(PREFACE + headers_open + wrong_stream)
    assert rep2.dropped_header_blocks == 1
    assert [e for e in events2 if e.kind == "request"] == []

    # the matching CONTINUATION completes the block normally
    rep3 = DirectionReplayer()
    done = _frame(CONTINUATION, 0x4, 1, block[4:])
    events3 = rep3.feed(PREFACE + headers_open + done)
    assert [e.stream_id for e in events3 if e.kind == "request"] == [1]
    assert rep3.dropped_header_blocks == 0


# ---------------------------------------------------------------------------
# strace reassembly
# ---------------------------------------------------------------------------

def _strace_escape(data: bytes) -> str:
    out = []
    for i, b in enumerate(data):
        if b == 0x22:
            out.append('\\"')
        elif b == 0x5C:
            out.append("\\\\")
        elif 0x20 <= b < 0x7F:
            out.append(chr(b))
        else:
            # strace pads octal to 3 digits when the next character is a
            # digit, so "\0" + literal '0' can't re-parse as "\00"
            nxt = data[i + 1] if i + 1 < len(data) else None
            if nxt is not None and 0x30 <= nxt <= 0x37:
                out.append("\\%03o" % b)
            else:
                out.append("\\%o" % b)
    return "".join(out)


def test_unescape_strace_octal_and_hex():
    assert unescape_strace("\\0\\1\\377abc") == b"\x00\x01\xffabc"
    assert unescape_strace("\\x00\\x41\\xff") == b"\x00A\xff"
    assert unescape_strace('\\"quoted\\"\\n') == b'"quoted"\n'
    payload = bytes(range(256))
    assert unescape_strace(_strace_escape(payload)) == payload


def _strace_lines_for(pid: int, op: str, fd: int, data: bytes, split_at=None):
    """Render one syscall as log lines, optionally as unfinished/resumed."""
    esc = _strace_escape(data)
    if split_at is None:
        return [f'{pid} {op}({fd}, "{esc}", {len(data)}) = {len(data)}']
    if op == "read":
        return [
            f"{pid} read({fd},  <unfinished ...>",
            f'{pid} <... read resumed>"{esc}", {len(data)}) = {len(data)}',
        ]
    return [
        f'{pid} write({fd}, "{esc}", {len(data)} <unfinished ...>',
        f"{pid} <... write resumed> ) = {len(data)}",
    ]


def test_strace_truncated_mid_escape_sequence():
    """A log truncated mid-escape (the capture died mid-line) must not
    crash or corrupt earlier streams: the partial line fails the
    tokenizer and is counted unmatched, and unescape handles dangling
    escapes at end-of-string."""
    from traceweaver_tpu.collector.strace import StraceParser

    # dangling escapes: lone backslash, partial hex, partial octal
    assert unescape_strace("abc\\") == b"abc"
    assert unescape_strace("abc\\x") == b"abcx"
    assert unescape_strace("abc\\x4") == b"abc\x04"
    assert unescape_strace("abc\\37") == b"abc\x1f"

    payload = b"intact-data"
    parser = StraceParser()
    parser.feed_line(_strace_lines_for(11, "read", 7, payload)[0])
    # the log ends mid-escape-sequence, no closing quote/ret
    parser.feed_line('11 read(7, "partial\\x4')
    parser.feed_line('11 read(7, "partial\\37')
    streams = parser.finish()
    assert parser.unmatched_lines == 2
    assert streams[(7, 0)].inbound == payload


def test_capture_ingest_rekeys_on_fd_reuse_without_close():
    """Connection churn: an fd reused (peer reconnected) with NO close
    syscall in the capture — the fresh HTTP/2 preface must re-key the
    logical connection instead of concatenating two connections' bytes,
    and both generations' exchanges must decode."""
    from traceweaver_tpu.collector.http2 import SETTINGS as _S
    from traceweaver_tpu.collector.source import (
        CaptureCounters,
        CaptureIngest,
    )

    def conn_bytes(key: str, enc: Encoder) -> bytes:
        return (PREFACE + _frame(_S, 0, 0, b"")
                + _client_request_bytes(enc, 1, "/x", key))

    counters = CaptureCounters()
    ing = CaptureIngest("svc", counters)
    ing._on_payload((7, 0), "in", conn_bytes("gen0", Encoder()), 100.0)
    # fd 7 reused with a fresh preface — no close line ever appeared
    ing._on_payload((7, 0), "in", conn_bytes("gen1", Encoder()), 200.0)
    ing.finish()
    assert counters.rekeyed == {"svc": 1}
    keys = sorted((r.key, r.gen) for r in ing.records)
    assert keys == [("gen0", 0), ("gen1", 1)]
    # both closed out half-open (requests had no captured response) —
    # counted, synthesized under the default policy, never silent
    assert counters.loss["svc"]["half_open"] == 2


def test_strace_ttt_timestamps_attributed():
    """strace -ttt epoch stamps ride the byte ranges (ts_at) and split
    unfinished/resumed pairs stamp at the data-bearing line."""
    from traceweaver_tpu.collector.strace import StraceParser

    parser = StraceParser()
    parser.feed_line('11 1722000000.250000 read(7, "abcd", 4) = 4')
    parser.feed_line('12 1722000000.500000 write(7, "efgh", 4 '
                     '<unfinished ...>')
    parser.feed_line('12 1722000000.900000 <... write resumed> ) = 4')
    streams = parser.finish()
    s = streams[(7, 0)]
    assert parser.saw_timestamps
    assert s.ts_at("in", 0) == pytest.approx(1722000000.25e6)
    # the write stamps at the UNFINISHED line (data already on the wire)
    assert s.ts_at("out", 0) == pytest.approx(1722000000.5e6)
    assert s.ts_at("out", 99) is None


def test_strace_reassembly_with_unfinished_and_fd_reuse():
    payload1 = b"hello-first-generation"
    payload2 = b"second-generation"
    lines = []
    lines += _strace_lines_for(11, "read", 7, payload1[:10])
    lines += _strace_lines_for(12, "read", 7, payload1[10:], split_at=1)
    lines += ["11 close(7) = 0"]
    lines += _strace_lines_for(13, "read", 7, payload2)
    streams = parse_strace_log("\n".join(lines))
    assert set(streams) == {(7, 0), (7, 1)}
    assert streams[(7, 0)].inbound == payload1
    assert streams[(7, 1)].inbound == payload2
    assert streams[(7, 0)].pid_at("in", 0) == 11
    assert streams[(7, 0)].pid_at("in", 15) == 12
    assert streams[(7, 1)].pid_at("in", 0) == 13


# ---------------------------------------------------------------------------
# End-to-end: synthetic capture -> causal pairs -> thread predictability
# ---------------------------------------------------------------------------

def _synthetic_capture() -> str:
    """A server process: two incoming requests handled by threads 101/102 on
    fd 7; each handler issues one downstream request on fd 9 carrying the
    same trace id (thread 201 for both)."""
    enc_in = Encoder()
    enc_down = Encoder(huffman=True)
    enc_resp = Encoder()

    in_stream = (PREFACE + _frame(SETTINGS, 0, 0, b"")
                 + _client_request_bytes(enc_in, 1, "/hotels", "trace-A"))
    in_stream2 = _client_request_bytes(enc_in, 3, "/hotels", "trace-B")
    down = (PREFACE + _frame(SETTINGS, 0, 0, b"")
            + _client_request_bytes(enc_down, 1, "/rates", "trace-A"))
    down2 = _client_request_bytes(enc_down, 3, "/rates", "trace-B")
    resp = (_frame(SETTINGS, 0, 0, b"")
            + _server_response_bytes(enc_resp, 1)
            + _server_response_bytes(enc_resp, 3))

    lines = []
    # thread 101 reads request A (split across an unfinished/resumed pair)
    lines += _strace_lines_for(101, "read", 7, in_stream[:40], split_at=1)
    lines += _strace_lines_for(101, "read", 7, in_stream[40:])
    # thread 201 writes downstream request A
    lines += _strace_lines_for(201, "write", 9, down, split_at=1)
    # thread 102 reads request B; 201 writes downstream B
    lines += _strace_lines_for(102, "read", 7, in_stream2)
    lines += _strace_lines_for(201, "write", 9, down2)
    # responses flow back
    lines += _strace_lines_for(101, "write", 7, resp)
    return "\n".join(lines)


def test_collector_end_to_end():
    report = collect_from_strace_log(_synthetic_capture())
    assert isinstance(report, CollectorReport)
    assert set(report.events_by_stream) == {(7, 0), (9, 0)}

    incoming = [r for r in report.requests if r.direction == "in"]
    outgoing = [r for r in report.requests if r.direction == "out"]
    assert {r.key for r in incoming} == {"trace-A", "trace-B"}
    assert {r.key for r in outgoing} == {"trace-A", "trace-B"}
    assert {r.pid for r in incoming} == {101, 102}
    assert {r.pid for r in outgoing} == {201}

    assert len(report.causal_pairs) == 2
    for parent, child in report.causal_pairs:
        assert parent.key == child.key
        assert parent.fd == 7 and child.fd == 9
    # downstream thread is constant -> perfectly predictable
    assert report.thread_predictability == 1.0


# ---------------------------------------------------------------------------
# eBPF module (gated: program text + event mirror only)
# ---------------------------------------------------------------------------

def test_ebpf_program_text_and_event_mirror():
    assert "BPF_PERF_OUTPUT(events)" in BPF_PROGRAM
    assert "kretprobe__ksys_read" in BPF_PROGRAM
    import ctypes

    ev = DataEvent(pid=42, fd=7, op=1, len=3)
    raw = ctypes.string_at(ctypes.addressof(ev), ctypes.sizeof(ev))
    parsed = parse_event(raw)
    assert (parsed.pid, parsed.fd, parsed.op, parsed.len) == (42, 7, 1, 3)
    # truncated submit (header only) still parses
    parsed2 = parse_event(raw[: ctypes.sizeof(DataEvent) - 4096])
    assert parsed2.pid == 42


def test_http_heuristic():
    assert looks_like_http(b"GET /index HTTP/1.1\r\n")
    assert looks_like_http(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")
    assert not looks_like_http(b"\x16\x03\x01")  # TLS hello


def test_strace_runner_attaches_to_new_pids(tmp_path, monkeypatch):
    """Runner attaches once per new PID and returns the log map (the live
    attach itself is stubbed — no strace binary / ptrace in the sandbox)."""
    from traceweaver_tpu.collector import strace_runner

    pids_by_poll = iter([[101], [101, 202], [101, 202]])
    attached = []

    class FakeProc:
        def poll(self):
            return 0

        def terminate(self):
            pass

    monkeypatch.setattr(strace_runner, "pgrep",
                        lambda name: next(pids_by_poll, [101, 202]))
    monkeypatch.setattr(strace_runner.shutil, "which", lambda _: "/usr/bin/strace")

    def fake_attach(pid, out_path, string_limit=65536):
        attached.append((pid, out_path))
        return FakeProc()

    monkeypatch.setattr(strace_runner, "attach_strace", fake_attach)
    seen = strace_runner.run("search", out_dir=str(tmp_path), tag="7",
                             duration=0.3, poll_interval=0.01, max_attempts=2)
    assert sorted(seen) == [101, 202]
    assert [p for p, _ in attached] == [101, 202]
    assert all(f"output7-attempt" in path for _, path in attached)


def test_strace_runner_keeps_captures_alive_until_duration(tmp_path, monkeypatch):
    """Hitting max-attempts must stop NEW attachments, not terminate
    in-flight captures before the requested window elapses."""
    import time as _time

    from traceweaver_tpu.collector import strace_runner

    terminated_at = []
    t0 = _time.monotonic()

    class FakeProc:
        def poll(self):
            return None

        def terminate(self):
            terminated_at.append(_time.monotonic() - t0)

    monkeypatch.setattr(strace_runner, "pgrep", lambda name: [11])
    monkeypatch.setattr(strace_runner.shutil, "which",
                        lambda _: "/usr/bin/strace")
    monkeypatch.setattr(strace_runner, "attach_strace",
                        lambda pid, path, string_limit=65536: FakeProc())
    strace_runner.run("search", out_dir=str(tmp_path), duration=0.25,
                      poll_interval=0.01, max_attempts=1)
    assert terminated_at and terminated_at[0] >= 0.2


def test_executor_compressed_tar_extraction(tmp_path):
    """--compressed: <path>.tar.* is extracted before loading (reference
    executor.py:854-855)."""
    import json
    import tarfile

    from traceweaver_tpu.runtime.executor import maybe_uncompress

    src = tmp_path / "payload"
    src.mkdir()
    (src / "t1.json").write_text(json.dumps({"data": []}))
    archive = tmp_path / "ds.tar.gz"
    with tarfile.open(archive, "w:gz") as tf:
        tf.add(src / "t1.json", arcname="t1.json")
    target = tmp_path / "ds"
    maybe_uncompress(str(target))
    assert (target / "t1.json").exists()
    # idempotent: second call with files present is a no-op
    maybe_uncompress(str(target))
