"""Guards for the driver-critical bench internals.

BENCH_r03 failed rc=124 and round 4 rebuilt bench.py around a hard
envelope; these tests pin the pieces a future edit could silently break:
the xplane profile parser's CPU fallback (the committed PROFILE artifact
depends on it) and the baseline child's recording-guided budget logic
(which decides how many fresh same-input exact pairs the driver's
accuracy delta gets).
"""

import importlib
import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bench(monkeypatch):
    sys.path.insert(0, REPO)
    import bench as bench_mod

    return importlib.reload(bench_mod)


def test_parse_profile_cpu_fallback(bench, tmp_path):
    """A real CPU-backend trace must parse through the /host:CPU tf_XLA*
    fallback: nonzero busy time, op table without ThunkExecutor wrapper
    events, and dur_s (containment) keys — not self_s. Skips (instead of
    erroring) on jax versions that do not export ProfileData — the same
    feature check _parse_profile gates on in production."""
    from traceweaver_tpu.obs.profile import profile_data_available

    if not profile_data_available():
        pytest.skip("jax.profiler.ProfileData unavailable on this jax "
                    "version (bench._parse_profile returns None)")
    import jax
    import jax.numpy as jnp

    jax.profiler.start_trace(str(tmp_path))
    jax.jit(lambda x: (x @ x).sum())(jnp.ones((256, 256))).block_until_ready()
    jax.profiler.stop_trace()

    prof = bench._parse_profile(str(tmp_path))
    assert prof is not None
    assert prof["profile_source"] == "host_cpu_xla_threads"
    # tiny programs may run entirely on codegen threads without
    # ThunkExecutor spans (busy 0); the op table is the load-bearing part
    assert prof["device_busy_s"] >= 0
    assert prof["top_ops"], "expected at least one op"
    for op in prof["top_ops"]:
        assert "dur_s" in op and "self_s" not in op
        assert not op["op"].startswith("ThunkExecutor")


def test_baseline_child_carries_recording_for_over_alarm_services(
        bench, tmp_path, monkeypatch):
    """Budget logic: services whose recorded cost exceeds the alarm carry
    the recording (measured=false) instead of burning a guaranteed-alarm
    fresh attempt; cheap services are solved fresh; a stale recording
    (different subset size) must not gate anything."""
    import pickle

    monkeypatch.setenv("TW_BENCH_APPS", "hotel")
    monkeypatch.setenv("TW_BENCH_MAX_TRACES", "40")
    monkeypatch.setenv("TW_BENCH_SUBSET", "8")
    monkeypatch.setenv("TW_BENCH_BASELINE_BUDGET", "120")
    b = importlib.reload(bench)

    bundles = b.build_problems()
    bundle = tmp_path / "bundle.pkl"
    with open(bundle, "wb") as f:
        pickle.dump(bundles, f)

    # recording matching this config: frontend "too slow" for the 95s
    # alarm, search cheap — frontend must carry, search must run fresh
    rec = {
        "subset_spans": 8, "compress": b.COMPRESS,
        "services": {
            "hotel/frontend": {"finished": True, "seconds": 500.0,
                               "n_spans": 8, "accuracy": 0.875},
            "hotel/search": {"finished": True, "seconds": 0.5,
                             "n_spans": 8, "accuracy": 1.0},
        },
    }
    monkeypatch.setattr(b, "RECORDED_PATH", str(tmp_path / "rec.json"))
    with open(b.RECORDED_PATH, "w") as f:
        json.dump(rec, f)

    out = tmp_path / "baseline.json"
    b.run_baseline_child(str(bundle), str(out))
    with open(out) as f:
        report = json.load(f)
    sub = report["subset"]
    assert sub["hotel/frontend"]["measured"] is False  # carried
    assert sub["hotel/frontend"]["accuracy"] == 0.875
    assert sub["hotel/search"]["measured"] is True     # fresh
    assert report["n_fresh"] == 1 and report["n_recorded"] == 1

    # stale recording (wrong subset size): nothing carried, both fresh
    rec["subset_spans"] = 99
    with open(b.RECORDED_PATH, "w") as f:
        json.dump(rec, f)
    b.run_baseline_child(str(bundle), str(out))
    with open(out) as f:
        report2 = json.load(f)
    assert all(v["measured"] for v in report2["subset"].values())
    assert report2["n_recorded"] == 0


def test_baseline_child_skips_recorded_dnf_without_ample_budget(
        bench, tmp_path, monkeypatch):
    """A service the recording proves cannot finish (finished=false) must
    NOT get a benefit-of-the-doubt fresh attempt on a normal budget — the
    budget goes to unmeasured services instead (ADVICE r4). With an ample
    budget (> 2 alarms) the DNF service is retried."""
    import pickle

    monkeypatch.setenv("TW_BENCH_APPS", "hotel")
    monkeypatch.setenv("TW_BENCH_MAX_TRACES", "40")
    monkeypatch.setenv("TW_BENCH_SUBSET", "8")
    monkeypatch.setenv("TW_BENCH_BASELINE_BUDGET", "60")
    b = importlib.reload(bench)

    bundles = b.build_problems()
    bundle = tmp_path / "bundle.pkl"
    with open(bundle, "wb") as f:
        pickle.dump(bundles, f)

    rec = {
        "subset_spans": 8, "compress": b.COMPRESS,
        "services": {
            "hotel/frontend": {"finished": False, "seconds": 95.0,
                               "n_spans": 8, "accuracy": None},
            "hotel/search": {"finished": True, "seconds": 0.5,
                             "n_spans": 8, "accuracy": 1.0},
        },
    }
    monkeypatch.setattr(b, "RECORDED_PATH", str(tmp_path / "rec.json"))
    with open(b.RECORDED_PATH, "w") as f:
        json.dump(rec, f)

    out = tmp_path / "baseline.json"
    b.run_baseline_child(str(bundle), str(out))
    with open(out) as f:
        report = json.load(f)
    sub = report["subset"]
    # DNF carried (not retried), cheap service solved fresh
    assert sub["hotel/frontend"]["measured"] is False
    assert sub["hotel/frontend"]["finished"] is False
    assert sub["hotel/search"]["measured"] is True
    assert report["n_fresh"] == 1


def test_backend_label_flags_cpu_fallback(bench):
    """A CPU-solver report must surface as backend=cpu_fallback in the
    final JSON line so fallback numbers can never be mistaken for
    on-chip results (this bit the round-5 driver bench); real chip
    backends pass through unrelabeled."""
    assert bench.backend_label("cpu") == ("cpu_fallback", False)
    assert bench.backend_label(None) == ("cpu_fallback", False)
    assert bench.backend_label("tpu") == ("tpu", True)
    assert bench.backend_label("axon") == ("axon", True)


@pytest.mark.aot
def test_aot_fields_summarizes_warmup_ledger(bench):
    """The AOT warmup report builder: runtime/aot.status() -> aot_*
    fields, with the miss ledger passed through verbatim and summed."""
    status = {"mode": "eager", "phase": "ready", "planned": 10,
              "compiled": 10, "compile_s": 2.7816,
              "misses": {"solve_windows_fleet[B=64,...]": 3.0,
                         "fit_gmm[e=8,n=128]": 1.0}}
    out = bench.aot_fields(status)
    assert out["aot_mode"] == "eager" and out["aot_phase"] == "ready"
    assert out["aot_lattice_size"] == 10
    assert out["aot_precompiled"] == 10
    assert out["aot_compile_s"] == 2.782
    assert out["aot_miss_count"] == 4
    assert out["aot_misses"]["fit_gmm[e=8,n=128]"] == 1.0
    # an empty status degrades to zeros, not a crash
    empty = bench.aot_fields({})
    assert empty["aot_lattice_size"] == 0
    assert empty["aot_miss_count"] == 0


@pytest.mark.aot
def test_coldstart_fields_targets_and_verdicts(bench):
    """The cold-start leg report builder: two child reports -> the
    cold_start_s/warm_start_s pair, the <5 s warm-restart verdict, the
    zero-solve-compile verdict, and the warm child's aot_* ledger."""
    cold = {"first_trace_s": 7.807, "warmup_s": 6.821,
            "fleet_backend_compiles": 0,
            "measured_compiles": {"backend_compiles": 0}}
    warm = {"first_trace_s": 3.785, "warmup_s": 2.837,
            "fleet_backend_compiles": 0,
            "measured_compiles": {"backend_compiles": 0},
            "aot": {"mode": "eager", "phase": "ready", "planned": 10,
                    "compiled": 10, "compile_s": 2.782, "misses": {}}}
    out = bench.coldstart_fields(cold, warm)
    assert out["cold_start_s"] == 7.807
    assert out["warm_start_s"] == 3.785
    assert out["coldstart_speedup"] == 2.06
    assert out["coldstart_warm_under_target"] is True
    assert out["coldstart_warm_zero_solve_compiles"] is True
    assert out["aot_lattice_size"] == 10 and out["aot_miss_count"] == 0

    # a slow warm restart or a compiling solve is flagged, not hidden
    slow = bench.coldstart_fields(
        cold, {**warm, "first_trace_s": 9.0, "fleet_backend_compiles": 2})
    assert slow["coldstart_warm_under_target"] is False
    assert slow["coldstart_warm_zero_solve_compiles"] is False
    # empty children degrade to None/False, not a crash
    empty = bench.coldstart_fields({}, {})
    assert empty["cold_start_s"] is None
    assert empty["coldstart_speedup"] is None
    assert empty["coldstart_warm_under_target"] is False


@pytest.mark.faults
def test_chaos_fields_ledger_and_delta(bench):
    """The chaos-leg report builder: fleet fault counters -> chaos_*
    ledger fields, accuracy fractions -> delta in POINTS against the
    ≤1 pt bar, dead-letter bytes passed through verbatim."""
    fault_stats = {"fault_retries": 7.0, "fault_bisections": 2.0,
                   "fault_xla_fallbacks": 1.0, "fault_host_fallbacks": 1.0,
                   "fault_quarantined": 1.0,
                   "fault_ladder": ["retry", "retry", "bisect"]}
    clean = {"hotel/frontend": 0.90, "hotel/search": 1.0}
    chaos = {"hotel/frontend": 0.90, "hotel/search": 0.99}
    out = bench.chaos_fields(fault_stats, clean, chaos, 123)
    assert out["chaos_retries"] == 7
    assert out["chaos_bisections"] == 2
    assert out["chaos_xla_fallbacks"] == 1
    assert out["chaos_host_fallbacks"] == 1
    assert out["chaos_quarantined"] == 1
    assert out["chaos_deadletter_bytes"] == 123
    # mean of (0, -1.0) pts
    assert out["chaos_accuracy_delta_pts"] == -0.5
    assert out["chaos_delta_exceeds_1pt"] is False

    # a quarantined-heavy run blows the bar -> flagged, not hidden
    bad = bench.chaos_fields({}, clean, {"hotel/frontend": 0.0,
                                         "hotel/search": 1.0}, 0)
    assert bad["chaos_delta_exceeds_1pt"] is True
    # empty accuracies degrade to None, not a crash
    empty = bench.chaos_fields({}, {}, {}, 0)
    assert empty["chaos_accuracy_delta_pts"] is None
    assert empty["chaos_delta_exceeds_1pt"] is False


@pytest.mark.precision
def test_bf16_delta_fields_per_dataset_and_warn_list(bench):
    """The bf16-vs-f32 accuracy delta aggregation: fraction accuracies
    keyed by service label -> per-dataset mean deltas in POINTS, with
    the >1 pt warn list naming datasets (apps), not services."""
    accs_f32 = {"hotel/frontend": 0.90, "hotel/search": 0.80,
                "media/compose": 0.95}
    accs_bf16 = {"hotel/frontend": 0.905, "hotel/search": 0.810,
                 "media/compose": 0.90}
    out = bench.bf16_delta_fields(accs_f32, accs_bf16)
    # hotel mean delta = (0.5 + 1.0)/2 = 0.75 pts; media = -5.0 pts
    assert out["accuracy_delta_vs_f32_per_dataset"] == {
        "hotel": 0.75, "media": -5.0}
    assert out["bf16_delta_exceeds_1pt"] == ["media"]
    # overall mean over services: (0.5 + 1.0 - 5.0) / 3
    assert out["accuracy_delta_vs_f32"] == round((0.5 + 1.0 - 5.0) / 3, 4)
    # empty input degrades to None / empty, not a crash
    empty = bench.bf16_delta_fields({}, {})
    assert empty["accuracy_delta_vs_f32"] is None
    assert empty["bf16_delta_exceeds_1pt"] == []


@pytest.mark.serve
def test_serve_fields_ledger_and_isolation_delta(bench):
    """The --serve-tenants leg's report builder: run summaries -> the
    serve_* field set the driver consumes, with the isolation metric as
    the healthy-tenant throughput delta (storm vs clean) in percent."""
    clean = dict(spans=4000, wall_s=4.0, healthy_spans=3000,
                 dispatches=3, shared_solves=2, tenant_batches=20,
                 shed_windows=1, per_tenant_min=10.0, per_tenant_max=90.0)
    storm = dict(spans=3800, wall_s=4.0, healthy_spans=2850,
                 quarantined_windows=5, deadletter_windows=5,
                 healthy_quarantined=0, healthy_shed=0,
                 faults_injected=17, spec="dispatch:0.5")
    out = bench.serve_fields(100, clean, storm)
    assert out["serve_tenants"] == 100
    assert out["serve_spans_total"] == 4000
    assert out["serve_spans_per_s"] == 1000.0
    assert out["serve_fleet_dispatches"] == 3
    assert out["serve_shared_solves"] == 2
    assert out["serve_tenant_batches"] == 20
    assert out["serve_shed_windows"] == 1
    assert out["serve_per_tenant_spans_per_s_min"] == 10.0
    assert out["serve_per_tenant_spans_per_s_max"] == 90.0
    assert out["serve_storm_spec"] == "dispatch:0.5"
    assert out["serve_storm_injected"] == 17
    assert out["serve_quarantined_windows"] == 5
    assert out["serve_deadletter_windows"] == 5
    assert out["serve_healthy_spans_per_s_clean"] == 750.0
    assert out["serve_healthy_spans_per_s_storm"] == 712.5
    assert out["serve_isolation_delta_pct"] == -5.0
    assert out["serve_only_faulty_tenant_accrues"] is True
    # a storm that taxes neighbors flips the isolation verdict
    bad = bench.serve_fields(
        100, clean, dict(storm, healthy_quarantined=2))
    assert bad["serve_only_faulty_tenant_accrues"] is False
    # empty/zero inputs degrade to None rates, never divide-by-zero
    empty = bench.serve_fields(0, {}, {})
    assert empty["serve_spans_per_s"] is None
    assert empty["serve_isolation_delta_pct"] is None


def test_continuous_fields_slo_and_throughput_verdicts(bench):
    """The --continuous leg's report builder: fixed-pump vs continuous
    run summaries -> the continuous_* field set, with the two headline
    verdicts (beats the fixed pump on spans/s; worst-tenant p99 inside
    the SLO) and the zero-steady-compiles flag."""
    fixed = dict(spans=4000, wall_s=4.0, p99_max_ms=900.0, dispatches=6)
    cont = dict(spans=4000, wall_s=2.0, p99_max_ms=750.0, dispatches=9,
                steady_compiles=0, h2d_bytes_ring=1234.0,
                h2d_bytes_index=5678.0,
                continuous=dict(dispatches=7, urgent_dispatches=2))
    out = bench.continuous_fields(100, 2000.0, fixed, cont)
    assert out["continuous_tenants"] == 100
    assert out["continuous_slo_p99_ms"] == 2000.0
    assert out["continuous_spans_per_s"] == 2000.0
    assert out["continuous_spans_per_s_fixed_pump"] == 1000.0
    assert out["continuous_speedup_vs_fixed_pct"] == 100.0
    assert out["continuous_beats_fixed_pump"] is True
    assert out["continuous_seal_emit_p99_ms_max"] == 750.0
    assert out["continuous_seal_emit_p99_ms_max_fixed"] == 900.0
    assert out["continuous_p99_within_slo"] is True
    assert out["continuous_dispatches"] == 7
    assert out["continuous_urgent_dispatches"] == 2
    assert out["continuous_steady_compiles"] == 0
    assert out["continuous_zero_steady_compiles"] is True
    assert out["continuous_h2d_bytes_ring"] == 1234.0
    assert out["continuous_h2d_bytes_index"] == 5678.0
    # an SLO breach and a recompiling steady state flip the verdicts
    slow = bench.continuous_fields(
        100, 2000.0, fixed,
        dict(cont, p99_max_ms=2500.0, steady_compiles=3))
    assert slow["continuous_p99_within_slo"] is False
    assert slow["continuous_zero_steady_compiles"] is False
    # empty/zero inputs degrade to None rates, never divide-by-zero
    empty = bench.continuous_fields(0, 2000.0, {}, {})
    assert empty["continuous_spans_per_s"] is None
    assert empty["continuous_speedup_vs_fixed_pct"] is None
    assert empty["continuous_p99_within_slo"] is None


def test_overlap_fields_ring_engagement_and_throughput_verdicts(bench):
    """The --serve-overlap leg's report builder: serial (inflight=1) vs
    ring run summaries -> the overlap_* field set, with the headline
    triple (beats serial on spans/s; measured overlap_pct > 0 with the
    ring actually engaged; worst-tenant p99 inside the SLO) and the
    zero-steady-compiles flag."""
    serial = dict(spans=3000, wall_s=3.0, p99_max_ms=900.0)
    ring = dict(spans=3000, wall_s=2.0, p99_max_ms=1100.0,
                steady_compiles=0,
                ring=dict(enabled=True, inflight_limit=2, outstanding=0,
                          submitted=12, completed=12, aborted=0,
                          overlap_pct=37.5))
    out = bench.overlap_fields(24, 2, 2000.0, serial, ring)
    assert out["overlap_tenants"] == 24
    assert out["overlap_inflight"] == 2
    assert out["overlap_spans_per_s"] == 1500.0
    assert out["overlap_spans_per_s_serial"] == 1000.0
    assert out["overlap_speedup_vs_serial_pct"] == 50.0
    assert out["overlap_beats_serial"] is True
    assert out["overlap_pct"] == 37.5
    assert out["overlap_ring_engaged"] is True
    assert out["overlap_tickets_submitted"] == 12
    assert out["overlap_tickets_completed"] == 12
    assert out["overlap_tickets_aborted"] == 0
    assert out["overlap_seal_emit_p99_ms_max"] == 1100.0
    assert out["overlap_seal_emit_p99_ms_max_serial"] == 900.0
    assert out["overlap_p99_within_slo"] is True
    assert out["overlap_zero_steady_compiles"] is True
    # a ring that never held two tickets at once is NOT engaged — and a
    # recompiling or SLO-breaching ring flips its verdicts
    idle = bench.overlap_fields(
        24, 2, 2000.0, serial,
        dict(ring, p99_max_ms=2500.0, steady_compiles=2,
             ring=dict(ring["ring"], overlap_pct=0.0)))
    assert idle["overlap_ring_engaged"] is False
    assert idle["overlap_p99_within_slo"] is False
    assert idle["overlap_zero_steady_compiles"] is False
    # empty/zero inputs degrade to None rates, never divide-by-zero
    empty = bench.overlap_fields(0, 1, 2000.0, {}, {})
    assert empty["overlap_spans_per_s"] is None
    assert empty["overlap_speedup_vs_serial_pct"] is None
    assert empty["overlap_p99_within_slo"] is None
    assert empty["overlap_ring_engaged"] is False


@pytest.mark.wal
def test_wal_fields_overhead_and_compile_verdicts(bench):
    """The --wal leg's report builder: per-sync-policy run summaries ->
    the wal_* field set, with the headline pair (batch policy's
    throughput overhead vs WAL-off <= 10%; zero steady compiles on
    every pass) and per-policy ack-latency passthrough."""
    passes = dict(
        off=dict(spans=3000, wall_s=3.0, ack_p50_ms=0.9, ack_p99_ms=5.0,
                 steady_compiles=0),
        batch=dict(spans=2910, wall_s=3.0, ack_p50_ms=2.1,
                   ack_p99_ms=10.0, steady_compiles=0, wal_appends=144),
        always=dict(spans=2700, wall_s=3.0, ack_p50_ms=4.2,
                    ack_p99_ms=14.0, steady_compiles=0, wal_appends=144),
    )
    out = bench.wal_fields(6, passes)
    assert out["wal_tenants"] == 6
    assert out["wal_off_spans_per_s"] == 1000.0
    assert out["wal_batch_spans_per_s"] == 970.0
    assert out["wal_batch_overhead_pct"] == 3.0
    assert out["wal_batch_within_overhead"] is True
    assert out["wal_batch_appends"] == 144
    assert "wal_off_appends" not in out  # no log to count when off
    assert out["wal_always_ack_p99_ms"] == 14.0
    assert out["wal_zero_steady_compiles"] is True
    # a batch pass pricier than the 10% budget, or any recompiling
    # pass, flips its verdict
    slow = bench.wal_fields(6, dict(
        passes, batch=dict(passes["batch"], spans=2500),
        always=dict(passes["always"], steady_compiles=2)))
    assert slow["wal_batch_overhead_pct"] > 10.0
    assert slow["wal_batch_within_overhead"] is False
    assert slow["wal_zero_steady_compiles"] is False
    # empty/zero inputs degrade to None rates, never divide-by-zero
    empty = bench.wal_fields(0, dict(off={}, batch={}, always={}))
    assert empty["wal_off_spans_per_s"] is None
    assert empty["wal_batch_overhead_pct"] is None
    assert empty["wal_batch_within_overhead"] is None


@pytest.mark.collector
def test_capture_fields_hardening_verdicts(bench):
    """The --capture leg's report builder: clean/skew/lossy run
    summaries -> the capture_* field set, with the three headline
    verdicts (skew corrected, churn tolerated, loss degrading
    gracefully) and the no-crash gate."""
    clean = dict(completed=True, spans=120, acc=100.0, loss={},
                 loss_rate=0.0, rekeyed=1, conf_mean=1.0)
    skewed = dict(completed=True, acc=99.5, skew_detected_us=-251000.0)
    lossy = dict(completed=True, acc=70.0,
                 loss={"dropped_chunk": 40, "half_open": 6},
                 loss_rate=0.25, conf_mean=0.75, conf_discount=0.75)
    out = bench.capture_fields(clean, skewed, lossy, 250000.0)
    assert out["capture_acc_clean"] == 100.0
    assert out["capture_skew_acc_delta_pts"] == 0.5
    assert out["capture_skew_detected_us"] == -251000.0
    assert out["capture_skew_corrected_ok"] is True
    assert out["capture_rekeyed_streams"] == 1
    assert out["capture_churn_tolerated"] is True
    assert out["capture_loss_counters"] == {"dropped_chunk": 40,
                                            "half_open": 6}
    assert out["capture_loss_counted"] is True
    assert out["capture_conf_discounted"] is True
    assert out["capture_no_crash"] is True
    assert out["capture_graceful"] is True

    # a skew fit off by >20% of the injection flips the correction flag
    bad_fit = bench.capture_fields(
        clean, dict(skewed, skew_detected_us=-100000.0), lossy, 250000.0)
    assert bad_fit["capture_skew_corrected_ok"] is False
    # a skew-leg accuracy collapse flips it too (fit alone isn't enough)
    bad_acc = bench.capture_fields(
        clean, dict(skewed, acc=40.0), lossy, 250000.0)
    assert bad_acc["capture_skew_corrected_ok"] is False
    # undiscounted confidence under loss = silent wrong traces -> not
    # graceful
    silent = bench.capture_fields(
        clean, skewed, dict(lossy, conf_discount=1.0, conf_mean=1.0),
        250000.0)
    assert silent["capture_conf_discounted"] is False
    assert silent["capture_graceful"] is False
    # a crashed leg fails the no-crash gate, never hides
    crashed = bench.capture_fields(
        clean, skewed, dict(completed=False, error="Boom"), 250000.0)
    assert crashed["capture_no_crash"] is False
    assert crashed["capture_graceful"] is False
    # empty summaries degrade to None/False, not exceptions
    empty = bench.capture_fields({}, {}, {}, 0.0)
    assert empty["capture_acc_clean"] is None
    assert empty["capture_skew_corrected_ok"] is False


def test_ingest_fields_ledger_and_ratio(bench):
    """The --ingest-only leg's report builder: pack timings under both
    TW_COLUMNAR settings -> the pack_* field set (spans/s, s/window, and
    the columnar-vs-object speedup the >=10x acceptance bar reads)."""
    out = bench.ingest_fields(100_000, 500, col_s=0.05, obj_s=1.0)
    assert out["ingest_spans"] == 100_000
    assert out["ingest_windows"] == 500
    assert out["pack_spans_per_s"] == 2_000_000.0
    assert out["pack_s_per_window"] == 0.0001
    assert out["pack_spans_per_s_object"] == 100_000.0
    assert out["pack_columnar_speedup"] == 20.0
    # empty/zero inputs degrade to None, never divide-by-zero
    empty = bench.ingest_fields(0, 0, 0.0, 0.0)
    assert empty["pack_spans_per_s"] is None
    assert empty["pack_s_per_window"] is None
    assert empty["pack_columnar_speedup"] is None


def test_wire_fields_ledger_and_speedups(bench):
    """The --wire-ingest leg's report builder: payload->store parse
    timings under the native, pure-Python, and object front ends -> the
    wire_* field set (the r18 >=5x acceptance bar reads wire_speedup)."""
    out = bench.wire_fields(100_000, 20_000, wire_s=0.5, python_s=2.0,
                            obj_s=2.5)
    assert out["wire_spans"] == 100_000
    assert out["wire_traces"] == 20_000
    assert out["wire_spans_per_s"] == 200_000.0
    assert out["wire_spans_per_s_python"] == 50_000.0
    assert out["wire_spans_per_s_object"] == 40_000.0
    assert out["wire_speedup"] == 5.0
    assert out["wire_speedup_python"] == 1.25
    # empty/zero inputs degrade to None, never divide-by-zero
    empty = bench.wire_fields(0, 0, 0.0, 0.0, 0.0)
    assert empty["wire_spans_per_s"] is None
    assert empty["wire_speedup"] is None
    assert empty["wire_speedup_python"] is None


def test_ingest_leg_small_run_parity_and_fields(bench, monkeypatch):
    """A tiny end-to-end --ingest-only run: both paths pack byte-identical
    blocks and every ledger field lands in the report."""
    report = bench.run_ingest_leg(2000)
    assert report["mode"] == "ingest"
    assert report["pack_parity_ok"] is True
    assert report["ingest_spans"] >= 1900
    assert report["ingest_windows"] > 0
    assert report["pack_spans_per_s"] > 0
    assert report["pack_spans_per_s_object"] > 0
    assert report["pack_columnar_speedup"] > 0


def test_parse_profile_none_when_profiledata_missing(bench, tmp_path,
                                                     monkeypatch):
    """The ProfileData feature gate: on jax versions without the export,
    _parse_profile degrades to None (profile fields stay null) instead
    of raising ImportError mid-enrichment."""
    import traceweaver_tpu.obs.profile as obs_profile

    monkeypatch.setattr(obs_profile, "profile_data_available",
                        lambda: False)
    assert bench._parse_profile(str(tmp_path)) is None


def test_telemetry_fields_agreement_and_mismatch(bench):
    """The obs-registry agreement proof: fleet ledger counter deltas ==
    the legacy stage-stats dict; gauge-mirrored high-water marks are
    excluded (read from the snapshot, not hardcoded); a counter the
    registry never saw is a NAMED mismatch."""
    snap0 = {'tw_fleet_ledger_total{key="wait_s"}': 1.0,
             'tw_fleet_ledger_total{key="fleet_dispatches"}': 3.0}
    snap1 = {'tw_fleet_ledger_total{key="wait_s"}': 1.5,
             'tw_fleet_ledger_total{key="fleet_dispatches"}': 5.0,
             'tw_fleet_gauge{key="pipeline_depth"}': 4.0}
    stats = {"wait_s": 0.5, "fleet_dispatches": 2.0,
             "pipeline_depth": 4.0,          # gauge key: excluded
             "fault_ladder": ["retry"]}      # list-valued: excluded
    out = bench.telemetry_fields(stats, snap0, snap1)
    assert out["telemetry_matches_legacy"] is True
    assert out["telemetry_mismatch_keys"] == []
    assert out["telemetry_snapshot"] == {"fleet_dispatches": 2.0,
                                         "wait_s": 0.5}

    rogue = dict(stats, rogue_counter=1.0)
    out2 = bench.telemetry_fields(rogue, snap0, snap1)
    assert out2["telemetry_matches_legacy"] is False
    assert out2["telemetry_mismatch_keys"] == ["rogue_counter"]


@pytest.mark.quality
def test_confidence_fields_summary(bench, monkeypatch):
    """The quality-ledger report builder: per-item confidence maps ->
    population/mean/min, the TW_CONF_LOW low share, and the OT-override
    share; empty input degrades to None fields, not a crash."""
    monkeypatch.setenv("TW_CONF_LOW", "0.5")
    maps = [
        {("t", "a"): {"conf": 1.0, "not_best": False},
         ("t", "b"): {"conf": 0.5, "not_best": True}},
        None,  # a quarantined/None slot must not crash the summary
        {("t", "c"): {"conf": 0.25, "not_best": True}},
    ]
    out = bench.confidence_fields(maps)
    assert out["conf_spans"] == 3
    assert out["conf_mean"] == pytest.approx((1.0 + 0.5 + 0.25) / 3,
                                             abs=1e-4)
    assert out["conf_min"] == 0.25
    assert out["conf_low_frac"] == pytest.approx(2 / 3, abs=1e-4)
    assert out["conf_overridden_frac"] == pytest.approx(2 / 3, abs=1e-4)

    empty = bench.confidence_fields([])
    assert empty["conf_spans"] == 0
    assert empty["conf_mean"] is None
    assert empty["conf_low_frac"] is None


@pytest.mark.quality
def test_scorecard_fields_regimes_and_calibration_flags(bench):
    """The scorecard-leg report builder: per-regime matrix passthrough,
    TPU-minus-best-baseline deltas, and BOTH calibration verdicts (the
    noise-aware monotone flag and the crude top-vs-bottom check)."""
    card = {
        "per_regime": {
            "sequential": {"fcfs": 1.0, "weaver_tpu": 1.0},
            "fanout": {"fcfs": 0.1, "wap5": 0.0, "weaver_tpu": 0.3},
        },
        "weaver_exact_subset_spans": 12,
        "calibration": [
            {"decile": 1, "conf_lo": 0.2, "conf_hi": 0.5, "n": 20,
             "accuracy": 0.2},
            {"decile": 2, "conf_lo": 0.5, "conf_hi": 1.0, "n": 20,
             "accuracy": 0.9},
        ],
        "calibration_monotone_ok": True,
        "calibration_violations": [],
    }
    out = bench.scorecard_fields(card)
    assert out["scorecard_regimes"] == card["per_regime"]
    assert out["scorecard_tpu_minus_best_baseline"] == {
        "sequential": 0.0, "fanout": 0.2}
    assert out["scorecard_exact_subset_spans"] == 12
    assert out["scorecard_calibration_monotone_ok"] is True
    assert out["scorecard_top_vs_bottom_ok"] is True
    assert out["scorecard_calibration_violations"] == []

    # an inverted table flags BOTH verdicts (warn surface, not a crash)
    inv = dict(card, calibration=list(reversed(card["calibration"])),
               calibration_monotone_ok=False,
               calibration_violations=["decile 2 ..."])
    out2 = bench.scorecard_fields(inv)
    assert out2["scorecard_calibration_monotone_ok"] is False
    assert out2["scorecard_top_vs_bottom_ok"] is False
    assert out2["scorecard_calibration_violations"] == ["decile 2 ..."]

    # degenerate cards (no calibration rows) stay well-formed
    bare = bench.scorecard_fields({"per_regime": {}, "calibration": []})
    assert bare["scorecard_top_vs_bottom_ok"] is None
    assert bare["scorecard_tpu_minus_best_baseline"] == {}


@pytest.mark.campaign
def test_campaign_fields_flatten_artifact_headlines(bench):
    """The campaign-leg report builder: per-rung sustained spans/s,
    the steady zero-compile gate, the accuracy floor, and the
    multislice agreement flag — flattened from one CAMPAIGN_* artifact
    (docs/CAMPAIGN.md)."""
    art = dict(
        name="mini",
        plan=dict(devices=2, slices=2),
        rungs=[
            dict(rung="a",
                 manifest=dict(spans=1000),
                 steady=dict(spans_per_s=2500.0, backend_compiles=0,
                             aot_misses=[], quarantined=0),
                 accuracy=dict(e2e_pct=100.0),
                 multislice=dict(agree=True)),
            dict(rung="b",
                 manifest=dict(spans=3000),
                 steady=dict(spans_per_s=4000.0, backend_compiles=2,
                             aot_misses=["solve_windows_fleet[B=64]"],
                             quarantined=1),
                 accuracy=dict(e2e_pct=98.5),
                 multislice=None),
        ])
    out = bench.campaign_fields(art)
    assert out["campaign_rungs"] == 2
    assert out["campaign_devices"] == 2
    assert out["campaign_spans_total"] == 4000
    assert out["campaign_spans_per_s"] == {"a": 2500.0, "b": 4000.0}
    assert out["campaign_accuracy_e2e_min"] == 98.5
    assert out["campaign_steady_compiles"] == 2
    assert out["campaign_aot_misses"] == 1
    assert out["campaign_quarantined"] == 1
    assert out["campaign_multislice_agree"] is True
    # empty artifact degrades to counts, not a crash
    empty = bench.campaign_fields(dict(name="x", plan={}, rungs=[]))
    assert empty["campaign_rungs"] == 0
    assert empty["campaign_accuracy_e2e_min"] is None
