"""Benchmark: TPU Sinkhorn reconstruction throughput vs the CPU oracle.

Workload: hotel_reservation AND media_microservices @ load150 (1000
recorded traces each), arrivals compressed 10x (reference
``repeat_change_spans`` semantics, transforms.py:10-40) — the
high-interleave regime the reference's Alibaba scale sweep (exp5)
stresses, where DFS candidate enumeration blows up combinatorially.
Eight services total (hotel frontend/search + media's six), fused into
one device dispatch per window-shape class — typically 1-2 for this
workload (fleet.py; supersedes the reference's per-service ThreadPool,
executor.py:1015-1026).

Two accuracy/throughput comparisons, both on identical inputs:

- full corpus: WeaverTPU (fused two-pass EM) over every span; the
  combinatorial baseline cannot run this.
- same-input subset: the first TW_BENCH_SUBSET (default 25) incoming
  spans per service are solved by BOTH WeaverTPU and the exact DFS+MWIS
  path (WeaverExact "MaxScoreBatch", Gurobi stand-in). The report
  carries ``accuracy_delta_same_inputs`` and a *measured* exact-path
  spans/sec. Exact solves are expensive (25-span subsets cost 4-90 s
  EACH, measured), so the baseline child fresh-solves as many services
  as its remaining budget allows — cheapest first, guided by
  ``exps/parity/exact_subset_recorded.json`` (a committed recording of
  a full uncapped run) — and carries the recording for the rest, each
  service flagged ``measured`` true/false.

The timed pass runs under ``jax.profiler`` and the trace is parsed
in-process (``jax.profiler.ProfileData``): ``device_busy_s_measured`` /
``mfu_measured_pct`` come from the device plane's executed-op timeline,
not wall-clock inference (committed as PROFILE_r{N}.json).

Prints ONE JSON line with the TPU spans/sec and the vs-baseline ratio.

Orchestration — the round-3 failure (BENCH_r03: rc=124, no parsed line)
dictates the design. The sandbox's remote TPU backend ("axon") tunnels
device init and every XLA compile through a relay; device init alone has
been observed to block >10 minutes, and a foreign on-disk compile cache
made every deserialization fail before that (now impossible: the cache is
namespaced per backend+host, runtime/jax_cache.py). So the parent:

1. never initializes a JAX backend itself; it builds + pickles the packed
   problems, then enforces ONE global deadline (TW_BENCH_DEADLINE,
   default 780 s) across every phase;
2. launches the solver child on the TPU backend with whatever budget the
   deadline leaves after reserving for the fallback + baseline legs —
   gated: the child drops a ``backend.up`` marker the moment backend
   init returns, so a down backend (init hang) is detected within
   ``TW_BENCH_BACKEND_UP`` seconds instead of eating the whole phase.
   The child then writes its report ATOMICALLY after each phase (timed
   pass -> subsets -> pallas/profile enrichment) and drops a
   ``timing.done`` marker when all solver work is done — a timeout kill
   after the first report write loses enrichment, never the measurement;
3. on marker-or-exit starts the exact-path baseline (CPU subprocess, no
   JAX), strictly after the solver child's work so nothing is timed
   under host contention;
4. if the TPU child produced nothing, runs a CPU-backend child — with
   the FULL two-app workload when the early down-detection left enough
   budget (~430 s), else reduced to the hotel app (media's nginx alone
   needs ~410 s on a cold CPU path) so the fallback provably finishes;
5. merges the child reports and prints the final JSON line — on the
   deadline, whatever has been written is merged as-is, so the driver
   always gets a parseable line inside the envelope.
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import subprocess
import sys
import tempfile
import time
from typing import Optional

from traceweaver_tpu.runtime import knobs as _knobs

DATASETS = (
    # (app, path, fix)
    ("hotel", "/root/reference/data/hotel_reservation/hotel_load150", 2),
    ("media", "/root/reference/data/media_microservices/media_load150", 1),
)
COMPRESS = 10.0
SUBSET_SPANS = _knobs.get_int("TW_BENCH_SUBSET")
# per-service safety alarm for the same-input exact solves. NOT every
# service fits it (the committed recording has media rating/text at
# ~130 s each on a 1-core host): services whose recorded cost exceeds
# the alarm carry the recording instead of burning the alarm for nothing
EXACT_ALARM_SECONDS = _knobs.get_int("TW_BENCH_EXACT_ALARM")
# the whole bench must fit this envelope (the round-3 artifact died by
# exceeding the driver's budget; this is the single knob that bounds us)
DEADLINE = _knobs.get_int("TW_BENCH_DEADLINE")
# How long the solver child may sit inside backend init before the
# parent declares the remote backend down. Evidence base: a DOWN axon
# does not init slowly — it blocks 25-40 min and then raises UNAVAILABLE
# (observed twice in round 4 and all of round 5's watcher probes); when
# axon was healthy (round 2) init + cold compile together took ~15 s
# (BENCH_r02 warmup_compile_s) and the whole child fit inside 85 s.
# 120 s therefore still gives a degraded-but-healthy relay ~8x headroom
# while converting a down backend into CPU budget early enough that the
# FULL two-app CPU leg fits the envelope on a 1-core host (round-5 host:
# warm full leg ~280 s measured). Raise via env on relay-saturated
# deployments.
BACKEND_UP_BUDGET = _knobs.get_int("TW_BENCH_BACKEND_UP")
# reserves the parent holds back when budgeting earlier phases
CPU_FALLBACK_RESERVE = _knobs.get_int("TW_BENCH_CPU_RESERVE")
BASELINE_RESERVE = _knobs.get_int("TW_BENCH_BASELINE_RESERVE")
MERGE_SLACK = 20
TPU_TIMEOUT_CAP = _knobs.get_int("TW_BENCH_TPU_TIMEOUT")

HERE = os.path.dirname(os.path.abspath(__file__))
RECORDED_PATH = os.path.join(
    HERE, "exps", "parity", "exact_subset_recorded.json")

T_START = time.time()


def log(msg: str) -> None:
    print(f"[bench +{time.time() - T_START:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def remaining(deadline_ts: float) -> float:
    return deadline_ts - time.time()


def write_json_atomic(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# Shared problem construction (pure NumPy/Python — safe in the parent)
# ---------------------------------------------------------------------------

def build_problems(apps=None):
    from traceweaver_tpu.ingest import (
        build_service_problem,
        infer_invocation_dag,
        load_corpus,
    )
    from traceweaver_tpu.metrics import get_ground_truth
    from traceweaver_tpu.synth import compress_spans

    # smoke-test knobs (unset in driver runs): restrict apps / corpus size
    env_apps = _knobs.get("TW_BENCH_APPS")
    if apps is None and env_apps:
        apps = set(env_apps.split(","))
    max_traces = _knobs.get_int("TW_BENCH_MAX_TRACES")

    bundles = []
    for app, path, fix in DATASETS:
        if apps is not None and app not in apps:
            continue
        store = load_corpus(path, fix=fix, max_traces=max_traces, cache=True)
        problems = []
        for svc in store.out_spans_by_process:
            prob = build_service_problem(store, svc)
            if prob.skipped:
                continue
            ta = get_ground_truth(prob.in_span_partitions,
                                  prob.out_span_partitions)
            dag = infer_invocation_dag(
                prob.in_span_partitions, prob.out_span_partitions, ta, store
            )
            compress_spans(prob.in_span_partitions, prob.out_span_partitions,
                           1, COMPRESS)
            ta = get_ground_truth(prob.in_span_partitions,
                                  prob.out_span_partitions)
            problems.append((f"{app}/{svc}", svc, prob, ta, dag))
        bundles.append((store, problems))
    return bundles


def subset_problem(prob, n):
    """First-n incoming spans of a service problem (shared by both the
    TPU and exact children so the comparison is on identical inputs)."""
    from traceweaver_tpu.metrics import get_ground_truth

    in_ep = next(iter(prob.in_span_partitions))
    spans = sorted(prob.in_span_partitions[in_ep],
                   key=lambda s: (s.start_mus, s.end_mus))[:n]
    sub_in = {in_ep: spans}
    sub_ta = get_ground_truth(sub_in, prob.out_span_partitions)
    return sub_in, sub_ta


# ---------------------------------------------------------------------------
# Solver child (runs under whichever JAX backend the env selects)
# ---------------------------------------------------------------------------

def _parse_profile(profile_dir):
    """Device-plane busy time + top self-time ops from the xplane trace.

    Returns None when this jax version cannot deserialize xplane traces
    in-process (``jax.profiler.ProfileData`` not exported — the feature
    check lives in obs/profile.py so the bench test can skip cleanly
    instead of erroring)."""
    import glob

    from traceweaver_tpu.obs.profile import profile_data_available

    if not profile_data_available():
        return None

    from jax.profiler import ProfileData

    paths = glob.glob(os.path.join(profile_dir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        return None
    with open(sorted(paths)[-1], "rb") as f:
        data = ProfileData.from_serialized_xspace(f.read())
    busy_ns = 0.0
    ops = {}
    source = None
    for plane in data.planes:
        name = plane.name or ""
        if not (name.startswith("/device:") or "TPU" in name.upper()):
            continue
        for line in plane.lines:
            lname = (line.name or "").lower()
            # "XLA Modules" spans whole executables (busy time);
            # "XLA Ops" has per-op self time (the roofline breakdown)
            if "module" in lname:
                for ev in line.events:
                    busy_ns += ev.duration_ns
                    source = "device_plane"
            elif "op" in lname:
                for ev in line.events:
                    ops[ev.name] = ops.get(ev.name, 0.0) + ev.duration_ns
                    source = "device_plane"
    if source is None:
        # CPU backend: no populated device plane — XLA op executions live
        # on the host plane's tf_XLA* executor thread lines. Busy time is
        # the exact ThunkExecutor::Execute run spans, summed across
        # worker threads (so it can exceed wall-clock; the caller-side
        # "... (wait for completion)" idle spans are excluded — they
        # would double-count time the workers' spans already cover).
        # Per-op durations are INCLUSIVE (while.* events contain their
        # body ops) — reported under "dur_s", not "self_s", so consumers
        # cannot mistake the CPU containment profile for additive
        # self-time.
        for plane in data.planes:
            if (plane.name or "") != "/host:CPU":
                continue
            for line in plane.lines:
                lname = line.name or ""
                if not (lname.startswith("tf_XLA")
                        or "xla-cpu-codegen" in lname):
                    continue
                for ev in line.events:
                    if ev.name == "ThunkExecutor::Execute":
                        busy_ns += ev.duration_ns
                        source = "host_cpu_xla_threads"
                    elif ev.name.startswith("ThunkExecutor::Execute"):
                        continue  # caller-side wait span
                    else:
                        ops[ev.name] = ops.get(ev.name, 0.0) + ev.duration_ns
                        source = "host_cpu_xla_threads"
    if source is None:
        return None
    dur_key = "self_s" if source == "device_plane" else "dur_s"
    top = sorted(ops.items(), key=lambda kv: -kv[1])[:12]
    return {
        "device_busy_s": busy_ns / 1e9,
        "profile_source": source,
        "top_ops": [
            {"op": k[:120], dur_key: round(v / 1e9, 4)} for k, v in top
        ],
    }


def run_solver_child(bundle_path: str, out_path: str) -> None:
    with open(bundle_path, "rb") as f:
        bundles = pickle.load(f)
    n_services = sum(len(p) for _, p in bundles)
    log(f"child: bundle loaded ({n_services} services)")

    import jax

    # the sandbox's sitecustomize force-updates jax_platforms="axon,cpu" at
    # interpreter start, so the env var alone cannot select CPU — mirror it
    # into the config before the first backend init (tests/conftest.py does
    # the same)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from traceweaver_tpu.runtime.jax_cache import (
        enable_persistent_compilation_cache,
    )

    # the cache dir is namespaced per backend+host (jax_cache.py), so a
    # warm cache is genuinely THIS machine's: warmup then measures cache
    # deserialization, not a cold compile — the report says which.
    cache_dir = enable_persistent_compilation_cache()
    cache_entries_before = set(os.listdir(cache_dir)) if cache_dir else set()

    t0 = time.perf_counter()
    backend = jax.default_backend()
    init_s = time.perf_counter() - t0
    # init can block for tens of minutes when the remote backend is down
    # (observed: ~40 min then UNAVAILABLE); this marker tells the parent
    # the backend actually came up, so an init hang is detected early and
    # the saved budget goes to a full-workload CPU leg instead
    write_json_atomic(out_path + ".backend.up",
                      {"backend": backend, "init_s": round(init_s, 2)})
    log(f"child: jax backend = {backend} (init {init_s:.1f}s), "
        f"devices = {jax.devices()}")

    from traceweaver_tpu.algorithms.fleet import FleetItem, solve_fleet
    from traceweaver_tpu.metrics import accuracy_for_service
    from traceweaver_tpu.ops.precision import precision_from_env, score_itemsize

    # score-path precision (TW_PRECISION): the timed pass and every
    # fused dispatch run at this precision; the subset leg additionally
    # measures the bf16-vs-f32 accuracy delta on identical inputs below
    precision = precision_from_env()
    log(f"child: score-path precision = {precision} "
        f"({score_itemsize(precision)} B/elem score blocks)")

    flat = [(label, svc, prob, ta, dag, store)
            for store, problems in bundles
            for label, svc, prob, ta, dag in problems]

    def one_pass(stage_stats=None):
        # ALL services (both apps) ride one fused device program —
        # pass0 + per-service BIC-GMM refit + pass1, one round trip
        # (fleet.py; proven assignment-identical to the per-service
        # path by tests/test_fleet.py)
        items = [FleetItem(svc, prob.in_span_partitions,
                           prob.out_span_partitions, ta, dag, store=store)
                 for _, svc, prob, ta, dag, store in flat]
        outs = solve_fleet(
            items, stats=stage_stats if stage_stats is not None else {})
        return {label: out[0] for (label, *_), out in zip(flat, outs)}

    from traceweaver_tpu.runtime.jax_cache import (
        compile_counters,
        counters_delta,
    )

    counters0 = compile_counters()
    t0 = time.perf_counter()
    one_pass()  # compile warm-up (cached afterwards)
    warmup_time = time.perf_counter() - t0
    warmup_counters = counters_delta(counters0)
    cache_warm = bool(cache_dir) and (
        set(os.listdir(cache_dir)) == cache_entries_before)
    log(f"child: warm-up (compile) pass {warmup_time:.1f}s "
        f"(cache_warm={cache_warm}, "
        f"{warmup_counters['backend_compiles']} compiles, "
        f"{warmup_counters['persistent_cache_hits']} cache hits)")

    profile_dir = _knobs.get("TW_BENCH_PROFILE_DIR")
    auto_profile_dir = profile_dir is None
    if auto_profile_dir:
        profile_dir = tempfile.mkdtemp(prefix="tw_profile_")
    from traceweaver_tpu.obs.registry import get_registry

    jax.profiler.start_trace(profile_dir)
    stage_stats: dict = {}
    counters0 = compile_counters()
    telemetry0 = get_registry().snapshot()
    t0 = time.perf_counter()
    preds = one_pass(stage_stats)
    solve_time = time.perf_counter() - t0
    telemetry1 = get_registry().snapshot()
    jax.profiler.stop_trace()
    timed_counters = counters_delta(counters0)
    if timed_counters["backend_compiles"]:
        log(f"child: WARNING — timed pass recompiled "
            f"{timed_counters['backend_compiles']} program(s); the "
            "headline includes compile time (shape classes multiplied "
            "between warm-up and the measured pass)")

    n_spans = sum(
        len(next(iter(prob.in_span_partitions.values())))
        for _, _, prob, _, _, _ in flat
    )
    log(f"child: timed pass {solve_time:.1f}s "
        f"({n_spans / solve_time:.0f} spans/s)")

    accs = {
        label: accuracy_for_service(preds[label], ta, prob.in_span_partitions)
        for label, _, prob, ta, _, _ in flat
    }

    # Utilization denominators. Peaks: TPU v5e ~197 TFLOP/s bf16 MXU (the
    # headline "MFU" denominator; this pipeline is f32/VPU-heavy, so its
    # MFU is structurally small) and ~819 GB/s HBM. Under the pipelined
    # dispatcher wait_s is summed across concurrent flows, so it can
    # exceed wall-clock — the min() caps the proxy at the wall.
    device_s_wall = min(stage_stats.get("wait_s", 0.0) or solve_time,
                        solve_time)
    flops = stage_stats.get("flops_est", 0.0)
    peak_flops = 197e12 if backend in ("tpu", "axon") else 2e11
    peak_bw = 819e9 if backend in ("tpu", "axon") else 5e10

    report = {
        "backend": backend,
        "backend_init_s": round(init_s, 2),
        # mixed-precision ledger: the configured score-block precision,
        # its bytes/element, and the analytic score-block HBM traffic at
        # that itemsize (bf16 halves bytes_est_xla's score stream — the
        # byte-ledger evidence the precision mode exists to produce)
        "precision": precision,
        "score_block_itemsize": score_itemsize(precision),
        "bytes_est_xla": stage_stats.get("bytes_est_xla", 0.0),
        "bytes_est_pallas": stage_stats.get("bytes_est_pallas", 0.0),
        "n_spans": n_spans,
        "n_services": len(flat),
        "solve_time_s": solve_time,
        "warmup_time_s": warmup_time,
        "compile_cache_warm": cache_warm,
        "spans_per_sec": n_spans / solve_time,
        "accuracy_mean": sum(accs.values()) / len(accs),
        "accuracy_per_service": {k: round(v, 4) for k, v in accs.items()},
        "stage_seconds": {
            k: round(stage_stats.get(k, 0.0), 3)
            for k in ("pack_s", "dispatch_s", "wait_s", "decode_s",
                      "refit_s", "plan_fit_s")
        },
        "fused_em_dispatches": int(stage_stats.get("fused_em_applied", 0)),
        # recompile accounting (runtime/jax_cache counters): the timed
        # pass should run at ZERO backend compiles — nonzero means shape
        # classes multiplied after warm-up and the headline is polluted
        "recompiles_timed": int(timed_counters["backend_compiles"]),
        "compile_counts_warmup": warmup_counters,
        "compile_counts_timed": timed_counters,
        "compaction_windows_total": int(
            stage_stats.get("compact_windows_total", 0)),
        "compaction_windows_redispatched": int(
            stage_stats.get("compact_windows_redispatched", 0)),
        # pipelined-dispatch ledger: groups that rode the pipeline, the
        # max concurrent in-flight groups (depth, bounded by the
        # live-element budget), total D2H bytes the host actually pulled,
        # and the flag-only share of it (the O(B)-bytes compaction fetch
        # — compare against d2h_bytes_fetched to see the byte reduction)
        "pipeline_groups": int(stage_stats.get("pipeline_groups", 0)),
        "pipeline_depth": int(stage_stats.get("pipeline_depth", 0)),
        "d2h_bytes_fetched": float(stage_stats.get("d2h_bytes_fetched", 0.0)),
        "d2h_bytes_flags": float(stage_stats.get("d2h_bytes_flags", 0.0)),
        # H2D ledger split (TW_DEVCOLS, docs/PERF.md "Device-resident
        # span columns"): host window tensors shipped vs resident-ring
        # appends vs gather index arrays. The resident path must show
        # ring+index traffic — these fields existing means a devcols run
        # can never silently claim zero H2D
        "h2d_bytes_shipped": float(stage_stats.get("h2d_bytes_shipped", 0.0)),
        "h2d_bytes_ring": float(stage_stats.get("h2d_bytes_ring", 0.0)),
        "h2d_bytes_index": float(stage_stats.get("h2d_bytes_index", 0.0)),
        "devcols_fallbacks": int(stage_stats.get("devcols_fallbacks", 0)),
        # device-busy time / stage wall-clock: how much of the timed pass
        # the device spent executing (wait_s proxy here; replaced by the
        # measured device plane after profile enrichment when available)
        "pipeline_overlap_pct": round(
            100.0 * device_s_wall / max(solve_time, 1e-9), 2),
        "flops_est": flops,
        "mfu_est_pct": round(100.0 * flops / max(device_s_wall, 1e-9)
                             / peak_flops, 4),
    }
    # obs-registry agreement proof (docs/OBSERVABILITY.md): the timed
    # pass's registry counter deltas must equal the legacy stats dict
    # field-for-field — the mirror is real, not a second bookkeeper
    report.update(telemetry_fields(stage_stats, telemetry0, telemetry1))
    if not report["telemetry_matches_legacy"]:
        log("child: WARNING — obs registry deltas disagree with the "
            f"legacy stage stats on {report['telemetry_mismatch_keys']}")
    # measurement is on disk from this point on — a timeout kill can only
    # lose enrichment below, never the headline
    write_json_atomic(out_path, report)
    log("child: report written (timed pass)")

    # --- same-input subset leg (identical spans + ground truth as the
    # exact-path baseline child; one fused dispatch for all subsets).
    # The subsets are solved under BOTH precisions regardless of the
    # configured one (they are tiny — seconds each): the active
    # precision's accuracies feed the vs-exact pairing, and the f32/bf16
    # pair on identical inputs is the measured accuracy-delta-vs-f32 the
    # acceptance bar (≤1 pt per dataset) is checked against. ----------
    t0 = time.perf_counter()
    sub_items, sub_meta = [], []
    for label, svc, prob, ta, dag, store in flat:
        sub_in, sub_ta = subset_problem(prob, SUBSET_SPANS)
        # key by the ACTUAL span count (a service may hold fewer spans
        # than requested) — the pairing key the parent reconstructs from
        # the baseline's recorded n_spans
        n_actual = len(next(iter(sub_in.values())))
        sub_items.append(FleetItem(svc, sub_in, prob.out_span_partitions,
                                   sub_ta, dag, store=store))
        sub_meta.append((label, f"{label}@{n_actual}", sub_in, sub_ta))
    accs_by_prec = {}
    sub_confs = None
    for prec_leg in ("f32", "bf16"):
        confs = [None] * len(sub_items)
        outs = solve_fleet(sub_items, precision=prec_leg,
                           confidences=confs)
        if prec_leg == precision or sub_confs is None:
            sub_confs = confs  # the active precision's quality ledger
        accs_by_prec[prec_leg] = {
            label: accuracy_for_service(out[0], sub_ta, sub_in)
            for (label, _, sub_in, sub_ta), out in zip(sub_meta, outs)
        }
    subset_accs = {
        key: accs_by_prec[precision][label]
        for label, key, _, _ in sub_meta
    }
    report["subset_spans_per_service"] = SUBSET_SPANS
    report["subset_accuracy_per_service"] = {
        k: round(v, 4) for k, v in subset_accs.items()}
    report.update(bf16_delta_fields(accs_by_prec["f32"],
                                    accs_by_prec["bf16"]))
    # the quality-telemetry ledger of the subset solve: what tw.confidence
    # would say about these windows (docs/OBSERVABILITY.md)
    report.update(confidence_fields(sub_confs))
    report["subset_solve_s"] = round(time.perf_counter() - t0, 2)
    if report["bf16_delta_exceeds_1pt"]:
        log("child: WARNING — bf16 accuracy delta exceeds 1 pt vs f32 on "
            f"dataset(s) {report['bf16_delta_exceeds_1pt']} "
            f"(per-dataset pts: {report['accuracy_delta_vs_f32_per_dataset']})")
    write_json_atomic(out_path, report)
    log(f"child: subset pass {report['subset_solve_s']}s "
        f"(delta_vs_f32 {report['accuracy_delta_vs_f32']} pts) — "
        "report updated")

    # --- opt-in chaos leg (bench.py --faults / TW_BENCH_FAULTS): the same
    # subset inputs re-solved under injected faults. The solve must
    # COMPLETE through the supervisor's degradation ladder; the ledger
    # (retries/bisections/fallbacks/quarantined/deadletter bytes) and the
    # chaos-vs-clean accuracy delta (must stay ≤ 1 pt) ship in the
    # report. ----------------------------------------------------------
    chaos_spec = _knobs.get("TW_BENCH_FAULTS")
    if chaos_spec:
        from traceweaver_tpu.runtime import faults as faults_mod

        t0 = time.perf_counter()
        chaos_stats: dict = {}
        chaos_q: list = []
        chaos_seed = _knobs.get_int("TW_FAULTS_SEED")
        log(f"child: chaos leg under TW_BENCH_FAULTS={chaos_spec!r} "
            f"(seed {chaos_seed})")
        with faults_mod.override(chaos_spec, seed=chaos_seed) as plan:
            chaos_outs = solve_fleet(sub_items, stats=chaos_stats,
                                     quarantined=chaos_q,
                                     precision=precision)
        accs_chaos = {
            label: accuracy_for_service(out[0], sub_ta, sub_in)
            for (label, _, sub_in, sub_ta), out in zip(sub_meta, chaos_outs)
        }
        dlq_bytes = sum(
            len(json.dumps({"service": sub_meta[i][0],
                            "reason": "quarantined"})) + 1
            for i in chaos_q)
        report.update(chaos_fields(
            chaos_stats, accs_by_prec[precision], accs_chaos, dlq_bytes))
        report["chaos_spec"] = chaos_spec
        report["chaos_injected"] = plan.total_injected()
        report["chaos_solve_s"] = round(time.perf_counter() - t0, 2)
        if report["chaos_delta_exceeds_1pt"]:
            log("child: WARNING — chaos-leg accuracy delta exceeds 1 pt "
                f"vs the unfaulted leg ({report['chaos_accuracy_delta_pts']}"
                " pts)")
        write_json_atomic(out_path, report)
        log(f"child: chaos leg {report['chaos_solve_s']}s — "
            f"{report['chaos_retries']} retries, "
            f"{report['chaos_quarantined']} quarantined, "
            f"delta {report['chaos_accuracy_delta_pts']} pts")

    # --- enrichment ------------------------------------------------------
    # NOTE: the parent holds the baseline child until the marker below, so
    # enrichment (profile parse, pallas compile check) must finish first —
    # the baseline's fresh exact-path timings would otherwise run under
    # host contention with this CPU work and inflate the headline ratio
    # (the measurement-protecting atomic report writes above already make
    # a mid-enrichment kill lose nothing but enrichment itself)
    profile = None
    try:
        profile = _parse_profile(profile_dir)
    except Exception as e:  # trace formats vary per backend plugin
        log(f"child: profile parse failed: {type(e).__name__}: {e}")
    log(f"child: profiler trace in {profile_dir}")
    if auto_profile_dir:
        import shutil

        shutil.rmtree(profile_dir, ignore_errors=True)

    busy_measured = (profile or {}).get("device_busy_s") or 0.0
    profile_source = (profile or {}).get("profile_source")
    report["device_busy_s_measured"] = (busy_measured if busy_measured > 0
                                        else None)
    report["profile_source"] = profile_source
    report["profile_top_ops"] = (profile or {}).get("top_ops")
    # "measured" MFU comes ONLY from a real device plane: the CPU
    # fallback's busy time is summed across XLA worker threads (can
    # exceed wall-clock), which would silently deflate a "measured"
    # utilization — on that path the metric stays null and the estimate
    # (wall-clock denominator) is the number to read
    report["mfu_measured_pct"] = (
        round(100.0 * flops / busy_measured / peak_flops, 4)
        if busy_measured > 0 and profile_source == "device_plane" else None)
    device_s = (busy_measured
                if busy_measured > 0 and profile_source == "device_plane"
                else device_s_wall)
    # with a real device plane, the overlap metric stops being a proxy:
    # measured busy time over the timed pass's wall-clock
    if busy_measured > 0 and profile_source == "device_plane":
        report["pipeline_overlap_pct"] = round(
            100.0 * min(busy_measured, solve_time) / max(solve_time, 1e-9),
            2)

    # --- Pallas kernel on-device proof (non-interpret) -------------------
    pallas_ok = None
    if backend in ("tpu", "axon"):
        try:
            import numpy as np

            from traceweaver_tpu.ops.pallas_sinkhorn import sinkhorn_log_pallas
            from traceweaver_tpu.ops.sinkhorn import sinkhorn_log

            rng = np.random.default_rng(0)
            S = rng.normal(size=(64, 128)).astype(np.float32)
            r = np.ones(64, np.float32)
            c = np.full(128, 0.5, np.float32)
            got = np.asarray(sinkhorn_log_pallas(S, r, c, epsilon=1.0,
                                                 n_iters=40, interpret=False))
            want = np.asarray(sinkhorn_log(S, r, c, epsilon=1.0, n_iters=40))
            pallas_ok = bool(np.allclose(got, want, rtol=2e-3, atol=2e-4))
            log(f"child: pallas on-device check ok={pallas_ok}")
        except Exception as e:  # lowering not supported on this plugin
            log(f"child: pallas on-device check failed: "
                f"{type(e).__name__}: {e}")
            pallas_ok = False
    report["pallas_on_device_ok"] = pallas_ok
    bytes_key = ("bytes_est_pallas" if pallas_ok else "bytes_est_xla")
    report["hbm_util_est_pct"] = round(
        100.0 * stage_stats.get(bytes_key, 0.0)
        / max(device_s, 1e-9) / peak_bw, 2)

    write_json_atomic(out_path, report)
    # all solver work (measured passes AND host-CPU enrichment) is done:
    # the baseline child may now run uncontended
    write_json_atomic(out_path + ".timing.done", {"ok": True})
    profile_json = _knobs.get("TW_BENCH_PROFILE_JSON")
    if profile_json:
        write_json_atomic(profile_json, {
            "backend": backend,
            "profile_source": report["profile_source"],
            "device_busy_s_measured": report["device_busy_s_measured"],
            "mfu_measured_pct": report["mfu_measured_pct"],
            "mfu_est_pct": report["mfu_est_pct"],
            "hbm_util_est_pct": report["hbm_util_est_pct"],
            "solve_time_s": round(solve_time, 3),
            "stage_seconds": report["stage_seconds"],
            "top_ops": report["profile_top_ops"],
        })
    log("child: report written (enriched)")


# ---------------------------------------------------------------------------
# Combinatorial baseline child (no JAX backend at all)
# ---------------------------------------------------------------------------

def _dataset_of(label: str) -> str:
    """``hotel/frontend`` -> ``hotel`` (the bench's per-app grouping)."""
    return label.split("/", 1)[0]


def bf16_delta_fields(accs_f32: dict, accs_bf16: dict) -> dict:
    """bf16-vs-f32 accuracy deltas on identical inputs -> report fields.

    Input accuracies are fractions (0..1) keyed by service label; the
    reported deltas are in POINTS (x100) to match the ≤1 pt acceptance
    bar. ``bf16_delta_exceeds_1pt`` lists every dataset (app) whose mean
    delta magnitude crosses 1 pt — the bench warns on any entry.
    """
    deltas = {k: (accs_bf16[k] - accs_f32[k]) * 100.0
              for k in accs_f32 if k in accs_bf16}
    by_ds: dict = {}
    for k, d in deltas.items():
        by_ds.setdefault(_dataset_of(k), []).append(d)
    per_dataset = {ds: sum(v) / len(v) for ds, v in sorted(by_ds.items())}
    return {
        "accuracy_delta_vs_f32": (
            round(sum(deltas.values()) / len(deltas), 4) if deltas else None),
        "accuracy_delta_vs_f32_per_dataset": {
            ds: round(d, 4) for ds, d in per_dataset.items()},
        "bf16_delta_exceeds_1pt": sorted(
            ds for ds, d in per_dataset.items() if abs(d) > 1.0),
    }


def chaos_fields(fault_stats: dict, accs_clean: dict, accs_chaos: dict,
                 deadletter_bytes: int) -> dict:
    """Chaos-leg ledger + accuracy delta -> report fields.

    ``fault_stats`` is the faulted solve's fleet stats dict (the
    supervisor's ``fault_*`` counters); accuracies are fractions (0..1)
    keyed by service label, deltas reported in POINTS against the ≤1 pt
    acceptance bar. Quarantined services score 0-vs-clean by definition
    (their windows are all-NA), so the delta *includes* the cost of
    giving up — the bar measures the whole ladder, not just the lucky
    retries."""
    deltas = [(accs_chaos[k] - accs_clean[k]) * 100.0
              for k in accs_clean if k in accs_chaos]
    delta = round(sum(deltas) / len(deltas), 4) if deltas else None
    return {
        "chaos_retries": int(fault_stats.get("fault_retries", 0)),
        "chaos_bisections": int(fault_stats.get("fault_bisections", 0)),
        "chaos_xla_fallbacks": int(
            fault_stats.get("fault_xla_fallbacks", 0)),
        "chaos_host_fallbacks": int(
            fault_stats.get("fault_host_fallbacks", 0)),
        "chaos_quarantined": int(fault_stats.get("fault_quarantined", 0)),
        "chaos_deadletter_bytes": int(deadletter_bytes),
        "chaos_accuracy_delta_pts": delta,
        "chaos_delta_exceeds_1pt": bool(delta is not None
                                        and abs(delta) > 1.0),
    }


def serve_fields(n_tenants: int, clean: dict, storm: dict) -> dict:
    """Serve-leg ledgers -> report fields (unit-tested like
    chaos_fields/bf16_delta_fields, tests/test_bench.py).

    ``clean``/``storm`` summarize one multi-tenant run each: total
    ``spans`` emitted, ``wall_s``, ``healthy_spans`` (spans emitted by
    every tenant EXCEPT tenant 0, the storm target), the dispatch ledger
    (``dispatches``/``shared_solves``/``tenant_batches``), and the
    isolation counters. The isolation metric is the healthy tenants'
    throughput delta between the two runs — the number that says one
    tenant's fault storm did (or did not) tax its neighbors."""
    def rate(spans, wall):
        return round(spans / wall, 1) if wall and wall > 0 else None

    clean_healthy = rate(clean.get("healthy_spans", 0),
                         clean.get("wall_s", 0))
    storm_healthy = rate(storm.get("healthy_spans", 0),
                         storm.get("wall_s", 0))
    iso = (round((storm_healthy - clean_healthy) / clean_healthy * 100.0, 2)
           if clean_healthy and storm_healthy is not None else None)
    return {
        "serve_tenants": int(n_tenants),
        "serve_spans_total": int(clean.get("spans", 0)),
        "serve_spans_per_s": rate(clean.get("spans", 0),
                                  clean.get("wall_s", 0)),
        "serve_fleet_dispatches": int(clean.get("dispatches", 0)),
        "serve_shared_solves": int(clean.get("shared_solves", 0)),
        "serve_tenant_batches": int(clean.get("tenant_batches", 0)),
        "serve_shed_windows": int(clean.get("shed_windows", 0)),
        "serve_per_tenant_spans_per_s_min": clean.get("per_tenant_min"),
        "serve_per_tenant_spans_per_s_max": clean.get("per_tenant_max"),
        "serve_storm_spec": storm.get("spec"),
        "serve_storm_injected": int(storm.get("faults_injected", 0)),
        "serve_quarantined_windows": int(
            storm.get("quarantined_windows", 0)),
        "serve_deadletter_windows": int(
            storm.get("deadletter_windows", 0)),
        "serve_healthy_spans_per_s_clean": clean_healthy,
        "serve_healthy_spans_per_s_storm": storm_healthy,
        "serve_isolation_delta_pct": iso,
        "serve_only_faulty_tenant_accrues": bool(
            storm.get("healthy_quarantined", 1) == 0
            and storm.get("healthy_shed", 1) == 0),
    }


def continuous_fields(n_tenants: int, slo_ms: float, fixed: dict,
                      cont: dict) -> dict:
    """Continuous-batching leg ledgers -> report fields (unit-tested
    like chaos_fields/serve_fields, tests/test_bench.py).

    ``fixed``/``cont`` summarize one multi-tenant run each (fixed
    threshold pump vs the continuous-batching dispatcher) over the SAME
    heavy-tailed feed: total emitted ``spans``, ``wall_s``, the max
    per-tenant seal→emit ``p99_max_ms``, and the dispatcher ledger. The
    headline pair: sustained spans/s must beat the fixed pump AND the
    worst tenant's p99 must sit inside the SLO — throughput bought by
    starving a tenant is a regression, not a win. ``steady_compiles``
    (backend compiles during the measured continuous pass, post-warmup)
    must be zero: adaptive bucket picks ride a bounded pow2 lattice."""
    def rate(spans, wall):
        return round(spans / wall, 1) if wall and wall > 0 else None

    fixed_rate = rate(fixed.get("spans", 0), fixed.get("wall_s", 0))
    cont_rate = rate(cont.get("spans", 0), cont.get("wall_s", 0))
    speedup = (round((cont_rate - fixed_rate) / fixed_rate * 100.0, 2)
               if fixed_rate and cont_rate is not None else None)
    p99 = cont.get("p99_max_ms")
    dispatcher = cont.get("continuous") or {}
    return {
        "continuous_tenants": int(n_tenants),
        "continuous_slo_p99_ms": float(slo_ms),
        "continuous_spans_total": int(cont.get("spans", 0)),
        "continuous_spans_per_s": cont_rate,
        "continuous_spans_per_s_fixed_pump": fixed_rate,
        "continuous_speedup_vs_fixed_pct": speedup,
        "continuous_beats_fixed_pump": bool(
            cont_rate is not None and fixed_rate is not None
            and cont_rate > fixed_rate),
        "continuous_seal_emit_p99_ms_max": p99,
        "continuous_seal_emit_p99_ms_max_fixed": fixed.get("p99_max_ms"),
        "continuous_p99_within_slo": (bool(p99 <= slo_ms)
                                      if p99 is not None else None),
        "continuous_dispatches": int(dispatcher.get("dispatches", 0)),
        "continuous_urgent_dispatches": int(
            dispatcher.get("urgent_dispatches", 0)),
        "continuous_fleet_dispatches": int(cont.get("dispatches", 0)),
        "continuous_fleet_dispatches_fixed": int(fixed.get("dispatches", 0)),
        "continuous_steady_compiles": int(cont.get("steady_compiles", 0)),
        "continuous_zero_steady_compiles": bool(
            cont.get("steady_compiles", 0) == 0),
        "continuous_h2d_bytes_ring": float(cont.get("h2d_bytes_ring", 0.0)),
        "continuous_h2d_bytes_index": float(
            cont.get("h2d_bytes_index", 0.0)),
    }


def overlap_fields(n_tenants: int, inflight: int, slo_ms: float,
                   serial: dict, ring: dict) -> dict:
    """Overlapped-drain leg ledgers -> report fields (unit-tested like
    chaos_fields/serve_fields, tests/test_bench.py).

    ``serial``/``ring`` summarize one continuous-dispatcher run each
    over the SAME heavy-tailed feed: ``TW_SERVE_INFLIGHT=1`` (the
    serial admit→solve→consume baseline — the kill switch) vs the
    in-flight dispatch ring at depth ``inflight``. The headline triple:
    the ring must beat serial on sustained spans/s, its solve/consume
    overlap must be REAL (measured ``overlap_pct`` > 0 — the ring
    engaged, not just configured), and the worst tenant's p99 must stay
    inside the SLO — overlap bought by starving the consume side is a
    regression, not a win. ``steady_compiles`` must stay zero: tickets
    ride the same admission lattice, so depth changes concurrency,
    never shapes."""
    def rate(spans, wall):
        return round(spans / wall, 1) if wall and wall > 0 else None

    serial_rate = rate(serial.get("spans", 0), serial.get("wall_s", 0))
    ring_rate = rate(ring.get("spans", 0), ring.get("wall_s", 0))
    speedup = (round((ring_rate - serial_rate) / serial_rate * 100.0, 2)
               if serial_rate and ring_rate is not None else None)
    p99 = ring.get("p99_max_ms")
    rstat = ring.get("ring") or {}
    overlap = rstat.get("overlap_pct")
    return {
        "overlap_tenants": int(n_tenants),
        "overlap_inflight": int(inflight),
        "overlap_slo_p99_ms": float(slo_ms),
        "overlap_spans_total": int(ring.get("spans", 0)),
        "overlap_spans_per_s": ring_rate,
        "overlap_spans_per_s_serial": serial_rate,
        "overlap_speedup_vs_serial_pct": speedup,
        "overlap_beats_serial": bool(
            ring_rate is not None and serial_rate is not None
            and ring_rate > serial_rate),
        "overlap_pct": overlap,
        "overlap_ring_engaged": bool(
            rstat.get("enabled") and int(rstat.get("completed", 0)) > 0
            and overlap is not None and overlap > 0.0),
        "overlap_tickets_submitted": int(rstat.get("submitted", 0)),
        "overlap_tickets_completed": int(rstat.get("completed", 0)),
        "overlap_tickets_aborted": int(rstat.get("aborted", 0)),
        "overlap_seal_emit_p99_ms_max": p99,
        "overlap_seal_emit_p99_ms_max_serial": serial.get("p99_max_ms"),
        "overlap_p99_within_slo": (bool(p99 <= slo_ms)
                                   if p99 is not None else None),
        "overlap_steady_compiles": int(ring.get("steady_compiles", 0)),
        "overlap_zero_steady_compiles": bool(
            ring.get("steady_compiles", 0) == 0),
    }


def wal_fields(n_tenants: int, passes: dict) -> dict:
    """Durable-WAL leg ledgers -> report fields (unit-tested like
    chaos_fields/serve_fields, tests/test_bench.py).

    ``passes`` maps sync policy -> one measured round each over the
    SAME heavy-tailed feed: ``off`` (``TW_WAL=0`` — the byte-exact
    pre-durability baseline), ``batch`` (group-committed fsync, the
    default), ``always`` (fsync per ack). The headline pair: the
    ``batch`` policy's sustained-throughput overhead vs WAL-off must
    stay <= 10% (durability priced in ack latency, not span rate), and
    steady-state compiles must stay zero on every pass — the WAL is
    bytes-on-disk plumbing, it never touches shapes."""
    def rate(p):
        w = p.get("wall_s") or 0
        return round(p.get("spans", 0) / w, 1) if w > 0 else None

    out = {"wal_tenants": int(n_tenants)}
    for name, p in passes.items():
        out[f"wal_{name}_spans_per_s"] = rate(p)
        out[f"wal_{name}_ack_p50_ms"] = p.get("ack_p50_ms")
        out[f"wal_{name}_ack_p99_ms"] = p.get("ack_p99_ms")
        out[f"wal_{name}_steady_compiles"] = int(
            p.get("steady_compiles", 0))
        if name != "off":
            out[f"wal_{name}_appends"] = int(p.get("wal_appends", 0))
    off_rate = rate(passes.get("off", {}))
    batch_rate = rate(passes.get("batch", {}))
    overhead = (round((off_rate - batch_rate) / off_rate * 100.0, 2)
                if off_rate and batch_rate is not None else None)
    out["wal_batch_overhead_pct"] = overhead
    out["wal_batch_within_overhead"] = (
        bool(overhead <= 10.0) if overhead is not None else None)
    out["wal_zero_steady_compiles"] = bool(all(
        p.get("steady_compiles", 0) == 0 for p in passes.values()))
    return out


def aot_fields(status: dict) -> dict:
    """AOT warmup ledger -> report fields (unit-tested like
    chaos_fields/serve_fields, tests/test_bench.py).

    ``status`` is :func:`traceweaver_tpu.runtime.aot.status` (or the
    ``aot`` block a cold-start child reports): lattice size, progress,
    compile seconds, and the bounded miss ledger — the production
    inputs for tuning ``TW_AOT_HORIZON``."""
    misses = dict(status.get("misses", {}))
    return {
        "aot_mode": status.get("mode"),
        "aot_phase": status.get("phase"),
        "aot_lattice_size": int(status.get("planned", 0)),
        "aot_precompiled": int(status.get("compiled", 0)),
        "aot_compile_s": round(float(status.get("compile_s", 0.0)), 3),
        "aot_misses": misses,
        "aot_miss_count": int(sum(misses.values())),
    }


def coldstart_fields(cold: dict, warm: dict, target_s: float = 5.0) -> dict:
    """Cold-start leg child reports -> report fields (unit-tested like
    chaos_fields/serve_fields, tests/test_bench.py).

    ``cold``/``warm`` each summarize one FRESH subprocess (cold vs warm
    persistent compile cache, identical TW_AOT=eager config) measuring
    process start -> first emitted trace. The headline pair: the
    warm-cache restart must reach its first trace inside ``target_s``
    (the rolling-restart bar, ROADMAP item 2) AND perform zero backend
    compiles during the measured solve — a fast restart that still
    compiles is a horizon gap, visible in the aot_* miss fields."""
    cold_s = cold.get("first_trace_s")
    warm_s = warm.get("first_trace_s")
    speedup = (round(cold_s / warm_s, 2)
               if cold_s and warm_s and warm_s > 0 else None)
    solve_compiles = warm.get("fleet_backend_compiles")
    measured = warm.get("measured_compiles", {})
    out = {
        "cold_start_s": cold_s,
        "warm_start_s": warm_s,
        "coldstart_target_s": float(target_s),
        "coldstart_speedup": speedup,
        "coldstart_warm_under_target": bool(
            warm_s is not None and warm_s < target_s),
        "coldstart_warm_solve_compiles": (
            None if solve_compiles is None else int(solve_compiles)),
        "coldstart_warm_zero_solve_compiles": solve_compiles == 0,
        "coldstart_warm_measured_backend_compiles": int(
            measured.get("backend_compiles", 0)),
        "coldstart_warmup_s_cold": cold.get("warmup_s"),
        "coldstart_warmup_s_warm": warm.get("warmup_s"),
    }
    out.update(aot_fields(warm.get("aot", {})))
    return out


def run_coldstart_child(out_path: str, spawn_ts: float,
                        n_bursts: int) -> None:
    """bench.py --mode coldstart: one fresh process of the cold-start
    leg — enable the persistent cache, run the TW_AOT=eager lattice
    warmup, stream a tiny synthetic corpus to its FIRST emitted trace,
    and report the timeline + compile ledgers. ``spawn_ts`` is the
    parent's clock at Popen, so ``first_trace_s`` includes interpreter
    start and imports — the number a rolling restart actually waits."""
    import jax

    if _knobs.get("TW_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from traceweaver_tpu.runtime.jax_cache import (
        compile_counters,
        counters_delta,
        enable_persistent_compilation_cache,
    )

    cache_dir = enable_persistent_compilation_cache()
    from traceweaver_tpu.runtime import aot

    t0 = time.time()
    aot.startup_warmup(context="bench-coldstart")
    warmup_s = time.time() - t0

    from traceweaver_tpu.stream.service import (
        StreamConfig,
        StreamingReconstructor,
    )
    from traceweaver_tpu.stream.sources import IterableSource

    events, _ = _adapt_burst_events(n_bursts, shift_at=10 ** 9)
    cfg = StreamConfig(window_us=1e6, overlap_us=0.0, ooo_bound_us=1e3,
                       checkpoint_every=10_000, verbose=False)
    svc = StreamingReconstructor(IterableSource(events), cfg)
    before = compile_counters()
    svc.run(max_windows=1)
    t_first = time.time()
    st = aot.status()
    write_json_atomic(out_path, dict(
        first_trace_s=round(t_first - spawn_ts, 3),
        warmup_s=round(warmup_s, 3),
        measured_compiles=counters_delta(before),
        fleet_backend_compiles=int(
            svc.fleet_stats.get("backend_compiles", 0)),
        emitted_windows=int(svc.emitted_windows),
        cache_dir=cache_dir,
        aot=dict(mode=st["mode"], phase=st["phase"],
                 planned=st["planned"], compiled=st["compiled"],
                 compile_s=round(float(st["compile_s"]), 3),
                 misses=st["misses"]),
    ))


def run_coldstart_leg(n_bursts: int) -> dict:
    """bench.py --cold-start N: the serving cold-start leg.

    Two FRESH subprocesses run the identical TW_AOT=eager startup
    (lattice warmup sized to the leg's single-service corpus) and
    measure process start -> first emitted trace: the first against a
    COLD persistent compile cache (every lattice variant compiles),
    the second against the cache the first just wrote (every variant
    deserializes). The warm number is the rolling-restart cost the
    /readyz gate holds traffic for; the acceptance bar is < 5 s on
    this CPU stand-in with ZERO backend compiles during the measured
    solve (TPU targets ride the driver's bench)."""
    workdir = tempfile.mkdtemp(prefix="tw_coldstart_")
    cache_dir = os.path.join(workdir, "jax_cache")
    env = dict(os.environ)
    env.update(TW_JAX_CACHE_DIR=cache_dir, TW_JAX_CACHE="1",
               TW_AOT="eager", TW_AOT_TIER="core",
               TW_AOT_HORIZON="1:1:8:8")

    def child(tag: str) -> dict:
        out = os.path.join(workdir, f"coldstart_{tag}.json")
        spawn_ts = time.time()
        proc = subprocess.Popen(
            [sys.executable, os.path.join(HERE, "bench.py"),
             "--mode", "coldstart", "--out", out,
             "--spawn-ts", repr(spawn_ts), "--cold-start", str(n_bursts)],
            cwd=HERE, env=env, stdout=sys.stderr, stderr=sys.stderr)
        rc = proc.wait(timeout=600)
        if rc != 0 or not os.path.exists(out):
            raise RuntimeError(f"coldstart {tag} child failed rc={rc}")
        with open(out) as f:
            report = json.load(f)
        log("coldstart %s: first trace %.2fs (warmup %.2fs, %d solve "
            "compiles)" % (tag, report["first_trace_s"],
                           report["warmup_s"],
                           report["fleet_backend_compiles"]))
        return report

    cold = child("cold")
    warm = child("warm")
    report = {"bench": "coldstart", "backend": "cpu",
              "n_bursts": int(n_bursts)}
    report.update(coldstart_fields(cold, warm))
    return report


def run_continuous_leg(n_tenants: int) -> dict:
    """bench.py --continuous N: the continuous-batching service leg.

    N tenants post at HEAVY-TAILED rates (tenant i ingests ~24/(i+1)
    traces per chunk — the hot head is ~24× the tail) into one
    TenantService, measured twice after a compile warmup: once under
    the fixed threshold pump (the PR 6 baseline) and once under the
    continuous-batching dispatcher (event-driven admission, SLO-aware,
    adaptive pow2 size classes — serve/continuous.py). Reports
    sustained spans/s, per-tenant seal→emit p99 (max across tenants vs
    TW_SERVE_SLO_P99_MS), and the steady-state compile count (must be
    zero: the admission lattice is bounded).

    SLO sizing: the budget must be configured relative to the
    deployment's warm solve latency (the admission deadline subtracts
    2x the solve EWMA) — on the CPU stand-in, where a warm fleet solve
    runs ~1 s, set TW_SERVE_SLO_P99_MS to ~4x that; the default 2 s is
    sized for device-scale solves."""
    import jax

    if _knobs.get("TW_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    os.environ.setdefault("TW_RETRY_BACKOFF_S", "0")
    from traceweaver_tpu.runtime.jax_cache import (
        compile_counters,
        counters_delta,
        enable_persistent_compilation_cache,
    )
    from traceweaver_tpu.serve import ServeConfig, TenantService

    # persistent compile cache (ROADMAP item 2): admission-lattice
    # coverage accumulates ACROSS leg invocations — a warm-cache rerun
    # deserializes every program and the steady state stays at zero
    # backend compiles from the first pass
    enable_persistent_compilation_cache()

    slo_ms = _knobs.get_float("TW_SERVE_SLO_P99_MS")

    def tenant_rate(i):
        return max(1, 24 // (i + 1))  # heavy-tailed: ~1/i decay

    def run_mode(continuous):
        """One LONG-LIVED service per mode: round 0 is the cold start
        (first-contact windows run the two-pass EM and compile the
        solve shapes — real, but startup, not steady state), warm
        rounds repeat until a round compiles nothing, then the best of
        two measured rounds is the steady-state number (the ingest
        leg's min-of-two convention — one OS scheduling stall in a
        2-3 s round otherwise dominates). A fresh service per pass
        would conflate cold-start compiles with the steady-state
        claim; production serving is a long-lived process."""
        svc = TenantService(ServeConfig(
            fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
            verbose=False, continuous=continuous, slo_p99_ms=slo_ms,
            # batch-fill scales with tenancy: at N=100 a fill target of
            # 8 windows means ~12 dispatches per chunk — admission
            # overhead swamps the win. Same value feeds the fixed
            # pump's threshold, so the comparison stays apples-to-
            # apples at every N.
            pump_windows=max(8, n_tenants // 4)))
        round_no = [0]

        def one_round():
            # 6 paced chunks (fresh trace ids, advancing event time):
            # chunks sit far apart in event time so earlier windows
            # SEAL while later chunks ingest — the continuous
            # dispatcher admits them live and its device work OVERLAPS
            # the ingest wall (the fixed pump solves inline on the
            # ingesting request's thread); the inter-chunk pacing
            # models request gaps, a pause both modes pay but only the
            # dispatcher can use
            r0 = round_no[0]
            round_no[0] += 1
            before = compile_counters()
            spans0 = sum(t["spans_emitted"]
                         for t in svc.stats()["tenants"].values())
            t0 = time.perf_counter()
            for chunk in range(6):
                for i in range(n_tenants):
                    svc.ingest(f"tenant-{i:04d}", {"data": [
                        _serve_trace(k, f"u{i:04d}r{r0}c{chunk}",
                                     base_us=(r0 * 6 + chunk + 1) * 100e6)
                        for k in range(tenant_rate(i))]})
                time.sleep(0.25)
            svc.flush()
            if continuous:
                deadline = time.time() + 120
                while (svc.total_backlog() or svc.in_flight_windows()) \
                        and time.time() < deadline:
                    time.sleep(0.02)
            wall = time.perf_counter() - t0
            st = svc.stats()
            tstats = st["tenants"]
            p99s = [t["seal_emit_p99_ms"] for t in tstats.values()
                    if t["seal_emit_p99_ms"]]
            return dict(
                spans=sum(t["spans_emitted"]
                          for t in tstats.values()) - spans0,
                wall_s=wall,
                p99_max_ms=round(max(p99s), 2) if p99s else None,
                dispatches=st["dispatch"]["fleet_dispatches"],
                continuous=st.get("continuous"),
                steady_compiles=counters_delta(
                    before)["backend_compiles"],
                h2d_bytes_ring=float(
                    st.get("fleet", {}).get("h2d_bytes_ring", 0.0)),
                h2d_bytes_index=float(
                    st.get("fleet", {}).get("h2d_bytes_index", 0.0)),
            )

        one_round()  # cold start: first-contact EM + compiles, untimed
        for _ in range(3):  # warm until a whole round compiles nothing
            if one_round()["steady_compiles"] == 0:
                break
        # grade the SLO over the steady state: cold-start compile
        # stalls sit in the rolling latency window otherwise
        svc.reset_latency_window()
        best = max((one_round() for _ in range(2)),
                   key=lambda r: r["spans"] / max(r["wall_s"], 1e-9))
        svc.drain()
        return best

    log(f"continuous leg: {n_tenants} tenants, fixed-pump service "
        "(cold start + warm rounds, best-of-two measured)")
    fixed = run_mode(False)
    log(f"continuous leg: fixed {fixed['spans']} spans in "
        f"{fixed['wall_s']:.1f}s (p99 {fixed['p99_max_ms']} ms); "
        "continuous service")
    cont = run_mode(True)
    report = continuous_fields(n_tenants, slo_ms, fixed, cont)
    report["mode"] = "continuous"
    log("continuous leg: %s spans/s vs %s fixed (%s%%), p99 %s ms vs "
        "SLO %.0f ms (within=%s), steady compiles %d"
        % (report["continuous_spans_per_s"],
           report["continuous_spans_per_s_fixed_pump"],
           report["continuous_speedup_vs_fixed_pct"],
           report["continuous_seal_emit_p99_ms_max"], slo_ms,
           report["continuous_p99_within_slo"],
           report["continuous_steady_compiles"]))
    if not report["continuous_zero_steady_compiles"]:
        log("continuous leg: WARNING — steady-state continuous loop "
            "recompiled; the admission bucket lattice leaked a shape")
    return report


def run_overlap_leg(n_tenants: int) -> dict:
    """bench.py --serve-overlap N: the overlapped serve drain leg.

    N tenants at the --continuous leg's heavy-tailed rates (tenant i
    ingests ~24/(i+1) traces per chunk) through one continuous-batching
    TenantService, measured twice after a compile warmup: once at
    ``TW_SERVE_INFLIGHT=1`` (serial admit→solve→consume — the kill
    switch and byte-exact baseline) and once at the in-flight dispatch
    ring's depth (default 2: the dispatcher packs batch N+1 while batch
    N executes, consume decoupled behind the FIFO ring —
    serve/tenancy.py). Reports sustained spans/s both ways, the
    MEASURED solve-interval overlap_pct from the ring ledger (must be
    > 0 — configured depth without engagement proves nothing),
    worst-tenant seal→emit p99 vs TW_SERVE_SLO_P99_MS, and the
    steady-state compile count (must be zero: tickets change
    concurrency, never shapes)."""
    import jax

    if _knobs.get("TW_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    os.environ.setdefault("TW_RETRY_BACKOFF_S", "0")
    from traceweaver_tpu.runtime.jax_cache import (
        compile_counters,
        counters_delta,
        enable_persistent_compilation_cache,
    )
    from traceweaver_tpu.serve import ServeConfig, TenantService

    enable_persistent_compilation_cache()
    slo_ms = _knobs.get_float("TW_SERVE_SLO_P99_MS")
    # the ring pass always runs a real ring, even under an env override
    # of the knob to 1 — the leg EXISTS to measure depth>1 vs depth=1
    depth = max(2, _knobs.get_int("TW_SERVE_INFLIGHT"))

    def tenant_rate(i):
        return max(1, 24 // (i + 1))  # heavy-tailed: ~1/i decay

    def run_mode(inflight):
        """Same long-lived-service shape as the --continuous leg: cold
        start untimed, warm until a round compiles nothing, best of two
        measured rounds. Both passes run the continuous dispatcher —
        only the ring depth differs."""
        svc = TenantService(ServeConfig(
            fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
            verbose=False, continuous=True, slo_p99_ms=slo_ms,
            inflight=inflight, pump_windows=max(8, n_tenants // 4)))
        round_no = [0]

        def one_round():
            r0 = round_no[0]
            round_no[0] += 1
            before = compile_counters()
            spans0 = sum(t["spans_emitted"]
                         for t in svc.stats()["tenants"].values())
            t0 = time.perf_counter()
            for chunk in range(6):
                for i in range(n_tenants):
                    svc.ingest(f"tenant-{i:04d}", {"data": [
                        _serve_trace(k, f"u{i:04d}r{r0}c{chunk}",
                                     base_us=(r0 * 6 + chunk + 1) * 100e6)
                        for k in range(tenant_rate(i))]})
                time.sleep(0.25)
            svc.flush()
            deadline = time.time() + 120
            while (svc.total_backlog() or svc.in_flight_windows()) \
                    and time.time() < deadline:
                time.sleep(0.02)
            wall = time.perf_counter() - t0
            st = svc.stats()
            p99s = [t["seal_emit_p99_ms"]
                    for t in st["tenants"].values()
                    if t["seal_emit_p99_ms"]]
            return dict(
                spans=sum(t["spans_emitted"]
                          for t in st["tenants"].values()) - spans0,
                wall_s=wall,
                p99_max_ms=round(max(p99s), 2) if p99s else None,
                ring=st.get("ring"),
                steady_compiles=counters_delta(
                    before)["backend_compiles"],
            )

        one_round()  # cold start: first-contact EM + compiles, untimed
        for _ in range(3):
            if one_round()["steady_compiles"] == 0:
                break
        svc.reset_latency_window()
        best = max((one_round() for _ in range(2)),
                   key=lambda r: r["spans"] / max(r["wall_s"], 1e-9))
        svc.drain()
        return best

    log(f"overlap leg: {n_tenants} tenants, serial dispatcher "
        "(TW_SERVE_INFLIGHT=1; cold start + warm rounds, best-of-two)")
    serial = run_mode(1)
    log(f"overlap leg: serial {serial['spans']} spans in "
        f"{serial['wall_s']:.1f}s (p99 {serial['p99_max_ms']} ms); "
        f"ring dispatcher (depth {depth})")
    ring = run_mode(depth)
    report = overlap_fields(n_tenants, depth, slo_ms, serial, ring)
    report["mode"] = "serve-overlap"
    log("overlap leg: %s spans/s vs %s serial (%s%%), overlap %s%%, "
        "p99 %s ms vs SLO %.0f ms (within=%s), steady compiles %d"
        % (report["overlap_spans_per_s"],
           report["overlap_spans_per_s_serial"],
           report["overlap_speedup_vs_serial_pct"],
           report["overlap_pct"],
           report["overlap_seal_emit_p99_ms_max"], slo_ms,
           report["overlap_p99_within_slo"],
           report["overlap_steady_compiles"]))
    if not report["overlap_ring_engaged"]:
        log("overlap leg: WARNING — ring configured but no measured "
            "solve-interval overlap; the dispatcher never had two "
            "tickets in flight (feed too slow or depth collapsed)")
    return report


def run_wal_leg(n_tenants: int) -> dict:
    """bench.py --wal N: the durable-ingest-WAL leg.

    The --serve-overlap leg's heavy-tailed feed (tenant i ingests
    ~24/(i+1) traces per chunk) through one continuous-batching
    TenantService, measured three times after a compile warmup: with
    ``TW_WAL=0`` (the in-memory baseline ack), with the WAL at
    ``TW_WAL_SYNC=batch`` (group-committed fsync — the default the
    fleet ships with), and at ``TW_WAL_SYNC=always`` (fsync per ack —
    the power-loss bound). Reports sustained spans/s and the measured
    per-POST ack latency (p50/p99 of the ingest call itself — the
    durability tax lands exactly there), gated on the batch policy
    costing <= 10% throughput vs WAL-off with zero steady compiles
    (docs/ROBUSTNESS.md "Durability")."""
    import tempfile

    import jax

    if _knobs.get("TW_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    os.environ.setdefault("TW_RETRY_BACKOFF_S", "0")
    from traceweaver_tpu.runtime.jax_cache import (
        compile_counters,
        counters_delta,
        enable_persistent_compilation_cache,
    )
    from traceweaver_tpu.serve import ServeConfig, TenantService

    enable_persistent_compilation_cache()
    depth = max(2, _knobs.get_int("TW_SERVE_INFLIGHT"))

    def tenant_rate(i):
        return max(1, 24 // (i + 1))  # same heavy tail as --serve-overlap

    def run_policy(policy, state_dir):
        """One fresh service per policy (the WAL opens lazily per
        tenant, reading TW_WAL_SYNC at open): cold start untimed, warm
        until a round compiles nothing, one measured round with a
        per-POST ack-latency ledger."""
        if policy == "off":
            os.environ["TW_WAL"] = "0"
        else:
            os.environ["TW_WAL"] = "1"
            os.environ["TW_WAL_SYNC"] = policy
        svc = TenantService(ServeConfig(
            fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
            verbose=False, continuous=True, inflight=depth,
            state_dir=state_dir, pump_windows=max(8, n_tenants // 4)))
        round_no = [0]
        seqs = [0]

        def post(i, r0, chunk, acks):
            payload = {"data": [
                _serve_trace(k, f"u{i:04d}r{r0}c{chunk}",
                             base_us=(r0 * 6 + chunk + 1) * 100e6)
                for k in range(tenant_rate(i))]}
            tid = f"tenant-{i:04d}"
            t0 = time.perf_counter()
            if policy == "off":
                svc.ingest(tid, payload)
            else:
                seqs[0] += 1
                raw = json.dumps(payload).encode("utf-8")
                svc.wal_ingest(tid, payload, raw=raw,
                               client_seq=seqs[0])
            acks.append(time.perf_counter() - t0)

        def one_round():
            r0 = round_no[0]
            round_no[0] += 1
            before = compile_counters()
            spans0 = sum(t["spans_emitted"]
                         for t in svc.stats()["tenants"].values())
            acks = []
            t0 = time.perf_counter()
            for chunk in range(6):
                for i in range(n_tenants):
                    post(i, r0, chunk, acks)
                time.sleep(0.25)
            svc.flush()
            deadline = time.time() + 120
            while (svc.total_backlog() or svc.in_flight_windows()) \
                    and time.time() < deadline:
                time.sleep(0.02)
            wall = time.perf_counter() - t0
            st = svc.stats()
            acks_ms = sorted(a * 1e3 for a in acks)

            def pct(q):
                return round(acks_ms[min(len(acks_ms) - 1,
                                         int(q * len(acks_ms)))], 3)
            return dict(
                spans=sum(t["spans_emitted"]
                          for t in st["tenants"].values()) - spans0,
                wall_s=wall,
                ack_p50_ms=pct(0.50) if acks_ms else None,
                ack_p99_ms=pct(0.99) if acks_ms else None,
                wal_appends=sum(
                    t["counters"].get("wal_appends", 0)
                    for t in st["tenants"].values()
                    if isinstance(t.get("counters"), dict)),
                steady_compiles=counters_delta(
                    before)["backend_compiles"],
            )

        one_round()  # cold start: first-contact EM + compiles, untimed
        for _ in range(3):
            if one_round()["steady_compiles"] == 0:
                break
        best = one_round()
        svc.drain()
        return best

    wal_env0 = {k: os.environ.get(k) for k in ("TW_WAL", "TW_WAL_SYNC")}
    passes = {}
    try:
        with tempfile.TemporaryDirectory(prefix="tw-bench-wal-") as root:
            for policy in ("off", "batch", "always"):
                log(f"wal leg: {n_tenants} tenants, policy={policy} "
                    "(cold start + warm rounds, then measured)")
                passes[policy] = run_policy(
                    policy, os.path.join(root, policy))
    finally:
        for k, v in wal_env0.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    report = wal_fields(n_tenants, passes)
    report["mode"] = "wal"
    log("wal leg: off %s spans/s, batch %s (overhead %s%%, within=%s), "
        "always %s; ack p99 off/batch/always %s/%s/%s ms; "
        "zero steady compiles=%s"
        % (report["wal_off_spans_per_s"],
           report["wal_batch_spans_per_s"],
           report["wal_batch_overhead_pct"],
           report["wal_batch_within_overhead"],
           report["wal_always_spans_per_s"],
           report["wal_off_ack_p99_ms"],
           report["wal_batch_ack_p99_ms"],
           report["wal_always_ack_p99_ms"],
           report["wal_zero_steady_compiles"]))
    return report


def adapt_fields(shift_at: int, slo: dict, ctrl: dict,
                 adapted: dict) -> dict:
    """Chaos-adapt leg ledgers -> report fields (unit-tested like
    chaos_fields/serve_fields, tests/test_bench.py).

    ``ctrl``/``adapted`` summarize one replay each of the SAME shifted
    corpus (``TW_ADAPT=0`` control vs ``TW_ADAPT=1``): window-accuracy
    means ``pre`` (before the shift) and ``tail`` (the post-adaptation
    tail), the drift/adaptation ledgers, and the final PSI. The
    headline triple: the adapted leg's tail must return to within 1
    accuracy POINT of its own pre-shift ledger, the control leg's tail
    must stay degraded (>= 10 points under pre — proving the
    controller, not noise, recovered it), and the drift gauge must
    re-arm (final PSI back under the alert threshold)."""
    pre = adapted.get("pre")
    tail = adapted.get("tail")
    ctrl_tail = ctrl.get("tail")
    pts = lambda a, b: (round((a - b) * 100.0, 2)  # noqa: E731
                        if a is not None and b is not None else None)
    return {
        "adapt_shift_window": int(shift_at),
        "adapt_windows": int(adapted.get("windows", 0)),
        "adapt_pre_acc": pre,
        "adapt_tail_acc": tail,
        "adapt_tail_acc_control": ctrl_tail,
        "adapt_recovery_gap_pts": pts(pre, tail),
        "adapt_control_degradation_pts": pts(ctrl.get("pre"), ctrl_tail),
        "adapt_recovered_within_1pt": (
            bool(pts(pre, tail) is not None and pts(pre, tail) <= 1.0)),
        "adapt_control_stays_degraded": (
            bool(pts(ctrl.get("pre"), ctrl_tail) is not None
                 and pts(ctrl.get("pre"), ctrl_tail) >= 10.0)),
        "adapt_drift_alerts": int(adapted.get("drift_alerts", 0)),
        "adapt_drift_alerts_control": int(ctrl.get("drift_alerts", 0)),
        "adapt_refits": int(adapted.get("refits", 0)),
        "adapt_refits_control": int(ctrl.get("refits", 0)),
        "adapt_fallbacks": int(adapted.get("fallbacks", 0)),
        "adapt_final_psi": adapted.get("final_psi"),
        "adapt_psi_threshold": float(slo.get("psi_threshold", 0.25)),
        "adapt_gauge_rearmed": (
            bool(adapted.get("final_psi") is not None
                 and adapted["final_psi"]
                 <= slo.get("psi_threshold", 0.25))),
        "adapt_steady_compiles": int(adapted.get("steady_compiles", 0)),
        "adapt_actions": adapted.get("actions"),
    }


def _adapt_burst_events(n_bursts: int, shift_at: int, n_req: int = 8,
                        gap_us: float = 800.0, pre_delay: float = 150.0,
                        post_delay: float = 950.0, seed: int = 7):
    """The chaos-adapt corpus: bursty frontend->search traffic whose
    call latency SWAPS distributions mid-stream (the injected workload
    shift). Geometry chosen so the shift poisons the warm-start
    feedback loop: post-shift delay ≈ one inter-arrival gap + the old
    delay, so under the STALE priors every call matches its
    neighbor's request perfectly (slot aliasing), the per-burst
    cache-hit request donates the skip that makes the wrong matching
    total, and the aliased assignment's delay samples re-teach the
    stale prior — a self-consistent wrong equilibrium that never
    heals on its own (the control leg proves it). A cold
    order-statistics refit sees the true shifted delay and breaks the
    loop (adapt/refit.py)."""
    import numpy as np

    from traceweaver_tpu.spans import Span
    from traceweaver_tpu.stream.sources import SpanEvent

    rng = np.random.default_rng(seed)
    procs = {"p1": "frontend", "p2": "search"}
    events = []
    for b in range(n_bursts):
        base = b * 1e6 + 1000.0
        delay = pre_delay if b < shift_at else post_delay
        for i in range(n_req):
            t = base + i * gap_us
            tid = f"b{b:03d}r{i:02d}"
            d = delay + float(rng.integers(-20, 21))
            spans = [Span(tid, "root", t, 2600.0, "req", [], "p1",
                          "server")]
            if i < n_req - 1:  # the burst's last request is a cache hit
                spans += [
                    Span(tid, "c", t + d, 150.0, "call",
                         [(tid, "root")], "p1", "client"),
                    Span(tid, "s", t + d + 10, 100.0, "search",
                         [(tid, "c")], "p2", "server"),
                ]
            for sp in spans:
                events.append(SpanEvent(
                    span=sp, event_us=float(sp.start_mus),
                    arrival_us=float(sp.start_mus), trace_id=tid,
                    processes=procs))
    events.sort(key=lambda e: (e.arrival_us, e.trace_id, e.span.sid))
    return events, n_req


def run_adapt_leg(n_bursts: int) -> dict:
    """bench.py --chaos-adapt N: the drift→adapt recovery leg.

    Replays the shifted corpus twice through the single-tenant stream
    service — once with ``TW_ADAPT=0`` (control) and once with
    ``TW_ADAPT=1`` — and grades every emitted window's frontend→search
    assignment against the generator's ground truth (a call belongs to
    its own trace's request; the cache-hit request takes the skip).
    Asserts the full recovery story from the ledgers: the PSI drift
    alert fires, an out-of-band refit lands, the adapted tail returns
    to within 1 point of the pre-shift accuracy, the drift gauge
    re-arms — and the control replay of the IDENTICAL corpus stays
    degraded, so the recovery is the controller's doing, not noise."""
    import jax

    if _knobs.get("TW_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    os.environ.setdefault("TW_RETRY_BACKOFF_S", "0")
    # the leg's drift window: small enough that the reference freezes
    # and the rolling window matures inside the replay (the default 256
    # is sized for production streams)
    os.environ.setdefault("TW_CONF_DRIFT_WINDOW", "64")
    shift_at = max(4, n_bursts // 2)
    tail_n = max(6, n_bursts // 6)

    def one_run(adapt_on: bool) -> dict:
        import numpy as np

        from traceweaver_tpu.runtime.jax_cache import (
            compile_counters,
            counters_delta,
        )
        from traceweaver_tpu.stream.service import (
            StreamConfig,
            StreamingReconstructor,
            TraceSink,
        )
        from traceweaver_tpu.stream.sources import IterableSource

        os.environ["TW_ADAPT"] = "1" if adapt_on else "0"
        events, n_req = _adapt_burst_events(n_bursts, shift_at)
        sink_path = os.path.join(
            tempfile.mkdtemp(prefix="tw_adapt_"), "out.jsonl")
        cfg = StreamConfig(window_us=1e6, overlap_us=0.0,
                           ooo_bound_us=1e3, checkpoint_every=10_000,
                           verbose=False)
        svc = StreamingReconstructor(IterableSource(events), cfg,
                                     sink=TraceSink(sink_path))
        compiles0 = compile_counters()
        summary = svc.run()
        compiles = counters_delta(compiles0)["backend_compiles"]

        skip_sid = "r%02d" % (n_req - 1)
        accs = {}
        with open(sink_path) as f:
            for line in f:
                rec = json.loads(line)
                rows = rec.get("services", {}).get(
                    "frontend", {}).get("search", [])
                if not rows:
                    continue
                ok = 0
                for in_id, out_id in rows:
                    is_real = (isinstance(out_id, list)
                               and str(out_id[0]).startswith("b"))
                    if in_id[0].endswith(skip_sid):
                        ok += not is_real       # truth: skip (cache hit)
                    else:
                        ok += is_real and out_id[0] == in_id[0]
                accs[rec["window"]] = ok / len(rows)

        pre = [accs[k] for k in sorted(accs) if k < shift_at]
        tail = [accs[k] for k in sorted(accs)[-tail_n:]]
        return dict(
            windows=len(accs),
            pre=round(float(np.mean(pre)), 4) if pre else None,
            tail=round(float(np.mean(tail)), 4) if tail else None,
            drift_alerts=summary["confidence"]["drift_alerts"],
            refits=summary["adapt"].get("refits_done", 0),
            fallbacks=summary["adapt"].get("fallbacks", 0),
            actions={k: summary["adapt"][k]
                     for k in ("refits_scheduled", "refits_done",
                               "refits_failed", "fallbacks", "restores",
                               "recoveries")}
            if summary["adapt"].get("enabled") else None,
            final_psi=(round(svc.drift.last_psi("frontend"), 4)
                       if svc.drift and svc.drift.last_psi("frontend")
                       is not None else None),
            # compiles AFTER the adaptation landed must be zero: the
            # refit is the hot path's own warm program (measured over
            # the whole run minus the cold-start classes is noisy on a
            # short replay, so report the raw count for the record)
            steady_compiles=compiles,
        )

    log(f"chaos-adapt leg: {n_bursts} windows, shift at {shift_at}; "
        "control replay (TW_ADAPT=0)")
    # twlint: disable=TW001 — raw env save/restore around the leg's two
    # replays (each replay SETS TW_ADAPT), not a knob read
    prev = os.environ.get("TW_ADAPT")
    try:
        ctrl = one_run(False)
        log("chaos-adapt leg: control pre=%s tail=%s alerts=%d; "
            "adapted replay (TW_ADAPT=1)"
            % (ctrl["pre"], ctrl["tail"], ctrl["drift_alerts"]))
        adapted = one_run(True)
    finally:
        if prev is None:
            os.environ.pop("TW_ADAPT", None)
        else:
            os.environ["TW_ADAPT"] = prev
    report = adapt_fields(
        shift_at,
        dict(psi_threshold=_knobs.get_float("TW_CONF_DRIFT_PSI")),
        ctrl, adapted)
    report["mode"] = "chaos_adapt"
    log("chaos-adapt leg: adapted pre=%s tail=%s (gap %s pts, "
        "within-1pt=%s) vs control tail=%s (degraded=%s); alerts=%d "
        "refits=%d gauge_rearmed=%s"
        % (adapted["pre"], adapted["tail"],
           report["adapt_recovery_gap_pts"],
           report["adapt_recovered_within_1pt"], ctrl["tail"],
           report["adapt_control_stays_degraded"],
           report["adapt_drift_alerts"], report["adapt_refits"],
           report["adapt_gauge_rearmed"]))
    if not (report["adapt_recovered_within_1pt"]
            and report["adapt_control_stays_degraded"]):
        log("chaos-adapt leg: WARNING — recovery story incomplete "
            "(see adapt_* fields)")
    return report


def capture_fields(clean: dict, skewed: dict, lossy: dict,
                   injected_skew_us: float) -> dict:
    """Capture-leg ledgers -> report fields (unit-tested like
    chaos_fields/serve_fields, tests/test_bench.py).

    ``clean``/``skewed``/``lossy`` summarize one replay each of the SAME
    recorded capture workload through the collector ingress + windowed
    solve: clean (churn only — the workload carries an fd reuse), under
    an injected per-source clock skew (``skew`` fault site), and under
    injected chunk loss (``capture`` fault site). The headline verdicts:
    skew must be *corrected* (accuracy within 1 pt of clean, the fitted
    offset within 20% of the injection), churn must be *tolerated*
    (re-keys counted, clean accuracy intact), and loss must *degrade
    gracefully* — loss counted, confidence discounted below clean, no
    crash, never silent."""
    def pts(a, b):
        return (round(a - b, 2)
                if a is not None and b is not None else None)

    detected = skewed.get("skew_detected_us")
    skew_ok = (clean.get("acc") is not None
               and skewed.get("acc") is not None
               and abs(clean["acc"] - skewed["acc"]) <= 1.0
               and detected is not None and injected_skew_us > 0
               and abs(abs(detected) - injected_skew_us)
               <= 0.2 * injected_skew_us)
    loss_counted = sum(lossy.get("loss", {}).values()) > 0
    conf_discounted = (
        lossy.get("conf_discount") is not None
        and lossy["conf_discount"] < 1.0
        and lossy.get("conf_mean") is not None
        and clean.get("conf_mean") is not None
        and lossy["conf_mean"] < clean["conf_mean"])
    no_crash = all(leg.get("completed") for leg in (clean, skewed, lossy))
    return {
        "capture_spans_clean": int(clean.get("spans", 0)),
        "capture_acc_clean": clean.get("acc"),
        "capture_acc_skew": skewed.get("acc"),
        "capture_acc_lossy": lossy.get("acc"),
        "capture_skew_injected_us": float(injected_skew_us),
        "capture_skew_detected_us": detected,
        "capture_skew_acc_delta_pts": pts(clean.get("acc"),
                                          skewed.get("acc")),
        "capture_skew_corrected_ok": bool(skew_ok),
        "capture_rekeyed_streams": int(clean.get("rekeyed", 0)),
        "capture_churn_tolerated": bool(clean.get("rekeyed", 0) > 0
                                        and clean.get("acc") is not None),
        "capture_loss_counters": dict(lossy.get("loss", {})),
        "capture_loss_rate": lossy.get("loss_rate"),
        "capture_loss_counted": bool(loss_counted),
        "capture_conf_mean_clean": clean.get("conf_mean"),
        "capture_conf_mean_lossy": lossy.get("conf_mean"),
        "capture_conf_discount": lossy.get("conf_discount"),
        "capture_conf_discounted": bool(conf_discounted),
        "capture_no_crash": bool(no_crash),
        "capture_graceful": bool(no_crash and loss_counted
                                 and conf_discounted),
    }


def _capture_workload(n_traces: int, churn_at: Optional[int] = None):
    """The capture-leg corpus: per-source ``strace -f -ttt`` logs of an
    uninstrumented frontend→search workload — the frontend's capture
    sees the client requests (fd 7) and its downstream calls (fd 9);
    the search host's capture (its own clock) sees the server side
    (fd 5). Tracing headers carry the ground-truth join (grading only —
    the solver reconstructs from timing). ``churn_at`` reconnects the
    frontend's inbound connection mid-capture WITHOUT a close syscall:
    the ingress must re-key on the fresh preface or the two connections'
    bytes concatenate into garbage."""
    from traceweaver_tpu.collector.hpack import Encoder
    from traceweaver_tpu.collector.http2 import (
        FLAG_END_HEADERS,
        FLAG_END_STREAM,
        HEADERS,
        PREFACE,
        SETTINGS,
    )

    def frame(ftype, flags, stream_id, payload):
        return (len(payload).to_bytes(3, "big") + bytes([ftype, flags])
                + stream_id.to_bytes(4, "big") + payload)

    def req(enc, stream_id, path, authority, key):
        block = enc.encode([
            (":method", "POST"), (":scheme", "http"), (":path", path),
            (":authority", authority),
            ("uber-trace-id", f"{key}:1:0:1"),
        ])
        return frame(HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM,
                     stream_id, block)

    def resp(enc, stream_id):
        return frame(HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM,
                     stream_id, enc.encode([(":status", "200")]))

    def esc(data):
        out = []
        for i, b in enumerate(data):
            if b == 0x22:
                out.append('\\"')
            elif b == 0x5C:
                out.append("\\\\")
            elif 0x20 <= b < 0x7F:
                out.append(chr(b))
            else:
                nxt = data[i + 1] if i + 1 < len(data) else None
                out.append(("\\%03o" if nxt is not None
                            and 0x30 <= nxt <= 0x37 else "\\%o") % b)
        return "".join(out)

    def line(pid, ts, op, fd, data):
        return (f'{pid} {ts:.6f} {op}({fd}, "{esc(data)}", {len(data)}) '
                f'= {len(data)}')

    if churn_at is None:
        churn_at = max(2, n_traces // 2)
    fe, se = [], []
    enc = {k: Encoder() for k in ("c_in", "fe_out", "fe_resp",
                                  "dn_resp", "se_in", "se_resp")}
    base = 1_722_000_000.0
    fe.append(line(10, base, "read", 7, PREFACE + frame(SETTINGS, 0, 0,
                                                        b"")))
    fe.append(line(10, base, "write", 9, PREFACE + frame(SETTINGS, 0, 0,
                                                         b"")))
    se.append(line(20, base, "read", 5, PREFACE + frame(SETTINGS, 0, 0,
                                                        b"")))
    gen = 0
    sid_in = 0
    for i in range(n_traces):
        if i == churn_at:
            # reconnect without close: fresh preface + fresh HPACK
            # contexts on fd 7, mid-capture
            gen, sid_in = 1, 0
            enc["c_in"], enc["fe_resp"] = Encoder(), Encoder()
            fe.append(line(10, base + 0.5 + i * 0.01, "read", 7,
                           PREFACE + frame(SETTINGS, 0, 0, b"")))
        key = f"t{i:04d}"
        sid_in += 2
        sid_dn = 2 * i + 1
        # jittered service delay so the solver sees a real distribution
        d = 0.002 + (i % 5) * 0.0004
        t0 = base + 0.5 + i * 0.01
        t1 = t0 + 0.001
        t2 = t1 + 0.0002
        t3 = t2 + d
        t4 = t3 + 0.0003
        t5 = t4 + 0.0005
        fe.append(line(10, t0, "read", 7,
                       req(enc["c_in"], sid_in - 1, "/hotels", "frontend",
                           key)))
        fe.append(line(10, t1, "write", 9,
                       req(enc["fe_out"], sid_dn, "/search", "search",
                           key)))
        se.append(line(20, t2, "read", 5,
                       req(enc["se_in"], sid_dn, "/search", "search",
                           key)))
        se.append(line(20, t3, "write", 5, resp(enc["se_resp"], sid_dn)))
        fe.append(line(10, t4, "read", 9, resp(enc["dn_resp"], sid_dn)))
        fe.append(line(10, t5, "write", 7, resp(enc["fe_resp"],
                                                sid_in - 1)))
    return {"frontend": "\n".join(fe), "search": "\n".join(se)}


def run_capture_leg(n_traces: int) -> dict:
    """bench.py --capture N: the capture-to-trace chaos leg.

    Replays the recorded uninstrumented workload through the collector
    ingress (CollectorSource -> skew correction -> windowed solve ->
    emitted traces) three times — clean (with mid-capture connection
    churn), under an injected per-source clock skew, and under injected
    capture loss — and gates on the hardening story: skew corrected
    (accuracy holds, offset detected), churn tolerated (re-keys
    counted), loss degrading gracefully (counted, confidence
    discounted, zero crashes, no silent wrong traces)."""
    import jax

    if _knobs.get("TW_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    os.environ.setdefault("TW_RETRY_BACKOFF_S", "0")
    from traceweaver_tpu.runtime import faults as faults_mod

    injected_us = _knobs.get_float("TW_SKEW_CHAOS_US")

    def one_run(name: str, spec: Optional[str]) -> dict:
        from traceweaver_tpu.collector.source import CollectorSource
        from traceweaver_tpu.stream.service import (
            StreamConfig,
            StreamingReconstructor,
            TraceSink,
        )

        logs = _capture_workload(n_traces)
        faults_mod.reset()
        try:
            if spec:
                with faults_mod.override(spec, seed=1):
                    src = CollectorSource(logs)
            else:
                src = CollectorSource(logs)
            sink_path = os.path.join(
                tempfile.mkdtemp(prefix="tw_capture_"), "out.jsonl")
            cfg = StreamConfig(window_us=0.2e6, overlap_us=0.05e6,
                               ooo_bound_us=0.02e6,
                               checkpoint_every=10_000, verbose=False)
            svc = StreamingReconstructor(src, cfg,
                                         sink=TraceSink(sink_path))
            summary = svc.run()
        except Exception as e:  # noqa: BLE001 — the no-crash gate
            log(f"capture leg {name}: CRASHED {type(e).__name__}: {e}")
            return dict(completed=False, error=f"{type(e).__name__}: {e}")
        quality = summary.get("capture", {})
        confs, discount = [], None
        with open(sink_path) as f:
            for raw in f:
                rec = json.loads(raw)
                tw = rec.get("tw.confidence") or {}
                for tconf in (tw.get("traces") or {}).values():
                    if tconf is not None:
                        confs.append(tconf["conf"])
                cap = tw.get("capture")
                if cap is not None:
                    discount = cap["discount"]
        skews = [v for v in quality.get("skew_us", {}).values() if v]
        acc = summary.get("accuracy", {}).get("e2e")
        return dict(
            completed=True,
            spans=int(summary["stats"].get("spans_emitted", 0)),
            acc=round(acc, 2) if acc is not None else None,
            loss=quality.get("loss", {}),
            loss_rate=quality.get("loss_rate"),
            rekeyed=quality.get("rekeyed_streams", 0),
            skew_detected_us=(max(skews, key=abs) if skews else None),
            conf_mean=(round(sum(confs) / len(confs), 4)
                       if confs else None),
            conf_discount=discount,
        )

    log(f"capture leg: {n_traces} traces; clean replay (churn only)")
    clean = one_run("clean", None)
    log("capture leg: clean acc=%s rekeyed=%s; skew replay "
        "(skew:1.0:max=1, %.0fus)"
        % (clean.get("acc"), clean.get("rekeyed"), injected_us))
    skewed = one_run("skew", "skew:1.0:max=1")
    log("capture leg: skew acc=%s detected=%s; lossy replay "
        "(capture:0.04)" % (skewed.get("acc"),
                            skewed.get("skew_detected_us")))
    lossy = one_run("lossy", "capture:0.04")
    faults_mod.reset()
    report = capture_fields(clean, skewed, lossy, injected_us)
    report["mode"] = "capture"
    log("capture leg: clean=%s skew=%s (corrected=%s) lossy=%s "
        "loss=%s discount=%s graceful=%s"
        % (report["capture_acc_clean"], report["capture_acc_skew"],
           report["capture_skew_corrected_ok"],
           report["capture_acc_lossy"],
           sum(report["capture_loss_counters"].values()),
           report["capture_conf_discount"], report["capture_graceful"]))
    if not report["capture_graceful"] or not report[
            "capture_skew_corrected_ok"]:
        log("capture leg: WARNING — hardening story incomplete "
            "(see capture_* fields)")
    return report


def confidence_fields(conf_maps) -> dict:
    """Per-span confidence ledger -> report fields (unit-tested like
    chaos_fields/serve_fields, tests/test_bench.py).

    ``conf_maps`` is a solve's per-item confidences list
    (``solve_fleet(confidences=...)`` — obs/quality.py records). The
    fields summarize the distribution the quality telemetry would emit:
    population, mean/min, the low-confidence share (TW_CONF_LOW), and
    the OT-override share."""
    vals, overridden = [], 0
    for m in conf_maps or ():
        for rec in (m or {}).values():
            vals.append(float(rec["conf"]))
            overridden += bool(rec.get("not_best"))
    if not vals:
        return {"conf_spans": 0, "conf_mean": None, "conf_min": None,
                "conf_low_frac": None, "conf_overridden_frac": None}
    low = _knobs.get_float("TW_CONF_LOW")
    return {
        "conf_spans": len(vals),
        "conf_mean": round(sum(vals) / len(vals), 4),
        "conf_min": round(min(vals), 4),
        "conf_low_frac": round(
            sum(v <= low for v in vals) / len(vals), 4),
        "conf_overridden_frac": round(overridden / len(vals), 4),
    }


def scorecard_fields(card: dict) -> dict:
    """Scorecard artifact -> report fields (unit-tested like
    chaos_fields/serve_fields, tests/test_bench.py).

    ``card`` is :func:`traceweaver_tpu.metrics.scorecard.run_scorecard`'s
    artifact. The headline fields are the per-regime accuracy matrix,
    the TPU-vs-best-baseline delta per regime, and the calibration
    verdict: ``scorecard_calibration_monotone_ok`` (warn-flagged — the
    decile table must show higher-confidence >= lower-confidence
    accuracy within tolerance) plus the cruder-but-unambiguous
    ``scorecard_top_vs_bottom_ok`` (top decile >= bottom decile)."""
    per_regime = card.get("per_regime", {})
    vs_best = {}
    for regime, accs in per_regime.items():
        base = [v for m, v in accs.items() if m != "weaver_tpu"]
        if base and "weaver_tpu" in accs:
            vs_best[regime] = round(accs["weaver_tpu"] - max(base), 4)
    cal = card.get("calibration", [])
    top_vs_bottom = (cal[-1]["accuracy"] >= cal[0]["accuracy"]
                     if len(cal) >= 2 else None)
    return {
        "scorecard_regimes": per_regime,
        "scorecard_tpu_minus_best_baseline": vs_best,
        "scorecard_exact_subset_spans": card.get(
            "weaver_exact_subset_spans"),
        "scorecard_calibration": cal,
        "scorecard_calibration_monotone_ok": bool(
            card.get("calibration_monotone_ok")),
        "scorecard_calibration_violations": card.get(
            "calibration_violations", []),
        "scorecard_top_vs_bottom_ok": top_vs_bottom,
    }


def run_scorecard_leg(n_traces: int) -> dict:
    """bench.py --scorecard N: the per-regime baseline scorecard leg.

    Runs all five in-repo baselines + the TPU solver over the synthetic
    labeled three-regime corpus (traceweaver_tpu/metrics/scorecard.py —
    no datasets required) and reports per-regime accuracy plus the
    confidence-decile calibration check. WARNS (never fails) when the
    calibration table is not monotone-ish: confidence that does not
    predict correctness is the regression this leg exists to catch."""
    import jax

    if _knobs.get("TW_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from traceweaver_tpu.metrics.scorecard import run_scorecard

    t0 = time.perf_counter()
    card = run_scorecard(n_traces=n_traces)
    report = dict(mode="scorecard",
                  scorecard_traces_per_service=n_traces,
                  scorecard_wall_s=round(time.perf_counter() - t0, 2),
                  **scorecard_fields(card))
    if not report["scorecard_calibration_monotone_ok"]:
        log("scorecard leg: WARNING — confidence-decile calibration is "
            "not monotone-ish: %s"
            % "; ".join(report["scorecard_calibration_violations"]))
    log("scorecard leg: per-regime %s; calibration monotone_ok=%s "
        "top_vs_bottom_ok=%s"
        % (report["scorecard_regimes"],
           report["scorecard_calibration_monotone_ok"],
           report["scorecard_top_vs_bottom_ok"]))
    return report


def campaign_fields(artifact: dict) -> dict:
    """Campaign-leg report builder: flatten one CAMPAIGN_* artifact's
    headline numbers into bench fields (per-rung sustained spans/s,
    the steady-state zero-compile gate, accuracy floor, and the
    aot-miss escape count) — the standing instrument later perf PRs
    report against (docs/CAMPAIGN.md)."""
    rungs = artifact.get("rungs", [])
    spans_per_s = {r["rung"]: r["steady"]["spans_per_s"] for r in rungs}
    accs = [r["accuracy"]["e2e_pct"] for r in rungs]
    return {
        "campaign_name": artifact.get("name"),
        "campaign_rungs": len(rungs),
        "campaign_devices": artifact.get("plan", {}).get("devices"),
        "campaign_slices": artifact.get("plan", {}).get("slices"),
        "campaign_spans_total": sum(r["manifest"]["spans"] for r in rungs),
        "campaign_spans_per_s": spans_per_s,
        "campaign_accuracy_e2e_min": min(accs) if accs else None,
        "campaign_steady_compiles": sum(
            r["steady"]["backend_compiles"] for r in rungs),
        "campaign_aot_misses": sum(
            len(r["steady"]["aot_misses"]) for r in rungs),
        "campaign_quarantined": sum(
            r["steady"]["quarantined"] for r in rungs),
        "campaign_multislice_agree": all(
            r["multislice"]["agree"] for r in rungs
            if r.get("multislice")),
    }


def run_campaign_leg(traces_per_graph: int) -> dict:
    """bench.py --campaign N: the 2-rung synthetic mini campaign
    through the REAL harness (traceweaver_tpu/campaign) — mesh-sharded
    fleet drive, warmup-to-zero-compiles, timed rounds, multislice
    allreduce — plus a self-compare through the regression gate (a
    broken gate would wave every future regression through). N sizes
    the rungs (traces per call graph). Full-scale campaigns run via
    `cli campaign run` (docs/CAMPAIGN.md)."""
    import jax

    if _knobs.get("TW_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import tempfile

    from traceweaver_tpu.campaign import (
        compare_artifacts,
        mini_plan,
        run_campaign,
    )

    n_dev = min(2, jax.device_count())
    plan = mini_plan(devices=n_dev if n_dev >= 2 else 0,
                     traces_per_graph=traces_per_graph)
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="tw-bench-campaign-") as tmp:
        artifact = run_campaign(plan, cache_root=os.path.join(tmp, "cache"),
                                print_fn=log)
    self_cmp = compare_artifacts(artifact, artifact)
    report = dict(mode="campaign",
                  campaign_wall_s=round(time.perf_counter() - t0, 2),
                  campaign_compare_self_ok=bool(self_cmp["ok"]),
                  **campaign_fields(artifact))
    log("campaign leg: %s spans/s per rung; steady compiles %d, "
        "aot misses %d, self-compare ok=%s"
        % (report["campaign_spans_per_s"],
           report["campaign_steady_compiles"],
           report["campaign_aot_misses"],
           report["campaign_compare_self_ok"]))
    return report


def run_fleet_wire_leg(seconds: float) -> dict:
    """bench.py --fleet-wire S: the replica-fleet wire campaign —
    closed-loop heavy-tailed generators POST Jaeger-JSON through the
    consistent-hash router to 1 then 2 in-process replicas (real HTTP
    servers on real sockets), with a live hot-tenant migration in the
    2-replica chaos phase; reports per-rung accepted spans/s, the
    zero-loss conservation proof, and a self-compare through the
    regression gate. Subprocess-replica fleets (rolling restarts
    included) run via `cli fleet campaign --mode subprocess`
    (docs/CAMPAIGN.md "Wire-level fleet campaign")."""
    import jax

    if _knobs.get("TW_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import tempfile

    from traceweaver_tpu.campaign import compare_artifacts
    from traceweaver_tpu.fleet_serve.campaign import run_fleet_campaign

    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="tw-bench-fleet-") as tmp:
        artifact = run_fleet_campaign(
            state_root=tmp, replica_counts=(1, 2), seconds=seconds,
            mode="inproc", verbose=True)
    self_cmp = compare_artifacts(artifact, artifact)
    rungs = artifact["rungs"]
    report = dict(
        mode="fleet-wire",
        fleet_wall_s=round(time.perf_counter() - t0, 2),
        fleet_compare_self_ok=bool(self_cmp["ok"]),
        fleet_migrations=sum(r["fleet"]["migrations"] for r in rungs),
        fleet_generator_429s=sum(
            r["fleet"]["generator_429s"] for r in rungs),
        fleet_zero_loss=all(r["fleet"]["zero_loss"] for r in rungs),
        **campaign_fields(artifact))
    log("fleet-wire leg: %s spans/s per rung; migrations %d, "
        "zero-loss %s, self-compare ok=%s"
        % (report["campaign_spans_per_s"], report["fleet_migrations"],
           report["fleet_zero_loss"], report["fleet_compare_self_ok"]))
    return report


def telemetry_fields(stage_stats: dict, snap_before: dict,
                     snap_after: dict) -> dict:
    """Obs-registry agreement proof -> report fields (unit-tested like
    chaos_fields/ingest_fields, tests/test_bench.py).

    ``snap_before``/``snap_after`` are registry ``snapshot()`` dicts
    taken around the timed solve; the fleet ledger mirror's counter
    deltas (``tw_fleet_ledger_total{key=...}``) must equal the solve's
    legacy ``stage_stats`` dict field-for-field. Keys mirrored as
    GAUGES (``record_max`` high-water marks like ``pipeline_depth``) are
    process-wide maxima, not per-solve deltas, so they are excluded
    from the counter comparison — the gauge set is read from the
    snapshot itself, never hardcoded."""
    import re as _re

    ledger_re = _re.compile(r'^tw_fleet_ledger_total\{key="([^"]+)"\}$')
    gauge_re = _re.compile(r'^tw_fleet_gauge\{key="([^"]+)"\}$')
    deltas = {}
    gauge_keys = set()
    for name, val in snap_after.items():
        m = ledger_re.match(name)
        if m:
            d = val - snap_before.get(name, 0.0)
            if d:
                deltas[m.group(1)] = d
            continue
        g = gauge_re.match(name)
        if g:
            gauge_keys.add(g.group(1))
    legacy = {k: float(v) for k, v in stage_stats.items()
              if isinstance(v, (int, float)) and k not in gauge_keys}
    mismatches = sorted(
        k for k in set(legacy) | set(deltas)
        if abs(deltas.get(k, 0.0) - legacy.get(k, 0.0))
        > 1e-6 * max(1.0, abs(legacy.get(k, 0.0))))
    return {
        "telemetry_snapshot": {k: round(v, 6)
                               for k, v in sorted(deltas.items())},
        "telemetry_matches_legacy": not mismatches,
        "telemetry_mismatch_keys": mismatches,
    }


def ingest_fields(n_spans: int, n_windows: int, col_s: float,
                  obj_s: float) -> dict:
    """Ingest-only leg ledger -> report fields (unit-tested like
    chaos_fields/serve_fields, tests/test_bench.py).

    ``col_s``/``obj_s`` are the wall seconds of one full host pack pass
    (partition sort -> perfect-cut windows -> candidate ranges -> skip
    caps -> packed window tensors, ZERO device involvement) under
    ``TW_COLUMNAR=1`` / ``=0`` on identical spans. The headline
    ``pack_spans_per_s`` is the columnar number; the object-path rate
    and the ratio make ROADMAP item 2's ≥10× claim measured, not
    asserted."""
    def rate(s):
        return round(n_spans / s, 1) if s and s > 0 else None

    return {
        "ingest_spans": int(n_spans),
        "ingest_windows": int(n_windows),
        "pack_spans_per_s": rate(col_s),
        "pack_s_per_window": (round(col_s / n_windows, 6)
                              if n_windows else None),
        "pack_spans_per_s_object": rate(obj_s),
        "pack_columnar_speedup": (round(obj_s / col_s, 2)
                                  if col_s and col_s > 0 and obj_s else None),
    }


def run_ingest_leg(n_spans: int) -> dict:
    """bench.py --ingest-only N: host pack throughput, no device at all.

    Synthesizes a ~N-span single-service corpus (bursty arrivals ->
    perfect-cut windows of realistic width) and its ingest-time columnar
    store (the ``TraceStore.build_columns`` handoff: SpanArray columns
    with an endpoint id column, built once at parse — untimed here, as
    in production), then times the parsed-store -> packed-blocks host
    pass under BOTH ``TW_COLUMNAR`` settings on identical spans:

    - **columnar**: per-endpoint partition = boolean-mask gather on the
      endpoint column + one lexsort, then perfect-cut windows, candidate
      ranges, water-filled skip caps and the dense window-tensor fill —
      all array work, zero span-object touches;
    - **object** (``TW_COLUMNAR=0``): the pre-columnar per-span walk
      (partition sort by key tuple, per-window list comprehensions).

    No JAX backend is initialized and nothing is dispatched — this is
    the host half of the solve in isolation, the quantity the columnar
    refactor exists to move (ROADMAP item 2, "measured, not asserted").
    The two paths' packed tensors are byte-compared
    (``pack_parity_ok``) so the throughput ratio can never come from
    diverging work.
    """
    import numpy as np

    from traceweaver_tpu.algorithms import weaver_tpu as wt
    from traceweaver_tpu.algorithms.skips import water_fill_skip_caps
    from traceweaver_tpu.ingest.partition import partition_spans_by_endpoint
    from traceweaver_tpu.spans import Span, SpanArray

    E = 4
    n_traces = max(8, n_spans // (1 + E))
    rng = np.random.default_rng(7)
    in_spans, out_flat = [], []
    t = 0.0
    for i in range(n_traces):
        # bursts of 8 overlapping requests, then a gap: perfect cuts land
        # every ~8 traces, giving windows wide enough to be realistic
        t += 40.0 if i % 8 else 5000.0
        s_in = Span(f"t{i}", "in", t, 600.0, "op", [], "svc", "server")
        in_spans.append(s_in)
        prev = t + 10.0
        for e in range(E):
            start = prev + 15.0 + float(rng.normal(0, 2))
            s_out = Span(f"t{i}", f"out{e}", start, 50.0, f"op{e}", [],
                         "svc", "client")
            s_out.ep = f"EP{e}"
            out_flat.append(s_out)
            prev = start + 50.0
    total = len(in_spans) + len(out_flat)
    # the ingest-time columnar store (built at parse in production —
    # load_corpus -> build_columns; untimed, like the JSON parse itself)
    ep_table = sorted({s.ep for s in out_flat})
    ep_of = {ep: i for i, ep in enumerate(ep_table)}
    out_all = SpanArray.from_spans(out_flat)
    out_all.endpoint = np.fromiter((ep_of[s.ep] for s in out_flat),
                                   np.int32, len(out_flat))
    out_all.endpoint_table = ep_table
    in_all = SpanArray.from_spans(in_spans)
    log(f"ingest leg: {total} synthetic spans, {n_traces} traces, E={E}")

    def columnar_pass():
        os.environ["TW_COLUMNAR"] = "1"
        t0 = time.perf_counter()
        order = np.lexsort((in_all.end, in_all.start))
        in_cols = in_all.take(order)
        out_cols = {}
        for e_idx, ep in enumerate(ep_table):
            arr = out_all.take(np.flatnonzero(out_all.endpoint == e_idx))
            arr = arr.take(np.lexsort((arr.end, arr.start)))
            out_cols[ep] = arr
        windows = wt.perfect_cut_windows_cols(in_cols,
                                              wt.DEFAULT_MAX_WINDOW)
        out_starts = {ep: out_cols[ep].start for ep in ep_table}
        ranges = wt.candidate_ranges([], windows, ep_table, out_starts,
                                     in_cols=in_cols)
        caps = water_fill_skip_caps(
            windows, ranges, len(in_cols),
            [len(out_cols[ep]) for ep in ep_table])
        # span lists are never walked when the columns are supplied —
        # placeholders prove it
        packed = wt.pack_problem(
            [], {ep: [] for ep in ep_table}, ep_table, {}, "IN", None,
            parallel=True, windows=windows, ranges=ranges, skip_caps=caps,
            in_cols=in_cols, out_cols=out_cols)
        return packed, windows, time.perf_counter() - t0

    def object_pass():
        os.environ["TW_COLUMNAR"] = "0"
        t0 = time.perf_counter()
        out_parts = partition_spans_by_endpoint(list(out_flat),
                                                lambda s: s.ep)
        ins = sorted(in_spans, key=lambda s: (s.start_mus, s.end_mus))
        out_eps = sorted(out_parts)
        windows = wt.perfect_cut_windows(ins, wt.DEFAULT_MAX_WINDOW)
        out_starts = {
            ep: np.array(sorted(float(s.start_mus) for s in out_parts[ep]))
            for ep in out_eps
        }
        ranges = wt.candidate_ranges(ins, windows, out_eps, out_starts)
        caps = water_fill_skip_caps(
            windows, ranges, len(ins),
            [len(out_parts[ep]) for ep in out_eps])
        packed = wt.pack_problem(
            ins, out_parts, out_eps, {}, "IN", None, parallel=True,
            windows=windows, ranges=ranges, skip_caps=caps)
        return packed, windows, time.perf_counter() - t0

    # twlint: disable=TW001 — raw save/restore of the literal env string
    # (not a parsed knob read): the finally block must put back exactly
    # what was set, including "unset"
    saved = os.environ.get("TW_COLUMNAR")
    try:
        # two timed passes per path, best-of (first pass pays allocator /
        # code warmup); object first so any shared warmup favors IT —
        # the reported ratio is the conservative one
        p_obj, w_obj, s_obj = object_pass()
        _, _, s_obj2 = object_pass()
        p_col, w_col, s_col = columnar_pass()
        _, _, s_col2 = columnar_pass()
    finally:
        if saved is None:
            os.environ.pop("TW_COLUMNAR", None)
        else:
            os.environ["TW_COLUMNAR"] = saved
    obj_s, col_s = min(s_obj, s_obj2), min(s_col, s_col2)
    parity = (w_obj == w_col) and all(
        p_obj.arrays[k].tobytes() == p_col.arrays[k].tobytes()
        and p_obj.arrays[k].dtype == p_col.arrays[k].dtype
        for k in p_obj.arrays)
    report = dict(mode="ingest", pack_parity_ok=bool(parity),
                  **ingest_fields(total, len(w_col), col_s, obj_s))
    log(f"ingest leg: columnar {report['pack_spans_per_s']} spans/s, "
        f"object {report['pack_spans_per_s_object']} spans/s "
        f"({report['pack_columnar_speedup']}x, parity={parity})")
    return report


def wire_fields(n_spans: int, n_traces: int, wire_s: float,
                python_s: float, obj_s: float) -> dict:
    """Wire-ingest leg ledger -> report fields (unit-tested like
    ingest_fields, tests/test_bench.py).

    ``wire_s``/``python_s``/``obj_s`` are the wall seconds of one
    payload-bytes -> columnar-store pass (parsed, validated, root-op
    filtered wire-trace slices — Span materialization is the LAZY stage
    and is timed separately) under the columnar wire parse (native
    front end), the same parse with ``TW_DISABLE_NATIVE=1`` (pure-
    Python front end), and the object pipeline (``TW_WIRE_COLUMNAR=0``:
    json.loads + parse_trace_payload, whose store IS the Span objects)
    on identical bytes. The headline ``wire_spans_per_s`` is the
    columnar number; the r18 acceptance bar is ``wire_speedup >= 5``."""
    def rate(s):
        return round(n_spans / s, 1) if s and s > 0 else None

    return {
        "wire_spans": int(n_spans),
        "wire_traces": int(n_traces),
        "wire_spans_per_s": rate(wire_s),
        "wire_spans_per_s_python": rate(python_s),
        "wire_spans_per_s_object": rate(obj_s),
        "wire_speedup": (round(obj_s / wire_s, 2)
                         if wire_s and wire_s > 0 and obj_s else None),
        "wire_speedup_python": (round(obj_s / python_s, 2)
                                if python_s and python_s > 0 and obj_s
                                else None),
    }


def run_wire_ingest_leg(n_spans: int) -> dict:
    """bench.py --wire-ingest N: serve-path payload parse throughput —
    no device, no windowing. Times the exact accepted-POST front half of
    ``Tenant.ingest_payload`` (payload bytes -> root-op-filtered,
    materialized traces) on ~N spans of fix=2 hotel traces, under all
    three parse paths on identical payload bytes:

    - **wire/native** (the ``TW_WIRE_COLUMNAR`` default): byte-level
      native field extraction (ingest/wire.py), Span objects built only
      for accepted traces;
    - **wire/python** (``TW_DISABLE_NATIVE=1``): the same columnar
      front end on the pure-Python field walk — the fallback a
      container without the toolchain runs;
    - **object** (``TW_WIRE_COLUMNAR=0``): ``json.loads`` +
      :func:`parse_trace_payload`, one ``Span`` per posted span before
      any filtering — the pre-r18 serve flow.

    The accepted traces of all three passes are canonicalized and
    compared (``wire_parity_ok``), along with the dead-letter counters,
    so the reported speedup can never come from diverging accept/reject
    work.
    """
    from traceweaver_tpu import native as native_mod
    from traceweaver_tpu.ingest import wire as wire_mod
    from traceweaver_tpu.ingest.jaeger import (
        FIX_ROOT_OPS,
        parse_trace_payload,
    )

    FIX = 2
    root_op = FIX_ROOT_OPS[FIX]
    n_traces = max(8, n_spans // 5)
    payload = {"data": [_serve_trace(i, "w", 1_000_000.0)
                        for i in range(n_traces)]}
    raw = json.dumps(payload).encode("utf-8")
    log(f"wire leg: {n_traces * 5} posted spans, {n_traces} traces, "
        f"{len(raw) >> 10} KiB payload")

    def object_pass():
        t0 = time.perf_counter()
        counters = {}
        parsed = parse_trace_payload(json.loads(raw), FIX, {}, {},
                                     strict=False, counters=counters)
        accepted = []
        for entry in parsed:
            if entry is None:
                continue
            _tid, spans, _procs = entry
            root = next((s for s in spans.values() if s.IsRoot()), None)
            if root is None or (root_op is not None
                                and root.op_name != root_op):
                continue
            accepted.append(entry)
        return accepted, counters, time.perf_counter() - t0

    def wire_pass():
        t0 = time.perf_counter()
        counters = {}
        entries = wire_mod.parse_payload_wire(raw, FIX, {}, strict=False,
                                              counters=counters)
        assert entries is not None, "wire path unexpectedly ineligible"
        kept = [w for w in entries
                if w is not None
                and not (root_op is not None and w.root_op != root_op)]
        parse_s = time.perf_counter() - t0
        # the lazy stage, timed apart: Span objects exist only past the
        # accept filter (and only because the window feed still consumes
        # objects) — the store -> object conversion is not parse cost
        t1 = time.perf_counter()
        accepted = [w.materialize() for w in kept]
        mat_s = time.perf_counter() - t1
        return accepted, counters, parse_s, mat_s

    def canon(entries):
        # engine-invariant view of the accepted traces: the native front
        # end parses JSON numbers as floats where the object path keeps
        # ints, so times are float()-coerced; tags ride only the object
        # path (the wire contract materializes tags=None) and are
        # excluded — nothing downstream of ingest reads them
        out = []
        for tid, spans, procs in entries:
            rows = tuple(sorted(
                (s.sid, float(s.start_mus), float(s.duration_mus),
                 s.op_name, repr(s.references), repr(s.process_id),
                 s.span_kind) for s in spans.values()))
            prows = tuple(sorted(
                (str(k), str(v.get("serviceName")
                             if isinstance(v, dict) else v))
                for k, v in (procs or {}).items()))
            out.append((tid, rows, prows))
        return out

    # twlint: disable=TW001 — raw save/restore of the literal env string
    # (not a parsed knob read): the finally block must put back exactly
    # what was set, including "unset"
    saved = os.environ.get("TW_DISABLE_NATIVE")
    try:
        # two timed passes per path, best-of (first pass pays warmup);
        # object first so any shared warmup favors IT — the reported
        # speedup is the conservative one
        acc_obj, cnt_obj, s_obj = object_pass()
        _, _, s_obj2 = object_pass()
        os.environ["TW_DISABLE_NATIVE"] = "1"
        acc_py, cnt_py, s_py, _ = wire_pass()
        _, _, s_py2, _ = wire_pass()
        if saved is None:
            os.environ.pop("TW_DISABLE_NATIVE", None)
        else:
            os.environ["TW_DISABLE_NATIVE"] = saved
        native_ok = native_mod.get_lib() is not None
        acc_nat, cnt_nat, s_nat, m_nat = wire_pass()
        _, _, s_nat2, m_nat2 = wire_pass()
    finally:
        if saved is None:
            os.environ.pop("TW_DISABLE_NATIVE", None)
        else:
            os.environ["TW_DISABLE_NATIVE"] = saved
    obj_s, py_s, nat_s = (min(s_obj, s_obj2), min(s_py, s_py2),
                          min(s_nat, s_nat2))
    mat_s = min(m_nat, m_nat2)
    ref = canon(acc_obj)
    parity = (ref == canon(acc_py) == canon(acc_nat)
              and cnt_obj == cnt_py == cnt_nat)
    n_acc = sum(len(spans) for _, spans, _ in acc_obj)
    report = dict(mode="wire", wire_parity_ok=bool(parity),
                  wire_native_loaded=bool(native_ok),
                  wire_materialize_s=round(mat_s, 6),
                  wire_spans_per_s_e2e=(round(n_acc / (nat_s + mat_s), 1)
                                        if nat_s + mat_s > 0 else None),
                  **wire_fields(n_acc, len(acc_obj), nat_s, py_s, obj_s))
    log(f"wire leg: columnar {report['wire_spans_per_s']} spans/s "
        f"(python {report['wire_spans_per_s_python']}, e2e w/ lazy "
        f"materialize {report['wire_spans_per_s_e2e']}), object "
        f"{report['wire_spans_per_s_object']} spans/s "
        f"({report['wire_speedup']}x native / "
        f"{report['wire_speedup_python']}x python, parity={parity})")
    return report


def _serve_trace(i, prefix, base_us, spacing_us=10_000.0, slow_every=6):
    """One synthetic frontend->search->geo Jaeger trace (fix=2 root op);
    every ``slow_every``-th trace plants its latency in search."""
    T = base_us + i * spacing_us
    s1 = 5000.0 if (i % slow_every) == slow_every - 1 else 600.0
    tid = f"{prefix}{i:04d}"

    def span(sid, start, dur, op, refs, pid, kind):
        return dict(traceID=tid, spanID=sid, startTime=start, duration=dur,
                    operationName=op,
                    references=[{"traceID": tid, "spanID": r} for r in refs],
                    processID=pid,
                    tags=[{"key": "span.kind", "value": kind}])

    return dict(traceID=tid, spans=[
        span("root", T, s1 + 900, "HTTP GET /hotels", [], "p1", "server"),
        span("c1", T + 200, s1 + 500, "call-search", ["root"], "p1",
             "client"),
        span("s1", T + 300, s1, "search", ["c1"], "p2", "server"),
        span("c2", T + 400, 300.0, "call-geo", ["s1"], "p2", "client"),
        span("s2", T + 450, 200.0, "geo", ["c2"], "p3", "server"),
    ], processes=dict(p1={"serviceName": "frontend"},
                      p2={"serviceName": "search"},
                      p3={"serviceName": "geo"}))


def run_serve_leg(n_tenants: int) -> dict:
    """bench.py --serve-tenants N: the multi-tenant service leg.

    N synthetic tenants POST at MIXED rates (tenant i ingests
    ``4 * (1 + i % 4)`` traces) into one TenantService; the leg reports
    sustained spans/s, per-tenant min/max, shed/quarantine counts, and
    the isolation metric: the healthy tenants' throughput delta while
    tenant 0 re-runs the same feed under a TW_FAULTS-style dispatch
    fault storm (TW_BENCH_FAULTS, default dispatch:0.5) in isolated
    dispatches."""
    import jax

    if _knobs.get("TW_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    os.environ.setdefault("TW_RETRY_BACKOFF_S", "0")
    from traceweaver_tpu.serve import ServeConfig, TenantService

    spec = _knobs.get("TW_BENCH_FAULTS") or "dispatch:0.5"

    def one_run(storm_spec=None):
        svc = TenantService(ServeConfig(
            fix=2, window_us=60e6, overlap_us=5e6, ooo_bound_us=1e6,
            verbose=False, pump_windows=10**9))
        if storm_spec:
            svc.tenant("tenant-0000").fault_spec = storm_spec
        t0 = time.perf_counter()
        # tenant 0 feeds in chunks with per-tenant flushes (several
        # solves -> several fault draws, so a p<1 storm actually fires);
        # chunk windows are far apart in event time, so an early seal
        # never makes the next chunk late. Same cadence on the clean
        # run, keeping the two walls comparable.
        for chunk in range(4):
            svc.ingest("tenant-0000", {"data": [
                _serve_trace(k, f"u0c{chunk}",
                             base_us=(chunk + 1) * 200e6)
                for k in range(4)]})
            svc.flush("tenant-0000")
        for i in range(1, n_tenants):
            tid = f"tenant-{i:04d}"
            n = 4 * (1 + i % 4)  # mixed rates
            svc.ingest(tid, {"data": [
                _serve_trace(k, f"u{i:04d}", base_us=(i + 1) * 1e6)
                for k in range(n)]})
        svc.flush()
        wall = time.perf_counter() - t0
        st = svc.stats()
        tstats = st["tenants"]
        healthy = [t for tid, t in tstats.items()
                   if tid != "tenant-0000"]
        per_tenant = [t["spans_emitted"] / wall
                      for t in tstats.values() if wall > 0]
        return dict(
            spans=sum(t["spans_emitted"] for t in tstats.values()),
            wall_s=wall,
            healthy_spans=sum(t["spans_emitted"] for t in healthy),
            dispatches=st["dispatch"]["fleet_dispatches"],
            shared_solves=st["dispatch"]["shared_solves"],
            tenant_batches=st["dispatch"]["tenant_batches"],
            shed_windows=sum(t["shed_dropped_windows"]
                             for t in tstats.values()),
            per_tenant_min=(round(min(per_tenant), 1)
                            if per_tenant else None),
            per_tenant_max=(round(max(per_tenant), 1)
                            if per_tenant else None),
            quarantined_windows=sum(t["quarantined_windows"]
                                    for t in tstats.values()),
            deadletter_windows=sum(t["deadletter_windows"]
                                   for t in tstats.values()),
            healthy_quarantined=sum(t["quarantined_windows"]
                                    for t in healthy),
            healthy_shed=sum(t["shed_dropped_windows"] for t in healthy),
            faults_injected=int(
                svc.tenant("tenant-0000").fleet_stats.get(
                    "faults_injected", 0)) if storm_spec else 0,
            spec=storm_spec,
        )

    # warmup pass (uncounted): compiles every shape class so the clean
    # and storm passes below compare warm-vs-warm wall clock — the
    # isolation delta must measure the storm, not XLA compilation
    log(f"serve leg: {n_tenants} tenants, warmup pass")
    one_run()
    log("serve leg: clean pass")
    clean = one_run()
    log(f"serve leg: clean {clean['spans']} spans in "
        f"{clean['wall_s']:.1f}s; storm pass under {spec!r}")
    storm = one_run(storm_spec=spec)
    report = serve_fields(n_tenants, clean, storm)
    report["mode"] = "serve"
    return report


def backend_label(solver_backend) -> tuple:
    """Top-level backend field for the final JSON line.

    A solver child that ran on the CPU stand-in (the explicit fallback
    leg or a JAX_PLATFORMS=cpu run) is labeled the unmistakable
    ``"cpu_fallback"``: round 5's driver read a host-thread-profiled CPU
    run (``pallas_on_device_ok: null``, ``profile_source:
    host_cpu_xla_threads``) as if it were on-chip numbers. Returns
    ``(label, on_chip)``; the raw backend name still ships as
    ``backend_raw``.
    """
    on_chip = solver_backend in ("tpu", "axon")
    return (solver_backend if on_chip else "cpu_fallback"), on_chip


def load_recorded():
    if os.path.exists(RECORDED_PATH):
        with open(RECORDED_PATH) as f:
            return json.load(f)
    return None


def run_baseline_child(bundle_path: str, out_path: str) -> None:
    """Same-input exact-path (DFS + MWIS) subset solves, budget-aware.

    Fresh-solves as many services as ``TW_BENCH_BASELINE_BUDGET`` seconds
    allow, cheapest first by the committed recording's measured times;
    carries the recording for the rest (flagged ``measured: false``). A
    full uncapped run of every service is regenerated by running with a
    large budget and ``TW_BENCH_RECORD=<path>``.
    """
    import signal

    # defensive: should any library path touch jnp, stay off the axon tunnel
    import jax

    jax.config.update("jax_platforms", "cpu")

    budget = _knobs.get_float("TW_BENCH_BASELINE_BUDGET")
    deadline_ts = time.time() + budget
    record_path = _knobs.get("TW_BENCH_RECORD")

    with open(bundle_path, "rb") as f:
        bundles = pickle.load(f)

    from traceweaver_tpu.algorithms.weaver_exact import WeaverExact
    from traceweaver_tpu.metrics import accuracy_for_service

    flat = [(label, svc, prob, ta, dag, store)
            for store, problems in bundles
            for label, svc, prob, ta, dag in problems]

    recorded = (load_recorded() or {}) if not record_path else {}
    rec_svcs = recorded.get("services", {})
    rec_valid = (recorded.get("subset_spans") == SUBSET_SPANS
                 and recorded.get("compress") == COMPRESS)

    # cheapest first, then unknown services, then recorded-DNF ones — so
    # the budget buys the maximum number of fresh same-input pairs and
    # never burns an alarm's worth on a solve the recording already
    # proves cannot finish; a recording for a DIFFERENT config (subset
    # size / compress) is not comparable and must not gate anything
    UNKNOWN, RECORDED_DNF = 1e9, float("inf")

    def est_cost(label):
        rec = rec_svcs.get(label)
        if rec_valid and rec:
            if rec.get("finished"):
                return rec["seconds"]
            return RECORDED_DNF
        return UNKNOWN

    order = sorted(flat, key=lambda item: est_cost(item[0]))

    class _Timeout(Exception):
        pass

    def _alarm(_sig, _frm):
        raise _Timeout()

    signal.signal(signal.SIGALRM, _alarm)

    subset = {}
    for label, svc, prob, ta, dag, store in order:
        sub_in, sub_ta = subset_problem(prob, SUBSET_SPANS)
        n_actual = len(next(iter(sub_in.values())))
        rec = rec_svcs.get(label)
        budget_left = deadline_ts - time.time()
        # fresh-solve only when the recording says the solve fits BOTH the
        # alarm and the remaining budget (unknown services get one alarm's
        # worth of benefit of the doubt); otherwise carry the recording —
        # a guaranteed-alarm fresh attempt would burn ~EXACT_ALARM seconds
        # AND discard a carriable finished recorded pair
        est = est_cost(label)
        known = est < 1e8
        alarm_cap = EXACT_ALARM_SECONDS
        if est == RECORDED_DNF:
            # proven not to finish under the alarm: retry only with ample
            # leftover budget (e.g. an uncapped recording regeneration) —
            # otherwise the budget goes to unmeasured services instead.
            # The retry must NOT re-impose the alarm the recording already
            # proved insufficient: it may use the whole leftover budget
            # minus one alarm of slack for services still to come.
            want_fresh = budget_left > 2 * EXACT_ALARM_SECONDS
            alarm_cap = max(EXACT_ALARM_SECONDS,
                            int(budget_left - EXACT_ALARM_SECONDS))
        else:
            fits_alarm = (est * 1.2 <= EXACT_ALARM_SECONDS) if known else True
            want_fresh = fits_alarm and budget_left > (
                est * 1.5 if known else EXACT_ALARM_SECONDS)
        if want_fresh:
            algo = WeaverExact(store.all_spans, store.all_processes)
            t0 = time.perf_counter()
            signal.alarm(min(alarm_cap, max(5, int(budget_left))))
            try:
                out = algo.FindAssignments(
                    "MaxScoreBatch", svc, sub_in, prob.out_span_partitions,
                    False, [], sub_ta,
                )
                subset[label] = {
                    "finished": True,
                    "seconds": time.perf_counter() - t0,
                    "n_spans": n_actual,
                    "accuracy": accuracy_for_service(out[0], sub_ta, sub_in),
                    "measured": True,
                }
            except _Timeout:
                subset[label] = {"finished": False,
                                 "seconds": time.perf_counter() - t0,
                                 "n_spans": n_actual, "accuracy": None,
                                 "measured": True}
            finally:
                signal.alarm(0)
            log(f"baseline: fresh {label} "
                f"{'done' if subset[label]['finished'] else 'ALARM'} "
                f"({subset[label]['seconds']:.1f}s)")
        elif rec_valid and rec and rec.get("n_spans") == n_actual:
            subset[label] = dict(rec, measured=False)
            log(f"baseline: recorded {label} carried "
                f"({rec['seconds']:.1f}s recorded)")
        else:
            subset[label] = {"finished": False, "seconds": 0.0,
                             "n_spans": n_actual, "accuracy": None,
                             "measured": False}
            log(f"baseline: {label} skipped (no budget, no recording)")

    fin = [v for v in subset.values() if v["finished"]]
    fresh = [v for v in fin if v["measured"]]
    report = {
        "subset": subset,
        "subset_spans_total": sum(v["n_spans"] for v in fin),
        "subset_time_total_s": sum(v["seconds"] for v in fin),
        "subset_spans_per_sec": (
            sum(v["n_spans"] for v in fresh) / sum(v["seconds"] for v in fresh)
            if fresh else None),
        "subset_spans_per_sec_incl_recorded": (
            sum(v["n_spans"] for v in fin) / sum(v["seconds"] for v in fin)
            if fin else None),
        "n_fresh": len(fresh),
        "n_recorded": len(fin) - len(fresh),
    }
    write_json_atomic(out_path, report)
    if record_path:
        import datetime
        import platform

        write_json_atomic(record_path, {
            "generated": datetime.date.today().isoformat(),
            "host": platform.node(),
            "note": "full uncapped exact-path subset run "
                    "(regenerate: TW_BENCH_RECORD=<path> "
                    "TW_BENCH_BASELINE_BUDGET=3600 bench.py --mode baseline)",
            "subset_spans": SUBSET_SPANS,
            "compress": COMPRESS,
            "services": {k: {kk: vv for kk, vv in v.items()
                             if kk != "measured"}
                         for k, v in subset.items()},
        })
    log("baseline: report written")


# ---------------------------------------------------------------------------
# Parent orchestration
# ---------------------------------------------------------------------------

def _spawn(mode: str, bundle: str, out: str, backend: str | None,
           extra_env: dict | None = None) -> subprocess.Popen:
    env = dict(os.environ)
    if backend is not None:
        env["JAX_PLATFORMS"] = backend
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, os.path.join(HERE, "bench.py"), "--mode", mode,
         "--bundle", bundle, "--out", out],
        cwd=HERE, env=env, stdout=sys.stderr, stderr=sys.stderr,
    )


def _wait_for_marker(proc: subprocess.Popen, marker: str,
                     timeout: float) -> int | None:
    """Poll until the child drops ``marker``, exits, or times out.
    Returns the returncode if the child exited, -9 after a timeout kill,
    else None (marker seen; child still running)."""
    end = time.time() + timeout
    while time.time() < end:
        rc = proc.poll()
        if rc is not None:
            return rc
        if os.path.exists(marker):
            return None
        time.sleep(2.0)
    proc.kill()
    proc.wait()
    return -9


def main() -> None:
    deadline_ts = T_START + DEADLINE
    log(f"parent: building problems (no JAX backend init); "
        f"deadline {DEADLINE}s")
    bundles = build_problems()
    tmpdir = tempfile.mkdtemp(prefix="tw_bench_")
    bundle = os.path.join(tmpdir, "bundle.pkl")
    with open(bundle, "wb") as f:
        pickle.dump(bundles, f, protocol=pickle.HIGHEST_PROTOCOL)
    n_services = sum(len(p) for _, p in bundles)
    log(f"parent: bundle pickled ({os.path.getsize(bundle) >> 20} MB, "
        f"{n_services} services)")

    base_out = os.path.join(tmpdir, "baseline.json")
    solver_out = os.path.join(tmpdir, "solver.json")
    marker = solver_out + ".timing.done"

    solver = None
    solver_proc = None
    tried = []
    default_backend = os.environ.get("JAX_PLATFORMS", "axon") or "axon"

    # --- phase 1: solver on the default (TPU) backend --------------------
    # gate 1: the backend must come UP within BACKEND_UP_BUDGET (a down
    # axon blocks inside init for ~40 min — detecting that early leaves
    # enough budget for a full-workload CPU leg); gate 2: the measured
    # passes must finish within the remaining phase budget
    tpu_budget = min(TPU_TIMEOUT_CAP,
                     remaining(deadline_ts) - CPU_FALLBACK_RESERVE
                     - BASELINE_RESERVE - MERGE_SLACK)
    if tpu_budget > 60:
        log(f"parent: solver child on backend={default_backend} "
            f"(backend-up gate {BACKEND_UP_BUDGET}s, "
            f"budget {tpu_budget:.0f}s)")
        t_phase = time.time()
        solver_proc = _spawn("solver", bundle, solver_out,
                             backend=default_backend)
        rc = _wait_for_marker(solver_proc, solver_out + ".backend.up",
                              min(BACKEND_UP_BUDGET, tpu_budget))
        tried.append(default_backend)
        if rc == -9:
            log(f"parent: {default_backend} backend never came up — "
                "declared down")
        elif rc not in (None, 0):
            log(f"parent: solver child on {default_backend} failed (rc={rc})")
        else:
            rc = _wait_for_marker(
                solver_proc, marker,
                max(1.0, tpu_budget - (time.time() - t_phase)))
            if rc == -9:
                # OUR budget kill, not a child crash (progressive report
                # writes mean the measurement may still have landed)
                log(f"parent: solver child on {default_backend} exceeded "
                    "the phase budget — killed (partial report kept if "
                    "the timed pass finished)")
            elif rc not in (None, 0):
                log(f"parent: solver child on {default_backend} "
                    f"failed (rc={rc})")

    def harvest(proc):
        if os.path.exists(solver_out):
            with open(solver_out) as f:
                return json.load(f)
        return None

    solver = harvest(solver_proc)

    # --- phase 2: CPU fallback only if the TPU leg produced nothing.
    # Scope depends on what budget the failed phase left behind: a fast
    # backend-down detection leaves enough for the FULL two-app workload
    # (warm compile cache ~245 s on the round-5 1-core host; ~345+ s
    # cold); otherwise fall back to hotel-only, which provably finishes
    # in its slice -----------------------------------------------------
    reduced_scope = False
    if solver is None and default_backend != "cpu":
        # scope ladder: try FULL only when the budget covers it PLUS a
        # reduced retry (the full leg's first report lands only after its
        # whole timed pass, so a mid-pass kill yields nothing — the
        # reduced retry is the guarantee the old hotel-only fallback gave).
        # full_needs, measured on the round-5 1-core host
        # (BENCH_r05_builder_cpu / the dress-rehearsal log): WARM cache
        # warmup ~105 s + timed pass ~90 s + subset ~3 s ≈ 200-245 s;
        # COLD cache 175 + 120 + 50 ≈ 345+ s. The cheap default applies
        # only when this host's CPU cache dir already has entries —
        # a cold host keeps the conservative bar so it never burns the
        # reduced retry's slice on a doomed full attempt.
        from traceweaver_tpu.runtime.jax_cache import (
            DEFAULT_CACHE_DIR, host_cache_key,
        )

        # evaluate the cache key AS THE CPU CHILD will see it (the child
        # is spawned with JAX_PLATFORMS=cpu; the key embeds that)
        saved = os.environ.get("JAX_PLATFORMS")
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            cpu_key = host_cache_key()
        finally:
            if saved is None:
                os.environ.pop("JAX_PLATFORMS", None)
            else:
                os.environ["JAX_PLATFORMS"] = saved
        cpu_cache = os.path.join(
            _knobs.get("TW_JAX_CACHE_DIR") or DEFAULT_CACHE_DIR, cpu_key)
        cache_primed = os.path.isdir(cpu_cache) and bool(os.listdir(cpu_cache))
        env_needs = _knobs.get_int("TW_BENCH_CPU_FULL_NEEDS")
        full_needs = env_needs if env_needs is not None else (
            320 if cache_primed else 430)
        retry_reserve = _knobs.get_int("TW_BENCH_CPU_RETRY_RESERVE")
        scopes = []
        if (remaining(deadline_ts) - BASELINE_RESERVE - MERGE_SLACK
                - retry_reserve > full_needs):
            scopes.append("full")
        scopes.append("reduced")
        for scope in scopes:
            cpu_budget = (remaining(deadline_ts) - BASELINE_RESERVE
                          - MERGE_SLACK)
            if scope == "full":
                cpu_budget -= retry_reserve
            if cpu_budget < 60:
                continue
            if scope == "full":
                cpu_bundle = bundle
            else:
                cpu_bundle = os.path.join(tmpdir, "bundle_hotel.pkl")
                with open(cpu_bundle, "wb") as f:
                    pickle.dump(build_problems(apps={"hotel"}), f,
                                protocol=pickle.HIGHEST_PROTOCOL)
            log(f"parent: {scope.upper()} solver child on cpu "
                f"(budget {cpu_budget:.0f}s)")
            cpu_proc = _spawn("solver", cpu_bundle, solver_out,
                              backend="cpu")
            _wait_for_marker(cpu_proc, marker, cpu_budget)
            tried.append(f"cpu/{scope}")
            solver = harvest(cpu_proc)
            if cpu_proc.poll() is None:
                cpu_proc.kill()
                cpu_proc.wait()
            if solver is not None:
                reduced_scope = scope == "reduced"
                break

    # --- phase 3: exact-path baseline (overlaps only solver enrichment) --
    baseline = None
    base_budget = remaining(deadline_ts) - MERGE_SLACK
    if base_budget > 10:
        log(f"parent: baseline child (budget {base_budget:.0f}s)")
        base_proc = _spawn(
            "baseline", bundle, base_out, backend="cpu",
            extra_env={"TW_BENCH_BASELINE_BUDGET":
                       str(max(5.0, base_budget - 25))})
        try:
            base_proc.wait(timeout=base_budget)
        except subprocess.TimeoutExpired:
            base_proc.kill()
            base_proc.wait()
        if os.path.exists(base_out):
            with open(base_out) as f:
                baseline = json.load(f)

    # give a still-running solver child the leftovers to finish enrichment
    if solver_proc is not None and solver_proc.poll() is None:
        try:
            solver_proc.wait(timeout=max(1.0, remaining(deadline_ts) - 10))
        except subprocess.TimeoutExpired:
            log("parent: killing solver child (enrichment unfinished)")
            solver_proc.kill()
            solver_proc.wait()
        solver = harvest(solver_proc) or solver

    if solver is None:
        # still emit a parseable line so the round records *something*
        print(json.dumps({
            "metric": "span_assignment_throughput_hotel+media_load150_x10",
            "value": 0.0,
            "unit": "spans/sec",
            "vs_baseline": 0.0,
            "error": f"no solver child completed (tried {tried})",
        }))
        return

    # apples-to-apples accuracy delta on identical inputs (finished
    # services only; unfinished exact solves can't be compared)
    delta_fresh = delta_all = None
    subset_pairs = {}
    if baseline:
        tpu_sub = solver.get("subset_accuracy_per_service", {})
        diffs_fresh, diffs_all = [], []
        for label, rec in baseline.get("subset", {}).items():
            key = f"{label}@{rec['n_spans']}"
            if rec["finished"] and key in tpu_sub:
                d = tpu_sub[key] - rec["accuracy"]
                diffs_all.append(d)
                if rec.get("measured"):
                    diffs_fresh.append(d)
                subset_pairs[label] = {
                    "n_spans": rec["n_spans"],
                    "tpu": tpu_sub[key],
                    "exact": round(rec["accuracy"], 4),
                    "exact_seconds": round(rec["seconds"], 2),
                    "exact_measured_here": bool(rec.get("measured")),
                }
        if diffs_fresh:
            delta_fresh = sum(diffs_fresh) / len(diffs_fresh)
        if diffs_all:
            delta_all = sum(diffs_all) / len(diffs_all)

    exact_sps = (baseline or {}).get("subset_spans_per_sec")
    exact_sps_all = (baseline or {}).get("subset_spans_per_sec_incl_recorded")
    # the headline ratio prefers a same-run denominator; falling back to
    # recorded timings (possibly another host/run) is flagged explicitly
    # so consumers can't mistake a recorded-denominator ratio for a
    # same-run measurement
    ratio_base = exact_sps or exact_sps_all
    ratio_basis = ("fresh" if exact_sps
                   else "recorded" if exact_sps_all else None)
    backend_field, on_chip = backend_label(solver.get("backend"))
    if not on_chip:
        log("WARNING: results come from the CPU fallback backend "
            f"({solver.get('backend')!r}) — spans/sec, MFU and HBM "
            "figures are NOT on-chip numbers")
    result = {
        # the reduced fallback corpus (hotel only) is NOT comparable to the
        # full two-app workload — it reports under its own metric name
        "metric": ("span_assignment_throughput_hotel_only_x10_REDUCED"
                   if reduced_scope else
                   "span_assignment_throughput_hotel+media_load150_x10"),
        "reduced_scope": reduced_scope,
        "value": round(solver["spans_per_sec"], 1),
        "unit": "spans/sec",
        "vs_baseline": (round(solver["spans_per_sec"] / ratio_base, 1)
                        if ratio_base else None),
        "vs_baseline_basis": ratio_basis,
        "backend": backend_field,
        "backend_raw": solver.get("backend"),
        "backend_init_s": solver.get("backend_init_s"),
        "n_spans": solver["n_spans"],
        "n_services": solver.get("n_services"),
        "solve_time_s": round(solver["solve_time_s"], 2),
        "warmup_compile_s": round(solver["warmup_time_s"], 2),
        "compile_cache_warm": solver.get("compile_cache_warm"),
        "accuracy_tpu": round(solver["accuracy_mean"], 4),
        # mixed-precision ledger (tentpole PR 4): configured score-path
        # precision, measured bf16-vs-f32 accuracy delta on identical
        # subset inputs (points; must stay ≤1 pt per dataset), and the
        # analytic score-block HBM byte estimates at the configured
        # itemsize (bf16 halves the XLA-path score stream)
        "precision": solver.get("precision"),
        "score_block_itemsize": solver.get("score_block_itemsize"),
        "accuracy_delta_vs_f32": solver.get("accuracy_delta_vs_f32"),
        "accuracy_delta_vs_f32_per_dataset": solver.get(
            "accuracy_delta_vs_f32_per_dataset"),
        "bf16_delta_exceeds_1pt": solver.get("bf16_delta_exceeds_1pt"),
        "bytes_est_xla": solver.get("bytes_est_xla"),
        "bytes_est_pallas": solver.get("bytes_est_pallas"),
        "accuracy_delta_same_inputs": (round(delta_fresh, 4)
                                       if delta_fresh is not None else None),
        "accuracy_delta_incl_recorded": (round(delta_all, 4)
                                         if delta_all is not None else None),
        "subset_same_inputs": subset_pairs,
        "exact_spans_per_sec_same_inputs": (round(exact_sps, 3)
                                            if exact_sps else None),
        "exact_spans_per_sec_incl_recorded": (round(exact_sps_all, 3)
                                              if exact_sps_all else None),
        "baseline_fresh_solves": (baseline or {}).get("n_fresh"),
        "baseline_recorded_carried": (baseline or {}).get("n_recorded"),
        # chaos leg (--faults / TW_BENCH_FAULTS): supervisor ledger of a
        # fault-injected re-solve of the subset inputs + its accuracy
        # delta vs the unfaulted leg (the ≤1 pt robustness bar)
        "chaos_spec": solver.get("chaos_spec"),
        "chaos_injected": solver.get("chaos_injected"),
        "chaos_retries": solver.get("chaos_retries"),
        "chaos_bisections": solver.get("chaos_bisections"),
        "chaos_xla_fallbacks": solver.get("chaos_xla_fallbacks"),
        "chaos_host_fallbacks": solver.get("chaos_host_fallbacks"),
        "chaos_quarantined": solver.get("chaos_quarantined"),
        "chaos_deadletter_bytes": solver.get("chaos_deadletter_bytes"),
        "chaos_accuracy_delta_pts": solver.get("chaos_accuracy_delta_pts"),
        "chaos_delta_exceeds_1pt": solver.get("chaos_delta_exceeds_1pt"),
        "pallas_on_device_ok": solver.get("pallas_on_device_ok"),
        "stage_seconds": solver.get("stage_seconds"),
        "fused_em_dispatches": solver.get("fused_em_dispatches"),
        "recompiles_timed": solver.get("recompiles_timed"),
        "compile_counts_warmup": solver.get("compile_counts_warmup"),
        "compile_counts_timed": solver.get("compile_counts_timed"),
        "compaction_windows_total": solver.get("compaction_windows_total"),
        "compaction_windows_redispatched": solver.get(
            "compaction_windows_redispatched"),
        "pipeline_groups": solver.get("pipeline_groups"),
        "pipeline_depth": solver.get("pipeline_depth"),
        "pipeline_overlap_pct": solver.get("pipeline_overlap_pct"),
        "d2h_bytes_fetched": solver.get("d2h_bytes_fetched"),
        "d2h_bytes_flags": solver.get("d2h_bytes_flags"),
        "h2d_bytes_shipped": solver.get("h2d_bytes_shipped"),
        "h2d_bytes_ring": solver.get("h2d_bytes_ring"),
        "h2d_bytes_index": solver.get("h2d_bytes_index"),
        "devcols_fallbacks": solver.get("devcols_fallbacks"),
        "device_busy_s_measured": solver.get("device_busy_s_measured"),
        "profile_source": solver.get("profile_source"),
        "mfu_measured_pct": solver.get("mfu_measured_pct"),
        "mfu_est_pct": solver.get("mfu_est_pct"),
        "hbm_util_est_pct": solver.get("hbm_util_est_pct"),
        "profile_top_ops": solver.get("profile_top_ops"),
        "wall_clock_s": round(time.time() - T_START, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode",
                    choices=["parent", "solver", "baseline", "coldstart"],
                    default="parent")
    ap.add_argument("--bundle")
    ap.add_argument("--out")
    ap.add_argument("--spawn-ts", type=float, default=None,
                    help="(coldstart child) parent clock at Popen, so "
                         "first_trace_s includes interpreter start")
    ap.add_argument("--cold-start", type=int, nargs="?", const=3,
                    default=None, metavar="N",
                    help="standalone serving cold-start leg: two fresh "
                         "subprocesses (cold vs warm persistent compile "
                         "cache, TW_AOT=eager lattice warmup) measure "
                         "process start -> first emitted trace over an "
                         "N-burst synthetic stream; reports "
                         "cold_start_s/warm_start_s + the aot_* warmup "
                         "ledger (bar: warm < 5 s, zero solve compiles)")
    ap.add_argument("--faults", nargs="?", const="dispatch:0.2",
                    default=None, metavar="SPEC",
                    help="opt-in chaos leg: re-solve the subset inputs "
                         "under injected faults (default spec "
                         "dispatch:0.2) and report the supervisor "
                         "ledger + accuracy delta vs the unfaulted leg")
    ap.add_argument("--ingest-only", type=int, nargs="?", const=131072,
                    default=None, metavar="N",
                    help="standalone host-pack leg: ~N synthetic spans "
                         "from parsed store to packed window blocks with "
                         "ZERO device involvement, timed under both "
                         "TW_COLUMNAR settings on identical inputs "
                         "(reports pack_spans_per_s, pack_s_per_window, "
                         "and the columnar-vs-object speedup)")
    ap.add_argument("--wire-ingest", type=int, nargs="?", const=100000,
                    default=None, metavar="N",
                    help="standalone serve-path parse leg: ~N spans of "
                         "fix=2 payload bytes through the accepted-POST "
                         "front half of ingest_payload, timed under the "
                         "columnar wire parse (native + pure-Python "
                         "front ends) and the object pipeline "
                         "(TW_WIRE_COLUMNAR=0) with canonicalized "
                         "accept-set parity (reports wire_spans_per_s "
                         "and the wire-vs-object speedup; r18 bar >= 5x)")
    ap.add_argument("--serve-tenants", type=int, default=None, metavar="N",
                    help="standalone multi-tenant service leg: N "
                         "synthetic tenants at mixed rates through one "
                         "TenantService; reports sustained spans/s, "
                         "shed/quarantine counts, and the healthy-tenant "
                         "isolation delta under tenant 0's fault storm "
                         "(TW_BENCH_FAULTS, default dispatch:0.5)")
    ap.add_argument("--continuous", type=int, nargs="?", const=100,
                    default=None, metavar="N",
                    help="standalone continuous-batching leg: N tenants "
                         "at heavy-tailed rates through one "
                         "TenantService, fixed-pump baseline vs the "
                         "event-driven admission scheduler; reports "
                         "sustained spans/s, per-tenant seal→emit p99 "
                         "vs TW_SERVE_SLO_P99_MS, and the steady-state "
                         "compile count (must be 0)")
    ap.add_argument("--serve-overlap", type=int, nargs="?", const=24,
                    default=None, metavar="N",
                    help="standalone overlapped-drain leg: N tenants at "
                         "heavy-tailed rates through the continuous "
                         "dispatcher, TW_SERVE_INFLIGHT=1 serial "
                         "baseline vs the in-flight dispatch ring "
                         "(default depth 2); reports spans/s both "
                         "ways, the measured solve-interval "
                         "overlap_pct (must be > 0), worst-tenant p99 "
                         "vs TW_SERVE_SLO_P99_MS, and the steady-state "
                         "compile count (must be 0)")
    ap.add_argument("--wal", type=int, nargs="?", const=24,
                    default=None, metavar="N",
                    help="standalone durable-WAL leg: the overlap leg's "
                         "N-tenant heavy-tailed feed through the "
                         "continuous dispatcher, measured at TW_WAL=0 "
                         "vs TW_WAL_SYNC=batch vs =always; reports "
                         "spans/s and per-POST ack p50/p99 per policy, "
                         "gated on batch costing <= 10%% throughput vs "
                         "WAL-off with zero steady compiles")
    ap.add_argument("--chaos-adapt", type=int, nargs="?", const=60,
                    default=None, metavar="N",
                    help="standalone drift→adapt recovery leg: replay "
                         "an N-window synthetic corpus whose call-"
                         "latency distribution swaps mid-stream, once "
                         "under TW_ADAPT=0 (control) and once under "
                         "TW_ADAPT=1; asserts the PSI alert fires, an "
                         "out-of-band refit lands, post-adapt accuracy "
                         "returns to within 1 pt of the pre-shift "
                         "ledger, the drift gauge re-arms, and the "
                         "control replay stays degraded")
    ap.add_argument("--capture", type=int, nargs="?", const=40,
                    default=None, metavar="N",
                    help="standalone capture-to-trace chaos leg: replay "
                         "an N-trace recorded strace workload through "
                         "the collector ingress (skew correction, "
                         "partial-capture policy, churn re-keying) and "
                         "the windowed solve, clean vs injected "
                         "skew/loss; gates on skew corrected, churn "
                         "tolerated, and loss degrading gracefully "
                         "(counted, confidence discounted, no crash)")
    ap.add_argument("--campaign", type=int, nargs="?", const=40,
                    default=None, metavar="N",
                    help="standalone campaign leg: the 2-rung synthetic "
                         "mini campaign through the real harness "
                         "(traceweaver_tpu/campaign) — mesh fleet drive, "
                         "warmup to zero compiles, timed rounds, "
                         "multislice allreduce, and a self-compare "
                         "through the regression gate; N = traces per "
                         "call graph (docs/CAMPAIGN.md)")
    ap.add_argument("--fleet-wire", type=float, nargs="?", const=6.0,
                    default=None, metavar="S",
                    help="standalone replica-fleet wire leg: closed-loop "
                         "generators POST through the consistent-hash "
                         "router to 1 then 2 in-process HTTP replicas, "
                         "live hot-tenant migration in the 2-replica "
                         "chaos phase, zero-loss gate, self-compare "
                         "through the regression gate; S = steady-phase "
                         "drive seconds per rung (docs/CAMPAIGN.md)")
    ap.add_argument("--scorecard", type=int, nargs="?", const=48,
                    default=None, metavar="N",
                    help="standalone per-regime scorecard leg: all five "
                         "baselines + the TPU solver over the synthetic "
                         "three-regime labeled corpus (N traces per "
                         "service); reports per-regime accuracy and the "
                         "confidence-decile calibration check "
                         "(warn-flagged when not monotone-ish)")
    args = ap.parse_args()
    if args.mode == "coldstart":
        run_coldstart_child(args.out, args.spawn_ts or time.time(),
                            args.cold_start or 3)
        sys.exit(0)
    if args.cold_start:
        coldstart_report = run_coldstart_leg(args.cold_start)
        line = json.dumps(coldstart_report)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        sys.exit(0)
    if args.faults:
        # env, so the solver CHILD (where the leg runs) inherits it
        os.environ["TW_BENCH_FAULTS"] = args.faults
    if args.ingest_only:
        ingest_report = run_ingest_leg(args.ingest_only)
        line = json.dumps(ingest_report)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        sys.exit(0)
    if args.wire_ingest:
        wire_report = run_wire_ingest_leg(args.wire_ingest)
        line = json.dumps(wire_report)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        sys.exit(0)
    if args.serve_tenants:
        serve_report = run_serve_leg(args.serve_tenants)
        line = json.dumps(serve_report)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        sys.exit(0)
    if args.continuous:
        continuous_report = run_continuous_leg(args.continuous)
        line = json.dumps(continuous_report)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        sys.exit(0)
    if args.serve_overlap:
        overlap_report = run_overlap_leg(args.serve_overlap)
        line = json.dumps(overlap_report)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        sys.exit(0)
    if args.wal:
        wal_report = run_wal_leg(args.wal)
        line = json.dumps(wal_report)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        sys.exit(0)
    if args.chaos_adapt:
        adapt_report = run_adapt_leg(args.chaos_adapt)
        line = json.dumps(adapt_report)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        sys.exit(0)
    if args.capture:
        capture_report = run_capture_leg(args.capture)
        line = json.dumps(capture_report)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        sys.exit(0)
    if args.campaign:
        campaign_report = run_campaign_leg(args.campaign)
        line = json.dumps(campaign_report)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        sys.exit(0)
    if args.fleet_wire:
        fleet_report = run_fleet_wire_leg(args.fleet_wire)
        line = json.dumps(fleet_report)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        sys.exit(0)
    if args.scorecard:
        scorecard_report = run_scorecard_leg(args.scorecard)
        line = json.dumps(scorecard_report)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        sys.exit(0)
    if args.mode == "solver":
        run_solver_child(args.bundle, args.out)
    elif args.mode == "baseline":
        run_baseline_child(args.bundle, args.out)
    else:
        main()
