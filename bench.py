"""Benchmark: TPU Sinkhorn reconstruction throughput vs the CPU oracle.

Workload: hotel_reservation @ load150 (1000 recorded traces), arrivals
compressed 10x (reference ``repeat_change_spans`` semantics,
transforms.py:10-40) — the high-interleave regime the reference's Alibaba
scale sweep (exp5) stresses, where DFS candidate enumeration blows up
combinatorially. Both solvers reconstruct the same per-service assignment
problems end-to-end (pack -> solve -> decode -> accuracy):

- TPU path:  WeaverTPU (windowed masked Sinkhorn, flagship), full corpus
- baseline:  WeaverExact "MaxScoreBatch" — the reference's DFS top-K +
             windowed exact-MWIS combinatorial path (Gurobi stand-in),
             timed on a per-service subset with a hard wall-clock cap
             (a capped service is credited its subset size over the cap
             time — an upper bound on its speed, which *understates*
             the reported ratio).

Prints ONE JSON line with the TPU spans/sec and the vs-baseline ratio.

Orchestration: the sandbox's remote TPU backend ("axon") tunnels device
init and every XLA compile through a relay and can stall for minutes —
round 1's monolithic bench died inside one jit compile. So this parent
process never initializes a JAX backend itself. It:

1. warms the corpus cache and pickles the packed service problems once;
2. launches the combinatorial baseline as a CPU subprocess (no JAX);
3. launches the solver child on the TPU backend with a hard timeout,
   falling back to an identical CPU-backend child if the TPU child cannot
   produce a result in budget (the JSON then carries ``backend: "cpu"``);
4. merges the child reports and prints the final JSON line.

Worst-case wall-clock is bounded (~load + TPU timeout + CPU child +
baseline cap), so the driver always gets a parseable line.
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import subprocess
import sys
import tempfile
import time

DATA = "/root/reference/data/hotel_reservation/hotel_load150"
COMPRESS = 10.0
CPU_SUBSET_SPANS = 30
CPU_CAP_SECONDS = int(os.environ.get("TW_BENCH_BASELINE_CAP", "120"))
TPU_TIMEOUT = int(os.environ.get("TW_BENCH_TPU_TIMEOUT", "540"))
CPU_TIMEOUT = int(os.environ.get("TW_BENCH_CPU_TIMEOUT", "480"))

HERE = os.path.dirname(os.path.abspath(__file__))


def log(msg: str) -> None:
    print(f"[bench +{time.time() - T_START:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


T_START = time.time()


# ---------------------------------------------------------------------------
# Shared problem construction (pure NumPy/Python — safe in the parent)
# ---------------------------------------------------------------------------

def build_problems():
    from traceweaver_tpu.ingest import (
        build_service_problem,
        infer_invocation_dag,
        load_corpus,
    )
    from traceweaver_tpu.metrics import get_ground_truth
    from traceweaver_tpu.synth import compress_spans

    store = load_corpus(DATA, fix=2, max_traces=1000, cache=True)
    problems = []
    for svc in store.out_spans_by_process:
        prob = build_service_problem(store, svc)
        if prob.skipped:
            continue
        ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
        dag = infer_invocation_dag(
            prob.in_span_partitions, prob.out_span_partitions, ta, store
        )
        compress_spans(prob.in_span_partitions, prob.out_span_partitions,
                       1, COMPRESS)
        ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
        problems.append((svc, prob, ta, dag))
    return store, problems


# ---------------------------------------------------------------------------
# Solver child (runs under whichever JAX backend the env selects)
# ---------------------------------------------------------------------------

def run_solver_child(bundle_path: str, out_path: str) -> None:
    import numpy as np

    with open(bundle_path, "rb") as f:
        store, problems = pickle.load(f)
    log(f"child: bundle loaded ({len(problems)} services)")

    import jax

    # the sandbox's sitecustomize force-updates jax_platforms="axon,cpu" at
    # interpreter start, so the env var alone cannot select CPU — mirror it
    # into the config before the first backend init (tests/conftest.py does
    # the same)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from traceweaver_tpu.runtime.jax_cache import (
        enable_persistent_compilation_cache,
    )

    # record whether the on-disk compile cache was warm FOR THIS CONFIG:
    # with it, warmup_time_s measures cache deserialization, not a cold
    # compile — the report must say which one it was. "Warm" is judged by
    # whether the warmup pass wrote new cache entries, not by the dir
    # being non-empty (a sweep sibling's entries don't warm this config).
    cache_dir = enable_persistent_compilation_cache()
    cache_entries_before = set(os.listdir(cache_dir)) if cache_dir else set()

    backend = jax.default_backend()
    log(f"child: jax backend = {backend}, devices = {jax.devices()}")

    from traceweaver_tpu.algorithms.weaver_tpu import WeaverTPU
    from traceweaver_tpu.metrics import accuracy_for_service

    def one_pass(stage_stats=None):
        preds = {}
        for svc, prob, ta, dag in problems:
            algo = WeaverTPU(store.all_spans, store.all_processes)
            out = algo.FindAssignments(
                "MaxScoreBatchSubsetWithSkips", svc,
                prob.in_span_partitions, prob.out_span_partitions,
                False, [], ta, dag,
            )
            preds[svc] = out[0]
            if stage_stats is not None:
                for k, v in algo.stats.items():
                    stage_stats[k] = stage_stats.get(k, 0.0) + v
            log(f"child: warm/solve {svc} done")
        return preds

    t0 = time.perf_counter()
    one_pass()  # compile warm-up (cached afterwards)
    warmup_time = time.perf_counter() - t0
    cache_warm = bool(cache_dir) and (
        set(os.listdir(cache_dir)) == cache_entries_before)
    log(f"child: warm-up (compile) pass {warmup_time:.1f}s "
        f"(cache_warm={cache_warm})")

    profile_dir = os.environ.get("TW_BENCH_PROFILE_DIR")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    stage_stats: dict = {}
    t0 = time.perf_counter()
    preds = one_pass(stage_stats)
    solve_time = time.perf_counter() - t0
    if profile_dir:
        jax.profiler.stop_trace()
        log(f"child: profiler trace written to {profile_dir}")
    n_spans = sum(
        len(next(iter(prob.in_span_partitions.values())))
        for _, prob, _, _ in problems
    )
    log(f"child: timed pass {solve_time:.1f}s ({n_spans / solve_time:.0f} spans/s)")

    accs = {
        svc: accuracy_for_service(preds[svc], ta, prob.in_span_partitions)
        for svc, prob, ta, _ in problems
    }

    # --- Pallas kernel on-device proof (non-interpret) -------------------
    pallas_ok = None
    if backend in ("tpu", "axon"):
        try:
            from traceweaver_tpu.ops.pallas_sinkhorn import sinkhorn_log_pallas
            from traceweaver_tpu.ops.sinkhorn import sinkhorn_log

            rng = np.random.default_rng(0)
            S = rng.normal(size=(64, 128)).astype(np.float32)
            r = np.ones(64, np.float32)
            c = np.full(128, 0.5, np.float32)
            got = np.asarray(sinkhorn_log_pallas(S, r, c, epsilon=1.0,
                                                 n_iters=40, interpret=False))
            want = np.asarray(sinkhorn_log(S, r, c, epsilon=1.0, n_iters=40))
            pallas_ok = bool(np.allclose(got, want, rtol=2e-3, atol=2e-4))
            log(f"child: pallas on-device check ok={pallas_ok}")
        except Exception as e:  # lowering not supported on this plugin
            log(f"child: pallas on-device check failed: {type(e).__name__}: {e}")
            pallas_ok = False

    # Utilization estimates from the solver's analytic op accounting.
    # Peaks: TPU v5e ~197 TFLOP/s bf16 MXU (the headline "MFU" denominator;
    # this pipeline is f32/VPU-heavy, so its MFU is structurally small) and
    # ~819 GB/s HBM — bandwidth utilization is the honest roofline for the
    # Sinkhorn inner loop under plain XLA.
    device_s = stage_stats.get("wait_s", 0.0) or solve_time
    flops = stage_stats.get("flops_est", 0.0)
    bytes_key = ("bytes_est_pallas" if pallas_ok else "bytes_est_xla")
    peak_flops = 197e12 if backend in ("tpu", "axon") else 2e11
    peak_bw = 819e9 if backend in ("tpu", "axon") else 5e10
    report = {
        "backend": backend,
        "n_spans": n_spans,
        "solve_time_s": solve_time,
        "warmup_time_s": warmup_time,
        "compile_cache_warm": cache_warm,
        "spans_per_sec": n_spans / solve_time,
        "accuracy_mean": sum(accs.values()) / len(accs),
        "pallas_on_device_ok": pallas_ok,
        "stage_seconds": {
            k: round(stage_stats.get(k, 0.0), 3)
            for k in ("pack_s", "dispatch_s", "wait_s", "decode_s", "refit_s")
        },
        "flops_est": flops,
        "mfu_est_pct": round(100.0 * flops / max(device_s, 1e-9)
                             / peak_flops, 4),
        "hbm_util_est_pct": round(
            100.0 * stage_stats.get(bytes_key, 0.0)
            / max(device_s, 1e-9) / peak_bw, 2),
    }
    with open(out_path, "w") as f:
        json.dump(report, f)
    log("child: report written")


# ---------------------------------------------------------------------------
# Combinatorial baseline child (no JAX backend at all)
# ---------------------------------------------------------------------------

def run_baseline_child(bundle_path: str, out_path: str) -> None:
    import signal

    # defensive: should any library path touch jnp, stay off the axon tunnel
    import jax

    jax.config.update("jax_platforms", "cpu")

    with open(bundle_path, "rb") as f:
        store, problems = pickle.load(f)

    from traceweaver_tpu.algorithms.weaver_exact import WeaverExact
    from traceweaver_tpu.metrics import accuracy_for_service, get_ground_truth

    class _Timeout(Exception):
        pass

    def _alarm(_sig, _frm):
        raise _Timeout()

    signal.signal(signal.SIGALRM, _alarm)
    deadline = time.perf_counter() + CPU_CAP_SECONDS
    per_service_cap = max(10, CPU_CAP_SECONDS // max(1, len(problems)))

    cpu_spans = 0
    cpu_time = 0.0
    accs = {}
    for svc, prob, ta, dag in problems:
        if time.perf_counter() > deadline:
            log(f"baseline: global cap hit, skipping remaining services")
            break
        in_ep = next(iter(prob.in_span_partitions))
        sub_in = {in_ep: prob.in_span_partitions[in_ep][:CPU_SUBSET_SPANS]}
        sub_ta = get_ground_truth(sub_in, prob.out_span_partitions)
        algo = WeaverExact(store.all_spans, store.all_processes)
        t0 = time.perf_counter()
        signal.alarm(per_service_cap)
        try:
            out = algo.FindAssignments(
                "MaxScoreBatch", svc, sub_in, prob.out_span_partitions,
                False, [], sub_ta,
            )
            accs[svc] = accuracy_for_service(out[0], sub_ta, sub_in)
        except _Timeout:
            accs[svc] = None  # did not finish the subset within the cap
        finally:
            signal.alarm(0)
        cpu_time += time.perf_counter() - t0
        cpu_spans += len(sub_in[in_ep])
        log(f"baseline: {svc} done ({cpu_time:.1f}s cumulative)")

    vals = [v for v in accs.values() if v is not None]
    report = {
        "spans": cpu_spans,
        "time_s": cpu_time,
        "spans_per_sec_upper_bound": cpu_spans / cpu_time if cpu_time else None,
        "accuracy_mean_subset": sum(vals) / len(vals) if vals else None,
    }
    with open(out_path, "w") as f:
        json.dump(report, f)
    log("baseline: report written")


# ---------------------------------------------------------------------------
# Parent orchestration
# ---------------------------------------------------------------------------

def _spawn(mode: str, bundle: str, out: str, backend: str | None,
           extra_env: dict | None = None) -> subprocess.Popen:
    env = dict(os.environ)
    if backend is not None:
        env["JAX_PLATFORMS"] = backend
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, os.path.join(HERE, "bench.py"), "--mode", mode,
         "--bundle", bundle, "--out", out],
        cwd=HERE, env=env, stdout=sys.stderr, stderr=sys.stderr,
    )


def main() -> None:
    log("parent: building problems (no JAX backend init)")
    store, problems = build_problems()
    tmpdir = tempfile.mkdtemp(prefix="tw_bench_")
    bundle = os.path.join(tmpdir, "bundle.pkl")
    with open(bundle, "wb") as f:
        pickle.dump((store, problems), f, protocol=pickle.HIGHEST_PROTOCOL)
    log(f"parent: bundle pickled ({os.path.getsize(bundle) >> 20} MB, "
        f"{len(problems)} services)")

    base_out = os.path.join(tmpdir, "baseline.json")
    solver_out = os.path.join(tmpdir, "solver.json")

    solver = None
    tried = []
    default_backend = os.environ.get("JAX_PLATFORMS", "axon") or "axon"
    for backend, timeout in ((default_backend, TPU_TIMEOUT),
                             ("cpu", CPU_TIMEOUT)):
        if backend == "cpu" and default_backend == "cpu" and tried:
            break
        log(f"parent: solver child on backend={backend} (timeout {timeout}s)")
        proc = _spawn("solver", bundle, solver_out, backend=backend)
        try:
            rc = proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            log(f"parent: solver child on {backend} timed out — killing")
            proc.kill()
            proc.wait()
            rc = -9
        tried.append(backend)
        if rc == 0 and os.path.exists(solver_out):
            with open(solver_out) as f:
                solver = json.load(f)
            break
        log(f"parent: solver child on {backend} failed (rc={rc})")

    # baseline runs AFTER the solver measurement so neither side's timing
    # is taken under host-CPU contention (the ratio stays a conservative
    # bound: capped baseline services are credited cap-time speed)
    log("parent: baseline child (sequential, no contention)")
    base_proc = _spawn("baseline", bundle, base_out, backend="cpu")
    try:
        base_proc.wait(timeout=CPU_CAP_SECONDS + 180)
    except subprocess.TimeoutExpired:
        base_proc.kill()
        base_proc.wait()
    baseline = None
    if os.path.exists(base_out):
        with open(base_out) as f:
            baseline = json.load(f)

    if solver is None:
        # still emit a parseable line so the round records *something*
        print(json.dumps({
            "metric": "span_assignment_throughput_hotel_load150_x10_interleave",
            "value": 0.0,
            "unit": "spans/sec",
            "vs_baseline": 0.0,
            "error": f"no solver child completed (tried {tried})",
        }))
        return

    base_sps = (baseline or {}).get("spans_per_sec_upper_bound")
    result = {
        "metric": "span_assignment_throughput_hotel_load150_x10_interleave",
        "value": round(solver["spans_per_sec"], 1),
        "unit": "spans/sec",
        "vs_baseline": (round(solver["spans_per_sec"] / base_sps, 1)
                        if base_sps else None),
        "backend": solver["backend"],
        "baseline_spans_per_sec_upper_bound": (round(base_sps, 2)
                                               if base_sps else None),
        "accuracy_tpu": round(solver["accuracy_mean"], 4),
        "accuracy_baseline_subset": (baseline or {}).get("accuracy_mean_subset"),
        "n_spans": solver["n_spans"],
        "solve_time_s": round(solver["solve_time_s"], 2),
        "warmup_compile_s": round(solver["warmup_time_s"], 2),
        "compile_cache_warm": solver.get("compile_cache_warm"),
        "pallas_on_device_ok": solver.get("pallas_on_device_ok"),
        "stage_seconds": solver.get("stage_seconds"),
        "mfu_est_pct": solver.get("mfu_est_pct"),
        "hbm_util_est_pct": solver.get("hbm_util_est_pct"),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["parent", "solver", "baseline"],
                    default="parent")
    ap.add_argument("--bundle")
    ap.add_argument("--out")
    args = ap.parse_args()
    if args.mode == "solver":
        run_solver_child(args.bundle, args.out)
    elif args.mode == "baseline":
        run_baseline_child(args.bundle, args.out)
    else:
        main()
