"""Benchmark: TPU Sinkhorn reconstruction throughput vs the CPU oracle.

Workload: hotel_reservation AND media_microservices @ load150 (1000
recorded traces each), arrivals compressed 10x (reference
``repeat_change_spans`` semantics, transforms.py:10-40) — the
high-interleave regime the reference's Alibaba scale sweep (exp5)
stresses, where DFS candidate enumeration blows up combinatorially.
Eight services total (hotel frontend/search + media's six), solved
concurrently by a thread pool (the reference's own per-service
concurrency model, executor.py:1015-1026) so device round trips overlap.

Two accuracy/throughput comparisons, both on identical inputs:

- full corpus: WeaverTPU (fused two-pass EM, one device dispatch per
  service) over every span; the combinatorial baseline is too slow here,
  so its capped upper bound only anchors the headline ratio's floor.
- same-input subset: the first TW_BENCH_SUBSET (default 40) incoming
  spans per service are solved by BOTH WeaverTPU and the exact DFS+MWIS
  path (WeaverExact "MaxScoreBatch", Gurobi stand-in) with no cap beyond
  a safety alarm; the report carries ``accuracy_delta_same_inputs`` and a
  *measured* exact-path spans/sec — the apples-to-apples numbers the
  round-2 artifact lacked.

The timed pass runs under ``jax.profiler`` and the trace is parsed
in-process (``jax.profiler.ProfileData``): the report's
``device_busy_s`` / ``mfu_measured_pct`` come from the device plane's
executed-op timeline, not wall-clock inference, and a top-op summary is
written next to the JSON (committed as PROFILE_r{N}.json).

Prints ONE JSON line with the TPU spans/sec and the vs-baseline ratio.

Orchestration: the sandbox's remote TPU backend ("axon") tunnels device
init and every XLA compile through a relay and can stall for minutes —
round 1's monolithic bench died inside one jit compile. So this parent
process never initializes a JAX backend itself. It:

1. warms the corpus cache and pickles the packed service problems once;
2. launches the solver child on the TPU backend with a hard timeout,
   falling back to an identical CPU-backend child if the TPU child cannot
   produce a result in budget (the JSON then carries ``backend: "cpu"``);
3. launches the exact-path baseline as a CPU subprocess (no JAX), after
   the solver so neither side is timed under host contention;
4. merges the child reports and prints the final JSON line.

Worst-case wall-clock is bounded (~load + TPU timeout + CPU child +
baseline cap), so the driver always gets a parseable line.
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import subprocess
import sys
import tempfile
import time

DATASETS = (
    # (app, path, fix)
    ("hotel", "/root/reference/data/hotel_reservation/hotel_load150", 2),
    ("media", "/root/reference/data/media_microservices/media_load150", 1),
)
COMPRESS = 10.0
SUBSET_SPANS = int(os.environ.get("TW_BENCH_SUBSET", "40"))
# fallback subset size when the exact path cannot finish SUBSET_SPANS
# within the alarm (x10-compressed hotel frontend needs this)
SUBSET_RETRY = int(os.environ.get("TW_BENCH_SUBSET_RETRY", "25"))
# legacy capped sweep (floor anchor for the full-corpus ratio)
CPU_SUBSET_SPANS = 30
CPU_CAP_SECONDS = int(os.environ.get("TW_BENCH_BASELINE_CAP", "120"))
# per-service safety alarm for the "uncapped" same-input exact solves;
# a service that trips it is retried at SUBSET_RETRY, then reported
# unfinished rather than credited
EXACT_ALARM_SECONDS = int(os.environ.get("TW_BENCH_EXACT_ALARM", "90"))
TPU_TIMEOUT = int(os.environ.get("TW_BENCH_TPU_TIMEOUT", "540"))
CPU_TIMEOUT = int(os.environ.get("TW_BENCH_CPU_TIMEOUT", "480"))

HERE = os.path.dirname(os.path.abspath(__file__))


def log(msg: str) -> None:
    print(f"[bench +{time.time() - T_START:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


T_START = time.time()


# ---------------------------------------------------------------------------
# Shared problem construction (pure NumPy/Python — safe in the parent)
# ---------------------------------------------------------------------------

def build_problems():
    from traceweaver_tpu.ingest import (
        build_service_problem,
        infer_invocation_dag,
        load_corpus,
    )
    from traceweaver_tpu.metrics import get_ground_truth
    from traceweaver_tpu.synth import compress_spans

    bundles = []
    for app, path, fix in DATASETS:
        store = load_corpus(path, fix=fix, max_traces=1000, cache=True)
        problems = []
        for svc in store.out_spans_by_process:
            prob = build_service_problem(store, svc)
            if prob.skipped:
                continue
            ta = get_ground_truth(prob.in_span_partitions,
                                  prob.out_span_partitions)
            dag = infer_invocation_dag(
                prob.in_span_partitions, prob.out_span_partitions, ta, store
            )
            compress_spans(prob.in_span_partitions, prob.out_span_partitions,
                           1, COMPRESS)
            ta = get_ground_truth(prob.in_span_partitions,
                                  prob.out_span_partitions)
            problems.append((f"{app}/{svc}", svc, prob, ta, dag))
        bundles.append((store, problems))
    return bundles


def subset_problem(prob, n):
    """First-n incoming spans of a service problem (shared by both the
    TPU and exact children so the comparison is on identical inputs)."""
    from traceweaver_tpu.metrics import get_ground_truth

    in_ep = next(iter(prob.in_span_partitions))
    spans = sorted(prob.in_span_partitions[in_ep],
                   key=lambda s: (s.start_mus, s.end_mus))[:n]
    sub_in = {in_ep: spans}
    sub_ta = get_ground_truth(sub_in, prob.out_span_partitions)
    return sub_in, sub_ta


# ---------------------------------------------------------------------------
# Solver child (runs under whichever JAX backend the env selects)
# ---------------------------------------------------------------------------

def _parse_profile(profile_dir):
    """Device-plane busy time + top self-time ops from the xplane trace."""
    import glob

    from jax.profiler import ProfileData

    paths = glob.glob(os.path.join(profile_dir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        return None
    with open(sorted(paths)[-1], "rb") as f:
        data = ProfileData.from_serialized_xspace(f.read())
    busy_ns = 0.0
    ops = {}
    for plane in data.planes:
        name = plane.name or ""
        if not (name.startswith("/device:") or "TPU" in name.upper()):
            continue
        for line in plane.lines:
            lname = (line.name or "").lower()
            # "XLA Modules" spans whole executables (busy time);
            # "XLA Ops" has per-op self time (the roofline breakdown)
            if "module" in lname:
                for ev in line.events:
                    busy_ns += ev.duration_ns
            elif "op" in lname:
                for ev in line.events:
                    ops[ev.name] = ops.get(ev.name, 0.0) + ev.duration_ns
    top = sorted(ops.items(), key=lambda kv: -kv[1])[:12]
    return {
        "device_busy_s": busy_ns / 1e9,
        "top_ops": [
            {"op": k[:120], "self_s": round(v / 1e9, 4)} for k, v in top
        ],
    }


def run_solver_child(bundle_path: str, out_path: str) -> None:
    with open(bundle_path, "rb") as f:
        bundles = pickle.load(f)
    n_services = sum(len(p) for _, p in bundles)
    log(f"child: bundle loaded ({n_services} services)")

    import jax

    # the sandbox's sitecustomize force-updates jax_platforms="axon,cpu" at
    # interpreter start, so the env var alone cannot select CPU — mirror it
    # into the config before the first backend init (tests/conftest.py does
    # the same)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from traceweaver_tpu.runtime.jax_cache import (
        enable_persistent_compilation_cache,
    )

    # record whether the on-disk compile cache was warm FOR THIS CONFIG:
    # with it, warmup_time_s measures cache deserialization, not a cold
    # compile — the report must say which one it was. "Warm" is judged by
    # whether the warmup pass wrote new cache entries, not by the dir
    # being non-empty (a sweep sibling's entries don't warm this config).
    cache_dir = enable_persistent_compilation_cache()
    cache_entries_before = set(os.listdir(cache_dir)) if cache_dir else set()

    backend = jax.default_backend()
    log(f"child: jax backend = {backend}, devices = {jax.devices()}")

    import threading
    from concurrent.futures import ThreadPoolExecutor

    from traceweaver_tpu.algorithms.fleet import FleetItem, solve_fleet
    from traceweaver_tpu.algorithms.weaver_tpu import WeaverTPU
    from traceweaver_tpu.metrics import accuracy_for_service

    flat = [(label, svc, prob, ta, dag, store)
            for store, problems in bundles
            for label, svc, prob, ta, dag in problems]
    stats_lock = threading.Lock()
    use_fleet = os.environ.get("TW_BENCH_FLEET", "1") not in ("0", "false")

    def solve_one(item, stage_stats=None):
        label, svc, prob, ta, dag, store = item
        algo = WeaverTPU(store.all_spans, store.all_processes)
        out = algo.FindAssignments(
            "MaxScoreBatchSubsetWithSkips", svc,
            prob.in_span_partitions, prob.out_span_partitions,
            False, [], ta, dag,
        )
        if stage_stats is not None:
            with stats_lock:  # solver threads race on the shared dict
                for k, v in algo.stats.items():
                    stage_stats[k] = stage_stats.get(k, 0.0) + v
        return label, out[0]

    def one_pass(stage_stats=None):
        if use_fleet:
            # ALL services (both apps) ride one fused device program —
            # pass0 + per-service BIC-GMM refit + pass1, one round trip
            # (fleet.py; proven assignment-identical to the per-service
            # path by tests/test_fleet.py)
            items = [FleetItem(svc, prob.in_span_partitions,
                               prob.out_span_partitions, ta, dag,
                               store=store)
                     for _, svc, prob, ta, dag, store in flat]
            outs = solve_fleet(
                items, stats=stage_stats if stage_stats is not None else {})
            return {label: out[0]
                    for (label, *_), out in zip(flat, outs)}
        # fallback: per-service solves, dispatches overlapped by threads
        # (the reference's ThreadPool-over-services model)
        with ThreadPoolExecutor(max_workers=max(1, len(flat))) as pool:
            preds = dict(pool.map(
                lambda it: solve_one(it, stage_stats), flat))
        return preds

    t0 = time.perf_counter()
    one_pass()  # compile warm-up (cached afterwards)
    warmup_time = time.perf_counter() - t0
    cache_warm = bool(cache_dir) and (
        set(os.listdir(cache_dir)) == cache_entries_before)
    log(f"child: warm-up (compile) pass {warmup_time:.1f}s "
        f"(cache_warm={cache_warm})")

    profile_dir = os.environ.get("TW_BENCH_PROFILE_DIR")
    auto_profile_dir = profile_dir is None
    if auto_profile_dir:
        profile_dir = tempfile.mkdtemp(prefix="tw_profile_")
    jax.profiler.start_trace(profile_dir)
    stage_stats: dict = {}
    t0 = time.perf_counter()
    preds = one_pass(stage_stats)
    solve_time = time.perf_counter() - t0
    jax.profiler.stop_trace()
    profile = None
    try:
        profile = _parse_profile(profile_dir)
    except Exception as e:  # trace formats vary per backend plugin
        log(f"child: profile parse failed: {type(e).__name__}: {e}")
    log(f"child: profiler trace in {profile_dir}")
    if auto_profile_dir:
        import shutil

        shutil.rmtree(profile_dir, ignore_errors=True)  # summary kept in report

    n_spans = sum(
        len(next(iter(prob.in_span_partitions.values())))
        for _, _, prob, _, _, _ in flat
    )
    log(f"child: timed pass {solve_time:.1f}s ({n_spans / solve_time:.0f} spans/s)")

    accs = {
        label: accuracy_for_service(preds[label], ta, prob.in_span_partitions)
        for label, _, prob, ta, _, _ in flat
    }

    # --- same-input subset leg (exact path runs these in the baseline
    # child; identical spans, identical ground truth). Solved for both
    # subset sizes so the parent can pair each service with whichever
    # size the exact path managed to finish. -----------------------------
    subset_accs = {}
    t0 = time.perf_counter()
    sub_items, sub_meta = [], []
    for n in dict.fromkeys((SUBSET_SPANS, SUBSET_RETRY)):
        for label, svc, prob, ta, dag, store in flat:
            sub_in, sub_ta = subset_problem(prob, n)
            # key by the ACTUAL span count (a service may hold fewer spans
            # than requested) — the pairing key the parent reconstructs
            # from the baseline's recorded n_spans; identical subsets
            # (service shorter than both sizes) solve once
            n_actual = len(next(iter(sub_in.values())))
            key = f"{label}@{n_actual}"
            if key in subset_accs or any(k == key for k, _, _ in sub_meta):
                continue
            sub_items.append(FleetItem(svc, sub_in,
                                       prob.out_span_partitions, sub_ta,
                                       dag, store=store))
            sub_meta.append((key, sub_in, sub_ta))
    if use_fleet:
        # every subset ride-shares one dispatch too
        outs = solve_fleet(sub_items)
        for (key, sub_in, sub_ta), out in zip(sub_meta, outs):
            subset_accs[key] = accuracy_for_service(out[0], sub_ta, sub_in)
    else:
        for item, (key, sub_in, sub_ta) in zip(sub_items, sub_meta):
            algo = WeaverTPU(item.store.all_spans, item.store.all_processes)
            out = algo.FindAssignments(
                "MaxScoreBatchSubsetWithSkips", item.svc, sub_in,
                item.out_span_partitions, False, [], sub_ta, item.dag,
            )
            subset_accs[key] = accuracy_for_service(out[0], sub_ta, sub_in)
    log(f"child: subset pass {time.perf_counter() - t0:.1f}s")

    # --- Pallas kernel on-device proof (non-interpret) -------------------
    pallas_ok = None
    if backend in ("tpu", "axon"):
        try:
            import numpy as np

            from traceweaver_tpu.ops.pallas_sinkhorn import sinkhorn_log_pallas
            from traceweaver_tpu.ops.sinkhorn import sinkhorn_log

            rng = np.random.default_rng(0)
            S = rng.normal(size=(64, 128)).astype(np.float32)
            r = np.ones(64, np.float32)
            c = np.full(128, 0.5, np.float32)
            got = np.asarray(sinkhorn_log_pallas(S, r, c, epsilon=1.0,
                                                 n_iters=40, interpret=False))
            want = np.asarray(sinkhorn_log(S, r, c, epsilon=1.0, n_iters=40))
            pallas_ok = bool(np.allclose(got, want, rtol=2e-3, atol=2e-4))
            log(f"child: pallas on-device check ok={pallas_ok}")
        except Exception as e:  # lowering not supported on this plugin
            log(f"child: pallas on-device check failed: {type(e).__name__}: {e}")
            pallas_ok = False

    # Utilization. Peaks: TPU v5e ~197 TFLOP/s bf16 MXU (the headline
    # "MFU" denominator; this pipeline is f32/VPU-heavy, so its MFU is
    # structurally small) and ~819 GB/s HBM. With a parsed profile the
    # denominator is MEASURED device busy time from the trace; the
    # wall-clock estimate is kept for comparison.
    # summed per-thread wait_s overlaps in wall-clock under the thread
    # pool (each thread's wait includes the device serving its siblings),
    # so the wall-clock estimate denominator is capped at the timed pass
    device_s_wall = min(stage_stats.get("wait_s", 0.0) or solve_time,
                        solve_time)
    # "measured" metrics come ONLY from a trace with nonzero device busy
    # time; otherwise they are reported null rather than silently falling
    # back to wall-clock under a measured label
    busy_measured = (profile or {}).get("device_busy_s") or 0.0
    device_s = busy_measured if busy_measured > 0 else device_s_wall
    flops = stage_stats.get("flops_est", 0.0)
    bytes_key = ("bytes_est_pallas" if pallas_ok else "bytes_est_xla")
    peak_flops = 197e12 if backend in ("tpu", "axon") else 2e11
    peak_bw = 819e9 if backend in ("tpu", "axon") else 5e10
    report = {
        "backend": backend,
        "n_spans": n_spans,
        "n_services": len(flat),
        "solve_time_s": solve_time,
        "warmup_time_s": warmup_time,
        "compile_cache_warm": cache_warm,
        "spans_per_sec": n_spans / solve_time,
        "accuracy_mean": sum(accs.values()) / len(accs),
        "accuracy_per_service": {k: round(v, 4) for k, v in accs.items()},
        "subset_spans_per_service": SUBSET_SPANS,
        "subset_accuracy_per_service": {
            k: round(v, 4) for k, v in subset_accs.items()},
        "pallas_on_device_ok": pallas_ok,
        "stage_seconds": {
            k: round(stage_stats.get(k, 0.0), 3)
            for k in ("pack_s", "dispatch_s", "wait_s", "decode_s", "refit_s")
        },
        "fused_em_dispatches": int(stage_stats.get("fused_em_applied", 0)),
        "flops_est": flops,
        "device_busy_s_measured": (busy_measured if busy_measured > 0
                                   else None),
        "profile_top_ops": (profile or {}).get("top_ops"),
        "mfu_measured_pct": (
            round(100.0 * flops / busy_measured / peak_flops, 4)
            if busy_measured > 0 else None),
        "mfu_est_pct": round(100.0 * flops / max(device_s_wall, 1e-9)
                             / peak_flops, 4),
        "hbm_util_est_pct": round(
            100.0 * stage_stats.get(bytes_key, 0.0)
            / max(device_s, 1e-9) / peak_bw, 2),
    }
    with open(out_path, "w") as f:
        json.dump(report, f)
    log("child: report written")


# ---------------------------------------------------------------------------
# Combinatorial baseline child (no JAX backend at all)
# ---------------------------------------------------------------------------

def run_baseline_child(bundle_path: str, out_path: str) -> None:
    import signal

    # defensive: should any library path touch jnp, stay off the axon tunnel
    import jax

    jax.config.update("jax_platforms", "cpu")

    with open(bundle_path, "rb") as f:
        bundles = pickle.load(f)

    from traceweaver_tpu.algorithms.weaver_exact import WeaverExact
    from traceweaver_tpu.metrics import accuracy_for_service, get_ground_truth

    flat = [(label, svc, prob, ta, dag, store)
            for store, problems in bundles
            for label, svc, prob, ta, dag in problems]

    class _Timeout(Exception):
        pass

    def _alarm(_sig, _frm):
        raise _Timeout()

    signal.signal(signal.SIGALRM, _alarm)

    # --- leg 1: same-input subsets, uncapped (safety alarm only); a
    # service that trips the alarm at SUBSET_SPANS is retried at the
    # smaller SUBSET_RETRY so every service contributes a finished,
    # measured exact solve when at all feasible -------------------------
    subset = {}
    for label, svc, prob, ta, dag, store in flat:
        tried_sizes = set()
        for n in dict.fromkeys((SUBSET_SPANS, SUBSET_RETRY)):
            sub_in, sub_ta = subset_problem(prob, n)
            if len(next(iter(sub_in.values()))) in tried_sizes:
                continue  # shorter service: retry would be byte-identical
            tried_sizes.add(len(next(iter(sub_in.values()))))
            algo = WeaverExact(store.all_spans, store.all_processes)
            t0 = time.perf_counter()
            signal.alarm(EXACT_ALARM_SECONDS)
            try:
                out = algo.FindAssignments(
                    "MaxScoreBatch", svc, sub_in, prob.out_span_partitions,
                    False, [], sub_ta,
                )
                dt = time.perf_counter() - t0
                subset[label] = {
                    "finished": True,
                    "seconds": dt,
                    "n_spans": len(next(iter(sub_in.values()))),
                    "accuracy": accuracy_for_service(out[0], sub_ta, sub_in),
                }
                break
            except _Timeout:
                subset[label] = {"finished": False,
                                 "seconds": EXACT_ALARM_SECONDS,
                                 "n_spans": len(next(iter(sub_in.values()))),
                                 "accuracy": None}
            finally:
                signal.alarm(0)
        log(f"baseline: subset {label} "
            f"{'done' if subset[label]['finished'] else 'ALARM'} "
            f"(n={subset[label]['n_spans']}, "
            f"{subset[label]['seconds']:.1f}s)")

    # --- leg 2: legacy capped sweep (floor anchor for the ratio) --------
    deadline = time.perf_counter() + CPU_CAP_SECONDS
    per_service_cap = max(10, CPU_CAP_SECONDS // max(1, len(flat)))
    cpu_spans = 0
    cpu_time = 0.0
    accs = {}
    for label, svc, prob, ta, dag, store in flat:
        if time.perf_counter() > deadline:
            log("baseline: global cap hit, skipping remaining services")
            break
        in_ep = next(iter(prob.in_span_partitions))
        sub_in = {in_ep: prob.in_span_partitions[in_ep][:CPU_SUBSET_SPANS]}
        sub_ta = get_ground_truth(sub_in, prob.out_span_partitions)
        algo = WeaverExact(store.all_spans, store.all_processes)
        t0 = time.perf_counter()
        signal.alarm(per_service_cap)
        try:
            out = algo.FindAssignments(
                "MaxScoreBatch", svc, sub_in, prob.out_span_partitions,
                False, [], sub_ta,
            )
            accs[label] = accuracy_for_service(out[0], sub_ta, sub_in)
        except _Timeout:
            accs[label] = None  # did not finish the subset within the cap
        finally:
            signal.alarm(0)
        cpu_time += time.perf_counter() - t0
        cpu_spans += len(sub_in[in_ep])
        log(f"baseline: capped {label} done ({cpu_time:.1f}s cumulative)")

    vals = [v for v in accs.values() if v is not None]
    fin = [v for v in subset.values() if v["finished"]]
    report = {
        "subset": subset,
        "subset_spans_total": sum(v["n_spans"] for v in fin),
        "subset_time_total_s": sum(v["seconds"] for v in fin),
        "subset_spans_per_sec": (
            sum(v["n_spans"] for v in fin) / sum(v["seconds"] for v in fin)
            if fin else None),
        "capped_spans": cpu_spans,
        "capped_time_s": cpu_time,
        "spans_per_sec_upper_bound": cpu_spans / cpu_time if cpu_time else None,
        "accuracy_mean_subset": sum(vals) / len(vals) if vals else None,
    }
    with open(out_path, "w") as f:
        json.dump(report, f)
    log("baseline: report written")


# ---------------------------------------------------------------------------
# Parent orchestration
# ---------------------------------------------------------------------------

def _spawn(mode: str, bundle: str, out: str, backend: str | None,
           extra_env: dict | None = None) -> subprocess.Popen:
    env = dict(os.environ)
    if backend is not None:
        env["JAX_PLATFORMS"] = backend
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, os.path.join(HERE, "bench.py"), "--mode", mode,
         "--bundle", bundle, "--out", out],
        cwd=HERE, env=env, stdout=sys.stderr, stderr=sys.stderr,
    )


def main() -> None:
    log("parent: building problems (no JAX backend init)")
    bundles = build_problems()
    tmpdir = tempfile.mkdtemp(prefix="tw_bench_")
    bundle = os.path.join(tmpdir, "bundle.pkl")
    with open(bundle, "wb") as f:
        pickle.dump(bundles, f, protocol=pickle.HIGHEST_PROTOCOL)
    n_services = sum(len(p) for _, p in bundles)
    log(f"parent: bundle pickled ({os.path.getsize(bundle) >> 20} MB, "
        f"{n_services} services)")

    base_out = os.path.join(tmpdir, "baseline.json")
    solver_out = os.path.join(tmpdir, "solver.json")

    solver = None
    tried = []
    default_backend = os.environ.get("JAX_PLATFORMS", "axon") or "axon"
    for backend, timeout in ((default_backend, TPU_TIMEOUT),
                             ("cpu", CPU_TIMEOUT)):
        if backend == "cpu" and default_backend == "cpu" and tried:
            break
        log(f"parent: solver child on backend={backend} (timeout {timeout}s)")
        proc = _spawn("solver", bundle, solver_out, backend=backend)
        try:
            rc = proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            log(f"parent: solver child on {backend} timed out — killing")
            proc.kill()
            proc.wait()
            rc = -9
        tried.append(backend)
        if rc == 0 and os.path.exists(solver_out):
            with open(solver_out) as f:
                solver = json.load(f)
            break
        log(f"parent: solver child on {backend} failed (rc={rc})")

    # baseline runs AFTER the solver measurement so neither side's timing
    # is taken under host-CPU contention
    log("parent: baseline child (sequential, no contention)")
    base_proc = _spawn("baseline", bundle, base_out, backend="cpu")
    try:
        base_proc.wait(timeout=n_services * 2 * EXACT_ALARM_SECONDS
                       + CPU_CAP_SECONDS + 240)
    except subprocess.TimeoutExpired:
        base_proc.kill()
        base_proc.wait()
    baseline = None
    if os.path.exists(base_out):
        with open(base_out) as f:
            baseline = json.load(f)

    if solver is None:
        # still emit a parseable line so the round records *something*
        print(json.dumps({
            "metric": "span_assignment_throughput_hotel+media_load150_x10",
            "value": 0.0,
            "unit": "spans/sec",
            "vs_baseline": 0.0,
            "error": f"no solver child completed (tried {tried})",
        }))
        return

    # apples-to-apples accuracy delta on identical inputs (finished
    # services only; unfinished exact solves can't be compared)
    delta = None
    subset_pairs = {}
    if baseline:
        tpu_sub = solver.get("subset_accuracy_per_service", {})
        diffs = []
        for label, rec in baseline.get("subset", {}).items():
            key = f"{label}@{rec['n_spans']}"
            if rec["finished"] and key in tpu_sub:
                diffs.append(tpu_sub[key] - rec["accuracy"])
                subset_pairs[label] = {
                    "n_spans": rec["n_spans"],
                    "tpu": tpu_sub[key],
                    "exact": round(rec["accuracy"], 4),
                    "exact_seconds": round(rec["seconds"], 2),
                }
        if diffs:
            delta = sum(diffs) / len(diffs)

    base_sps = (baseline or {}).get("spans_per_sec_upper_bound")
    exact_sps = (baseline or {}).get("subset_spans_per_sec")
    # headline ratio: prefer the MEASURED uncapped exact-path speed on the
    # same inputs; fall back to the capped upper bound (a floor)
    ratio_base = exact_sps or base_sps
    result = {
        "metric": "span_assignment_throughput_hotel+media_load150_x10",
        "value": round(solver["spans_per_sec"], 1),
        "unit": "spans/sec",
        "vs_baseline": (round(solver["spans_per_sec"] / ratio_base, 1)
                        if ratio_base else None),
        "backend": solver["backend"],
        "n_spans": solver["n_spans"],
        "n_services": solver.get("n_services"),
        "solve_time_s": round(solver["solve_time_s"], 2),
        "warmup_compile_s": round(solver["warmup_time_s"], 2),
        "compile_cache_warm": solver.get("compile_cache_warm"),
        "accuracy_tpu": round(solver["accuracy_mean"], 4),
        "accuracy_delta_same_inputs": (round(delta, 4)
                                       if delta is not None else None),
        "subset_same_inputs": subset_pairs,
        "exact_spans_per_sec_same_inputs": (round(exact_sps, 3)
                                            if exact_sps else None),
        "baseline_spans_per_sec_capped_upper_bound": (round(base_sps, 2)
                                                      if base_sps else None),
        "pallas_on_device_ok": solver.get("pallas_on_device_ok"),
        "stage_seconds": solver.get("stage_seconds"),
        "fused_em_dispatches": solver.get("fused_em_dispatches"),
        "device_busy_s_measured": solver.get("device_busy_s_measured"),
        "mfu_measured_pct": solver.get("mfu_measured_pct"),
        "mfu_est_pct": solver.get("mfu_est_pct"),
        "hbm_util_est_pct": solver.get("hbm_util_est_pct"),
        "profile_top_ops": solver.get("profile_top_ops"),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["parent", "solver", "baseline"],
                    default="parent")
    ap.add_argument("--bundle")
    ap.add_argument("--out")
    args = ap.parse_args()
    if args.mode == "solver":
        run_solver_child(args.bundle, args.out)
    elif args.mode == "baseline":
        run_baseline_child(args.bundle, args.out)
    else:
        main()
