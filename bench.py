"""Benchmark: TPU Sinkhorn reconstruction throughput vs the CPU oracle.

Workload: hotel_reservation @ load150 (1000 recorded traces), arrivals
compressed 10x (reference ``repeat_change_spans`` semantics,
transforms.py:10-40) — the high-interleave regime the reference's Alibaba
scale sweep (exp5) stresses, where DFS candidate enumeration blows up
combinatorially. Both solvers reconstruct the same per-service assignment
problems end-to-end (pack → solve → decode → accuracy):

- TPU path:  WeaverTPU (windowed masked Sinkhorn, flagship), full corpus
- baseline:  WeaverExact "MaxScoreBatch" — the reference's DFS top-K +
             windowed exact-MWIS combinatorial path (Gurobi stand-in),
             timed on a per-service subset with a hard wall-clock cap.
             A service that exceeds the cap is credited its subset size
             over the cap time — an upper bound on its true speed, which
             *understates* the reported ratio.

Prints ONE JSON line with the TPU spans/sec and the vs-baseline ratio.
"""

from __future__ import annotations

import json
import signal
import time

DATA = "/root/reference/data/hotel_reservation/hotel_load150"
COMPRESS = 10.0
CPU_SUBSET_SPANS = 30
CPU_CAP_SECONDS = 60


class _Timeout(Exception):
    pass


def _alarm(_sig, _frm):
    raise _Timeout()


def main() -> None:
    from traceweaver_tpu.algorithms.weaver_exact import WeaverExact
    from traceweaver_tpu.algorithms.weaver_tpu import WeaverTPU
    from traceweaver_tpu.ingest import (
        build_service_problem,
        infer_invocation_dag,
        load_corpus,
    )
    from traceweaver_tpu.metrics import accuracy_for_service, get_ground_truth
    from traceweaver_tpu.synth import compress_spans

    store = load_corpus(DATA, fix=2, max_traces=1000, cache=True)

    problems = []
    for svc in store.out_spans_by_process:
        prob = build_service_problem(store, svc)
        if prob.skipped:
            continue
        ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
        dag = infer_invocation_dag(
            prob.in_span_partitions, prob.out_span_partitions, ta, store
        )
        compress_spans(prob.in_span_partitions, prob.out_span_partitions,
                       1, COMPRESS)
        ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
        problems.append((svc, prob, ta, dag))

    # ---- TPU path (warm-up compile, then timed full pass) ---------------
    def tpu_pass():
        preds = {}
        for svc, prob, ta, dag in problems:
            algo = WeaverTPU(store.all_spans, store.all_processes)
            out = algo.FindAssignments(
                "MaxScoreBatchSubsetWithSkips", svc,
                prob.in_span_partitions, prob.out_span_partitions,
                False, [], ta, dag,
            )
            preds[svc] = out[0]
        return preds

    tpu_pass()  # compile warm-up (cached afterwards)
    t0 = time.perf_counter()
    tpu_preds = tpu_pass()
    tpu_time = time.perf_counter() - t0
    n_spans = sum(
        len(next(iter(prob.in_span_partitions.values())))
        for _, prob, _, _ in problems
    )
    tpu_sps = n_spans / tpu_time
    acc_tpu = {
        svc: accuracy_for_service(tpu_preds[svc], ta, prob.in_span_partitions)
        for svc, prob, ta, _ in problems
    }

    # ---- CPU combinatorial baseline on capped subsets -------------------
    signal.signal(signal.SIGALRM, _alarm)
    cpu_spans = 0
    cpu_time = 0.0
    acc_cpu = {}
    for svc, prob, ta, dag in problems:
        in_ep = next(iter(prob.in_span_partitions))
        sub_in = {in_ep: prob.in_span_partitions[in_ep][:CPU_SUBSET_SPANS]}
        sub_ta = get_ground_truth(sub_in, prob.out_span_partitions)
        algo = WeaverExact(store.all_spans, store.all_processes)
        t0 = time.perf_counter()
        signal.alarm(CPU_CAP_SECONDS)
        try:
            out = algo.FindAssignments(
                "MaxScoreBatch", svc, sub_in, prob.out_span_partitions,
                False, [], sub_ta,
            )
            acc_cpu[svc] = accuracy_for_service(out[0], sub_ta, sub_in)
        except _Timeout:
            acc_cpu[svc] = None  # did not finish the subset within the cap
        finally:
            signal.alarm(0)
        cpu_time += time.perf_counter() - t0
        cpu_spans += len(sub_in[in_ep])
    cpu_sps = cpu_spans / cpu_time  # upper bound where capped

    def mean(d):
        vals = [v for v in d.values() if v is not None]
        return round(sum(vals) / len(vals), 4) if vals else None

    print(json.dumps({
        "metric": "span_assignment_throughput_hotel_load150_x10_interleave",
        "value": round(tpu_sps, 1),
        "unit": "spans/sec",
        "vs_baseline": round(tpu_sps / cpu_sps, 1),
        "baseline_spans_per_sec_upper_bound": round(cpu_sps, 2),
        "accuracy_tpu": mean(acc_tpu),
        "accuracy_baseline_subset": mean(acc_cpu),
        "n_spans": n_spans,
    }))


if __name__ == "__main__":
    main()
