"""Measured before/after for the score-build reformulations (VERDICT r4 #2).

Times the flagship device solve on the bench workload's heaviest service
(hotel/frontend, load150, compress x10 — the reference hot loop's home,
traceweaver_v1.py:117-148) under three score-build configurations:

- ``full``    — every endpoint's score matrix sums masked mixture blocks
                over ALL E endpoints (the round-4 codegen: O(E^2) [W,M,K]
                blocks per sweep);
- ``bounded`` — the production path: per-endpoint gathers over the DAG's
                real neighbours only (max in/out degree, power-of-two
                bucketed);
- ``gemm``    — ``bounded`` plus TW_SCORE_GEMM=1: mixture logits via the
                quadratic-feature matmul (ops/scores.py
                ``mixture_logpdf_gemm``).

Each configuration runs in its OWN subprocess (the GEMM flag is read at
import; jit caches must not leak between configs). Two timed passes per
config: cold (compile + solve) and warm (solve only); the warm pass is
the comparable number. Prints one JSON line per config and a summary.

Usage: ``python utils/score_roofline.py``  (parent; runs all three)
       ``python utils/score_roofline.py --config bounded``  (one child)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DATA = "/root/reference/data/hotel_reservation/hotel_load150"
COMPRESS = 10.0


def run_child(config: str) -> None:
    import jax

    from traceweaver_tpu.runtime import knobs as _knobs

    if _knobs.get("TW_ROOFLINE_BACKEND") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import traceweaver_tpu.algorithms.weaver_tpu as wt
    from traceweaver_tpu.ingest import (
        build_service_problem, infer_invocation_dag, load_corpus,
    )
    from traceweaver_tpu.metrics import get_ground_truth
    from traceweaver_tpu.synth import compress_spans

    store = load_corpus(DATA, fix=2, max_traces=1000, cache=True)
    prob = build_service_problem(store, "frontend")
    ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)
    dag = infer_invocation_dag(prob.in_span_partitions,
                               prob.out_span_partitions, ta, store)
    compress_spans(prob.in_span_partitions, prob.out_span_partitions,
                   1, COMPRESS)
    ta = get_ground_truth(prob.in_span_partitions, prob.out_span_partitions)

    if config == "full":
        # monkeypatch the neighbour bounds off: every pack/solve falls
        # back to n_pred = n_succ = E (the round-4 codegen)
        orig = wt._solve_windows_impl

        def unbounded(*args, **kw):
            kw["max_preds"] = 0
            kw["max_succs"] = 0
            return orig(*args, **kw)

        wt._solve_windows_impl = unbounded

    def solve():
        algo = wt.WeaverTPU(store.all_spans, store.all_processes)
        import copy
        out = algo.FindAssignments(
            "MaxScoreBatchSubsetWithSkips", "frontend",
            copy.deepcopy(prob.in_span_partitions),
            copy.deepcopy(prob.out_span_partitions), False, [],
            copy.deepcopy(ta), dag)
        return algo.stats, out

    t0 = time.perf_counter()
    stats_cold, out_cold = solve()
    cold_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    stats_warm, out_warm = solve()
    warm_s = time.perf_counter() - t0

    from traceweaver_tpu.metrics import accuracy_for_service
    import copy as _copy
    acc = accuracy_for_service(out_warm[0], _copy.deepcopy(ta),
                               prob.in_span_partitions)
    print(json.dumps({
        "config": config,
        "backend": jax.default_backend(),
        "cold_s": round(cold_s, 2),
        "warm_s": round(warm_s, 2),
        "warm_dispatch_wait_s": round(
            stats_warm.get("dispatch_s", 0.0) + stats_warm.get("wait_s", 0.0),
            2),
        "accuracy": round(acc, 4),
        "flops_est": stats_warm.get("flops_est"),
    }), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None,
                    choices=["full", "bounded", "gemm"])
    args = ap.parse_args()
    if args.config:
        run_child(args.config)
        return
    results = []
    for config in ("full", "bounded", "gemm"):
        env = dict(os.environ)
        if config == "gemm":
            env["TW_SCORE_GEMM"] = "1"
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--config", config],
            capture_output=True, text=True, env=env)
        line = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
        if line:
            results.append(json.loads(line[-1]))
            print(line[-1], flush=True)
        else:
            print(json.dumps({"config": config, "error": r.stderr[-500:]}),
                  flush=True)
    if len(results) == 3:
        by = {r["config"]: r for r in results}
        print(json.dumps({
            "summary": "warm seconds full -> bounded -> gemm",
            "full_s": by["full"]["warm_s"],
            "bounded_s": by["bounded"]["warm_s"],
            "gemm_s": by["gemm"]["warm_s"],
            "bounded_speedup_vs_full": round(
                by["full"]["warm_s"] / by["bounded"]["warm_s"], 2),
            "gemm_speedup_vs_bounded": round(
                by["bounded"]["warm_s"] / by["gemm"]["warm_s"], 2),
            # gemm is documented to differ numerically; the
            # bit-compatibility claim is full vs bounded only
            "accuracy_equal_full_vs_bounded": (
                by["full"]["accuracy"] == by["bounded"]["accuracy"]),
            "gemm_accuracy": by["gemm"]["accuracy"],
        }, indent=1), flush=True)


if __name__ == "__main__":
    main()
