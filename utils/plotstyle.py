"""Shared styling for the paper-figure plot scripts.

Clean-room reimplementation of the styling the reference's 7 plot scripts
share (reference utils/plot_*.py): consistent colors/markers, dotted grid,
inward ticks, PDF-friendly fonttype. Every script keeps the reference argv
contract: ``script.py results_dir test_name_suffix outfile``.
"""

from __future__ import annotations

import warnings

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402

warnings.filterwarnings("ignore")

PCOLORS = ["#000080", "#008000", "#990000", "#a5669f", "#db850d", "#00112d"]
MARKERS = ["s", "o", "x", "^", "v", "*", "p", "h"]
LIGHT_GREY = (0.5, 0.5, 0.5)
LABEL_FONTSIZE = 16

matplotlib.rcParams["pdf.fonttype"] = 42
matplotlib.rcParams["ps.fonttype"] = 42


def _style_axes(ax):
    ax.grid(linestyle=":", linewidth=1, color="grey")
    ax.tick_params(axis="both", direction="in", labelsize=LABEL_FONTSIZE)
    for side in ("top", "bottom", "left", "right"):
        ax.spines[side].set_color(LIGHT_GREY)
    ax.spines["top"].set_linestyle(":")
    ax.spines["right"].set_linestyle(":")


def plot_lines(xs, ys, labels, xlabel, ylabel, outfile,
               ylim=(0, 100), xlim=None):
    fig, ax = plt.subplots()
    for i, (x, y, label) in enumerate(zip(xs, ys, labels)):
        c = PCOLORS[i % len(PCOLORS)]
        ax.plot(x, y, "-", color=c, lw=2.5, marker=MARKERS[i % len(MARKERS)],
                mew=1.5, markersize=9, markeredgecolor=c, label=label,
                zorder=10, clip_on=False)
    ax.set_xlabel(xlabel, fontsize=LABEL_FONTSIZE)
    ax.set_ylabel(ylabel, fontsize=LABEL_FONTSIZE)
    if ylim:
        ax.set_ylim(*ylim)
    if xlim:
        ax.set_xlim(*xlim)
    _style_axes(ax)
    leg = ax.legend(loc="best", fontsize=LABEL_FONTSIZE - 3)
    leg.get_frame().set_linewidth(0.0)
    plt.tight_layout()
    plt.savefig(outfile)
    plt.close(fig)


def plot_grouped_boxes(ticks, ys, labels, xlabel, ylabel, outfile):
    """One box group per tick; ys[i] is a list (per tick) of sample lists."""
    fig, ax = plt.subplots()
    n = len(ys)
    group_width = n + 1.0
    for i, (series, label) in enumerate(zip(ys, labels)):
        c = PCOLORS[i % len(PCOLORS)]
        offset = (n - 1) / 2.0 - i
        positions = np.arange(len(series)) * group_width - offset * 0.8
        bp = ax.boxplot(series, positions=positions, sym="", widths=0.6)
        for part in ("boxes", "whiskers", "caps", "medians"):
            plt.setp(bp[part], color=c)
        ax.plot([], c=c, label=str(label))
    ax.set_xticks(np.arange(len(ticks)) * group_width)
    ax.set_xticklabels([str(t) for t in ticks])
    ax.set_xlabel(xlabel, fontsize=LABEL_FONTSIZE)
    ax.set_ylabel(ylabel, fontsize=LABEL_FONTSIZE)
    ax.set_ylim(0, 1.05)
    _style_axes(ax)
    leg = ax.legend(loc="best", fontsize=LABEL_FONTSIZE - 3)
    leg.get_frame().set_linewidth(0.0)
    plt.tight_layout()
    plt.savefig(outfile)
    plt.close(fig)


def plot_scatter(x, y, xlabel, ylabel, outfile):
    fig, ax = plt.subplots()
    ax.scatter(x, y, color=PCOLORS[0], s=28, zorder=10)
    ax.set_xlabel(xlabel, fontsize=LABEL_FONTSIZE)
    ax.set_ylabel(ylabel, fontsize=LABEL_FONTSIZE)
    _style_axes(ax)
    plt.tight_layout()
    plt.savefig(outfile)
    plt.close(fig)
