"""fig5 — ablation ladder vs load (hotel+media).

argv: results_dir test_name_suffix outfile (reference:
utils/plot_accuracy_vs_load_ablation_study.py tail).
"""

import pickle
import sys

import numpy as np

from plotstyle import plot_lines

results_directory, suffix, outfile = sys.argv[1], sys.argv[2], sys.argv[3]

METHODS = ["MaxScoreBatchSubsetWithSkipsTopK", "MaxScoreBatchSubsetWithSkips",
           "MaxScoreBatchParallel", "MaxScoreBatchParallelWithoutIterations",
           "MaxScore"]
LABELS = ["1: TraceWeaver w/ TopK", "2: TraceWeaver",
          "3: (2) w/o invocation order", "4: (3) w/o GMM iterations",
          "5: (4) w/o joint optimization"]
LOADS = [25, 50, 75, 100, 125, 150]
APPS = ["hotel", "media"]

xs, ys = [], []
for method in METHODS:
    x, y = [], []
    for load in LOADS:
        accs = []
        for app in APPS:
            path = (f"{results_directory}accuracy_{app}_{suffix}_{load}"
                    "_1_1_0.0.pickle")
            with open(path, "rb") as f:
                accs.append(pickle.load(f)[method])
        x.append(load * 100 / 150)
        y.append(float(np.mean(accs)))
    xs.append(x)
    ys.append(y)

plot_lines(xs, ys, LABELS, "System load %", "Accuracy % (avg. across apps)",
           outfile, ylim=(0, 100), xlim=(10, 100))
