"""fig4b — accuracy per response-time percentile bin, averaged over apps
and loads. argv: results_dir test_name_suffix outfile (reference:
utils/plot_accuracy_vs_response_times_multiple_apps.py tail).
"""

import pickle
import sys

import numpy as np

from plotstyle import plot_lines

results_directory, suffix, outfile = sys.argv[1], sys.argv[2], sys.argv[3]

METHODS = ["MaxScoreBatchSubsetWithSkipsTopK", "MaxScoreBatchSubsetWithSkips",
           "WAP5", "vPath", "FCFS"]
LABELS = ["TraceWeaver (Top K)", "TraceWeaver", "WAP5", "vPath", "FCFS"]
LOADS = [25, 50, 75, 100, 125, 150]
APPS = ["hotel", "media", "node"]

per_method = {}
for load in LOADS:
    for app in APPS:
        path = (f"{results_directory}bin_acc_{app}_{suffix}_{load}"
                "_1_1_0.0.pickle")
        with open(path, "rb") as f:
            bins = pickle.load(f)
        for method, acc in bins.items():
            bucket = per_method.setdefault(method, {})
            for percentile, a, _ms in acc:
                bucket.setdefault(percentile, []).append(a * 100)

xs, ys = [], []
for method in METHODS:
    percentiles = sorted(per_method[method])
    xs.append(percentiles)
    ys.append([float(np.mean(per_method[method][p])) for p in percentiles])

plot_lines(xs, ys, LABELS, "Latency Percentile Bins",
           "Accuracy % (avg. across apps)", outfile, ylim=(0, 100))
