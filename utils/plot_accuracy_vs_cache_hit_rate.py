"""fig4c — accuracy vs injected cache-hit rate (hotel@load150).

argv: results_dir test_name_suffix outfile (reference:
utils/plot_accuracy_vs_cache_hit_rate.py tail).
"""

import pickle
import sys

from plotstyle import plot_lines

results_directory, suffix, outfile = sys.argv[1], sys.argv[2], sys.argv[3]

METHODS = ["MaxScoreBatchSubsetWithSkips", "WAP5", "FCFS"]
LABELS = ["TraceWeaver", "WAP5", "FCFS"]
RATES = [0.0, 0.05, 0.1, 0.15, 0.2, 0.25, 0.3, 0.35,
         0.4, 0.45, 0.5, 0.55, 0.6, 0.65, 0.7]
LOAD = 150

xs, ys = [], []
for method in METHODS:
    x, y = [], []
    for j, rate in enumerate(RATES):
        path = (f"{results_directory}accuracy_{suffix}_{LOAD}_1_1_"
                f"{rate}.pickle")
        with open(path, "rb") as f:
            y.append(pickle.load(f)[method])
        x.append(j * 5)
    xs.append(x)
    ys.append(y)

plot_lines(xs, ys, LABELS, "Cache %", "Accuracy %", outfile, ylim=(0, 100))
