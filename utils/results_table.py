"""Render the executed exp results as a markdown table (README §Results).

Reads the accuracy pickles the experiment sweeps produce (same 5-family
naming as the reference, reference executor.py:1235-1244) and prints a
compact per-app end-to-end accuracy table plus the exp5 compress ladder.

Usage: python utils/results_table.py [exps_root]
"""

from __future__ import annotations

import glob
import os
import pickle
import sys
from collections import defaultdict

METHOD_ORDER = [
    "WAP5", "FCFS", "vPath", "vPathOld", "ArrivalOrder", "MaxScore",
    "MaxScoreBatch", "MaxScoreBatchParallel",
    "MaxScoreBatchParallelWithoutIterations",
    "MaxScoreBatchSubsetWithSkips",
]


def load_results(pattern):
    out = defaultdict(dict)  # (test, load_or_factor) -> {method: acc}
    for f in sorted(glob.glob(pattern)):
        name = os.path.basename(f).replace(".pickle", "")
        # accuracy_{test...}_{load}_{compress}_{repeat}_{cache}
        parts = name.split("_")
        cache = parts[-1]
        compress = parts[-3]
        load = parts[-4]
        test = "_".join(parts[1:-4])
        with open(f, "rb") as fh:
            d = pickle.load(fh)
        key = (test, load, compress, cache)
        for m, acc in d.items():
            out[key][m] = acc
    return out


def fmt_table(rows, header):
    lines = ["| " + " | ".join(header) + " |",
             "|" + "---|" * len(header)]
    for r in rows:
        lines.append("| " + " | ".join(str(c) for c in r) + " |")
    return "\n".join(lines)


def main(root="exps"):
    # exp1: accuracy vs load per app
    res = load_results(os.path.join(root, "exp1/results/accuracy_*.pickle"))
    if res:
        methods = [m for m in METHOD_ORDER
                   if any(m in v for v in res.values())]
        print("### exp1 — end-to-end accuracy vs load (fig4a)\n")
        for app in ("hotel", "node", "media"):
            keys = sorted((k for k in res if k[0].startswith(app)),
                          key=lambda k: int(k[1]))
            if not keys:
                continue
            rows = [[k[1]] + [f"{res[k].get(m, float('nan')):.1f}"
                              for m in methods] for k in keys]
            print(f"**{app}**\n")
            print(fmt_table(rows, ["load"] + methods))
            print()

    # exp2: accuracy vs cache rate
    res = load_results(os.path.join(root, "exp2/results/accuracy_*.pickle"))
    if res:
        methods = [m for m in METHOD_ORDER
                   if any(m in v for v in res.values())]
        keys = sorted(res, key=lambda k: float(k[3]))
        rows = [[k[3]] + [f"{res[k].get(m, float('nan')):.1f}"
                          for m in methods] for k in keys]
        print("### exp2 — accuracy vs cache-hit rate, hotel@150 (fig4c)\n")
        print(fmt_table(rows, ["cache"] + methods))
        print()

    # exp3: interleaving
    res = load_results(os.path.join(root, "exp3/results/accuracy_*.pickle"))
    if res:
        methods = [m for m in METHOD_ORDER
                   if any(m in v for v in res.values())]
        keys = sorted(res)
        rows = [[k[0]] + [f"{res[k].get(m, float('nan')):.1f}"
                          for m in methods] for k in keys]
        print("### exp3 — accuracy vs interleaving intensity (fig4d)\n")
        print(fmt_table(rows, ["dataset"] + methods))
        print()

    # exp4: ablation
    res = load_results(os.path.join(root, "exp4/results/accuracy_*.pickle"))
    if res:
        methods = sorted({m for v in res.values() for m in v})
        print("### exp4 — flagship ablation (fig5)\n")
        for app in ("hotel", "media"):
            keys = sorted((k for k in res if k[0].startswith(app)),
                          key=lambda k: int(k[1]))
            if not keys:
                continue
            rows = [[k[1]] + [f"{res[k].get(m, float('nan')):.1f}"
                              for m in methods] for k in keys]
            print(f"**{app}**\n")
            print(fmt_table(rows, ["load"] + methods))
            print()

    # exp5: compress ladder (mean over call graphs)
    res = load_results(os.path.join(root, "exp5/results/accuracy_*.pickle"))
    if res:
        methods = [m for m in METHOD_ORDER
                   if any(m in v for v in res.values())]
        by_factor = defaultdict(lambda: defaultdict(list))
        for k, v in res.items():
            for m, acc in v.items():
                by_factor[int(k[2])][m].append(acc)
        print("### exp5 — Alibaba scale: mean e2e accuracy over 15 call "
              "graphs vs compress factor (fig6a)\n")
        rows = []
        for f in sorted(by_factor):
            rows.append([f] + [
                f"{sum(by_factor[f][m]) / len(by_factor[f][m]):.1f}"
                if by_factor[f].get(m) else "—" for m in methods])
        print(fmt_table(rows, ["compress"] + methods))
        print()


if __name__ == "__main__":
    main(*sys.argv[1:])
