"""fig6a — accuracy distribution over 15 Alibaba call graphs per compress
factor (grouped boxplots). argv: results_dir test_name_suffix outfile
(reference: utils/plot_accuracy_vs_load_multiple_cgs.py tail).
"""

import pickle
import sys

from plotstyle import plot_grouped_boxes

results_directory, suffix, outfile = sys.argv[1], sys.argv[2], sys.argv[3]

METHODS = ["MaxScoreBatchSubsetWithSkipsTopK", "MaxScoreBatchSubsetWithSkips",
           "WAP5", "vPath", "FCFS"]
LABELS = ["TraceWeaver (Top K)", "TraceWeaver", "WAP5", "vPath", "FCFS"]
COMPRESS_LEVELS = [1, 200, 1000, 4000, 10000, 15000]
CALL_GRAPHS = list(range(15))

ys = []
for method in METHODS:
    series = []
    for compress in COMPRESS_LEVELS:
        samples = []
        for cg in CALL_GRAPHS:
            path = (f"{results_directory}accuracy_alibaba_cg_{cg}_{suffix}"
                    f"_1_{compress}_1_0.0.pickle")
            try:
                with open(path, "rb") as f:
                    samples.append(pickle.load(f)[method] / 100.0)
            except FileNotFoundError:
                continue
        series.append(samples)
    ys.append(series)

plot_grouped_boxes(COMPRESS_LEVELS, ys, LABELS, "Compress factor",
                   "Accuracy (over call graphs)", outfile)
