"""fig6b — per-service accuracy vs confidence score scatter at the highest
compress factor, with the Pearson correlation printed. argv: results_dir
test_name_suffix outfile (reference:
utils/plot_accuracy_vs_confidence_multiple_cgs.py tail). Confidence =
1 − not_best/num_spans (reference executor.py:1038-1039).
"""

import pickle
import sys

from scipy.stats import pearsonr

from plotstyle import plot_scatter

results_directory, suffix, outfile = sys.argv[1], sys.argv[2], sys.argv[3]

COMPRESS = 15000
CALL_GRAPHS = list(range(15))

combined = {}
for cg in CALL_GRAPHS:
    path = (f"{results_directory}confidence_scores_alibaba_cg_{cg}_{suffix}"
            f"_1_{COMPRESS}_1_0.0.pickle")
    try:
        with open(path, "rb") as f:
            scores = pickle.load(f)
    except FileNotFoundError:
        continue
    for process, values in scores.items():
        combined.setdefault(process, []).append(values)

x, y = [], []
for values in combined.values():
    for acc, not_best, num_spans in values:
        x.append(acc * 100)
        y.append((1 - not_best / num_spans) * 100)

plot_scatter(x, y, "Accuracy (%)", "Confidence Score", outfile)
if len(x) >= 2:
    print("Pearson coefficient:", pearsonr(x, y))
