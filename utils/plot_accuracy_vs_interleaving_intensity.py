"""fig4d — accuracy vs request-interleaving intensity (node app).

argv: results_dir test_name_suffix outfile (reference:
utils/plot_accuracy_vs_interleaving_intensity.py tail).
"""

import pickle
import sys

from plotstyle import plot_lines

results_directory, suffix, outfile = sys.argv[1], sys.argv[2], sys.argv[3]

METHODS = ["MaxScoreBatchSubsetWithSkips", "vPath"]
LABELS = ["TraceWeaver", "vPath"]
RATES = [0, 0.2, 0.4, 0.6, 0.8, 1]
LOAD = 50

xs, ys = [], []
for method in METHODS:
    y = []
    for rate in RATES:
        path = (f"{results_directory}accuracy_node_{rate}_{suffix}_{LOAD}"
                "_1_1_0.0.pickle")
        with open(path, "rb") as f:
            y.append(pickle.load(f)[method])
    xs.append(list(range(1, len(RATES) + 1)))
    ys.append(y)

plot_lines(xs, ys, LABELS, "Intensity Level of Request Interleaving",
           "Accuracy %", outfile, ylim=(0, 100))
