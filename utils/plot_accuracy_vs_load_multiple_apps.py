"""fig4a — end-to-end accuracy vs system load, averaged over 3 apps.

argv: results_dir test_name_suffix outfile (reference:
utils/plot_accuracy_vs_load_multiple_apps.py:75-96).
"""

import pickle
import sys

import numpy as np

from plotstyle import plot_lines

results_directory, suffix, outfile = sys.argv[1], sys.argv[2], sys.argv[3]

METHODS = ["MaxScoreBatchSubsetWithSkipsTopK", "MaxScoreBatchSubsetWithSkips",
           "WAP5", "vPath", "FCFS"]
LABELS = ["TraceWeaver (Top K)", "TraceWeaver", "WAP5", "vPath", "FCFS"]
LOADS = [25, 50, 75, 100, 125, 150]
APPS = ["hotel", "media", "node"]

xs, ys = [], []
for method in METHODS:
    x, y = [], []
    for load in LOADS:
        accs = []
        for app in APPS:
            path = (f"{results_directory}accuracy_{app}_{suffix}_{load}"
                    "_1_1_0.0.pickle")
            with open(path, "rb") as f:
                accs.append(pickle.load(f)[method])
        x.append(load * 100 / 150)
        y.append(float(np.mean(accs)))
    xs.append(x)
    ys.append(y)

plot_lines(xs, ys, LABELS, "System load %", "Accuracy % (avg. across apps)",
           outfile, ylim=(0, 100), xlim=(10, 100))
