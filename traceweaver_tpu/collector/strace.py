"""strace log reassembly into per-connection byte streams.

Port of the reference span-collector's offline strace parser
(reference: src/span_collector/http2_parser/parser.py:299-486): an
``strace -f`` log interleaves ``read``/``write``/``close`` syscalls from
many threads, including split ``<unfinished ...>`` / ``<... resumed>``
pairs. This module reassembles them into bidirectional per-(fd, iteration)
byte streams — an fd generation ends at ``close`` — while recording which
thread (pid) contributed every byte range, so HTTP/2 events recovered from
the streams can be attributed to threads
(:mod:`traceweaver_tpu.collector.threading_model`).

The nine line shapes handled mirror the reference's pattern1..pattern9
(parser.py:299-307), via a single tokenizer instead of nine regexes.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# One regex per syscall family, complete and split forms
# (reference parser.py:299-307 pattern1..pattern9). The optional ``ts``
# group accepts ``strace -ttt`` epoch timestamps (seconds.micro) right
# after the pid — the capture ingress (collector/source.py) needs real
# event times; logs recorded without ``-ttt`` still parse (ts=None, and
# the parser substitutes a deterministic line-sequence clock).
_PRE = r'^(?P<pid>\d+)\s+(?:(?P<ts>\d+\.\d+)\s+)?'
_RE_COMPLETE = re.compile(
    _PRE + r'(?P<op>read|write)\((?P<fd>\d+),\s*"(?P<data>(?:[^"\\]|\\.)*)"'
    r'(?:\.\.\.)?,\s*(?P<count>\d+)\)\s*=\s*(?P<ret>-?\d+)'
)
_RE_READ_UNFINISHED = re.compile(
    _PRE + r'read\((?P<fd>\d+),\s*<unfinished\s+\.+>'
)
_RE_READ_RESUMED = re.compile(
    _PRE + r'<\.+\s+read resumed>\s*"(?P<data>(?:[^"\\]|\\.)*)"'
    r'(?:\.\.\.)?,\s*(?P<count>\d+)\)\s*=\s*(?P<ret>-?\d+)'
)
_RE_WRITE_UNFINISHED = re.compile(
    _PRE + r'write\((?P<fd>\d+),\s*"(?P<data>(?:[^"\\]|\\.)*)"'
    r'(?:\.\.\.)?,\s*(?P<count>\d+)\s*<unfinished\s+\.+>'
)
_RE_WRITE_RESUMED = re.compile(
    _PRE + r'<\.+\s+write resumed>\s*\)\s*=\s*(?P<ret>-?\d+)'
)
_RE_CLOSE = re.compile(
    _PRE + r'close\((?P<fd>\d+)\)\s*=\s*(?P<ret>-?\d+)'
)
_RE_CLOSE_UNFINISHED = re.compile(
    _PRE + r'close\((?P<fd>\d+)\s*<unfinished\s+\.*>'
)
_RE_CLOSE_RESUMED = re.compile(
    _PRE + r'<\.*\s*close resumed>\s*\)\s*=\s*(?P<ret>-?\d+)'
)

_OCTAL = frozenset("01234567")


def unescape_strace(s: str) -> bytes:
    """Decode strace's C-style string escaping (octal by default, hex under
    ``strace -x``) into raw bytes."""
    out = bytearray()
    i = 0
    n = len(s)
    while i < n:
        c = s[i]
        if c != "\\":
            out.append(ord(c) & 0xFF)
            i += 1
            continue
        i += 1
        if i >= n:
            break
        e = s[i]
        if e == "x":
            j = i + 1
            hexdigits = ""
            while j < n and len(hexdigits) < 2 and s[j] in "0123456789abcdefABCDEF":
                hexdigits += s[j]
                j += 1
            out.append(int(hexdigits, 16) if hexdigits else ord("x"))
            i = j
        elif e in _OCTAL:
            j = i
            digits = ""
            while j < n and len(digits) < 3 and s[j] in _OCTAL:
                digits += s[j]
                j += 1
            out.append(int(digits, 8) & 0xFF)
            i = j
        else:
            out.append({
                "n": 10, "t": 9, "r": 13, "f": 12, "v": 11, "b": 8,
                "a": 7, "\\": 92, '"': 34, "'": 39, "0": 0,
            }.get(e, ord(e)))
            i += 1
    return bytes(out)


@dataclass
class ByteRange:
    """Attribution of one syscall's bytes within a direction stream."""

    pid: int
    start: int
    end: int
    seq: int  # global line order of the completing syscall
    # capture timestamp of the syscall (µs since epoch under strace
    # -ttt; the synthetic line-sequence clock otherwise) — the raw,
    # per-source clock the skew estimator corrects, never solver time
    ts_us: float = 0.0


@dataclass
class FdStream:
    """One fd generation (between opens/closes) with both directions."""

    fd: int
    iteration: int
    inbound: bytes = b""      # bytes the process read
    outbound: bytes = b""     # bytes the process wrote
    read_ranges: List[ByteRange] = field(default_factory=list)
    write_ranges: List[ByteRange] = field(default_factory=list)

    def pid_at(self, direction: str, offset: int) -> Optional[int]:
        """The thread that read/wrote the byte at ``offset``."""
        ranges = self.read_ranges if direction == "in" else self.write_ranges
        for r in ranges:
            if r.start <= offset < r.end:
                return r.pid
        return None

    def ts_at(self, direction: str, offset: int) -> Optional[float]:
        """Capture timestamp (µs, raw source clock) of the syscall that
        carried the byte at ``offset``; None when unattributed."""
        ranges = self.read_ranges if direction == "in" else self.write_ranges
        for r in ranges:
            if r.start <= offset < r.end:
                return r.ts_us
        return None


@dataclass
class _Pending:
    op: str
    fd: Optional[int]
    data: Optional[str] = None
    count: Optional[int] = None
    ts_us: Optional[float] = None


class StraceParser:
    """Streaming parser over strace log lines.

    Two optional hooks let a live consumer ride the parse incrementally
    (the capture ingress, :mod:`traceweaver_tpu.collector.source`):

    - ``payload_hook(key, direction, payload, ts_us) -> bool`` fires per
      completed read/write payload *before* it lands in the stream
      buffers; returning False discards the payload (the capture-loss
      fault site drops chunks here, so the buffers always match what the
      consumer actually saw);
    - ``close_hook(key)`` fires when an fd generation ends, so half-open
      exchanges can be closed out promptly instead of at end-of-log.
    """

    def __init__(self) -> None:
        self.streams: Dict[Tuple[int, int], FdStream] = {}
        self._iteration: Dict[int, int] = {}
        self._in_buf: Dict[Tuple[int, int], bytearray] = {}
        self._out_buf: Dict[Tuple[int, int], bytearray] = {}
        self._pending: Dict[int, _Pending] = {}  # per-pid outstanding call
        self._seq = 0
        self.unmatched_lines = 0
        self.payload_hook = None  # (key, dir, payload, ts_us) -> keep?
        self.close_hook = None    # (key) -> None
        self.saw_timestamps = False

    # -- helpers ----------------------------------------------------------

    def _key(self, fd: int) -> Tuple[int, int]:
        return (fd, self._iteration.get(fd, 0))

    def _stream(self, fd: int) -> Tuple[FdStream, bytearray, bytearray]:
        key = self._key(fd)
        if key not in self.streams:
            self.streams[key] = FdStream(fd=fd, iteration=key[1])
            self._in_buf[key] = bytearray()
            self._out_buf[key] = bytearray()
        return self.streams[key], self._in_buf[key], self._out_buf[key]

    def _record(self, pid: int, op: str, fd: int, data_str: str,
                ret: int, ts_us: Optional[float] = None) -> None:
        if ret <= 0:
            return
        stream, in_buf, out_buf = self._stream(fd)
        payload = unescape_strace(data_str)[:ret]
        if ts_us is None:
            # no -ttt stamps in this log: a deterministic line-sequence
            # clock (1 ms per line) keeps relative order meaningful
            ts_us = self._seq * 1000.0
        direction = "in" if op == "read" else "out"
        if self.payload_hook is not None and not self.payload_hook(
                self._key(fd), direction, payload, ts_us):
            return
        if op == "read":
            stream.read_ranges.append(
                ByteRange(pid, len(in_buf), len(in_buf) + len(payload),
                          self._seq, ts_us)
            )
            in_buf.extend(payload)
        else:
            stream.write_ranges.append(
                ByteRange(pid, len(out_buf), len(out_buf) + len(payload),
                          self._seq, ts_us)
            )
            out_buf.extend(payload)

    def _close(self, fd: int) -> None:
        key = self._key(fd)
        if key in self.streams:
            self._iteration[fd] = key[1] + 1
            if self.close_hook is not None:
                self.close_hook(key)

    # -- line handling ----------------------------------------------------

    def _ts(self, m) -> Optional[float]:
        raw = m.groupdict().get("ts")
        if not raw:
            return None
        self.saw_timestamps = True
        return float(raw) * 1e6

    def feed_line(self, line: str) -> None:
        self._seq += 1
        line = line.strip()
        if not line:
            return

        m = _RE_COMPLETE.match(line)
        if m:
            self._record(int(m["pid"]), m["op"], int(m["fd"]), m["data"],
                         int(m["ret"]), ts_us=self._ts(m))
            return
        m = _RE_READ_UNFINISHED.match(line)
        if m:
            self._pending[int(m["pid"])] = _Pending("read", int(m["fd"]))
            return
        m = _RE_READ_RESUMED.match(line)
        if m:
            pending = self._pending.pop(int(m["pid"]), None)
            if pending is not None and pending.op == "read":
                # reads stamp at the RESUMED line: that is when the data
                # actually arrived in the process
                self._record(int(m["pid"]), "read", pending.fd, m["data"],
                             int(m["ret"]), ts_us=self._ts(m))
            return
        m = _RE_WRITE_UNFINISHED.match(line)
        if m:
            # writes stamp at the UNFINISHED line: the payload was
            # submitted (and visible on the wire) before the call blocked
            self._pending[int(m["pid"])] = _Pending(
                "write", int(m["fd"]), m["data"], int(m["count"]),
                ts_us=self._ts(m)
            )
            return
        m = _RE_WRITE_RESUMED.match(line)
        if m:
            pending = self._pending.pop(int(m["pid"]), None)
            if pending is not None and pending.op == "write":
                self._record(int(m["pid"]), "write", pending.fd,
                             pending.data, int(m["ret"]),
                             ts_us=pending.ts_us)
            return
        m = _RE_CLOSE.match(line)
        if m:
            self._close(int(m["fd"]))
            return
        m = _RE_CLOSE_UNFINISHED.match(line)
        if m:
            self._pending[int(m["pid"])] = _Pending("close", int(m["fd"]))
            return
        m = _RE_CLOSE_RESUMED.match(line)
        if m:
            pending = self._pending.pop(int(m["pid"]), None)
            if pending is not None and pending.op == "close":
                self._close(pending.fd)
            return
        self.unmatched_lines += 1

    def finish(self) -> Dict[Tuple[int, int], FdStream]:
        """Freeze buffers into the stream objects and return them."""
        for key, stream in self.streams.items():
            stream.inbound = bytes(self._in_buf[key])
            stream.outbound = bytes(self._out_buf[key])
        return self.streams


def parse_strace_log(text: str) -> Dict[Tuple[int, int], FdStream]:
    """Parse a whole ``strace -f`` log into per-(fd, iteration) streams."""
    parser = StraceParser()
    for line in text.splitlines():
        parser.feed_line(line)
    return parser.finish()
