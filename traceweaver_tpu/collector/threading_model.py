"""Request→thread attribution over recovered HTTP/2 events.

Port of the reference prototype's final analysis stages
(reference: src/span_collector/http2_parser/parser.py:44-68 —
``map_request_to_thread`` via tracing headers — and :543-579, a logistic
regression predicting the downstream-request thread from a one-hot
encoding of the upstream thread): given per-connection event streams with
byte-level thread attribution (from :mod:`.strace`), join incoming
requests to the outgoing requests they caused using propagated tracing
headers (``uber-trace-id``, ``x-request-id``, ``x-b3-*``), then test how
predictable the handling thread is.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from traceweaver_tpu.collector.http2 import Event
from traceweaver_tpu.collector.strace import FdStream

# Headers that propagate request identity (reference parser.py:44-68).
TRACE_HEADERS = (
    "uber-trace-id",
    "x-request-id",
    "x-b3-traceid",
    "x-b3-spanid",
    "x-b3-parentspanid",
)


def request_key(headers: List[Tuple[str, str]]) -> Optional[str]:
    """A stable request identity from tracing headers. ``uber-trace-id``
    carries ``trace:span:parent:flags`` — the trace id joins a service's
    incoming request with the outgoing calls it makes."""
    h = {name.lower(): value for name, value in headers}
    uber = h.get("uber-trace-id")
    if uber:
        return uber.split(":")[0]
    b3 = h.get("x-b3-traceid")
    if b3:
        return b3
    return h.get("x-request-id")


@dataclass
class AttributedRequest:
    """One request event attributed to the thread that carried its bytes."""

    key: Optional[str]
    stream_id: int
    fd: int
    iteration: int
    direction: str          # "in" = received by the process, "out" = sent
    pid: Optional[int]
    headers: List[Tuple[str, str]]
    seq: int                # capture order of the first byte


def attribute_requests(
    streams: Dict[Tuple[int, int], "FdStream"],
    events_by_stream: Dict[Tuple[int, int], Tuple[List[Event], List[Event]]],
) -> List[AttributedRequest]:
    """Join request events back to the pids that read/wrote their frames."""
    out: List[AttributedRequest] = []
    for key, (in_events, out_events) in events_by_stream.items():
        stream = streams[key]
        for direction, events in (("in", in_events), ("out", out_events)):
            ranges = (stream.read_ranges if direction == "in"
                      else stream.write_ranges)
            for ev in events:
                if ev.kind != "request":
                    continue
                pid = stream.pid_at(direction, ev.offset)
                seq = 0
                for r in ranges:
                    if r.start <= ev.offset < r.end:
                        seq = r.seq
                        break
                out.append(AttributedRequest(
                    key=request_key(ev.headers),
                    stream_id=ev.stream_id,
                    fd=stream.fd,
                    iteration=stream.iteration,
                    direction=direction,
                    pid=pid,
                    headers=ev.headers,
                    seq=seq,
                ))
    return out


def join_causal_pairs(
    requests: List[AttributedRequest],
) -> List[Tuple[AttributedRequest, AttributedRequest]]:
    """Pair each incoming request with the outgoing requests sharing its
    tracing identity — the capture-side analogue of the reconstruction
    problem (here the join key is observed, not inferred)."""
    incoming: Dict[str, List[AttributedRequest]] = {}
    for req in requests:
        if req.direction == "in" and req.key:
            incoming.setdefault(req.key, []).append(req)
    pairs = []
    for req in requests:
        if req.direction != "out" or not req.key:
            continue
        for parent in incoming.get(req.key, []):
            pairs.append((parent, req))
    return pairs


def thread_predictability(
    pairs: List[Tuple[AttributedRequest, AttributedRequest]],
) -> Optional[float]:
    """Reference parser.py:543-579: fit a logistic regression predicting the
    downstream (outgoing) thread from a one-hot of the upstream (incoming)
    thread; returns training accuracy, or None with too little data. A high
    score means thread identity alone links requests across a service —
    the hypothesis the vPath baseline encodes."""
    import numpy as np

    data = [(p.pid, c.pid) for p, c in pairs
            if p.pid is not None and c.pid is not None]
    if len(data) < 2:
        return None
    up = sorted({u for u, _ in data})
    down = sorted({d for _, d in data})
    if len(down) == 1:
        return 1.0
    up_idx = {u: i for i, u in enumerate(up)}
    down_idx = {d: i for i, d in enumerate(down)}
    X = np.zeros((len(data), len(up)))
    y = np.zeros(len(data), dtype=int)
    for i, (u, d) in enumerate(data):
        X[i, up_idx[u]] = 1.0
        y[i] = down_idx[d]
    from sklearn.linear_model import LogisticRegression

    model = LogisticRegression(max_iter=1000)
    model.fit(X, y)
    return float(model.score(X, y))
