"""Live strace attach runner: follow a process by name, attaching strace to
every new PID as it appears.

The offline pipeline (:mod:`traceweaver_tpu.collector.strace` +
:mod:`traceweaver_tpu.collector.http2`) replays logs this runner captures.
Python port of the reference's polling shell loop
(reference: src/span_collector/http2_parser/strace_runner.sh:11-26), which
busy-polls ``pgrep <name>`` and attaches
``strace -f -p <pid> -v -s 65536 -o output<tag>-attempt<i>.log`` once per
newly seen PID. Differences from the shell script, all deliberate:

- every PID returned by ``pgrep`` is attached (the script races: it re-runs
  ``pgrep`` for the attach and only ever handles the first match);
- the poll sleeps instead of spinning;
- bounded by ``--duration`` / ``--max-attempts`` so it can be supervised
  (and tested) instead of running forever.

Usage::

    python -m traceweaver_tpu.collector.strace_runner search \
        --out-dir /tmp/straces --duration 60
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import time
from typing import Dict, List, Optional


def pgrep(name: str) -> List[int]:
    """PIDs whose command matches ``name`` (pgrep semantics)."""
    proc = subprocess.run(["pgrep", name], capture_output=True, text=True)
    if proc.returncode != 0:
        return []
    return [int(line) for line in proc.stdout.split() if line.strip()]


def attach_strace(pid: int, out_path: str,
                  string_limit: int = 65536) -> subprocess.Popen:
    """Attach ``strace -f -v`` to a live PID, logging to ``out_path``
    (same flags as strace_runner.sh:24)."""
    return subprocess.Popen(
        ["strace", "-f", "-p", str(pid), "-v", "-s", str(string_limit),
         "-o", out_path],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def run(process_name: str, out_dir: str = ".", tag: str = "0",
        duration: Optional[float] = None, poll_interval: float = 0.2,
        max_attempts: Optional[int] = None) -> Dict[int, str]:
    """Poll for PIDs of ``process_name``; attach strace to each new one.

    Returns {pid: log_path} for every attachment made. Runs until
    ``duration`` seconds elapse (forever when None, like the reference
    loop) or ``max_attempts`` attachments happened.
    """
    if shutil.which("strace") is None:
        raise RuntimeError("strace binary not available on this host")
    os.makedirs(out_dir, exist_ok=True)
    seen: Dict[int, str] = {}
    procs: List[subprocess.Popen] = []
    deadline = None if duration is None else time.monotonic() + duration
    attempt = 0
    try:
        while deadline is None or time.monotonic() < deadline:
            if max_attempts is None or attempt < max_attempts:
                for pid in pgrep(process_name):
                    if pid in seen:
                        continue
                    attempt += 1
                    log = os.path.join(
                        out_dir, f"output{tag}-attempt{attempt}.log")
                    try:
                        procs.append(attach_strace(pid, log))
                    except OSError as e:
                        print(f"attach to {pid} failed: {e}", file=sys.stderr)
                        continue
                    seen[pid] = log
                    print(f"Running for new pid {pid} -> {log}",
                          file=sys.stderr)
                    if max_attempts is not None and attempt >= max_attempts:
                        break
            elif deadline is None:
                # attach cap reached and no capture window requested:
                # returning here (not earlier) keeps in-flight captures
                # alive for the whole requested duration otherwise
                break
            time.sleep(poll_interval)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
    return seen


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("process_name", help="process name to follow (pgrep)")
    ap.add_argument("--out-dir", default=".")
    ap.add_argument("--tag", default="0",
                    help="log name tag (strace_runner.sh $1)")
    ap.add_argument("--duration", type=float, default=None,
                    help="stop after this many seconds (default: run forever)")
    ap.add_argument("--poll-interval", type=float, default=0.2)
    ap.add_argument("--max-attempts", type=int, default=None)
    args = ap.parse_args(argv)
    seen = run(args.process_name, out_dir=args.out_dir, tag=args.tag,
               duration=args.duration, poll_interval=args.poll_interval,
               max_attempts=args.max_attempts)
    print(f"attached to {len(seen)} pid(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
