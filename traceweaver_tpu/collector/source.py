"""Capture ingress: the collector → stream bridge (docs/COLLECTOR.md).

Closes the capture-to-trace loop (ROADMAP item 5): recorded ``strace``
logs (or replayed eBPF event streams) from *uninstrumented* processes
run through the offline collector pipeline — syscall reassembly
(:mod:`.strace`), HTTP/2+HPACK replay (:mod:`.http2`) — incrementally,
and every completed request/response exchange becomes one timed span
event the streaming reconstructor consumes
(:class:`~traceweaver_tpu.stream.sources.SpanEvent`). The stream CLI
reaches it as ``--source collector:<path|fifo>``; the serve layer as
``POST /api/v1/tenants/<id>/capture``.

Real capture is an adversarial input regime, and this module is the
hardening front-end between capture and windowing:

- **Clock skew** (:mod:`.skew`): every capture source (host) has its own
  clock; a per-source offset is fitted from cross-source request/response
  exchange pairs (NTP-style, median per edge) and subtracted from every
  timestamp *before* watermarking — skewed clocks otherwise break the
  parent⊇child containment the candidate enumeration assumes. The fitted
  offset is exported as ``tw_clock_skew_us{source}`` and each fit lands a
  ``clock_skew`` event.
- **Partial capture**: half-open exchanges (request observed, response
  lost), truncated frames, interrupted CONTINUATION sequences, and HPACK
  decode failures are counted per source in
  ``tw_capture_loss_total{source,reason}`` and handled under the
  ``TW_COLLECTOR_PARTIAL`` policy — ``synthetic`` closes a half-open
  exchange out as a counted synthetic span at the last observed activity;
  ``deadletter`` drops it with accounting. The observed loss rate
  discounts every emitted trace's confidence downstream
  (``stream/service.py``, the PR 10 quality path).
- **Connection churn**: an fd reused (or a peer reconnecting) without an
  observed ``close`` re-keys mid-capture — a fresh HTTP/2 preface on a
  connection that already carried bytes starts a NEW logical connection
  (counted in ``tw_capture_rekeyed_total``); exchanges stranded on the
  old one are closed out per the partial policy. Open exchanges awaiting
  their response live in a bounded per-source orphan buffer
  (``TW_COLLECTOR_ORPHANS``); past the bound the oldest is evicted and
  counted.

Chaos sites (``runtime/faults.py``): ``capture`` drops payload chunks
(and the remainder of that connection direction — an HTTP/2 byte stream
cannot be resynchronized after a gap); ``skew`` offsets a drawn source's
raw clock by ``TW_SKEW_CHAOS_US``, the stimulus the estimator must
correct. Both are drawn via ``plan.should_fail`` (state perturbations,
not raised errors). ``bench.py --capture N`` drives all three legs.

Arrival semantics: a span *arrives* when its exchange completes (the
response closes it), so out-of-order arrival falls out of the capture
naturally — longer requests arrive later — and the watermark machinery
sees exactly the fan-in a live collector subscription would produce.
``SpanEvent.capture_us`` keeps the raw (pre-correction) capture
timestamp; ``event_us`` is solver event time (skew-corrected).
"""

from __future__ import annotations

import os
import stat as _stat
from bisect import bisect_right
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from traceweaver_tpu.collector.http2 import (
    PREFACE,
    DirectionReplayer,
    looks_like_http2,
)
from traceweaver_tpu.collector.skew import SkewEstimator
from traceweaver_tpu.collector.strace import StraceParser
from traceweaver_tpu.collector.threading_model import request_key
from traceweaver_tpu.obs import events as _events
from traceweaver_tpu.obs.registry import get_registry as _get_registry
from traceweaver_tpu.runtime import faults as _faults
from traceweaver_tpu.runtime import knobs as _knobs
from traceweaver_tpu.spans import Span
from traceweaver_tpu.stream.sources import SpanEvent

#: every capture-loss reason the ingress can count. Span-shaped reasons
#: (one count ≈ one lost/approximated span) feed the loss RATE that
#: discounts confidence; byte/line-level reasons are reported but do not
#: inflate the rate (their spans surface as half-open/truncated anyway).
LOSS_REASONS = (
    "dropped_chunk",        # capture fault site / post-gap discard (bytes)
    "truncated_stream",     # capture ended mid-frame
    "interrupted_headers",  # CONTINUATION sequence broken / re-keyed
    "decode_error",         # HPACK fragment undecodable (lost bootstrap)
    "half_open",            # request without response, synthetic closeout
    "half_open_dropped",    # request without response, dead-lettered
    "orphan_evicted",       # orphan-buffer bound hit
    "unmatched_lines",      # strace lines the tokenizer rejected
    "skew_clamped",         # fitted offset clamped at TW_SKEW_MAX_US
)
_SPAN_LOSS_REASONS = ("truncated_stream", "interrupted_headers",
                     "decode_error", "half_open", "half_open_dropped",
                     "orphan_evicted")

_OBS = _get_registry()
_OBS_LOSS = _OBS.counter(
    "tw_capture_loss_total",
    "capture ingress losses per source and reason (docs/COLLECTOR.md); "
    "the span-shaped reasons drive the per-source loss rate that "
    "discounts emitted-trace confidence",
    labels=("source", "reason"))
_OBS_SPANS = _OBS.counter(
    "tw_capture_spans_total",
    "spans the capture ingress delivered to the stream layer, per source",
    labels=("source",))
_OBS_REKEYED = _OBS.counter(
    "tw_capture_rekeyed_total",
    "connections re-keyed mid-capture (fd reuse / reconnect without an "
    "observed close), per source",
    labels=("source",))
_OBS_SKEW = _OBS.gauge(
    "tw_clock_skew_us",
    "fitted per-source clock offset vs the reference capture clock "
    "(subtracted from every timestamp before watermarking)",
    labels=("source",))


class CaptureCounters:
    """Shared per-run capture ledger: plain dicts for the stats surface,
    mirrored 1:1 onto the obs registry (tw_capture_* families) and the
    structured event sink on every bump."""

    def __init__(self) -> None:
        self.loss: Dict[str, Dict[str, int]] = {}       # source -> reason
        self.delivered: Dict[str, int] = {}
        self.rekeyed: Dict[str, int] = {}
        self.synthetic: Dict[str, int] = {}

    def count_loss(self, source: str, reason: str, n: int = 1) -> None:
        if n <= 0:
            return
        by = self.loss.setdefault(source, {})
        by[reason] = by.get(reason, 0) + n
        _OBS_LOSS.inc(float(n), source=source, reason=reason)
        _events.emit("capture_loss", reason, source=source, n=by[reason])

    def count_span(self, source: str, n: int = 1) -> None:
        self.delivered[source] = self.delivered.get(source, 0) + n
        _OBS_SPANS.inc(float(n), source=source)

    def count_rekey(self, source: str) -> None:
        self.rekeyed[source] = self.rekeyed.get(source, 0) + 1
        _OBS_REKEYED.inc(1.0, source=source)
        _events.emit("capture_churn", "rekeyed", source=source,
                     n=self.rekeyed[source])

    def count_synthetic(self, source: str) -> None:
        self.synthetic[source] = self.synthetic.get(source, 0) + 1

    # -- rates -------------------------------------------------------------
    def span_losses(self, source: Optional[str] = None) -> int:
        srcs = [source] if source else list(self.loss)
        return sum(self.loss.get(s, {}).get(r, 0)
                   for s in srcs for r in _SPAN_LOSS_REASONS)

    def loss_rate(self, source: Optional[str] = None) -> float:
        lost = self.span_losses(source)
        got = (self.delivered.get(source, 0) if source
               else sum(self.delivered.values()))
        return lost / (lost + got) if (lost + got) else 0.0

    def snapshot(self, skew: Optional[SkewEstimator] = None) -> Dict:
        sources = sorted(set(self.loss) | set(self.delivered)
                         | set(self.rekeyed))
        total_loss: Dict[str, int] = {}
        for by in self.loss.values():
            for reason, n in by.items():
                total_loss[reason] = total_loss.get(reason, 0) + n
        out = dict(
            delivered_spans=sum(self.delivered.values()),
            synthetic_spans=sum(self.synthetic.values()),
            loss=dict(sorted(total_loss.items())),
            loss_rate=round(self.loss_rate(), 4),
            rekeyed_streams=sum(self.rekeyed.values()),
            per_source={
                s: dict(
                    delivered=self.delivered.get(s, 0),
                    loss=dict(sorted(self.loss.get(s, {}).items())),
                    loss_rate=round(self.loss_rate(s), 4),
                    rekeyed=self.rekeyed.get(s, 0),
                ) for s in sources},
        )
        if skew is not None:
            out["skew_us"] = {s: round(v, 1)
                              for s, v in sorted(skew.offsets().items())}
            out["skew_pairs"] = skew.n_pairs
            out["skew_fits"] = skew.fits
        return out


@dataclass
class CaptureRecord:
    """One completed (or closed-out) request/response exchange."""

    source: str
    fd: int
    gen: int
    stream_id: int
    direction: str              # "in" = server-side, "out" = client-side
    key: Optional[str]          # propagated tracing identity, if any
    authority: Optional[str]
    path: Optional[str]
    start_us: float             # RAW source clock (pre-skew-correction)
    end_us: float
    complete: bool              # False = half-open synthetic closeout
    open_seq: int = 0

    @property
    def sid(self) -> str:
        return "%s/%d.%d.%d%s" % (self.source, self.fd, self.gen,
                                  self.stream_id,
                                  "s" if self.direction == "in" else "c")


@dataclass
class _Exchange:
    stream_id: int
    req_dir: str
    start_us: float
    headers: List[Tuple[str, str]]
    key: Optional[str]
    authority: Optional[str]
    path: Optional[str]
    open_seq: int
    resp_started: bool = False
    resp_ts: Optional[float] = None


class _Conn:
    """One logical connection (fd generation after churn re-keying)."""

    __slots__ = ("fd", "gen", "replayers", "fed", "ts_offsets", "ts_vals",
                 "prelude", "decided", "dead", "exchanges", "last_ts")

    def __init__(self, fd: int, gen: int) -> None:
        self.fd = fd
        self.gen = gen
        self.replayers = {"in": DirectionReplayer(),
                          "out": DirectionReplayer()}
        self.fed = {"in": 0, "out": 0}
        # frame offsets -> capture ts lookup, per direction
        self.ts_offsets: Dict[str, List[int]] = {"in": [], "out": []}
        self.ts_vals: Dict[str, List[float]] = {"in": [], "out": []}
        # chunks buffered until the protocol sniff decides
        self.prelude: List[Tuple[str, bytes, float]] = []
        self.decided: Optional[bool] = None
        self.dead = {"in": False, "out": False}
        self.exchanges: Dict[int, _Exchange] = {}
        self.last_ts = 0.0

    def ts_at(self, direction: str, offset: int) -> float:
        offs = self.ts_offsets[direction]
        if not offs:
            return self.last_ts
        i = bisect_right(offs, offset) - 1
        return self.ts_vals[direction][max(i, 0)]


_OTHER = {"in": "out", "out": "in"}


class CaptureIngest:
    """One capture source's incremental pipeline: feed strace lines (or
    eBPF events); completed exchanges land in :attr:`records` (and fire
    ``on_record`` when set — the live/fifo mode hook)."""

    def __init__(self, name: str, counters: CaptureCounters,
                 estimator: Optional[SkewEstimator] = None,
                 service: Optional[str] = None,
                 on_record=None) -> None:
        self.name = name
        self.service = service or name
        self.counters = counters
        self.estimator = estimator
        self.on_record = on_record
        self.records: List[CaptureRecord] = []
        # request identities opened at this source, for in-source
        # parent joins: key -> [(start_ts, server-span sid)]
        self.in_requests_by_key: Dict[str, List[Tuple[float, str]]] = {}
        self.partial_policy = _knobs.get("TW_COLLECTOR_PARTIAL")
        self.orphan_bound = _knobs.get_int("TW_COLLECTOR_ORPHANS")
        self._parser = StraceParser()
        self._parser.payload_hook = self._on_payload
        self._parser.close_hook = self._on_close
        self._conns: Dict[Tuple[int, int], _Conn] = {}  # parser key -> conn
        self._gen_seq: Dict[int, int] = {}
        self._open_seq = 0
        self._n_open = 0
        self._ebpf_gen: Dict[int, int] = {}
        if estimator is not None:
            estimator.register_source(name)
        # chaos site "skew": a drawn source's raw clock is offset by
        # TW_SKEW_CHAOS_US — the stimulus the estimator must correct
        self.ts_offset = 0.0
        plan = _faults.active()
        if plan is not None and plan.should_fail("skew"):
            self.ts_offset = _knobs.get_float("TW_SKEW_CHAOS_US")
            _events.emit("fault_injected", "skew", source=name,
                         offset_us=self.ts_offset, seed=plan.seed)

    # -- feeding -----------------------------------------------------------
    def feed_line(self, line: str) -> None:
        before = self._parser.unmatched_lines
        self._parser.feed_line(line)
        if self._parser.unmatched_lines > before:
            self.counters.count_loss(self.name, "unmatched_lines")

    def feed_ebpf(self, ev) -> None:
        """Fold one perf-buffer event (a :class:`~traceweaver_tpu.
        collector.ebpf.DataEvent` or anything with ``fd``/``op``/
        ``ts_ns``/``len``/``buf``) into the same pipeline the strace
        front-end drives."""
        fd = int(ev.fd)
        if ev.op == 2:  # close
            key = (fd, self._ebpf_gen.get(fd, 0))
            self._ebpf_gen[fd] = key[1] + 1
            self._on_close(key)
            return
        if ev.op not in (0, 1):
            return
        direction = "in" if ev.op == 0 else "out"
        payload = bytes(ev.buf[:ev.len])
        self._on_payload((fd, self._ebpf_gen.get(fd, 0)), direction,
                         payload, ev.ts_ns / 1e3)

    # -- per-chunk pipeline ------------------------------------------------
    def _on_payload(self, key: Tuple[int, int], direction: str,
                    payload: bytes, ts_us: float) -> bool:
        ts_us += self.ts_offset
        conn = self._conns.get(key)
        if conn is not None and payload.startswith(PREFACE) \
                and conn.fed[direction] > 0:
            # churn: a fresh client preface on a connection that already
            # carried bytes = fd reuse / reconnect without an observed
            # close. Re-key: strand the old logical connection (its open
            # exchanges close out per the partial policy) and start a new
            # one, so the two connections' bytes never concatenate.
            self.counters.count_rekey(self.name)
            self._finalize_conn(conn)
            conn = None
            self._conns.pop(key, None)
        if conn is None:
            gen = self._gen_seq.get(key[0], 0)
            self._gen_seq[key[0]] = gen + 1
            conn = self._conns[key] = _Conn(key[0], gen)
        if conn.dead[direction]:
            # post-gap bytes are unusable (no HTTP/2 resync after a hole)
            self.counters.count_loss(self.name, "dropped_chunk")
            return False
        plan = _faults.active()
        if plan is not None and plan.should_fail("capture"):
            _events.emit("fault_injected", "capture", source=self.name,
                         fd=conn.fd, seed=plan.seed)
            conn.dead[direction] = True
            self.counters.count_loss(self.name, "dropped_chunk")
            return False
        conn.last_ts = max(conn.last_ts, ts_us)
        if conn.decided is None:
            conn.prelude.append((direction, payload, ts_us))
            self._maybe_decide(conn, final=False)
        elif conn.decided:
            self._replay_chunk(conn, direction, payload, ts_us)
        return True

    def _maybe_decide(self, conn: _Conn, final: bool) -> None:
        heads = {"in": bytearray(), "out": bytearray()}
        for d, payload, _ in conn.prelude:
            heads[d].extend(payload)
        if not final and max(len(heads["in"]), len(heads["out"])) \
                < len(PREFACE):
            return
        conn.decided = looks_like_http2(bytes(heads["in"]),
                                        bytes(heads["out"]))
        if conn.decided:
            for d, payload, ts in conn.prelude:
                self._replay_chunk(conn, d, payload, ts)
        conn.prelude = []

    def _replay_chunk(self, conn: _Conn, direction: str, payload: bytes,
                      ts_us: float) -> None:
        conn.ts_offsets[direction].append(conn.fed[direction])
        conn.ts_vals[direction].append(ts_us)
        conn.fed[direction] += len(payload)
        for ev in conn.replayers[direction].feed(payload):
            self._handle_event(conn, direction, ev)

    # -- HTTP/2 event handling --------------------------------------------
    def _handle_event(self, conn: _Conn, direction: str, ev) -> None:
        ts = conn.ts_at(direction, ev.offset)
        if ev.kind == "request":
            old = conn.exchanges.get(ev.stream_id)
            if old is not None:
                self._close_out(conn, old, reason="half_open")
            h = {n.lower(): v for n, v in ev.headers}
            self._open_seq += 1
            exch = _Exchange(
                stream_id=ev.stream_id, req_dir=direction, start_us=ts,
                headers=ev.headers, key=request_key(ev.headers),
                authority=h.get(":authority"), path=h.get(":path"),
                open_seq=self._open_seq)
            conn.exchanges[ev.stream_id] = exch
            self._n_open += 1
            if direction == "in" and exch.key:
                self.in_requests_by_key.setdefault(exch.key, []).append(
                    (ts, CaptureRecord(
                        self.name, conn.fd, conn.gen, ev.stream_id,
                        "in", exch.key, exch.authority, exch.path,
                        ts, ts, True).sid))
            self._evict_orphans()
        elif ev.kind in ("response", "trailers"):
            exch = conn.exchanges.get(ev.stream_id)
            if exch is not None and direction == _OTHER[exch.req_dir]:
                exch.resp_started = True
                exch.resp_ts = ts
                if ev.end_stream:
                    self._complete(conn, exch, ts)
        elif ev.kind == "stream_end":
            exch = conn.exchanges.get(ev.stream_id)
            if exch is not None and direction == _OTHER[exch.req_dir] \
                    and exch.resp_started:
                self._complete(conn, exch, ts)

    def _emit_record(self, rec: CaptureRecord) -> None:
        self.records.append(rec)
        self.counters.count_span(self.name)
        if not rec.complete:
            self.counters.count_synthetic(self.name)
        if self.on_record is not None:
            self.on_record(rec)

    def _complete(self, conn: _Conn, exch: _Exchange, end_ts: float) -> None:
        conn.exchanges.pop(exch.stream_id, None)
        self._n_open -= 1
        self._emit_record(CaptureRecord(
            self.name, conn.fd, conn.gen, exch.stream_id, exch.req_dir,
            exch.key, exch.authority, exch.path,
            exch.start_us, max(end_ts, exch.start_us), True,
            open_seq=exch.open_seq))

    def _close_out(self, conn: _Conn, exch: _Exchange,
                   reason: str) -> None:
        """Half-open exchange disposal under the partial-capture policy."""
        conn.exchanges.pop(exch.stream_id, None)
        self._n_open -= 1
        self.counters.count_loss(self.name, reason)
        if reason == "half_open_dropped" \
                or self.partial_policy == "deadletter":
            if reason == "half_open":
                # counted above as half_open; the drop itself is the
                # policy outcome, counted under its own reason
                self.counters.count_loss(self.name, "half_open_dropped")
            return
        end = exch.resp_ts if exch.resp_ts is not None else conn.last_ts
        self._emit_record(CaptureRecord(
            self.name, conn.fd, conn.gen, exch.stream_id, exch.req_dir,
            exch.key, exch.authority, exch.path,
            exch.start_us, max(end, exch.start_us), False,
            open_seq=exch.open_seq))

    def _evict_orphans(self) -> None:
        while self._n_open > self.orphan_bound:
            oldest: Optional[Tuple[_Conn, _Exchange]] = None
            for conn in self._conns.values():
                for exch in conn.exchanges.values():
                    if oldest is None or exch.open_seq < oldest[1].open_seq:
                        oldest = (conn, exch)
            if oldest is None:
                break
            self._close_out(oldest[0], oldest[1], reason="orphan_evicted")

    # -- teardown ----------------------------------------------------------
    def _on_close(self, key: Tuple[int, int]) -> None:
        conn = self._conns.pop(key, None)
        if conn is not None:
            self._finalize_conn(conn)

    def _finalize_conn(self, conn: _Conn) -> None:
        if conn.decided is None:
            self._maybe_decide(conn, final=True)
        for exch in sorted(conn.exchanges.values(),
                           key=lambda e: e.open_seq):
            self._close_out(conn, exch, reason="half_open")
        if conn.decided:
            for d in ("in", "out"):
                rep = conn.replayers[d]
                if rep.pending_bytes and not conn.dead[d]:
                    self.counters.count_loss(self.name, "truncated_stream")
                self.counters.count_loss(self.name, "interrupted_headers",
                                         rep.dropped_header_blocks
                                         + int(rep.pending_headers))
                self.counters.count_loss(self.name, "decode_error",
                                         rep.decode_errors)

    def finish(self) -> None:
        for key in sorted(self._conns):
            self._finalize_conn(self._conns[key])
        self._conns.clear()


# ---------------------------------------------------------------------------
# span synthesis + the stream-source contract
# ---------------------------------------------------------------------------

def _stub_process(authority: Optional[str]) -> Tuple[str, str]:
    """(process id, service name) of a synthesized downstream stub."""
    svc = (authority or "peer").split(":")[0]
    return "ext:" + svc, svc


class CollectorSource:
    """Adapt captured logs into the stream layer's span-event contract.

    ``captures`` maps source name (one capture host/process = one clock
    = one service) to its recorded ``strace -f [-ttt]`` log text. Parsing
    runs through the incremental :class:`CaptureIngest` machinery,
    cross-source exchanges fit the skew estimator, and the corrected,
    arrival-ordered event list replays deterministically —
    ``events(skip=n)`` resumes exactly like
    :class:`~traceweaver_tpu.stream.sources.ReplaySource`.
    """

    def __init__(self, captures: Dict[str, str],
                 services: Optional[Dict[str, str]] = None,
                 ebpf_events: Optional[Dict[str, Iterable]] = None,
                 counters: Optional[CaptureCounters] = None,
                 estimator: Optional[SkewEstimator] = None) -> None:
        # counters/estimator can be shared across sources (the serve
        # capture endpoint accumulates one ledger per tenant across
        # many posted logs)
        self.counters = counters if counters is not None \
            else CaptureCounters()
        self.estimator = estimator if estimator is not None \
            else SkewEstimator()
        self.store = None   # the replay-source attribute surface
        self._ingests: Dict[str, CaptureIngest] = {}
        services = services or {}
        names = sorted(set(captures) | set(ebpf_events or {}))
        for name in names:
            ing = CaptureIngest(name, self.counters,
                                estimator=self.estimator,
                                service=services.get(name))
            self._ingests[name] = ing
            for ev in (ebpf_events or {}).get(name, ()):
                ing.feed_ebpf(ev)
            for line in captures.get(name, "").splitlines():
                ing.feed_line(line)
            ing.finish()
        self._events: List[SpanEvent] = self._synthesize(
            [r for ing in self._ingests.values() for r in ing.records])

    # -- the source contract ----------------------------------------------
    def __len__(self) -> int:
        return len(self._events)

    def events(self, skip: int = 0) -> Iterator[SpanEvent]:
        return iter(self._events[skip:])

    def capture_quality(self) -> Dict:
        """The per-source capture ledger the stream layer's confidence
        discount and summary consume (docs/COLLECTOR.md)."""
        return self.counters.snapshot(skew=self.estimator)

    # -- synthesis ---------------------------------------------------------
    def _service_of(self, source: str) -> str:
        ing = self._ingests.get(source)
        return ing.service if ing is not None else source

    def _synthesize(self, records: List[CaptureRecord]) -> List[SpanEvent]:
        service_to_source = {self._service_of(n): n for n in self._ingests}
        ins = [r for r in records if r.direction == "in"]
        outs = [r for r in records if r.direction == "out"]

        # cross-source exchange join: an outgoing request at source A
        # matches the incoming request it became at source B, per
        # (tracing key, callee source), order-matched by open sequence
        ins_by: Dict[Tuple[str, str], List[CaptureRecord]] = {}
        for r in sorted(ins, key=lambda r: (r.open_seq, r.sid)):
            if r.key:
                ins_by.setdefault((r.key, r.source), []).append(r)
        joined_child: Dict[str, CaptureRecord] = {}   # out sid -> in rec
        joined_parent_of_in: Dict[str, str] = {}      # in sid -> out sid
        for o in sorted(outs, key=lambda r: (r.open_seq, r.sid)):
            if not o.key:
                continue
            callee_src = service_to_source.get(
                _stub_process(o.authority)[1])
            if callee_src is None or callee_src == o.source:
                continue
            cands = ins_by.get((o.key, callee_src), [])
            if not cands:
                continue
            child = cands.pop(0)
            joined_child[o.sid] = child
            joined_parent_of_in[child.sid] = o.sid
            if o.complete and child.complete \
                    and self.estimator is not None:
                self.estimator.observe_pair(
                    o.source, child.source,
                    o.start_us, child.start_us, child.end_us, o.end_us)

        if self.estimator.ready():
            offsets = self.estimator.fit()
            for src, off in sorted(offsets.items()):
                _OBS_SKEW.set(off, source=src)
            _events.emit(
                "clock_skew", "fit",
                offsets_us={s: round(v, 1)
                            for s, v in sorted(offsets.items())},
                pairs=self.estimator.n_pairs,
                reference=self.estimator.reference())
            self.counters.count_loss(
                self.estimator.reference() or "capture", "skew_clamped",
                self.estimator.clamped)

        spans: List[Tuple[Span, float, float]] = []  # span, arrival, raw
        processes: Dict[str, Dict[str, str]] = {}

        def corrected(source: str, t: float) -> float:
            return self.estimator.correct(source, t)

        def trace_of(rec: CaptureRecord) -> str:
            return rec.key or ("cap:" + rec.sid)

        def note_process(trace_id: str, pid: str, service: str) -> None:
            processes.setdefault(trace_id, {})[pid] = service

        # server spans from incoming requests
        for r in ins:
            tid = trace_of(r)
            refs = []
            parent_sid = joined_parent_of_in.get(r.sid)
            if parent_sid is not None:
                refs = [(tid, parent_sid)]
            start = corrected(r.source, r.start_us)
            dur = max(0.0, r.end_us - r.start_us)
            spans.append((Span(tid, r.sid, start, dur, r.path or "req",
                               refs, r.source, "server"),
                          start + dur, r.start_us))
            note_process(tid, r.source, self._service_of(r.source))

        # client spans from outgoing requests (+ downstream stubs where
        # the callee was not captured)
        for o in outs:
            tid = trace_of(o)
            refs = []
            if o.key:
                ing = self._ingests.get(o.source)
                opened = (ing.in_requests_by_key.get(o.key, [])
                          if ing is not None else [])
                # parent = the last request this source OPENED at or
                # before the outgoing call (raw clocks are comparable
                # within one source)
                best = None
                for ts, sid in opened:
                    if ts <= o.start_us and (best is None or ts >= best[0]):
                        best = (ts, sid)
                if best is None and opened:
                    best = opened[0]
                if best is not None:
                    refs = [(tid, best[1])]
            start = corrected(o.source, o.start_us)
            dur = max(0.0, o.end_us - o.start_us)
            spans.append((Span(tid, o.sid, start, dur, o.path or "call",
                               refs, o.source, "client"),
                          start + dur, o.start_us))
            note_process(tid, o.source, self._service_of(o.source))
            child = joined_child.get(o.sid)
            if child is None:
                # downstream not captured: synthesize the callee's server
                # half inside the client interval so the stream layer can
                # resolve the callee endpoint (child_service_of)
                pid, svc = _stub_process(o.authority)
                eps = min(1.0, dur / 4.0)
                spans.append((Span(tid, o.sid + "d", start + eps,
                                   max(0.0, dur - 2 * eps),
                                   o.path or "call", [(tid, o.sid)],
                                   pid, "server"),
                              start + dur, o.start_us))
                note_process(tid, pid, svc)

        events = [
            SpanEvent(span=s, event_us=float(s.start_mus),
                      arrival_us=max(arrival, float(s.start_mus)),
                      trace_id=s.trace_id,
                      processes=processes.get(s.trace_id, {}),
                      capture_us=raw)
            for s, arrival, raw in spans
        ]
        events.sort(key=lambda e: (e.arrival_us, e.trace_id, e.span.sid))
        return events

    # -- construction helpers ---------------------------------------------
    @classmethod
    def from_spec(cls, path: str,
                  service: Optional[str] = None) -> "CollectorSource":
        """Build from a filesystem spec: a single strace log file (one
        source; service name from ``service``, ``TW_COLLECTOR_SERVICE``,
        or the file stem), a directory of per-source logs (every
        ``*.log`` / ``*.txt`` / ``*.strace`` file is one source named by
        its stem), or a FIFO (live single-source mode — see
        :meth:`iter_live`)."""
        if os.path.isdir(path):
            captures = {}
            for fn in sorted(os.listdir(path)):
                if fn.rsplit(".", 1)[-1] not in ("log", "txt", "strace"):
                    continue
                stem = fn.rsplit(".", 1)[0]
                with open(os.path.join(path, fn)) as f:
                    captures[stem] = f.read()
            if not captures:
                raise ValueError(
                    f"collector:{path}: no *.log/*.txt/*.strace capture "
                    "files in the directory")
            return cls(captures)
        if not os.path.exists(path):
            raise ValueError(f"collector:{path}: no such file")
        name = (service or _knobs.get("TW_COLLECTOR_SERVICE")
                or os.path.basename(path).rsplit(".", 1)[0])
        if _stat.S_ISFIFO(os.stat(path).st_mode):
            return _LiveCollectorSource(path, name)
        with open(path) as f:
            return cls({name: f.read()})


class _LiveCollectorSource:
    """Single-source live ingress over a FIFO: lines are parsed as the
    writer produces them and spans are emitted as their exchanges
    complete. Not checkpoint-resumable (``skip`` must be 0) — a FIFO
    cannot be replayed."""

    def __init__(self, path: str, name: str) -> None:
        self.path = path
        self.name = name
        self.counters = CaptureCounters()
        self.estimator = SkewEstimator()
        self.store = None

    def capture_quality(self) -> Dict:
        return self.counters.snapshot(skew=self.estimator)

    def __len__(self) -> int:
        return 0

    def events(self, skip: int = 0) -> Iterator[SpanEvent]:
        if skip:
            raise ValueError(
                "collector FIFO sources cannot fast-forward (skip=%d): "
                "a live capture is not replayable; checkpoint/resume "
                "needs a recorded log" % skip)
        with open(self.path) as f:
            yield from iter_live(f, self.name, counters=self.counters,
                                 estimator=self.estimator)


def iter_live(lines: Iterable[str], name: str,
              counters: Optional[CaptureCounters] = None,
              estimator: Optional[SkewEstimator] = None,
              ) -> Iterator[SpanEvent]:
    """Incremental single-source ingress: feed strace lines as they
    arrive, yield span events as exchanges complete (arrival order ==
    completion order — exactly a collector subscription's fan-in).
    Downstream callees synthesize as stubs (a single live source has no
    cross-source joins, so the skew estimator stays inert at offset 0)."""
    counters = counters if counters is not None else CaptureCounters()
    completed: List[CaptureRecord] = []
    ing = CaptureIngest(name, counters, estimator=estimator,
                        on_record=completed.append)
    src = CollectorSource.__new__(CollectorSource)
    src.counters = counters
    src.estimator = estimator or SkewEstimator()
    src.store = None
    src._ingests = {name: ing}

    def drain() -> Iterator[SpanEvent]:
        if completed:
            batch = list(completed)
            del completed[:]
            yield from src._synthesize(batch)

    for line in lines:
        ing.feed_line(line)
        yield from drain()
    ing.finish()
    yield from drain()
