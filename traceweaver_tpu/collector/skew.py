"""Per-source clock-skew estimation over request/response event pairs.

A multi-host capture gives every source (captured process / host) its own
clock. The windowed solver assumes one event-time axis: the candidate
enumeration requires a parent span's interval to contain its children's,
and the watermark assumes bounded out-of-orderness — a few hundred
milliseconds of host skew violates both (a child "starting before" its
parent is simply never enumerated as a candidate). This module fits a
constant per-source offset from the capture's own request/response
geometry and the ingress (:mod:`traceweaver_tpu.collector.source`)
subtracts it from every timestamp *before* watermarking.

The fit is the classic NTP exchange estimate. One cross-source exchange
gives four timestamps::

    t0  caller writes the request        (caller clock)
    t1  callee reads the request         (callee clock)
    t2  callee writes the response       (callee clock)
    t3  caller reads the response        (caller clock)

    theta = ((t1 - t0) + (t2 - t3)) / 2     # callee clock - caller clock

which cancels the symmetric part of the network delay; the residual
error is bounded by the delay asymmetry, far below the skews that break
containment. Per (caller, callee) edge the estimator keeps every
observed ``theta`` and takes the *median* (a single retransmitted or
half-captured exchange must not drag the fit), then anchors one
reference source at offset zero and walks the exchange graph breadth-
first, accumulating edge medians into absolute per-source offsets.

The reference is chosen deterministically: the alphabetically-first
source that only ever appears as a caller (the capture closest to the
external client), falling back to the alphabetically-first source
overall. Offsets are clamped to ``±TW_SKEW_MAX_US`` (a fit driven by a
corrupt capture must not fling a source's spans outside every window);
clamps are counted so the ingress can surface them as capture loss.
"""

from __future__ import annotations

import statistics
from typing import Dict, List, Optional, Set, Tuple

from traceweaver_tpu.runtime import knobs as _knobs


class SkewEstimator:
    """Pairwise-offset fit over captured request/response exchanges."""

    def __init__(self, min_pairs: Optional[int] = None,
                 max_us: Optional[float] = None) -> None:
        self.min_pairs = (min_pairs if min_pairs is not None
                          else _knobs.get_int("TW_SKEW_MIN_PAIRS"))
        self.max_us = (max_us if max_us is not None
                       else _knobs.get_float("TW_SKEW_MAX_US"))
        # (caller, callee) -> observed thetas (callee clock - caller clock)
        self._pairs: Dict[Tuple[str, str], List[float]] = {}
        self._offsets: Dict[str, float] = {}
        self._sources: Set[str] = set()
        self._callees: Set[str] = set()
        self.n_pairs = 0
        self.fits = 0
        self.clamped = 0

    def register_source(self, source: str) -> None:
        """Make a source known even before (or without) any exchange
        pairs — it participates in the fit with offset 0."""
        self._sources.add(source)

    def observe_pair(self, caller: str, callee: str,
                     t0: float, t1: float, t2: float, t3: float) -> None:
        """Fold one cross-source exchange in (all four stamps in the
        respective source's *raw* capture clock, microseconds)."""
        if caller == callee:
            return
        theta = ((t1 - t0) + (t2 - t3)) / 2.0
        self._pairs.setdefault((caller, callee), []).append(theta)
        self._sources.update((caller, callee))
        self._callees.add(callee)
        self.n_pairs += 1

    def reference(self) -> Optional[str]:
        """Deterministic anchor: alphabetically-first caller-only source,
        else alphabetically-first source."""
        if not self._sources:
            return None
        caller_only = sorted(self._sources - self._callees)
        return caller_only[0] if caller_only else sorted(self._sources)[0]

    def ready(self) -> bool:
        """Enough exchange pairs for a trustworthy first fit?"""
        return self.n_pairs >= self.min_pairs

    def fit(self) -> Dict[str, float]:
        """(Re)fit absolute per-source offsets: median per edge, then a
        breadth-first walk from the reference source. Sources the
        exchange graph never reaches keep offset 0 (there is nothing to
        align them against). Returns the offset map; also retrievable
        per source via :meth:`offset_us`."""
        ref = self.reference()
        if ref is None:
            return {}
        edges: Dict[str, List[Tuple[str, float]]] = {}
        for (caller, callee), thetas in self._pairs.items():
            med = statistics.median(thetas)
            # offset[callee] - offset[caller] = median theta, both ways
            edges.setdefault(caller, []).append((callee, med))
            edges.setdefault(callee, []).append((caller, -med))
        offsets = {s: 0.0 for s in self._sources}
        seen = {ref}
        frontier = [ref]
        while frontier:
            nxt: List[str] = []
            for src in frontier:
                for other, delta in sorted(edges.get(src, ())):
                    if other in seen:
                        continue
                    seen.add(other)
                    val = offsets[src] + delta
                    if abs(val) > self.max_us:
                        self.clamped += 1
                        val = max(-self.max_us, min(self.max_us, val))
                    offsets[other] = val
                    nxt.append(other)
            frontier = nxt
        self._offsets = offsets
        self.fits += 1
        return dict(offsets)

    def offset_us(self, source: str) -> float:
        """The fitted offset of ``source``'s clock (0.0 before any fit
        reaches it)."""
        return self._offsets.get(source, 0.0)

    def correct(self, source: str, t_us: float) -> float:
        """Map a raw capture timestamp onto the reference clock."""
        return t_us - self.offset_us(source)

    def offsets(self) -> Dict[str, float]:
        return dict(self._offsets)
