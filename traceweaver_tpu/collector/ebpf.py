"""eBPF syscall-capture prototype (BCC), import-gated.

TPU-era rebuild of the reference's capture-side eBPF program
(reference: src/span_collector/ebpf/http2_filter.py:1-393): kprobe/
kretprobe pairs on ``read``/``write``/``accept4``/``close`` record
per-(pid, fd) payload chunks into a per-CPU staging buffer and ship them
through a perf ring in bounded chunks; userspace reassembles them into the
same per-(fd, iteration) stream layout :mod:`traceweaver_tpu.collector.strace`
produces, so the HTTP/2 replay and thread-mapping stages run unchanged on
live captures.

BCC is not available in this image (and loading kernel programs requires
privileges test runners don't have), so the harness degrades: the program
text and the ctypes event mirror are importable and unit-testable; only
:func:`run_capture` needs a live ``bcc``.
"""

from __future__ import annotations

import ctypes
from typing import Callable, Optional

# Payload bytes shipped per perf event; the reference ships up to 4 chunks
# of 30 KiB per syscall (http2_filter.py:180-229) — we keep one page per
# event and rely on chunk sequencing instead.
CHUNK_SIZE = 4096
MAX_CHUNKS = 8

BPF_PROGRAM = r"""
#include <uapi/linux/ptrace.h>
#include <linux/sched.h>

#define CHUNK_SIZE %(chunk_size)d
#define MAX_CHUNKS %(max_chunks)d

struct data_event_t {
    u64 ts_ns;
    u32 pid;
    u32 tid;
    s32 fd;
    u32 op;        // 0 = read, 1 = write, 2 = close, 3 = accept
    u32 chunk;     // chunk index within one syscall's payload
    u32 len;       // valid bytes in buf
    s64 ret;
    char comm[TASK_COMM_LEN];
    char buf[CHUNK_SIZE];
};

// Per-CPU staging slot: data_event_t is far beyond the 512-byte BPF stack.
BPF_PERCPU_ARRAY(staging, struct data_event_t, 1);
BPF_PERF_OUTPUT(events);

// entry args we need again at return: fd + user buffer pointer
struct call_ctx_t {
    s32 fd;
    const char *ubuf;
};
BPF_HASH(read_ctx, u64, struct call_ctx_t);
BPF_HASH(write_ctx, u64, struct call_ctx_t);

// fds observed doing plausible-HTTP traffic (filter, reference :151-178)
BPF_HASH(tracked_fd, u64, u8);

static __always_inline u64 pid_fd_key(u32 pid, s32 fd) {
    return ((u64)pid << 32) | (u32)fd;
}

static __always_inline int emit_payload(struct pt_regs *ctx, u32 op,
                                        s32 fd, const char *ubuf, s64 ret) {
    if (ret <= 0)
        return 0;
    int zero = 0;
    struct data_event_t *ev = staging.lookup(&zero);
    if (!ev)
        return 0;
    u64 id = bpf_get_current_pid_tgid();
    ev->ts_ns = bpf_ktime_get_ns();
    ev->pid = id >> 32;
    ev->tid = (u32)id;
    ev->fd = fd;
    ev->op = op;
    ev->ret = ret;
    bpf_get_current_comm(&ev->comm, sizeof(ev->comm));

    u64 remaining = (u64)ret;
    #pragma unroll
    for (int chunk = 0; chunk < MAX_CHUNKS; chunk++) {
        if (remaining == 0)
            break;
        u32 this_len = remaining > CHUNK_SIZE ? CHUNK_SIZE : (u32)remaining;
        ev->chunk = chunk;
        ev->len = this_len;
        bpf_probe_read_user(&ev->buf, CHUNK_SIZE,
                            ubuf + (u64)chunk * CHUNK_SIZE);
        events.perf_submit(ctx, ev, sizeof(*ev) - CHUNK_SIZE + this_len);
        remaining -= this_len;
    }
    return 0;
}

int kprobe__ksys_read(struct pt_regs *ctx, unsigned int fd,
                      char __user *buf, size_t count) {
    u64 id = bpf_get_current_pid_tgid();
    struct call_ctx_t c = {.fd = (s32)fd, .ubuf = buf};
    read_ctx.update(&id, &c);
    return 0;
}

int kretprobe__ksys_read(struct pt_regs *ctx) {
    u64 id = bpf_get_current_pid_tgid();
    struct call_ctx_t *c = read_ctx.lookup(&id);
    if (!c)
        return 0;
    s64 ret = PT_REGS_RC(ctx);
    emit_payload(ctx, 0, c->fd, c->ubuf, ret);
    read_ctx.delete(&id);
    return 0;
}

int kprobe__ksys_write(struct pt_regs *ctx, unsigned int fd,
                       const char __user *buf, size_t count) {
    u64 id = bpf_get_current_pid_tgid();
    struct call_ctx_t c = {.fd = (s32)fd, .ubuf = buf};
    write_ctx.update(&id, &c);
    return 0;
}

int kretprobe__ksys_write(struct pt_regs *ctx) {
    u64 id = bpf_get_current_pid_tgid();
    struct call_ctx_t *c = write_ctx.lookup(&id);
    if (!c)
        return 0;
    s64 ret = PT_REGS_RC(ctx);
    emit_payload(ctx, 1, c->fd, c->ubuf, ret);
    write_ctx.delete(&id);
    return 0;
}

int kprobe__close_fd(struct pt_regs *ctx, unsigned int fd) {
    int zero = 0;
    struct data_event_t *ev = staging.lookup(&zero);
    if (!ev)
        return 0;
    u64 id = bpf_get_current_pid_tgid();
    ev->ts_ns = bpf_ktime_get_ns();
    ev->pid = id >> 32;
    ev->tid = (u32)id;
    ev->fd = (s32)fd;
    ev->op = 2;
    ev->chunk = 0;
    ev->len = 0;
    ev->ret = 0;
    events.perf_submit(ctx, ev, sizeof(*ev) - CHUNK_SIZE);
    u64 key = pid_fd_key(id >> 32, (s32)fd);
    tracked_fd.delete(&key);
    return 0;
}
""" % {"chunk_size": CHUNK_SIZE, "max_chunks": MAX_CHUNKS}

_TASK_COMM_LEN = 16


class DataEvent(ctypes.Structure):
    """ctypes mirror of ``struct data_event_t`` (reference :300-345)."""

    _fields_ = [
        ("ts_ns", ctypes.c_uint64),
        ("pid", ctypes.c_uint32),
        ("tid", ctypes.c_uint32),
        ("fd", ctypes.c_int32),
        ("op", ctypes.c_uint32),
        ("chunk", ctypes.c_uint32),
        ("len", ctypes.c_uint32),
        ("ret", ctypes.c_int64),
        ("comm", ctypes.c_char * _TASK_COMM_LEN),
        ("buf", ctypes.c_char * CHUNK_SIZE),
    ]


OP_NAMES = {0: "read", 1: "write", 2: "close", 3: "accept"}


def looks_like_http(payload: bytes) -> bool:
    """Userspace twin of the in-kernel HTTP heuristic (reference :151-178):
    HTTP/1 methods, response preamble, or the HTTP/2 client preface."""
    return payload.startswith((
        b"GET ", b"POST ", b"PUT ", b"DELETE ", b"HEAD ", b"PATCH ",
        b"HTTP/1.", b"PRI * HTTP/2.0",
    ))


def parse_event(raw: bytes) -> DataEvent:
    """Decode one perf-buffer record (possibly truncated to the valid
    payload length, as submitted by ``emit_payload``)."""
    ev = DataEvent()
    ctypes.memmove(ctypes.addressof(ev), raw,
                   min(len(raw), ctypes.sizeof(ev)))
    return ev


def bcc_available() -> bool:
    try:
        import bcc  # noqa: F401
        return True
    except ImportError:
        return False


def run_capture(callback: Callable[[DataEvent], None],
                page_cnt: int = 64,
                poll_timeout_ms: int = 100,
                stop: Optional[Callable[[], bool]] = None) -> None:
    """Load the program and poll the perf buffer, invoking ``callback`` per
    event. Requires bcc + root; raises RuntimeError otherwise."""
    if not bcc_available():
        raise RuntimeError(
            "bcc is not available in this environment; use the strace "
            "front-end (traceweaver_tpu.collector.strace) instead"
        )
    from bcc import BPF  # type: ignore[import-not-found]

    bpf = BPF(text=BPF_PROGRAM)

    def _on_event(cpu, data, size):
        callback(parse_event(ctypes.string_at(data, size)))

    bpf["events"].open_perf_buffer(_on_event, page_cnt=page_cnt)
    while not (stop and stop()):
        bpf.perf_buffer_poll(timeout=poll_timeout_ms)
