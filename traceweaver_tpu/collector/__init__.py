"""Span collector: capture-side prototypes (§2.5 of the survey).

Offline pipeline over recorded ``strace`` logs — the rebuild of the
reference's span-collector prototypes (reference:
src/span_collector/http2_parser/parser.py, span_collector/ebpf/
http2_filter.py) without the ``h2`` dependency:

1. :mod:`.strace` — reassemble interleaved syscalls into per-(fd,
   iteration) bidirectional byte streams with thread attribution;
2. :mod:`.http2` + :mod:`.hpack` — replay streams as HTTP/2, recovering
   request/response events (self-contained RFC 7540/7541 implementation);
3. :mod:`.threading_model` — join requests via tracing headers and measure
   thread predictability (the vPath hypothesis test);
4. :mod:`.ebpf` — live-capture equivalent (BCC), import-gated.

:func:`collect_from_strace_log` runs 1–3 end-to-end.

The **capture ingress** (:mod:`.source` + :mod:`.skew`, docs/COLLECTOR.md)
closes the loop the offline pipeline leaves open: it runs the same
reassembly/replay machinery incrementally, hardens it against clock skew,
partial capture, and connection churn, and emits the stream layer's
timed span events — ``--source collector:<path|fifo>`` on the stream CLI,
``POST /api/v1/tenants/<id>/capture`` on the serve server.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from traceweaver_tpu.collector.hpack import Decoder, Encoder  # noqa: F401
from traceweaver_tpu.collector.http2 import (  # noqa: F401
    DirectionReplayer,
    Event,
    looks_like_http2,
    replay_connection,
)
from traceweaver_tpu.collector.strace import (  # noqa: F401
    FdStream,
    StraceParser,
    parse_strace_log,
    unescape_strace,
)
from traceweaver_tpu.collector.threading_model import (  # noqa: F401
    AttributedRequest,
    attribute_requests,
    join_causal_pairs,
    request_key,
    thread_predictability,
)

# NOTE: the capture ingress (collector.source.CollectorSource, the
# skew/loss/churn hardening layer) is intentionally NOT imported here —
# it pulls in the stream layer and numpy, and the offline pipeline above
# must stay importable from lint/tail fast paths. Import it explicitly:
#   from traceweaver_tpu.collector.source import CollectorSource


@dataclass
class CollectorReport:
    """Everything the offline collector recovers from one strace log."""

    streams: Dict[Tuple[int, int], FdStream]
    events_by_stream: Dict[Tuple[int, int], Tuple[List[Event], List[Event]]]
    requests: List[AttributedRequest]
    causal_pairs: List[Tuple[AttributedRequest, AttributedRequest]]
    thread_predictability: Optional[float]


def collect_from_strace_log(text: str) -> CollectorReport:
    """Run the full offline pipeline on an ``strace -f`` log."""
    streams = parse_strace_log(text)
    events_by_stream = {
        key: replay_connection(s.inbound, s.outbound)
        for key, s in streams.items()
        if looks_like_http2(s.inbound, s.outbound)
    }
    requests = attribute_requests(streams, events_by_stream)
    pairs = join_causal_pairs(requests)
    return CollectorReport(
        streams=streams,
        events_by_stream=events_by_stream,
        requests=requests,
        causal_pairs=pairs,
        thread_predictability=thread_predictability(pairs),
    )
