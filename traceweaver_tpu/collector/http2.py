"""HTTP/2 (RFC 7540) frame parsing and capture replay.

The reference's span-collector prototype replays captured per-fd byte
streams through paired ``h2`` client+server connection state machines to
recover ``RequestReceived``/``ResponseReceived`` events
(reference: src/span_collector/http2_parser/parser.py:69-159, ``handle3``).
This module is the self-contained equivalent: a frame splitter tolerant of
partial/truncated captures, HEADERS+CONTINUATION reassembly through the
:mod:`~traceweaver_tpu.collector.hpack` codec, and per-direction replay
that emits request/response/data/trailers events with byte offsets (so
captured syscalls can be attributed to the threads that issued them).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from traceweaver_tpu.collector.hpack import Decoder, Header, HpackError

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

# Frame types (RFC 7540 §6)
DATA = 0x0
HEADERS = 0x1
PRIORITY = 0x2
RST_STREAM = 0x3
SETTINGS = 0x4
PUSH_PROMISE = 0x5
PING = 0x6
GOAWAY = 0x7
WINDOW_UPDATE = 0x8
CONTINUATION = 0x9

# Flags
FLAG_END_STREAM = 0x1   # DATA / HEADERS
FLAG_ACK = 0x1          # SETTINGS / PING
FLAG_END_HEADERS = 0x4
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20


class Http2ParseError(ValueError):
    pass


@dataclass
class Frame:
    type: int
    flags: int
    stream_id: int
    payload: bytes
    offset: int  # byte offset of the frame header within the direction


def split_frames(data: bytes, start: int = 0) -> Iterator[Frame]:
    """Yield frames from a contiguous byte stream; stops cleanly at a
    trailing partial frame (captures often end mid-frame)."""
    pos = start
    n = len(data)
    while pos + 9 <= n:
        length = int.from_bytes(data[pos:pos + 3], "big")
        ftype = data[pos + 3]
        flags = data[pos + 4]
        stream_id = int.from_bytes(data[pos + 5:pos + 9], "big") & 0x7FFFFFFF
        if pos + 9 + length > n:
            return  # truncated final frame
        yield Frame(ftype, flags, stream_id, data[pos + 9:pos + 9 + length],
                    pos)
        pos += 9 + length


def _strip_padding(frame: Frame) -> bytes:
    payload = frame.payload
    if frame.flags & FLAG_PADDED:
        if not payload:
            raise Http2ParseError("PADDED frame with empty payload")
        pad = payload[0]
        payload = payload[1:]
        if pad > len(payload):
            raise Http2ParseError("padding exceeds payload")
        payload = payload[:len(payload) - pad]
    return payload


def headers_fragment(frame: Frame) -> bytes:
    """The HPACK fragment of a HEADERS frame (padding/priority stripped)."""
    payload = _strip_padding(frame)
    if frame.type == HEADERS and frame.flags & FLAG_PRIORITY:
        if len(payload) < 5:
            raise Http2ParseError("HEADERS priority block truncated")
        payload = payload[5:]
    return payload


# ---------------------------------------------------------------------------
# Event replay
# ---------------------------------------------------------------------------

@dataclass
class Event:
    kind: str          # request | response | trailers | data | stream_end
    stream_id: int
    offset: int        # where the originating frame started in the stream
    headers: List[Header] = field(default_factory=list)
    data_len: int = 0
    end_stream: bool = False


class DirectionReplayer:
    """Replays one direction of an HTTP/2 connection (all bytes one peer
    sent). Maintains the direction's HPACK dynamic table; classifies header
    blocks as request (``:method``), response (``:status``) or trailers.
    """

    def __init__(self) -> None:
        self.decoder = Decoder()
        self._buffer = bytearray()
        self._consumed = 0
        self._preface_checked = False
        # streams that already saw their initial header block
        self._opened: Dict[int, bool] = {}
        # pending HEADERS awaiting CONTINUATION: (stream, flags, frag, offset)
        self._pending: Optional[Tuple[int, int, bytearray, int]] = None
        # capture-loss ledger (consumed by the collector ingress): header
        # blocks dropped because a CONTINUATION sequence was interrupted
        # or re-keyed, and HPACK fragments the lost-bootstrap tolerance
        # skipped — every tolerated corruption is COUNTED, never silent
        self.dropped_header_blocks = 0
        self.decode_errors = 0

    def feed(self, data: bytes) -> List[Event]:
        """Add captured bytes; returns newly completed events."""
        self._buffer.extend(data)
        return list(self._drain())

    def _drain(self) -> Iterator[Event]:
        buf = bytes(self._buffer)
        pos = 0
        if not self._preface_checked:
            if buf.startswith(PREFACE):
                pos = len(PREFACE)
                self._preface_checked = True
            elif PREFACE.startswith(buf):
                return  # still a strict preface prefix: need more bytes
            else:
                # diverged from the preface: this direction starts at a
                # frame boundary (a server direction, or a mid-stream
                # attach) — decide NOW so short captures (a lone 10-byte
                # response frame) don't wait forever for 24 bytes
                self._preface_checked = True
        for frame in split_frames(buf, pos):
            pos = frame.offset + 9 + len(frame.payload)
            yield from self._handle(frame)
        # keep the unconsumed tail
        del self._buffer[:pos]
        self._consumed += pos

    @property
    def pending_bytes(self) -> int:
        """Unconsumed tail bytes (a capture that ended mid-frame)."""
        return len(self._buffer)

    @property
    def pending_headers(self) -> bool:
        """A HEADERS block still awaiting CONTINUATION frames."""
        return self._pending is not None

    def _handle(self, frame: Frame) -> Iterator[Event]:
        abs_offset = self._consumed + frame.offset
        if self._pending is not None and frame.type != CONTINUATION:
            # header block interrupted: drop it (tolerant replay)
            self._pending = None
            self.dropped_header_blocks += 1
        if frame.type == HEADERS:
            frag = headers_fragment(frame)
            if frame.flags & FLAG_END_HEADERS:
                yield from self._header_block(
                    frame.stream_id, frame.flags, bytes(frag), abs_offset
                )
            else:
                self._pending = (frame.stream_id, frame.flags,
                                 bytearray(frag), abs_offset)
        elif frame.type == CONTINUATION and self._pending is not None:
            stream_id, flags, frag, offset = self._pending
            if frame.stream_id == stream_id:
                frag.extend(frame.payload)
                if frame.flags & FLAG_END_HEADERS:
                    self._pending = None
                    yield from self._header_block(
                        stream_id, flags, bytes(frag), offset
                    )
            else:
                # interleaved CONTINUATION for a different stream: a
                # protocol error on a live connection, but a real capture
                # artifact under loss/churn — drop the pending block,
                # counted (RFC 7540 §6.10 requires contiguity)
                self._pending = None
                self.dropped_header_blocks += 1
        elif frame.type == DATA:
            payload = _strip_padding(frame)
            yield Event("data", frame.stream_id, abs_offset,
                        data_len=len(payload),
                        end_stream=bool(frame.flags & FLAG_END_STREAM))
            if frame.flags & FLAG_END_STREAM:
                yield Event("stream_end", frame.stream_id, abs_offset)
        elif frame.type == RST_STREAM:
            self._opened.pop(frame.stream_id, None)

    def _header_block(self, stream_id: int, flags: int, fragment: bytes,
                      offset: int) -> Iterator[Event]:
        try:
            headers = self.decoder.decode(fragment)
        except HpackError:
            # Mid-connection attach: the dynamic table bootstrap is lost.
            # Tolerate and skip, like the reference's error_count path
            # (parser.py:250-258).
            self.decode_errors += 1
            return
        names = {n for n, _ in headers}
        end_stream = bool(flags & FLAG_END_STREAM)
        if self._opened.get(stream_id):
            kind = "trailers"
        elif ":method" in names:
            kind = "request"
        elif ":status" in names:
            kind = "response"
        else:
            kind = "trailers"
        self._opened[stream_id] = True
        yield Event(kind, stream_id, offset, headers=headers,
                    end_stream=end_stream)
        if end_stream:
            yield Event("stream_end", stream_id, offset)


def looks_like_http2(inbound: bytes, outbound: bytes) -> bool:
    """Heuristic: a connection is HTTP/2 if either direction starts with the
    preface or with a well-formed SETTINGS frame (mid-stream attach)."""
    for direction in (inbound, outbound):
        if direction.startswith(PREFACE):
            return True
        if len(direction) >= 9:
            length = int.from_bytes(direction[:3], "big")
            if direction[3] == SETTINGS and direction[4] in (0, FLAG_ACK) \
                    and length % 6 == 0 and length <= 1024:
                return True
    return False


def replay_connection(
    inbound: bytes, outbound: bytes
) -> Tuple[List[Event], List[Event]]:
    """Replay both directions of one connection independently (each carries
    its own HPACK context). Returns (inbound_events, outbound_events)."""
    return (DirectionReplayer().feed(inbound),
            DirectionReplayer().feed(outbound))
