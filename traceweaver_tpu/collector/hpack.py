"""HPACK (RFC 7541) header compression codec.

Self-contained replacement for the ``h2``/``hpack`` dependency the
reference's span-collector prototype leans on for HTTP/2 header decoding
(reference: src/span_collector/http2_parser/parser.py:69-159, which replays
captured byte streams through paired h2 connection state machines). The
image ships neither package, so the collector port implements the codec:

- integer primitive with N-bit prefix (RFC 7541 §5.1);
- string literals, raw or Huffman-coded (§5.2, Appendix B canonical code);
- indexed / literal-with-incremental-indexing / literal-without-indexing /
  never-indexed field representations (§6.2);
- dynamic table with size updates and eviction (§4);
- an encoder (used by tests and synthetic capture generation) emitting
  either raw or Huffman string literals.

Constants live in :mod:`traceweaver_tpu.collector._rfc7541` (spec data).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from traceweaver_tpu.collector._rfc7541 import (
    HUFFMAN_CODES,
    HUFFMAN_LENGTHS,
    STATIC_TABLE,
)

Header = Tuple[str, str]


class HpackError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Integer primitive (RFC 7541 §5.1)
# ---------------------------------------------------------------------------

def encode_integer(value: int, prefix_bits: int, flags: int = 0) -> bytes:
    """Encode ``value`` with an N-bit prefix; ``flags`` sets bits above the
    prefix in the first octet."""
    if value < 0:
        raise HpackError("negative integer")
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([flags | value])
    out = [flags | limit]
    value -= limit
    while value >= 128:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def decode_integer(data: bytes, pos: int, prefix_bits: int) -> Tuple[int, int]:
    """Decode an N-bit-prefix integer at ``pos``; returns (value, new_pos)."""
    if pos >= len(data):
        raise HpackError("truncated integer")
    limit = (1 << prefix_bits) - 1
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise HpackError("truncated integer continuation")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if shift > 63:
            raise HpackError("integer overflow")
        if not b & 0x80:
            return value, pos


# ---------------------------------------------------------------------------
# Huffman code (RFC 7541 Appendix B)
# ---------------------------------------------------------------------------

def _build_decode_tree():
    # Binary trie as nested 2-lists; leaves are symbol ints.
    root: list = [None, None]
    for sym in range(257):
        code = HUFFMAN_CODES[sym]
        length = HUFFMAN_LENGTHS[sym]
        node = root
        for bit_pos in range(length - 1, -1, -1):
            bit = (code >> bit_pos) & 1
            if bit_pos == 0:
                node[bit] = sym
            else:
                if node[bit] is None:
                    node[bit] = [None, None]
                node = node[bit]
    return root


_DECODE_TREE = _build_decode_tree()
_EOS = 256


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    node = _DECODE_TREE
    partial_bits = 0    # bits consumed since the last completed symbol
    partial_all_ones = True
    for byte in data:
        for bit_pos in range(7, -1, -1):
            bit = (byte >> bit_pos) & 1
            node = node[bit]
            if node is None:
                raise HpackError("invalid Huffman code")
            partial_bits += 1
            partial_all_ones = partial_all_ones and bit == 1
            if isinstance(node, int):
                if node == _EOS:
                    raise HpackError("EOS in Huffman string")
                out.append(node)
                node = _DECODE_TREE
                partial_bits = 0
                partial_all_ones = True
    # Trailing bits must be a strict EOS prefix: all ones, fewer than 8
    # (RFC 7541 §5.2).
    if partial_bits and (partial_bits > 7 or not partial_all_ones):
        raise HpackError("invalid Huffman padding")
    return bytes(out)


def huffman_encode(data: bytes) -> bytes:
    bits = 0
    nbits = 0
    out = bytearray()
    for byte in data:
        code = HUFFMAN_CODES[byte]
        length = HUFFMAN_LENGTHS[byte]
        bits = (bits << length) | code
        nbits += length
        while nbits >= 8:
            nbits -= 8
            out.append((bits >> nbits) & 0xFF)
    if nbits:
        # pad with EOS prefix (all ones)
        out.append(((bits << (8 - nbits)) | ((1 << (8 - nbits)) - 1)) & 0xFF)
    return bytes(out)


# ---------------------------------------------------------------------------
# String literals (RFC 7541 §5.2)
# ---------------------------------------------------------------------------

def encode_string(s: bytes, huffman: bool = False) -> bytes:
    if huffman:
        coded = huffman_encode(s)
        return encode_integer(len(coded), 7, flags=0x80) + coded
    return encode_integer(len(s), 7) + s


def decode_string(data: bytes, pos: int) -> Tuple[bytes, int]:
    if pos >= len(data):
        raise HpackError("truncated string")
    huffman = bool(data[pos] & 0x80)
    length, pos = decode_integer(data, pos, 7)
    if pos + length > len(data):
        raise HpackError("truncated string payload")
    raw = data[pos:pos + length]
    pos += length
    return (huffman_decode(raw) if huffman else raw), pos


# ---------------------------------------------------------------------------
# Dynamic table (RFC 7541 §4) + decoder / encoder
# ---------------------------------------------------------------------------

def _entry_size(name: bytes, value: bytes) -> int:
    return len(name) + len(value) + 32  # §4.1 overhead constant


_STATIC = [(n.encode(), v.encode()) for n, v in STATIC_TABLE]
_STATIC_LOOKUP: Dict[bytes, int] = {}
_STATIC_FULL_LOOKUP: Dict[Tuple[bytes, bytes], int] = {}
for _i, (_n, _v) in enumerate(_STATIC):
    _STATIC_LOOKUP.setdefault(_n, _i + 1)
    _STATIC_FULL_LOOKUP.setdefault((_n, _v), _i + 1)


class _DynamicTable:
    def __init__(self, max_size: int = 4096):
        self.entries: List[Tuple[bytes, bytes]] = []  # newest first
        self.size = 0
        self.max_size = max_size
        self.protocol_max = max_size

    def add(self, name: bytes, value: bytes) -> None:
        self.entries.insert(0, (name, value))
        self.size += _entry_size(name, value)
        self._evict()

    def resize(self, new_max: int) -> None:
        self.max_size = new_max
        self._evict()

    def _evict(self) -> None:
        while self.size > self.max_size and self.entries:
            n, v = self.entries.pop()
            self.size -= _entry_size(n, v)

    def get(self, index: int) -> Tuple[bytes, bytes]:
        # 1-based global index space: static table first (§2.3.3)
        if 1 <= index <= len(_STATIC):
            return _STATIC[index - 1]
        d = index - len(_STATIC) - 1
        if 0 <= d < len(self.entries):
            return self.entries[d]
        raise HpackError(f"index {index} out of table bounds")


class Decoder:
    """Stateful HPACK decoder (one per connection direction)."""

    def __init__(self, max_table_size: int = 4096):
        self.table = _DynamicTable(max_table_size)

    def decode(self, data: bytes) -> List[Header]:
        headers: List[Header] = []
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # indexed field (§6.1)
                index, pos = decode_integer(data, pos, 7)
                if index == 0:
                    raise HpackError("index 0 in indexed representation")
                name, value = self.table.get(index)
            elif b & 0x40:  # literal with incremental indexing (§6.2.1)
                index, pos = decode_integer(data, pos, 6)
                name, value, pos = self._literal(data, pos, index)
                self.table.add(name, value)
            elif b & 0x20:  # dynamic table size update (§6.3)
                new_size, pos = decode_integer(data, pos, 5)
                if new_size > self.table.protocol_max:
                    raise HpackError("table size update above protocol max")
                self.table.resize(new_size)
                continue
            else:  # literal without indexing / never indexed (§6.2.2/6.2.3)
                index, pos = decode_integer(data, pos, 4)
                name, value, pos = self._literal(data, pos, index)
            headers.append((name.decode("utf-8", "replace"),
                            value.decode("utf-8", "replace")))
        return headers

    def _literal(self, data: bytes, pos: int,
                 index: int) -> Tuple[bytes, bytes, int]:
        if index:
            name = self.table.get(index)[0]
        else:
            name, pos = decode_string(data, pos)
        value, pos = decode_string(data, pos)
        return name, value, pos


class Encoder:
    """Stateful HPACK encoder; used by tests and synthetic captures."""

    def __init__(self, max_table_size: int = 4096, huffman: bool = False):
        self.table = _DynamicTable(max_table_size)
        self.huffman = huffman

    def _dyn_index(self, name: bytes,
                   value: Optional[bytes]) -> Optional[int]:
        for i, (n, v) in enumerate(self.table.entries):
            if n == name and (value is None or v == value):
                return len(_STATIC) + 1 + i
        return None

    def encode(self, headers: List[Header]) -> bytes:
        out = bytearray()
        for name_s, value_s in headers:
            name = name_s.encode()
            value = value_s.encode()
            full = _STATIC_FULL_LOOKUP.get((name, value))
            if full is None:
                full = self._dyn_index(name, value)
            if full is not None:
                out += encode_integer(full, 7, flags=0x80)
                continue
            name_idx = _STATIC_LOOKUP.get(name) or self._dyn_index(name, None)
            if name_idx:
                out += encode_integer(name_idx, 6, flags=0x40)
            else:
                out += encode_integer(0, 6, flags=0x40)
                out += encode_string(name, self.huffman)
            out += encode_string(value, self.huffman)
            self.table.add(name, value)
        return bytes(out)
