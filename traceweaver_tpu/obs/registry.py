"""Typed, thread-safe metrics registry — the telemetry spine (ISSUE 9).

Before this module, every subsystem kept its own ad-hoc ledger: the
fleet's ``_Stats`` dict, the stream service's ``stats``/``_bump``, the
serve layer's per-tenant ``counters``, ``runtime/jax_cache``'s module
``_COUNTERS``. Those ledgers stay (their field names are load-bearing —
bench schemas, executor prints, a dozen tests) but every update now
ALSO mirrors into this registry, so one scrape surface
(:mod:`traceweaver_tpu.obs.exposition`, ``GET /metrics``) sees the
whole pipeline with labels instead of N private dicts.

Design constraints, in order:

- **import-light**: stdlib only (no jax, no numpy) — the registry is
  imported by ``algorithms/fleet.py`` and the analysis CLI alike, and
  must cost nothing before the first metric moves;
- **typed**: three metric kinds only — :class:`Counter` (monotonic,
  negative increments raise), :class:`Gauge` (set / set-if-greater),
  :class:`Histogram` (fixed buckets, cumulative) — and a declared label
  schema per family: declaring the same name twice with a different
  kind or label set raises :class:`MetricError` instead of silently
  forking the series (the ``ops/precision.py`` raise-on-typo rule
  applied to telemetry);
- **thread-safe**: the fleet's pack thread, decode workers, and the
  serve pump all mirror concurrently; every mutation runs under the
  owning registry's lock (the ``fleet._Stats`` discipline, twlint
  TW005);
- **scrape-time collectors**: state that already lives elsewhere
  (``jax_cache._COUNTERS``, the serve layer's per-tenant stats) is
  exposed via registered collector callbacks evaluated at scrape time,
  so the exposition can never drift from the source ledger — exact
  match is by construction, not by double bookkeeping.

See docs/OBSERVABILITY.md for the metric catalog and label schema.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: child key of a family's cardinality-overflow series (rendered as
#: ``{overflow="1"}``): once a family holds ``TW_METRICS_MAX_SERIES``
#: distinct label-value sets, updates for NEW sets collapse into this
#: one counted series instead of growing the registry unbounded — the
#: many-tenant protection (docs/OBSERVABILITY.md "Quality telemetry").
OVERFLOW_KEY = ("__overflow__",)


def _max_series() -> int:
    """The per-family series cap (``TW_METRICS_MAX_SERIES``), read at
    new-series-admission time only — the hot inc path on an existing
    series never touches the environment. Imported lazily: the knob
    registry lives under ``runtime/`` and this module must stay
    import-light for the lint/events CLI fast paths."""
    from traceweaver_tpu.runtime import knobs

    return knobs.get_int("TW_METRICS_MAX_SERIES")

#: default histogram buckets (seconds-flavored: 1 ms .. 60 s, then +Inf)
DEFAULT_BUCKETS = (0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0, 60.0)


class MetricError(ValueError):
    """A metric misuse (name/label schema conflict, negative counter
    increment, bad label set) — raised loudly instead of silently
    forking or corrupting a series."""


class _Family:
    """One metric family: a name, a kind, a label schema, and children
    keyed by label-value tuples. All mutation happens under the owning
    registry's lock (passed in — one lock per registry, so cross-family
    snapshots are consistent)."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labels: Sequence[str],
                 lock: threading.RLock) -> None:
        if not _NAME_RE.match(name):
            raise MetricError(f"invalid metric name {name!r}")
        for lab in labels:
            if not _LABEL_RE.match(lab):
                raise MetricError(
                    f"invalid label name {lab!r} on metric {name!r}")
        self.name = name
        self.help = help
        self.labels = tuple(labels)
        self._lock = lock
        self._children: Dict[Tuple[str, ...], float] = {}

    def _key(self, labelkw: Dict[str, object]) -> Tuple[str, ...]:
        if set(labelkw) != set(self.labels):
            raise MetricError(
                f"metric {self.name!r} declared labels {self.labels}, "
                f"got {tuple(sorted(labelkw))}")
        return tuple(str(labelkw[lab]) for lab in self.labels)

    def _admit(self, key: Tuple[str, ...], table: Dict) -> Tuple[str, ...]:
        """Cardinality guard (caller holds the lock): an update for a
        label-value set the family already tracks passes through; a NEW
        set is admitted only while the family holds fewer than
        ``TW_METRICS_MAX_SERIES`` distinct sets, else it lands on the
        single :data:`OVERFLOW_KEY` series — counted, never silently
        dropped, and the registry stays bounded under many tenants."""
        if key in table or not self.labels:
            return key
        n_real = len(table) - (1 if OVERFLOW_KEY in table else 0)
        if n_real >= _max_series():
            return OVERFLOW_KEY
        return key

    def _sample_labels(self, key: Tuple[str, ...]) -> Dict[str, str]:
        if key == OVERFLOW_KEY:
            return {"overflow": "1"}
        return dict(zip(self.labels, key))

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        """``[(labels_dict, value)]`` snapshot, label-sorted (stable
        exposition order; the overflow series, if any, rides along as
        ``{overflow="1"}``)."""
        with self._lock:
            items = sorted(self._children.items())
        return [(self._sample_labels(key), val) for key, val in items]


class Counter(_Family):
    """Monotonic counter. ``inc`` with a negative value raises — a
    decreasing 'counter' is a gauge wearing the wrong type."""

    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        if value < 0:
            raise MetricError(
                f"counter {self.name!r}: negative increment {value}")
        key = self._key(labels)
        with self._lock:
            key = self._admit(key, self._children)
            self._children[key] = self._children.get(key, 0.0) + value


class Gauge(_Family):
    """Point-in-time value; ``set_max`` is the ``_Stats.record_max``
    mirror (set-if-greater)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            key = self._admit(key, self._children)
            self._children[key] = float(value)

    def set_max(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            key = self._admit(key, self._children)
            self._children[key] = max(self._children.get(key, float(value)),
                                      float(value))


class Histogram(_Family):
    """Fixed-bucket cumulative histogram (Prometheus semantics: each
    bucket counts observations ≤ its bound, ``+Inf`` counts all)."""

    kind = "histogram"

    def __init__(self, name: str, help: str, labels: Sequence[str],
                 lock: threading.RLock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        super().__init__(name, help, labels, lock)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds or any(math.isnan(b) for b in bounds):
            raise MetricError(
                f"histogram {name!r}: need at least one finite bucket")
        self.buckets = bounds
        # child value: [count_per_bucket..., +Inf count, sum]
        self._hchildren: Dict[Tuple[str, ...], List[float]] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        v = float(value)
        with self._lock:
            key = self._admit(key, self._hchildren)
            child = self._hchildren.get(key)
            if child is None:
                child = [0.0] * (len(self.buckets) + 2)
                self._hchildren[key] = child
            for i, bound in enumerate(self.buckets):
                if v <= bound:
                    child[i] += 1.0
            child[-2] += 1.0          # +Inf
            child[-1] += v            # sum

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        """Flattened exposition samples: ``_bucket{le=...}``, ``_sum``,
        ``_count`` per child (the exposition layer keys on the sample
        name suffixes)."""
        out: List[Tuple[Dict[str, str], float]] = []
        with self._lock:
            items = sorted(self._hchildren.items())
        for key, child in items:
            base = self._sample_labels(key)
            for i, bound in enumerate(self.buckets):
                out.append(({**base, "le": _fmt_bound(bound),
                             "__name__": self.name + "_bucket"}, child[i]))
            out.append(({**base, "le": "+Inf",
                         "__name__": self.name + "_bucket"}, child[-2]))
            out.append(({**base, "__name__": self.name + "_sum"}, child[-1]))
            out.append(({**base, "__name__": self.name + "_count"},
                        child[-2]))
        return out


def _fmt_bound(b: float) -> str:
    return repr(b) if b != int(b) else str(int(b))


#: a collector returns families as plain tuples so sources need no
#: registry objects: ``(name, kind, help, [(labels_dict, value), ...])``
CollectorFn = Callable[[], Iterable[Tuple[str, str, str,
                                          List[Tuple[Dict[str, str],
                                                     float]]]]]


class MetricsRegistry:
    """Family store + scrape-time collectors. One instance per process
    in practice (:func:`get_registry`); tests may build private ones."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}
        self._collectors: Dict[str, CollectorFn] = {}

    # -- declaration (idempotent; schema conflicts raise) -----------------
    def _declare(self, cls, name: str, help: str, labels: Sequence[str],
                 **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if type(fam) is not cls or fam.labels != tuple(labels):
                    raise MetricError(
                        f"metric {name!r} already declared as "
                        f"{fam.kind} with labels {fam.labels}; "
                        f"redeclaration as {cls.kind} with "
                        f"{tuple(labels)} would fork the series")
                return fam
            fam = cls(name, help, labels, self._lock, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._declare(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._declare(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._declare(Histogram, name, help, labels, buckets=buckets)

    def register_collector(self, key: str, fn: CollectorFn) -> None:
        """Register (or replace — idempotence under re-install) a
        scrape-time collector. Collectors are evaluated on every
        :meth:`collect`, so the exposed values ARE the source ledger's
        current values, never a mirrored copy that could drift."""
        with self._lock:
            self._collectors[key] = fn

    # -- read side ---------------------------------------------------------
    def collect(self, include_collectors: bool = True):
        """Yield ``(name, kind, help, samples)`` for every family (owned
        first, then collectors). Collector callbacks run OUTSIDE the
        registry lock — they read other subsystems' locked state and
        must not nest under ours."""
        with self._lock:
            owned = sorted(self._families.items())
            collectors = list(self._collectors.items())
        for name, fam in owned:
            yield (name, fam.kind, fam.help, fam.samples())
        for _, fn in sorted(collectors):
            for entry in fn():
                yield entry

    def snapshot(self, include_collectors: bool = False) -> Dict[str, float]:
        """Flat ``{'name{label="v",...}': value}`` view — the bench
        ``telemetry_snapshot`` delta input (histograms contribute their
        ``_sum``/``_count``/``_bucket`` samples)."""
        out: Dict[str, float] = {}
        for name, _kind, _help, samples in self.collect(include_collectors):
            for labels, value in samples:
                labels = dict(labels)
                sample_name = labels.pop("__name__", name)
                body = ",".join('%s="%s"' % (k, v)
                                for k, v in sorted(labels.items()))
                out[sample_name + ("{%s}" % body if body else "")] = value
        return out

    def reset(self) -> None:
        """Drop every family and collector (test isolation only)."""
        with self._lock:
            self._families.clear()
            self._collectors.clear()


_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem mirrors into."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = MetricsRegistry()
    return _DEFAULT
