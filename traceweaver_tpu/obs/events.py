"""Structured JSONL event sink + the ``cli events`` tail subcommand.

Replaces the fault ladder's dict-internal ordered event list as the
OPERATOR surface: every ladder rung (retry/bisect/xla/host/quarantine),
every injected fault, and any other subsystem event lands as one
structured JSON record per line in an append-only sink — the SAME
record-per-line format the quarantine dead-letter sidecar already uses
(``stream/service.py _deadletter``), so one tail tool reads both. The
in-dict ``fault_ladder`` list the bench/tests consume is unchanged
(``fleet._Stats.note`` still appends); the sink is the durable,
tail-able copy with timestamps and context the list never had.

Record shape (sorted keys, one JSON object per line)::

    {"event": "retry", "kind": "fault_ladder", "ts": 1754300000.123, ...}

Offset/truncate semantics mirror the stream's ``TraceSink`` so a
checkpoint/resume splice can rewind an event log the same way it
rewinds the emission sink — no double-recorded, no lost events.

Install one process-wide via :func:`install` (the CLIs wire
``TW_EVENTS=<path>``); :func:`emit` is a no-op returning immediately
when none is installed, so the production no-events path costs one
global read per call.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional


class EventLog:
    """Append-only JSONL event sink with a recorded byte offset."""

    def __init__(self, path: str) -> None:
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(path, "a+b")
        self._f.seek(0, os.SEEK_END)
        self.offset = self._f.tell()
        self.records = 0

    def emit(self, kind: str, event: str, **fields) -> None:
        rec = dict(fields)
        rec["kind"] = kind
        rec["event"] = event
        rec.setdefault("ts", round(time.time(), 6))
        data = (json.dumps(rec, sort_keys=True, default=str) + "\n") \
            .encode("utf-8")
        with self._lock:
            self._f.write(data)
            self._f.flush()
            self.offset += len(data)
            self.records += 1

    def truncate(self, offset: int) -> None:
        with self._lock:
            self._f.truncate(offset)
            self._f.seek(offset)
            self.offset = offset

    def close(self) -> None:
        with self._lock:
            self._f.close()


#: event kinds the subsystems emit (the ``cli events --kind`` values);
#: not enforced on emit — the sink takes any kind — but kept here so the
#: tail tool's help can name the tailing surface completely
KNOWN_KINDS = (
    "fault_ladder",       # solve-supervisor rungs (retry/bisect/...)
    "fault_injected",     # chaos stimulus draws (runtime/faults.py)
    "confidence_drift",   # PSI excursions (obs/quality.py)
    "adapt",              # adaptation-ladder actuations (adapt/)
    "slo_breach",         # seal→emit p99 excursions (stream/serve)
    "serve",              # serve-layer lifecycle (dispatcher degradation)
    "fleet",              # fleet tier: router health/breaker, tenant
                          # migrations, rolling restarts (fleet_serve/)
    "campaign",           # campaign harness start/rung/finish (campaign/)
    "capture_loss",       # capture ingress losses per reason
    "capture_churn",      # connection re-keying (collector/source.py)
    "clock_skew",         # per-source skew fits (collector/skew.py)
)

_ACTIVE: Optional[EventLog] = None


def install(log: Optional[EventLog]) -> Optional[EventLog]:
    """Install (or clear, with None) the process-wide event sink.
    Returns the previous one so scopes can restore it."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = log
    return prev


def active() -> Optional[EventLog]:
    return _ACTIVE


def emit(kind: str, event: str, **fields) -> None:
    """Emit to the installed sink, if any (one global read when not)."""
    log = _ACTIVE
    if log is not None:
        log.emit(kind, event, **fields)


# ---------------------------------------------------------------------------
# `python -m traceweaver_tpu.runtime.cli events` — tail the sink
# ---------------------------------------------------------------------------

def _fmt_record(rec: Dict) -> str:
    """One human line per record: timestamp, kind/event head, then the
    remaining fields as k=v. Dead-letter records (no kind/event) print
    their fields generically — same tool, both formats."""
    ts = rec.pop("ts", None)
    head = []
    if ts is not None:
        try:
            head.append(time.strftime("%H:%M:%S", time.localtime(float(ts)))
                        + ("%.3f" % (float(ts) % 1))[1:])
        except (TypeError, ValueError):
            head.append(str(ts))
    kind = rec.pop("kind", None)
    event = rec.pop("event", None)
    if kind is not None or event is not None:
        head.append("%s/%s" % (kind or "-", event or "-"))
    elif "reason" in rec:
        head.append("deadletter")
    tail = " ".join("%s=%s" % (k, rec[k]) for k in sorted(rec))
    return " ".join(head + ([tail] if tail else []))


def tail_main(argv: List[str]) -> int:
    """``cli events <path> [-n N] [--follow] [--kind K]``: pretty-tail a
    JSONL event (or dead-letter) sink."""
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="python -m traceweaver_tpu.runtime.cli events",
        description="Tail a structured JSONL event sink (fault-ladder "
                    "events, quarantine dead-letters, capture-loss / "
                    "clock-skew excursions — one record per line, "
                    "docs/OBSERVABILITY.md).")
    p.add_argument("path", help="event/dead-letter JSONL file")
    p.add_argument("-n", type=int, default=20,
                   help="show the last N records (default 20; 0 = all)")
    p.add_argument("--follow", action="store_true",
                   help="keep the file open and print records as they "
                        "arrive (Ctrl-C to stop)")
    p.add_argument("--kind", default=None,
                   help="only records whose 'kind' field matches; known "
                        "kinds: " + ", ".join(KNOWN_KINDS))
    args = p.parse_args(argv)
    if not os.path.exists(args.path):
        print(f"events: no such file: {args.path}", file=sys.stderr)
        return 2

    def emit_line(raw) -> None:
        if isinstance(raw, bytes):
            raw = raw.decode("utf-8", errors="replace")
        raw = raw.strip()
        if not raw:
            return
        try:
            rec = json.loads(raw)
        except json.JSONDecodeError:
            print("? " + raw)
            return
        if not isinstance(rec, dict):
            print("? " + raw)
            return
        if args.kind is not None and rec.get("kind") != args.kind:
            return
        print(_fmt_record(dict(rec)))

    # binary mode: the follow loop does byte-offset arithmetic (seek /
    # pread anchors), which text-mode tell() cookies cannot support
    with open(args.path, "rb") as f:
        lines = f.readlines()
        for raw in (lines[-args.n:] if args.n else lines):
            emit_line(raw)
        if not args.follow:
            return 0
        # rotation/truncate splice (TraceSink/EventLog semantics: the
        # checkpoint/resume path truncates back to a recorded offset and
        # immediately re-appends). Two detectors, both needed:
        #  - size < offset: plain truncation caught before regrowth;
        #  - the ANCHOR: the last line read, re-verified by pread at its
        #    recorded offset on every idle tick. A truncate+reappend that
        #    regrows past the follower's offset between polls leaves
        #    size >= offset — only the rewritten bytes under the anchor
        #    betray the splice. On mismatch, rewind to the anchor (the
        #    earliest rewritten point the follower can prove) and
        #    re-read: re-emitted records print and the follow never
        #    sticks at a stale offset.
        anchor_pos, anchor_bytes = 0, b""
        if lines and lines[-1].endswith(b"\n"):
            # seed the anchor from the initial dump's last record, so a
            # splice that lands before the first live read is caught too
            anchor_bytes = lines[-1]
            anchor_pos = f.tell() - len(anchor_bytes)
        try:
            while True:
                if anchor_bytes:
                    # verify BEFORE consuming: a splice that already
                    # regrew past our offset would otherwise hand us a
                    # mid-record tail to read (and re-anchor on) first
                    cur = os.pread(f.fileno(), len(anchor_bytes),
                                   anchor_pos)
                    if cur != anchor_bytes:
                        f.seek(anchor_pos)
                        anchor_pos, anchor_bytes = 0, b""
                        continue
                pos = f.tell()
                raw = f.readline()
                if raw.endswith(b"\n"):
                    emit_line(raw)
                    anchor_pos, anchor_bytes = pos, raw
                    continue
                f.seek(pos)  # partial line: re-read once it completes
                try:
                    size = os.path.getsize(args.path)
                except OSError:
                    size = None
                if size is not None and size < pos:
                    f.seek(size)
                    anchor_pos, anchor_bytes = 0, b""
                time.sleep(0.2)
        except KeyboardInterrupt:
            return 0
